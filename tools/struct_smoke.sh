#!/usr/bin/env bash
# Structured-generation smoke — the full KV-fork + grammar matrix
# (tests/test_structured.py: greedy/sampled/spec fork differentials,
# preempt-mid-fork, pool pressure, jump-ahead bitwise, the randomized
# cancel/preempt zero-leak soak, the TokenServer wire arms and the
# example) plus the fork-aware race-checker proof in test_tdcheck, on
# the forced multi-device CPU mesh tier-1 uses. Archives the pass
# count next to the log and reports the delta vs the previous run,
# tier1.sh-style. Run from the repo root: bash tools/struct_smoke.sh
set -o pipefail
rm -f /tmp/_struct_smoke.log
# NO `-m 'not slow'` here: this loop exists to run the FULL
# structured matrix, including the arms tier-1's 870 s budget pushes
# behind the slow mark (sampled/spec forks, pressure, soak, sockets,
# the example).
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest tests/test_structured.py \
    "tests/test_tdcheck.py::test_races_fork_sharing_legal_and_violation_fires" \
    "tests/test_examples.py::test_structured_output_example_runs" \
    -q -p no:cacheprovider -p no:xdist -p no:randomly \
    2>&1 | tee /tmp/_struct_smoke.log
rc=${PIPESTATUS[0]}
passed=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_struct_smoke.log | tr -cd . | wc -c)
last_file=/tmp/_struct_smoke.last
if [ -f "$last_file" ]; then
    last=$(cat "$last_file")
    delta=$((passed - last))
    [ "$delta" -ge 0 ] && delta="+$delta"
    echo "STRUCT_SMOKE_PASSED=$passed (prev $last, delta $delta)"
else
    echo "STRUCT_SMOKE_PASSED=$passed"
fi
echo "$passed" > "$last_file"
exit $rc
