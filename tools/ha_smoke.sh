#!/usr/bin/env bash
# Fleet HA smoke — the full high-availability matrix
# (tests/test_fleet_ha.py including the slow arms: the partition ->
# resteer -> readmit cycle, the promoted-router shadow/session
# inheritance, the latency-brownout drain, and the seeded chaos soak)
# plus the HA satellites riding in other modules (the full-jitter
# backoff distribution, the bench_compare HA-row directions). This is
# the focused loop for iterating on triton_dist_tpu/fleet/ha.py and
# the router/breaker surgery alone; tier-1 (tools/tier1.sh) runs only
# the lean arms under its 870 s budget. Archives the pass count next
# to the log and reports the delta vs the previous run, tier1.sh-style.
# Run from the repo root: bash tools/ha_smoke.sh
set -o pipefail
rm -f /tmp/_ha_smoke.log
# NO `-m 'not slow'` here: this loop exists to run the FULL HA matrix,
# including the arms tier-1's budget pushes behind the slow mark (the
# chaos soak alone replays a 200-coin schedule over a live fleet).
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest tests/test_fleet_ha.py \
    "tests/test_serving.py::test_full_jitter_backoff_distribution" \
    "tests/test_observability.py::test_bench_compare_ha_row_directions" \
    -q -p no:cacheprovider -p no:xdist -p no:randomly \
    2>&1 | tee /tmp/_ha_smoke.log
rc=${PIPESTATUS[0]}
passed=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_ha_smoke.log | tr -cd . | wc -c)
last_file=/tmp/_ha_smoke.last
if [ -f "$last_file" ]; then
    last=$(cat "$last_file")
    delta=$((passed - last))
    [ "$delta" -ge 0 ] && delta="+$delta"
    echo "HA_SMOKE_PASSED=$passed (prev $last, delta $delta)"
else
    echo "HA_SMOKE_PASSED=$passed"
fi
echo "$passed" > "$last_file"
exit $rc
