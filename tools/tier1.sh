#!/usr/bin/env bash
# Tier-1 gate — the EXACT command from ROADMAP.md ("Tier-1 verify"),
# plus a --durations report so builders and reviewers see the same
# timing picture they would use to (re)assign `slow` marks (pytest.ini),
# a DOTS_PASSED delta vs the previous run (count stored next to the
# log) so a regression is one glance, not two terminal scrollbacks,
# and a tier1_history.tsv ledger (date, pass count, wall seconds, rc)
# next to the archived trace artifact so the suite's trajectory on
# this host — pass count AND wall-vs-the-870s-budget — is greppable
# across runs instead of living in lost scrollback.
# Run from the repo root: bash tools/tier1.sh
set -o pipefail
rm -f /tmp/_t1.log /tmp/_t1.trace.json /tmp/_t1_modules.tsv
# tdcheck pre-pass (ISSUE 15): the static-analysis gate — kernel
# contracts, comm protocol graph, paged-KV symbolic race proof,
# hot-loop lint, dead-code lint — is trace-only and runs in ~20s, so
# it fronts the 870s suite: a protocol or contract regression fails
# here in seconds instead of deep in a bitwise differential.
bash "$(dirname "$0")/tdcheck.sh" > /tmp/_tdcheck.log 2>&1
tdrc=$?
tail -3 /tmp/_tdcheck.log
if [ "$tdrc" -ne 0 ]; then
    echo "TDCHECK FAILED (rc=$tdrc) — full log: /tmp/_tdcheck.log; suite continues"
fi
# TDTPU_TRACE: poll-loop tracing ON for every serving test (telemetry
# is stream-exact by contract, so this doubles as a suite-wide
# integration check); the last TokenServer to exit leaves its
# perfetto-loadable timeline next to this log — inspect with
# python tools/trace_view.py /tmp/_t1.trace.json  (--json for CI)
t0=$SECONDS
timeout -k 10 870 env JAX_PLATFORMS=cpu TDTPU_TRACE=/tmp/_t1.trace.json \
    TDTPU_TIMING_TSV=/tmp/_t1_modules.tsv \
    python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly --durations=20 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
wall=$((SECONDS - t0))
passed=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
last_file=/tmp/_t1.last
if [ -f "$last_file" ]; then
    last=$(cat "$last_file")
    delta=$((passed - last))
    [ "$delta" -ge 0 ] && delta="+$delta"
    echo "DOTS_PASSED=$passed (prev $last, delta $delta)"
else
    echo "DOTS_PASSED=$passed"
fi
echo "$passed" > "$last_file"
hist=/tmp/tier1_history.tsv
[ -f "$hist" ] || printf 'date\tdots_passed\twall_s\trc\n' > "$hist"
printf '%s\t%s\t%s\t%s\n' "$(date -u +%Y-%m-%dT%H:%M:%SZ)" "$passed" "$wall" "$rc" >> "$hist"
echo "TIER1_HISTORY=$hist ($(($(wc -l < "$hist") - 1)) runs; wall ${wall}s of the 870s budget)"
if [ -s /tmp/_t1.trace.json ]; then
    echo "TRACE_ARTIFACT=/tmp/_t1.trace.json ($(wc -c < /tmp/_t1.trace.json) bytes; summarize: python tools/trace_view.py /tmp/_t1.trace.json)"
fi
if [ -s /tmp/_t1_modules.tsv ]; then
    echo "--- per-module wall (top 15; full table /tmp/_t1_modules.tsv) ---"
    head -16 /tmp/_t1_modules.tsv | awk -F'\t' '{printf "%-40s %8s\n", $1, $2}'
fi
[ $rc -eq 0 ] && rc=$tdrc
exit $rc
