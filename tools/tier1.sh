#!/usr/bin/env bash
# Tier-1 gate — the EXACT command from ROADMAP.md ("Tier-1 verify"),
# plus a --durations report so builders and reviewers see the same
# timing picture they would use to (re)assign `slow` marks (pytest.ini).
# Run from the repo root: bash tools/tier1.sh
set -o pipefail
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly --durations=20 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
exit $rc
