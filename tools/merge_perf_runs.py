#!/usr/bin/env python
"""Merge several perf_report JSON runs into PERF_OPS_tpu.json by
per-row minimum (the least-contended estimate on the shared tunneled
chip — single runs swing +-40%; methodology note embedded in the
output). Degenerate (zero-SOL) rows are taken from the LAST run and
not min-merged, matching the round-3 artifact's convention.

Usage: python tools/merge_perf_runs.py /tmp/perf_run_*.json
"""
import json
import sys


def main(paths):
    runs = [json.load(open(p)) for p in paths]
    base = runs[-1]
    by_op = {}
    for run in runs:
        for row in run["ops"]:
            key = row["op"]
            cur = by_op.get(key)
            if row.get("achieved_us") is None:
                # degenerate rows: keep overwriting -> LAST run wins
                if cur is None or cur.get("achieved_us") is None:
                    by_op[key] = row
                continue
            if (cur is None or cur.get("achieved_us") is None
                    or row["achieved_us"] < cur["achieved_us"]):
                by_op[key] = row
    ops = []
    for row in base["ops"]:
        r = dict(by_op[row["op"]])
        if r.get("achieved_us") and r.get("sol_us"):
            r["sol_frac"] = r["sol_us"] / r["achieved_us"]
        ops.append(r)
    out = {
        "env": base["env"],
        "note": ("rows with a nonzero SOL are the per-row MIN over "
                 f"{len(runs)} full report runs on the shared tunneled "
                 "chip (same code, same methodology: data-chained fori "
                 "loops, pooled-min slopes; single runs swing +-40% in "
                 "multi-minute contention windows, so the per-row "
                 "minimum is the least-contended estimate). ndev=1 "
                 "pure-collective rows are DEGENERATE (the op is "
                 "near-identity) and are NOT min-merged."),
        "ops": ops,
    }
    with open("PERF_OPS_tpu.json", "w") as f:
        json.dump(out, f, indent=1)
    for r in ops:
        frac = r.get("sol_frac")
        print(f"{r['op']:24s} {r.get('achieved_us') or 0:9.2f} us  "
              f"{'' if frac is None else f'{frac:.3f} SOL'}")
    print("wrote PERF_OPS_tpu.json")


if __name__ == "__main__":
    main(sys.argv[1:])
