#!/usr/bin/env bash
# Regenerate every on-chip artifact in one command, the moment the chip
# returns (VERDICT r4 next #2). Safe to re-run; each step is
# independent and failures don't stop the rest.
#
#   bash tools/onchip_regen.sh
#
# Produces (repo root):
#   tune cache (TDTPU_TUNE_CACHE / ~/.triton_dist_tpu/tune_cache.json)
#   PERF_OPS_tpu.json            per-op SOL report (git+date stamped)
#   PROFILE_<kernel>.json/.trace.json   ablation profiles x4
#   BENCH_local.json             bench line (driver writes BENCH_rNN)
set -u
cd "$(dirname "$0")/.."

echo "== backend probe =="
if ! timeout 120 python -c "import jax; assert jax.default_backend() == 'tpu', jax.default_backend()"; then
    echo "no TPU backend reachable - aborting (artifacts unchanged)"
    exit 1
fi

echo "== autotune sweep (populates the tune cache the reports read) =="
timeout 3600 python -m triton_dist_tpu.tools.sweep \
    || echo "sweep FAILED"

echo "== per-op SOL report =="
timeout 3000 python -m triton_dist_tpu.tools.perf_report \
    --json PERF_OPS_tpu.json || echo "perf_report FAILED"

echo "== kernel ablation profiles =="
timeout 3600 python -m triton_dist_tpu.tools.kprof_run --out . \
    || echo "kprof_run FAILED"

echo "== bench =="
timeout 3600 python bench.py | tee BENCH_local.json || echo "bench FAILED"

echo "== done; diff the artifacts and update README numbers =="
