#!/usr/bin/env bash
# Autotuning-loop smoke — the sweep/tune suite (tests/test_sweep.py,
# INCLUDING the arms tier-1's 870 s budget pushes behind the slow
# mark: the full-registry dry-run test and the bitwise-identity
# matrix), then a full-registry CLI dry-run, then the bounded
# 3-kernel sweep + roofline gate (tools/perf_gate.sh) — all on the
# forced multi-device CPU mesh tier-1 uses. Archives the pass count
# next to the log and reports the delta vs the previous run,
# tp_smoke.sh-style. Run from the repo root: bash tools/tune_smoke.sh
set -o pipefail
rm -f /tmp/_tune_smoke.log
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest tests/test_sweep.py \
    -q -p no:cacheprovider -p no:xdist -p no:randomly \
    2>&1 | tee /tmp/_tune_smoke.log
rc=${PIPESTATUS[0]}
passed=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_tune_smoke.log | tr -cd . | wc -c)
last_file=/tmp/_tune_smoke.last
if [ -f "$last_file" ]; then
    last=$(cat "$last_file")
    delta=$((passed - last))
    [ "$delta" -ge 0 ] && delta="+$delta"
    echo "TUNE_SMOKE_PASSED=$passed (prev $last, delta $delta)"
else
    echo "TUNE_SMOKE_PASSED=$passed"
fi
echo "$passed" > "$last_file"
[ "$rc" -ne 0 ] && exit "$rc"

echo "== full-registry dry run =="
timeout -k 10 300 env JAX_PLATFORMS=cpu TDTPU_NO_FAKECPUS=1 \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m triton_dist_tpu.tools.sweep --dry-run \
    || { echo "TUNE_SMOKE: dry-run FAILED"; exit 1; }

echo "== bounded sweep + roofline gate =="
bash tools/perf_gate.sh || { echo "TUNE_SMOKE: perf gate FAILED"; exit 1; }
echo "TUNE_SMOKE: OK"
exit 0
