#!/usr/bin/env bash
# TP-sharded serving smoke — the TP=4-vs-TP=1 bitwise differential
# suite (tests/test_tp_serving.py + the sharded host-tier round trip
# in tests/test_kv_tier.py) on the forced multi-device CPU mesh, the
# same substrate tier-1 uses (tools/tier1.sh runs the whole tests/
# tree under it — this script is the focused loop for iterating on
# the TP layer alone). Archives the pass count next to the log and
# reports the delta vs the previous run, tier1.sh-style.
# Run from the repo root: bash tools/tp_smoke.sh
set -o pipefail
rm -f /tmp/_tp_smoke.log
# NO `-m 'not slow'` here: this loop exists to run the FULL TP
# differential matrix, including the arms tier-1's 870 s budget
# pushes behind the slow mark (sampled/spec, chunked+overlap,
# preemption+host-tier, the example).
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest tests/test_tp_serving.py \
    "tests/test_kv_tier.py::test_extract_restore_bitwise_on_sharded_pool" \
    "tests/test_examples.py::test_tp_serving_example_runs" \
    -q -p no:cacheprovider -p no:xdist -p no:randomly \
    2>&1 | tee /tmp/_tp_smoke.log
rc=${PIPESTATUS[0]}
passed=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_tp_smoke.log | tr -cd . | wc -c)
last_file=/tmp/_tp_smoke.last
if [ -f "$last_file" ]; then
    last=$(cat "$last_file")
    delta=$((passed - last))
    [ "$delta" -ge 0 ] && delta="+$delta"
    echo "TP_SMOKE_PASSED=$passed (prev $last, delta $delta)"
else
    echo "TP_SMOKE_PASSED=$passed"
fi
echo "$passed" > "$last_file"
exit $rc
