#!/usr/bin/env bash
# Disaggregated-serving smoke — the full disagg-vs-fused bitwise
# differential matrix (tests/test_disagg.py) on the forced
# multi-device CPU mesh, the same substrate tier-1 uses. Tier-1's
# 870 s budget keeps only the greedy core + churn guard + fault
# matrix; this script runs EVERYTHING — the sampled/spec arms,
# preemption + host tier, overlap, threaded workers, the ICI/DCN
# device transports, and the example — and archives the pass count
# with a delta vs the previous run, tp_smoke.sh-style.
# Run from the repo root: bash tools/disagg_smoke.sh
set -o pipefail
rm -f /tmp/_disagg_smoke.log
timeout -k 10 900 env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest tests/test_disagg.py \
    "tests/test_examples.py::test_disaggregation_example_runs" \
    -q -p no:cacheprovider -p no:xdist -p no:randomly \
    2>&1 | tee /tmp/_disagg_smoke.log
rc=${PIPESTATUS[0]}
passed=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_disagg_smoke.log | tr -cd . | wc -c)
last_file=/tmp/_disagg_smoke.last
if [ -f "$last_file" ]; then
    last=$(cat "$last_file")
    delta=$((passed - last))
    [ "$delta" -ge 0 ] && delta="+$delta"
    echo "DISAGG_SMOKE_PASSED=$passed (prev $last, delta $delta)"
else
    echo "DISAGG_SMOKE_PASSED=$passed"
fi
echo "$passed" > "$last_file"
exit $rc
