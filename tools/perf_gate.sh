#!/usr/bin/env bash
# Roofline CI gate (ISSUE 16, ROADMAP item 5): the perf loop's exit
# check. Two steps, both bounded:
#
#   1. sweep smoke — the registry-driven autotuner
#      (triton_dist_tpu/tools/sweep.py) over a 3-kernel subset that
#      executes on the CPU interpreter, 1 timing iter, writing to an
#      ephemeral store unless the caller pins TDTPU_TUNE_CACHE. Proves
#      prune -> time -> persist stays runnable.
#   2. bench_compare --strict over the BENCH_history.jsonl tail — fails
#      (exit 1) on a same-backend, non-cpu regression, which now
#      includes the per-kernel roofline rows ({op}_sol_frac) bench.py
#      emits. CPU-smoke rows stay advisory; a ledger with fewer than
#      two runs (rc 2) is a pass-with-warning, not a failure: the gate
#      must be installable before the history exists.
#
# Run from the repo root: bash tools/perf_gate.sh
set -u
cd "$(dirname "$0")/.."

echo "== sweep smoke (3-kernel subset) =="
if ! timeout -k 10 300 env JAX_PLATFORMS=cpu TDTPU_NO_FAKECPUS=1 \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    TDTPU_TUNE_CACHE="${TDTPU_TUNE_CACHE:-/tmp/_perf_gate_tune_cache.json}" \
    python -m triton_dist_tpu.tools.sweep \
    --kernels flash_decode,flash_decode_paged,grouped_gemm \
    --iters 1 --warmup 1; then
    echo "PERF_GATE: sweep smoke FAILED"
    exit 1
fi

echo "== roofline regression compare (history tail) =="
python tools/bench_compare.py --history --strict
rc=$?
if [ "$rc" -eq 2 ]; then
    echo "PERF_GATE: no comparable history yet (need 2 runs)" \
         "- pass with warning"
    exit 0
fi
if [ "$rc" -eq 0 ]; then
    echo "PERF_GATE: OK"
else
    echo "PERF_GATE: regression gate FAILED"
fi
exit $rc
