#!/usr/bin/env bash
# Fleet traffic-plane smoke — the full router/membership/SLO matrix
# (tests/test_fleet.py including the slow arms: the mixed-SLO storm
# differential, the subprocess-replica fleet with the AOT warm join,
# and the example) plus the SLO-aware preemption-victim tests riding
# in tests/test_resilience.py. This is the focused loop for iterating
# on triton_dist_tpu/fleet/ alone; tier-1 (tools/tier1.sh) runs the
# lean arms under its 870 s budget. Archives the pass count next to
# the log and reports the delta vs the previous run, tier1.sh-style.
# Run from the repo root: bash tools/fleet_smoke.sh
set -o pipefail
rm -f /tmp/_fleet_smoke.log
# NO `-m 'not slow'` here: this loop exists to run the FULL fleet
# matrix, including the arms tier-1's budget pushes behind the slow
# mark (the storm goodput differential, the subprocess replicas —
# each a fresh process paying its own model build).
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest tests/test_fleet.py \
    "tests/test_resilience.py::test_slo_victim_batch_preempted_before_interactive" \
    "tests/test_resilience.py::test_slo_victim_uniform_classes_degenerate_to_blind_bitwise" \
    "tests/test_observability.py::test_bench_compare_fleet_row_directions" \
    "tests/test_examples.py::test_fleet_router_example_runs" \
    -q -p no:cacheprovider -p no:xdist -p no:randomly \
    2>&1 | tee /tmp/_fleet_smoke.log
rc=${PIPESTATUS[0]}
passed=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_fleet_smoke.log | tr -cd . | wc -c)
last_file=/tmp/_fleet_smoke.last
if [ -f "$last_file" ]; then
    last=$(cat "$last_file")
    delta=$((passed - last))
    [ "$delta" -ge 0 ] && delta="+$delta"
    echo "FLEET_SMOKE_PASSED=$passed (prev $last, delta $delta)"
else
    echo "FLEET_SMOKE_PASSED=$passed"
fi
echo "$passed" > "$last_file"
exit $rc
