#!/usr/bin/env python
"""Perf-regression ledger: diff two bench captures (or the history
tail) with noise-aware thresholds.

`bench.py` emits one JSON line per row and appends every capture —
stamped with a run id, git sha, backend and timestamp — to
`BENCH_history.jsonl` (override the path with TDTPU_BENCH_HISTORY;
set it empty to disable). This CLI closes the loop: nothing previously
compared captures over time, so the bench trajectory was write-only.

Usage:
  python tools/bench_compare.py BENCH_a.json BENCH_b.json
  python tools/bench_compare.py --history [--file BENCH_history.jsonl]
  ... [--threshold 0.25] [--strict] [--json]

Rows are matched by metric name (the LAST row per metric in each
capture wins — a capture file may append multiple runs). Direction is
inferred from the unit: latency rows ("ms") regress UP, throughput
rows (tok/s, fractions) regress DOWN. A delta within --threshold
(default 0.25 — this class of host swings >25% between boxes, see the
ROADMAP tier-1 budget note) is flagged `noise`, beyond it
`improved`/`regressed` with direction + magnitude.

NEVER hard-fails on CPU smoke noise: rows from a cpu backend, and
pairs whose backends differ, are advisory (`cpu-smoke` /
`cross-backend` note) and exit 0 regardless. --strict exits 1 only
when a SAME-backend, non-cpu row regressed past the threshold — the
only comparison a real chip regression gate should trust. Importable:
`compare(rows_a, rows_b, threshold)` is pure.
"""

import argparse
import json
import os
import sys

DEFAULT_THRESHOLD = 0.25
DEFAULT_HISTORY = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "BENCH_history.jsonl")


def load_rows(path):
    """Read one capture: JSON lines (comments/garbage skipped), keep
    only dict rows that carry a metric and a numeric value."""
    rows = []
    with open(path) as f:
        for ln in f:
            ln = ln.strip()
            if not ln.startswith("{"):
                continue
            try:
                d = json.loads(ln)
            except ValueError:
                continue
            if isinstance(d, dict) and "metric" in d \
                    and isinstance(d.get("value"), (int, float)):
                rows.append(d)
    return rows


def _by_metric(rows):
    out = {}
    for r in rows:                      # last row per metric wins
        out[r["metric"]] = r
    return out


def _lower_is_better(row) -> bool:
    """Regression direction from the unit: latencies ("ms"/"s"/"us",
    including annotated spellings like "s (restart)"), overhead
    percentages ("%") and slowdown factors ("x slowdown") regress UP;
    throughputs (tok/s), fractions and capacity multipliers regress
    DOWN. Plain-seconds rows whose unit string is exotic still
    resolve through the metric-NAME suffix convention every bench row
    follows (`*_ms` / `*_us` / `*_s`, e.g. `aot_warm_start_s`) —
    previously such rows fell through to higher-is-better and a
    warm-start REGRESSION rendered as an improvement."""
    unit = str(row.get("unit", ""))
    # roofline fractions (perf_report sol_frac rows: achieved/SOL) are
    # throughput-like — higher is better — and must resolve FIRST:
    # their metric names end in a latency-looking spelling for some
    # ops, and unit strings like "frac of SOL" carry no "/" to trip
    # the rate-unit rule below
    if str(row.get("metric", "")).endswith("_sol_frac") \
            or "sol" in unit.lower() or "roofline" in unit.lower():
        return False
    head = unit.split()[0] if unit.split() else ""
    if ("ms" in unit and "tok" not in unit) \
            or head in ("s", "us", "ms") or unit == "%" \
            or "slowdown" in unit:
        return True
    if "/" in unit:
        # a rate unit (tok/s, x pages/s, ...) is never a latency,
        # whatever the metric name's suffix says
        return False
    return str(row.get("metric", "")).endswith(("_ms", "_us", "_s"))


def compare(rows_a, rows_b, threshold: float = DEFAULT_THRESHOLD):
    """Pure diff of two captures' rows. Returns a list of per-metric
    dicts: {metric, a, b, delta_pct, direction, flag, notes} — flag in
    {improved, regressed, noise, added, removed}; notes carries the
    advisory markers (cpu-smoke, cross-backend, zero-baseline) that
    make a flagged row non-gating."""
    am, bm = _by_metric(rows_a), _by_metric(rows_b)
    out = []
    for metric in sorted(set(am) | set(bm)):
        ra, rb = am.get(metric), bm.get(metric)
        if ra is None or rb is None:
            out.append({"metric": metric,
                        "a": None if ra is None else ra["value"],
                        "b": None if rb is None else rb["value"],
                        "delta_pct": None, "direction": None,
                        "flag": "added" if ra is None else "removed",
                        "notes": []})
            continue
        a, b = float(ra["value"]), float(rb["value"])
        notes = []
        back_a = str(ra.get("backend", "?"))
        back_b = str(rb.get("backend", "?"))
        if back_a != back_b:
            notes.append("cross-backend")
        if "cpu" in (back_a, back_b) or "none" in (back_a, back_b):
            notes.append("cpu-smoke")
        lower = _lower_is_better(ra)
        if a == 0.0:
            # a zero baseline (outage fallback rows) has no meaningful
            # ratio — report, never flag
            notes.append("zero-baseline")
            out.append({"metric": metric, "a": a, "b": b,
                        "delta_pct": None, "direction": None,
                        "flag": "noise", "notes": notes})
            continue
        delta = (b - a) / abs(a)
        better = (delta < 0) if lower else (delta > 0)
        if abs(delta) < threshold:
            flag = "noise"
        else:
            flag = "improved" if better else "regressed"
        out.append({
            "metric": metric, "a": a, "b": b,
            "delta_pct": round(delta * 100.0, 2),
            "direction": ("lower-is-better" if lower
                          else "higher-is-better"),
            "flag": flag, "notes": notes,
        })
    return out


def gating_regressions(results):
    """The only rows a regression gate should trust: regressed, same
    backend, not a cpu smoke."""
    return [r for r in results
            if r["flag"] == "regressed" and not r["notes"]]


def history_runs(path):
    """Group a BENCH_history.jsonl into runs (by the `run` stamp
    bench.py writes; rows without one fall into a shared legacy
    bucket), ordered oldest -> newest by first appearance."""
    order, runs = [], {}
    for r in load_rows(path):
        run = str(r.get("run", "legacy"))
        if run not in runs:
            runs[run] = []
            order.append(run)
        runs[run].append(r)
    return [(run, runs[run]) for run in order]


def render(results, label_a: str, label_b: str) -> str:
    out = [f"bench compare: {label_a} -> {label_b}"]
    width = max([len(r["metric"]) for r in results] + [6])
    for r in results:
        if r["flag"] in ("added", "removed"):
            out.append(f"  {r['metric']:<{width}s} {r['flag']}")
            continue
        d = r["delta_pct"]
        arrow = "=" if d is None else ("+" if d >= 0 else "")
        notes = (" [" + ",".join(r["notes"]) + "]") if r["notes"] \
            else ""
        out.append(
            f"  {r['metric']:<{width}s} {r['a']:>12.4g} -> "
            f"{r['b']:>12.4g}  "
            f"{'n/a' if d is None else f'{arrow}{d:.1f}%':>8s}  "
            f"{r['flag']}{notes}")
    gates = gating_regressions(results)
    out.append(f"regressions (gating): {len(gates)}"
               + ("" if not gates
                  else "  <- " + ", ".join(g["metric"] for g in gates)))
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("captures", nargs="*",
                    help="two capture files (JSON lines) to diff")
    ap.add_argument("--history", action="store_true",
                    help="diff the last two runs of the history ledger")
    ap.add_argument("--file", default=None,
                    help=f"history ledger path (default "
                         f"TDTPU_BENCH_HISTORY or {DEFAULT_HISTORY})")
    ap.add_argument("--threshold", type=float,
                    default=DEFAULT_THRESHOLD,
                    help="noise threshold as a fraction (default 0.25)")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 on a gating regression (same-backend, "
                         "non-cpu) — never fails on smoke noise")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable result list")
    args = ap.parse_args(argv)

    if args.history:
        path = args.file or os.environ.get("TDTPU_BENCH_HISTORY") \
            or DEFAULT_HISTORY
        if not os.path.exists(path):
            print(f"no history ledger at {path}", file=sys.stderr)
            return 2
        runs = history_runs(path)
        if len(runs) < 2:
            print(f"history has {len(runs)} run(s); need 2",
                  file=sys.stderr)
            return 2
        (la, rows_a), (lb, rows_b) = runs[-2], runs[-1]
        label_a, label_b = f"run {la}", f"run {lb}"
    elif len(args.captures) == 2:
        label_a, label_b = args.captures
        rows_a, rows_b = load_rows(label_a), load_rows(label_b)
    else:
        ap.error("pass two capture files, or --history")
        return 2
    results = compare(rows_a, rows_b, threshold=args.threshold)
    if args.json:
        print(json.dumps(results, indent=1))
    else:
        print(render(results, label_a, label_b))
    if args.strict and gating_regressions(results):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
