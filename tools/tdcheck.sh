#!/usr/bin/env bash
# tdcheck smoke — the static-analysis gate (ISSUE 15), the tp_smoke.sh
# pattern: full registry scan (kernel contracts + comm protocol), the
# paged-KV symbolic race proof, the hot-loop lint over the engine's
# decode-tick program set, and the dead-code lint — all TRACE-ONLY
# (nothing compiles or executes on device), so the whole gate is well
# under a minute and runs as a fast pre-pass in tools/tier1.sh.
# Run from the repo root: bash tools/tdcheck.sh
set -o pipefail
t0=$SECONDS
timeout -k 10 300 env JAX_PLATFORMS=cpu TDTPU_NO_FAKECPUS=1 \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m triton_dist_tpu.analysis "$@" 2>&1 | tail -40
rc=${PIPESTATUS[0]}
echo "TDCHECK_RC=$rc (wall $((SECONDS - t0))s)"
exit $rc
