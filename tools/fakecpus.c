/* LD_PRELOAD shim for the CPU test substrate: report FAKE_NPROC (default 8)
 * CPUs so XLA's PJRT CPU client sizes its thread pools large enough for the
 * Pallas TPU interpreter's blocking io_callbacks (one per virtual device)
 * plus async d2h transfers. On the 1-core CI machine the default pool of 1
 * deadlocks as soon as a >16KB buffer transfer queues behind a blocked
 * device callback. Threads timeshare the single core; correctness over
 * speed — this is a test substrate, not the TPU path. */
#define _GNU_SOURCE
#include <sched.h>
#include <stdlib.h>
#include <string.h>
#include <unistd.h>

static int fake_n(void) {
  const char *e = getenv("FAKE_NPROC");
  int n = e ? atoi(e) : 8;
  return n > 0 ? n : 8;
}

int sched_getaffinity(pid_t pid, size_t cpusetsize, cpu_set_t *mask) {
  (void)pid;
  int n = fake_n();
  if (cpusetsize < CPU_ALLOC_SIZE(n)) n = 8 * (int)cpusetsize;
  CPU_ZERO_S(cpusetsize, mask);
  for (int i = 0; i < n; i++) CPU_SET_S(i, cpusetsize, mask);
  return 0;
}

int get_nprocs(void) { return fake_n(); }
int get_nprocs_conf(void) { return fake_n(); }

long sysconf(int name) {
  if (name == _SC_NPROCESSORS_ONLN || name == _SC_NPROCESSORS_CONF)
    return fake_n();
  /* forward everything else */
  long (*real)(int) = NULL;
  if (!real) {
    extern long __sysconf(int);
    return __sysconf(name);
  }
  return real(name);
}
