#!/usr/bin/env bash
# Sequence-parallel serving smoke — the sp=4-vs-sp=1 bitwise
# differential suite (tests/test_sp_serving.py: sampled/spec,
# chunked+overlap, preemption+host-tier+chaos arms that tier-1's
# 870 s budget pushes behind the slow mark, plus the tier-1 core and
# the sp kernel oracles in tests/test_sp_decode.py) on the forced
# multi-device CPU mesh — the focused loop for iterating on the
# long-context layer alone (tp_smoke.sh pattern). Archives the pass
# count next to the log and reports the delta vs the previous run.
# Run from the repo root: bash tools/sp_smoke.sh
set -o pipefail
rm -f /tmp/_sp_smoke.log
# NO `-m 'not slow'` here: this loop exists to run the FULL sp
# differential matrix.
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest tests/test_sp_serving.py tests/test_sp_decode.py \
    "tests/test_examples.py::test_long_context_example_runs" \
    -q -p no:cacheprovider -p no:xdist -p no:randomly \
    2>&1 | tee /tmp/_sp_smoke.log
rc=${PIPESTATUS[0]}
passed=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_sp_smoke.log | tr -cd . | wc -c)
last_file=/tmp/_sp_smoke.last
if [ -f "$last_file" ]; then
    last=$(cat "$last_file")
    delta=$((passed - last))
    [ "$delta" -ge 0 ] && delta="+$delta"
    echo "SP_SMOKE_PASSED=$passed (prev $last, delta $delta)"
else
    echo "SP_SMOKE_PASSED=$passed"
fi
echo "$passed" > "$last_file"
exit $rc
