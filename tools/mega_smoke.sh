#!/usr/bin/env bash
# Megakernel paged-serving smoke — the FULL mega-vs-per-op
# differential matrix (tests/test_mega_paged.py: kernel oracles,
# dispatch-count guard, greedy/int8/overlap/chunked/preemption serving
# arms) plus the contiguous megakernel suite (tests/test_mega.py) and
# the AOT warm-start tests (tests/test_aot_serving.py), on the same
# CPU substrate tier-1 uses. No `-m 'not slow'`: this loop exists to
# run the arms tier-1's 870 s budget pushes behind the slow mark.
# Archives the pass count and reports the delta vs the previous run,
# tier1.sh-style. Run from the repo root: bash tools/mega_smoke.sh
set -o pipefail
rm -f /tmp/_mega_smoke.log
timeout -k 10 900 env JAX_PLATFORMS=cpu \
    python -m pytest tests/test_mega_paged.py tests/test_mega.py \
    tests/test_aot_serving.py \
    -q -p no:cacheprovider -p no:xdist -p no:randomly \
    2>&1 | tee /tmp/_mega_smoke.log
rc=${PIPESTATUS[0]}
passed=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_mega_smoke.log | tr -cd . | wc -c)
last_file=/tmp/_mega_smoke.last
if [ -f "$last_file" ]; then
    last=$(cat "$last_file")
    delta=$((passed - last))
    [ "$delta" -ge 0 ] && delta="+$delta"
    echo "MEGA_SMOKE_PASSED=$passed (prev $last, delta $delta)"
else
    echo "MEGA_SMOKE_PASSED=$passed"
fi
echo "$passed" > "$last_file"
exit $rc
