#!/usr/bin/env bash
# MoE serving smoke — the FULL Qwen3MoE serving matrix
# (tests/test_moe_serving.py: greedy/sampled/spec x prefix cache,
# chunked prefill, overlap, preemption, host tier, int8, chaos,
# disaggregation, the EP + hybrid-mesh arms and the example) on the
# forced multi-device CPU mesh — the focused loop for iterating on the
# MoE serving layer alone, since tier-1's 870 s budget keeps only the
# greedy differential + churn guard + units (the tp_smoke/disagg_smoke
# pattern). Archives the pass count next to the log and reports the
# delta vs the previous run, tier1.sh-style.
# Run from the repo root: bash tools/moe_smoke.sh
set -o pipefail
rm -f /tmp/_moe_smoke.log
# NO `-m 'not slow'` here: this loop exists to run the whole matrix.
timeout -k 10 900 env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest tests/test_moe_serving.py \
    "tests/test_examples.py::test_moe_serving_example_runs" \
    -q -p no:cacheprovider -p no:xdist -p no:randomly \
    2>&1 | tee /tmp/_moe_smoke.log
rc=${PIPESTATUS[0]}
passed=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_moe_smoke.log | tr -cd . | wc -c)
last_file=/tmp/_moe_smoke.last
if [ -f "$last_file" ]; then
    last=$(cat "$last_file")
    delta=$((passed - last))
    [ "$delta" -ge 0 ] && delta="+$delta"
    echo "MOE_SMOKE_PASSED=$passed (prev $last, delta $delta)"
else
    echo "MOE_SMOKE_PASSED=$passed"
fi
echo "$passed" > "$last_file"
exit $rc
