#!/usr/bin/env bash
# Observability smoke — the FULL observability matrix (tier-1's 870 s
# budget keeps only the cheap arms: the telemetry units, the SLO
# partition burst, the inline cross-plane flow assertions riding the
# disagg churn guard). This script runs EVERYTHING — the threaded
# TokenServer(disagg=True, prefill_workers=2) merged-trace run, the
# disagg trace-on==off bitwise arm, the slow telemetry/disagg arms —
# on the forced multi-device CPU mesh, and archives the pass count
# with a delta vs the previous run (tp_smoke.sh/disagg_smoke.sh
# pattern). Run from the repo root: bash tools/obs_smoke.sh
set -o pipefail
rm -f /tmp/_obs_smoke.log
timeout -k 10 900 env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest tests/test_observability.py \
    tests/test_telemetry.py tests/test_disagg.py \
    -q -p no:cacheprovider -p no:xdist -p no:randomly \
    2>&1 | tee /tmp/_obs_smoke.log
rc=${PIPESTATUS[0]}
passed=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_obs_smoke.log | tr -cd . | wc -c)
last_file=/tmp/_obs_smoke.last
if [ -f "$last_file" ]; then
    last=$(cat "$last_file")
    delta=$((passed - last))
    [ "$delta" -ge 0 ] && delta="+$delta"
    echo "OBS_SMOKE_PASSED=$passed (prev $last, delta $delta)"
else
    echo "OBS_SMOKE_PASSED=$passed"
fi
echo "$passed" > "$last_file"
exit $rc
