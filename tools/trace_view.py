#!/usr/bin/env python
"""Summarize a TDTPU_TRACE dump without perfetto.

The serving stack (runtime/telemetry.py) dumps Chrome trace-event JSON
on exit — perfetto-loadable, but a terminal answer is often enough.
This CLI reads one dump and prints:

- per-phase HOST time shares (bookkeep/dispatch/land/retire/drafter/
  step as a fraction of total poll time) and total DEVICE occupancy,
- the top-k slowest polls (seq + duration — the stalls worth opening
  perfetto for),
- a per-request table (status, tokens, ttft_ms) plus the ttft_ms /
  inter_token_ms histogram summary from the embedded metrics snapshot.

Usage: python tools/trace_view.py /path/to/trace.json [--top 5]
No dependencies beyond the stdlib; importable (`summarize(dump)`) so
tests and notebooks can reuse the formatting.
"""

import argparse
import json
import sys


def _fmt_ms(us: float) -> str:
    return f"{us / 1e3:8.3f}ms"


def summarize(dump: dict, top_k: int = 5) -> str:
    """Render one dumped trace (the dict form of the JSON file) as a
    terminal report. Pure function: no I/O, returns the text."""
    events = dump.get("traceEvents", [])
    spans = [e for e in events if e.get("ph") == "X"]
    polls = [e for e in spans if e.get("name") == "poll"]
    host = [e for e in spans
            if e.get("tid") == 0 and e.get("name") != "poll"]
    device = [e for e in spans if e.get("tid") == 1]
    instants = [e for e in events if e.get("ph") == "i"]

    out = []
    poll_total = sum(e["dur"] for e in polls)
    out.append(f"polls: {len(polls)}  total {poll_total / 1e3:.3f}ms  "
               f"instants: {len(instants)}")

    # --- per-phase host time shares (vs total poll time)
    if polls:
        by_phase = {}
        for e in host:
            by_phase.setdefault(e["name"], [0.0, 0])
            by_phase[e["name"]][0] += e["dur"]
            by_phase[e["name"]][1] += 1
        out.append("host phases (share of poll time):")
        for name, (dur, n) in sorted(by_phase.items(),
                                     key=lambda kv: -kv[1][0]):
            share = dur / poll_total if poll_total else 0.0
            out.append(f"  {name:<12s} {dur / 1e3:9.3f}ms "
                       f"{share:6.1%}  (n={n})")
        dev_total = sum(e["dur"] for e in device)
        out.append(f"device occupancy: {dev_total / 1e3:.3f}ms over "
                   f"{len(device)} dispatches "
                   f"({dev_total / poll_total if poll_total else 0.0:.1%} "
                   f"of poll time)")

    # --- top-k slowest polls
    if polls:
        out.append(f"top {min(top_k, len(polls))} slowest polls:")
        ranked = sorted(polls, key=lambda e: -e["dur"])[:top_k]
        for e in ranked:
            seq = e.get("args", {}).get("seq", "?")
            out.append(f"  poll #{seq:<6} {_fmt_ms(e['dur'])}  "
                       f"at {e['ts'] / 1e3:.3f}ms")

    # --- instants (watchdog fires, preemptions, drains, kv demote/
    # promote, and the disagg transfer plane's kv_push/kv_install)
    if instants:
        kinds = {}
        for e in instants:
            kinds[e["name"]] = kinds.get(e["name"], 0) + 1
        out.append("instants: " + "  ".join(
            f"{k}={v}" for k, v in sorted(kinds.items())))

    # --- per-request TTFT table
    reqs = dump.get("requests", {})
    if reqs:
        out.append(f"requests ({len(reqs)}):")
        out.append(f"  {'rid':<12s} {'status':<10s} {'tokens':>6s} "
                   f"{'ttft_ms':>9s}")
        for rid, r in sorted(reqs.items()):
            ttft = r.get("ttft_ms")
            out.append(f"  {rid:<12.12s} {r.get('status', '?'):<10s} "
                       f"{r.get('tokens', 0):>6d} "
                       f"{'-' if ttft is None else format(ttft, '9.3f')}")

    # --- latency histograms from the embedded metrics snapshot
    metrics = dump.get("metrics", {})
    for key in ("ttft_ms", "inter_token_ms", "poll_ms"):
        m = metrics.get(key)
        if isinstance(m, dict) and m.get("count"):
            out.append(f"{key}: n={m['count']} p50={m['p50']} "
                       f"p95={m['p95']} p99={m['p99']}")
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", help="TDTPU_TRACE dump (JSON)")
    ap.add_argument("--top", type=int, default=5,
                    help="how many slowest polls to list")
    args = ap.parse_args(argv)
    with open(args.trace) as f:
        dump = json.load(f)
    print(summarize(dump, top_k=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
