#!/usr/bin/env python
"""Summarize a TDTPU_TRACE dump without perfetto.

The serving stack (runtime/telemetry.py) dumps Chrome trace-event JSON
on exit — perfetto-loadable, but a terminal answer is often enough.
This CLI reads one dump and prints:

- per-phase HOST time shares (bookkeep/dispatch/land/retire/drafter/
  step as a fraction of total poll time), total DEVICE occupancy, and
  per-PLANE time (every named track beyond host/device — the disagg
  prefill workers each own one),
- the top-k slowest polls (seq + duration — the stalls worth opening
  perfetto for),
- the cross-plane FLOW pairs (route -> prefill compute -> kv_push ->
  kv_install arrow chains) with per-request transfer latency,
- a per-request table (status, tokens, ttft_ms, transfer_ms) plus the
  ttft_ms / inter_token_ms histogram summary from the embedded
  metrics snapshot,
- the fleet HA event line (replica_death / breaker_open /
  breaker_close / router_failover instants) so a resteer or failover
  is visible in the terminal report, not only in perfetto.

Usage: python tools/trace_view.py /path/to/trace.json [--top 5]
       python tools/trace_view.py /path/to/trace.json --json
--json emits the machine-readable analysis (the `analyze(dump)` dict)
so CI and tools/bench_compare.py can consume traces. No dependencies
beyond the stdlib; importable (`analyze(dump)` / `summarize(dump)`)
so tests and notebooks can reuse the analysis and formatting.
"""

import argparse
import json
import sys


def _fmt_ms(us: float) -> str:
    return f"{us / 1e3:8.3f}ms"


def analyze(dump: dict, top_k: int = 5) -> dict:
    """Digest one dumped trace (the dict form of the JSON file) into a
    plain machine-readable dict — the single source both the text
    report and the --json output render. Pure function, stdlib only."""
    events = dump.get("traceEvents", [])
    tracks = {0: "host phases", 1: "device occupancy"}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            tracks[e.get("tid", 0)] = e.get("args", {}).get(
                "name", str(e.get("tid")))
    spans = [e for e in events if e.get("ph") == "X"]
    polls = [e for e in spans if e.get("name") == "poll"]
    # the PHASE table covers the scheduler's named host phases only —
    # other tid-0 spans (poll itself, the disagg kv_install, which is
    # stamped INSIDE the bookkeep phase) would double-count wall time
    # already attributed to a phase
    _PHASES = ("bookkeep", "dispatch", "land", "retire", "drafter",
               "step")
    host = [e for e in spans
            if e.get("tid") == 0 and e.get("name") in _PHASES]
    device = [e for e in spans if e.get("tid") == 1]
    instants = [e for e in events if e.get("ph") == "i"]
    flows = [e for e in events if e.get("ph") in ("s", "t", "f")]

    poll_total = sum(e["dur"] for e in polls)
    out = {
        "polls": {"n": len(polls),
                  "total_ms": round(poll_total / 1e3, 3)},
        "phases": {},
        "planes": {},
        "device": {},
        "slowest_polls": [],
        "instants": {},
        "flows": [],
        "requests": [],
        "metrics": {},
    }

    by_phase = {}
    for e in host:
        d, n = by_phase.get(e["name"], (0.0, 0))
        by_phase[e["name"]] = (d + e["dur"], n + 1)
    for name, (dur, n) in by_phase.items():
        out["phases"][name] = {
            "ms": round(dur / 1e3, 3), "n": n,
            "share": round(dur / poll_total, 4) if poll_total else 0.0}

    # per-plane time: every track beyond host(0)/device(1) — the
    # disagg prefill workers — plus the two standard tracks, so the
    # merged timeline's time split reads at a glance
    # a plane's time is the UNION of its span intervals, not their
    # sum — host phase spans nest inside poll spans (and kv_install
    # inside bookkeep), so a plain sum double-counts the host track
    # against the worker tracks this table exists to compare
    by_tid: dict = {}
    for e in spans:
        by_tid.setdefault(e.get("tid", 0), []).append(
            (e["ts"], e["ts"] + e["dur"]))
    plane_ms = {}
    for tid, ivals in by_tid.items():
        ivals.sort()
        busy, cur_s, cur_e = 0.0, None, None
        for s, t in ivals:
            if cur_e is None or s > cur_e:
                if cur_e is not None:
                    busy += cur_e - cur_s
                cur_s, cur_e = s, t
            elif t > cur_e:
                cur_e = t
        if cur_e is not None:
            busy += cur_e - cur_s
        plane_ms[tid] = (busy, len(ivals))
    total_plane = sum(d for d, _ in plane_ms.values())
    for tid, (dur, n) in sorted(plane_ms.items()):
        out["planes"][tracks.get(tid, f"track {tid}")] = {
            "ms": round(dur / 1e3, 3), "spans": n,
            "share": (round(dur / total_plane, 4)
                      if total_plane else 0.0)}

    dev_total = sum(e["dur"] for e in device)
    out["device"] = {
        "ms": round(dev_total / 1e3, 3), "dispatches": len(device),
        "share_of_poll": (round(dev_total / poll_total, 4)
                          if poll_total else 0.0)}

    for e in sorted(polls, key=lambda e: -e["dur"])[:top_k]:
        out["slowest_polls"].append(
            {"seq": e.get("args", {}).get("seq"),
             "ms": round(e["dur"] / 1e3, 3),
             "at_ms": round(e["ts"] / 1e3, 3)})

    for e in instants:
        out["instants"][e["name"]] = out["instants"].get(
            e["name"], 0) + 1

    # fleet HA events pulled out of the generic instant counts: the
    # resteer/failover story of a merged fleet trace, otherwise
    # invisible among the kv_push/kv_install traffic
    _HA = ("replica_death", "breaker_open", "breaker_close",
           "router_failover")
    out["ha_events"] = {k: out["instants"][k] for k in _HA
                        if k in out["instants"]}

    # flow chains (cross-plane request journeys): group by id, order
    # by ts; transfer latency = last push step -> the "f" arrowhead
    # (kv_install). rid rides in args on every event of a chain.
    chains = {}
    for e in sorted(flows, key=lambda e: e["ts"]):
        chains.setdefault(e.get("id"), []).append(e)
    transfer_by_rid = {}
    for fid, evs in sorted(chains.items()):
        rid = next((e.get("args", {}).get("rid") for e in evs
                    if e.get("args", {}).get("rid")), None)
        fin = next((e for e in evs if e["ph"] == "f"), None)
        push = None
        for e in evs:
            if e.get("args", {}).get("at") == "kv_push":
                push = e          # the LAST push wins (retries)
        latency = (round((fin["ts"] - push["ts"]) / 1e3, 3)
                   if fin is not None and push is not None else None)
        out["flows"].append({
            "id": fid, "rid": rid, "events": len(evs),
            "hops": [(tracks.get(e.get("tid", 0), str(e.get("tid"))),
                      e.get("args", {}).get("at") or e["ph"])
                     for e in evs],
            "complete": fin is not None,
            "transfer_ms": latency,
        })
        if rid is not None and latency is not None:
            transfer_by_rid[rid] = latency

    for rid, r in sorted(dump.get("requests", {}).items()):
        out["requests"].append({
            "rid": rid, "status": r.get("status", "?"),
            "tokens": r.get("tokens", 0),
            "ttft_ms": r.get("ttft_ms"),
            "transfer_ms": transfer_by_rid.get(rid),
        })

    metrics = dump.get("metrics", {})
    for key, m in metrics.items():
        base = key.split("{", 1)[0]
        if base in ("ttft_ms", "inter_token_ms", "poll_ms",
                    "kv_transfer_latency_ms") \
                and isinstance(m, dict) and m.get("count"):
            out["metrics"][key] = m
    return out


def summarize(dump: dict, top_k: int = 5) -> str:
    """Render one dumped trace as a terminal report. Pure function:
    no I/O, returns the text."""
    a = analyze(dump, top_k=top_k)
    out = []
    n_inst = sum(a["instants"].values())
    out.append(f"polls: {a['polls']['n']}  total "
               f"{a['polls']['total_ms']:.3f}ms  instants: {n_inst}")

    if a["polls"]["n"]:
        out.append("host phases (share of poll time):")
        for name, p in sorted(a["phases"].items(),
                              key=lambda kv: -kv[1]["ms"]):
            out.append(f"  {name:<12s} {p['ms']:9.3f}ms "
                       f"{p['share']:6.1%}  (n={p['n']})")
        d = a["device"]
        out.append(f"device occupancy: {d['ms']:.3f}ms over "
                   f"{d['dispatches']} dispatches "
                   f"({d['share_of_poll']:.1%} of poll time)")

    # per-plane time (the disagg prefill workers' tracks next to the
    # host/device pair — the merged-timeline split)
    if len(a["planes"]) > 2:
        out.append("planes (share of span time):")
        for name, p in a["planes"].items():
            out.append(f"  {name:<20s} {p['ms']:9.3f}ms "
                       f"{p['share']:6.1%}  ({p['spans']} spans)")

    if a["slowest_polls"]:
        out.append(f"top {len(a['slowest_polls'])} slowest polls:")
        for p in a["slowest_polls"]:
            seq = p["seq"] if p["seq"] is not None else "?"
            out.append(f"  poll #{seq:<6} {_fmt_ms(p['ms'] * 1e3)}  "
                       f"at {p['at_ms']:.3f}ms")

    if a["instants"]:
        out.append("instants: " + "  ".join(
            f"{k}={v}" for k, v in sorted(a["instants"].items())))

    # HA timeline events (replica deaths, breaker trips/readmissions,
    # router failovers) — the "what went wrong and when" line
    if a.get("ha_events"):
        out.append("fleet ha events: " + "  ".join(
            f"{k}={v}" for k, v in sorted(a["ha_events"].items())))

    # cross-plane flow chains (disagg: route -> compute -> kv_push ->
    # kv_install per request)
    if a["flows"]:
        done = sum(1 for fl in a["flows"] if fl["complete"])
        out.append(f"flows: {len(a['flows'])} chains "
                   f"({done} complete)")
        for fl in a["flows"][:top_k]:
            hops = " -> ".join(f"{at}@{plane}"
                               for plane, at in fl["hops"])
            lat = ("-" if fl["transfer_ms"] is None
                   else f"{fl['transfer_ms']:.3f}ms")
            out.append(f"  rid={fl['rid']} transfer={lat}  {hops}")

    if a["requests"]:
        out.append(f"requests ({len(a['requests'])}):")
        out.append(f"  {'rid':<12s} {'status':<10s} {'tokens':>6s} "
                   f"{'ttft_ms':>9s} {'transfer_ms':>11s}")
        for r in a["requests"]:
            ttft = r["ttft_ms"]
            tr = r["transfer_ms"]
            out.append(
                f"  {r['rid']:<12.12s} {r['status']:<10s} "
                f"{r['tokens']:>6d} "
                f"{'-' if ttft is None else format(ttft, '9.3f')} "
                f"{'-' if tr is None else format(tr, '11.3f')}")

    for key, m in a["metrics"].items():
        out.append(f"{key}: n={m['count']} p50={m['p50']} "
                   f"p95={m['p95']} p99={m['p99']}")
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", help="TDTPU_TRACE dump (JSON)")
    ap.add_argument("--top", type=int, default=5,
                    help="how many slowest polls to list")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable analysis instead "
                         "of the text report (CI / bench_compare)")
    args = ap.parse_args(argv)
    with open(args.trace) as f:
        dump = json.load(f)
    if args.json:
        print(json.dumps(analyze(dump, top_k=args.top), indent=1))
    else:
        print(summarize(dump, top_k=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
