"""Streaming socket serving: server + chat client over TCP (reference
flow: `mega_triton_kernel/test/models/model_server.py:265` server +
`chat.py:207` client — prompt in, sampled tokens streamed back).

Run with no args to see the full two-process flow: this script spawns
ITSELF with --serve as the server process, waits for its PORT line,
then streams a prompt through the socket and prints chunks as they
arrive. `--serve` runs the server alone (connect with
triton_dist_tpu.serving.request_stream or any line-JSON TCP client).
"""

import argparse
import os
import select
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import _common  # noqa: E402
_common.bootstrap()              # widen the CPU substrate BEFORE jax loads


def run_server(max_requests, port):
    from triton_dist_tpu.models import AutoLLM, Engine
    from triton_dist_tpu.models.config import tiny_qwen3
    from triton_dist_tpu.runtime import initialize_distributed
    from triton_dist_tpu.serving import ByteTokenizer, TokenServer

    ctx = initialize_distributed()
    n = ctx.tp_size()
    cfg = tiny_qwen3(n)
    model = AutoLLM.from_config(cfg, ctx.mesh)
    eng = Engine(model, max_seq=64, backend="dist", sampling="top_p",
                 temperature=0.8)
    srv = TokenServer(eng, ByteTokenizer(cfg.vocab_size),
                      batch=max(n, 2), port=port, chunk=4)
    # the client (or test) parses this line to find the socket
    print(f"PORT {srv.port}", flush=True)
    srv.serve_forever(max_requests=max_requests)


def run_client(port):
    from triton_dist_tpu.serving import request_stream

    print(f"client: streaming from 127.0.0.1:{port}")
    chunks = []
    for msg in request_stream("127.0.0.1", port, "hello tpu",
                              gen_len=12, seed=1):
        if msg.get("done"):
            print(f"client: done, {msg['n_tokens']} tokens "
                  f"in {len(chunks)} chunks")
        else:
            chunks.append(msg["text"])
            print(f"client: chunk {len(chunks)}: {msg['text']!r}")
    # the stream must actually be incremental: gen_len=12 at chunk=4
    # arrives as 3 separate messages, not one
    assert len(chunks) == 3, chunks
    assert sum(len(c) for c in chunks) == 12
    return "".join(chunks)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--serve", action="store_true")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--max-requests", type=int, default=1)
    args = ap.parse_args()
    if args.serve:
        return run_server(args.max_requests, args.port)

    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--serve",
         "--max-requests", "1"],
        stdout=subprocess.PIPE, text=True, env=dict(os.environ))
    try:
        port = None
        deadline = time.time() + 600
        while time.time() < deadline and port is None:
            r, _, _ = select.select([proc.stdout], [], [], 1.0)
            if not r:
                if proc.poll() is not None:
                    raise RuntimeError("server exited before PORT line")
                continue
            line = proc.stdout.readline()
            if not line:
                raise RuntimeError("server closed stdout before PORT")
            if line.startswith("PORT "):
                port = int(line.split()[1])
        assert port, "server never reported its port"
        text = run_client(port)
        print(f"streamed reply: {text!r}")
        print("OK")
    finally:
        # never orphan the server: it exits after max_requests on the
        # happy path; on any client failure, terminate it
        if proc.poll() is None:
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.terminate()
                try:
                    proc.wait(timeout=15)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()


if __name__ == "__main__":
    main()
