"""Overlap scheduler: the host loop hides behind device compute
(the SGLang zero-overhead overlap design, 2312.07104 — PAPERS.md) —
plus the int8 paged pool that halves decode KV bandwidth.

A synchronous serving poll blocks on the previous tick's readback
before any host bookkeeping runs (admissions, drafting, the radix-tree
inserts, socket writes) — so at large slot counts the HOST becomes the
inter-token floor even though the device finished long ago. With
``ContinuousScheduler(overlap=True)`` the driver dispatches tick N+1
BEFORE reading back tick N: the same host work now runs while the
device computes, every blocking readback is one coalesced
``jax.device_get``, and token streams stay BITWISE identical.

This demo serves the same request mix three ways and prints:
- overlap off/on: identical streams, and the ``host_ms_per_poll``
  gauge (dispatch-to-dispatch host time minus device wait — the work
  the pipeline hides);
- the int8 PAGED pool (``kv_dtype=jnp.int8``): per-page scale planes
  ride the page payload through sharing/CoW/eviction, the paged flash
  kernel dequants in-kernel, and streams match the contiguous-int8
  reference bitwise while the pool holds ~2x the pages per byte.

Run on CPU (no TPU needed):
  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/15_overlap_scheduler.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import _common  # noqa: E402
_common.bootstrap()              # widen the CPU substrate BEFORE jax loads

import numpy as np  # noqa: E402


def main():
    import jax.numpy as jnp
    from triton_dist_tpu.models import (AutoLLM, ContinuousScheduler,
                                        Engine, Request)
    from triton_dist_tpu.models.config import tiny_qwen3
    from triton_dist_tpu.runtime import initialize_distributed

    ctx = initialize_distributed()
    cfg = tiny_qwen3(ctx.tp_size())
    model = AutoLLM.from_config(cfg, ctx.mesh)

    rng = np.random.RandomState(0)
    prefix = rng.randint(0, cfg.vocab_size, size=(12,)).astype(np.int32)

    def requests():
        out = []
        r2 = np.random.RandomState(1)
        for i in range(5):
            tail = r2.randint(0, cfg.vocab_size,
                              size=(4 + 3 * (i % 3),)).astype(np.int32)
            ids = np.concatenate([prefix, tail]) if i % 2 else tail
            out.append(Request(rid=i, ids=ids.astype(np.int32),
                               gen_len=10 + 2 * (i % 2), seed=7 + i))
        return out

    # --- overlap off vs on over the paged pool with prefix sharing
    eng = Engine(model, max_seq=64, backend="xla")
    runs = {}
    for overlap in (False, True):
        sched = ContinuousScheduler(eng, batch=3, chunk=4, paged=True,
                                    page=8, prefill_budget=4,
                                    overlap=overlap)
        runs[overlap] = (sched.run(requests()), sched.stats())

    for rid, toks in runs[False][0].items():
        assert np.array_equal(runs[True][0][rid], toks), \
            f"rid={rid}: overlap changed the stream"
    print("overlap-on streams bitwise identical to overlap-off: yes")
    for overlap in (False, True):
        st = runs[overlap][1]
        print(f"  overlap={str(overlap):5s} host_ms_per_poll="
              f"{st['host_ms_per_poll']:.2f} "
              f"device_wait_s={st['device_wait_s']:.3f}")
    print("  (host_ms_per_poll is the work the dispatch-ahead loop "
          "hides under device compute; on real chips the sync loop's "
          "inter-token floor is exactly this number)")

    # --- int8 paged pool vs the contiguous int8 reference
    eng8 = Engine(model, max_seq=64, backend="xla", kv_dtype=jnp.int8)
    contig = ContinuousScheduler(eng8, batch=3, chunk=4).run(requests())
    paged8 = ContinuousScheduler(eng8, batch=3, chunk=4, paged=True,
                                 page=8, overlap=True)
    got = paged8.run(requests())
    for rid, toks in contig.items():
        assert np.array_equal(got[rid], toks), \
            f"rid={rid}: int8 paged diverged from contiguous int8"
    st = paged8.stats()
    print("int8 paged pool (overlap on) bitwise identical to the "
          "contiguous int8 cache: yes")
    print(f"  prefix hits={st['hits']} — scale planes follow pages "
          f"through the radix tree for free")
    print("OK")


if __name__ == "__main__":
    main()
