"""TP training through the framework kernels: the forward runs
custom-VJP ag_gemm / gemm_rs and the differentiable Pallas flash
attention; each backward contraction is itself a fused comm kernel
(kernels/grad.py). Reference analog: training through the
torch.autograd Function wrappers over the dist ops."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import _common  # noqa: E402
_common.bootstrap()              # widen the CPU substrate BEFORE jax loads

import jax
import jax.numpy as jnp
import numpy as np

from triton_dist_tpu.models import AutoLLM
from triton_dist_tpu.models.config import tiny_qwen3
from triton_dist_tpu.runtime import initialize_distributed


def main():
    ctx = initialize_distributed()
    n = ctx.tp_size()
    cfg = tiny_qwen3(n)
    model = AutoLLM.from_config(cfg, ctx.mesh)

    rng = np.random.RandomState(0)
    B, S = 2, 2 * n                       # B*S divisible by tp
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S)), jnp.int32)
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S)), jnp.int32)

    def loss_fn(m, ids, labels):
        logits = m.forward_train(ids, mode="train")   # the kernel path
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.mean(
            jnp.take_along_axis(logp, labels[..., None], axis=-1))

    @jax.jit
    def sgd_step(m, ids, labels, lr=5e-2):
        loss, grads = jax.value_and_grad(loss_fn)(m, ids, labels)
        return loss, jax.tree.map(
            lambda p, g: p - lr * g if g is not None else p, m, grads)

    for step in range(5):
        loss, model = sgd_step(model, ids, labels)
        # materialize the whole step before launching the next: the CPU
        # interpreter substrate is per-execution (tests/test_train_e2e.py)
        jax.block_until_ready(model)
        print(f"step {step}: loss {float(loss):.4f}")
    print("loss decreased through the Pallas training path: OK")


if __name__ == "__main__":
    main()
