"""Resilient serving: a tiny KV pool survives oversubscription.

The degradation ladder (triton_dist_tpu/models/scheduler.py): a paged
admission that cannot get pages — even after LRU eviction — PREEMPTS a
victim slot instead of rejecting: the victim's prompt + generated
tokens go into the radix prefix tree (the normal retire path), its
pages become evictable, and the request re-queues with a resume
snapshot (evolved PRNG key, pending spec token). On re-admission the
prefix cache hands the pages back and decode resumes mid-stream. The
demo runs a pool sized for ONE worst-case request under a 4-request
load and asserts every stream is bitwise identical to an ample-pool
run — preemption is invisible in the tokens, it only costs time.

Also shown: bounded admission (max_queue -> submit() returns False,
the server-side busy/backpressure signal), per-request deadlines
(expired requests are cancelled with a visible reason), and the chunk
watchdog surface (stats()['hang'] would carry the HANG verdict).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import _common  # noqa: E402
_common.bootstrap()              # widen the CPU substrate BEFORE jax loads

import numpy as np  # noqa: E402


def main():
    from triton_dist_tpu.models import (AutoLLM, ContinuousScheduler,
                                        Engine, Request)
    from triton_dist_tpu.models.config import tiny_qwen3
    from triton_dist_tpu.runtime import initialize_distributed
    from triton_dist_tpu.serving import ByteTokenizer

    ctx = initialize_distributed()
    n = ctx.tp_size()
    cfg = tiny_qwen3(n)
    model = AutoLLM.from_config(cfg, ctx.mesh)
    eng = Engine(model, max_seq=96, backend="xla")
    tok = ByteTokenizer(cfg.vocab_size)

    page, chunk = 8, 4
    prompts = ["tell me about pages", "preempt me if you must",
               "the third tenant", "last but not least"]
    gen = 12

    def reqs():
        return [Request(rid=i, ids=np.asarray(tok.encode(p), np.int32),
                        gen_len=gen) for i, p in enumerate(prompts)]

    # pool sized for ONE worst-case request (+1 spare group): with 2
    # slots and 4 requests this is heavy oversubscription
    worst = -(-(max(len(tok.encode(p)) for p in prompts) + gen
                + chunk - 1) // page)
    tiny_pool = (worst + 1) * cfg.num_kv_heads + 1

    runs = {}
    for label, npages in (("tiny", tiny_pool), ("ample", None)):
        sched = ContinuousScheduler(eng, batch=2, chunk=chunk,
                                    paged=True, prefix_cache=True,
                                    page=page, num_pages=npages)
        t0 = time.perf_counter()
        runs[label] = sched.run(reqs())
        dt = time.perf_counter() - t0
        st = sched.stats()
        assert not sched.rejected, (
            f"{label} pool unexpectedly rejected: {sched.rejected}")
        print(f"{label:>5} pool ({sched.slots.cache.num_pages} pages): "
              f"{len(prompts)} requests in {dt:.2f}s, "
              f"{st['preemptions']} preemptions, "
              f"{st['evictions']} evictions, 0 rejections")
        if label == "tiny":
            assert st["preemptions"] > 0, "pool was not actually tiny"
            pool = sched.slots.prefix.pool
            assert pool.available + pool.outstanding == pool.num_pages

    for r in reqs():
        assert np.array_equal(runs["tiny"][r.rid], runs["ample"][r.rid]), (
            f"request {r.rid}: preempted stream diverged")
    print("token streams bitwise identical, tiny pool vs ample pool")

    # bounded admission: the waiting line refuses past max_queue
    sched = ContinuousScheduler(eng, batch=1, chunk=chunk, max_queue=2)
    a, b, c = reqs()[:3]
    assert sched.submit(a) and sched.submit(b) and not sched.submit(c)
    print(f"backpressure: 3rd submit refused at max_queue=2 "
          f"(busy_rejections={sched.stats()['busy_rejections']})")
    while not sched.idle:
        sched.poll()

    # deadlines: an expired request is cancelled with a visible reason
    sched = ContinuousScheduler(eng, batch=1, chunk=chunk)
    sched.submit(Request(rid="late", ids=np.asarray(
        tok.encode("no time for this"), np.int32), gen_len=8,
        deadline_ms=0.0))
    while not sched.idle:
        sched.poll()
    print(f"deadline: {sched.rejected['late']!r}")
    print("OK")


if __name__ == "__main__":
    main()
