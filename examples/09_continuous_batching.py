"""Continuous batching: slot-based multi-request serving over the
decode scheduler (reference serving loop: model_server.py:265, grown to
Orca/vLLM-style iteration-level scheduling — PAPERS.md).

Six requests of very different prompt/gen lengths share four decode
slots: the first finisher retires mid-stream and a queued request is
admitted into its freed slot while the others keep decoding — the
decode hot loop stays ONE jitted slot scan per chunk. The demo checks
token-for-token equality against sequential Engine.serve() calls (the
scheduler's core contract) and prints the aggregate throughput win
over serving the same requests one at a time.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import _common  # noqa: E402
_common.bootstrap()              # widen the CPU substrate BEFORE jax loads

import numpy as np  # noqa: E402


def main():
    from triton_dist_tpu.models import (AutoLLM, ContinuousScheduler,
                                        Engine, Request)
    from triton_dist_tpu.models.config import tiny_qwen3
    from triton_dist_tpu.runtime import initialize_distributed

    ctx = initialize_distributed()
    n = ctx.tp_size()
    cfg = tiny_qwen3(n)
    model = AutoLLM.from_config(cfg, ctx.mesh)
    eng = Engine(model, max_seq=64, backend="xla")

    B, chunk = 4, 4
    rng = np.random.RandomState(0)
    spec = [(5, 6), (9, 13), (3, 4), (12, 10), (7, 9), (4, 17)]
    reqs = [Request(rid=i,
                    ids=rng.randint(0, cfg.vocab_size,
                                    size=(L,)).astype(np.int32),
                    gen_len=g)
            for i, (L, g) in enumerate(spec)]

    sched = ContinuousScheduler(eng, batch=B, chunk=chunk)
    t0 = time.perf_counter()
    got = sched.run(reqs)
    dt_batched = time.perf_counter() - t0
    total = sum(len(t) for t in got.values())
    print(f"{len(reqs)} requests through {B} slots: {total} tokens "
          f"in {dt_batched:.2f}s")

    # the contract: every request's tokens == a sequential serve()
    t0 = time.perf_counter()
    for r in reqs:
        want = np.asarray(eng.serve(np.tile(r.ids[None], (B, 1)),
                                    r.gen_len))[0]
        assert np.array_equal(got[r.rid], want), r.rid
    dt_seq = time.perf_counter() - t0
    print(f"token-exact vs sequential serve() "
          f"({dt_seq:.2f}s one-at-a-time vs {dt_batched:.2f}s batched, "
          f"{dt_seq / dt_batched:.1f}x aggregate speedup)")
    print("OK")


if __name__ == "__main__":
    main()
