"""Multi-host launch via the framework's env convention (the torchrun
analog, runtime/bootstrap.py::_maybe_init_multihost): this script
spawns TWO OS processes that join one JAX coordination service and run
a collective over the global mesh. On a real pod slice, run one process
per host with the same env vars (or TDTPU_MULTIHOST=1 on Cloud TPU)."""

import os
import socket
import subprocess
import sys
import textwrap

_CHILD = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, os.environ["TDTPU_REPO"])
    import jax, jax.numpy as jnp, numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P
    from triton_dist_tpu.runtime import initialize_distributed

    ctx = initialize_distributed({"dcn": 2, "tp": 4})
    me = jax.process_index()
    x = jax.make_array_from_callback(
        (16, 4), NamedSharding(ctx.mesh, P(("dcn", "tp"), None)),
        lambda idx: np.full((2, 4), float(idx[0].start), np.float32))
    total = float(jax.jit(jnp.sum)(x))
    print(f"process {me}: {jax.process_count()} processes, "
          f"{len(jax.devices())} global devices, sum={total}")
""")


def main():
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.update({
            "TDTPU_REPO": os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))),
            "PALLAS_AXON_POOL_IPS": "",
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
            "JAX_COORDINATOR_ADDRESS": f"localhost:{port}",
            "JAX_NUM_PROCESSES": "2",
            "JAX_PROCESS_ID": str(pid),
        })
        procs.append(subprocess.Popen([sys.executable, "-c", _CHILD],
                                      env=env))
    rc = [p.wait(timeout=600) for p in procs]
    assert rc == [0, 0], rc
    print("multihost OK")


if __name__ == "__main__":
    main()
