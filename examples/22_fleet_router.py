"""Fleet traffic plane: a prefix-aware, SLO-aware router over N
TokenServer replicas (triton_dist_tpu/fleet/).

One FleetRouter in front of two in-process replicas — each a real
TokenServer on its own socket — shows the three policy layers:

  - PREFIX-AWARE PLACEMENT: the router keeps a shadow index of every
    replica's prefix cache (fed by the done messages it relays), so a
    request sharing a system prompt with earlier traffic lands on the
    replica whose radix tree is already warm and skips that prefill.
    Session affinity (`session` wire field) breaks placement ties so
    one conversation stays on one replica.

  - ELASTIC MEMBERSHIP: health is probed over the existing
    `{"op": "stats"}` protocol request. A replica killed MID-STREAM
    (abrupt socket death, no done) is detected by the EOF, marked
    dead, and the interrupted request is re-served on a survivor —
    greedy same-seed decoding makes the spliced stream bitwise
    seamless. A joining replica is routable the moment add_replica
    returns.

  - SLO-AWARE SHEDDING: under saturation the router sheds `batch`
    (and untagged) requests with a structured error while
    `interactive` traffic keeps its queue slot — the same class
    priorities that drive preemption-victim choice and prefill-budget
    splits inside each replica's scheduler.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import _common  # noqa: E402
_common.bootstrap()              # widen the CPU substrate BEFORE jax loads


def main():
    from triton_dist_tpu.fleet import FleetRouter, InprocReplica
    from triton_dist_tpu.models import AutoLLM, Engine
    from triton_dist_tpu.models.config import tiny_qwen3
    from triton_dist_tpu.runtime import initialize_distributed
    from triton_dist_tpu.serving import ByteTokenizer

    ctx = initialize_distributed()
    cfg = tiny_qwen3(ctx.tp_size())
    model = AutoLLM.from_config(cfg, ctx.mesh)
    engine = Engine(model, max_seq=64, backend="xla")
    tok = ByteTokenizer(cfg.vocab_size)

    # two replicas, one engine: same-config TokenServers share the
    # process-wide jitted programs, so the fleet costs one compile
    replicas = [InprocReplica(f"r{i}", engine, tok, batch=2, chunk=4,
                              paged=True, page=8) for i in range(2)]
    router = FleetRouter(replicas, tok, policy="prefix")

    # ---- prefix-aware placement: follow-ups land warm --------------
    system = "You are a helpful TPU fleet. "
    for i, q in enumerate(("alpha?", "beta!", "gamma.")):
        out = router.run(system + q, gen_len=8, seed=i)
        print(f"prompt {i} -> replica {out['done']['replica']} "
              f"({len(out['token_ids'])} tokens)")
    st = router.stats()
    cache = router.fleet_cache_stats()
    print(f"router_prefix_hit_frac={st['router_prefix_hit_frac']} "
          f"fleet prefill_skip_frac={cache['prefill_skip_frac']:.3f}")
    assert st["router_prefix_hit_frac"] > 0.0

    # ---- session affinity pins a conversation ----------------------
    homes = {router.run(f"{w} something new", gen_len=6, seed=i,
                        session="user-1")["done"]["replica"]
             for i, w in enumerate(("alpha", "bravo", "charlie"))}
    print(f"session user-1 stayed on {sorted(homes)}")
    assert len(homes) == 1

    # ---- mid-stream failover: kill a replica, stream survives ------
    want = router.run("kill me midstream", gen_len=12,
                      seed=3)["token_ids"]
    target, _ = router._route(tok.encode("kill me midstream"), None)
    stream = router.stream("kill me midstream", gen_len=12, seed=3)
    first = next(stream)                      # first chunk relayed...
    router.members.replicas[target].kill()    # ...then the home dies
    router.members.mark_dead(target)
    got = list(first.get("token_ids", []))
    done = None
    for msg in stream:
        if msg.get("done"):
            done = msg
            break
        got.extend(msg["token_ids"])
    survivors = router.members.healthy_rids()
    print(f"replica {target} killed mid-stream -> re-served on "
          f"{survivors} (resteered={done.get('resteered')})")
    assert done.get("error") is None and got == want, "splice broke"

    # ---- SLO-aware shedding under saturation -----------------------
    router.shed_inflight = 0                  # everything is "over"
    shed = router.run("batch job", gen_len=4, slo="batch")
    ok = router.run("human waiting", gen_len=4, slo="interactive")
    print(f"batch under storm: {shed['done']['error']!r}")
    print(f"interactive under storm: {len(ok['token_ids'])} tokens")
    assert "shed" in shed["done"]["error"]
    assert ok["done"].get("error") is None

    router.shutdown()
    print("OK")


if __name__ == "__main__":
    main()
