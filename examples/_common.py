"""Shared example bootstrap. Call `bootstrap()` BEFORE importing jax.

On the virtual CPU mesh substrate (JAX_PLATFORMS=cpu +
--xla_force_host_platform_device_count=N), the Pallas TPU interpreter
issues blocking per-device waits; on hosts with few cores the XLA CPU
client sizes its thread pool from nproc and the interpreted ring
kernels starve. tests/conftest.py and __graft_entry__ widen the pool
with the tools/fakecpus.c LD_PRELOAD shim — this does the same for the
examples by re-exec'ing with the shim loaded. No-op on real TPUs and
on well-provisioned hosts."""

import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def bootstrap():
    if _REPO not in sys.path:
        sys.path.insert(0, _REPO)
    if os.environ.get("JAX_PLATFORMS") != "cpu":
        return
    flags = os.environ.get("XLA_FLAGS", "")
    marker = "--xla_force_host_platform_device_count="
    if marker not in flags:
        return
    n = int(flags.split(marker)[1].split()[0])
    if ((os.cpu_count() or 1) >= 4 * n
            or "fakecpus" in os.environ.get("LD_PRELOAD", "")
            or os.environ.get("TDTPU_NO_FAKECPUS") == "1"):
        return
    shim_src = os.path.join(_REPO, "tools", "fakecpus.c")
    shim = os.path.join(_REPO, "tools", "fakecpus.so")
    if not os.path.exists(shim) and os.path.exists(shim_src):
        subprocess.run(["gcc", "-shared", "-fPIC", "-O2", "-o", shim,
                        shim_src], check=False)
    if not os.path.exists(shim):
        return
    env = dict(os.environ)
    env["LD_PRELOAD"] = (shim + " " + env.get("LD_PRELOAD", "")).strip()
    env.setdefault("FAKE_NPROC", str(max(32, 4 * n)))
    os.execve(sys.executable, [sys.executable] + sys.argv, env)
