"""Host-RAM KV tier: a prefix working set LARGER than the device pool
survives eviction in host memory and comes back bitwise.

Four tenants' system prompts rotate through a device page pool sized
for roughly ONE of them. Without the tier, every return visit finds
its prefix LRU-evicted and re-prefills from scratch. With
`host_pool_pages` set (triton_dist_tpu/models/kv_tier.py + the
residency state machine in models/prefix_cache.py), eviction DEMOTES
each prefix's page-groups to host RAM (one d2h gather across every
layer's pool) and the return visit PROMOTES them back into fresh
device pages (one h2d install) before prefilling only its own suffix —
the effective cache becomes device + host pages. The demo asserts the
token streams are bitwise identical tier-on vs tier-off vs cache-off,
while the printed counters show the spans actually travelling through
the host pool.

Run on CPU (no TPU needed):
  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/14_kv_tiering.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import _common  # noqa: E402
_common.bootstrap()              # widen the CPU substrate BEFORE jax loads

import numpy as np  # noqa: E402


def main():
    from triton_dist_tpu.models import (AutoLLM, ContinuousScheduler,
                                        Engine, Request)
    from triton_dist_tpu.models.config import tiny_qwen3
    from triton_dist_tpu.runtime import initialize_distributed
    from triton_dist_tpu.serving import ByteTokenizer

    ctx = initialize_distributed()
    cfg = tiny_qwen3(ctx.tp_size())
    model = AutoLLM.from_config(cfg, ctx.mesh)
    eng = Engine(model, max_seq=64, backend="xla")
    tok = ByteTokenizer(cfg.vocab_size)

    page, chunk, gen = 8, 4, 6
    tenants = ["Avery's terse TPU sage. ", "Blake, a verbose bard!! ",
               "Casey the careful clerk ", "Devon =) daring daemon. "]
    questions = ["ping?", "again", "more!?"]
    # two visits per tenant, interleaved so every return visit finds
    # its prefix displaced from the device pool by the other tenants
    reqs = [Request(rid=i, ids=np.asarray(
                tok.encode(tenants[i % 4] + questions[i % 3]),
                np.int32), gen_len=gen)
            for i in range(8)]
    pre_tokens = len(tok.encode(tenants[0]))

    # device pool: ~one worst-case slot; host pool: the whole set
    Hkv = cfg.num_kv_heads
    worst = -(-(pre_tokens + 8 + gen + chunk - 1) // page)
    num_pages = worst * Hkv + 1 + Hkv
    host_pages = 4 * worst * Hkv * 2

    runs, stats = {}, {}
    for label, kw in (
            ("cache-off", dict(prefix_cache=False, num_pages=num_pages)),
            ("tier-off", dict(num_pages=num_pages)),
            ("tier-on", dict(num_pages=num_pages,
                             host_pool_pages=host_pages))):
        sched = ContinuousScheduler(eng, batch=1, chunk=chunk,
                                    paged=True, page=page, **kw)
        runs[label] = sched.run(reqs)
        stats[label] = sched.stats()

    on, off = stats["tier-on"], stats["tier-off"]
    print(f"4 tenants x 2 visits, {pre_tokens}-token prefixes, device "
          f"pool {num_pages} pages (~1 slot), host pool {host_pages} "
          f"pages:")
    print(f"  tier-off: hit_rate {off['hit_rate']:.2f}, prefill "
          f"skipped {off['prefill_tokens_skipped']} tokens "
          f"(returning prefixes were evicted)")
    print(f"  tier-on:  hit_rate {on['hit_rate']:.2f}, prefill "
          f"skipped {on['prefill_tokens_skipped']} tokens")
    print(f"            demotions {on['demotions']}, promotions "
          f"{on['promotions']}, host_hits {on['host_hits']}, "
          f"host_pages_resident {on['host_pages_resident']}/"
          f"{on['host_pool_pages']}, restore EMA "
          f"{on['restore_latency_ms']:.2f} ms")

    assert on["demotions"] > 0 and on["promotions"] > 0
    assert on["host_hits"] >= 2
    assert on["prefill_tokens_skipped"] > off["prefill_tokens_skipped"]
    for r in reqs:
        a = runs["tier-on"][r.rid]
        assert np.array_equal(a, runs["tier-off"][r.rid]), r.rid
        assert np.array_equal(a, runs["cache-off"][r.rid]), r.rid
    print("warm-from-host streams bitwise identical to recompute: yes")
    print("OK")


if __name__ == "__main__":
    main()
