"""Minimal serving/chat loop over Engine: each request tokenizes the
prompt, prefills a fresh KV cache, and decodes with the engine's
sampler (reference flow:
`mega_triton_kernel/test/models/model_server.py` + `chat.py` — an
interactive server that tokenizes prompts, prefills, then streams
sampled tokens). Stateless per request: multi-turn chat re-sends the
full transcript as the prompt, the way the reference's chat.py does.

Runs on the tiny random-weight model with a toy byte tokenizer so the
loop works anywhere; swap `tiny_qwen3`/`ByteTokenizer` for
`DenseLLM.from_hf(path, mesh)` + a real tokenizer to serve a
checkpoint."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import _common  # noqa: E402
_common.bootstrap()              # widen the CPU substrate BEFORE jax loads

import numpy as np

from triton_dist_tpu.models import AutoLLM, Engine
from triton_dist_tpu.models.config import tiny_qwen3
from triton_dist_tpu.runtime import initialize_distributed


class ByteTokenizer:
    """Toy byte-level tokenizer capped to the tiny model's vocab."""

    def __init__(self, vocab_size: int):
        self.vocab_size = vocab_size

    def encode(self, text: str):
        return [b % self.vocab_size for b in text.encode()]

    def decode(self, ids):
        return bytes(int(i) % 256 for i in ids).decode("latin-1")


class ChatServer:
    """The reference server's request loop, minus the socket: accept a
    prompt, prefill once, decode with the engine's sampler. Batches the
    prompt to the engine's expected [B, S] layout (B = TP size so the
    row-sharded backends keep their contract)."""

    def __init__(self, model, tokenizer, *, batch: int, max_seq: int = 64,
                 backend: str = "dist", sampling: str = "top_p",
                 temperature: float = 0.8):
        self.tok = tokenizer
        self.batch = batch
        self.engine = Engine(model, max_seq=max_seq, backend=backend,
                             sampling=sampling, temperature=temperature)

    def chat(self, prompt: str, gen_len: int = 8, seed: int = 0) -> str:
        ids = self.tok.encode(prompt) or [0]
        x = np.tile(np.asarray(ids, np.int32)[None], (self.batch, 1))
        out = np.asarray(self.engine.serve(x, gen_len, seed=seed))
        return self.tok.decode(out[0])


def main():
    ctx = initialize_distributed()
    n = ctx.tp_size()
    cfg = tiny_qwen3(n)
    model = AutoLLM.from_config(cfg, ctx.mesh)
    tok = ByteTokenizer(cfg.vocab_size)

    server = ChatServer(model, tok, batch=max(n, 2), backend="dist")
    reply1 = server.chat("hello tpu", gen_len=8, seed=1)
    reply2 = server.chat("hello tpu", gen_len=8, seed=2)
    print(f"prompt 'hello tpu' -> {reply1!r} (seed 1), {reply2!r} (seed 2)")

    # greedy must equal the argmax path bit for bit: the differential
    # check the reference's chat demo leans on implicitly
    greedy = ChatServer(model, tok, batch=max(n, 2), backend="dist",
                        sampling="top_p", temperature=0.0)
    oracle = ChatServer(model, tok, batch=max(n, 2), backend="xla",
                        sampling="greedy")
    a = greedy.chat("determinism", gen_len=8)
    b = oracle.chat("determinism", gen_len=8)
    assert a == b, (a, b)
    print(f"greedy(temp=0) == xla argmax: {a!r} OK")


if __name__ == "__main__":
    main()
