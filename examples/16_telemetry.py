"""Serving telemetry: live latency histograms, a Prometheus scrape,
and a perfetto-ready poll-loop timeline.

The serving stack's observability substrate (runtime/telemetry.py)
gives every scheduler a METRICS REGISTRY — stats() is one deep,
point-in-time snapshot with live ``ttft_ms`` / ``inter_token_ms``
p50/p95/p99 histograms (the Sarathi-Serve tail numbers, measured on
real traffic instead of an offline bench) — and, with tracing on, a
Chrome-trace-event TIMELINE of the poll loop: host phase spans
(bookkeep/dispatch/land/retire/drafter), device-occupancy spans
(dispatch → readback landing), and instants for preemptions and
watchdog fires. Load the dump at https://ui.perfetto.dev or summarize
it in the terminal with tools/trace_view.py.

This demo serves a small burst through a real TokenServer (paged pool,
prefix cache, overlap scheduler, tracing ON) and then:
- fetches the live stats snapshot in-protocol ({"op": "stats"}),
- scrapes the Prometheus ``/metrics`` listener,
- dumps the poll timeline (TDTPU_TRACE) and summarizes it.

Telemetry is exact-by-construction: tracing is host-side only, so the
token streams here are bitwise identical to a telemetry-off server
(asserted in tests/test_telemetry.py).

Run on CPU (no TPU needed):
  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/16_telemetry.py
"""

import json
import os
import socket
import sys
import tempfile
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import _common  # noqa: E402
_common.bootstrap()              # widen the CPU substrate BEFORE jax loads

# the TDTPU_TRACE convention: tracing on + dump-on-exit to this path
TRACE = os.path.join(tempfile.gettempdir(), "tdtpu_example16_trace.json")
os.environ["TDTPU_TRACE"] = TRACE


def main():
    from triton_dist_tpu.models import AutoLLM, Engine
    from triton_dist_tpu.models.config import tiny_qwen3
    from triton_dist_tpu.runtime import initialize_distributed
    from triton_dist_tpu.serving import (ByteTokenizer, TokenServer,
                                         request_stream)

    ctx = initialize_distributed()
    cfg = tiny_qwen3(ctx.tp_size())
    model = AutoLLM.from_config(cfg, ctx.mesh)
    eng = Engine(model, max_seq=64, backend="xla")
    tok = ByteTokenizer(cfg.vocab_size)

    srv = TokenServer(eng, tok, batch=4, chunk=4, paged=True, page=8,
                      prefill_budget=8, overlap=True, metrics_port=0)
    th = threading.Thread(target=srv.serve_forever, daemon=True)
    th.start()

    # --- a small burst: 4 concurrent clients, shared system prompt
    system = "You are a helpful TPU. "
    prompts = [system + q for q in ("alpha?", "beta!", "gamma.",
                                    "delta;")]
    results = {}

    def client(i):
        toks = []
        for msg in request_stream("127.0.0.1", srv.port, prompts[i],
                                  gen_len=12, seed=i):
            if msg.get("done"):
                break
            toks.extend(msg["token_ids"])
        results[i] = toks

    # two waves: the second admits AFTER the first retired its pages
    # into the radix tree, so its shared system prompt is a cache hit
    for wave in ((0, 1), (2, 3)):
        cts = [threading.Thread(target=client, args=(i,)) for i in wave]
        for t in cts:
            t.start()
        for t in cts:
            t.join(timeout=600)
    assert all(len(results[i]) == 12 for i in range(4))
    print(f"served {len(results)} streams x 12 tokens in two waves")

    # --- the live latency histograms, fetched in-protocol
    with socket.create_connection(("127.0.0.1", srv.port)) as s:
        f = s.makefile("rw", encoding="utf-8", newline="\n")
        f.write(json.dumps({"op": "stats"}) + "\n")
        f.flush()
        st = json.loads(f.readline())["stats"]
    print('{"op": "stats"} snapshot (live, per-request-derived):')
    for key in ("ttft_ms", "inter_token_ms", "poll_ms"):
        m = st[key]
        print(f"  {key:<15s} n={m['count']:<4d} p50={m['p50']:<8g} "
              f"p95={m['p95']:<8g} p99={m['p99']:g}")
    print(f"  prefix-cache hit_rate={st['hit_rate']:.2f} "
          f"(shared system prompt), host_ms_per_poll="
          f"{st['host_ms_per_poll']:.2f}")

    # --- Prometheus text exposition (what a scraper would ingest)
    with socket.create_connection(("127.0.0.1", srv.metrics_port)) as s:
        s.sendall(b"GET /metrics HTTP/1.0\r\n\r\n")
        raw = b""
        while chunk := s.recv(65536):
            raw += chunk
    body = raw.split(b"\r\n\r\n", 1)[1].decode()
    assert "tdtpu_ttft_ms_bucket" in body
    wanted = ("tdtpu_requests_retired", "tdtpu_ttft_ms_count",
              "tdtpu_engine_decode_dispatches")
    print(f"GET /metrics -> {len(body.splitlines())} exposition lines, "
          f"e.g.:")
    for line in body.splitlines():
        if line.split(" ")[0].split("{")[0] in wanted:
            print(f"  {line}")

    # --- stop the server: TDTPU_TRACE makes it dump the timeline
    srv.stop()
    th.join(timeout=60)
    with open(TRACE) as f:
        dump = json.load(f)
    print(f"poll-loop timeline dumped to {TRACE} "
          f"({len(dump['traceEvents'])} events — load in "
          f"https://ui.perfetto.dev), summary:")
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "trace_view", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools", "trace_view.py"))
    tv = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tv)
    print("  " + tv.summarize(dump, top_k=3).replace("\n", "\n  "))
    print("OK")


if __name__ == "__main__":
    main()
