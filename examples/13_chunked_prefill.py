"""Chunked prefill: a LONG prompt is admitted mid-decode without
stalling the live streams (Sarathi-Serve, 2403.02310 — PAPERS.md).

Two clients are streaming tokens when a third arrives with a prompt an
order of magnitude longer. Monolithically, its admission runs the whole
prompt as ONE prefill program and every live stream's next token waits
behind it — the inter-token latency spike Sarathi-Serve measures.
With `prefill_budget` set, the scheduler absorbs the prompt in budgeted
chunks FUSED into the regular decode step (one mixed forward per poll,
riding the same per-slot q_lens/kv_lens kernel masks speculative
verify uses), so the live streams emit a token on every poll while the
long prompt soaks in — and every stream is BITWISE identical to the
monolithic run.

Run on CPU (no TPU needed):
  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/13_chunked_prefill.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import _common  # noqa: E402
_common.bootstrap()              # widen the CPU substrate BEFORE jax loads

import numpy as np  # noqa: E402


def main():
    from triton_dist_tpu.models import (AutoLLM, ContinuousScheduler,
                                        Engine, Request)
    from triton_dist_tpu.models.config import tiny_qwen3
    from triton_dist_tpu.runtime import initialize_distributed

    ctx = initialize_distributed()
    cfg = tiny_qwen3(ctx.tp_size())
    model = AutoLLM.from_config(cfg, ctx.mesh)
    eng = Engine(model, max_seq=96, backend="xla")

    rng = np.random.RandomState(0)
    live = [Request(rid=f"live{i}",
                    ids=rng.randint(0, cfg.vocab_size,
                                    size=(4,)).astype(np.int32),
                    gen_len=32)
            for i in range(2)]
    long_req = Request(
        rid="long",
        ids=rng.randint(0, cfg.vocab_size, size=(48,)).astype(np.int32),
        gen_len=4)
    budget = 6

    def serve(prefill_budget):
        sched = ContinuousScheduler(eng, batch=3, chunk=1,
                                    prefill_budget=prefill_budget)
        for r in live:
            sched.submit(r)
        acc = {r.rid: [] for r in live + [long_req]}
        live_emitted_during_absorb = 0
        absorb_polls = 0
        for _ in range(3):                # live slots armed + streaming
            out, _ = sched.poll()
            for rid, t in out.items():
                acc[rid].extend(t.tolist())
        sched.submit(long_req)
        while not acc["long"] and not sched.idle:
            out, _ = sched.poll()
            absorb_polls += 1
            live_emitted_during_absorb += sum(
                len(t) for rid, t in out.items() if rid != "long")
            for rid, t in out.items():
                acc[rid].extend(t.tolist())
        while not sched.idle:
            out, _ = sched.poll()
            for rid, t in out.items():
                acc[rid].extend(t.tolist())
        return acc, sched.stats(), absorb_polls, \
            live_emitted_during_absorb

    acc_c, st_c, polls_c, live_c = serve(budget)
    acc_m, st_m, _, _ = serve(None)

    print(f"long prompt: {len(long_req.ids)} tokens, "
          f"prefill_budget={budget}")
    print(f"  monolithic: max prefill tokens in one poll = "
          f"{st_m['max_prefill_tokens_per_poll']} (the whole prompt "
          f"stalls every live stream)")
    print(f"  chunked:    max prefill tokens in one poll = "
          f"{st_c['max_prefill_tokens_per_poll']} "
          f"(<= budget {budget})")
    print(f"  chunked absorption took {polls_c} polls; live streams "
          f"emitted {live_c} tokens during it "
          f"({live_c / max(polls_c, 1):.1f}/poll — no stall)")

    assert st_c["max_prefill_tokens_per_poll"] <= budget
    assert st_m["max_prefill_tokens_per_poll"] == len(long_req.ids)
    assert polls_c >= 2 and live_c >= 2 * (polls_c - 1)
    for rid in acc_m:
        assert acc_c[rid] == acc_m[rid], (
            f"{rid}: chunked and monolithic streams diverged")
    print("chunked streams bitwise identical to monolithic: yes")
    print("OK")


if __name__ == "__main__":
    main()
