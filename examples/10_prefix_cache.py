"""Shared-prefix KV cache: N clients sharing a system prompt reuse its
cached KV pages instead of each re-prefilling it.

The radix tree (triton_dist_tpu/models/prefix_cache.py) keys cached KV
pages by token ids: the first admission prefills the whole prompt and
inserts its pages; every later prompt sharing the system-prompt head
maps those pages READ-ONLY into its slot's page table (refcount +1),
copy-on-writes the partially-matched boundary page, and computes only
its own suffix (Engine.admit_slot_paged's prefill-from-offset). Token
streams are bitwise identical to running with the cache disabled — the
demo asserts it — while the printed counters show most prefill work
disappearing.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import _common  # noqa: E402
_common.bootstrap()              # widen the CPU substrate BEFORE jax loads

import numpy as np  # noqa: E402


def main():
    from triton_dist_tpu.models import (AutoLLM, ContinuousScheduler,
                                        Engine, Request)
    from triton_dist_tpu.models.config import tiny_qwen3
    from triton_dist_tpu.runtime import initialize_distributed
    from triton_dist_tpu.serving import ByteTokenizer

    ctx = initialize_distributed()
    n = ctx.tp_size()
    cfg = tiny_qwen3(n)
    model = AutoLLM.from_config(cfg, ctx.mesh)
    eng = Engine(model, max_seq=96, backend="xla")
    tok = ByteTokenizer(cfg.vocab_size)

    system = "System: you are a terse, helpful TPU assistant. "
    questions = ["What is a page table?", "Why radix trees?",
                 "Who refcounts the refcounters?", "Evict me, maybe",
                 "One more, shared", "And the last one."]
    reqs = [Request(rid=i, ids=np.asarray(tok.encode(system + q),
                                          np.int32), gen_len=10)
            for i, q in enumerate(questions)]

    runs = {}
    for pc_on in (False, True):
        sched = ContinuousScheduler(eng, batch=3, chunk=4, paged=True,
                                    prefix_cache=pc_on, page=16)
        t0 = time.perf_counter()
        runs[pc_on] = sched.run(reqs)
        dt = time.perf_counter() - t0
        if pc_on:
            st = sched.stats()
            print(f"{len(reqs)} clients sharing a "
                  f"{len(tok.encode(system))}-token system prompt "
                  f"({dt:.2f}s):")
            print(f"  hit rate          {st['hit_rate']:.2f} "
                  f"({st['hits']}/{st['admissions']} admissions)")
            print(f"  prefill skipped   {st['prefill_tokens_skipped']} "
                  f"of {st['prompt_tokens']} prompt tokens "
                  f"({st['prefill_skip_frac']:.0%})")
            print(f"  pages in use      {st['pages_in_use']} "
                  f"(+{st['pages_free']} free), "
                  f"{st['evictions']} evictions")

    # the whole point: sharing must be invisible in the tokens
    for r in reqs:
        assert np.array_equal(runs[True][r.rid], runs[False][r.rid]), (
            f"client {r.rid}: cache-on stream diverged from cache-off")
    print("token streams bitwise identical with the cache on and off")
    print("OK")


if __name__ == "__main__":
    main()
