"""Parallel sampling + structured output over the TokenServer wire
(models/structured.py + the scheduler's KV-fork and grammar paths).

Two client-visible features, both riding the plain line-JSON socket
protocol (examples/08_socket_serving.py):

  - `"n": 4` — one prompt, four sampled continuations. The scheduler
    prefills the prompt ONCE and forks the armed slot's KV pages to
    the siblings (refcount+1 on the shared pages, copy-on-write for
    the boundary page), so the burst costs one prefill instead of
    four. Each chunk message carries a `"fork"` tag; ONE fan-in done
    message closes the burst.

  - `"grammar": {"type": "json_schema", ...}` — constrained decoding:
    per-state token masks ride the decode tick as operands (no extra
    host round trip, no new programs), the host automaton tracks the
    state, and the stream is guaranteed to parse as JSON conforming
    to the schema, finishing early the moment the object is complete.
"""

import json
import os
import socket
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import _common  # noqa: E402
_common.bootstrap()              # widen the CPU substrate BEFORE jax loads


def request(host, port, payload):
    """One request, all reply lines (the raw wire, no client helper)."""
    with socket.create_connection((host, port), timeout=300) as s:
        with s.makefile("rw") as f:
            f.write(json.dumps(payload) + "\n")
            f.flush()
            return [json.loads(line) for line in f]


def main():
    from triton_dist_tpu.models import AutoLLM, Engine
    from triton_dist_tpu.models.config import tiny_qwen3
    from triton_dist_tpu.runtime import initialize_distributed
    from triton_dist_tpu.serving import ByteTokenizer, TokenServer

    ctx = initialize_distributed()
    cfg = tiny_qwen3(ctx.tp_size())
    model = AutoLLM.from_config(cfg, ctx.mesh)
    # sampled engine: parallel samples should actually diversify
    eng = Engine(model, max_seq=96, backend="xla", sampling="top_k",
                 temperature=0.9)
    tok = ByteTokenizer(cfg.vocab_size)
    srv = TokenServer(eng, tok, batch=6, chunk=4, paged=True, page=8,
                      max_forks=4)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    print(f"server on 127.0.0.1:{srv.port}")

    # ---- parallel sampling: one prefill, four continuations --------
    msgs = request(srv.host, srv.port,
                   {"prompt": "Once upon a TPU, ", "gen_len": 24,
                    "n": 4, "seed": 7})
    done = msgs[-1]
    assert done.get("done") and "error" not in done, done
    streams = {}
    for m in msgs[:-1]:
        streams.setdefault(m["fork"], []).append(m["text"])
    assert sorted(streams) == [0, 1, 2, 3], sorted(streams)
    print(f"\nn=4 burst, one prefill, {done['n_tokens']} tokens:")
    for k in sorted(streams):
        print(f"  fork {k}: {''.join(streams[k])!r}")
    st = srv.stats()
    print(f"  fork_shared_pages={st['fork_shared_pages']} "
          f"fork_cow_breaks={st['fork_cow_breaks']} "
          f"prefill_skip_frac={st['prefill_skip_frac']:.2f}")
    assert st["fork_shared_pages"] > 0

    # ---- grammar-constrained decoding: guaranteed-valid JSON -------
    schema = {"type": "object",
              "properties": {"answer": {"type": "boolean"},
                             "count": {"type": "integer",
                                       "maxDigits": 3}}}
    msgs = request(srv.host, srv.port,
                   {"prompt": "Report status as JSON: ", "gen_len": 48,
                    "grammar": {"type": "json_schema",
                                "schema": schema}})
    assert msgs[-1].get("done") and "error" not in msgs[-1], msgs[-1]
    text = "".join(m["text"] for m in msgs[:-1])
    obj = json.loads(text)            # the masks make this a certainty
    print(f"\nconstrained stream ({msgs[-1]['n_tokens']} tokens, "
          f"finished early of 48): {text!r}")
    print(f"  parsed: {obj}")
    st = srv.stats()
    print(f"  grammar_mask_tokens={st['grammar_mask_tokens']} "
          f"constrained_tokens_per_step="
          f"{st['constrained_tokens_per_step']}")

    # ---- a malformed grammar is refused, never crashes the server --
    msgs = request(srv.host, srv.port,
                   {"prompt": "x", "grammar": {"type": "wat"}})
    assert len(msgs) == 1 and msgs[0]["done"] and msgs[0]["error"]
    print(f"\nmalformed grammar refused: {msgs[0]['error']!r}")

    srv.stop()
    pool = srv.sched.slots.prefix.pool
    assert pool.available + pool.outstanding == pool.num_pages
    print("page pool conserved after the burst")
    print("OK")


if __name__ == "__main__":
    main()
