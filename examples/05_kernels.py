"""The overlapped kernel library, called directly on a mesh (the role
of the reference's per-op test/nvidia runs): every op is a host-level
function taking globally-sharded arrays; comm + compute overlap lives
inside the Pallas kernel."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import _common  # noqa: E402
_common.bootstrap()              # widen the CPU substrate BEFORE jax loads

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_dist_tpu.kernels import (
    ag_gemm, all_gather, all_reduce, create_ag_gemm_context,
    create_gemm_ar_context, create_gemm_rs_context, flash_decode,
    gemm_allreduce, gemm_rs)
from triton_dist_tpu.runtime import initialize_distributed


def main():
    ctx = initialize_distributed()
    mesh, n = ctx.mesh, ctx.tp_size()
    rng = np.random.RandomState(0)
    M, K, N = 8 * n, 128, 128 * n

    a = jnp.asarray(rng.randn(M, K), jnp.float32) * 0.1
    b = jnp.asarray(rng.randn(K, N), jnp.float32) * 0.1
    a_rows = jax.device_put(a, NamedSharding(mesh, P("tp", None)))
    b_cols = jax.device_put(b, NamedSharding(mesh, P(None, "tp")))

    # fused AllGather+GEMM: y = allgather(a) @ b, overlap inside the kernel
    y = jax.jit(lambda a, b: ag_gemm(a, b, create_ag_gemm_context(mesh)))(
        a_rows, b_cols)
    err = float(jnp.max(jnp.abs(y - a @ b)))
    print(f"ag_gemm [M={M},K={K},N={N}] max err {err:.2e}")

    # GEMM + fused ReduceScatter / AllReduce epilogues
    a_cols = jax.device_put(a, NamedSharding(mesh, P(None, "tp")))
    b_rows = jax.device_put(
        jnp.asarray(rng.randn(K, 128), jnp.float32) * 0.1,
        NamedSharding(mesh, P("tp", None)))
    y_rs = jax.jit(
        lambda a, b: gemm_rs(a, b, create_gemm_rs_context(mesh)))(
            a_cols, b_rows)
    y_ar = jax.jit(
        lambda a, b: gemm_allreduce(a, b, create_gemm_ar_context(mesh)))(
            a_cols, b_rows)
    print("gemm_rs out", y_rs.shape, "| gemm_allreduce out", y_ar.shape)

    # standalone collectives
    xg = jax.jit(lambda v: all_gather(v, mesh=mesh))(a_rows)
    parts = jax.device_put(
        jnp.broadcast_to(a[None] / n, (n,) + a.shape),
        NamedSharding(mesh, P("tp", None, None)))
    xr = jax.jit(lambda v: all_reduce(v, mesh=mesh))(parts)
    print("all_gather", xg.shape, "| all_reduce err",
          float(jnp.max(jnp.abs(xr - a))))

    # split-KV flash decode (single-device compute kernel)
    B, Hq, Hkv, T, d = 2, 8, 4, 256, 64
    q = jnp.asarray(rng.randn(B, 1, Hq, d), jnp.float32)
    k = jnp.asarray(rng.randn(B, Hkv, T, d), jnp.float32)
    v = jnp.asarray(rng.randn(B, Hkv, T, d), jnp.float32)
    o = jax.jit(lambda q, k, v: flash_decode(q, k, v, jnp.int32(100)))(
        q, k, v)
    print("flash_decode out", o.shape)
    print("OK")


if __name__ == "__main__":
    main()
