"""TP-sharded paged serving: one scheduler drives a TP=N mesh.

The whole serving stack built over the paged pool — continuous
batching, radix prefix cache, chunked prefill, spec decode, overlap —
runs TP-NATIVE (ROADMAP open item 1): the pool's page payloads carry
a head-group axis sharded over the mesh (models/kv_cache.py
PagedSlotCache TP SHARDING), the slot attends run under jax.shard_map
with each chip walking only its own kv-head shard
(layers/tp_attn.py), and the projections route through the TP
backends — so a TP=N mesh serves at N× the aggregate FLOPs and KV
bandwidth per token while the allocator, radix tree, CoW and
preemption logic stay host-side and layout-oblivious.

This demo runs the SAME multi-tenant burst (shared system prompt,
mixed lengths) through a single-chip engine and a TP=4 engine and
shows:
- token streams BITWISE identical across topologies,
- the prefix-cache hit counters agreeing (policy is layout-blind),
- stats() reporting tp_size + aggregate AND per-chip tok/s.

Run on CPU (no TPU needed):
  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/17_tp_serving.py
"""

import dataclasses
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import _common  # noqa: E402
_common.bootstrap()              # widen the CPU substrate BEFORE jax loads


def main():
    import jax
    import numpy as np

    from triton_dist_tpu.models import (AutoLLM, ContinuousScheduler,
                                        Engine, Request)
    from triton_dist_tpu.models.config import tiny_qwen3

    TP = min(4, len(jax.devices()))
    cfg = tiny_qwen3(TP)

    # one config, two topologies: random_init is mesh-independent, so
    # the weights are bitwise identical — only the layout differs
    rng = np.random.RandomState(0)
    system = rng.randint(0, cfg.vocab_size, size=(8,)).astype(np.int32)
    reqs = []
    for i, (tail, gen) in enumerate([(4, 6), (7, 8), (3, 5), (9, 6)]):
        ids = np.concatenate(
            [system,
             rng.randint(0, cfg.vocab_size, size=(tail,))]
        ).astype(np.int32)
        reqs.append(Request(rid=i, ids=ids, gen_len=gen, seed=50 + i))

    def serve(n):
        mesh = jax.make_mesh((n,), ("tp",))
        model = AutoLLM.from_config(cfg, mesh)
        eng = Engine(model, max_seq=64, backend="flash")
        sched = ContinuousScheduler(eng, batch=3, chunk=2, paged=True,
                                    page=8)
        out = sched.run([dataclasses.replace(r) for r in reqs])
        return out, sched.stats()

    out1, st1 = serve(1)
    outN, stN = serve(TP)

    for r in reqs:
        np.testing.assert_array_equal(
            outN[r.rid], out1[r.rid],
            err_msg=f"rid={r.rid} diverged across topologies")
    assert stN["hits"] == st1["hits"] and stN["hits"] > 0

    print(f"served {len(reqs)} requests on TP=1 and TP={TP}: "
          f"streams bitwise identical")
    print(f"  prefix-cache hits (both topologies): {stN['hits']}, "
          f"prefill tokens skipped: {stN['prefill_tokens_skipped']}")
    for label, st in (("TP=1 ", st1), (f"TP={TP}", stN)):
        print(f"  {label}: tp_size={st['tp_size']} "
              f"aggregate={st['serving_tok_per_s_aggregate']} tok/s "
              f"per-chip={st['serving_tok_per_s_per_chip']} tok/s")
    print("(on this CPU smoke all 'chips' share the host's cores — "
          "real TPU meshes are where the aggregate scales)")
    print("OK")


if __name__ == "__main__":
    main()
