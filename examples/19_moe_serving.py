"""MoE paged serving: Qwen3-MoE behind the full serving stack.

The serving stack — continuous batching, radix prefix cache, paged KV,
chunked prefill, spec decode, overlap — is MODEL-BLIND (ISSUE 13):
`Qwen3MoE` carries the same slot surface `DenseLLM` does
(`forward_tokens_slots_paged` + the verify/mixed twins), with per-slot
top-k routing run INSIDE every decode tick and the expert MLPs
dispatched through the grouped-GEMM kernel (kernels/group_gemm.py) —
the megablox-style pattern of vLLM-TPU (SNIPPETS.md [1]) — or through
the EP a2a wire when the experts are sharded (moe_impl="ep",
backend="ep_flash").

This demo:
- serves a multi-tenant burst (shared system prompt) through
  ContinuousScheduler(paged=True) over a TP-MoE Qwen3MoE,
- shows the streams BITWISE equal to sequential Engine.serve() calls,
- prints the per-expert load gauges (`expert_tokens{expert=...}`), the
  `moe_capacity_drops` counter and the `expert_load_imbalance` gauge —
  the observable half of the dropless-or-loud capacity contract,
- when >= 2 devices are visible, serves a second burst through an
  expert-SHARDED model (EP, same config) over the a2a dispatch and
  shows its streams bitwise equal that engine's own serve().

Run on CPU (no TPU needed):
  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/19_moe_serving.py
"""

import dataclasses
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import _common  # noqa: E402
_common.bootstrap()              # widen the CPU substrate BEFORE jax loads


def main():
    import jax
    import numpy as np

    from triton_dist_tpu.models import (AutoLLM, ContinuousScheduler,
                                        Engine, Request)
    from triton_dist_tpu.models.config import tiny_qwen3_moe

    cfg = tiny_qwen3_moe(1, num_experts=4)       # E=4 experts, top-2
    rng = np.random.RandomState(0)
    system = rng.randint(0, cfg.vocab_size, size=(8,)).astype(np.int32)
    reqs = []
    for i, (tail, gen) in enumerate([(4, 6), (7, 8), (3, 5), (9, 6)]):
        ids = np.concatenate(
            [system, rng.randint(0, cfg.vocab_size, size=(tail,))]
        ).astype(np.int32)
        reqs.append(Request(rid=i, ids=ids, gen_len=gen, seed=50 + i))

    # --- TP-MoE serving: experts replicated, grouped-GEMM dispatch
    mesh1 = jax.make_mesh((1,), ("tp",))
    model = AutoLLM.from_config(cfg, mesh1, capacity_factor="dropless")
    eng = Engine(model, max_seq=64, backend="flash")
    sched = ContinuousScheduler(eng, batch=3, chunk=2, paged=True,
                                page=8)
    out = sched.run([dataclasses.replace(r) for r in reqs])

    for r in reqs:
        want = np.asarray(eng.serve(np.tile(r.ids[None], (3, 1)),
                                    r.gen_len))[0]
        np.testing.assert_array_equal(out[r.rid], want)
    st = sched.stats()
    print(f"served {len(reqs)} requests through the paged MoE "
          f"scheduler: streams bitwise equal sequential serve()")
    print(f"  prefix-cache hits: {st['hits']} "
          f"(prefill tokens skipped: {st['prefill_tokens_skipped']})")
    loads = {e: st.get(f"expert_tokens{{expert={e}}}", 0)
             for e in range(cfg.num_experts)}
    print(f"  expert load (routed entries): {loads}")
    print(f"  capacity drops: {st['moe_capacity_drops']} "
          f"(dropless config), load imbalance max/mean: "
          f"{st['expert_load_imbalance']:.2f}")

    # --- EP serving: the SAME config expert-sharded over the a2a wire
    # (some jax builds' interpret mode cannot run the one-sided a2a
    # kernels — the known dma_start discharge limitation; the demo
    # then reports and moves on, exactly like the skip-guarded tests)
    if len(jax.devices()) >= 2:
        try:
            mesh2 = jax.make_mesh((2,), ("tp",))
            model_ep = AutoLLM.from_config(
                tiny_qwen3_moe(2, num_experts=4), mesh2, moe_impl="ep",
                capacity_factor="dropless")
            eng_ep = Engine(model_ep, max_seq=64, backend="ep_flash")
            sched_ep = ContinuousScheduler(eng_ep, batch=2, chunk=2,
                                           paged=True, page=8)
            cfg2 = model_ep.config
            rng2 = np.random.RandomState(1)
            reqs_ep = [Request(rid=i,
                               ids=rng2.randint(0, cfg2.vocab_size,
                                                size=(6 + i,)
                                                ).astype(np.int32),
                               gen_len=5) for i in range(3)]
            out_ep = sched_ep.run(
                [dataclasses.replace(r) for r in reqs_ep])
            for r in reqs_ep:
                want = np.asarray(eng_ep.serve(
                    np.tile(r.ids[None], (2, 1)), r.gen_len))[0]
                np.testing.assert_array_equal(out_ep[r.rid], want)
            print(f"EP serving (experts sharded over 2 chips, tokens "
                  f"over the a2a wire): {len(reqs_ep)} streams bitwise "
                  f"equal serve()")
        except AssertionError:
            raise        # a real stream divergence must fail the demo
        except Exception as e:
            print(f"EP arm skipped: interpret-mode a2a kernels "
                  f"unavailable here ({type(e).__name__})")

    print("OK")


if __name__ == "__main__":
    main()
