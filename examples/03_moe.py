"""Qwen3-MoE with BOTH parallel compositions (reference: e2e_moe +
the EP a2a path):

  TP-MoE — experts replicated, intermediate sharded; forward =
           AG-GroupGEMM + MoE-reduce-RS fused ring kernels.
  EP-MoE — experts sharded, tokens routed to their experts' owners by
           one-sided a2a dispatch/combine kernels.

Both also TRAIN through their kernels (custom VJPs, kernels/grad.py).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import _common  # noqa: E402
_common.bootstrap()              # widen the CPU substrate BEFORE jax loads

import jax
import jax.numpy as jnp
import numpy as np

from triton_dist_tpu.models.config import tiny_qwen3_moe
from triton_dist_tpu.models.qwen_moe import Qwen3MoE
from triton_dist_tpu.runtime import initialize_distributed


def main():
    ctx = initialize_distributed()
    n = ctx.tp_size()
    cfg = tiny_qwen3_moe(n, num_layers=1)   # 1 layer: quick on any host
    rng = np.random.RandomState(0)
    B, S = 1, 2 * n
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S)), jnp.int32)

    for impl, mode in (("tp", "fused"), ("ep", "ep")):
        model = Qwen3MoE.random_init(cfg, ctx.mesh, moe_impl=impl)
        cache = model.make_cache(B, 4 * n)
        # oracle vs kernel path
        logits_x, _ = jax.jit(
            lambda i, c, m=model: m.forward_tokens(i, c, "xla"))(ids, cache)
        cache = model.make_cache(B, 4 * n)
        logits_k, _ = jax.jit(
            lambda i, c, m=model, mo=mode: m.forward_tokens(i, c, mo))(
                ids, cache)
        err = float(jnp.max(jnp.abs(logits_k - logits_x)))
        print(f"moe_impl={impl}: kernel path vs oracle max err {err:.2e}")

        # one training step through the kernels
        labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S)),
                             jnp.int32)

        def loss_fn(m, ids, labels):
            logits = m.forward_train(ids, mode="train")
            logp = jax.nn.log_softmax(logits, axis=-1)
            return -jnp.mean(
                jnp.take_along_axis(logp, labels[..., None], axis=-1))

        loss, _ = jax.jit(jax.value_and_grad(loss_fn))(model, ids, labels)
        print(f"moe_impl={impl}: train-mode loss {float(loss):.4f}")
    print("OK")


if __name__ == "__main__":
    main()
