"""Prefill/decode disaggregation: dedicated prefill workers stream KV
pages to decode workers over the transfer plane (the DistServe split,
2401.09670 — PAPERS.md; models/disagg.py has the design).

Chunked prefill BOUNDS the stall a long admission puts on live decode
streams; disaggregation REMOVES it: admissions prefill on a dedicated
worker (its own staging paged pool — on a real deployment its own mesh
slice), the finished page-groups cross the transfer plane in the
host-tier wire format (raw page bytes, one-DMA gather/scatter), and
the decode mesh installs them through the radix tree and arms the
slot. Decode ticks never carry a prefill q_len again —
``stats()["max_prefill_tokens_per_poll"]`` is structurally 0.

This demo admits a LONG prompt into a busy decode batch three ways and
prints:
- fused monolithic / fused chunked / disaggregated streams BITWISE
  identical (same tokens, same PRNG chains);
- the decode-mesh prefill counters: fused forwards every prompt token
  on the decode mesh, disagg forwards ZERO (they land in
  ``prefill_plane_tokens`` instead);
- the transfer-plane telemetry: kv_transfers, pages_transferred,
  transfer_bytes, kv_transfer_latency_ms.

Run on CPU (no TPU needed):
  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/18_disaggregation.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import _common  # noqa: E402
_common.bootstrap()              # widen the CPU substrate BEFORE jax loads

import numpy as np  # noqa: E402


def main():
    from triton_dist_tpu.models import (AutoLLM, ContinuousScheduler,
                                        DisaggScheduler, Engine, Request)
    from triton_dist_tpu.models.config import tiny_qwen3
    from triton_dist_tpu.runtime import initialize_distributed

    ctx = initialize_distributed()
    cfg = tiny_qwen3(ctx.tp_size())
    model = AutoLLM.from_config(cfg, ctx.mesh)
    eng = Engine(model, max_seq=96, backend="xla")

    def requests():
        rng = np.random.RandomState(0)
        out = [Request(rid=i,
                       ids=rng.randint(0, cfg.vocab_size,
                                       size=(5 + 2 * i,)).astype(np.int32),
                       gen_len=12, seed=20 + i)
               for i in range(3)]
        # the long admission: 48 prompt tokens into the busy batch
        out.append(Request(
            rid="long",
            ids=rng.randint(0, cfg.vocab_size,
                            size=(48,)).astype(np.int32),
            gen_len=8, seed=99))
        return out

    fused = ContinuousScheduler(eng, batch=4, chunk=2,
                                paged=True).run(requests())
    chunked_sched = ContinuousScheduler(eng, batch=4, chunk=2,
                                        paged=True, prefill_budget=8)
    chunked = chunked_sched.run(requests())
    disagg_sched = DisaggScheduler(eng, batch=4, chunk=2)
    disagg = disagg_sched.run(requests())
    disagg_sched.close()

    for rid in fused:
        assert np.array_equal(chunked[rid], fused[rid]), rid
        assert np.array_equal(disagg[rid], fused[rid]), rid
    print("disagg == fused-chunked == fused-monolithic streams "
          "(bitwise): yes")

    st_c, st_d = chunked_sched.stats(), disagg_sched.stats()
    print(f"  fused chunked : decode-mesh prefill tokens="
          f"{st_c['prefill_tokens_forwarded']:.0f} "
          f"max/poll={st_c['max_prefill_tokens_per_poll']}")
    print(f"  disaggregated : decode-mesh prefill tokens="
          f"{st_d['prefill_tokens_forwarded']:.0f} "
          f"max/poll={st_d['max_prefill_tokens_per_poll']} "
          f"(plane forwarded {st_d['prefill_plane_tokens']})")
    assert st_d["max_prefill_tokens_per_poll"] == 0
    lat = st_d["kv_transfer_latency_ms"]
    print(f"  transfer plane: kv_transfers={st_d['kv_transfers']} "
          f"pages={st_d['pages_transferred']} "
          f"bytes={st_d['transfer_bytes']} "
          f"latency p50={lat['p50']:.2f}ms p99={lat['p99']:.2f}ms")
    print("  (on real chips the prefill plane is its own mesh slice "
          "and the payload rides the ICI/DCN transports — "
          "kernels/p2p.py p2p_push_pages, two_tier.py kv_push_slices)")
    print("OK")


if __name__ == "__main__":
    main()
