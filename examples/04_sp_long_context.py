"""Sequence parallelism for long context (reference: the SP AG-attention
prefill, distributed flash-decode and Ulysses mechanisms):

  - ring-attention prefill: KV chunks stream around the ICI ring while
    each chip's queries consume them (kernels/sp_attention.py).
  - seq-sharded decode: each chip holds a slice of the KV cache,
    produces split-KV partials, and an inter-chip LSE combine merges
    them (kernels/sp_flash_decode.py).
  - Ulysses: a2a head-reshard so attention is local over the full
    sequence (layers/sp_attn.py::UlyssesAttn, trainable).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import _common  # noqa: E402
_common.bootstrap()              # widen the CPU substrate BEFORE jax loads

import jax
import jax.numpy as jnp
import numpy as np

from triton_dist_tpu.layers.common import precompute_rope
from triton_dist_tpu.layers.sp_attn import SPAttn, UlyssesAttn
from triton_dist_tpu.runtime import initialize_distributed


def main():
    ctx = initialize_distributed({"sp": len(jax.devices())})
    n = ctx.mesh.shape["sp"]
    B, D, hd = 1, 128, 64
    Hq = Hkv = n                     # one q + one kv head per chip
    S = 16 * n                       # the "long" sequence, sharded
    rng = np.random.RandomState(0)
    sc = 0.5 / np.sqrt(D)
    mk = lambda *s: (rng.randn(*s) * sc).astype(np.float32)
    cos, sin = precompute_rope(hd, 4 * S)
    x = jnp.asarray(rng.randn(B, S, D) * 0.3, jnp.float32)
    from jax.sharding import NamedSharding, PartitionSpec as P
    xs = jax.device_put(x, NamedSharding(ctx.mesh, P(None, "sp", None)))

    # --- ring attention prefill + seq-sharded decode
    sp = SPAttn.init(mk(D, Hq * hd), mk(D, Hkv * hd), mk(D, Hkv * hd),
                     mk(Hq * hd, D), mesh=ctx.mesh, n_heads=Hq,
                     n_kv_heads=Hkv, head_dim=hd)
    ck, cv = sp.alloc_cache(B, 2 * S, dtype=jnp.float32)
    out, ck, cv, kv_len = jax.jit(sp.prefill)(xs, cos, sin, ck, cv)
    print("ring prefill out:", out.shape)
    x1 = jnp.asarray(rng.randn(B, 1, D) * 0.3, jnp.float32)
    out1, ck, cv, kv_len = jax.jit(sp.decode)(x1, cos, sin, ck, cv, kv_len)
    print("seq-sharded flash-decode out:", out1.shape,
          "cache len:", int(kv_len))

    # --- Ulysses (fused GEMM+a2a prefill; also trainable via fwd_train)
    ul = UlyssesAttn.init(mk(D, Hq * hd), mk(D, Hkv * hd), mk(D, Hkv * hd),
                          mk(Hq * hd, D), mesh=ctx.mesh, n_heads=Hq,
                          n_kv_heads=Hkv, head_dim=hd)
    out_u = jax.jit(lambda x: ul.prefill(x, cos, sin, mode="fused"))(xs)
    print("ulysses fused prefill out:", out_u.shape)

    # --- context-parallel TRAINING: gradients through the ring
    # (sp_ring_attention_train custom VJP: (k, v, dk, dv) rotate
    # together in the backward) — beyond the reference's inference-only SP
    def loss(l, x):
        return jnp.sum(l.fwd_train(x, cos, sin).astype(jnp.float32) ** 2)

    lval, grads = jax.jit(jax.value_and_grad(loss))(sp, xs)
    jax.block_until_ready(lval)
    print("ring-attention train loss:", float(lval),
          "| dw_qkv norm:", float(jnp.linalg.norm(grads.w_qkv)))
    print("OK")


if __name__ == "__main__":
    main()
