"""Long-context serving: sequence-parallel paged decode under the
scheduler (ISSUE 14 — the serving promotion of the repo's SP kernel
suite; Ring Attention arXiv:2310.01889 sets the blockwise cross-chip
attention pattern, Infinite-LLM/DistAttention arXiv:2401.02669 the
cluster-wide paged-KV deployment story).

With `sp_axis` set on the model, the paged pool's PAGE-ID space shards
over the sp mesh axis (models/kv_cache.py PagedSlotCache SP SHARDING):
chip s holds physical pages [s*NP/S, (s+1)*NP/S) of every layer, the
host allocator rotates fresh page groups across shards, and each
decode tick walks only its local pages through the split-KV partial
kernel (kernels/paged_kv.flash_decode_paged_partial) before the
cross-chip LSE combine (kernels/sp_flash_decode.sp_combine_partials)
merges the partial softmaxes — per-chip KV reads and attention FLOPs
drop to ~1/S, and a slot's max context is bounded by the WHOLE mesh's
paged HBM instead of one chip's.

This demo shows the capability jump, not a speedup (on the CPU
substrate all "chips" timeshare the host):
- a long request whose KV footprint exceeds one chip's pool is
  HARD-REJECTED upfront by an sp=1 scheduler,
- the same request ADMITS and decodes under sp=4 with the same
  per-chip pool size,
- where both fit, the sp=4 stream is BITWISE equal to a single-chip
  scheduler's,
- stats() reports sp_size, per-shard page residency and the
  sp_combine device-wait attribution.

Run on CPU (no TPU needed):
  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/20_long_context.py
"""

import dataclasses
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import _common  # noqa: E402
_common.bootstrap()              # widen the CPU substrate BEFORE jax loads


def main():
    import jax
    import numpy as np

    from triton_dist_tpu.models import (AutoLLM, ContinuousScheduler,
                                        Engine, Request)
    from triton_dist_tpu.models.config import tiny_qwen3

    SP = min(4, len(jax.devices()))
    cfg = tiny_qwen3(4)
    page, chip_groups = 8, 4            # one chip's pool: 4 page groups
    chip_pages = (chip_groups + 1) * cfg.num_kv_heads

    # one config, two topologies — random_init is mesh-independent, so
    # the weights are bitwise identical; only the pool layout differs
    model_1 = AutoLLM.from_config(cfg, jax.make_mesh((1,), ("tp",)))
    model_sp = AutoLLM.from_config(
        cfg, jax.make_mesh((1, SP), ("tp", "sp")), sp_axis="sp")
    eng_1 = Engine(model_1, max_seq=128, backend="flash")
    eng_sp = Engine(model_sp, max_seq=128, backend="flash")

    long_doc = Request(
        rid="doc",
        ids=(np.arange(40) % cfg.vocab_size).astype(np.int32),
        gen_len=10, seed=7)

    # --- sp=1, one chip's pool: the admission hard-rejects UPFRONT ---
    s1 = ContinuousScheduler(eng_1, batch=1, paged=True, chunk=2,
                             page=page, num_pages=chip_pages)
    out = s1.run([dataclasses.replace(long_doc)])
    print(f"sp=1 ({chip_pages} pages/chip): "
          f"rejected -> {s1.rejected['doc'][:64]}...")
    assert "doc" in s1.rejected and not out.get("doc", ()).__len__()

    # --- sp=4, the SAME per-chip pool x4 chips: admits and decodes ---
    s4 = ContinuousScheduler(eng_sp, batch=1, paged=True, chunk=2,
                             page=page, num_pages=chip_pages * SP)
    out4 = s4.run([dataclasses.replace(long_doc)])
    st = s4.stats()
    print(f"sp={SP} (same pool/chip): {len(out4['doc'])} tokens; "
          f"sp_size={st['sp_size']}, "
          f"resident by shard={st['sp_pages_resident']}, "
          f"sp_combine wait={st['device_wait_s_by_kind']['sp_combine']}s")

    # --- bitwise vs a big single-chip pool (where both fit) ---
    sb = ContinuousScheduler(eng_1, batch=1, paged=True, chunk=2,
                             page=page)
    outB = sb.run([dataclasses.replace(long_doc)])
    assert np.array_equal(out4["doc"], outB["doc"])
    print("stream bitwise equal to the single-chip reference — "
          f"max context grew x{SP} for free")


if __name__ == "__main__":
    main()
