"""Speculative decoding: n-gram self-drafting + batched multi-token
verify (models/spec_decode.py).

Decode is weight-bandwidth-bound — every forward reads the whole model
to emit ONE token per slot. With spec=K the scheduler drafts up to K
continuation tokens per slot by prompt-lookup (match the last n-gram
of the slot's own prompt+generated history, propose what followed it
last time), scores all slots' drafts in ONE verify forward, and keeps
each slot's longest accepted prefix plus the corrected token — several
tokens per forward when generation re-quotes its context, and the
greedy streams stay BITWISE identical to spec=0 (the demo asserts it).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import _common  # noqa: E402
_common.bootstrap()              # widen the CPU substrate BEFORE jax loads

import numpy as np  # noqa: E402


def main():
    from triton_dist_tpu.models import (AutoLLM, ContinuousScheduler,
                                        Engine, Request)
    from triton_dist_tpu.models.config import tiny_qwen3
    from triton_dist_tpu.runtime import initialize_distributed
    from triton_dist_tpu.serving import ByteTokenizer

    ctx = initialize_distributed()
    n = ctx.tp_size()
    cfg = tiny_qwen3(n)
    model = AutoLLM.from_config(cfg, ctx.mesh)
    eng = Engine(model, max_seq=128, backend="xla")
    tok = ByteTokenizer(cfg.vocab_size)

    # a self-quoting workload (the regime prompt-lookup targets): the
    # prompt repeats a phrase, greedy decode locks into the loop, and
    # the drafter proposes the continuation it has already seen
    phrase = "the pod of the slice of the pod "
    prompts = [phrase * 2 + "the pod", phrase * 2 + "the slice"]
    def reqs():
        return [Request(rid=i, ids=np.asarray(tok.encode(p), np.int32),
                        gen_len=40)
                for i, p in enumerate(prompts)]

    runs = {}
    for K in (0, 4):
        sched = ContinuousScheduler(eng, batch=2, chunk=4, spec=K)
        t0 = time.perf_counter()
        runs[K] = sched.run(reqs())
        dt = time.perf_counter() - t0
        if K:
            st = sched.stats()
            print(f"spec={K} over {len(prompts)} slots ({dt:.2f}s):")
            print(f"  tokens / verify forward  "
                  f"{st['tokens_per_step']:.2f}  (spec=0: 1.00)")
            print(f"  draft accept rate        "
                  f"{st['spec_accept_rate']:.0%} "
                  f"({st['spec_accepted']}/{st['spec_drafted']})")
            print(f"  verify forwards          {st['spec_steps']} "
                  f"for {st['spec_emitted']} tokens")
            assert st["tokens_per_step"] > 1.0, st

    # the whole point: speculation must be invisible in the tokens
    for i in range(len(prompts)):
        assert np.array_equal(runs[0][i], runs[4][i]), (
            f"slot {i}: spec-on stream diverged from spec-off")
    print("token streams bitwise identical with spec on and off")
    print("OK")


if __name__ == "__main__":
    main()
