"""TP inference end to end: build a Qwen3-shaped model over the mesh,
prefill + greedy decode through each backend, and check they agree
(reference flow: docs/getting-started e2e_dense — torch prefill, dist
decode backends, same generations)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import _common  # noqa: E402
_common.bootstrap()              # widen the CPU substrate BEFORE jax loads

import jax
import numpy as np

from triton_dist_tpu.models import AutoLLM, Engine
from triton_dist_tpu.models.config import tiny_qwen3
from triton_dist_tpu.runtime import initialize_distributed


def main():
    ctx = initialize_distributed()          # all devices on one "tp" axis
    n = ctx.tp_size()
    print(f"mesh: {dict(ctx.mesh.shape)} on {jax.default_backend()}")

    # tiny random-weight model so the example runs anywhere; swap in
    # DenseLLM.from_hf("/path/to/Qwen3-1.7B", ctx.mesh) for a checkpoint
    cfg = tiny_qwen3(n)
    model = AutoLLM.from_config(cfg, ctx.mesh)

    # B divisible by the TP size ("dist" decode keeps activations
    # row-sharded, models/dense.py contract)
    prompts = np.random.RandomState(0).randint(
        0, cfg.vocab_size, size=(max(n, 2), 2 * n)).astype(np.int32)

    outs = {}
    for backend in ("xla", "flash", "gemm_ar", "ar", "dist"):
        eng = Engine(model, max_seq=8 * n, backend=backend)
        outs[backend] = np.asarray(eng.serve(prompts, 8))
        print(f"{backend:8s} -> {outs[backend][0, :8].tolist()}")

    for backend, toks in outs.items():
        np.testing.assert_array_equal(
            toks, outs["xla"], err_msg=backend)
    print("all backends generate identical tokens: OK")


if __name__ == "__main__":
    main()
