"""MegaDecodeLayer: one transformer decode layer as ONE Pallas kernel.

TPU-native re-design of the reference megakernel
(`mega_triton_kernel/models/model_builder.py:86` builds the whole layer
step as tasks executed by persistent SMs; task kinds at
`mega_triton_kernel/task/`). Task list here (emitted in schedule order
by MegaKernelBuilder — see mega/__init__ for why program order replaces
the scoreboard on a sequential TPU core):

  rmsnorm(x) -> qkv matmul -> per-head qk-norm + rope -> cache write at
  pos -> flash decode over the cache -> o-proj (+residual) ->
  rmsnorm -> gate/up matmul + swiglu -> down-proj (+residual)

The payoff mirrors the reference's: activations stay resident in VMEM
for the entire layer (zero HBM round-trips between ops), weights stream
through a single staging tile, and the per-op pipeline
prologue/epilogue cost of nine kernels collapses into one.

Decode-only (S=1). tp=1 runs the single-chip layer. tp>1 (r5) is the
reference's FLAGSHIP composition — TP=8 Qwen3 decode inside the
megakernel (`model_builder.py:86`, allreduce as an in-kernel task over
nvshmem multimem): the layer stays ONE kernel per chip and the two
cross-chip reduction points (o-proj and down-proj partials, which need
an all-reduce BEFORE their residual adds) run as in-kernel one-shot
AR sections — stage the partial to HBM, push it to every peer over
ICI, wait the n arrivals, fold on the VPU, add the residual — the
gemm_allreduce kernel's protocol inlined as tasks. Weights arrive as
the LOCAL TP shards (heads / ffn columns sharded; construct the layer
with local head/ffn counts) and activations stay replicated, exactly
the per-op gemm_ar decode sharding. Perf stance unchanged
(CEILING.md): the per-op scan remains the fast path on TPU; tp>1 mega
exists for architecture parity with the reference's flagship,
numerically close to the sharded oracle (bf16 dots + a deterministic
f32 AR fold — chained greedy tokens can diverge from other backends at
near-ties, which the tests treat as expected, not a regression).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_dist_tpu import language as dl
from triton_dist_tpu.mega.builder import MegaKernelBuilder
from triton_dist_tpu.runtime import (interpret_mode, next_collective_id,
                                     shmem_compiler_params)


def _pick_bn(total: int, want: int) -> int:
    """Largest 128-multiple tile <= want dividing `total` (sliced DMAs
    must be 128-aligned in the minor dim)."""
    b = min(want, total) // 128 * 128
    while b > 0 and total % b:
        b -= 128
    assert b > 0, (total, want)
    return b


def _mm_tiles(env, dst, src, w, rows, cols, bn, wt_name, add=None,
              act=None):
    """Tiled matmul task body: dst[:, j*bn:...] = src @ w_tile (+add).
    Weight tiles are double-buffered: the fetch of tile j+1 is in
    flight under the dot of tile j, so the MXU never stalls on HBM."""
    w_ref = env[w]
    wt = env[wt_name]
    sems = env["copy_sems"]
    nt = cols // bn

    def fetch(j, slot):
        sl = slice(j * bn, (j + 1) * bn)
        cp = pltpu.make_async_copy(
            w_ref.at[:, sl], wt.at[slot, :rows, :bn], sems.at[slot])
        cp.start()
        return cp

    fetch(0, 0)
    for j in range(nt):
        slot = j % 2
        pltpu.make_async_copy(w_ref.at[:, :bn], wt.at[slot, :rows, :bn],
                              sems.at[slot]).wait()
        if j + 1 < nt:
            fetch(j + 1, (j + 1) % 2)
        sl = slice(j * bn, (j + 1) * bn)
        acc = jax.lax.dot(env[src][...].astype(jnp.bfloat16),
                          wt[slot, :rows, :bn],
                          preferred_element_type=jnp.float32)
        if add is not None:
            acc = acc + env[add][:, sl]
        if act is not None:
            acc = act(acc)
        env[dst][:, sl] = acc


def _rmsnorm(env, dst, src, w_name, eps):
    x = env[src][...]
    g = env[w_name][...]
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    env[dst][...] = x * jax.lax.rsqrt(ms + eps) * g


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class MegaDecodeLayer:
    """Static geometry + the compiled task program for one layer."""

    d_model: int = dataclasses.field(metadata=dict(static=True))
    n_heads: int = dataclasses.field(metadata=dict(static=True))
    n_kv_heads: int = dataclasses.field(metadata=dict(static=True))
    head_dim: int = dataclasses.field(metadata=dict(static=True))
    ffn: int = dataclasses.field(metadata=dict(static=True))
    T: int = dataclasses.field(metadata=dict(static=True))
    eps: float = dataclasses.field(default=1e-6,
                                   metadata=dict(static=True))
    block_n: int = dataclasses.field(default=256,
                                     metadata=dict(static=True))
    block_t: int = dataclasses.field(default=128,
                                     metadata=dict(static=True))
    # Qwen3-style per-head RMS norm on q/k before RoPE; False skips it
    # (matching the other backends' `if q_norm is not None` gate)
    qk_norm: bool = dataclasses.field(default=True,
                                      metadata=dict(static=True))
    # TP composition (see module docstring): tp > 1 adds the two
    # in-kernel AR sections; geometry fields are then the LOCAL shards
    # (n_heads = Hq/tp etc.) and the call must run inside shard_map
    # over `axis`
    tp: int = dataclasses.field(default=1, metadata=dict(static=True))
    axis: str = dataclasses.field(default="tp",
                                  metadata=dict(static=True))

    def __call__(self, x, pos, weights: Dict[str, jax.Array], cache_k,
                 cache_v):
        """x: [B, D]; pos: traced scalar (tokens already cached);
        weights: w_ln1 [1,D], w_qkv [D,(Hq+2Hkv)hd], q_norm/k_norm
        [1,hd], w_o [Hq*hd,D], w_ln2 [1,D], w_gu [D,2F] (gate|up),
        w_d [F,D], cos_row/sin_row [1,hd//2] for position `pos`.
        cache_k/v: [Hkv, B, T, hd]. Returns (y [B,D], cache_k, cache_v).
        """
        B, D = x.shape
        Hq, Hkv, hd = self.n_heads, self.n_kv_heads, self.head_dim
        rep = Hq // Hkv
        F = self.ffn
        T = self.T
        bn = self.block_n
        bt = self.block_t
        eps = self.eps
        Nqkv = (Hq + 2 * Hkv) * hd
        scale = hd ** -0.5
        assert D % bn == 0 and F % bn == 0 and (Hq * hd) % bn == 0, \
            (D, F, Hq * hd, bn)
        assert Hq % Hkv == 0, (Hq, Hkv)
        assert cache_k.shape == (Hkv, B, T, hd), (cache_k.shape,
                                                  (Hkv, B, T, hd))
        assert T % bt == 0

        ntp = self.tp
        ax = self.axis
        b = MegaKernelBuilder()
        b.inputs("xv", "w_ln1", "w_qkv", "q_norm", "k_norm", "w_o",
                 "w_ln2", "w_gu", "w_d", "cos", "sin", "ck", "cv",
                 "pos", "copy_sem", "copy_sems", "y")
        b.buffer("xn", (B, D), jnp.float32)
        b.buffer("qkv", (B, Nqkv), jnp.float32)
        b.buffer("attn", (B, Hq * hd), jnp.float32)
        b.buffer("ores", (B, D), jnp.float32)
        b.buffer("on", (B, D), jnp.float32)
        b.buffer("h", (B, F), jnp.float32)
        b.buffer("wt", (2, max(D, F, Hq * hd), bn), jnp.bfloat16)
        b.buffer("kvst", (B, 8, hd), jnp.bfloat16)
        # double-buffered KV tiles: the fetch of tile t+1 rides under
        # the online-softmax update of tile t
        b.buffer("kt", (2, B, bt, hd), jnp.bfloat16)
        b.buffer("vt", (2, B, bt, hd), jnp.bfloat16)
        if ntp > 1:
            # in-kernel AR plumbing (module docstring): landing/staging
            # HBM buffers are kernel outputs, fold tile in VMEM
            b.inputs("land1", "stage1", "land2", "stage2",
                     "recv1", "recv2")
            b.buffer("fold", (B, D), jnp.float32)
            b.buffer("ores_p", (B, D), jnp.float32)
            b.buffer("y_p", (B, D), jnp.float32)

            b.add_task("tp_barrier", lambda env: dl.barrier_all(ax),
                       reads=(), writes=())

        def ar_section(env, src, stage, land, recv, dst, add):
            """One-shot in-kernel all-reduce of a [B, D] partial (the
            gemm_allreduce protocol as a mega task; reference: the
            megakernel's allreduce task over nvshmem multimem):
            stage -> n pushes -> n arrival waits -> VPU fold + residual.
            """
            me = dl.my_pe(ax)
            sem = env["copy_sem"]
            cp = pltpu.make_async_copy(env[src], env[stage], sem)
            cp.start()
            cp.wait()
            for p in range(ntp):
                dl.putmem_nbi(env[land].at[me], env[stage], sem,
                              env[recv], jnp.int32(p), ax)
            for _ in range(ntp):
                pltpu.make_async_copy(env[stage], env[stage],
                                      env[recv]).wait()
            dl.quiet(sem, env[stage], ntp)
            acc = env[add][...]
            for i in range(ntp):
                cpf = pltpu.make_async_copy(env[land].at[i], env["fold"],
                                            sem)
                cpf.start()
                cpf.wait()
                acc = acc + env["fold"][...]
            env[dst][...] = acc

        b.add_task("ln1", functools.partial(_rmsnorm, dst="xn", src="xv",
                                            w_name="w_ln1", eps=eps),
                   reads=("xv", "w_ln1"), writes=("xn",))
        b.add_task("qkv_mm",
                   functools.partial(_mm_tiles, dst="qkv", src="xn",
                                     w="w_qkv", rows=D, cols=Nqkv,
                                     bn=_pick_bn(Nqkv, bn),
                                     wt_name="wt"),
                   reads=("xn", "w_qkv"), writes=("qkv", "wt"))

        def rope_norm(env):
            qkv = env["qkv"]
            c = env["cos"][...]
            s = env["sin"][...]
            half = hd // 2
            for hidx in range(Hq + Hkv):
                off = hidx * hd
                v = qkv[:, off:off + hd]
                if self.qk_norm:
                    gw = (env["q_norm"][...] if hidx < Hq
                          else env["k_norm"][...])
                    ms = jnp.mean(v * v, axis=-1, keepdims=True)
                    v = v * jax.lax.rsqrt(ms + eps) * gw
                x1 = v[:, :half]
                x2 = v[:, half:]
                qkv[:, off:off + half] = x1 * c - x2 * s
                qkv[:, off + half:off + hd] = x2 * c + x1 * s

        b.add_task("rope_norm", rope_norm,
                   reads=("qkv", "cos", "sin", "q_norm", "k_norm"),
                   writes=("qkv",))

        def cache_write(env):
            # Mosaic requires T-dim DMA slices 8-sublane aligned, so a
            # single-token append is a read-modify-write of its 8-token
            # granule (cost: one [B, 8, hd] round trip per kv head)
            qkv = env["qkv"]
            p = env["pos"]
            sem = env["copy_sem"]
            gb = (p // 8) * 8
            r = p - gb
            row = jax.lax.broadcasted_iota(jnp.int32, (B, 8, hd), 1)
            for g in range(Hkv):
                for which, buf in (("k", "ck"), ("v", "cv")):
                    base = (Hq + g) * hd if which == "k" else \
                           (Hq + Hkv + g) * hd
                    dst = env[buf].at[g, :, pl.ds(gb, 8), :]
                    cp = pltpu.make_async_copy(dst, env["kvst"], sem)
                    cp.start()
                    cp.wait()
                    new = qkv[:, base:base + hd].astype(jnp.bfloat16)
                    env["kvst"][...] = jnp.where(
                        row == r, new[:, None, :], env["kvst"][...])
                    cp = pltpu.make_async_copy(env["kvst"], dst, sem)
                    cp.start()
                    cp.wait()

        b.add_task("cache_write", cache_write,
                   reads=("qkv", "ck", "cv"), writes=("ck", "cv"))

        def flash(env):
            qkv = env["qkv"]
            p = env["pos"]
            sems = env["copy_sems"]
            nt = p // bt + 1
            for g in range(Hkv):
                q3 = qkv[:, g * rep * hd:(g + 1) * rep * hd].reshape(
                    B, rep, hd).astype(jnp.bfloat16)

                # double-buffered: copies are reconstructible
                # descriptors, so start tile t+1 in iteration t and
                # wait on its semaphore in iteration t+1
                def k_copy(t, slot, g=g):
                    return pltpu.make_async_copy(
                        env["ck"].at[g, :, pl.ds(t * bt, bt), :],
                        env["kt"].at[slot], sems.at[0])

                def v_copy(t, slot, g=g):
                    return pltpu.make_async_copy(
                        env["cv"].at[g, :, pl.ds(t * bt, bt), :],
                        env["vt"].at[slot], sems.at[1])

                k_copy(0, 0).start()
                v_copy(0, 0).start()

                def body(t, carry, g=g, q3=q3):
                    m, l, acc = carry
                    slot = jax.lax.rem(t, 2)
                    k_copy(t, slot).wait()
                    kt_t = env["kt"][slot]
                    s = jax.lax.dot_general(
                        q3, kt_t,
                        (((2,), (2,)), ((0,), (0,))),
                        preferred_element_type=jnp.float32) * scale

                    @pl.when(t + 1 < nt)
                    def _prefetch_k():
                        k_copy(t + 1, 1 - slot).start()

                    col = (t * bt
                           + jax.lax.broadcasted_iota(
                               jnp.int32, (B, rep, bt), 2))
                    sm = jnp.where(col <= p, s, -1e30)
                    m_new = jnp.maximum(m, jnp.max(sm, axis=-1))
                    alpha = jnp.exp(m - m_new)
                    pr = jnp.exp(sm - m_new[..., None])
                    pr = jnp.where(col <= p, pr, 0.0)
                    l_new = l * alpha + jnp.sum(pr, -1)
                    v_copy(t, slot).wait()
                    acc_new = (acc * alpha[..., None]
                               + jax.lax.dot_general(
                                   pr.astype(jnp.bfloat16),
                                   env["vt"][slot],
                                   (((2,), (1,)), ((0,), (0,))),
                                   preferred_element_type=jnp.float32))

                    @pl.when(t + 1 < nt)
                    def _prefetch_v():
                        v_copy(t + 1, 1 - slot).start()

                    return m_new, l_new, acc_new

                m0 = jnp.full((B, rep), -1e30, jnp.float32)
                l0 = jnp.zeros((B, rep), jnp.float32)
                a0 = jnp.zeros((B, rep, hd), jnp.float32)
                m, l, acc = jax.lax.fori_loop(0, nt, body, (m0, l0, a0))
                out = (acc / jnp.maximum(l, 1e-30)[..., None]).reshape(
                    B, rep * hd)
                env["attn"][:, g * rep * hd:(g + 1) * rep * hd] = out

        b.add_task("flash", flash, reads=("qkv", "ck", "cv"),
                   writes=("attn",))
        if ntp > 1:
            # partial o-proj (no residual: the AR must see the bare
            # partial), then the in-kernel AR adds the residual
            b.add_task("o_proj",
                       functools.partial(_mm_tiles, dst="ores_p",
                                         src="attn", w="w_o",
                                         rows=Hq * hd, cols=D, bn=bn,
                                         wt_name="wt"),
                       reads=("attn", "w_o"), writes=("ores_p", "wt"))
            b.add_task("o_allreduce",
                       functools.partial(ar_section, src="ores_p",
                                         stage="stage1", land="land1",
                                         recv="recv1", dst="ores",
                                         add="xv"),
                       reads=("ores_p", "xv"), writes=("ores", "fold"))
        else:
            b.add_task("o_proj",
                       functools.partial(_mm_tiles, dst="ores",
                                         src="attn", w="w_o",
                                         rows=Hq * hd, cols=D, bn=bn,
                                         wt_name="wt", add="xv"),
                       reads=("attn", "w_o", "xv"),
                       writes=("ores", "wt"))
        b.add_task("ln2", functools.partial(_rmsnorm, dst="on",
                                            src="ores", w_name="w_ln2",
                                            eps=eps),
                   reads=("ores", "w_ln2"), writes=("on",))

        def gate_up(env):
            # gate and up tiles in separate slots: the up-tile DMA is in
            # flight under the gate dot; swiglu fused in the epilogue
            # (reference: the megakernel's MLP task)
            wref = env["w_gu"]
            wt = env["wt"]
            sems = env["copy_sems"]
            on_bf = None
            for j in range(F // bn):
                sl = slice(j * bn, (j + 1) * bn)
                sl2 = slice(F + j * bn, F + (j + 1) * bn)
                cpg = pltpu.make_async_copy(wref.at[:, sl],
                                            wt.at[0, :D, :bn], sems.at[0])
                cpu = pltpu.make_async_copy(wref.at[:, sl2],
                                            wt.at[1, :D, :bn], sems.at[1])
                cpg.start()
                cpu.start()
                if on_bf is None:
                    on_bf = env["on"][...].astype(jnp.bfloat16)
                cpg.wait()
                g = jax.lax.dot(on_bf, wt[0, :D, :bn],
                                preferred_element_type=jnp.float32)
                cpu.wait()
                u = jax.lax.dot(on_bf, wt[1, :D, :bn],
                                preferred_element_type=jnp.float32)
                env["h"][:, sl] = g * jax.lax.logistic(g) * u

        b.add_task("gate_up_swiglu", gate_up, reads=("on", "w_gu"),
                   writes=("h", "wt"))
        if ntp > 1:
            b.add_task("down_proj",
                       functools.partial(_mm_tiles, dst="y_p", src="h",
                                         w="w_d", rows=F, cols=D, bn=bn,
                                         wt_name="wt"),
                       reads=("h", "w_d"), writes=("y_p", "wt"))
            b.add_task("d_allreduce",
                       functools.partial(ar_section, src="y_p",
                                         stage="stage2", land="land2",
                                         recv="recv2", dst="y",
                                         add="ores"),
                       reads=("y_p", "ores"), writes=("y", "fold"))
        else:
            b.add_task("down_proj",
                       functools.partial(_mm_tiles, dst="y", src="h",
                                         w="w_d", rows=F, cols=D, bn=bn,
                                         wt_name="wt", add="ores"),
                       reads=("h", "w_d", "ores"), writes=("y", "wt"))

        in_names = ["xv", "w_ln1", "w_qkv", "q_norm", "k_norm", "w_o",
                    "w_ln2", "w_gu", "w_d", "cos", "sin",
                    "ck_in", "cv_in"]
        out_names = ["y", "ck", "cv"]
        if ntp > 1:
            out_names += ["land1", "stage1", "land2", "stage2"]
        buf_names = list(b.buffers)
        sem_names = ["copy_sem", "copy_sems"]
        if ntp > 1:
            sem_names += ["recv1", "recv2"]

        def kernel(pos_ref, *refs):
            env = {"pos": pos_ref[0]}
            for i, nm in enumerate(in_names + out_names + buf_names
                                   + sem_names):
                env[nm] = refs[i]
            b.emit_all(env)   # ck/cv resolve to the ALIASED outputs

        vm = pl.BlockSpec(memory_space=pltpu.MemorySpace.VMEM)
        anym = pl.BlockSpec(memory_space=pl.ANY)
        scratch = [pltpu.VMEM(shape, dt)
                   for (shape, dt) in b.buffers.values()]
        scratch.append(pltpu.SemaphoreType.DMA(()))
        scratch.append(pltpu.SemaphoreType.DMA((2,)))
        out_shape = [jax.ShapeDtypeStruct((B, D), jnp.float32),
                     jax.ShapeDtypeStruct(cache_k.shape, cache_k.dtype),
                     jax.ShapeDtypeStruct(cache_v.shape, cache_v.dtype)]
        out_specs = [vm, anym, anym]
        if ntp > 1:
            scratch.append(pltpu.SemaphoreType.DMA(()))
            scratch.append(pltpu.SemaphoreType.DMA(()))
            for _ in range(2):   # (land, stage) x 2 AR sections
                out_shape += [
                    jax.ShapeDtypeStruct((ntp, B, D), jnp.float32),
                    jax.ShapeDtypeStruct((B, D), jnp.float32)]
                out_specs += [anym, anym]
        res = pl.pallas_call(
            kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(1,),
                in_specs=[vm, vm, anym, vm, vm, anym, vm, anym, anym,
                          vm, vm, anym, anym],
                out_specs=tuple(out_specs),
                scratch_shapes=scratch,
            ),
            out_shape=tuple(out_shape),
            input_output_aliases={12: 1, 13: 2},
            # the megakernel deliberately holds a whole layer's
            # activations + staging tiles in VMEM; lift the default 16MB
            # scoped-vmem ceiling (v5e has 128MB physical VMEM)
            compiler_params=shmem_compiler_params(
                next_collective_id() if ntp > 1 else None, n=ntp,
                vmem_limit_bytes=100 << 20),
            interpret=interpret_mode(),
        )(jnp.asarray(pos, jnp.int32)[None],
          x.astype(jnp.float32),
          weights["w_ln1"], weights["w_qkv"].astype(jnp.bfloat16),
          weights["q_norm"], weights["k_norm"],
          weights["w_o"].astype(jnp.bfloat16), weights["w_ln2"],
          weights["w_gu"].astype(jnp.bfloat16),
          weights["w_d"].astype(jnp.bfloat16),
          weights["cos_row"], weights["sin_row"],
          cache_k, cache_v)
        return res[0], res[1], res[2]


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class MegaPagedDecodeLayer:
    """One transformer decode layer as ONE Pallas kernel over the PAGED
    serving pool (the paged serving contract of
    kv_cache.PagedSlotCache — ISSUE 12 / ROADMAP item 5): the fused
    layer learns exactly what `flash_decode_paged` + the per-op slot
    ops already know, but inside one kernel:

      - per-slot positions: `pos` [B] int32 — slot b's new token sits
        at ITS position (kv_lens = pos + 1), not a shared offset; the
        flash walk masks each stream to its own length;
      - the page-table walk: every KV tile resolves through the
        slot's table row (rows ride the scalar-prefetch operand, so
        the per-tile page ids are static-index scalar reads — the
        same machinery the BlockSpec index maps of flash_decode_paged
        use, minus the grid);
      - the trash-page write sink: a retired/padded slot's table rows
        all point at the reserved trash page, so its masked-out
        read-modify-write lands where no live slot ever maps;
      - in-kernel int8 dequant (quant=int8 pool): the per-position
        scale planes (PR-7, KIVI 2402.02750) ride the SAME page id as
        the payload; K's scale multiplies the logits column-wise, V's
        folds into P — the exact dequant of the per-op kernel — and
        the new row quantizes with the shared quantizer's math
        (quantize_kv_int8) before its write-back.

    Decode-only (S == 1 per slot, the greedy tick); the spec-verify
    window (q_lens > 1) and mixed prefill rows stay on the per-op
    programs (engine._jit_programs falls back per poll). Single chip:
    the TP=N paged pool keeps the per-op `shard_map` path (the
    head-group plane split lives outside the kernel).

    Perf stance (mega/CEILING.md): the walk is per-(head, slot) —
    the same bx=1 stream economics the paged per-op kernel pays —
    with page-granular DMAs under the online-softmax update. What the
    fusion buys is the LAYER: one kernel launch where the per-op tick
    pays ~7 op dispatches (norms, projections, rope/scatter, flash,
    swiglu), with activations VMEM-resident across all of them."""

    d_model: int = dataclasses.field(metadata=dict(static=True))
    n_heads: int = dataclasses.field(metadata=dict(static=True))
    n_kv_heads: int = dataclasses.field(metadata=dict(static=True))
    head_dim: int = dataclasses.field(metadata=dict(static=True))
    ffn: int = dataclasses.field(metadata=dict(static=True))
    page: int = dataclasses.field(metadata=dict(static=True))
    maxp: int = dataclasses.field(metadata=dict(static=True))
    eps: float = dataclasses.field(default=1e-6,
                                   metadata=dict(static=True))
    block_n: int = dataclasses.field(default=256,
                                     metadata=dict(static=True))
    qk_norm: bool = dataclasses.field(default=True,
                                      metadata=dict(static=True))

    def __call__(self, x, pos, weights: Dict[str, jax.Array], pages_k,
                 pages_v, table, scales_k=None, scales_v=None):
        """x: [B, D] f32; pos: [B] int32 (tokens already cached per
        slot — the new token lands at pos[b]); weights: the contiguous
        layer's dict with PER-SLOT rope rows cos_row/sin_row [B, hd//2]
        (gathered at each slot's own position); pages_k/v:
        [NP, 1, page, d] (one layer's pool, single head-group plane);
        table: [B*Hkv, maxp] int32 (trash-padded rows — every entry is
        a valid physical page); scales_k/v: [NP, 1, page] f32 for the
        int8 pool. Returns (y [B, D], pages_k, pages_v[, scales_k,
        scales_v]) with the pools updated in place (aliased)."""
        B, D = x.shape
        Hq, Hkv, hd = self.n_heads, self.n_kv_heads, self.head_dim
        rep = Hq // Hkv
        F = self.ffn
        page, maxp = self.page, self.maxp
        bn = self.block_n
        eps = self.eps
        Nqkv = (Hq + 2 * Hkv) * hd
        scale = hd ** -0.5
        X = B * Hkv
        quant = scales_k is not None
        assert (scales_k is None) == (scales_v is None)
        assert D % bn == 0 and F % bn == 0 and (Hq * hd) % bn == 0, \
            (D, F, Hq * hd, bn)
        assert Hq % Hkv == 0, (Hq, Hkv)
        assert pages_k.shape[1] == 1, (
            "MegaPagedDecodeLayer is the single-chip tick: the TP pool "
            f"has {pages_k.shape[1]} head-group planes; serve TP "
            "meshes on the per-op backends")
        assert pages_k.shape[2:] == (page, hd), (pages_k.shape,
                                                 (page, hd))
        assert table.shape == (X, maxp), (table.shape, (X, maxp))
        pool_dt = pages_k.dtype
        qdt = jnp.bfloat16 if quant else pool_dt

        b = MegaKernelBuilder()
        b.inputs("xv", "w_ln1", "w_qkv", "q_norm", "k_norm", "w_o",
                 "w_ln2", "w_gu", "w_d", "cos", "sin", "pk", "pv",
                 "ks", "vs", "scal", "copy_sem", "copy_sems", "y")
        b.buffer("xn", (B, D), jnp.float32)
        b.buffer("qkv", (B, Nqkv), jnp.float32)
        b.buffer("attn", (B, Hq * hd), jnp.float32)
        b.buffer("ores", (B, D), jnp.float32)
        b.buffer("on", (B, D), jnp.float32)
        b.buffer("h", (B, F), jnp.float32)
        b.buffer("wt", (2, max(D, F, Hq * hd), bn), jnp.bfloat16)
        # page-granular staging: the append is a read-modify-write of
        # the slot's whole current page (pages of different slots are
        # not adjacent, so single-row DMA cannot batch across slots)
        b.buffer("pgst", (page, hd), pool_dt)
        # flash tiles + per-(head, slot) online-softmax state
        b.buffer("kt", (page, hd), pool_dt)
        b.buffer("vt", (page, hd), pool_dt)
        b.buffer("fm", (rep, 1), jnp.float32)
        b.buffer("fl", (rep, 1), jnp.float32)
        b.buffer("facc", (rep, hd), jnp.float32)
        if quant:
            b.buffer("sgst", (1, page), jnp.float32)
            b.buffer("kst", (1, page), jnp.float32)
            b.buffer("vst", (1, page), jnp.float32)

        # scalar-prefetch layout: [pos (B) | in-page row (B) | write
        # page id (X) | table (X * maxp)]. The write page id and row
        # are precomputed OUTSIDE the kernel (pos // page indexing of
        # the table is a dynamic scalar lookup the kernel body
        # avoids — the same older-interpreter constraint
        # flash_decode_paged's index maps note), so every in-kernel
        # scalar read is at a STATIC offset.
        def s_pos(env, bi):
            return env["scal"][bi]

        def s_row(env, bi):
            return env["scal"][B + bi]

        def s_wpid(env, bi, g):
            return env["scal"][2 * B + bi * Hkv + g]

        def s_table(env, bi, g, t):
            return env["scal"][2 * B + X + (bi * Hkv + g) * maxp + t]

        b.add_task("ln1", functools.partial(_rmsnorm, dst="xn", src="xv",
                                            w_name="w_ln1", eps=eps),
                   reads=("xv", "w_ln1"), writes=("xn",))
        b.add_task("qkv_mm",
                   functools.partial(_mm_tiles, dst="qkv", src="xn",
                                     w="w_qkv", rows=D, cols=Nqkv,
                                     bn=_pick_bn(Nqkv, bn),
                                     wt_name="wt"),
                   reads=("xn", "w_qkv"), writes=("qkv", "wt"))

        def rope_norm(env):
            # identical to the contiguous task, with PER-SLOT rope rows
            # ([B, hd//2] — each slot rotates at its own position)
            qkv = env["qkv"]
            c = env["cos"][...]
            s = env["sin"][...]
            half = hd // 2
            for hidx in range(Hq + Hkv):
                off = hidx * hd
                v = qkv[:, off:off + hd]
                if self.qk_norm:
                    gw = (env["q_norm"][...] if hidx < Hq
                          else env["k_norm"][...])
                    ms = jnp.mean(v * v, axis=-1, keepdims=True)
                    v = v * jax.lax.rsqrt(ms + eps) * gw
                x1 = v[:, :half]
                x2 = v[:, half:]
                qkv[:, off:off + half] = x1 * c - x2 * s
                qkv[:, off + half:off + hd] = x2 * c + x1 * s

        b.add_task("rope_norm", rope_norm,
                   reads=("qkv", "cos", "sin", "q_norm", "k_norm"),
                   writes=("qkv",))

        def cache_write(env):
            # per-slot paged append: slot b's new K/V row lands in the
            # physical page its table row maps for pos[b] (a retired
            # slot's rows map the trash page — the sanctioned sink).
            # RMW of the whole page per (slot, head): read, mask-in row
            # pos[b] % page, write back. int8 pools quantize the row
            # through the SHARED quantizer (pure jnp, so it runs
            # inside the kernel body) and RMW the scale row of the
            # SAME page alongside — the repo-wide bitwise-identity
            # contract of kernels/quant.quantize_kv_int8 rides on
            # every int8 store calling the one helper.
            from triton_dist_tpu.kernels.quant import quantize_kv_int8
            qkv = env["qkv"]
            sem = env["copy_sem"]
            rowi = jax.lax.broadcasted_iota(jnp.int32, (page, hd), 0)
            if quant:
                srow = jax.lax.broadcasted_iota(jnp.int32, (1, page), 1)
            for bi in range(B):
                r = s_row(env, bi)
                for g in range(Hkv):
                    pid = s_wpid(env, bi, g)
                    for which in ("k", "v"):
                        base = ((Hq + g) * hd if which == "k"
                                else (Hq + Hkv + g) * hd)
                        buf = env["pk" if which == "k" else "pv"]
                        dst = buf.at[pid, 0]
                        cp = pltpu.make_async_copy(dst, env["pgst"], sem)
                        cp.start()
                        cp.wait()
                        new = qkv[bi:bi + 1, base:base + hd]  # [1, hd]
                        if quant:
                            q8, sc = quantize_kv_int8(new)
                            env["pgst"][...] = jnp.where(
                                rowi == r,
                                jnp.broadcast_to(q8, (page, hd)
                                                 ).astype(pool_dt),
                                env["pgst"][...])
                        else:
                            env["pgst"][...] = jnp.where(
                                rowi == r,
                                jnp.broadcast_to(new, (page, hd)
                                                 ).astype(pool_dt),
                                env["pgst"][...])
                        cp = pltpu.make_async_copy(env["pgst"], dst, sem)
                        cp.start()
                        cp.wait()
                        if quant:
                            sbuf = env["ks" if which == "k" else "vs"]
                            sdst = sbuf.at[pid]
                            cp = pltpu.make_async_copy(sdst, env["sgst"],
                                                       sem)
                            cp.start()
                            cp.wait()
                            env["sgst"][...] = jnp.where(
                                srow == r, sc[0], env["sgst"][...])
                            cp = pltpu.make_async_copy(env["sgst"], sdst,
                                                       sem)
                            cp.start()
                            cp.wait()

        cw_reads = ("qkv", "scal", "pk", "pv") + (("ks", "vs") if quant
                                                  else ())
        cw_writes = ("pk", "pv", "pgst") + (("ks", "vs", "sgst")
                                            if quant else ())
        b.add_task("cache_write_paged", cache_write,
                   reads=cw_reads, writes=cw_writes)

        def flash(env):
            # the paged flash walk, per (kv head, slot) stream: every
            # logical tile resolves through the slot's table row (all
            # entries valid — trash-padded), tiles past the slot's own
            # kv_len are skipped (pl.when), and the in-tile column
            # mask col <= pos[b] drops the tail of the last page.
            qkv = env["qkv"]
            sem = env["copy_sem"]
            for g in range(Hkv):
                for bi in range(B):
                    p = s_pos(env, bi)
                    kvl = p + 1
                    q3 = (qkv[bi:bi + 1,
                              g * rep * hd:(g + 1) * rep * hd]
                          .reshape(rep, hd).astype(qdt))
                    env["fm"][...] = jnp.full((rep, 1), -1e30,
                                              jnp.float32)
                    env["fl"][...] = jnp.zeros((rep, 1), jnp.float32)
                    env["facc"][...] = jnp.zeros((rep, hd), jnp.float32)
                    for t in range(maxp):
                        pid = s_table(env, bi, g, t)

                        @pl.when(t * page < kvl)
                        def _tile(t=t, pid=pid, p=p, q3=q3):
                            cp = pltpu.make_async_copy(
                                env["pk"].at[pid, 0], env["kt"], sem)
                            cp.start()
                            cp.wait()
                            kj = env["kt"][...]
                            if quant:
                                cp = pltpu.make_async_copy(
                                    env["ks"].at[pid], env["kst"], sem)
                                cp.start()
                                cp.wait()
                                kj = kj.astype(qdt)
                            s = jax.lax.dot_general(
                                q3, kj, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32
                                ) * scale                  # [rep, page]
                            if quant:
                                # K's per-position scale multiplies the
                                # logits column-wise (exact dequant)
                                s = s * env["kst"][...]
                            col = (t * page
                                   + jax.lax.broadcasted_iota(
                                       jnp.int32, (rep, page), 1))
                            sm = jnp.where(col <= p, s, -1e30)
                            m_prev = env["fm"][...]        # [rep, 1]
                            m_new = jnp.maximum(
                                m_prev, jnp.max(sm, -1, keepdims=True))
                            alpha = jnp.exp(m_prev - m_new)
                            pr = jnp.where(col <= p,
                                           jnp.exp(sm - m_new), 0.0)
                            env["fl"][...] = (env["fl"][...] * alpha
                                              + jnp.sum(pr, -1,
                                                        keepdims=True))
                            cp = pltpu.make_async_copy(
                                env["pv"].at[pid, 0], env["vt"], sem)
                            cp.start()
                            cp.wait()
                            vj = env["vt"][...]
                            if quant:
                                cp = pltpu.make_async_copy(
                                    env["vs"].at[pid], env["vst"], sem)
                                cp.start()
                                cp.wait()
                                vj = vj.astype(qdt)
                                # V's scale folds into P (diag(sv) V)
                                pr = pr * env["vst"][...]
                            env["facc"][...] = (
                                env["facc"][...] * alpha
                                + jax.lax.dot_general(
                                    pr.astype(vj.dtype), vj,
                                    (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32))
                            env["fm"][...] = m_new

                    out = (env["facc"][...]
                           / jnp.maximum(env["fl"][...], 1e-30))
                    env["attn"][bi:bi + 1,
                                g * rep * hd:(g + 1) * rep * hd] = \
                        out.reshape(1, rep * hd)

        fl_reads = ("qkv", "scal", "pk", "pv") + (("ks", "vs") if quant
                                                  else ())
        fl_writes = ("attn", "kt", "vt", "fm", "fl", "facc") + (
            ("kst", "vst") if quant else ())
        b.add_task("flash_paged", flash, reads=fl_reads,
                   writes=fl_writes)
        b.add_task("o_proj",
                   functools.partial(_mm_tiles, dst="ores", src="attn",
                                     w="w_o", rows=Hq * hd, cols=D,
                                     bn=bn, wt_name="wt", add="xv"),
                   reads=("attn", "w_o", "xv"), writes=("ores", "wt"))
        b.add_task("ln2", functools.partial(_rmsnorm, dst="on",
                                            src="ores", w_name="w_ln2",
                                            eps=eps),
                   reads=("ores", "w_ln2"), writes=("on",))

        def gate_up(env):
            wref = env["w_gu"]
            wt = env["wt"]
            sems = env["copy_sems"]
            on_bf = None
            for j in range(F // bn):
                sl = slice(j * bn, (j + 1) * bn)
                sl2 = slice(F + j * bn, F + (j + 1) * bn)
                cpg = pltpu.make_async_copy(wref.at[:, sl],
                                            wt.at[0, :D, :bn], sems.at[0])
                cpu = pltpu.make_async_copy(wref.at[:, sl2],
                                            wt.at[1, :D, :bn], sems.at[1])
                cpg.start()
                cpu.start()
                if on_bf is None:
                    on_bf = env["on"][...].astype(jnp.bfloat16)
                cpg.wait()
                g = jax.lax.dot(on_bf, wt[0, :D, :bn],
                                preferred_element_type=jnp.float32)
                cpu.wait()
                u = jax.lax.dot(on_bf, wt[1, :D, :bn],
                                preferred_element_type=jnp.float32)
                env["h"][:, sl] = g * jax.lax.logistic(g) * u

        b.add_task("gate_up_swiglu", gate_up, reads=("on", "w_gu"),
                   writes=("h", "wt"))
        b.add_task("down_proj",
                   functools.partial(_mm_tiles, dst="y", src="h",
                                     w="w_d", rows=F, cols=D, bn=bn,
                                     wt_name="wt", add="ores"),
                   reads=("h", "w_d", "ores"), writes=("y", "wt"))

        in_names = ["xv", "w_ln1", "w_qkv", "q_norm", "k_norm", "w_o",
                    "w_ln2", "w_gu", "w_d", "cos", "sin",
                    "pk_in", "pv_in"] + (["ks_in", "vs_in"] if quant
                                         else [])
        out_names = ["y", "pk", "pv"] + (["ks", "vs"] if quant else [])
        buf_names = list(b.buffers)
        sem_names = ["copy_sem", "copy_sems"]

        def kernel(scal_ref, *refs):
            env = {"scal": scal_ref}
            for i, nm in enumerate(in_names + out_names + buf_names
                                   + sem_names):
                env[nm] = refs[i]
            if not quant:
                env["ks"] = env["vs"] = None
            b.emit_all(env)   # pk/pv (+ks/vs) resolve to the ALIASED
            # outputs

        vm = pl.BlockSpec(memory_space=pltpu.MemorySpace.VMEM)
        anym = pl.BlockSpec(memory_space=pl.ANY)
        scratch = [pltpu.VMEM(shape, dt)
                   for (shape, dt) in b.buffers.values()]
        scratch.append(pltpu.SemaphoreType.DMA(()))
        scratch.append(pltpu.SemaphoreType.DMA((2,)))
        out_shape = [jax.ShapeDtypeStruct((B, D), jnp.float32),
                     jax.ShapeDtypeStruct(pages_k.shape, pages_k.dtype),
                     jax.ShapeDtypeStruct(pages_v.shape, pages_v.dtype)]
        out_specs = [vm, anym, anym]
        in_specs = [vm, vm, anym, vm, vm, anym, vm, anym, anym,
                    vm, vm, anym, anym]
        aliases = {12: 1, 13: 2}
        if quant:
            out_shape += [
                jax.ShapeDtypeStruct(scales_k.shape, scales_k.dtype),
                jax.ShapeDtypeStruct(scales_v.shape, scales_v.dtype)]
            out_specs += [anym, anym]
            in_specs += [anym, anym]
            aliases.update({14: 3, 15: 4})

        pos = jnp.asarray(pos, jnp.int32)
        # write page id per (slot, head) stream + the in-page row,
        # resolved host/XLA-side so every in-kernel scalar read is at a
        # static offset (see the scalar-layout comment above)
        pos_x = jnp.repeat(pos, Hkv)                          # [X]
        wpid = table[jnp.arange(X),
                     jnp.minimum(pos_x // page, maxp - 1)]
        scalars = jnp.concatenate([
            pos, pos % page, wpid,
            table.reshape(-1).astype(jnp.int32)])
        args = [x.astype(jnp.float32),
                weights["w_ln1"], weights["w_qkv"].astype(jnp.bfloat16),
                weights["q_norm"], weights["k_norm"],
                weights["w_o"].astype(jnp.bfloat16), weights["w_ln2"],
                weights["w_gu"].astype(jnp.bfloat16),
                weights["w_d"].astype(jnp.bfloat16),
                weights["cos_row"], weights["sin_row"],
                pages_k, pages_v]
        if quant:
            args += [scales_k, scales_v]
        res = pl.pallas_call(
            kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(1,),
                in_specs=in_specs,
                out_specs=tuple(out_specs),
                scratch_shapes=scratch,
            ),
            out_shape=tuple(out_shape),
            input_output_aliases=aliases,
            compiler_params=shmem_compiler_params(
                None, n=1, vmem_limit_bytes=100 << 20),
            interpret=interpret_mode(),
        )(scalars, *args)
        return res


def mega_paged_decode_layer_ref(x, pos, weights, pages_k, pages_v,
                                table, scales_k=None, scales_v=None, *,
                                n_heads, n_kv_heads, head_dim,
                                eps=1e-6):
    """jnp oracle of MegaPagedDecodeLayer: the same paged layer step
    out of ordinary ops — per-slot qk-norm + rope, the (quantized)
    row scatter through the table, per-slot-length attention over the
    gathered pool, then the MLP half. Mirrors the per-op serving
    semantics (`layers/tp_attn.py _attend_paged_slots`)."""
    from triton_dist_tpu.kernels.quant import (dequantize_kv_int8,
                                               quantize_kv_int8)
    B, D = x.shape
    Hq, Hkv, hd = n_heads, n_kv_heads, head_dim
    rep = Hq // Hkv
    quant = scales_k is not None
    X, maxp = table.shape
    page = pages_k.shape[2]
    x = x.astype(jnp.float32)

    def rms(v, g):
        return v * jax.lax.rsqrt(
            jnp.mean(v * v, -1, keepdims=True) + eps) * g

    xn = rms(x, weights["w_ln1"][0])
    qkv = xn @ weights["w_qkv"].astype(jnp.float32)
    c = weights["cos_row"]            # [B, hd//2] — per-slot rows
    s = weights["sin_row"]
    half = hd // 2

    def rope_head(v, g):
        v = rms(v, g)
        x1, x2 = v[:, :half], v[:, half:]
        return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], -1)

    heads = []
    for hi in range(Hq + Hkv):
        off = hi * hd
        g = (weights["q_norm"][0] if hi < Hq else weights["k_norm"][0])
        heads.append(rope_head(qkv[:, off:off + hd], g))
    q = jnp.stack(heads[:Hq], 1)                       # [B, Hq, hd]
    k_new = jnp.stack(heads[Hq:], 1).reshape(X, hd)    # [X, hd]
    v_new = qkv[:, (Hq + Hkv) * hd:].reshape(X, hd)
    pos = jnp.asarray(pos, jnp.int32)
    pos_x = jnp.repeat(pos, Hkv)
    pidx = table[jnp.arange(X), jnp.minimum(pos_x // page, maxp - 1)]
    r = pos_x % page
    pk, pv = pages_k[:, 0], pages_v[:, 0]
    if quant:
        sk, sv = scales_k[:, 0], scales_v[:, 0]
        k8, k_s = quantize_kv_int8(k_new)
        v8, v_s = quantize_kv_int8(v_new)
        pk = pk.at[pidx, r].set(k8)
        pv = pv.at[pidx, r].set(v8)
        sk = sk.at[pidx, r].set(k_s)
        sv = sv.at[pidx, r].set(v_s)
        kd = dequantize_kv_int8(pk, sk)
        vd = dequantize_kv_int8(pv, sv)
    else:
        pk = pk.at[pidx, r].set(k_new.astype(pk.dtype))
        pv = pv.at[pidx, r].set(v_new.astype(pv.dtype))
        kd, vd = pk, pv
    T = maxp * page
    kfull = kd[table].reshape(B, Hkv, T, hd).astype(jnp.float32)
    vfull = vd[table].reshape(B, Hkv, T, hd).astype(jnp.float32)
    col = jnp.arange(T)
    attn = []
    for g in range(Hkv):
        qg = q[:, g * rep:(g + 1) * rep].astype(jnp.float32)
        sc = jnp.einsum("brd,btd->brt", qg, kfull[:, g]) * hd ** -0.5
        sc = jnp.where(col[None, None] <= pos[:, None, None], sc,
                       -jnp.inf)
        pr = jax.nn.softmax(sc, -1)
        attn.append(jnp.einsum("brt,btd->brd", pr, vfull[:, g]))
    a = jnp.concatenate(attn, 1).reshape(B, Hq * hd)
    ores = a @ weights["w_o"].astype(jnp.float32) + x
    on = rms(ores, weights["w_ln2"][0])
    gu = on @ weights["w_gu"].astype(jnp.float32)
    F = gu.shape[1] // 2
    h = jax.nn.silu(gu[:, :F]) * gu[:, F:]
    y = h @ weights["w_d"].astype(jnp.float32) + ores
    out = (y, pk[:, None], pv[:, None])
    if quant:
        out += (sk[:, None], sv[:, None])
    return out


def mega_decode_layer_ref(x, pos, weights, cache_k, cache_v, *,
                          n_heads, n_kv_heads, head_dim, eps=1e-6):
    """jnp oracle: the same layer step out of ordinary ops."""
    B, D = x.shape
    Hq, Hkv, hd = n_heads, n_kv_heads, head_dim
    rep = Hq // Hkv
    x = x.astype(jnp.float32)

    def rms(v, g):
        return v * jax.lax.rsqrt(
            jnp.mean(v * v, -1, keepdims=True) + eps) * g

    xn = rms(x, weights["w_ln1"][0])
    qkv = xn @ weights["w_qkv"].astype(jnp.float32)
    c = weights["cos_row"]
    s = weights["sin_row"]
    half = hd // 2

    def rope_head(v, g):
        v = rms(v, g)
        x1, x2 = v[:, :half], v[:, half:]
        return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], -1)

    heads = []
    for hi in range(Hq + Hkv):
        off = hi * hd
        g = (weights["q_norm"][0] if hi < Hq else weights["k_norm"][0])
        heads.append(rope_head(qkv[:, off:off + hd], g))
    q = jnp.stack(heads[:Hq], 1)                       # [B, Hq, hd]
    k_new = jnp.stack(heads[Hq:], 1)                   # [B, Hkv, hd]
    v_new = qkv[:, (Hq + Hkv) * hd:].reshape(B, Hkv, hd)
    ck = cache_k.at[:, :, pos, :].set(
        k_new.transpose(1, 0, 2).astype(cache_k.dtype))
    cv = cache_v.at[:, :, pos, :].set(
        v_new.transpose(1, 0, 2).astype(cache_v.dtype))
    T = ck.shape[2]
    col = jnp.arange(T)
    attn = []
    for g in range(Hkv):
        qg = q[:, g * rep:(g + 1) * rep].astype(jnp.float32)
        kg = ck[g].astype(jnp.float32)                 # [B, T, hd]
        vg = cv[g].astype(jnp.float32)
        sc = jnp.einsum("brd,btd->brt", qg, kg) * hd ** -0.5
        sc = jnp.where(col[None, None] <= pos, sc, -jnp.inf)
        pr = jax.nn.softmax(sc, -1)
        attn.append(jnp.einsum("brt,btd->brd", pr, vg))
    a = jnp.concatenate(attn, 1).reshape(B, Hq * hd)
    ores = a @ weights["w_o"].astype(jnp.float32) + x
    on = rms(ores, weights["w_ln2"][0])
    gu = on @ weights["w_gu"].astype(jnp.float32)
    F = gu.shape[1] // 2
    h = jax.nn.silu(gu[:, :F]) * gu[:, F:]
    y = h @ weights["w_d"].astype(jnp.float32) + ores
    return y, ck, cv
