"""MegaDecodeLayer: one transformer decode layer as ONE Pallas kernel.

TPU-native re-design of the reference megakernel
(`mega_triton_kernel/models/model_builder.py:86` builds the whole layer
step as tasks executed by persistent SMs; task kinds at
`mega_triton_kernel/task/`). Task list here (emitted in schedule order
by MegaKernelBuilder — see mega/__init__ for why program order replaces
the scoreboard on a sequential TPU core):

  rmsnorm(x) -> qkv matmul -> per-head qk-norm + rope -> cache write at
  pos -> flash decode over the cache -> o-proj (+residual) ->
  rmsnorm -> gate/up matmul + swiglu -> down-proj (+residual)

The payoff mirrors the reference's: activations stay resident in VMEM
for the entire layer (zero HBM round-trips between ops), weights stream
through a single staging tile, and the per-op pipeline
prologue/epilogue cost of nine kernels collapses into one.

Decode-only (S=1). tp=1 runs the single-chip layer. tp>1 (r5) is the
reference's FLAGSHIP composition — TP=8 Qwen3 decode inside the
megakernel (`model_builder.py:86`, allreduce as an in-kernel task over
nvshmem multimem): the layer stays ONE kernel per chip and the two
cross-chip reduction points (o-proj and down-proj partials, which need
an all-reduce BEFORE their residual adds) run as in-kernel one-shot
AR sections — stage the partial to HBM, push it to every peer over
ICI, wait the n arrivals, fold on the VPU, add the residual — the
gemm_allreduce kernel's protocol inlined as tasks. Weights arrive as
the LOCAL TP shards (heads / ffn columns sharded; construct the layer
with local head/ffn counts) and activations stay replicated, exactly
the per-op gemm_ar decode sharding. Perf stance unchanged
(CEILING.md): the per-op scan remains the fast path on TPU; tp>1 mega
exists for architecture parity with the reference's flagship,
numerically close to the sharded oracle (bf16 dots + a deterministic
f32 AR fold — chained greedy tokens can diverge from other backends at
near-ties, which the tests treat as expected, not a regression).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_dist_tpu import language as dl
from triton_dist_tpu.mega.builder import MegaKernelBuilder
from triton_dist_tpu.runtime import (interpret_mode, next_collective_id,
                                     shmem_compiler_params)


def _pick_bn(total: int, want: int) -> int:
    """Largest 128-multiple tile <= want dividing `total` (sliced DMAs
    must be 128-aligned in the minor dim)."""
    b = min(want, total) // 128 * 128
    while b > 0 and total % b:
        b -= 128
    assert b > 0, (total, want)
    return b


def _mm_tiles(env, dst, src, w, rows, cols, bn, wt_name, add=None,
              act=None):
    """Tiled matmul task body: dst[:, j*bn:...] = src @ w_tile (+add).
    Weight tiles are double-buffered: the fetch of tile j+1 is in
    flight under the dot of tile j, so the MXU never stalls on HBM."""
    w_ref = env[w]
    wt = env[wt_name]
    sems = env["copy_sems"]
    nt = cols // bn

    def fetch(j, slot):
        sl = slice(j * bn, (j + 1) * bn)
        cp = pltpu.make_async_copy(
            w_ref.at[:, sl], wt.at[slot, :rows, :bn], sems.at[slot])
        cp.start()
        return cp

    fetch(0, 0)
    for j in range(nt):
        slot = j % 2
        pltpu.make_async_copy(w_ref.at[:, :bn], wt.at[slot, :rows, :bn],
                              sems.at[slot]).wait()
        if j + 1 < nt:
            fetch(j + 1, (j + 1) % 2)
        sl = slice(j * bn, (j + 1) * bn)
        acc = jax.lax.dot(env[src][...].astype(jnp.bfloat16),
                          wt[slot, :rows, :bn],
                          preferred_element_type=jnp.float32)
        if add is not None:
            acc = acc + env[add][:, sl]
        if act is not None:
            acc = act(acc)
        env[dst][:, sl] = acc


def _rmsnorm(env, dst, src, w_name, eps):
    x = env[src][...]
    g = env[w_name][...]
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    env[dst][...] = x * jax.lax.rsqrt(ms + eps) * g


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class MegaDecodeLayer:
    """Static geometry + the compiled task program for one layer."""

    d_model: int = dataclasses.field(metadata=dict(static=True))
    n_heads: int = dataclasses.field(metadata=dict(static=True))
    n_kv_heads: int = dataclasses.field(metadata=dict(static=True))
    head_dim: int = dataclasses.field(metadata=dict(static=True))
    ffn: int = dataclasses.field(metadata=dict(static=True))
    T: int = dataclasses.field(metadata=dict(static=True))
    eps: float = dataclasses.field(default=1e-6,
                                   metadata=dict(static=True))
    block_n: int = dataclasses.field(default=256,
                                     metadata=dict(static=True))
    block_t: int = dataclasses.field(default=128,
                                     metadata=dict(static=True))
    # Qwen3-style per-head RMS norm on q/k before RoPE; False skips it
    # (matching the other backends' `if q_norm is not None` gate)
    qk_norm: bool = dataclasses.field(default=True,
                                      metadata=dict(static=True))
    # TP composition (see module docstring): tp > 1 adds the two
    # in-kernel AR sections; geometry fields are then the LOCAL shards
    # (n_heads = Hq/tp etc.) and the call must run inside shard_map
    # over `axis`
    tp: int = dataclasses.field(default=1, metadata=dict(static=True))
    axis: str = dataclasses.field(default="tp",
                                  metadata=dict(static=True))

    def __call__(self, x, pos, weights: Dict[str, jax.Array], cache_k,
                 cache_v):
        """x: [B, D]; pos: traced scalar (tokens already cached);
        weights: w_ln1 [1,D], w_qkv [D,(Hq+2Hkv)hd], q_norm/k_norm
        [1,hd], w_o [Hq*hd,D], w_ln2 [1,D], w_gu [D,2F] (gate|up),
        w_d [F,D], cos_row/sin_row [1,hd//2] for position `pos`.
        cache_k/v: [Hkv, B, T, hd]. Returns (y [B,D], cache_k, cache_v).
        """
        B, D = x.shape
        Hq, Hkv, hd = self.n_heads, self.n_kv_heads, self.head_dim
        rep = Hq // Hkv
        F = self.ffn
        T = self.T
        bn = self.block_n
        bt = self.block_t
        eps = self.eps
        Nqkv = (Hq + 2 * Hkv) * hd
        scale = hd ** -0.5
        assert D % bn == 0 and F % bn == 0 and (Hq * hd) % bn == 0, \
            (D, F, Hq * hd, bn)
        assert Hq % Hkv == 0, (Hq, Hkv)
        assert cache_k.shape == (Hkv, B, T, hd), (cache_k.shape,
                                                  (Hkv, B, T, hd))
        assert T % bt == 0

        ntp = self.tp
        ax = self.axis
        b = MegaKernelBuilder()
        b.inputs("xv", "w_ln1", "w_qkv", "q_norm", "k_norm", "w_o",
                 "w_ln2", "w_gu", "w_d", "cos", "sin", "ck", "cv",
                 "pos", "copy_sem", "copy_sems", "y")
        b.buffer("xn", (B, D), jnp.float32)
        b.buffer("qkv", (B, Nqkv), jnp.float32)
        b.buffer("attn", (B, Hq * hd), jnp.float32)
        b.buffer("ores", (B, D), jnp.float32)
        b.buffer("on", (B, D), jnp.float32)
        b.buffer("h", (B, F), jnp.float32)
        b.buffer("wt", (2, max(D, F, Hq * hd), bn), jnp.bfloat16)
        b.buffer("kvst", (B, 8, hd), jnp.bfloat16)
        # double-buffered KV tiles: the fetch of tile t+1 rides under
        # the online-softmax update of tile t
        b.buffer("kt", (2, B, bt, hd), jnp.bfloat16)
        b.buffer("vt", (2, B, bt, hd), jnp.bfloat16)
        if ntp > 1:
            # in-kernel AR plumbing (module docstring): landing/staging
            # HBM buffers are kernel outputs, fold tile in VMEM
            b.inputs("land1", "stage1", "land2", "stage2",
                     "recv1", "recv2")
            b.buffer("fold", (B, D), jnp.float32)
            b.buffer("ores_p", (B, D), jnp.float32)
            b.buffer("y_p", (B, D), jnp.float32)

            b.add_task("tp_barrier", lambda env: dl.barrier_all(ax),
                       reads=(), writes=())

        def ar_section(env, src, stage, land, recv, dst, add):
            """One-shot in-kernel all-reduce of a [B, D] partial (the
            gemm_allreduce protocol as a mega task; reference: the
            megakernel's allreduce task over nvshmem multimem):
            stage -> n pushes -> n arrival waits -> VPU fold + residual.
            """
            me = dl.my_pe(ax)
            sem = env["copy_sem"]
            cp = pltpu.make_async_copy(env[src], env[stage], sem)
            cp.start()
            cp.wait()
            for p in range(ntp):
                dl.putmem_nbi(env[land].at[me], env[stage], sem,
                              env[recv], jnp.int32(p), ax)
            for _ in range(ntp):
                pltpu.make_async_copy(env[stage], env[stage],
                                      env[recv]).wait()
            dl.quiet(sem, env[stage], ntp)
            acc = env[add][...]
            for i in range(ntp):
                cpf = pltpu.make_async_copy(env[land].at[i], env["fold"],
                                            sem)
                cpf.start()
                cpf.wait()
                acc = acc + env["fold"][...]
            env[dst][...] = acc

        b.add_task("ln1", functools.partial(_rmsnorm, dst="xn", src="xv",
                                            w_name="w_ln1", eps=eps),
                   reads=("xv", "w_ln1"), writes=("xn",))
        b.add_task("qkv_mm",
                   functools.partial(_mm_tiles, dst="qkv", src="xn",
                                     w="w_qkv", rows=D, cols=Nqkv,
                                     bn=_pick_bn(Nqkv, bn),
                                     wt_name="wt"),
                   reads=("xn", "w_qkv"), writes=("qkv", "wt"))

        def rope_norm(env):
            qkv = env["qkv"]
            c = env["cos"][...]
            s = env["sin"][...]
            half = hd // 2
            for hidx in range(Hq + Hkv):
                off = hidx * hd
                v = qkv[:, off:off + hd]
                if self.qk_norm:
                    gw = (env["q_norm"][...] if hidx < Hq
                          else env["k_norm"][...])
                    ms = jnp.mean(v * v, axis=-1, keepdims=True)
                    v = v * jax.lax.rsqrt(ms + eps) * gw
                x1 = v[:, :half]
                x2 = v[:, half:]
                qkv[:, off:off + half] = x1 * c - x2 * s
                qkv[:, off + half:off + hd] = x2 * c + x1 * s

        b.add_task("rope_norm", rope_norm,
                   reads=("qkv", "cos", "sin", "q_norm", "k_norm"),
                   writes=("qkv",))

        def cache_write(env):
            # Mosaic requires T-dim DMA slices 8-sublane aligned, so a
            # single-token append is a read-modify-write of its 8-token
            # granule (cost: one [B, 8, hd] round trip per kv head)
            qkv = env["qkv"]
            p = env["pos"]
            sem = env["copy_sem"]
            gb = (p // 8) * 8
            r = p - gb
            row = jax.lax.broadcasted_iota(jnp.int32, (B, 8, hd), 1)
            for g in range(Hkv):
                for which, buf in (("k", "ck"), ("v", "cv")):
                    base = (Hq + g) * hd if which == "k" else \
                           (Hq + Hkv + g) * hd
                    dst = env[buf].at[g, :, pl.ds(gb, 8), :]
                    cp = pltpu.make_async_copy(dst, env["kvst"], sem)
                    cp.start()
                    cp.wait()
                    new = qkv[:, base:base + hd].astype(jnp.bfloat16)
                    env["kvst"][...] = jnp.where(
                        row == r, new[:, None, :], env["kvst"][...])
                    cp = pltpu.make_async_copy(env["kvst"], dst, sem)
                    cp.start()
                    cp.wait()

        b.add_task("cache_write", cache_write,
                   reads=("qkv", "ck", "cv"), writes=("ck", "cv"))

        def flash(env):
            qkv = env["qkv"]
            p = env["pos"]
            sems = env["copy_sems"]
            nt = p // bt + 1
            for g in range(Hkv):
                q3 = qkv[:, g * rep * hd:(g + 1) * rep * hd].reshape(
                    B, rep, hd).astype(jnp.bfloat16)

                # double-buffered: copies are reconstructible
                # descriptors, so start tile t+1 in iteration t and
                # wait on its semaphore in iteration t+1
                def k_copy(t, slot, g=g):
                    return pltpu.make_async_copy(
                        env["ck"].at[g, :, pl.ds(t * bt, bt), :],
                        env["kt"].at[slot], sems.at[0])

                def v_copy(t, slot, g=g):
                    return pltpu.make_async_copy(
                        env["cv"].at[g, :, pl.ds(t * bt, bt), :],
                        env["vt"].at[slot], sems.at[1])

                k_copy(0, 0).start()
                v_copy(0, 0).start()

                def body(t, carry, g=g, q3=q3):
                    m, l, acc = carry
                    slot = jax.lax.rem(t, 2)
                    k_copy(t, slot).wait()
                    kt_t = env["kt"][slot]
                    s = jax.lax.dot_general(
                        q3, kt_t,
                        (((2,), (2,)), ((0,), (0,))),
                        preferred_element_type=jnp.float32) * scale

                    @pl.when(t + 1 < nt)
                    def _prefetch_k():
                        k_copy(t + 1, 1 - slot).start()

                    col = (t * bt
                           + jax.lax.broadcasted_iota(
                               jnp.int32, (B, rep, bt), 2))
                    sm = jnp.where(col <= p, s, -1e30)
                    m_new = jnp.maximum(m, jnp.max(sm, axis=-1))
                    alpha = jnp.exp(m - m_new)
                    pr = jnp.exp(sm - m_new[..., None])
                    pr = jnp.where(col <= p, pr, 0.0)
                    l_new = l * alpha + jnp.sum(pr, -1)
                    v_copy(t, slot).wait()
                    acc_new = (acc * alpha[..., None]
                               + jax.lax.dot_general(
                                   pr.astype(jnp.bfloat16),
                                   env["vt"][slot],
                                   (((2,), (1,)), ((0,), (0,))),
                                   preferred_element_type=jnp.float32))

                    @pl.when(t + 1 < nt)
                    def _prefetch_v():
                        v_copy(t + 1, 1 - slot).start()

                    return m_new, l_new, acc_new

                m0 = jnp.full((B, rep), -1e30, jnp.float32)
                l0 = jnp.zeros((B, rep), jnp.float32)
                a0 = jnp.zeros((B, rep, hd), jnp.float32)
                m, l, acc = jax.lax.fori_loop(0, nt, body, (m0, l0, a0))
                out = (acc / jnp.maximum(l, 1e-30)[..., None]).reshape(
                    B, rep * hd)
                env["attn"][:, g * rep * hd:(g + 1) * rep * hd] = out

        b.add_task("flash", flash, reads=("qkv", "ck", "cv"),
                   writes=("attn",))
        if ntp > 1:
            # partial o-proj (no residual: the AR must see the bare
            # partial), then the in-kernel AR adds the residual
            b.add_task("o_proj",
                       functools.partial(_mm_tiles, dst="ores_p",
                                         src="attn", w="w_o",
                                         rows=Hq * hd, cols=D, bn=bn,
                                         wt_name="wt"),
                       reads=("attn", "w_o"), writes=("ores_p", "wt"))
            b.add_task("o_allreduce",
                       functools.partial(ar_section, src="ores_p",
                                         stage="stage1", land="land1",
                                         recv="recv1", dst="ores",
                                         add="xv"),
                       reads=("ores_p", "xv"), writes=("ores", "fold"))
        else:
            b.add_task("o_proj",
                       functools.partial(_mm_tiles, dst="ores",
                                         src="attn", w="w_o",
                                         rows=Hq * hd, cols=D, bn=bn,
                                         wt_name="wt", add="xv"),
                       reads=("attn", "w_o", "xv"),
                       writes=("ores", "wt"))
        b.add_task("ln2", functools.partial(_rmsnorm, dst="on",
                                            src="ores", w_name="w_ln2",
                                            eps=eps),
                   reads=("ores", "w_ln2"), writes=("on",))

        def gate_up(env):
            # gate and up tiles in separate slots: the up-tile DMA is in
            # flight under the gate dot; swiglu fused in the epilogue
            # (reference: the megakernel's MLP task)
            wref = env["w_gu"]
            wt = env["wt"]
            sems = env["copy_sems"]
            on_bf = None
            for j in range(F // bn):
                sl = slice(j * bn, (j + 1) * bn)
                sl2 = slice(F + j * bn, F + (j + 1) * bn)
                cpg = pltpu.make_async_copy(wref.at[:, sl],
                                            wt.at[0, :D, :bn], sems.at[0])
                cpu = pltpu.make_async_copy(wref.at[:, sl2],
                                            wt.at[1, :D, :bn], sems.at[1])
                cpg.start()
                cpu.start()
                if on_bf is None:
                    on_bf = env["on"][...].astype(jnp.bfloat16)
                cpg.wait()
                g = jax.lax.dot(on_bf, wt[0, :D, :bn],
                                preferred_element_type=jnp.float32)
                cpu.wait()
                u = jax.lax.dot(on_bf, wt[1, :D, :bn],
                                preferred_element_type=jnp.float32)
                env["h"][:, sl] = g * jax.lax.logistic(g) * u

        b.add_task("gate_up_swiglu", gate_up, reads=("on", "w_gu"),
                   writes=("h", "wt"))
        if ntp > 1:
            b.add_task("down_proj",
                       functools.partial(_mm_tiles, dst="y_p", src="h",
                                         w="w_d", rows=F, cols=D, bn=bn,
                                         wt_name="wt"),
                       reads=("h", "w_d"), writes=("y_p", "wt"))
            b.add_task("d_allreduce",
                       functools.partial(ar_section, src="y_p",
                                         stage="stage2", land="land2",
                                         recv="recv2", dst="y",
                                         add="ores"),
                       reads=("y_p", "ores"), writes=("y", "fold"))
        else:
            b.add_task("down_proj",
                       functools.partial(_mm_tiles, dst="y", src="h",
                                         w="w_d", rows=F, cols=D, bn=bn,
                                         wt_name="wt", add="ores"),
                       reads=("h", "w_d", "ores"), writes=("y", "wt"))

        in_names = ["xv", "w_ln1", "w_qkv", "q_norm", "k_norm", "w_o",
                    "w_ln2", "w_gu", "w_d", "cos", "sin",
                    "ck_in", "cv_in"]
        out_names = ["y", "ck", "cv"]
        if ntp > 1:
            out_names += ["land1", "stage1", "land2", "stage2"]
        buf_names = list(b.buffers)
        sem_names = ["copy_sem", "copy_sems"]
        if ntp > 1:
            sem_names += ["recv1", "recv2"]

        def kernel(pos_ref, *refs):
            env = {"pos": pos_ref[0]}
            for i, nm in enumerate(in_names + out_names + buf_names
                                   + sem_names):
                env[nm] = refs[i]
            b.emit_all(env)   # ck/cv resolve to the ALIASED outputs

        vm = pl.BlockSpec(memory_space=pltpu.MemorySpace.VMEM)
        anym = pl.BlockSpec(memory_space=pl.ANY)
        scratch = [pltpu.VMEM(shape, dt)
                   for (shape, dt) in b.buffers.values()]
        scratch.append(pltpu.SemaphoreType.DMA(()))
        scratch.append(pltpu.SemaphoreType.DMA((2,)))
        out_shape = [jax.ShapeDtypeStruct((B, D), jnp.float32),
                     jax.ShapeDtypeStruct(cache_k.shape, cache_k.dtype),
                     jax.ShapeDtypeStruct(cache_v.shape, cache_v.dtype)]
        out_specs = [vm, anym, anym]
        if ntp > 1:
            scratch.append(pltpu.SemaphoreType.DMA(()))
            scratch.append(pltpu.SemaphoreType.DMA(()))
            for _ in range(2):   # (land, stage) x 2 AR sections
                out_shape += [
                    jax.ShapeDtypeStruct((ntp, B, D), jnp.float32),
                    jax.ShapeDtypeStruct((B, D), jnp.float32)]
                out_specs += [anym, anym]
        res = pl.pallas_call(
            kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(1,),
                in_specs=[vm, vm, anym, vm, vm, anym, vm, anym, anym,
                          vm, vm, anym, anym],
                out_specs=tuple(out_specs),
                scratch_shapes=scratch,
            ),
            out_shape=tuple(out_shape),
            input_output_aliases={12: 1, 13: 2},
            # the megakernel deliberately holds a whole layer's
            # activations + staging tiles in VMEM; lift the default 16MB
            # scoped-vmem ceiling (v5e has 128MB physical VMEM)
            compiler_params=shmem_compiler_params(
                next_collective_id() if ntp > 1 else None, n=ntp,
                vmem_limit_bytes=100 << 20),
            interpret=interpret_mode(),
        )(jnp.asarray(pos, jnp.int32)[None],
          x.astype(jnp.float32),
          weights["w_ln1"], weights["w_qkv"].astype(jnp.bfloat16),
          weights["q_norm"], weights["k_norm"],
          weights["w_o"].astype(jnp.bfloat16), weights["w_ln2"],
          weights["w_gu"].astype(jnp.bfloat16),
          weights["w_d"].astype(jnp.bfloat16),
          weights["cos_row"], weights["sin_row"],
          cache_k, cache_v)
        return res[0], res[1], res[2]


def mega_decode_layer_ref(x, pos, weights, cache_k, cache_v, *,
                          n_heads, n_kv_heads, head_dim, eps=1e-6):
    """jnp oracle: the same layer step out of ordinary ops."""
    B, D = x.shape
    Hq, Hkv, hd = n_heads, n_kv_heads, head_dim
    rep = Hq // Hkv
    x = x.astype(jnp.float32)

    def rms(v, g):
        return v * jax.lax.rsqrt(
            jnp.mean(v * v, -1, keepdims=True) + eps) * g

    xn = rms(x, weights["w_ln1"][0])
    qkv = xn @ weights["w_qkv"].astype(jnp.float32)
    c = weights["cos_row"]
    s = weights["sin_row"]
    half = hd // 2

    def rope_head(v, g):
        v = rms(v, g)
        x1, x2 = v[:, :half], v[:, half:]
        return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], -1)

    heads = []
    for hi in range(Hq + Hkv):
        off = hi * hd
        g = (weights["q_norm"][0] if hi < Hq else weights["k_norm"][0])
        heads.append(rope_head(qkv[:, off:off + hd], g))
    q = jnp.stack(heads[:Hq], 1)                       # [B, Hq, hd]
    k_new = jnp.stack(heads[Hq:], 1)                   # [B, Hkv, hd]
    v_new = qkv[:, (Hq + Hkv) * hd:].reshape(B, Hkv, hd)
    ck = cache_k.at[:, :, pos, :].set(
        k_new.transpose(1, 0, 2).astype(cache_k.dtype))
    cv = cache_v.at[:, :, pos, :].set(
        v_new.transpose(1, 0, 2).astype(cache_v.dtype))
    T = ck.shape[2]
    col = jnp.arange(T)
    attn = []
    for g in range(Hkv):
        qg = q[:, g * rep:(g + 1) * rep].astype(jnp.float32)
        kg = ck[g].astype(jnp.float32)                 # [B, T, hd]
        vg = cv[g].astype(jnp.float32)
        sc = jnp.einsum("brd,btd->brt", qg, kg) * hd ** -0.5
        sc = jnp.where(col[None, None] <= pos, sc, -jnp.inf)
        pr = jax.nn.softmax(sc, -1)
        attn.append(jnp.einsum("brt,btd->brd", pr, vg))
    a = jnp.concatenate(attn, 1).reshape(B, Hq * hd)
    ores = a @ weights["w_o"].astype(jnp.float32) + x
    on = rms(ores, weights["w_ln2"][0])
    gu = on @ weights["w_gu"].astype(jnp.float32)
    F = gu.shape[1] // 2
    h = jax.nn.silu(gu[:, :F]) * gu[:, F:]
    y = h @ weights["w_d"].astype(jnp.float32) + ores
    return y, ck, cv
