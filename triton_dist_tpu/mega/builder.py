"""Task-program builder: compose a megakernel from task closures.

Reference analog: `mega_triton_kernel/models/model_builder.py:86` — ops
are recorded as tasks with buffer dependencies and compiled into one
launch. Here each task is a Python closure emitted at trace time into a
single Pallas kernel body; buffers are named VMEM residencies managed
by the builder (the reference's buffer manager role). Because a TPU
core is a single instruction stream, the recorded order is the
schedule (see package docstring); the builder still validates the
read-after-write chain so a misordered program fails at build, the
role the runtime scoreboard plays in the reference.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Sequence, Tuple


@dataclasses.dataclass
class Task:
    name: str
    reads: Tuple[str, ...]
    writes: Tuple[str, ...]
    emit: Callable          # emit(env: dict[str, ref]) -> None


class MegaKernelBuilder:
    """Record named VMEM buffers and tasks; validate dependencies;
    produce the ordered emit list a kernel body runs."""

    def __init__(self):
        self._buffers: Dict[str, Tuple[Tuple[int, ...], object]] = {}
        self._tasks: List[Task] = []
        self._written: set = set()

    def buffer(self, name: str, shape: Tuple[int, ...], dtype) -> str:
        """Declare a VMEM-resident intermediate (the buffer-manager
        analog)."""
        if name in self._buffers:
            raise ValueError(f"buffer {name!r} already declared")
        self._buffers[name] = (tuple(shape), dtype)
        return name

    def inputs(self, *names: str) -> None:
        """Mark buffers produced outside the kernel (kernel operands)."""
        self._written.update(names)

    def add_task(self, name: str, emit: Callable, *,
                 reads: Sequence[str] = (),
                 writes: Sequence[str] = ()) -> None:
        known = set(self._buffers) | self._written
        for nm in (*reads, *writes):
            if nm not in known:
                raise ValueError(
                    f"task {name!r} references undeclared name {nm!r} "
                    "(declare it with buffer()/inputs())")
        for r in reads:
            if r not in self._written:
                raise ValueError(
                    f"task {name!r} reads {r!r} before any task wrote it "
                    "(the scoreboard-order violation the reference "
                    "detects at runtime)")
        self._written.update(writes)
        self._tasks.append(Task(name=name, reads=tuple(reads),
                                writes=tuple(writes), emit=emit))

    @property
    def buffers(self) -> Dict[str, Tuple[Tuple[int, ...], object]]:
        return dict(self._buffers)

    @property
    def tasks(self) -> List[Task]:
        return list(self._tasks)

    def emit_all(self, env: Dict[str, object]) -> None:
        """Run every task's emitter in schedule order (called inside the
        Pallas kernel body)."""
        for t in self._tasks:
            t.emit(env)
