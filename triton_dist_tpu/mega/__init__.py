"""Megakernel subsystem (reference analog: mega_triton_kernel/ —
`models/model_builder.py:86` task-graph builder + the persistent-SM
scoreboard runtime).

On TPU the analog changes shape for a hardware reason worth recording:
the reference needs a scoreboard because 100+ SMs execute tasks
concurrently and dependencies must be enforced at runtime; a TPU core
executes ONE instruction stream, so a topologically-sorted task list IS
the schedule and the scoreboard degenerates to program order. What
survives — and is the actual win on both platforms — is running an
entire decode layer as ONE kernel with activations resident in VMEM:
no HBM round-trips between norm/proj/attention/MLP, no per-op launch
or pipeline-prologue cost.
"""

from triton_dist_tpu.mega.builder import MegaKernelBuilder  # noqa: F401
from triton_dist_tpu.mega.decode_layer import (  # noqa: F401
    MegaDecodeLayer,
    MegaPagedDecodeLayer,
    mega_decode_layer_ref,
    mega_paged_decode_layer_ref,
)
