"""Kernel contract analyzer (tdcheck checker 1).

Walks the jaxpr of every registered kernel wrapper
(kernels.kernel_registry) at its canonical sample shapes — a pure
trace, nothing executes — and checks, per pallas_call:

- **VMEM budget**: the per-grid-step footprint estimate (pipelined
  operand blocks double-buffered + VMEM scratch) must fit the chip's
  VMEM (~16 MiB/core, pallas_guide). An over-budget kernel compiles on
  the interpreter substrate and dies (or silently spills) on the chip —
  exactly the class of break the CPU suite cannot see.
- **block divisibility**: a pipelined BlockSpec whose block shape does
  not divide its array shape makes Mosaic pad trailing blocks — with
  OOB garbage flowing into reductions unless the kernel masks. The
  repo's kernels all pick dividing blocks on purpose (e.g.
  swiglu's _pick loop); a non-dividing block is a refactor regression.
- **in-place donation**: a kernel registered with `inplace=((in, out),
  ...)` (kv_update's aliased cache, kv_cache_scatter's window buffer)
  must actually carry those input_output_aliases in its trace — a
  dropped alias silently doubles the buffer's HBM traffic and
  allocation.

Every diagnostic carries the pallas_call's file:line (its
name_and_src_info), so a finding lands in the kernel source, not in
the analyzer.
"""

from __future__ import annotations

import math
from typing import Optional

from triton_dist_tpu.analysis import Report, eqn_src, iter_eqns

# ~16 MiB/core (pallas_guide "VMEM ~16 MB/core"); the estimate below
# is deliberately conservative (counts double buffering) so a kernel
# flagged here is genuinely close to the edge on a v5e core.
DEFAULT_VMEM_BUDGET = 16 << 20


def _dtype_size(dt) -> int:
    import jax.numpy as jnp
    try:
        return jnp.dtype(dt).itemsize
    except Exception:
        return 4


def _block_bytes(block_shape, dtype) -> int:
    n = 1
    for d in block_shape:
        # older jax spells "no block axis" as None; newer as pl.Squeezed
        n *= int(d) if isinstance(d, int) else 1
    return n * _dtype_size(dtype)


def _io_and_scratch_vars(eqn):
    gm = eqn.params["grid_mapping"]
    inner = eqn.params["jaxpr"]
    # inner invars: [scalar-prefetch] + inputs + outputs + scratch
    n_idx = gm.num_index_operands
    n_io = gm.num_inputs + gm.num_outputs
    return inner.invars[n_idx:n_idx + n_io], inner.invars[n_idx + n_io:]


def _unpipelined(var) -> bool:
    space = str(getattr(var.aval, "memory_space", None)).lower()
    # unpipelined HBM operand (comm kernels) / scalars: no VMEM block,
    # no divisibility contract
    return "any" in space or "smem" in space or "semaphore" in space


def eqn_vmem(eqn) -> int:
    """Per-grid-step VMEM estimate (bytes) of ONE pallas_call eqn:
    pipelined operand blocks (double-buffered when the grid actually
    pipelines) plus VMEM scratch — the single footprint model shared by
    the contract checker and `estimate_vmem` (the sweep pruner)."""
    gm = eqn.params["grid_mapping"]
    io_vars, scratch_vars = _io_and_scratch_vars(eqn)
    nsteps = math.prod(int(g) for g in gm.grid) if gm.grid else 1
    vmem = 0
    for bm, var in zip(gm.block_mappings, io_vars):
        if _unpipelined(var):
            continue
        bb = _block_bytes(bm.block_shape, bm.array_shape_dtype.dtype)
        # Pallas double-buffers pipelined blocks (grid>1): 2x per operand
        vmem += bb * (2 if nsteps > 1 else 1)
    for var in scratch_vars:
        space = str(getattr(var.aval, "memory_space", None)).lower()
        if "vmem" in space:
            vmem += _block_bytes(var.aval.shape, var.aval.dtype)
    return vmem


def estimate_vmem(fn, args) -> int:
    """Public VMEM-footprint API (ISSUE 16): trace `fn(*args)` (a pure
    trace — nothing executes, no device memory is touched) and return
    the MAX per-grid-step VMEM estimate in bytes over every pallas_call
    in the trace — exactly the model the contract checker gates on.
    Returns 0 when the trace contains no pallas_call (XLA-only fn)."""
    import jax
    jaxpr = jax.make_jaxpr(fn)(*args)
    return max((eqn_vmem(e)
                for e in iter_eqns(jaxpr.jaxpr, "pallas_call")),
               default=0)


def analyze_pallas_eqn(eqn, report: Report, kernel_name: str,
                       budget: int) -> dict:
    """Contract checks for ONE pallas_call eqn; returns the extracted
    facts. The in-place-donation contract is enforced by check_kernel
    (aliases may live on ANY pallas_call of a kernel's trace)."""
    gm = eqn.params["grid_mapping"]
    src = eqn_src(eqn)
    body_name = eqn.params["name_and_src_info"].name
    subject = f"{kernel_name}/{body_name}"

    io_vars, _ = _io_and_scratch_vars(eqn)
    vmem = eqn_vmem(eqn)
    pipelined = 0
    blocks = []
    for bm, var in zip(gm.block_mappings, io_vars):
        space = str(getattr(var.aval, "memory_space", None)).lower()
        arr = bm.array_shape_dtype
        rec = dict(block=tuple(bm.block_shape), array=tuple(arr.shape),
                   dtype=str(arr.dtype), space=space)
        blocks.append(rec)
        if _unpipelined(var):
            continue
        pipelined += 1
        for bdim, adim in zip(bm.block_shape, arr.shape):
            if not isinstance(bdim, int):
                continue
            if bdim > int(adim) or int(adim) % bdim:
                report.add(
                    "error", src, subject,
                    f"block shape {tuple(bm.block_shape)} does not "
                    f"divide array shape {tuple(arr.shape)} "
                    f"(dim {bdim} vs {int(adim)}): Mosaic pads the "
                    f"trailing block and unmasked reductions read "
                    f"garbage")
                break

    if vmem > budget:
        report.add(
            "error", src, subject,
            f"per-grid-step VMEM estimate {vmem / (1 << 20):.2f} MiB "
            f"exceeds the {budget / (1 << 20):.0f} MiB budget "
            f"({pipelined} pipelined operands double-buffered + VMEM "
            f"scratch): shrink the BlockSpecs or raise the registry's "
            f"vmem_budget with a measured justification")

    aliases = set(eqn.params.get("input_output_aliases") or ())
    return dict(subject=subject, src=src, vmem=vmem, grid=tuple(gm.grid),
                blocks=blocks, aliases=sorted(aliases))


def check_kernel(spec, mesh, report: Optional[Report] = None) -> Report:
    """Trace one registered kernel and run the contract checks over
    every pallas_call in its jaxpr."""
    import jax
    if report is None:
        report = Report("contracts")
    fn, args = spec.build(mesh)
    jaxpr = jax.make_jaxpr(fn)(*args)
    budget = spec.vmem_budget or DEFAULT_VMEM_BUDGET
    eqns = list(iter_eqns(jaxpr.jaxpr, "pallas_call"))
    if not eqns:
        report.add("warning", f"triton_dist_tpu/{spec.module}",
                   spec.name,
                   "registered kernel traces to zero pallas_calls "
                   "(XLA fallback path? fix the sample shapes or the "
                   "registry entry)")
    pending = set(map(tuple, spec.inplace))
    for eqn in eqns:
        analyze_pallas_eqn(eqn, report, spec.name, budget)
        pending -= set(map(
            tuple, eqn.params.get("input_output_aliases") or ()))
    for pair in sorted(pending):
        report.add(
            "error", f"triton_dist_tpu/{spec.module}", spec.name,
            f"registered in-place kernel: no pallas_call in the trace "
            f"carries input_output_aliases {pair} — the donation was "
            f"dropped (the 'in-place' update now allocates and copies "
            f"a second buffer every call)")
    report.covered.append(spec.name)
    return report


def run(mesh=None, names=None) -> Report:
    """Contract-check the full registry (the tdcheck CLI entry)."""
    import jax
    from triton_dist_tpu.kernels import kernel_registry
    if mesh is None:
        n = len(jax.devices())
        mesh = jax.make_mesh((n,), ("tp",))
    ndev = mesh.shape["tp"]
    report = Report("contracts")
    for name, spec in kernel_registry().items():
        if names and name not in names:
            continue
        if spec.min_devices > ndev:
            continue
        try:
            check_kernel(spec, mesh, report)
        except Exception as e:  # a broken trace is itself a finding
            report.add("error", f"triton_dist_tpu/{spec.module}", name,
                       f"kernel failed to trace at its canonical "
                       f"sample shapes: {e!r}")
    return report
