"""Comm protocol verifier (tdcheck checker 3).

Builds the per-device signal graph of every one-sided kernel from the
facade's trace-time recorder (language.comm_trace — the kernels are
TRACED via jax.make_jaxpr, never executed, so this runs on any
substrate including ones whose interpreter cannot simulate remote
DMA). The per-device SPMD program is symmetric: each device runs the
same event sequence, so per-program balance is exactly the global
protocol contract:

- **unmatched set/wait**: every one-sided put signals its send
  semaphore (locally) and its recv semaphore (on the peer); the
  program must drain exactly the bytes it sent (quiet) and await
  exactly the bytes its peers' symmetric puts land on it. A missing
  wait is a data race on the landing buffer; a missing drain lets the
  kernel retire with DMAs in flight reading reclaimed memory. A
  surplus wait deadlocks on hardware (the interpreter's synchronous
  DMAs can mask it).
- **wait-before-set**: a wait on a semaphore positioned before ANY
  event that could signal it — symmetric peers run the same program,
  so every device blocks before any device signals: guaranteed
  deadlock.
- **barrier elision**: remote puts with no barrier_all anywhere
  before the first put. The entry barrier is what guarantees the
  peer's landing buffer (a fresh pallas output) exists and its
  previous consumer is done — eliding it is the symmetric-buffer
  reuse hazard the reference documents around nvshmem_barrier_all.
- **regular-semaphore credits**: signal_op increments must equal
  signal_wait_until consumed values (flow-control credits leak
  otherwise, skewing the NEXT kernel on the same collective id).

Kernels registered protocol="dynamic" use data-dependent arrival
counts (dl.dma_wait_dyn); exact balance is unknowable statically, so
only ordering/barrier checks apply to the dynamic semaphore.
"""

from __future__ import annotations

from typing import List, Optional

from triton_dist_tpu.analysis import Report


def trace_kernel_events(spec, mesh) -> List[dict]:
    """Trace one registered comm kernel under dl.comm_trace (pure
    trace: make_jaxpr, nothing executes)."""
    import jax
    from triton_dist_tpu import language as dl
    fn, args = spec.build(mesh)
    with dl.comm_trace() as events:
        jax.make_jaxpr(fn)(*args)
    return list(events)


def verify_events(events: List[dict], subject: str,
                  report: Optional[Report] = None,
                  strict: bool = True) -> Report:
    """Signal-graph checks over one kernel's per-device event stream."""
    if report is None:
        report = Report("protocol")
    puts = [(i, e) for i, e in enumerate(events) if e["op"] == "put"]
    waits = [(i, e) for i, e in enumerate(events)
             if e["op"] == "dma_wait"]
    dyn_waits = [(i, e) for i, e in enumerate(events)
                 if e["op"] == "dma_wait_dyn"]
    sem_waits = [(i, e) for i, e in enumerate(events)
                 if e["op"] == "sem_wait"]
    signals = [(i, e) for i, e in enumerate(events)
               if e["op"] == "signal"]
    local = [(i, e) for i, e in enumerate(events)
             if e["op"] in ("local_copy", "local_copy_nbi")]
    barriers = [i for i, e in enumerate(events)
                if e["op"] == "barrier_all" and (e.get("n") or 2) > 1]
    src_of = {i: e.get("src", "<unknown>") for i, e in enumerate(events)}

    # --- barrier elision ------------------------------------------------
    if puts:
        first_put = puts[0][0]
        if not any(b < first_put for b in barriers):
            report.add(
                "error", src_of[first_put], subject,
                "one-sided put with no barrier_all before it: the "
                "peer's landing buffer may still be owned by its "
                "previous consumer (symmetric-buffer reuse hazard) — "
                "open the kernel with dl.barrier_all(axis)")

    # --- per-semaphore DMA byte ledgers --------------------------------
    sent = {}      # send_sem -> bytes signalled locally by puts
    landed = {}    # recv_sem -> bytes peers' symmetric puts land here
    first_set = {}
    for i, e in puts:
        b = e.get("bytes") or 0
        for role in ("send_sem", "recv_sem"):
            s = e.get(role)
            if s is None:
                continue
            (sent if role == "send_sem" else landed)[s] = \
                (sent if role == "send_sem" else landed).get(s, 0) + b
            first_set.setdefault(s, i)
    for i, e in local:
        s = e.get("sem")
        if s is not None:
            sent[s] = sent.get(s, 0)  # known sem; bytes self-balanced
            first_set.setdefault(s, i)

    awaited = {}
    dynamic = set()
    for i, e in waits:
        s = e.get("sem")
        awaited[s] = awaited.get(s, 0) + (e.get("bytes") or 0) * \
            e.get("count", 1)
        if s not in first_set and s is not None:
            report.add(
                "error", e.get("src", "<unknown>"), subject,
                "dma_wait on a semaphore no put or local copy in this "
                "program ever signals: every device blocks here "
                "forever (wait-before-set across the whole program)")
        elif s is not None and i < first_set[s]:
            report.add(
                "error", e.get("src", "<unknown>"), subject,
                "wait-before-set: this dma_wait precedes every "
                "event that signals its semaphore in program order — "
                "symmetric peers all block before any signals "
                "(guaranteed deadlock on hardware)")
    for i, e in dyn_waits:
        s = e.get("sem")
        dynamic.add(s)
        if s is not None and s not in first_set:
            report.add(
                "error", e.get("src", "<unknown>"), subject,
                "dma_wait_dyn on a semaphore no put or local copy in "
                "this program ever signals: any rank whose runtime "
                "count is nonzero blocks forever")
        elif s in first_set and i < first_set[s]:
            report.add(
                "error", e.get("src", "<unknown>"), subject,
                "wait-before-set: dynamic arrival wait precedes every "
                "signalling event of its semaphore")

    if strict:
        for s, b in sent.items():
            if s in dynamic or b == 0:
                continue
            got = awaited.get(s, 0)
            if got != b:
                report.add(
                    "error", src_of[first_set[s]], subject,
                    f"unmatched set/wait on a SEND semaphore: puts "
                    f"signalled {b} bytes but the program drains "
                    f"{got} — "
                    + ("in-flight DMAs outlive the kernel (quiet is "
                       "missing or short)" if got < b else
                       "surplus drain deadlocks on hardware"))
        for s, b in landed.items():
            if s in dynamic:
                continue
            got = awaited.get(s, 0)
            if got != b:
                report.add(
                    "error", src_of[first_set[s]], subject,
                    f"unmatched set/wait on a RECV semaphore: "
                    f"symmetric peers land {b} bytes here but the "
                    f"program awaits {got} — "
                    + ("the landing buffer is read before the DMA "
                       "completes (data race)" if got < b else
                       "surplus wait deadlocks on hardware"))

    # --- regular-semaphore credit ledger -------------------------------
    cred = {}
    first_sig = {}
    for i, e in signals:
        s = e.get("sem")
        cred[s] = cred.get(s, 0) + e.get("inc", 1)
        first_sig.setdefault(s, i)
    consumed = {}
    for i, e in sem_waits:
        s = e.get("sem")
        consumed[s] = consumed.get(s, 0) + e.get("value", 1)
        if s not in first_sig:
            report.add(
                "error", e.get("src", "<unknown>"), subject,
                "signal_wait_until on a semaphore this program never "
                "signals (no symmetric peer will either): guaranteed "
                "deadlock")
        elif i < first_sig[s]:
            report.add(
                "error", e.get("src", "<unknown>"), subject,
                "wait-before-set on a REGULAR semaphore: the wait "
                "precedes every signal_op in program order")
    if strict:
        for s, c in cred.items():
            got = consumed.get(s, 0)
            if got != c:
                report.add(
                    "error", src_of[first_sig[s]], subject,
                    f"credit imbalance: signal_op grants {c} but "
                    f"signal_wait_until consumes {got} — leftover "
                    f"credits skew the next kernel on this "
                    f"collective id" if got < c else
                    f"credit imbalance: consumes {got} of {c} "
                    f"granted — the surplus wait deadlocks")
    return report


def check_kernel(spec, mesh, report: Optional[Report] = None) -> Report:
    if report is None:
        report = Report("protocol")
    events = trace_kernel_events(spec, mesh)
    if not any(e["op"] == "put" for e in events):
        report.add(
            "warning", f"triton_dist_tpu/{spec.module}", spec.name,
            "registered comm kernel traced zero one-sided puts "
            "(degenerate shape or XLA fallback — fix the registry "
            "sample)")
    verify_events(events, spec.name, report,
                  strict=spec.protocol == "strict")
    report.covered.append(spec.name)
    return report


def run(mesh=None, names=None) -> Report:
    """Protocol-verify every registered comm kernel (CLI entry)."""
    import jax
    from triton_dist_tpu.kernels import kernel_registry
    if mesh is None:
        n = len(jax.devices())
        mesh = jax.make_mesh((n,), ("tp",))
    ndev = mesh.shape["tp"]
    report = Report("protocol")
    for name, spec in kernel_registry().items():
        if names and name not in names:
            continue
        if spec.protocol is None or spec.min_devices > ndev:
            continue
        try:
            check_kernel(spec, mesh, report)
        except Exception as e:
            report.add("error", f"triton_dist_tpu/{spec.module}", name,
                       f"comm kernel failed to trace: {e!r}")
    return report
