"""Serving hot-loop lint (tdcheck checker 4).

The scheduler's poll loop has two structural perf contracts the
bitwise suites guard only dynamically (test_overlap's compile-counter
churn guard; the coalesced-readback design of DecodeSlots._fetch):

1. **no recompile-key churn**: every poll must reuse the SAME jitted
   program objects with the SAME trace — a fresh partial per poll, a
   non-deterministic static arg, or a trace-time fresh collective id
   silently turns the decode tick into a compile storm. Checked two
   ways: `_jit_programs` must be process-cached (calling it twice with
   one configuration returns the IDENTICAL program dict), and every
   decode-tick program must trace DETERMINISTICALLY (two traces at the
   canonical shapes hash identically).
2. **no host transfer inside the decode tick**: the tick programs must
   contain no callback/infeed/outfeed primitive — any host hop inside
   the jitted tick serializes the device pipeline the overlap
   scheduler exists to fill (the PR-7 zero-host-transfer contract).
   The ONE legitimate host readback is the scheduler's coalesced
   device_get in `_fetch`, which lives outside the programs.

Everything here is trace-only (jax.make_jaxpr): the full lint over the
canonical tiny-model program set compiles nothing and runs in seconds.
"""

from __future__ import annotations

import hashlib
from typing import Dict, Optional, Tuple

from triton_dist_tpu.analysis import Report, eqn_src, iter_eqns

_HERE = "triton_dist_tpu/analysis/hotloop.py"

# host-transfer primitives: anything here inside a decode-tick program
# is a poll-loop stall (jax spells callbacks differently across
# versions; match on substring)
_HOST_PRIM_MARKERS = ("callback", "infeed", "outfeed")


def jaxpr_hash(fn, *args, **kwargs) -> str:
    """Stable hash of fn's trace at these shapes (the recompile key's
    observable body)."""
    import jax
    jaxpr = jax.make_jaxpr(lambda *a: fn(*a, **kwargs))(*args)
    return hashlib.sha256(str(jaxpr).encode()).hexdigest()


def check_host_transfers(fn, args, kwargs, subject: str,
                         report: Report) -> None:
    import jax
    jaxpr = jax.make_jaxpr(lambda *a: fn(*a, **kwargs))(*args)
    for eqn in iter_eqns(jaxpr.jaxpr):
        name = eqn.primitive.name
        if any(m in name for m in _HOST_PRIM_MARKERS):
            report.add(
                "error", eqn_src(eqn), subject,
                f"host transfer inside a decode-tick program: "
                f"primitive '{name}' round-trips to the host every "
                f"tick, serializing the device pipeline the overlap "
                f"scheduler hides host work behind — move it to the "
                f"scheduler's coalesced readback (_fetch) or drop it")


def check_trace_determinism(fn, args, kwargs, subject: str,
                            report: Report) -> None:
    h1 = jaxpr_hash(fn, *args, **kwargs)
    h2 = jaxpr_hash(fn, *args, **kwargs)
    if h1 != h2:
        report.add(
            "error", _HERE + ":check_trace_determinism", subject,
            f"recompile-key churn: two traces of this program at "
            f"identical shapes differ ({h1[:12]} vs {h2[:12]}) — "
            f"something trace-impure (a fresh collective id, a counter "
            f"baked as a literal, an id()-keyed branch) retraces every "
            f"poll and recompiles the tick")


def check_program_cache_identity(report: Report) -> None:
    """_jit_programs must hand back the SAME dict (and program
    objects) for one configuration — jax's executable cache keys on
    the callable object, so fresh wrappers mean a compile per poll."""
    from triton_dist_tpu.models.engine import _jit_programs
    key = ("flash", "greedy", (0.0, 0, 1.0), "auto")
    a = _jit_programs(*key)
    b = _jit_programs(*key)
    if a is not b:
        report.add(
            "error", "triton_dist_tpu/models/engine.py:_jit_programs",
            "_jit_programs",
            "program-set factory is not process-cached: two calls "
            "with one configuration returned distinct dicts — every "
            "engine construction recompiles the whole slot-program "
            "family")
    else:
        for name in a:
            if a[name] is not b[name]:
                report.add(
                    "error",
                    "triton_dist_tpu/models/engine.py:_jit_programs",
                    name,
                    "program object is rebuilt per call: jax's "
                    "executable cache keys on the callable, so this "
                    "program recompiles per engine")


def canonical_programs(engine, batch: int = 2
                       ) -> Dict[str, Tuple]:
    """(fn, args, kwargs) per decode-tick program at canonical tiny
    shapes — the hot-loop surface ContinuousScheduler polls."""
    import jax
    import jax.numpy as jnp
    from triton_dist_tpu.models import engine as eng_mod
    model = engine.model
    V = model.config.vocab_size
    B = batch
    fb = "flash" if engine.backend == "mega" else engine.backend
    cache = engine.make_slot_cache(B)
    pcache = engine.make_paged_slot_cache(B)
    logits0 = jnp.zeros((B, V), jnp.float32)
    pos = jnp.zeros((B,), jnp.int32)
    active = jnp.ones((B,), bool)
    tokens = jnp.zeros((B, 2), jnp.int32)
    q_lens = jnp.ones((B,), jnp.int32)
    prefilling = jnp.zeros((B,), bool)
    keys = jax.random.split(jax.random.PRNGKey(0), B)
    ids = jnp.zeros((2,), jnp.int32)
    owners = jnp.zeros((2,), jnp.int32)
    params = dict(temperature=0.0, k=0, p=1.0)

    progs = {
        "slot_scan": (
            lambda *a: eng_mod._slot_scan_decode_fn(fb, *a, gen_len=2),
            (model, logits0, cache, pos, active), {}),
        "paged_slot_scan": (
            lambda *a: eng_mod._paged_slot_scan_decode_fn(
                fb, *a, gen_len=2),
            (model, logits0, pcache, pos, active), {}),
        "slot_verify": (
            lambda *a: eng_mod._slot_verify_fn(fb, *a),
            (model, cache, pos, active, tokens, q_lens), {}),
        "paged_slot_verify": (
            lambda *a: eng_mod._paged_slot_verify_fn(fb, *a),
            (model, pcache, pos, active, tokens, q_lens), {}),
        "slot_mixed": (
            lambda *a: eng_mod._mixed_step_fn(fb, None, params,
                                              False, *a),
            (model, logits0, cache, pos, active, prefilling, tokens,
             q_lens, keys), {}),
        "paged_slot_mixed": (
            lambda *a: eng_mod._mixed_step_fn(fb, None, params,
                                              True, *a),
            (model, logits0, pcache, pos, active, prefilling, tokens,
             q_lens, keys), {}),
        "gather_pages": (
            eng_mod._gather_pages_fn, (model, pcache, ids, owners), {}),
    }
    if engine.backend == "mega":
        progs["paged_slot_mega"] = (
            lambda *a: eng_mod._paged_slot_mega_scan_fn(*a, gen_len=2),
            (model, logits0, pcache, pos, active), {})
    # restore_pages' payload shapes come from the gather's avals
    gshape = jax.eval_shape(eng_mod._gather_pages_fn, model, pcache,
                            ids, owners)
    hk = jnp.zeros(gshape[0].shape, gshape[0].dtype)
    hv = jnp.zeros(gshape[1].shape, gshape[1].dtype)
    progs["restore_pages"] = (
        eng_mod._restore_pages_fn, (model, pcache, ids, hk, hv), {})
    return progs


def check_engine(engine, batch: int = 2,
                 report: Optional[Report] = None) -> Report:
    if report is None:
        report = Report("hotloop")
    for name, (fn, args, kwargs) in canonical_programs(
            engine, batch).items():
        subject = f"{name}[{engine.backend}]"
        try:
            check_host_transfers(fn, args, kwargs, subject, report)
            check_trace_determinism(fn, args, kwargs, subject, report)
            report.covered.append(subject)
        except Exception as e:
            report.add("error",
                       "triton_dist_tpu/models/engine.py", subject,
                       f"decode-tick program failed to trace at "
                       f"canonical shapes: {e!r}")
    return report


def run(report: Optional[Report] = None) -> Report:
    """CLI entry: the canonical tiny engine's full decode-tick program
    surface + the process-wide program-cache identity check."""
    import jax
    from triton_dist_tpu.models import AutoLLM, Engine
    from triton_dist_tpu.models.config import tiny_qwen3
    if report is None:
        report = Report("hotloop")
    check_program_cache_identity(report)
    mesh = jax.make_mesh((1,), ("tp",), devices=jax.devices()[:1])
    model = AutoLLM.from_config(tiny_qwen3(1), mesh)
    engine = Engine(model, max_seq=64, backend="flash")
    check_engine(engine, report=report)
    return report
