"""tdcheck CLI: ``python -m triton_dist_tpu.analysis [checker ...]``.

Runs the requested checkers (default: all) and exits non-zero when any
ERROR finding survives — the tools/tdcheck.sh gate. Checkers:
contracts, protocol, races, hotloop, deadcode. To add one: write a
module with a ``run() -> Report`` and register it in _CHECKERS.
"""

from __future__ import annotations

import argparse
import sys
import time


def _load(name):
    import importlib
    return importlib.import_module(f"triton_dist_tpu.analysis.{name}")


_CHECKERS = ("contracts", "protocol", "races", "hotloop", "deadcode")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m triton_dist_tpu.analysis",
        description="tdcheck: static analysis for Pallas kernels and "
                    "the serving hot loop")
    ap.add_argument("checkers", nargs="*", default=None,
                    metavar="checker",
                    help=f"subset of {', '.join(_CHECKERS)} "
                         f"(default: all)")
    ap.add_argument("--warnings-as-errors", action="store_true",
                    help="exit non-zero on warnings too")
    args = ap.parse_args(argv)
    picked = args.checkers or list(_CHECKERS)
    unknown = [c for c in picked if c not in _CHECKERS]
    if unknown:
        ap.error(f"unknown checker(s) {unknown}; choose from "
                 f"{list(_CHECKERS)}")
    rc = 0
    t_all = time.time()
    for name in picked:
        t0 = time.time()
        report = _load(name).run()
        print(report.format())
        print(f"[{name}] {time.time() - t0:.1f}s")
        if report.errors or (args.warnings_as_errors
                             and report.findings):
            rc = 1
    print(f"tdcheck: {len(picked)} checker(s) in "
          f"{time.time() - t_all:.1f}s -> "
          f"{'FAIL' if rc else 'OK'}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
