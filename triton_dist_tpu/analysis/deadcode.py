"""Dead-code / import lint (tdcheck satellite checker).

Pure-AST, zero-dependency lint over the package, tuned for this
repo's idioms (re-export blocks carry `# noqa: F401`; kernels import
lazily inside builders). Three precise checks — each one a class of
rot that a growing kernel library accumulates:

- **unused import**: an imported name never referenced in the module
  (and not re-exported via `# noqa` or __all__). Dead imports are not
  free here: most modules import jax eagerly, and the serving CLI's
  cold start pays every one.
- **unreachable code**: statements after an unconditional
  return/raise/break/continue in the same block — a refactor fossil
  that silently stops running (the "unreachable fallback branch"
  failure mode: the fallback still reads as if it protects the call
  site).
- **shadowed name**: a module-level def/class/assignment that rebinds
  an earlier import, or a duplicate top-level def/class — the first
  binding is dead code and the reader is looking at the wrong body.
"""

from __future__ import annotations

import ast
import os
from typing import Iterable, Optional

from triton_dist_tpu.analysis import Report


def _noqa_lines(src: str) -> set:
    return {i + 1 for i, line in enumerate(src.splitlines())
            if "# noqa" in line}


def _imported_names(node):
    """(local_name, lineno) pairs bound by an import statement."""
    if getattr(node, "module", None) == "__future__":
        return
    for alias in node.names:
        if alias.name == "*":
            continue
        local = alias.asname or alias.name.split(".")[0]
        yield local, node.lineno


class _Usage(ast.NodeVisitor):
    def __init__(self):
        self.loads = set()
        self.string_refs = set()

    def visit_Name(self, node):
        if isinstance(node.ctx, (ast.Load, ast.Del)):
            self.loads.add(node.id)
        self.generic_visit(node)

    def visit_Attribute(self, node):
        self.generic_visit(node)

    def visit_Constant(self, node):
        # __all__ entries / getattr strings count as usage
        if isinstance(node.value, str) and node.value.isidentifier():
            self.string_refs.add(node.value)


_TERMINAL = (ast.Return, ast.Raise, ast.Break, ast.Continue)


def _walk_blocks(node):
    """Yield every statement list in the tree (bodies of modules,
    functions, ifs, loops, withs, trys)."""
    for child in ast.walk(node):
        for field in ("body", "orelse", "finalbody"):
            block = getattr(child, field, None)
            if isinstance(block, list) and block and \
                    isinstance(block[0], ast.stmt):
                yield block
        for handler in getattr(child, "handlers", []) or []:
            yield handler.body


def check_source(src: str, path: str,
                 report: Optional[Report] = None) -> Report:
    if report is None:
        report = Report("deadcode")
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        report.add("error", f"{path}:{e.lineno}", os.path.basename(path),
                   f"syntax error: {e.msg}")
        return report
    noqa = _noqa_lines(src)
    mod = os.path.basename(path)

    usage = _Usage()
    usage.visit(tree)
    used = usage.loads | usage.string_refs

    # --- unused imports + import shadowing (module level) -------------
    imports = {}          # name -> lineno
    for node in tree.body:
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            if node.lineno in noqa:
                continue
            for name, lineno in _imported_names(node):
                imports[name] = lineno
    for name, lineno in sorted(imports.items(), key=lambda kv: kv[1]):
        if name not in used and name != "_":
            report.add(
                "warning", f"{path}:{lineno}", mod,
                f"unused import '{name}' (re-exports want "
                f"'# noqa: F401' on the import line)")

    # --- shadowed / duplicate top-level bindings ----------------------
    defs = {}
    for node in tree.body:
        names = []
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            names = [(node.name, node.lineno)]
        elif isinstance(node, ast.Assign):
            names = [(t.id, node.lineno) for t in node.targets
                     if isinstance(t, ast.Name)]
        for name, lineno in names:
            if lineno in noqa:
                continue
            if name in imports and imports[name] < lineno:
                report.add(
                    "warning", f"{path}:{lineno}", mod,
                    f"'{name}' shadows the import at line "
                    f"{imports[name]} — the import is dead")
            elif name in defs:
                report.add(
                    "warning", f"{path}:{lineno}", mod,
                    f"duplicate top-level definition of '{name}' "
                    f"(first at line {defs[name]}): the first body is "
                    f"dead code")
            defs[name] = lineno

    # --- unreachable statements ---------------------------------------
    for block in _walk_blocks(tree):
        for i, stmt in enumerate(block[:-1]):
            if isinstance(stmt, _TERMINAL):
                nxt = block[i + 1]
                if nxt.lineno in noqa:
                    break
                report.add(
                    "warning", f"{path}:{nxt.lineno}", mod,
                    f"unreachable code after "
                    f"{type(stmt).__name__.lower()} at line "
                    f"{stmt.lineno}")
                break
    report.covered.append(path)
    return report


def check_tree(root: str, report: Optional[Report] = None,
               exclude: Iterable[str] = ("__pycache__",)) -> Report:
    if report is None:
        report = Report("deadcode")
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d not in exclude]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path, "r") as f:
                check_source(f.read(), path, report)
    return report


def run(report: Optional[Report] = None) -> Report:
    import triton_dist_tpu
    root = os.path.dirname(os.path.abspath(triton_dist_tpu.__file__))
    return check_tree(root, report)
