"""Paged-KV race detector (tdcheck checker 2).

The paged serving stack's correctness rests on WRITE EXCLUSIVITY: in
one tick, no two (slot, kv-head) streams may write the same physical
page (kernels/paged_kv.py append_slots, mega/decode_layer.py's fused
table walk), and no stream may write a page whose refcount exceeds 1 —
a shared page is radix-tree prefix KV, writable only through the CoW
boundary-copy path (models/prefix_cache.py). A violation corrupts a
DIFFERENT request's stream, which the bitwise suites only catch after
the fact. Three complementary proofs:

1. **state check** (`check_state` / `check_scheduler`): over the live
   host-side state — page table, per-slot positions, pool refcounts —
   prove the CURRENT tick's write targets are pairwise distinct and
   unshared. Pure numpy on host mirrors; run it between polls or in a
   chaos soak.
2. **symbolic jaxpr check** (`check_tick_jaxpr`): over the traced
   decode-tick program, prove every write into a pool buffer derives
   its scatter indices from the page TABLE input (taint analysis) —
   a kernel that writes pool rows at indices not resolved through the
   table (the bug class the table indirection exists to prevent) is
   rejected at trace time, covering the XLA scatter appends AND the
   megakernel's scalar-prefetch table walk alike.
3. **shadow-page dynamic mode** (`snapshot_pool` / `check_shadow`):
   under interpret, snapshot the pool's bytes around ONE real tick and
   prove the changed-page set is contained in the expected write set
   (active slots' current pages + the trash sink). Catches what
   symbols cannot: a kernel whose index MATH is wrong.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from triton_dist_tpu.analysis import Report, eqn_src

_HERE = "triton_dist_tpu/analysis/races.py"


# ---------------------------------------------------------------------------
# 1. host-state write-exclusivity proof
# ---------------------------------------------------------------------------

def page_write_targets(table: np.ndarray, pos: np.ndarray, page: int,
                       n_kv_heads: int) -> np.ndarray:
    """Physical page each (slot, kv-head) stream writes at its current
    position: [B, Hkv] int32 (the exact resolution append_slots and the
    mega table walk perform: table[slot*Hkv+h, pos//page])."""
    B = pos.shape[0]
    maxp = table.shape[1]
    tile = np.minimum(np.asarray(pos, np.int64) // page, maxp - 1)
    streams = np.arange(B * n_kv_heads).reshape(B, n_kv_heads)
    return table[streams, tile[:, None]]


def check_state(table, pos, active, page: int, n_kv_heads: int, *,
                trash: int, refcount=None, shared=None,
                subject: str = "paged-state",
                report: Optional[Report] = None) -> Report:
    """Write-exclusivity + CoW discipline over one host-side snapshot.

    Four rules:
    - no two (slot, head) streams write one physical page this tick;
    - no slot writes a page that lies inside ANOTHER slot's mapped
      valid extent (tiles 0..pos//page) — that reader would see the
      writer's bytes, which is exactly what admission's boundary-page
      copy-on-write exists to prevent. NOTE a refcount of 2 alone is
      NOT a violation: a slot legitimately tail-extends the last page
      of a prefix the radix TREE also holds (readers are capped at
      the tree extent; only a deeper match boundary-copies).
    - with `refcount` (prefix_cache.RefcountedPages.refcount): a
      non-trash write target at refcount 0 is a freed page — the
      allocator may re-issue it mid-write.
    - with `shared` (the page set mapped by TWO OR MORE live slots —
      the KV-fork sharing set, models/structured.py): n slots holding
      those pages READ-ONLY is legal (that sharing is the point of
      fork), but any write target inside the set is a fork CoW
      violation — fork must boundary-copy before a fork's appends can
      land, exactly like admission's prefix-cache CoW.
    """
    if report is None:
        report = Report("races")
    table = np.asarray(table)
    pos = np.asarray(pos)
    active = np.asarray(active, bool)
    wp = page_write_targets(table, pos, page, n_kv_heads)
    maxp = table.shape[1]
    # per-slot mapped valid extent: the pages tiles 0..pos//page map
    extent: Dict[int, set] = {}
    for b in range(pos.shape[0]):
        if not active[b]:
            continue
        last = min(int(pos[b]) // page, maxp - 1)
        extent[b] = {int(p)
                     for h in range(n_kv_heads)
                     for p in table[b * n_kv_heads + h, :last + 1]}
    owner: Dict[int, tuple] = {}
    for b in range(pos.shape[0]):
        if not active[b]:
            continue
        for h in range(n_kv_heads):
            p = int(wp[b, h])
            if p == trash:
                continue
            if p in owner:
                ob, oh = owner[p]
                report.add(
                    "error", _HERE + ":check_state", subject,
                    f"write race: slot {b} head {h} (pos {int(pos[b])})"
                    f" and slot {ob} head {oh} (pos {int(pos[ob])}) "
                    f"both write physical page {p} this tick — one "
                    f"stream's KV will corrupt the other's")
            else:
                owner[p] = (b, h)
            for ob, pages in extent.items():
                if ob != b and p in pages:
                    report.add(
                        "error", _HERE + ":check_state", subject,
                        f"CoW violation: slot {b} head {h} writes page "
                        f"{p} which slot {ob}'s table maps inside its "
                        f"valid extent (pos {int(pos[ob])}) — the "
                        f"reader sees the writer's bytes; admission "
                        f"must boundary-copy before mapping a shared "
                        f"page writable")
            if refcount is not None and refcount(p) == 0:
                report.add(
                    "error", _HERE + ":check_state", subject,
                    f"write to freed page: slot {b} head {h} writes "
                    f"page {p} at refcount 0 — the allocator may "
                    f"re-issue it to another slot mid-write")
            if shared is not None and p in shared:
                report.add(
                    "error", _HERE + ":check_state", subject,
                    f"fork CoW violation: slot {b} head {h} writes "
                    f"page {p} which two or more live slots map "
                    f"(fork-shared prefix KV) — a fork's appends must "
                    f"land on a boundary-copied page, never the "
                    f"shared original (every sibling reads it)")
    report.covered.append(subject)
    return report


def check_scheduler(sched, report: Optional[Report] = None) -> Report:
    """check_state over a live PagedDecodeSlots/ContinuousScheduler
    (device table+pos are tiny: one coalesced device_get). Fork-aware:
    the pages mapped by two or more live slots' host group mirrors
    form the `shared` set — KV-fork siblings reading them is legal,
    any write target among them fires. Also re-proves the pool
    conservation invariant as a finding instead of an assert."""
    import jax
    if report is None:
        report = Report("races")
    slots = getattr(sched, "slots", sched)   # ContinuousScheduler wraps
    table, pos, active = jax.device_get(
        (slots.cache.table, slots.pos, slots.active))
    pool = slots.prefix.pool
    # fork sharing set: a page counted once per live slot that maps it
    holders: Dict[int, int] = {}
    for b, groups in enumerate(getattr(slots, "_groups", ())):
        if b < len(active) and active[b]:
            for p in {int(p) for g in groups for p in g}:
                holders[p] = holders.get(p, 0) + 1
    shared = {p for p, c in holders.items() if c >= 2}
    check_state(table, pos, active, slots.page,
                slots.engine.model.config.num_kv_heads,
                trash=slots.cache.trash, refcount=pool.refcount,
                shared=shared, subject=type(slots).__name__,
                report=report)
    if pool.available + pool.outstanding != pool.num_pages:
        report.add(
            "error", _HERE + ":check_scheduler", type(slots).__name__,
            f"pool conservation violated: {pool.available} free + "
            f"{pool.outstanding} outstanding != {pool.num_pages} total "
            f"(a page leaked or was double-mapped)")
    return report


# ---------------------------------------------------------------------------
# 2. symbolic jaxpr proof: pool writes derive their indices from the table
# ---------------------------------------------------------------------------

_SCATTER_PRIMS = ("scatter", "scatter-add", "scatter-mul", "scatter-min",
                  "scatter-max", "dynamic_update_slice")
# buffer identity survives these (the result IS the pool buffer,
# updated); anything else (dot, gather, reduce) produces derived data
_BUF_CARRY_PRIMS = _SCATTER_PRIMS + ("convert_element_type", "copy",
                                     "select_n", "transpose", "reshape")


def _subjaxprs_with_mapping(eqn):
    """(closed_jaxpr, invar_map) pairs for call-like eqns: invar_map[i]
    = index into eqn.invars feeding body invar i (None = no direct
    operand, e.g. scan's per-step slice keeps the same position)."""
    import jax.core as jc
    prim = eqn.primitive.name
    out = []
    if prim in ("pjit", "closed_call", "core_call", "xla_call",
                "remat", "checkpoint", "custom_jvp_call",
                "custom_vjp_call", "custom_vjp_call_jaxpr"):
        jx = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
        if jx is not None:
            body = jx.jaxpr if isinstance(jx, jc.ClosedJaxpr) else jx
            out.append((body, list(range(len(eqn.invars)))))
    elif prim == "scan":
        body = eqn.params["jaxpr"].jaxpr
        out.append((body, list(range(len(eqn.invars)))))
    elif prim == "while":
        for k in ("cond_jaxpr", "body_jaxpr"):
            body = eqn.params[k].jaxpr
            out.append((body, list(range(len(eqn.invars)))))
    elif prim == "cond":
        for br in eqn.params["branches"]:
            # invars[0] is the predicate; branches see invars[1:]
            out.append((br.jaxpr, [i + 1 for i in
                                   range(len(eqn.invars) - 1)]))
    elif prim == "shard_map":
        body = eqn.params["jaxpr"]
        body = body.jaxpr if isinstance(body, jc.ClosedJaxpr) else body
        out.append((body, list(range(len(eqn.invars)))))
    return out


def _taint_jaxpr(jaxpr, table_in: set, buf_in: set, findings: list,
                 subject: str, depth: int = 0):
    """One pass over `jaxpr`: table_in/buf_in are sets of invar
    INDICES tainted on entry. Returns (table_out, buf_out) outvar index
    sets. Appends (src, message) findings for table-bypassing pool
    writes."""
    from jax.core import Literal
    table_t = {jaxpr.invars[i] for i in table_in if i < len(jaxpr.invars)}
    buf_t = {jaxpr.invars[i] for i in buf_in if i < len(jaxpr.invars)}

    def tt(v):
        return not isinstance(v, Literal) and v in table_t

    def bt(v):
        return not isinstance(v, Literal) and v in buf_t

    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        subs = _subjaxprs_with_mapping(eqn)
        if subs:
            n_out_t, n_out_b = set(), set()
            for body, imap in subs:
                t_in = {bi for bi, oi in enumerate(imap)
                        if oi is not None and oi < len(eqn.invars)
                        and tt(eqn.invars[oi])}
                b_in = {bi for bi, oi in enumerate(imap)
                        if oi is not None and oi < len(eqn.invars)
                        and bt(eqn.invars[oi])}
                # scan/while bodies have extra leading invars on
                # mismatch; clamp handled inside by index bound check
                ot, ob = _taint_jaxpr(body, t_in, b_in, findings,
                                      subject, depth + 1)
                n_out_t |= ot
                n_out_b |= ob
            for i, v in enumerate(eqn.outvars):
                if i in n_out_t or (n_out_t and prim in
                                    ("while", "cond")):
                    table_t.add(v)
                if i in n_out_b:
                    buf_t.add(v)
            # conservative: any tainted input to an opaque call taints
            # table-taint of all outputs (over-taint never FAILS a
            # clean program; it only widens what counts as
            # table-derived)
            if any(tt(v) for v in eqn.invars):
                table_t.update(eqn.outvars)
            continue
        if prim == "pallas_call":
            aliased = {i for i, _ in
                       (eqn.params.get("input_output_aliases") or ())}
            gm = eqn.params.get("grid_mapping")
            n_idx = gm.num_index_operands if gm is not None else 0
            for i, v in enumerate(eqn.invars):
                if not bt(v):
                    continue
                if i in aliased:
                    # in-place pool update inside a kernel (the mega
                    # table walk): its write offsets ride the scalar-
                    # prefetch operand, which must be table-derived
                    if n_idx and not any(tt(eqn.invars[j])
                                         for j in range(n_idx)):
                        findings.append((
                            eqn_src(eqn),
                            "pallas kernel updates a pool buffer "
                            "in-place but its scalar-prefetch operand "
                            "does not derive from the page table: the "
                            "in-kernel write offsets bypass the table "
                            "(write-exclusivity unprovable)"))
                # read-only pool operand: fine
            # outputs aliased from tainted inputs keep buffer identity
            for i, o in (eqn.params.get("input_output_aliases") or ()):
                if i < len(eqn.invars) and bt(eqn.invars[i]):
                    if o < len(eqn.outvars):
                        buf_t.add(eqn.outvars[o])
            if any(tt(v) for v in eqn.invars):
                table_t.update(eqn.outvars)
            continue
        if prim in _SCATTER_PRIMS and bt(eqn.invars[0]):
            idx_ops = eqn.invars[1:2] if prim.startswith("scatter") \
                else eqn.invars[2:]
            # scatter: (operand, indices, updates); DUS: (operand,
            # update, *start_indices)
            if prim == "dynamic_update_slice":
                idx_ops = eqn.invars[2:]
            if not any(tt(v) or isinstance(v, Literal)
                       for v in idx_ops):
                findings.append((
                    eqn_src(eqn),
                    f"pool write bypasses the page table: {prim} into "
                    f"a pool buffer with indices not derived from the "
                    f"table input — write exclusivity cannot be "
                    f"guaranteed for this update"))
        # ordinary taint propagation
        if any(tt(v) for v in eqn.invars):
            table_t.update(eqn.outvars)
        if prim in _BUF_CARRY_PRIMS and bt(eqn.invars[0]):
            buf_t.add(eqn.outvars[0])

    out_t = {i for i, v in enumerate(jaxpr.outvars)
             if not isinstance(v, Literal) and v in table_t}
    out_b = {i for i, v in enumerate(jaxpr.outvars)
             if not isinstance(v, Literal) and v in buf_t}
    return out_t, out_b


def check_tick_jaxpr(fn, args, pcache, subject: str,
                     report: Optional[Report] = None) -> Report:
    """Symbolic write-exclusivity proof over one traced tick program.

    fn(*args) must take the paged cache somewhere in `args` (the SAME
    pcache object, for leaf identification by object identity)."""
    import jax
    if report is None:
        report = Report("races")
    jaxpr = jax.make_jaxpr(fn)(*args)
    flat, _ = jax.tree_util.tree_flatten(args)
    pool_ids = {id(x) for x in
                list(pcache.pages_k) + list(pcache.pages_v)
                + list(getattr(pcache, "scales_k", ()) or ())
                + list(getattr(pcache, "scales_v", ()) or ())}
    table_idx = {i for i, x in enumerate(flat)
                 if x is pcache.table}
    buf_idx = {i for i, x in enumerate(flat) if id(x) in pool_ids}
    if not table_idx or not buf_idx:
        report.add("error", _HERE + ":check_tick_jaxpr", subject,
                   "could not locate the page table / pool buffers in "
                   "the program's flattened arguments (pass the same "
                   "pcache object the program was built with)")
        return report
    findings: list = []
    _taint_jaxpr(jaxpr.jaxpr, table_idx, buf_idx, findings, subject)
    for src, msg in findings:
        report.add("error", src, subject, msg)
    report.covered.append(subject)
    return report


def check_engine_tick(engine, batch: int = 2,
                      report: Optional[Report] = None) -> Report:
    """check_tick_jaxpr over the engine's canonical paged decode tick
    (the program PagedDecodeSlots drives every poll) — and the mega
    fused tick when the engine serves backend='mega'."""
    import jax.numpy as jnp
    from triton_dist_tpu.models import engine as eng_mod
    if report is None:
        report = Report("races")
    model = engine.model
    pcache = engine.make_paged_slot_cache(batch)
    V = model.config.vocab_size
    logits0 = jnp.zeros((batch, V), jnp.float32)
    pos = jnp.zeros((batch,), jnp.int32)
    active = jnp.ones((batch,), bool)

    def tick(model, logits0, pcache, pos, active):
        return eng_mod._paged_slot_scan_decode_fn(
            "flash" if engine.backend == "mega" else engine.backend,
            model, logits0, pcache, pos, active, gen_len=2)

    check_tick_jaxpr(tick, (model, logits0, pcache, pos, active),
                     pcache, f"paged_slot_scan[{engine.backend}]",
                     report)
    if engine.backend == "mega":
        def mega_tick(model, logits0, pcache, pos, active):
            return eng_mod._paged_slot_mega_scan_fn(
                model, logits0, pcache, pos, active, gen_len=2)
        check_tick_jaxpr(mega_tick,
                         (model, logits0, pcache, pos, active),
                         pcache, "paged_slot_mega", report)
    return report


# ---------------------------------------------------------------------------
# 3. shadow-page dynamic mode (interpret substrate)
# ---------------------------------------------------------------------------

def snapshot_pool(pcache) -> List[np.ndarray]:
    """Host snapshot of every layer's K/V (and scale) pool planes."""
    import jax
    bufs = list(pcache.pages_k) + list(pcache.pages_v) \
        + list(getattr(pcache, "scales_k", ()) or ()) \
        + list(getattr(pcache, "scales_v", ()) or ())
    return [np.asarray(x) for x in jax.device_get(bufs)]


def changed_pages(before: Sequence[np.ndarray],
                  after: Sequence[np.ndarray]) -> set:
    """Page ids whose bytes differ in ANY plane between snapshots."""
    out = set()
    for b, a in zip(before, after):
        if b.shape != a.shape:
            raise ValueError(f"snapshot shapes diverged: {b.shape} vs "
                             f"{a.shape}")
        diff = (b != a).reshape(b.shape[0], -1).any(axis=1)
        out.update(int(i) for i in np.nonzero(diff)[0])
    return out


def check_shadow(before, after, expected: set, *, trash: int,
                 subject: str = "shadow-tick",
                 report: Optional[Report] = None) -> Report:
    """Containment proof: pages changed by the tick ⊆ expected write
    set + trash. A page outside the set means some stream's write
    landed on KV it does not own — the dynamic form of the write race
    the state check proves symbolically."""
    if report is None:
        report = Report("races")
    stray = changed_pages(before, after) - set(expected) - {trash}
    for p in sorted(stray):
        report.add(
            "error", _HERE + ":check_shadow", subject,
            f"shadow-page violation: physical page {p} changed during "
            f"the tick but is not in the expected write set "
            f"(sorted head: {sorted(expected)[:8]}) — a stream wrote "
            f"KV it does not own")
    report.covered.append(subject)
    return report


def expected_write_pages(sched, steps: int) -> set:
    """The pages a `steps`-token decode chunk may legitimately write:
    each active slot's pages covering [pos, pos+steps), resolved
    through the live table (plus the trash sink, which check_shadow
    always allows)."""
    import jax
    slots = getattr(sched, "slots", sched)
    table, pos, active = jax.device_get(
        (slots.cache.table, slots.pos, slots.active))
    table = np.asarray(table)
    Hkv = slots.engine.model.config.num_kv_heads
    maxp = table.shape[1]
    out = set()
    for b in range(len(pos)):
        if not active[b]:
            continue
        for k in range(steps):
            tile = min((int(pos[b]) + k) // slots.page, maxp - 1)
            for h in range(Hkv):
                out.add(int(table[b * Hkv + h, tile]))
    return out


def run(report: Optional[Report] = None) -> Report:
    """CLI entry: symbolic jaxpr proof over the canonical tiny engine's
    paged decode tick (the state/shadow modes need live scheduler
    state and run from the test suite / operator tooling)."""
    import jax
    from triton_dist_tpu.models import AutoLLM, Engine
    from triton_dist_tpu.models.config import tiny_qwen3
    if report is None:
        report = Report("races")
    mesh = jax.make_mesh((1,), ("tp",), devices=jax.devices()[:1])
    cfg = tiny_qwen3(1)
    model = AutoLLM.from_config(cfg, mesh)
    engine = Engine(model, max_seq=64, backend="flash")
    check_engine_tick(engine, report=report)
    return report
