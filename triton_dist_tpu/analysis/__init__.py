"""`tdcheck` — static analysis for the Pallas kernels and the serving
hot loop (ISSUE 15).

The reference Triton-distributed system's correctness rests on
hand-maintained protocols (one-sided signal set/wait pairing,
symmetric-buffer aliasing, barrier placement — SURVEY.md §2.3); this
TPU rebuild grew the same classes of invariant: paged-table write
exclusivity and CoW-on-refcount>1 discipline (models/prefix_cache.py),
per-shard page-id partitioning (kernels/paged_kv.PageAllocator),
zero-host-transfer poll loops (models/scheduler.py). The bitwise
differential suites catch a violation AFTER it corrupts a stream;
tdcheck makes the invariants statically checkable over every
registered kernel (kernels.kernel_registry) and every jitted slot
program (models.engine._jit_programs), BEFORE a tick runs.

Checkers (one module each):

- contracts  : walks the jaxpr of every registered kernel, extracts
               each pallas_call's grid/BlockSpecs/dtypes, estimates the
               per-grid-step VMEM footprint, flags over-budget kernels,
               non-divisible block shapes, and missing
               input_output_aliases on registered in-place kernels.
- races      : proves paged-KV write exclusivity — symbolically on the
               tick jaxpr (every pool write's indices must derive from
               the page table; pool operands of a pallas_call must not
               alias outputs undeclared) and on live scheduler state
               (no two slots write one physical page; no write to a
               refcount>1 page outside the CoW boundary), plus a
               shadow-page dynamic mode diffing pool bytes around a
               real tick under interpret.
- protocol   : builds the per-device signal graph of the one-sided
               kernels from dl.comm_trace() events and rejects
               unmatched set/wait pairs, wait-before-set orderings and
               barrier-elision hazards.
- hotloop    : hashes the jaxprs of the engine's _jit_programs set
               (double-trace determinism = no recompile-key churn
               between polls; lru identity = one program set
               process-wide) and fails on any host transfer
               (callback/infeed/outfeed) inside a decode-tick program.
- deadcode   : AST lint over the package — unused imports, unreachable
               fallback branches, shadowed names.

CLI: ``python -m triton_dist_tpu.analysis [checkers...]`` — exits
non-zero on any error finding; ``tools/tdcheck.sh`` is the CI smoke.
Every diagnostic carries a file:line. To ADD a checker: emit
`Finding`s, return a `Report`, register the runner in __main__.py
(ROADMAP standing note).
"""

from __future__ import annotations

import dataclasses
from typing import List


@dataclasses.dataclass
class Finding:
    """One diagnostic: which checker fired, where (file:line), on what
    (kernel/program/module name), and why."""

    checker: str
    severity: str            # "error" | "warning"
    where: str               # file:line (best effort, never empty)
    subject: str             # kernel / program / module name
    message: str

    def format(self) -> str:
        return (f"[{self.checker}] {self.severity.upper()} "
                f"{self.subject} @ {self.where}: {self.message}")


@dataclasses.dataclass
class Report:
    """A checker run's findings + the subjects it actually covered
    (coverage is part of the contract: an empty report over zero
    kernels is a broken scan, not a clean tree)."""

    checker: str
    findings: List[Finding] = dataclasses.field(default_factory=list)
    covered: List[str] = dataclasses.field(default_factory=list)

    def add(self, severity: str, where: str, subject: str,
            message: str) -> None:
        self.findings.append(Finding(self.checker, severity, where,
                                     subject, message))

    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == "error"]

    def merge(self, other: "Report") -> "Report":
        self.findings.extend(other.findings)
        self.covered.extend(other.covered)
        return self

    def format(self) -> str:
        lines = [f.format() for f in self.findings]
        lines.append(f"[{self.checker}] covered {len(self.covered)} "
                     f"subject(s), {len(self.errors)} error(s), "
                     f"{len(self.findings) - len(self.errors)} "
                     f"warning(s)")
        return "\n".join(lines)


def iter_jaxprs(jaxpr):
    """Yield every (sub)jaxpr reachable from `jaxpr` (pjit/scan/while/
    cond/shard_map/custom_* bodies), outermost first."""
    import jax.core as jc
    seen = set()
    stack = [jaxpr]
    while stack:
        jx = stack.pop()
        if id(jx) in seen:
            continue
        seen.add(id(jx))
        yield jx
        for eqn in jx.eqns:
            for v in eqn.params.values():
                vs = v if isinstance(v, (list, tuple)) else [v]
                for vv in vs:
                    if isinstance(vv, jc.ClosedJaxpr):
                        stack.append(vv.jaxpr)
                    elif isinstance(vv, jc.Jaxpr):
                        stack.append(vv)


def iter_eqns(jaxpr, primitive: str = None):
    """Yield every eqn in the nested jaxpr, optionally filtered by
    primitive name. pallas_call kernel bodies are descended too."""
    for jx in iter_jaxprs(jaxpr):
        for eqn in jx.eqns:
            if primitive is None or eqn.primitive.name == primitive:
                yield eqn


def eqn_src(eqn) -> str:
    """Best-effort file:line of an eqn (the user frame of its source
    info; pallas_call eqns prefer their kernel's src note)."""
    nsi = eqn.params.get("name_and_src_info")
    if nsi is not None and getattr(nsi, "src_info", ""):
        # "at /path/file.py:123" -> "/path/file.py:123"
        s = str(nsi.src_info)
        return s[3:] if s.startswith("at ") else s
    try:
        from jax._src import source_info_util as siu
        fr = siu.user_frame(eqn.source_info)
        if fr is not None:
            return f"{fr.file_name}:{fr.start_line}"
    except Exception:
        pass
    return "<unknown>"
