"""Socket serving: a continuously-batched streaming token server +
client over the Engine.

TPU re-design of the reference's serving pair
(`mega_triton_kernel/test/models/model_server.py:265` — a TCP server
that tokenizes prompts, prefills, and streams sampled tokens — and the
interactive `chat.py:207` client). Protocol is line-delimited JSON over
TCP:

  client -> {"prompt": str, "gen_len": int, "seed": int}\n
  server -> {"text": str, "token_ids": [...]}\n        per decode chunk
            {"done": true, "n_tokens": int}\n          terminator

Tokens stream INCREMENTALLY: the decode runs in chunks of `chunk`
steps (each chunk one jitted scan), so clients render text while the
model is still generating. The server is MULTI-CLIENT (continuous
batching, models/scheduler.py): up to `batch` concurrent requests
decode in distinct slots of one slot scan — distinct prompts, per-slot
positions and PRNG chains — and a finished client's slot is refilled
from the accept queue between chunks while the other streams keep
flowing. Chunked decode is token-exact vs Engine.serve() in BOTH
sampling modes (greedy: same argmax chain; sampled: the scan's evolved
key chains across chunks).

paged=True additionally serves over the paged KV pool with the
SHARED-PREFIX radix cache (models/prefix_cache.py): prompts sharing a
system-prompt/few-shot prefix reuse its cached KV pages and skip that
prefill work — token streams stay bitwise identical to prefix_cache=
False. The final {"done": ...} message then reports a "cache" dict
(hit rate, prefill tokens skipped). Clients that hang up mid-stream
are detected (EOF probe or failed write) and their slot is CANCELLED —
pages freed and the partial sequence inserted into the prefix tree —
instead of decoding to gen_len for nobody.
"""

from __future__ import annotations

import json
import socket
import threading
from typing import Iterator, Optional

import numpy as np


class ByteTokenizer:
    """Toy byte-level tokenizer capped to a vocab (examples/07's demo
    tokenizer, importable for the serving tests)."""

    def __init__(self, vocab_size: int):
        self.vocab_size = vocab_size

    def encode(self, text: str):
        return [b % self.vocab_size for b in text.encode()]

    def decode(self, ids):
        return bytes(int(i) % 256 for i in ids).decode("latin-1")


def decode_stream(engine, logits, cache, gen_len: int, *, chunk: int = 4,
                  seed: int = 0):
    """Yield token chunks [B, <=chunk] as they are generated: each chunk
    is one jitted decode scan, with (logits, cache) carried between
    chunks (the cache is donated into each scan, so memory stays flat).
    Chunking is exact in BOTH modes: greedy because the argmax chain is
    identical to one gen_len-long scan, and sampled because the scan
    returns its evolved PRNG key and the next chunk resumes the chain —
    the sampled stream equals Engine.serve() at the same seed for every
    chunk size (it used to re-split a fresh key per chunk and diverge)."""
    import jax
    if engine.backend == "mega":
        raise ValueError("mega decode carries no resumable logits; "
                         "stream with the per-op backends")
    key = jax.random.key(seed)
    done = 0
    while done < gen_len:
        g = min(chunk, gen_len - done)
        if engine.sampling == "greedy":
            toks, logits, cache = engine._decode_scan(
                engine.model, logits, cache, gen_len=g)
        else:
            toks, logits, cache, key = engine._decode_scan(
                engine.model, logits, cache, key, gen_len=g)
        yield np.asarray(toks)
        done += g


class TokenServer:
    """Accept prompts, stream decode chunks back (reference:
    model_server.py's request loop), now CONTINUOUSLY BATCHED: up to
    `batch` clients decode concurrently, each in its own slot of the
    scheduler (models/scheduler.py) — distinct requests, distinct KV
    rows, one jitted slot scan per chunk. A freed slot is refilled
    from the connection queue between chunks while the other clients'
    streams keep flowing. Still single-threaded ON THE MODEL: socket
    threads only parse requests and write replies; every jax dispatch
    happens on the serve_forever thread (concurrency is batching, not
    model threads — the discipline the old one-request loop had, kept)."""

    def __init__(self, engine, tokenizer, *, batch: int,
                 host: str = "127.0.0.1", port: int = 0,
                 chunk: int = 4, paged: bool = False,
                 prefix_cache: bool = True, page: int = 16,
                 num_pages: Optional[int] = None, spec: int = 0,
                 drafter=None):
        """paged=True serves over the paged KV pool with the
        shared-prefix radix cache (models/prefix_cache.py): concurrent
        prompts sharing a system-prompt/few-shot prefix reuse its
        cached KV pages and skip that prefill; the final {"done": ...}
        message then carries a "cache" dict (hit rate, prefill tokens
        skipped) and stats() exposes the running counters.

        spec=K > 0 turns each decode step into a speculative
        draft-then-verify iteration (models/spec_decode.py, n-gram
        prompt-lookup drafting by default): every slot streams 1..K+1
        tokens per model forward, token-for-token identical to spec=0
        under greedy sampling. stats() then also reports
        spec_accept_rate and tokens_per_step."""
        from triton_dist_tpu.models.scheduler import ContinuousScheduler
        self.engine = engine
        self.tok = tokenizer
        self.batch = batch
        self.chunk = chunk
        self.paged = paged
        self.sched = ContinuousScheduler(
            engine, batch=batch, chunk=chunk, paged=paged,
            prefix_cache=prefix_cache, page=page, num_pages=num_pages,
            spec=spec, drafter=drafter)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(max(4, batch))
        self.host, self.port = self._sock.getsockname()
        self._stop = threading.Event()
        self._next_rid = 0
        self._conns: dict = {}          # rid -> _ClientStream
        self._lock = threading.Lock()   # guards scheduler submit + _conns

    class _ClientStream:
        """Per-connection state: the socket + reply file handle + token
        count. Owned by the model loop after admission; the reader
        thread only hands it over."""

        def __init__(self, conn, fh):
            self.conn = conn
            self.fh = fh
            self.n = 0
            self.dead = False

    def _reader(self, conn: socket.socket) -> None:
        """Connection thread: parse ONE request line, enqueue it for
        the model loop, leave the socket open for streaming replies."""
        import sys
        from triton_dist_tpu.models.scheduler import Request
        try:
            conn.settimeout(60.0)   # a silent client cannot hold a slot
            f = conn.makefile("rw")
            line = f.readline()
            if not line.strip():
                conn.close()
                return
            req = json.loads(line)
            ids = self.tok.encode(req.get("prompt", "")) or [0]
            gen_len = int(req.get("gen_len", 16))
            # clamp to slot capacity (prompt + gen must fit the slot);
            # a prompt with no room for even one token is refused here
            # with a visible error instead of occupying a slot
            slot_cap = self.sched.slots.capacity
            cap = slot_cap - len(ids)
            if cap < 1:
                f.write(json.dumps({
                    "done": True, "n_tokens": 0,
                    "error": f"prompt of {len(ids)} tokens exceeds "
                             f"capacity {slot_cap - 1}"}) + "\n")
                f.flush()
                conn.close()
                return
            gen_len = max(1, min(gen_len, cap))
            seed = int(req.get("seed", 0))
            with self._lock:
                rid = self._next_rid
                self._next_rid += 1
                self._conns[rid] = self._ClientStream(conn, f)
                self.sched.submit(Request(
                    rid=rid, ids=np.asarray(ids, np.int32),
                    gen_len=gen_len, seed=seed))
        except (OSError, ValueError, KeyError) as e:
            print(f"[TokenServer] bad request: {type(e).__name__}: {e}",
                  file=sys.stderr)
            conn.close()

    def _emit(self, rid, toks) -> None:
        """Stream one chunk's tokens to the owning client; a dead
        socket marks the stream dead — the model loop then CANCELS its
        slot (sched.cancel) instead of decoding to gen_len with the
        tokens falling on the floor."""
        cs = self._conns.get(rid)
        if cs is None or cs.dead:
            return
        row = [int(t) for t in toks]
        try:
            cs.fh.write(json.dumps({"text": self.tok.decode(row),
                                    "token_ids": row}) + "\n")
            cs.fh.flush()           # the stream is the point
            cs.n += len(row)
        except OSError:
            cs.dead = True

    def _probe_disconnects(self) -> None:
        """Detect clients that hung up WITHOUT a failed write: after
        the request line a client never sends again, so a non-blocking
        recv returning b'' is EOF — mark the stream dead so the model
        loop cancels its slot this iteration."""
        for cs in list(self._conns.values()):
            if cs.dead:
                continue
            try:
                timeout = cs.conn.gettimeout()
            except OSError:
                cs.dead = True
                continue
            try:
                cs.conn.setblocking(False)
                if cs.conn.recv(1) == b"":
                    cs.dead = True
            except (BlockingIOError, InterruptedError):
                pass            # alive, nothing to read
            except OSError:
                cs.dead = True
            finally:
                try:
                    cs.conn.settimeout(timeout)   # keep the write timeout
                except OSError:
                    pass

    def stats(self) -> dict:
        """Serving counters: prefix-cache (hit rate, prefill tokens
        skipped — paged path) and speculative decoding
        (spec_accept_rate, tokens_per_step — spec=K mode); empty dict
        for the plain contiguous path."""
        with self._lock:
            return dict(self.sched.stats())

    def _finish(self, rid) -> None:
        cs = self._conns.pop(rid, None)
        if cs is None:
            return
        reason = self.sched.rejected.pop(rid, None)
        try:
            if not cs.dead:
                msg = {"done": True, "n_tokens": cs.n}
                if reason is not None:
                    # a scheduler-rejected request (pool exhausted,
                    # over capacity) must not look like a legitimate
                    # zero-token completion
                    msg["error"] = reason
                if self.paged:
                    st = self.sched.stats()
                    msg["cache"] = {
                        k: st[k] for k in ("hit_rate",
                                           "prefill_tokens_skipped",
                                           "prefill_skip_frac")}
                cs.fh.write(json.dumps(msg) + "\n")
                cs.fh.flush()
        except OSError:
            pass
        for closer in (cs.fh.close, cs.conn.close):
            try:
                closer()
            except OSError:
                pass

    def serve_forever(self, max_requests: Optional[int] = None) -> None:
        """Model loop: accept connections (handing each to a reader
        thread), then run the scheduler — admit, one chunk, stream each
        slot's tokens to its client. max_requests counts COMPLETED
        requests (so a test can serve N concurrent clients and exit)."""
        done_count = 0
        self._sock.settimeout(0.02)
        try:
            while not self._stop.is_set():
                # drain the accept queue without blocking the decode
                # loop (reader threads are daemonic and short-lived:
                # one request line each, no tracking needed)
                while True:
                    try:
                        conn, _ = self._sock.accept()
                    except socket.timeout:
                        break
                    threading.Thread(target=self._reader, args=(conn,),
                                     daemon=True).start()
                with self._lock:
                    out, finished = self.sched.poll()
                for rid, toks in out.items():
                    self._emit(rid, toks)
                for rid in finished:
                    self._finish(rid)
                    done_count += 1
                # cancel-on-disconnect: a hung-up client's slot retires
                # NOW (pages freed / inserted into the prefix tree)
                # instead of decoding to gen_len for nobody
                self._probe_disconnects()
                dead = [rid for rid, cs in list(self._conns.items())
                        if cs.dead]
                for rid in dead:
                    with self._lock:
                        self.sched.cancel(rid)
                    self._finish(rid)
                    done_count += 1
                if max_requests is not None and done_count >= max_requests:
                    break
                if self.sched.idle:
                    # nothing in flight: sleep on accept instead of
                    # spinning the poll loop
                    self._stop.wait(0.05)
        finally:
            self._sock.close()
            for rid in list(self._conns):
                self._finish(rid)

    def stop(self) -> None:
        self._stop.set()


def request_stream(host: str, port: int, prompt: str, *,
                   gen_len: int = 16, seed: int = 0,
                   timeout: float = 300.0) -> Iterator[dict]:
    """Client: send one prompt, yield the server's chunk messages as
    they arrive (the last one has {"done": true}). Reference: the
    chat.py client's receive loop."""
    with socket.create_connection((host, port), timeout=timeout) as s:
        with s.makefile("rw") as f:
            f.write(json.dumps({"prompt": prompt, "gen_len": gen_len,
                                "seed": seed}) + "\n")
            f.flush()
            for line in f:
                msg = json.loads(line)
                yield msg
                if msg.get("done"):
                    return
