"""Socket serving: a minimal streaming token server + client over the
Engine.

TPU re-design of the reference's serving pair
(`mega_triton_kernel/test/models/model_server.py:265` — a TCP server
that tokenizes prompts, prefills, and streams sampled tokens — and the
interactive `chat.py:207` client). Protocol is line-delimited JSON over
TCP:

  client -> {"prompt": str, "gen_len": int, "seed": int}\n
  server -> {"text": str, "token_ids": [...]}\n        per decode chunk
            {"done": true, "n_tokens": int}\n          terminator

Tokens stream INCREMENTALLY: the decode runs in chunks of `chunk`
steps (each chunk one jitted scan, carrying (logits, cache) across
chunks), so the client renders text while the model is still
generating — the reference's streaming UX without its per-token Python
loop. Greedy chunked decode is token-exact vs the single-scan path
(same argmax chain); sampled decode draws one fresh key per chunk.
"""

from __future__ import annotations

import json
import socket
import threading
from typing import Iterator, Optional

import numpy as np


class ByteTokenizer:
    """Toy byte-level tokenizer capped to a vocab (examples/07's demo
    tokenizer, importable for the serving tests)."""

    def __init__(self, vocab_size: int):
        self.vocab_size = vocab_size

    def encode(self, text: str):
        return [b % self.vocab_size for b in text.encode()]

    def decode(self, ids):
        return bytes(int(i) % 256 for i in ids).decode("latin-1")


def decode_stream(engine, logits, cache, gen_len: int, *, chunk: int = 4,
                  seed: int = 0):
    """Yield token chunks [B, <=chunk] as they are generated: each chunk
    is one jitted decode scan, with (logits, cache) carried between
    chunks (the cache is donated into each scan, so memory stays flat).
    Greedy chunking is exact — the argmax chain is identical to one
    gen_len-long scan."""
    import jax
    if engine.backend == "mega":
        raise ValueError("mega decode carries no resumable logits; "
                         "stream with the per-op backends")
    key = jax.random.key(seed)
    done = 0
    while done < gen_len:
        g = min(chunk, gen_len - done)
        if engine.sampling == "greedy":
            toks, logits, cache = engine._decode_scan(
                engine.model, logits, cache, gen_len=g)
        else:
            key, sub = jax.random.split(key)
            toks, logits, cache = engine._decode_scan(
                engine.model, logits, cache, sub, gen_len=g)
        yield np.asarray(toks)
        done += g


class TokenServer:
    """Accept prompts, prefill, stream decode chunks back (reference:
    model_server.py's request loop). One request at a time — the model
    owns the chip; concurrency is batching, not threads."""

    def __init__(self, engine, tokenizer, *, batch: int,
                 host: str = "127.0.0.1", port: int = 0,
                 chunk: int = 4):
        self.engine = engine
        self.tok = tokenizer
        self.batch = batch
        self.chunk = chunk
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(4)
        self.host, self.port = self._sock.getsockname()
        self._stop = threading.Event()

    def handle(self, conn: socket.socket) -> None:
        conn.settimeout(60.0)     # a silent client cannot pin the loop
        with conn, conn.makefile("rw") as f:
            line = f.readline()
            if not line.strip():
                return
            req = json.loads(line)
            ids = self.tok.encode(req.get("prompt", "")) or [0]
            gen_len = int(req.get("gen_len", 16))
            seed = int(req.get("seed", 0))
            x = np.tile(np.asarray(ids, np.int32)[None], (self.batch, 1))
            logits, cache = self.engine.prefill(x)
            n = 0
            for toks in decode_stream(self.engine, logits, cache,
                                      gen_len, chunk=self.chunk,
                                      seed=seed):
                row = [int(t) for t in toks[0]]
                f.write(json.dumps(
                    {"text": self.tok.decode(row),
                     "token_ids": row}) + "\n")
                f.flush()           # the stream is the point
                n += len(row)
            f.write(json.dumps({"done": True, "n_tokens": n}) + "\n")
            f.flush()

    def serve_forever(self, max_requests: Optional[int] = None) -> None:
        import sys
        served = 0
        self._sock.settimeout(0.5)
        try:
            while not self._stop.is_set():
                try:
                    conn, _ = self._sock.accept()
                except socket.timeout:
                    continue
                try:
                    self.handle(conn)
                except (OSError, ValueError, KeyError) as e:
                    # malformed request / client gone mid-stream: log,
                    # keep serving (the reference server's loop survives
                    # bad clients too)
                    print(f"[TokenServer] request failed: "
                          f"{type(e).__name__}: {e}", file=sys.stderr)
                served += 1
                if max_requests is not None and served >= max_requests:
                    break
        finally:
            self._sock.close()

    def stop(self) -> None:
        self._stop.set()


def request_stream(host: str, port: int, prompt: str, *,
                   gen_len: int = 16, seed: int = 0,
                   timeout: float = 300.0) -> Iterator[dict]:
    """Client: send one prompt, yield the server's chunk messages as
    they arrive (the last one has {"done": true}). Reference: the
    chat.py client's receive loop."""
    with socket.create_connection((host, port), timeout=timeout) as s:
        with s.makefile("rw") as f:
            f.write(json.dumps({"prompt": prompt, "gen_len": gen_len,
                                "seed": seed}) + "\n")
            f.flush()
            for line in f:
                msg = json.loads(line)
                yield msg
                if msg.get("done"):
                    return
