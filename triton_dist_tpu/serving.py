"""Socket serving: a continuously-batched streaming token server +
client over the Engine.

TPU re-design of the reference's serving pair
(`mega_triton_kernel/test/models/model_server.py:265` — a TCP server
that tokenizes prompts, prefills, and streams sampled tokens — and the
interactive `chat.py:207` client). Protocol is line-delimited JSON over
TCP:

  client -> {"prompt": str, "gen_len": int, "seed": int}\n
  server -> {"text": str, "token_ids": [...]}\n        per decode chunk
            {"done": true, "n_tokens": int}\n          terminator

Tokens stream INCREMENTALLY: the decode runs in chunks of `chunk`
steps (each chunk one jitted scan), so clients render text while the
model is still generating. The server is MULTI-CLIENT (continuous
batching, models/scheduler.py): up to `batch` concurrent requests
decode in distinct slots of one slot scan — distinct prompts, per-slot
positions and PRNG chains — and a finished client's slot is refilled
from the accept queue between chunks while the other streams keep
flowing. Chunked decode is token-exact vs Engine.serve() in BOTH
sampling modes (greedy: same argmax chain; sampled: the scan's evolved
key chains across chunks).

paged=True additionally serves over the paged KV pool with the
SHARED-PREFIX radix cache (models/prefix_cache.py): prompts sharing a
system-prompt/few-shot prefix reuse its cached KV pages and skip that
prefill work — token streams stay bitwise identical to prefix_cache=
False. The final {"done": ...} message then reports a "cache" dict
(hit rate, prefill tokens skipped). Clients that hang up mid-stream
are detected (EOF probe or failed write) and their slot is CANCELLED —
pages freed and the partial sequence inserted into the prefix tree —
instead of decoding to gen_len for nobody.

Resilience (models/scheduler.py has the scheduler-side story):
- a malformed request (bad JSON, over-capacity prompt, an unbounded
  garbage "line" past _MAX_LINE bytes) gets a structured
  {"done": true, "error": ...} refusal before the close — never a
  silent slam, never a ballooning reader buffer;
- max_queue bounds the accept line: overflow is answered with
  {"busy": true, "retry_after_ms": ...} (retry_after scaled by the
  measured poll cadence x queue depth), and request_stream retries it
  with bounded backoff — as it retries refused connects during server
  startup;
- requests may carry "deadline_ms"; an expired request is cancelled
  with a visible error in its done message;
- under KV-pool pressure the scheduler PREEMPTS a victim slot instead
  of rejecting (the client just sees a pause — resumed streams are
  bitwise identical), and a hung decode chunk (watchdog_s) ends the
  loop with a HANG error to every live client instead of freezing.

Multi-chip TP: build the model over a TP mesh and ONE TokenServer
drives every chip — the paged pool is head-sharded and the slot scan
runs under shard_map with the projections on the TP comm backends
(models/kv_cache.py TP SHARDING + models/scheduler.py module
docstring); streams are bitwise identical TP=N vs TP=1 and stats()
reports tp_size plus aggregate AND per-chip tok/s
(tests/test_tp_serving.py).

Telemetry (runtime/telemetry.py): stats() is a deep registry snapshot
with live `ttft_ms` / `inter_token_ms` p50/p95/p99 histograms; any
client can fetch it in-protocol with a `{"op": "stats"}` request
(one JSON reply line, then close). `metrics_port=` starts a minimal
Prometheus text-exposition listener (`GET /metrics` over HTTP/1.0 —
scrape `http://host:server.metrics_port/metrics`), and
`TDTPU_TRACE=path` enables poll-loop tracing AND dumps the
perfetto-loadable timeline + request traces to `path` when
serve_forever exits (summarize with tools/trace_view.py).
"""

from __future__ import annotations

import json
import os
import random
import socket
import threading
import time
from typing import Iterator, Optional

import numpy as np

# longest accepted request line: a protocol message is a few hundred
# bytes; anything bigger is a firehose and gets a structured refusal
_MAX_LINE = 65536


class ServerBusy(RuntimeError):
    """request_stream exhausted its busy retries; retry_after_ms is the
    server's latest hint."""

    def __init__(self, retry_after_ms: float):
        super().__init__(
            f"server busy (retry_after_ms={retry_after_ms:g})")
        self.retry_after_ms = retry_after_ms


class ByteTokenizer:
    """Toy byte-level tokenizer capped to a vocab (examples/07's demo
    tokenizer, importable for the serving tests)."""

    def __init__(self, vocab_size: int):
        self.vocab_size = vocab_size

    def encode(self, text: str):
        return [b % self.vocab_size for b in text.encode()]

    def decode(self, ids):
        return bytes(int(i) % 256 for i in ids).decode("latin-1")


def decode_stream(engine, logits, cache, gen_len: int, *, chunk: int = 4,
                  seed: int = 0):
    """Yield token chunks [B, <=chunk] as they are generated: each chunk
    is one jitted decode scan, with (logits, cache) carried between
    chunks (the cache is donated into each scan, so memory stays flat).
    Chunking is exact in BOTH modes: greedy because the argmax chain is
    identical to one gen_len-long scan, and sampled because the scan
    returns its evolved PRNG key and the next chunk resumes the chain —
    the sampled stream equals Engine.serve() at the same seed for every
    chunk size (it used to re-split a fresh key per chunk and diverge)."""
    import jax
    if engine.backend == "mega":
        raise ValueError("mega decode carries no resumable logits; "
                         "stream with the per-op backends")
    key = jax.random.key(seed)
    done = 0
    while done < gen_len:
        g = min(chunk, gen_len - done)
        if engine.sampling == "greedy":
            toks, logits, cache = engine._decode_scan(
                engine.model, logits, cache, gen_len=g)
        else:
            toks, logits, cache, key = engine._decode_scan(
                engine.model, logits, cache, key, gen_len=g)
        yield np.asarray(toks)
        done += g


class TokenServer:
    """Accept prompts, stream decode chunks back (reference:
    model_server.py's request loop), now CONTINUOUSLY BATCHED: up to
    `batch` clients decode concurrently, each in its own slot of the
    scheduler (models/scheduler.py) — distinct requests, distinct KV
    rows, one jitted slot scan per chunk. A freed slot is refilled
    from the connection queue between chunks while the other clients'
    streams keep flowing. Still single-threaded ON THE MODEL: socket
    threads only parse requests and write replies; every jax dispatch
    happens on the serve_forever thread (concurrency is batching, not
    model threads — the discipline the old one-request loop had, kept).

    Engine(backend="mega") engines serve here unchanged with
    paged=True (greedy streams): pure-decode polls run the FUSED
    megakernel tick (one Pallas kernel per layer —
    engine.paged_slot_chunk routes it), admissions and chunked-prefill
    mixed polls fall back per-op per poll, and the `mega_enabled`
    gauge + `device_wait_kind_s{kind="mega"}` ride the stats()/
    Prometheus surfacing below. Unsupported combinations (sampled,
    spec=K, paged=False, TP meshes) refuse at construction with the
    precise missing capability named — never mid-stream."""

    def __init__(self, engine, tokenizer, *, batch: int,
                 host: str = "127.0.0.1", port: int = 0,
                 chunk: int = 4, paged: bool = False,
                 prefix_cache: bool = True, page: int = 16,
                 num_pages: Optional[int] = None, spec: int = 0,
                 drafter=None, max_queue: Optional[int] = None,
                 watchdog_s: Optional[float] = None, fault=None,
                 prefill_budget: Optional[int] = None,
                 host_pool_pages: int = 0, overlap: bool = False,
                 metrics_port: Optional[int] = None,
                 trace: Optional[bool] = None,
                 disagg: bool = False, prefill_workers: int = 1,
                 disagg_threads: bool = True, transport=None,
                 slo_classes: Optional[dict] = None,
                 max_forks: int = 8,
                 replica_id: Optional[str] = None):
        """paged=True serves over the paged KV pool with the
        shared-prefix radix cache (models/prefix_cache.py): concurrent
        prompts sharing a system-prompt/few-shot prefix reuse its
        cached KV pages and skip that prefill; the final {"done": ...}
        message then carries a "cache" dict (hit rate, prefill tokens
        skipped) and stats() exposes the running counters.

        spec=K > 0 turns each decode step into a speculative
        draft-then-verify iteration (models/spec_decode.py, n-gram
        prompt-lookup drafting by default): every slot streams 1..K+1
        tokens per model forward, token-for-token identical to spec=0
        under greedy sampling. stats() then also reports
        spec_accept_rate and tokens_per_step.

        max_queue bounds the waiting line (overflow clients get
        {"busy": true, "retry_after_ms": ...}); watchdog_s deadlines
        every decode chunk (a hang ends serve_forever with a clean
        error to every client); fault is a chaos hook
        (runtime/chaos.py::FaultInjector) for resilience tests.

        prefill_budget enables CHUNKED PREFILL (Sarathi-Serve — the
        models/scheduler.py docstring has the design): a long prompt's
        admission no longer stalls every live client's stream for its
        whole prefill; at most `prefill_budget` prompt tokens ride
        each decode step until the prompt is absorbed and its slot
        starts streaming. Token streams are bitwise identical either
        way — this knob trades a bounded per-step latency bump for the
        removal of multi-hundred-ms inter-token spikes under load.

        host_pool_pages enables the HOST-RAM KV TIER on the paged path
        (models/kv_tier.py): evicted prefix spans demote to a host
        pool of that many device-page-sized buffers instead of being
        dropped, and a returning tenant's prefix promotes back into
        fresh device pages — the effective cache becomes
        num_pages + host_pool_pages. stats() (and each done message's
        "cache" dict) then reports host_hits / host_pages_resident /
        demotions / promotions / restore_latency_ms live.

        overlap enables the DISPATCH-AHEAD OVERLAP SCHEDULER
        (models/scheduler.py module docstring): the driver dispatches
        the next device tick before reading back the previous one, so
        this server's per-poll host work — admissions, drafting, the
        socket writes between polls — runs while the device computes
        instead of serializing with it. Token streams are bitwise
        identical either way; the watchdog and deadline checks move to
        landed-tick boundaries (a dispatch cannot hang — the readback
        can). The win is visible as stats()["host_ms_per_poll"] (also
        in every done message): when that approaches the device step
        time, overlap=True is the difference between host-bound and
        device-bound serving.

        metrics_port: not None starts a Prometheus text-exposition
        listener on that TCP port (0 = ephemeral; the bound port is
        `self.metrics_port`) — `GET /metrics` returns the scheduler's
        registry plus the process-global one (Engine dispatch
        counters) in exposition format v0.0.4.

        trace: poll-loop + request tracing (runtime/telemetry.py,
        perfetto-loadable; None = the TDTPU_TRACE env convention —
        setting TDTPU_TRACE=path also makes serve_forever dump the
        trace to `path` on exit). Clients can fetch the live stats
        snapshot — ttft_ms / inter_token_ms histograms included —
        with a `{"op": "stats"}` request.

        disagg=True serves in PREFILL/DECODE DISAGGREGATED mode
        (models/disagg.py — the DistServe split): admissions prefill
        on `prefill_workers` dedicated workers (their own threads by
        default — disagg_threads) and stream finished KV pages to the
        decode mesh over `transport` (HostTransport default;
        ICITransport/DCNTransport for the device tiers), so decode
        polls never carry a prefill q_len and inter-token latency
        stays flat under long-prompt admission load. Always paged;
        mutually exclusive with prefill_budget (chunked prefill is
        the fused alternative disaggregation replaces). Streams are
        bitwise identical either way (tests/test_disagg.py).

        slo_classes: the SLO classes clients may tag requests with
        (the in-protocol `"slo"` field — e.g. "interactive"/"batch";
        None = runtime/telemetry.DEFAULT_SLO_CLASSES). Tagged
        requests land their lifecycle latencies in per-class
        `ttft_ms{slo=...}` / `inter_token_ms{slo=...}` histograms and
        partition into `slo_goodput`/`slo_violations` counters —
        visible in stats(), `{"op": "stats"}` and `/metrics`. An
        unknown class tag on a request is REFUSED (bounded metric
        cardinality) with the configured names in the error.

        max_forks caps the in-protocol `"n"` field (parallel sampling:
        one prefill, n KV-forked decode slots — models/structured.py
        has the subsystem story). A request may also carry a
        `"grammar"` spec ({"type": "json_schema", "schema": ...} or
        {"type": "token_fsm", ...}) compiled server-side against the
        byte vocab; n<=0, n over the cap, n>1 without paged=True, and
        a malformed grammar all get the structured {"done", error}
        refusal with the parse error echoed — never a crashed poll
        loop. Fork chunks are tagged {"fork": k} and the n streams
        share ONE fan-in done message once every fork finishes.

        replica_id names this server inside a FLEET (fleet/router.py):
        when set, every done message and stats() snapshot carries
        ``"replica"`` — the retire event a router's shadow placement
        index consumes — and `{"op": "stats"}` doubles as the identity
        handshake of a membership health probe. Requests may also tag a
        ``"session"`` field (any string up to 128 chars): the server
        accepts and ignores it, the ROUTER uses it for session
        affinity, so one client codepath speaks to both a bare server
        and a fleet. A ``"request_id"`` field (non-empty string up to
        128 chars) rides the same contract: validated and ignored
        here, it is the idempotency key the HA router tier
        (fleet/ha.py) dedups on for exactly-once delivery."""
        from triton_dist_tpu.models.disagg import DisaggScheduler
        from triton_dist_tpu.models.scheduler import ContinuousScheduler
        self.engine = engine
        self.tok = tokenizer
        self.batch = batch
        self.chunk = chunk
        self.paged = paged or disagg
        if disagg:
            if prefill_budget is not None:
                raise ValueError(
                    "disagg=True replaces chunked prefill — drop "
                    "prefill_budget (the decode mesh never prefills)")
            self.sched = DisaggScheduler(
                engine, batch=batch, chunk=chunk,
                prefix_cache=prefix_cache, page=page,
                num_pages=num_pages, spec=spec, drafter=drafter,
                max_queue=max_queue, watchdog_s=watchdog_s,
                fault=fault, host_pool_pages=host_pool_pages,
                overlap=overlap, trace=trace,
                prefill_workers=prefill_workers,
                threads=disagg_threads, transport=transport,
                slo_classes=slo_classes)
        else:
            self.sched = ContinuousScheduler(
                engine, batch=batch, chunk=chunk, paged=paged,
                prefix_cache=prefix_cache, page=page,
                num_pages=num_pages, spec=spec, drafter=drafter,
                max_queue=max_queue, watchdog_s=watchdog_s,
                fault=fault, prefill_budget=prefill_budget,
                host_pool_pages=host_pool_pages, overlap=overlap,
                trace=trace, slo_classes=slo_classes)
        self.max_forks = max_forks
        self.replica_id = replica_id
        self._vocab = None       # lazy byte vocab for grammar compiles
        self._poll_ema = 0.05    # measured poll cadence, seeds retry_after
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(max(4, batch))
        self.host, self.port = self._sock.getsockname()
        self._stop = threading.Event()
        self._next_rid = 0
        self._conns: dict = {}          # rid -> _ClientStream
        self._lock = threading.Lock()   # guards scheduler submit + _conns
        # optional Prometheus /metrics listener (daemon thread; dies
        # with stop()). metrics_port=0 binds an ephemeral port.
        self.metrics_port: Optional[int] = None
        self._msock: Optional[socket.socket] = None
        if metrics_port is not None:
            self._msock = socket.socket(socket.AF_INET,
                                        socket.SOCK_STREAM)
            self._msock.setsockopt(socket.SOL_SOCKET,
                                   socket.SO_REUSEADDR, 1)
            self._msock.bind((host, metrics_port))
            self._msock.listen(4)
            self._msock.settimeout(0.25)
            self.metrics_port = self._msock.getsockname()[1]
            threading.Thread(target=self._serve_metrics,
                             daemon=True).start()

    class _ClientStream:
        """Per-connection state: the socket + reply file handle + token
        count. Owned by the model loop after admission; the reader
        thread only hands it over."""

        def __init__(self, conn, fh):
            self.conn = conn
            self.fh = fh
            self.n = 0
            self.dead = False
            self.n_left = 1     # forks still streaming (fan-in count)
            self.errors = []    # per-fork failure reasons, fan-in done

    @staticmethod
    def _refuse(conn, f, msg: dict) -> None:
        """Best-effort structured refusal, then close: a bad or
        refused request gets a visible reason, never a silent slam.
        Before closing, signal end-of-stream and BRIEFLY drain unread
        input (the oversized-line path leaves the rest of the firehose
        in the receive queue; closing with unread bytes makes TCP send
        RST, which can discard the refusal before the client reads it).
        The drain is bounded in time and bytes so an endless firehose
        cannot park this thread."""
        try:
            f.write(json.dumps(msg) + "\n")
            f.flush()
        except (OSError, ValueError):
            pass
        try:
            conn.shutdown(socket.SHUT_WR)
            conn.settimeout(0.25)
            drained, t0 = 0, time.monotonic()
            while drained < (4 << 20) and time.monotonic() - t0 < 1.0:
                chunk = conn.recv(65536)
                if not chunk:
                    break
                drained += len(chunk)
        except OSError:
            pass
        for closer in (f.close, conn.close):
            try:
                closer()
            except OSError:
                pass

    def _reader(self, conn: socket.socket) -> None:
        """Connection thread: parse ONE request line (capped at
        _MAX_LINE bytes — a garbage firehose cannot balloon this
        thread), enqueue it for the model loop, leave the socket open
        for streaming replies. Every refusal — malformed JSON,
        over-capacity prompt, oversized line, full queue — is answered
        with a structured line before the close."""
        import sys
        from triton_dist_tpu.models.scheduler import Request
        try:
            conn.settimeout(60.0)   # a silent client cannot hold a slot
            f = conn.makefile("rw")
            try:
                line = f.readline(_MAX_LINE + 1)
            except UnicodeDecodeError:
                # the reply side of the text-mode file is independent
                # of the poisoned read side — refuse, don't hang the
                # client until its timeout
                self._refuse(conn, f, {
                    "done": True, "n_tokens": 0,
                    "error": "bad request: line is not valid UTF-8"})
                return
            if not line.strip():
                conn.close()
                return
            # readline's cap counts decoded CHARACTERS; the contract is
            # BYTES (multi-byte UTF-8 would otherwise stretch it 4x)
            if len(line) > _MAX_LINE or len(line.encode()) > _MAX_LINE:
                self._refuse(conn, f, {
                    "done": True, "n_tokens": 0,
                    "error": f"request line exceeds {_MAX_LINE} bytes"})
                return
            try:
                req = json.loads(line)
                if not isinstance(req, dict):
                    raise ValueError("request must be a JSON object")
                if req.get("op") == "stats":
                    # in-protocol stats fetch: one deep-snapshot JSON
                    # reply (live ttft/inter-token histograms
                    # included), then close — no slot consumed
                    self._refuse(conn, f, {"done": True,
                                           "stats": self.stats()})
                    return
                ids = self.tok.encode(str(req.get("prompt", ""))) or [0]
                gen_len = int(req.get("gen_len", 16))
                seed = int(req.get("seed", 0))
                n = int(req.get("n", 1))
                if n < 1:
                    raise ValueError(f"bad n={n}: must be >= 1")
                if n > self.max_forks:
                    raise ValueError(
                        f"n={n} exceeds max_forks cap {self.max_forks}")
                if n > 1 and not self.paged:
                    raise ValueError(
                        "n>1 parallel sampling needs paged=True (the "
                        "KV fork shares the prompt's pages)")
                grammar = req.get("grammar")
                gspec = None
                if grammar is not None:
                    # compiled HERE so a malformed spec refuses at the
                    # wire with the parse error echoed, never inside
                    # the poll loop
                    from triton_dist_tpu.models.structured import \
                        GrammarSpec
                    if not isinstance(grammar, dict):
                        raise ValueError(
                            "grammar must be a JSON object")
                    gspec = GrammarSpec.from_wire(
                        grammar, self._byte_vocab())
                deadline_ms = req.get("deadline_ms")
                if deadline_ms is not None:
                    deadline_ms = float(deadline_ms)
                session = req.get("session")
                if session is not None:
                    # accepted (and bounded) so one client codepath
                    # works against a bare server and a fleet router;
                    # affinity itself is ROUTER state (fleet/router.py)
                    if not isinstance(session, str) or \
                            len(session) > 128:
                        raise ValueError(
                            "session must be a string of <= 128 chars")
                request_id = req.get("request_id")
                if request_id is not None:
                    # same contract as session: validated + ignored by
                    # a bare server; the exactly-once dedup window is
                    # ROUTER state (fleet/ha.py journal watermarks)
                    if not isinstance(request_id, str) or \
                            not request_id or len(request_id) > 128:
                        raise ValueError("request_id must be a "
                                         "non-empty string of "
                                         "<= 128 chars")
                slo = req.get("slo")
                if slo is not None:
                    slo = str(slo)
                    # bounded metric cardinality: only configured
                    # classes may be tagged over the wire (scheduler-
                    # level callers can still register ad hoc)
                    known = self.sched.tele.slo_classes
                    if slo not in known:
                        raise ValueError(
                            f"unknown slo class {slo!r} (configured: "
                            f"{sorted(known)})")
            except (ValueError, KeyError, TypeError) as e:
                self._refuse(conn, f, {
                    "done": True, "n_tokens": 0,
                    "error": f"bad request: {type(e).__name__}: {e}"})
                return
            # clamp to slot capacity (prompt + gen must fit the slot);
            # a prompt with no room for even one token is refused here
            # with a visible error instead of occupying a slot
            slot_cap = self.sched.slots.capacity
            cap = slot_cap - len(ids)
            if cap < 1:
                self._refuse(conn, f, {
                    "done": True, "n_tokens": 0,
                    "error": f"prompt of {len(ids)} tokens exceeds "
                             f"capacity {slot_cap - 1}"})
                return
            gen_len = max(1, min(gen_len, cap))
            with self._lock:
                rid = self._next_rid
                self._next_rid += 1
                accepted = self.sched.submit(Request(
                    rid=rid, ids=np.asarray(ids, np.int32),
                    gen_len=gen_len, seed=seed, n=n, grammar=gspec,
                    deadline_ms=deadline_ms, slo=slo))
                if accepted:
                    cs = self._ClientStream(conn, f)
                    cs.n_left = n
                    if n > 1:
                        # the scheduler fans rid out into kid rids
                        # (rid, 0)..(rid, n-1); every fork streams to
                        # this ONE connection and the done message
                        # fans back in once all n finish
                        for k in range(n):
                            self._conns[(rid, k)] = cs
                    else:
                        self._conns[rid] = cs
                else:
                    hint = self._retry_after_ms()
            if not accepted:
                # backpressure, not an unbounded queue: tell the client
                # WHEN to come back instead of buffering it forever
                self._refuse(conn, f, {"busy": True,
                                       "retry_after_ms": hint})
        except OSError as e:
            print(f"[TokenServer] bad request: {type(e).__name__}: {e}",
                  file=sys.stderr)
            try:
                conn.close()
            except OSError:
                pass

    def _serve_metrics(self) -> None:
        """Prometheus text-exposition listener: one short-lived HTTP
        exchange per scrape (HTTP/1.0, connection-close — the format
        every Prometheus-compatible scraper speaks). Refreshes the
        point-in-time gauges via stats() before rendering, and serves
        the scheduler registry plus the process-global default (the
        Engine dispatch counters)."""
        from triton_dist_tpu.runtime.telemetry import (
            default_registry, prometheus_text)
        while not self._stop.is_set():
            try:
                conn, _ = self._msock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                conn.settimeout(2.0)
                conn.recv(4096)          # request line + headers
                self.stats()             # refresh registry gauges
                body = prometheus_text(self.sched.tele.registry,
                                       default_registry()).encode()
                conn.sendall(
                    b"HTTP/1.0 200 OK\r\n"
                    b"Content-Type: text/plain; version=0.0.4; "
                    b"charset=utf-8\r\n"
                    b"Content-Length: " + str(len(body)).encode()
                    + b"\r\n\r\n" + body)
            except OSError:
                pass
            finally:
                try:
                    conn.close()
                except OSError:
                    pass

    def _byte_vocab(self):
        """Byte-string vocab for grammar compiles, built once per
        server against the model's vocab size (every grammar request
        shares it — compiling a JSON schema is cheap, rebuilding the
        vocab per request is not)."""
        if self._vocab is None:
            from triton_dist_tpu.models.structured import byte_vocab
            self._vocab = byte_vocab(self.sched.slots._vocab_size)
        return self._vocab

    def _retry_after_ms(self) -> int:
        """Backpressure hint: the measured poll cadence times the line
        ahead of the client — crude, but it scales with actual load
        instead of being a magic constant."""
        depth = self.sched.queue_depth
        return int(max(25.0, min(5000.0,
                                 1e3 * self._poll_ema * (depth + 2))))

    def _emit(self, rid, toks) -> None:
        """Stream one chunk's tokens to the owning client; a dead
        socket marks the stream dead — the model loop then CANCELS its
        slot (sched.cancel) instead of decoding to gen_len with the
        tokens falling on the floor."""
        cs = self._conns.get(rid)
        if cs is None or cs.dead:
            return
        row = [int(t) for t in toks]
        msg = {"text": self.tok.decode(row), "token_ids": row}
        if isinstance(rid, tuple):
            # fork kid rid (parent, k): tag the chunk so the client
            # can demux the n interleaved streams
            msg["fork"] = int(rid[1])
        try:
            cs.fh.write(json.dumps(msg) + "\n")
            cs.fh.flush()           # the stream is the point
            cs.n += len(row)
        except OSError:
            cs.dead = True

    def _probe_disconnects(self) -> None:
        """Detect clients that hung up WITHOUT a failed write: after
        the request line a client never sends again, so a non-blocking
        recv returning b'' is EOF — mark the stream dead so the model
        loop cancels its slot this iteration."""
        for cs in list(self._conns.values()):
            if cs.dead:
                continue
            try:
                timeout = cs.conn.gettimeout()
            except OSError:
                cs.dead = True
                continue
            try:
                cs.conn.setblocking(False)
                if cs.conn.recv(1) == b"":
                    cs.dead = True
            except (BlockingIOError, InterruptedError):
                pass            # alive, nothing to read
            except OSError:
                cs.dead = True
            finally:
                try:
                    cs.conn.settimeout(timeout)   # keep the write timeout
                except OSError:
                    pass

    def stats(self) -> dict:
        """Serving counters: prefix-cache (hit rate, prefill tokens
        skipped — paged path), speculative decoding (spec_accept_rate,
        tokens_per_step — spec=K mode), the resilience counters
        (queue_depth, preemptions, deadline_expired, busy_rejections,
        "hang" verdict once a watchdogged chunk missed its deadline),
        and the live ttft_ms / inter_token_ms / poll_ms histograms.

        The scheduler already returns a DEEP single-point-in-time
        registry snapshot (runtime/telemetry.py) — every container
        freshly allocated under the scheduler + registry locks — so
        cross-thread readers (this server's reader threads, the
        /metrics listener, test hammers) can iterate and serialize it
        while the driver keeps polling."""
        with self._lock:
            st = self.sched.stats()
        if self.replica_id is not None:
            st["replica_id"] = self.replica_id
        return st

    def _finish(self, rid, error: Optional[str] = None) -> bool:
        """Close out one finished rid; returns True when the client
        stream fully closed. A forked request registers one stream
        under n kid rids — each kid's finish decrements the fan-in
        count and only the LAST writes the single done message."""
        cs = self._conns.pop(rid, None)
        if cs is None:
            return False
        reason = error if error is not None \
            else self.sched.rejected.pop(rid, None)
        if reason is not None:
            cs.errors.append(f"fork {rid[1]}: {reason}"
                             if isinstance(rid, tuple) else reason)
        cs.n_left -= 1
        if cs.n_left > 0:
            return False
        reason = "; ".join(cs.errors) if cs.errors else None
        try:
            if not cs.dead:
                msg = {"done": True, "n_tokens": cs.n}
                if self.replica_id is not None:
                    # fleet identity echo: the router feeds its shadow
                    # placement index from this retire event
                    msg["replica"] = self.replica_id
                if reason is not None:
                    # a scheduler-rejected request (pool exhausted,
                    # over capacity) must not look like a legitimate
                    # zero-token completion
                    msg["error"] = reason
                st = self.sched.stats()
                # host time per poll with device wait subtracted — the
                # overlap scheduler's observable win (the EMA the
                # operator compares overlap on vs off)
                msg["host_ms_per_poll"] = st["host_ms_per_poll"]
                if self.paged:
                    msg["cache"] = {
                        k: st[k] for k in ("hit_rate",
                                           "prefill_tokens_skipped",
                                           "prefill_skip_frac")}
                    if st.get("host_pool_pages"):
                        # host-tier gauges: the operator's live view
                        # of demote/promote behaviour per reply
                        msg["cache"].update({
                            k: st[k] for k in ("host_hits",
                                               "host_pages_resident",
                                               "demotions",
                                               "promotions",
                                               "restore_latency_ms")})
                cs.fh.write(json.dumps(msg) + "\n")
                cs.fh.flush()
        except OSError:
            pass
        for closer in (cs.fh.close, cs.conn.close):
            try:
                closer()
            except OSError:
                pass
        return True

    def serve_forever(self, max_requests: Optional[int] = None) -> None:
        """Model loop: accept connections (handing each to a reader
        thread), then run the scheduler — admit, one chunk, stream each
        slot's tokens to its client. max_requests counts COMPLETED
        requests (so a test can serve N concurrent clients and exit).
        A watchdogged chunk that hangs (watchdog_s) ends the loop with
        a structured HANG error to every live client — the process is
        poisoned (runtime/stress.py::watchdog contract), and a visible
        verdict beats a silent freeze."""
        from triton_dist_tpu.runtime.stress import HangError
        done_count = 0
        self._sock.settimeout(0.02)
        try:
            while not self._stop.is_set():
                # drain the accept queue without blocking the decode
                # loop (reader threads are daemonic and short-lived:
                # one request line each, no tracking needed)
                while True:
                    try:
                        conn, _ = self._sock.accept()
                    except socket.timeout:
                        break
                    threading.Thread(target=self._reader, args=(conn,),
                                     daemon=True).start()
                t0 = time.monotonic()
                try:
                    with self._lock:
                        out, finished = self.sched.poll()
                except HangError as e:
                    for rid in list(self._conns):
                        self._finish(rid, error=str(e))
                    break
                self._poll_ema = 0.9 * self._poll_ema + \
                    0.1 * (time.monotonic() - t0)
                for rid, toks in out.items():
                    self._emit(rid, toks)
                for rid in finished:
                    if self._finish(rid):
                        done_count += 1
                # cancel-on-disconnect: a hung-up client's slot retires
                # NOW (pages freed / inserted into the prefix tree)
                # instead of decoding to gen_len for nobody
                self._probe_disconnects()
                dead = [rid for rid, cs in list(self._conns.items())
                        if cs.dead]
                for rid in dead:
                    with self._lock:
                        self.sched.cancel(rid)
                    if self._finish(rid):
                        done_count += 1
                if max_requests is not None and done_count >= max_requests:
                    break
                if self.sched.idle:
                    # nothing in flight: sleep on accept instead of
                    # spinning the poll loop
                    self._stop.wait(0.05)
        finally:
            self._sock.close()
            for rid in list(self._conns):
                self._finish(rid)
            # TDTPU_TRACE contract: dump the poll-loop timeline +
            # request traces + metrics snapshot on exit (perfetto-
            # loadable; summarize with tools/trace_view.py)
            path = os.environ.get("TDTPU_TRACE")
            if path:
                try:
                    self.sched.dump_trace(path)
                except OSError:
                    pass

    def stop(self) -> None:
        self._stop.set()
        # disaggregated mode: stop the prefill worker threads too
        close = getattr(self.sched, "close", None)
        if close is not None:
            close()
        if self._msock is not None:
            try:
                self._msock.close()
            except OSError:
                pass


def full_jitter(delay_s: float, rand=None) -> float:
    """Full-jitter backoff (AWS architecture-blog flavor): a uniform
    draw over [0, delay_s]. The deterministic alternative — sleeping
    exactly delay_s — means N clients that failed TOGETHER (a router
    death severs every stream at once) retry together forever, each
    round a synchronized thundering herd; the uniform draw decorrelates
    them in one round. ``rand`` is an injectable () -> [0, 1) for
    distribution tests (tests/test_serving.py)."""
    if rand is None:
        rand = random.random
    return max(0.0, float(delay_s)) * rand()


def request_stream(host: str, port: int, prompt: str, *,
                   gen_len: int = 16, seed: int = 0,
                   timeout: float = 300.0,
                   deadline_ms: Optional[float] = None,
                   slo: Optional[str] = None,
                   session: Optional[str] = None,
                   request_id: Optional[str] = None,
                   n: int = 1, grammar: Optional[dict] = None,
                   connect_retries: int = 8,
                   connect_backoff_s: float = 0.05,
                   busy_retries: int = 4) -> Iterator[dict]:
    """Client: send one prompt, yield the server's chunk messages as
    they arrive (the last one has {"done": true}, possibly carrying an
    "error" — rejection, deadline expiry, server hang — which callers
    should check rather than trusting n_tokens). Reference: the chat.py
    client's receive loop.

    n>1 requests parallel sampling (KV fork server-side): chunk
    messages then carry a "fork" index to demux the n interleaved
    streams, and ONE fan-in done message closes them all. grammar= is
    passed through as the wire spec ({"type": "json_schema", ...} or
    {"type": "token_fsm", ...}) for constrained decoding.

    Resilient by default: a refused connect (server still starting —
    the classic flaky-test source) retries with bounded exponential
    backoff, and a {"busy": ...} backpressure reply sleeps the server's
    retry_after_ms hint and resubmits, up to busy_retries times before
    raising ServerBusy. Busy replies are consumed internally — they are
    NEVER yielded as chunks."""
    payload = {"prompt": prompt, "gen_len": gen_len, "seed": seed}
    if n != 1:
        payload["n"] = n
    if grammar is not None:
        payload["grammar"] = grammar
    if deadline_ms is not None:
        payload["deadline_ms"] = deadline_ms
    if slo is not None:
        payload["slo"] = slo
    if session is not None:
        # affinity hint: a bare server validates and ignores it; a
        # fleet router (fleet/router.py) pins the session to a replica
        payload["session"] = session
    if request_id is not None:
        # idempotency key: a bare server validates and ignores it; a
        # fleet router dedups on it (fleet/ha.py) so a retried submit
        # after an ambiguous EOF never double-serves
        payload["request_id"] = request_id
    connects = 0
    busy_left = busy_retries
    while True:
        try:
            s = socket.create_connection((host, port), timeout=timeout)
        except OSError:
            if connects >= connect_retries:
                raise
            # full jitter: every client that lost its router at the
            # same instant must NOT reconnect at the same instant
            time.sleep(full_jitter(
                min(connect_backoff_s * (2 ** connects), 2.0)))
            connects += 1
            continue
        retry_ms = None
        with s, s.makefile("rw") as f:
            f.write(json.dumps(payload) + "\n")
            f.flush()
            for line in f:
                msg = json.loads(line)
                if msg.get("busy"):
                    retry_ms = float(msg.get("retry_after_ms", 100.0))
                    break
                yield msg
                if msg.get("done"):
                    return
            else:
                return      # server closed without a done message
        if retry_ms is None:
            return
        if busy_left <= 0:
            raise ServerBusy(retry_ms)
        busy_left -= 1
        time.sleep(full_jitter(retry_ms / 1e3))
