"""Shared layer math: RMSNorm, RoPE, TP weight packing.

Reference analogs: RoPE at layers/nvidia/tp_attn.py:165, weight sharding
`shard_local` at layers/nvidia/tp_mlp.py:38 (torch chunk per rank). Here
sharding is declarative (NamedSharding) and packing is a host-side array
transform.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def rms_norm(x, weight, eps: float = 1e-6):
    """RMSNorm in f32 accumulation (Qwen3-style). The result keeps x's
    dtype: an f32 weight must not promote the activation — a bf16
    activation silently becoming f32 here used to cascade into
    full-KV-cache dtype converts per layer per decode step (55% of the
    step time on the profile)."""
    dt = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    out = x32 * jax.lax.rsqrt(var + eps) * weight.astype(jnp.float32)
    return out.astype(dt)


def precompute_rope(head_dim: int, max_seq: int, theta: float = 1e6):
    """cos/sin tables [max_seq, head_dim//2] (Qwen3 uses theta=1e6)."""
    inv = 1.0 / (theta ** (np.arange(0, head_dim, 2) / head_dim))
    t = np.arange(max_seq)
    freqs = np.outer(t, inv)
    return (jnp.asarray(np.cos(freqs), dtype=jnp.float32),
            jnp.asarray(np.sin(freqs), dtype=jnp.float32))


def apply_rope(x, cos, sin, positions):
    """Rotate half-pairs: x [..., S, H, D]; cos/sin [max_seq, D/2];
    positions [S] (ref: tp_attn.py:165 applies the same rotation on the
    gathered QKV)."""
    c = cos[positions][:, None, :]  # [S, 1, D/2]
    s = sin[positions][:, None, :]
    x1, x2 = jnp.split(x, 2, axis=-1)
    dt = x.dtype
    x1f, x2f = x1.astype(jnp.float32), x2.astype(jnp.float32)
    return jnp.concatenate(
        [x1f * c - x2f * s, x2f * c + x1f * s], axis=-1).astype(dt)


def apply_rope_slots(x, cos, sin, pos):
    """Per-slot RoPE: x [B, S, H, D]; pos [B] int32 — row b rotates at
    positions pos[b] .. pos[b]+S-1. The continuous-batching decode path
    (models/scheduler.py), where every batch row is a different request
    at a different sequence position."""
    B, S = x.shape[0], x.shape[1]
    p = pos[:, None] + jnp.arange(S)            # [B, S]
    c = cos[p][:, :, None, :]                   # [B, S, 1, D/2]
    s = sin[p][:, :, None, :]
    x1, x2 = jnp.split(x, 2, axis=-1)
    dt = x.dtype
    x1f, x2f = x1.astype(jnp.float32), x2.astype(jnp.float32)
    return jnp.concatenate(
        [x1f * c - x2f * s, x2f * c + x1f * s], axis=-1).astype(dt)


def shard_cols_packed(mats, n: int):
    """Pack several column-parallel weights into one matrix whose global
    column layout is n per-rank blocks, each the concat of every input's
    rank-slice: [m0_r | m1_r | ...] for rank r.

    This is how gate/up (MLP) and q/k/v (attention) projections fuse into
    ONE ag_gemm while keeping each rank's output slice self-contained
    (reference analog: per-rank torch chunking in shard_local,
    tp_mlp.py:38).
    """
    blocks = []
    for r in range(n):
        for m in mats:
            cols = m.shape[1] // n
            blocks.append(m[:, r * cols:(r + 1) * cols])
    return jnp.concatenate(blocks, axis=1)
