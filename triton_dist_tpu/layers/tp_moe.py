"""Tensor-parallel MoE layer (experts replicated, intermediate sharded).

TPU-native re-design of `python/triton_dist/layers/nvidia/tp_moe.py`
(AG-GroupGEMM front half + MoE-reduce-RS back half; kernels
`allgather_group_gemm.py:253` and `moe_reduce_rs.py:168`).

Data flow ("dist" mode, x row-sharded [M/n, D] over the TP axis):

    all_gather (Pallas ring)        <- cp-engine AG producer
    route + capacity grouping (XLA) <- sort_topk_ids_align_block_size
                                       (csrc/lib/moe_utils.cu:61)
    grouped GEMM w1 (Pallas)        <- scatter-group-GEMM consumer :536
    SwiGLU
    grouped GEMM w2 (Pallas) -> per-rank PARTIAL expert outputs
    topk-weighted scatter (XLA) + ring reduce_scatter (Pallas)
                                    <- moe_gather_rs_grouped_gemm :168

The reference fuses AG into the group-GEMM's tile waits and the weighted
gather into the RS producer; on TPU the gather/scatter planning is XLA
(it fuses with neighbors and needs dynamic indexing Pallas can't do
cheaply), while the AG, grouped-GEMM and RS stay hand-scheduled Pallas
kernels. The capacity trade (compute-then-mask padding) replaces the
reference's dynamic per-expert tile scheduling — grouped GEMM needs
static shapes on the MXU.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from triton_dist_tpu.kernels import all_gather, grouped_gemm, reduce_scatter
from triton_dist_tpu.kernels.ep_a2a import (expert_token_counts,
                                            group_tokens_by_expert, route,
                                            scatter_weighted)
from triton_dist_tpu.kernels.swiglu import swiglu_ref
from triton_dist_tpu.layers.common import shard_cols_packed


def _pack_expert_cols(w_gate, w_up, n: int):
    """Per-expert column-parallel packing: for each expert, n per-rank
    blocks [gate_r | up_r] (the MLP packing, vmapped over experts)."""
    E = w_gate.shape[0]
    return jnp.stack([shard_cols_packed([w_gate[e], w_up[e]], n)
                      for e in range(E)])


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TP_MoE:
    """Router + per-expert SwiGLU MLPs, intermediate dim sharded over TP.

    w_router:  [D, E] replicated.
    w_gate_up: [E, D, 2I] — per expert, n per-rank [gate_r | up_r] blocks
               (column-parallel), sharded P(None, None, tp).
    w_down:    [E, I, D] row-parallel, sharded P(None, tp, None).
    """

    w_router: jax.Array
    w_gate_up: jax.Array
    w_down: jax.Array
    mesh: Mesh = dataclasses.field(metadata=dict(static=True))
    axis: str = dataclasses.field(metadata=dict(static=True))
    top_k: int = dataclasses.field(metadata=dict(static=True))
    capacity_factor: float = dataclasses.field(
        default=2.0, metadata=dict(static=True))

    @staticmethod
    def init(w_router, w_gate, w_up, w_down, *, mesh: Mesh,
             axis: str = "tp", top_k: int,
             capacity_factor: float = 2.0) -> "TP_MoE":
        n = mesh.shape[axis]
        packed = _pack_expert_cols(jnp.asarray(w_gate), jnp.asarray(w_up), n)
        packed = jax.device_put(packed,
                                NamedSharding(mesh, P(None, None, axis)))
        w_down = jax.device_put(jnp.asarray(w_down),
                                NamedSharding(mesh, P(None, axis, None)))
        return TP_MoE(w_router=jnp.asarray(w_router), w_gate_up=packed,
                      w_down=w_down, mesh=mesh, axis=axis, top_k=top_k,
                      capacity_factor=capacity_factor)

    @property
    def num_experts(self) -> int:
        return self.w_router.shape[1]

    def _cap(self, M: int) -> int:
        """Static per-expert capacity (reference analog: the max_M-sized
        symmetric workspaces). capacity_factor='dropless' uses the
        provable worst case (all routed entries on one expert) — never
        drops, at the memory price of the bound."""
        if self.capacity_factor == "dropless":
            # rounded up to whole 8-row tiles (kernel slab slices must
            # stay sublane-aligned on real TPUs)
            return -(-M * self.top_k // 8) * 8
        E = self.num_experts
        c = int(self.capacity_factor * self.top_k * M / E) + 1
        return min(max(8, -(-c // 8) * 8), M * self.top_k)

    def _expert_mlp_sharded(self, x_e, gemm=None):
        """Per-rank grouped GEMMs over the sharded intermediate dim;
        output is this rank's PARTIAL [E, cap, D] (needs a sum over tp).
        Stacked via out_specs P(axis, ...) for the explicit RS/AR kernels.
        `gemm` swaps the grouped-GEMM callable (the train path passes
        the custom-VJP wrapper)."""
        gemm = gemm or grouped_gemm

        @functools.partial(
            jax.shard_map, mesh=self.mesh,
            in_specs=(P(None, None, None), P(None, None, self.axis),
                      P(None, self.axis, None)),
            out_specs=P(self.axis, None, None, None), check_vma=False)
        def f(x_e, wgu_loc, wd_loc):
            h = gemm(x_e, wgu_loc.astype(x_e.dtype))
            h = swiglu_ref(h)
            y = gemm(h, wd_loc.astype(x_e.dtype))
            return y[None]

        return f(x_e, self.w_gate_up, self.w_down)   # [n, E, cap, D]

    def _stats(self, topk_idx, inv_slot=None, cap: int = 0):
        """Serving-telemetry stats dict (return_stats=True on the
        forwards below): per-expert routed-entry counts + the capacity
        drop count (`inv_slot >= E*cap` marks entries
        group_tokens_by_expert clamped out; the dense oracle never
        drops). The dropless-or-loud contract made observable."""
        E = self.num_experts
        dropped = (jnp.sum(inv_slot >= E * cap).astype(jnp.int32)
                   if inv_slot is not None else jnp.zeros((), jnp.int32))
        return {"expert_tokens": expert_token_counts(topk_idx, E),
                "dropped": dropped}

    def fwd_xla(self, x, return_stats: bool = False):
        """Oracle: dense all-experts math with XLA psum — every token
        through every expert, topk-weighted (the torch oracle role)."""
        M, D = x.shape
        E, k = self.num_experts, self.top_k
        topk_w, topk_idx = route(x @ self.w_router, k)

        @functools.partial(
            jax.shard_map, mesh=self.mesh,
            in_specs=(P(None, None), P(None, None, self.axis),
                      P(None, self.axis, None)),
            out_specs=P(None, None, None), check_vma=False)
        def dense_all(x_full, wgu_loc, wd_loc):
            h = jnp.einsum("md,edf->emf", x_full, wgu_loc.astype(x_full.dtype))
            h = swiglu_ref(h)
            y = jnp.einsum("emf,efd->emd", h, wd_loc.astype(x_full.dtype))
            return jax.lax.psum(y, self.axis)        # [E, M, D]

        y_all = dense_all(x, self.w_gate_up, self.w_down)
        onehot = jax.nn.one_hot(topk_idx, E, dtype=jnp.float32)
        w_e = jnp.einsum("tk,tke->te", topk_w, onehot)
        y = jnp.einsum("te,etd->td", w_e, y_all.astype(jnp.float32))
        y = y.astype(x.dtype)
        if return_stats:
            return y, self._stats(topk_idx)
        return y

    def fwd_dist(self, x, return_stats: bool = False):
        """AG-GroupGEMM + MoE-reduce-RS (x row-sharded [M/n, D] ->
        row-sharded [M/n, D])."""
        n = self.mesh.shape[self.axis]
        xg = all_gather(x, mesh=self.mesh, axis=self.axis)  # [M, D] repl
        M = xg.shape[0]
        cap = self._cap(M)
        topk_w, topk_idx = route(xg @ self.w_router, self.top_k)
        x_e, inv_slot, token = group_tokens_by_expert(
            xg, topk_idx, self.num_experts, cap)
        y_parts = self._expert_mlp_sharded(x_e)       # [n, E, cap, D]

        # topk-weighted gather back to token order, still per-rank partial
        def _scatter(y_e):
            return scatter_weighted(y_e, inv_slot, token, topk_w, M)

        y_partial = jax.vmap(_scatter)(y_parts).astype(x.dtype)  # [n, M, D]
        y = reduce_scatter(y_partial, mesh=self.mesh, axis=self.axis)
        if return_stats:
            return y, self._stats(topk_idx, inv_slot, cap)
        return y

    def fwd_fused(self, x):
        """Fully fused path: ag_group_gemm (ring-AG of capacity chunks
        consumed by per-expert GEMMs) + moe_reduce_rs (grouped down-proj
        whose epilogue ring-reduce-scatters the slabs) — the reference's
        allgather_group_gemm.py:253 + moe_reduce_rs.py:168 pair. x
        row-sharded [M, D] -> row-sharded [M, D]; routing/grouping is
        rank-local, so rank r's capacity block r holds its own tokens
        and the RS hands each rank exactly its combine inputs back."""
        from triton_dist_tpu.kernels.ag_group_gemm import ag_group_gemm
        from triton_dist_tpu.kernels.moe_reduce_rs import moe_reduce_rs
        axis = self.axis
        n = self.mesh.shape[axis]
        M = x.shape[0]
        m_loc = M // n
        E, k = self.num_experts, self.top_k
        cap_loc = self._cap(m_loc)

        @functools.partial(
            jax.shard_map, mesh=self.mesh,
            in_specs=(P(axis, None), P(None, None)),
            out_specs=(P(None, axis, None), P(axis, None), P(axis, None),
                       P(axis, None, None)),
            check_vma=False)
        def prep(x_loc, w_router):
            topk_w, topk_idx = route(x_loc @ w_router, k)
            x_e, inv_slot, token = group_tokens_by_expert(
                x_loc, topk_idx, E, cap_loc)
            return (x_e, inv_slot[None], token[None], topk_w[None])

        x_e, inv_slot, token, topk_w = prep(x, self.w_router)
        h = ag_group_gemm(x_e, self.w_gate_up.astype(x.dtype),
                          mesh=self.mesh, axis=axis)

        # local slice is packed [gate_r | up_r]: swiglu splits halves
        @functools.partial(
            jax.shard_map, mesh=self.mesh,
            in_specs=P(None, None, axis), out_specs=P(None, None, axis),
            check_vma=False)
        def act(h_loc):
            return swiglu_ref(h_loc)

        h2 = act(h)
        y_e = moe_reduce_rs(h2, self.w_down.astype(x.dtype),
                            mesh=self.mesh, axis=axis)

        @functools.partial(
            jax.shard_map, mesh=self.mesh,
            in_specs=(P(None, axis, None), P(axis, None), P(axis, None),
                      P(axis, None, None)),
            out_specs=P(axis, None), check_vma=False)
        def combine(y_loc, inv_loc, tok_loc, w_loc):
            return scatter_weighted(y_loc, inv_loc[0], tok_loc[0],
                                    w_loc[0], m_loc).astype(x.dtype)

        return combine(y_e, inv_slot, token, topk_w)

    def fwd_fused_ar(self, x):
        """Decode path: fused grouped-GEMM + AllReduce epilogue
        (reference: moe_reduce_ar.py:323-645, the small-M latency-bound
        regime). x REPLICATED [M, D] -> replicated [M, D]: routing and
        grouping are replicated (every rank computes the same plan),
        GEMM1 consumes only local weight columns, and the down-proj's
        partial sums are combined by the one-shot push-all AR inside
        moe_reduce_ar — no separate collective, the decode analog of
        TP_MLP's gemm_ar mode."""
        from triton_dist_tpu.kernels.moe_reduce_ar import moe_reduce_ar
        E, k = self.num_experts, self.top_k
        M = x.shape[0]
        cap = self._cap(M)
        topk_w, topk_idx = route(x @ self.w_router, k)
        x_e, inv_slot, token = group_tokens_by_expert(x, topk_idx, E, cap)

        @functools.partial(
            jax.shard_map, mesh=self.mesh,
            in_specs=(P(None, None, None), P(None, None, self.axis)),
            out_specs=P(None, None, self.axis), check_vma=False)
        def up(x_e, wgu_loc):
            h = grouped_gemm(x_e, wgu_loc.astype(x_e.dtype))
            return swiglu_ref(h)

        h2 = up(x_e, self.w_gate_up)
        y_e = moe_reduce_ar(h2, self.w_down.astype(x.dtype),
                            mesh=self.mesh, axis=self.axis)
        return scatter_weighted(y_e, inv_slot, token, topk_w,
                                M).astype(x.dtype)

    def fwd_local(self, x, return_stats: bool = False):
        """Single-chip framework path: route + grouped-GEMM kernels with
        everything resident (the MoE analog of TP_MLP.fwd_flash)."""
        M, D = x.shape
        cap = self._cap(M)
        topk_w, topk_idx = route(x @ self.w_router, self.top_k)
        x_e, inv_slot, token = group_tokens_by_expert(
            x, topk_idx, self.num_experts, cap)
        y_parts = self._expert_mlp_sharded(x_e)       # [n, E, cap, D]
        y_sum = jnp.sum(y_parts.astype(jnp.float32), axis=0).astype(x.dtype)
        y = scatter_weighted(y_sum, inv_slot, token, topk_w,
                             M).astype(x.dtype)
        if return_stats:
            return y, self._stats(topk_idx, inv_slot, cap)
        return y

    def fwd_train(self, x):
        """Training path through framework kernels: custom-VJP
        all_gather -> route/group (XLA, differentiable) -> custom-VJP
        grouped GEMMs -> weighted scatter -> custom-VJP reduce_scatter
        (reference analog: the autograd Function over the fused MoE ops,
        function/nvidia/ep_moe_fused.py:42). x row-sharded [M/n, D] ->
        row-sharded [M/n, D]; gradients reach w_router (via the top-k
        softmax weights), w_gate_up and w_down."""
        from triton_dist_tpu.kernels.grad import (all_gather_grad,
                                                  grouped_gemm_grad,
                                                  reduce_scatter_grad)
        xg = all_gather_grad(self.mesh, self.axis)(x)
        M = xg.shape[0]
        cap = self._cap(M)
        topk_w, topk_idx = route(xg @ self.w_router, self.top_k)
        x_e, inv_slot, token = group_tokens_by_expert(
            xg, topk_idx, self.num_experts, cap)
        y_parts = self._expert_mlp_sharded(
            x_e, gemm=grouped_gemm_grad())   # [n, E, cap, D]

        # per-rank weighted combine under shard_map (Manual axes: the
        # scatter-add and its transpose stay rank-local, which
        # explicit-sharding mode cannot express for a tp-stacked vmap)
        @functools.partial(
            jax.shard_map, mesh=self.mesh,
            in_specs=(P(self.axis, None, None, None), P(None), P(None),
                      P(None, None)),
            out_specs=P(self.axis, None, None), check_vma=False)
        def scat(y_loc, inv, tok, w):
            return scatter_weighted(y_loc[0], inv, tok, w, M)[None]

        y_partial = scat(y_parts, inv_slot, token,
                         topk_w).astype(x.dtype)
        return reduce_scatter_grad(self.mesh, self.axis)(y_partial)

    def __call__(self, x, mode: str = "dist", **kw):
        """kw (`return_stats=True`) reaches the serving-reachable paths
        (xla/dist/local) — the slot-tick forwards ask for the routing
        load the telemetry gauges surface; the fused/train paths take
        no kwargs (not serving tick modes)."""
        if mode == "train":
            assert not kw, f"mode='train' takes no extra kwargs: {kw}"
            return self.fwd_train(x)
        if mode == "fused":
            assert not kw, f"mode='fused' takes no extra kwargs: {kw}"
            return self.fwd_fused(x)
        if mode == "fused_ar":
            assert not kw, f"mode='fused_ar' takes no extra kwargs: {kw}"
            return self.fwd_fused_ar(x)
        if mode in ("dist",):
            return self.fwd_dist(x, **kw)
        if mode in ("flash", "ar", "gemm_ar"):
            return self.fwd_local(x, **kw)
        return self.fwd_xla(x, **kw)
