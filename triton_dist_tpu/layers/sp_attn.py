"""Sequence-parallel attention layers: SP flash-decode and Ulysses.

TPU-native re-design of the reference SP layers
(`python/triton_dist/layers/nvidia/sp_attn.py` — the SP AG-attention
prefill wrapper and the flash-decode layer driven by
`kernels/nvidia/flash_decode.py:482`'s inter-rank combine — and the
Ulysses layer over `ulysses_sp_dispatch.py:39` /
`sp_ulysess_qkv_gemm_all2all.py:64`).

Two layers:
  - ``SPAttn``: weights replicated, activations and KV cache sharded on
    the sequence dimension. Prefill runs ring attention (KV blocks
    rotate over ICI); decode runs the distributed flash-decode with the
    one-sided LSE-combine kernel. This is the long-context serving
    layout: the cache grows with T but each chip only holds T/n of it.
  - ``UlyssesAttn``: prefill where the QKV projection is fused with the
    head-reshard a2a (each head-group GEMM tile is pushed to its owner
    as the MXU finishes it), attention runs over the full sequence on
    1/n of the heads, and the inverse a2a restores sequence sharding
    before the local O projection — no collective in the O path at all.

SERVING (ISSUE 14): the paged long-context serving path does NOT go
through these layers — it lives on TP_Attn (the weight-holding layer
the scheduler's slot forwards already drive):
``layers/tp_attn.fwd_cached_slots_paged_sp`` runs the same
split-KV-partial + inter-chip-LSE-combine math over the SP-SHARDED
PAGED pool (kv_cache.PagedSlotCache SP SHARDING, page-id space
partitioned per chip) using ``kernels/paged_kv.
flash_decode_paged_partial`` + ``kernels/sp_flash_decode.
sp_combine_partials``. These SPAttn layers remain the contiguous
whole-sequence SP reference (prefill ring attention, Ulysses) and the
kernels' first consumer.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from triton_dist_tpu.kernels.sp_attention import (qkv_gemm_a2a,
                                                  sp_ring_attention,
                                                  sp_ring_attention_ref,
                                                  ulysses_combine,
                                                  ulysses_dispatch)
from triton_dist_tpu.kernels.sp_flash_decode import (kv_cache_scatter,
                                                     sp_flash_decode)
from triton_dist_tpu.kernels.flash_attn import flash_decode
from triton_dist_tpu.layers.common import (apply_rope, rms_norm,
                                           shard_cols_packed)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class SPAttn:
    """Sequence-parallel GQA attention with a sequence-sharded KV cache.

    w_qkv: [D, (Hq + 2*Hkv) * hd] replicated (natural head order).
    w_o:   [Hq * hd, D] replicated.
    """

    w_qkv: jax.Array
    w_o: jax.Array
    q_norm: Optional[jax.Array]
    k_norm: Optional[jax.Array]
    mesh: Mesh = dataclasses.field(metadata=dict(static=True))
    axis: str = dataclasses.field(metadata=dict(static=True))
    n_heads: int = dataclasses.field(metadata=dict(static=True))
    n_kv_heads: int = dataclasses.field(metadata=dict(static=True))
    head_dim: int = dataclasses.field(metadata=dict(static=True))

    @staticmethod
    def init(w_q, w_k, w_v, w_o, *, mesh: Mesh, axis: str = "sp",
             n_heads: int, n_kv_heads: int, head_dim: int,
             q_norm=None, k_norm=None):
        w_qkv = jnp.concatenate(
            [jnp.asarray(w_q), jnp.asarray(w_k), jnp.asarray(w_v)], axis=1)
        rep = NamedSharding(mesh, P(*(None,) * 2))
        return SPAttn(
            w_qkv=jax.device_put(w_qkv, rep),
            w_o=jax.device_put(jnp.asarray(w_o), rep),
            q_norm=None if q_norm is None else jnp.asarray(q_norm),
            k_norm=None if k_norm is None else jnp.asarray(k_norm),
            mesh=mesh, axis=axis, n_heads=n_heads,
            n_kv_heads=n_kv_heads, head_dim=head_dim)

    @staticmethod
    def _split_norm(qkv, B, S, hq, hkv, hd, q_norm, k_norm):
        """Shared QKV unpack + QK-norm (norms as explicit ARGS so the
        training path's cotangents come back psum-replicated)."""
        q = qkv[..., :hq * hd].reshape(B, S, hq, hd)
        k = qkv[..., hq * hd:(hq + hkv) * hd].reshape(B, S, hkv, hd)
        v = qkv[..., (hq + hkv) * hd:].reshape(B, S, hkv, hd)
        if q_norm is not None:
            q = rms_norm(q, q_norm)
        if k_norm is not None:
            k = rms_norm(k, k_norm)
        return q, k, v

    def _split_qkv(self, qkv, B, S):
        return self._split_norm(qkv, B, S, self.n_heads, self.n_kv_heads,
                                self.head_dim, self.q_norm, self.k_norm)

    def alloc_cache(self, B: int, T: int, dtype=jnp.bfloat16):
        """Sequence-sharded KV cache: [B, Hkv, T, d], T over `axis`
        (chip r owns global positions [r*T/n, (r+1)*T/n))."""
        spec = NamedSharding(self.mesh, P(None, None, self.axis, None))
        shape = (B, self.n_kv_heads, T, self.head_dim)
        z = jnp.zeros(shape, dtype)
        return (jax.device_put(z, spec), jax.device_put(z, spec))

    def prefill(self, x, cos, sin, cache_k, cache_v, *, mode="ring"):
        """x: [B, S, D] sequence-sharded. Runs ring attention and writes
        K/V into the cache's owner windows. Returns (out seq-sharded,
        cache_k, cache_v, kv_len)."""
        B, S, D = x.shape
        n = self.mesh.shape[self.axis]
        s_loc = S // n
        axis = self.axis

        @functools.partial(jax.shard_map, mesh=self.mesh,
                           in_specs=(P(None, axis, None), P(None, None)),
                           out_specs=(P(None, axis, None, None),
                                      P(None, None, axis, None),
                                      P(None, None, axis, None)),
                           check_vma=False)
        def project(x_loc, w):
            me = jax.lax.axis_index(axis)
            qkv = x_loc @ w
            q, k, v = self._split_qkv(qkv, B, s_loc)
            pos = me * s_loc + jnp.arange(s_loc)
            q = apply_rope(q, cos, sin, pos)
            k = apply_rope(k, cos, sin, pos)
            return (q, k.transpose(0, 2, 1, 3),   # [B, Hkv, s_loc, d]
                    v.transpose(0, 2, 1, 3))

        q, k_s, v_s = project(x, self.w_qkv)
        # one-sided scatter of the s_loc blocks into the t_loc owner
        # windows: S/n bytes per link, no full gather
        cache_k = kv_cache_scatter(cache_k, k_s, mesh=self.mesh,
                                   axis=axis)
        cache_v = kv_cache_scatter(cache_v, v_s, mesh=self.mesh,
                                   axis=axis)
        out = sp_ring_attention(
            q, k_s, v_s, mesh=self.mesh, axis=axis, causal=True,
            mode=mode, out_dtype=x.dtype)
        out = out.reshape(B, S, self.n_heads * self.head_dim)
        o = _local_proj(out, self.w_o, self.mesh, axis)
        return o, cache_k, cache_v, jnp.int32(S)

    def fwd_train(self, x, cos, sin):
        """Differentiable context-parallel attention (training, no
        cache): local QKV GEMM + RoPE -> causal ring attention with the
        custom-VJP ring backward (kernels/sp_attention.py::
        sp_ring_attention_train — (k, v, dk, dv) rotate together) ->
        local O projection. x: [B, S, D] sequence-sharded -> same.
        The reference's SP mechanisms are inference-only; this extends
        them to training."""
        from triton_dist_tpu.kernels.sp_attention import (
            sp_ring_attention_train)
        B, S, D = x.shape
        n = self.mesh.shape[self.axis]
        s_loc = S // n
        axis = self.axis
        hq, hkv, hd = self.n_heads, self.n_kv_heads, self.head_dim
        norms = [a for a in (self.q_norm, self.k_norm) if a is not None]

        @functools.partial(
            jax.shard_map, mesh=self.mesh,
            in_specs=(P(None, axis, None), P(None, None), P(None, None),
                      P(None, None)) + (P(None),) * len(norms),
            out_specs=(P(None, axis, None, None),
                       P(None, None, axis, None),
                       P(None, None, axis, None)),
            check_vma=False)
        def project(x_loc, w, cos, sin, *norms):
            ni = iter(norms)
            me = jax.lax.axis_index(axis)
            qn = next(ni) if self.q_norm is not None else None
            kn = next(ni) if self.k_norm is not None else None
            q, k, v = self._split_norm(x_loc @ w, B, s_loc, hq, hkv, hd,
                                       qn, kn)
            pos = me * s_loc + jnp.arange(s_loc)
            q = apply_rope(q, cos, sin, pos)
            k = apply_rope(k, cos, sin, pos)
            return (q, k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3))

        q, k_s, v_s = project(x, self.w_qkv, cos, sin, *norms)
        out = sp_ring_attention_train(q, k_s, v_s, mesh=self.mesh,
                                      axis=axis)
        out = out.reshape(B, S, hq * hd)
        return _local_proj(out, self.w_o, self.mesh, axis)

    def decode(self, x, cos, sin, cache_k, cache_v, kv_len, *,
               combine="dist"):
        """One decode step. x: [B, 1, D] replicated; cache seq-sharded;
        kv_len: traced count of tokens already in the cache. Returns
        (out [B, 1, D] replicated, cache_k, cache_v, kv_len+1)."""
        B = x.shape[0]
        axis = self.axis
        qkv = x @ self.w_qkv             # replicated compute: tiny M
        q, k, v = self._split_qkv(qkv, B, 1)
        q = apply_rope(q, cos, sin, kv_len[None])
        k = apply_rope(k, cos, sin, kv_len[None])
        # [B, 1, Hkv, d] -> the cache's [B, Hkv, 1, d] layout
        cache_k = _write_token(cache_k, k.transpose(0, 2, 1, 3), kv_len,
                               self.mesh, axis)
        cache_v = _write_token(cache_v, v.transpose(0, 2, 1, 3), kv_len,
                               self.mesh, axis)
        out = sp_flash_decode(q, cache_k, cache_v, kv_len + 1,
                              mesh=self.mesh, axis=axis, combine=combine,
                              out_dtype=x.dtype)
        out = out.reshape(B, 1, self.n_heads * self.head_dim)
        return out @ self.w_o, cache_k, cache_v, kv_len + 1


def _write_token(cache, kv_new, pos, mesh, axis):
    """Scatter one token's K/V [B, Hkv, 1, d] into the owner chip's
    window at global position `pos` (traced)."""
    n = mesh.shape[axis]
    T = cache.shape[2]
    t_loc = T // n

    @functools.partial(jax.shard_map, mesh=mesh,
                       in_specs=(P(None, None, axis, None),
                                 P(None, None, None, None), P()),
                       out_specs=P(None, None, axis, None),
                       check_vma=False)
    def _f(c_loc, new, p):
        me = jax.lax.axis_index(axis)
        local = p - me * t_loc
        idx = jnp.clip(local, 0, t_loc - 1)
        updated = jax.lax.dynamic_update_slice_in_dim(
            c_loc, new.astype(c_loc.dtype), idx, axis=2)
        mine = (local >= 0) & (local < t_loc)
        return jnp.where(mine, updated, c_loc)

    return _f(cache, kv_new, jnp.asarray(pos, jnp.int32))


def _local_proj(x, w, mesh, axis):
    """Seq-sharded local GEMM (replicated weight, zero collectives —
    the SP payoff: the reduction dim is intact). Used for both the QKV
    and O projections."""
    @functools.partial(jax.shard_map, mesh=mesh,
                       in_specs=(P(None, axis, None), P(None, None)),
                       out_specs=P(None, axis, None), check_vma=False)
    def _f(x_loc, w):
        return x_loc @ w

    return _f(x, w)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class UlyssesAttn:
    """Ulysses SP prefill: a2a head-reshard fused with the QKV GEMM.

    w_qkv: [D, n * (hq_loc + 2*hkv_loc) * hd] — head-GROUP-major packed
    (chunk j = [q grp j | k grp j | v grp j]), so the fused GEMM+a2a can
    push chunk j straight to chip j.
    w_o: [Hq * hd, D] replicated (the O path has no collective).
    """

    w_qkv: jax.Array
    w_o: jax.Array
    q_norm: Optional[jax.Array]
    k_norm: Optional[jax.Array]
    mesh: Mesh = dataclasses.field(metadata=dict(static=True))
    axis: str = dataclasses.field(metadata=dict(static=True))
    n_heads: int = dataclasses.field(metadata=dict(static=True))
    n_kv_heads: int = dataclasses.field(metadata=dict(static=True))
    head_dim: int = dataclasses.field(metadata=dict(static=True))

    @staticmethod
    def init(w_q, w_k, w_v, w_o, *, mesh: Mesh, axis: str = "sp",
             n_heads: int, n_kv_heads: int, head_dim: int,
             q_norm=None, k_norm=None):
        n = mesh.shape[axis]
        packed = shard_cols_packed([w_q, w_k, w_v], n)
        rep = NamedSharding(mesh, P(*(None,) * 2))
        return UlyssesAttn(
            w_qkv=jax.device_put(packed, rep),
            w_o=jax.device_put(jnp.asarray(w_o), rep),
            q_norm=None if q_norm is None else jnp.asarray(q_norm),
            k_norm=None if k_norm is None else jnp.asarray(k_norm),
            mesh=mesh, axis=axis, n_heads=n_heads,
            n_kv_heads=n_kv_heads, head_dim=head_dim)

    def prefill(self, x, cos, sin, *, mode: str = "fused"):
        """x: [B, S, D] sequence-sharded -> [B, S, D] sequence-sharded.

        mode="fused":   qkv_gemm_a2a (GEMM tiles pushed per head group)
        mode="unfused": local GEMM then ulysses_dispatch a2a
        mode="xla":     replicated-einsum oracle
        """
        B, S, D = x.shape
        n = self.mesh.shape[self.axis]
        hq_loc = self.n_heads // n
        hkv_loc = self.n_kv_heads // n
        hd = self.head_dim
        axis = self.axis
        C = (hq_loc + 2 * hkv_loc) * hd

        if mode == "xla":
            return self._oracle(x, cos, sin)

        if mode == "fused":
            qkv = qkv_gemm_a2a(x, self.w_qkv, mesh=self.mesh, axis=axis)
        else:
            qkv_seq = _local_proj(x, self.w_qkv, self.mesh,
                                  axis)     # [B, S, n*C] seq-sharded
            # dispatch on a head-like trailing dim: n chunks ("heads")
            # of width C, keeping a full C-wide lane dim for the DMAs
            qkv = ulysses_dispatch(
                qkv_seq.reshape(B, S, n, C), mesh=self.mesh,
                axis=axis).reshape(B, S, n * C)

        # head-sharded full-seq attention
        @functools.partial(jax.shard_map, mesh=self.mesh,
                           in_specs=P(None, None, axis),
                           out_specs=P(None, None, axis, None),
                           check_vma=False)
        def attend(qkv_loc):
            q, k, v = self._unpack_norm_rope(
                qkv_loc, B, S, hq_loc, hkv_loc, hd, self.q_norm,
                self.k_norm, cos, sin)
            return flash_decode(q, k, v, jnp.int32(S))

        o = attend(qkv)                      # [B, S, Hq, d] head-sharded
        if mode == "fused":
            # combine-direction fusion: the O projection consumes each
            # peer's seq-block tile as it lands (o_a2a_gemm; reference
            # sp_ulysess_o_all2all_gemm.py:147) — both a2a directions
            # are now fused with their adjacent GEMMs
            from triton_dist_tpu.kernels.sp_attention import o_a2a_gemm
            o = o.reshape(B, S, hq_loc * hd * n)   # head-sharded dim 2
            return o_a2a_gemm(o, self.w_o, mesh=self.mesh, axis=axis)
        o = ulysses_combine(o, mesh=self.mesh, axis=axis)
        o = o.reshape(B, S, self.n_heads * hd)
        return _local_proj(o, self.w_o, self.mesh, axis)

    @staticmethod
    def _unpack_norm_rope(qkv_loc, B, S, hq_loc, hkv_loc, hd,
                          q_norm, k_norm, cos, sin):
        """Shared per-rank QKV unpack + QK-norm + RoPE for prefill AND
        fwd_train: q [B, S, hq_loc, hd]; k, v in the cache layout
        [B, hkv_loc, S, hd]."""
        q = qkv_loc[..., :hq_loc * hd].reshape(B, S, hq_loc, hd)
        k = (qkv_loc[..., hq_loc * hd:(hq_loc + hkv_loc) * hd]
             .reshape(B, S, hkv_loc, hd))
        v = (qkv_loc[..., (hq_loc + hkv_loc) * hd:]
             .reshape(B, S, hkv_loc, hd))
        if q_norm is not None:
            q = rms_norm(q, q_norm)
        if k_norm is not None:
            k = rms_norm(k, k_norm)
        pos = jnp.arange(S)
        q = apply_rope(q, cos, sin, pos)
        k = apply_rope(k, cos, sin, pos)
        return q, k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3)

    def fwd_train(self, x, cos, sin):
        """Differentiable Ulysses SP attention (training): local QKV
        GEMM -> custom-VJP dispatch a2a (adjoint = the combine kernel)
        -> differentiable Pallas flash attention on this chip's heads
        over the full sequence -> custom-VJP combine a2a -> local O
        projection. x: [B, S, D] sequence-sharded -> same sharding.
        Reference analog: training through the Ulysses SP dispatch under
        autograd (ulysses_sp_dispatch.py:39 + torch.autograd)."""
        from triton_dist_tpu.kernels.flash_attn_train import flash_attention
        from triton_dist_tpu.kernels.grad import (ulysses_combine_grad,
                                                  ulysses_dispatch_grad)
        B, S, D = x.shape
        n = self.mesh.shape[self.axis]
        hq_loc = self.n_heads // n
        hkv_loc = self.n_kv_heads // n
        hd = self.head_dim
        axis = self.axis
        C = (hq_loc + 2 * hkv_loc) * hd

        qkv_seq = _local_proj(x, self.w_qkv, self.mesh,
                              axis)         # [B, S, n*C] seq-sharded
        qkv = ulysses_dispatch_grad(self.mesh, axis)(
            qkv_seq.reshape(B, S, n, C)).reshape(B, S, n * C)

        norms = [a for a in (self.q_norm, self.k_norm) if a is not None]

        @functools.partial(
            jax.shard_map, mesh=self.mesh,
            in_specs=(P(None, None, axis), P(None, None), P(None, None))
                     + (P(None),) * len(norms),
            out_specs=P(None, None, axis, None), check_vma=False)
        def attend(qkv_loc, cos, sin, *norms):
            # norms as shard_map ARGS (not closures): Explicit-sharded
            # cotangents must come back psum-replicated
            ni = iter(norms)
            qn = next(ni) if self.q_norm is not None else None
            kn = next(ni) if self.k_norm is not None else None
            q, k, v = self._unpack_norm_rope(
                qkv_loc, B, S, hq_loc, hkv_loc, hd, qn, kn, cos, sin)
            return flash_attention(q, k, v)

        o = attend(qkv, cos, sin, *norms)    # [B, S, Hq, d] head-sharded
        o = ulysses_combine_grad(self.mesh, axis)(o)
        o = o.reshape(B, S, self.n_heads * hd)
        return _local_proj(o, self.w_o, self.mesh, axis)

    def _oracle(self, x, cos, sin):
        """Replicated jnp oracle with identical weight unpacking."""
        B, S, D = x.shape
        n = self.mesh.shape[self.axis]
        hq_loc = self.n_heads // n
        hkv_loc = self.n_kv_heads // n
        hd = self.head_dim
        C = (hq_loc + 2 * hkv_loc) * hd
        w = self.w_qkv.reshape(D, n, C)
        wq = w[:, :, :hq_loc * hd].reshape(D, n * hq_loc * hd)
        wk = (w[:, :, hq_loc * hd:(hq_loc + hkv_loc) * hd]
              .reshape(D, n * hkv_loc * hd))
        wv = (w[:, :, (hq_loc + hkv_loc) * hd:]
              .reshape(D, n * hkv_loc * hd))
        xr = jax.reshard(x, NamedSharding(self.mesh, P(None, None, None)))
        q = (xr @ wq).reshape(B, S, self.n_heads, hd)
        k = (xr @ wk).reshape(B, S, self.n_kv_heads, hd)
        v = (xr @ wv).reshape(B, S, self.n_kv_heads, hd)
        if self.q_norm is not None:
            q = rms_norm(q, self.q_norm)
        if self.k_norm is not None:
            k = rms_norm(k, self.k_norm)
        pos = jnp.arange(S)
        q = apply_rope(q, cos, sin, pos)
        k = apply_rope(k, cos, sin, pos)
        o = sp_ring_attention_ref(q, k.transpose(0, 2, 1, 3),
                                  v.transpose(0, 2, 1, 3), causal=True)
        o = o.reshape(B, S, self.n_heads * hd) @ self.w_o
        return jax.reshard(
            o, NamedSharding(self.mesh, P(None, self.axis, None)))
