"""Pipeline-parallel comm layer and schedule.

TPU-native re-design of the reference PP layer
(`python/triton_dist/layers/nvidia/pp_block.py`: `PPCommLayer` :102 —
p2p send/recv of activations between consecutive stages — and the
microbatch schedule it drives). On TPU the stages are the `pp` axis of
the device mesh; every stage holds its block's parameters (stacked
leaves sharded on dim 0) and the handoff is the one-sided p2p shift
kernel. The schedule is GPipe-style: with M microbatches and n stages
the loop runs M + n - 1 ticks; at tick t stage s works on microbatch
t - s (bubble ticks compute on garbage and are masked at the edges —
the SPMD-uniform formulation, same shape as the reference's per-rank
send/recv ordering but without any rank-divergent control flow).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from triton_dist_tpu.kernels.p2p import _p2p_pallas
from triton_dist_tpu.runtime import next_collective_id


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class PPipeline:
    """A pipeline of n identical-shaped stages.

    stage_params: a pytree whose leaves are stacked [n_stages, ...] and
    sharded on dim 0 over `axis`; stage_fn(params_slice, x) -> y is the
    per-stage compute (params_slice has the stacked dim removed).
    """

    stage_params: object
    mesh: Mesh = dataclasses.field(metadata=dict(static=True))
    axis: str = dataclasses.field(metadata=dict(static=True))
    stage_fn: Callable = dataclasses.field(metadata=dict(static=True))

    @staticmethod
    def init(stage_params, stage_fn, *, mesh: Mesh, axis: str = "pp"):
        def put(leaf):
            leaf = jnp.asarray(leaf)
            spec = P(axis, *(None,) * (leaf.ndim - 1))
            return jax.device_put(leaf, NamedSharding(mesh, spec))

        return PPipeline(stage_params=jax.tree.map(put, stage_params),
                         mesh=mesh, axis=axis, stage_fn=stage_fn)

    def __call__(self, x_mb, replicate_out: bool = True):
        """x_mb: [M, B, D] microbatches, replicated. Returns [M, B, D]:
        each microbatch passed through all n stages in order.

        replicate_out=True (default) replicates the output stack to
        every stage with ONE psum over the pp axis per call (a ring
        all-reduce: ~2(n-1)/n of the stack's bytes per device — n-1
        stages contribute zero stacks, the price of the SPMD-uniform
        formulation). replicate_out=False skips the collective
        entirely and returns the per-stage banks as an HONESTLY-sharded
        [n_stages, M, B, D] array (P(pp) on dim 0): only index n-1
        holds data; `out[-1]` materializes it where consumed, so a
        consumer living on the last stage pays zero comm."""
        n = self.mesh.shape[self.axis]
        M, B, D = x_mb.shape
        axis = self.axis
        fn = self.stage_fn
        cid = next_collective_id()

        p_specs = jax.tree.map(
            lambda l: P(axis, *(None,) * (l.ndim - 1)), self.stage_params)

        @functools.partial(
            jax.shard_map, mesh=self.mesh,
            in_specs=(p_specs, P(*(None,) * 3)),
            out_specs=(P(*(None,) * 3) if replicate_out
                       else P(axis, *(None,) * 3)), check_vma=False)
        def run(params_loc, mb):
            me = jax.lax.axis_index(axis)
            params = jax.tree.map(lambda l: l[0], params_loc)

            def tick(t, carry):
                reg, outs = carry
                # stage 0 swaps in microbatch t (clamped; bubble ticks
                # at t >= M re-feed the last mb and are masked below)
                inject = jax.lax.dynamic_index_in_dim(
                    mb, jnp.clip(t, 0, M - 1), keepdims=False)
                cur = jnp.where(me == 0, inject, reg)
                y = fn(params, cur)
                # last stage banks microbatch t-(n-1); other stages'
                # contribution is masked out by the psum of a zero
                out_slot = jnp.clip(t - (n - 1), 0, M - 1)
                bank = jnp.where((me == n - 1) & (t >= n - 1),
                                 y, jnp.zeros_like(y))
                outs = jax.lax.dynamic_update_index_in_dim(
                    outs, outs[out_slot] + bank, out_slot, axis=0)
                # handoff: stage s's y becomes stage s+1's register
                reg = _p2p_pallas(y.reshape(-1, y.shape[-1]), n=n,
                                  axis=axis, reverse=False,
                                  collective_id=cid).reshape(y.shape)
                return reg, outs

            outs0 = jnp.zeros((M, B, D), x_mb.dtype)
            reg0 = jnp.zeros((B, D), x_mb.dtype)
            _, outs = jax.lax.fori_loop(0, M + n - 1, tick, (reg0, outs0))
            if not replicate_out:
                return outs[None]     # -> [n, M, B, D] sharded on pp
            # only the last stage banked non-zeros; psum replicates its
            # values to every stage (the out spec says replicated)
            return jax.lax.psum(outs, axis)

        return run(self.stage_params, x_mb)
