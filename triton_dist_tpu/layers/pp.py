"""Pipeline-parallel comm layer and schedule.

TPU-native re-design of the reference PP layer
(`python/triton_dist/layers/nvidia/pp_block.py`: `PPCommLayer` :102 —
p2p send/recv of activations between consecutive stages — and the
microbatch schedule it drives). On TPU the stages are the `pp` axis of
the device mesh; every stage holds its block's parameters (stacked
leaves sharded on dim 0) and the handoff is the one-sided p2p shift
kernel. The schedule is GPipe-style: with M microbatches and n stages
the loop runs M + n - 1 ticks; at tick t stage s works on microbatch
t - s (bubble ticks compute on garbage and are masked at the edges —
the SPMD-uniform formulation, same shape as the reference's per-rank
send/recv ordering but without any rank-divergent control flow).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from triton_dist_tpu.kernels.p2p import _p2p_pallas
from triton_dist_tpu.runtime import next_collective_id


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class PPipeline:
    """A pipeline of n identical-shaped stages.

    stage_params: a pytree whose leaves are stacked [n_stages, ...] and
    sharded on dim 0 over `axis`; stage_fn(params_slice, x) -> y is the
    per-stage compute (params_slice has the stacked dim removed).
    """

    stage_params: object
    mesh: Mesh = dataclasses.field(metadata=dict(static=True))
    axis: str = dataclasses.field(metadata=dict(static=True))
    stage_fn: Callable = dataclasses.field(metadata=dict(static=True))

    @staticmethod
    def init(stage_params, stage_fn, *, mesh: Mesh, axis: str = "pp"):
        def put(leaf):
            leaf = jnp.asarray(leaf)
            spec = P(axis, *(None,) * (leaf.ndim - 1))
            return jax.device_put(leaf, NamedSharding(mesh, spec))

        return PPipeline(stage_params=jax.tree.map(put, stage_params),
                         mesh=mesh, axis=axis, stage_fn=stage_fn)

    def _p_specs(self):
        return jax.tree.map(
            lambda l: P(self.axis, *(None,) * (l.ndim - 1)),
            self.stage_params)

    def __call__(self, x_mb, replicate_out: bool = True):
        """x_mb: [M, B, D] microbatches, replicated. Returns [M, B, D]:
        each microbatch passed through all n stages in order.

        replicate_out=True (default) replicates the output stack to
        every stage with ONE psum over the pp axis per call (a ring
        all-reduce: ~2(n-1)/n of the stack's bytes per device — n-1
        stages contribute zero stacks, the price of the SPMD-uniform
        formulation). replicate_out=False skips the collective
        entirely and returns the per-stage banks as an HONESTLY-sharded
        [n_stages, M, B, D] array (P(pp) on dim 0): only index n-1
        holds data; `out[-1]` materializes it where consumed, so a
        consumer living on the last stage pays zero comm."""
        n = self.mesh.shape[self.axis]
        M, B, D = x_mb.shape
        axis = self.axis
        fn = self.stage_fn
        cid = next_collective_id()

        p_specs = self._p_specs()

        @functools.partial(
            jax.shard_map, mesh=self.mesh,
            in_specs=(p_specs, P(*(None,) * 3)),
            out_specs=(P(*(None,) * 3) if replicate_out
                       else P(axis, *(None,) * 3)), check_vma=False)
        def run(params_loc, mb):
            me = jax.lax.axis_index(axis)
            params = jax.tree.map(lambda l: l[0], params_loc)

            def tick(t, carry):
                reg, outs = carry
                # stage 0 swaps in microbatch t (clamped; bubble ticks
                # at t >= M re-feed the last mb and are masked below)
                inject = jax.lax.dynamic_index_in_dim(
                    mb, jnp.clip(t, 0, M - 1), keepdims=False)
                cur = jnp.where(me == 0, inject, reg)
                y = fn(params, cur)
                # last stage banks microbatch t-(n-1); other stages'
                # contribution is masked out by the psum of a zero
                out_slot = jnp.clip(t - (n - 1), 0, M - 1)
                bank = jnp.where((me == n - 1) & (t >= n - 1),
                                 y, jnp.zeros_like(y))
                outs = jax.lax.dynamic_update_index_in_dim(
                    outs, outs[out_slot] + bank, out_slot, axis=0)
                # handoff: stage s's y becomes stage s+1's register
                reg = _p2p_pallas(y.reshape(-1, y.shape[-1]), n=n,
                                  axis=axis, reverse=False,
                                  collective_id=cid).reshape(y.shape)
                return reg, outs

            outs0 = jnp.zeros((M, B, D), x_mb.dtype)
            reg0 = jnp.zeros((B, D), x_mb.dtype)
            _, outs = jax.lax.fori_loop(0, M + n - 1, tick, (reg0, outs0))
            if not replicate_out:
                return outs[None]     # -> [n, M, B, D] sharded on pp
            # only the last stage banked non-zeros; psum replicates its
            # values to every stage (the out spec says replicated)
            return jax.lax.psum(outs, axis)

        return run(self.stage_params, x_mb)


def _zeros_like_tree(t):
    return jax.tree.map(jnp.zeros_like, t)


def train_1f1b(pipe: PPipeline, x_mb, g_mb):
    """1F1B pipeline training pass (VERDICT r4 next #8; reference: the
    microbatch schedule the PP comm layer drives, pp_block.py:102-245).

    x_mb: [M, B, D] microbatch inputs (replicated); g_mb: [M, B, D]
    cotangents of the pipeline outputs. Returns
    (y_mb [M, B, D], dx_mb [M, B, D], dparams stacked like
    stage_params, stats) where stats["work"] is the per-stage
    [n, 2] (fwd, bwd) tick-occupancy counts the schedule tests assert
    on, and stats["slots"] / stats["ticks"] document the memory/time
    shape of the schedule.

    Schedule (SPMD-uniform; every tick runs one fwd sub-step and one
    bwd sub-step per stage, each skipped via lax.cond on bubble
    ticks so garbage is neither computed nor banked):
      fwd:  stage s works on microbatch  t - s
      bwd:  stage s works on microbatch  t - 2(n-1) + s
      T  =  M + 2(n-1) ticks.
    The backward recomputes the stage forward from the SAVED INPUT
    (rematerialized PP — the standard memory/compute trade), so each
    stage stores only its in-flight inputs: at stage s at most
    2(n-1-s)+1 microbatches are live, so the activation buffer has
    min(M, 2n) slots — the 1F1B property (O(n) activation memory,
    independent of M; GPipe's fwd-then-bwd stores all M).
    Grads of the outputs enter at the last stage exactly on the tick
    its fwd of the same microbatch runs; activations shift forward and
    grad cotangents shift backward by one stage per tick (reverse
    p2p), so both handoffs are single-register."""
    n = pipe.mesh.shape[pipe.axis]
    M, B, D = x_mb.shape
    axis = pipe.axis
    fn = pipe.stage_fn
    cid_f = next_collective_id()
    cid_b = next_collective_id()
    S = min(M, 2 * n)
    T = M + 2 * (n - 1)
    p_specs = pipe._p_specs()

    @functools.partial(
        jax.shard_map, mesh=pipe.mesh,
        in_specs=(p_specs, P(*(None,) * 3), P(*(None,) * 3)),
        out_specs=(P(*(None,) * 3), P(*(None,) * 3), p_specs,
                   P(axis, None)),
        check_vma=False)
    def run(params_loc, mb, gmb):
        me = jax.lax.axis_index(axis)
        params = jax.tree.map(lambda l: l[0], params_loc)

        def bwd_op(args):
            x_s, g = args
            _, vjp = jax.vjp(lambda p, x: fn(p, x), params, x_s)
            return vjp(g)

        def bwd_zero(args):
            return (_zeros_like_tree(params),
                    jnp.zeros((B, D), x_mb.dtype))

        def tick(t, carry):
            freg, breg, abuf, outs, dxs, dps, fcnt, bcnt = carry
            # ---- fwd sub-step: stage s, microbatch t - s
            m_f = t - me
            fv = (m_f >= 0) & (m_f < M)
            mf_c = jnp.clip(m_f, 0, M - 1)
            inject = jax.lax.dynamic_index_in_dim(mb, mf_c,
                                                  keepdims=False)
            x_in = jnp.where(me == 0, inject, freg)
            slot_f = jax.lax.rem(mf_c, S)
            abuf = jax.lax.dynamic_update_index_in_dim(
                abuf, jnp.where(fv, x_in, abuf[slot_f]), slot_f, axis=0)
            y = jax.lax.cond(
                fv, lambda x: fn(params, x),
                lambda x: jnp.zeros((B, D), x_mb.dtype), x_in)
            bank = jnp.where((me == n - 1) & fv, y, jnp.zeros_like(y))
            outs = jax.lax.dynamic_update_index_in_dim(
                outs, outs[mf_c] + bank, mf_c, axis=0)
            # ---- bwd sub-step: stage s, microbatch t - 2(n-1) + s
            m_b = t - 2 * (n - 1) + me
            bv = (m_b >= 0) & (m_b < M)
            mb_c = jnp.clip(m_b, 0, M - 1)
            x_saved = abuf[jax.lax.rem(mb_c, S)]
            g_inj = jax.lax.dynamic_index_in_dim(gmb, mb_c,
                                                 keepdims=False)
            g_in = jnp.where(me == n - 1, g_inj, breg)
            dp, dx = jax.lax.cond(bv, bwd_op, bwd_zero,
                                  (x_saved, g_in))
            dps = jax.tree.map(lambda a, b: a + b, dps, dp)
            dbank = jnp.where((me == 0) & bv, dx, jnp.zeros_like(dx))
            dxs = jax.lax.dynamic_update_index_in_dim(
                dxs, dxs[mb_c] + dbank, mb_c, axis=0)
            fcnt = fcnt + fv.astype(jnp.int32)
            bcnt = bcnt + bv.astype(jnp.int32)
            # ---- handoffs: activations forward, cotangents backward
            # (uniform collectives every tick; bubble payloads are
            # zeros, ignored at the consume masks above)
            freg = _p2p_pallas(y.reshape(-1, D), n=n, axis=axis,
                               reverse=False,
                               collective_id=cid_f).reshape(B, D)
            breg = _p2p_pallas(dx.reshape(-1, D), n=n, axis=axis,
                               reverse=True,
                               collective_id=cid_b).reshape(B, D)
            return freg, breg, abuf, outs, dxs, dps, fcnt, bcnt

        z = jnp.zeros((B, D), x_mb.dtype)
        init = (z, z, jnp.zeros((S, B, D), x_mb.dtype),
                jnp.zeros((M, B, D), x_mb.dtype),
                jnp.zeros((M, B, D), x_mb.dtype),
                _zeros_like_tree(params),
                jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32))
        _, _, _, outs, dxs, dps, fcnt, bcnt = jax.lax.fori_loop(
            0, T, tick, init)
        outs = jax.lax.psum(outs, axis)    # only the last stage banked
        dxs = jax.lax.psum(dxs, axis)      # only stage 0 banked
        dps = jax.tree.map(lambda l: l[None], dps)   # -> stacked [n,..]
        work = jnp.stack([fcnt, bcnt])[None]         # -> [n, 2]
        return outs, dxs, dps, work

    y, dx, dparams, work = run(pipe.stage_params, x_mb, g_mb)
    return y, dx, dparams, {"work": work, "slots": S, "ticks": T}
