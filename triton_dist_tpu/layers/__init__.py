"""Layers (reference analog: python/triton_dist/layers/nvidia/,
SURVEY.md §2.4): TP building blocks over the overlapped kernel library,
with the reference's forward-mode switch (xla oracle / overlapped dist /
AR / fused GEMM-AR)."""

from triton_dist_tpu.layers.common import (  # noqa: F401
    rms_norm,
    precompute_rope,
    apply_rope,
    shard_cols_packed,
)
from triton_dist_tpu.layers.tp_mlp import TP_MLP  # noqa: F401
from triton_dist_tpu.layers.tp_attn import TP_Attn  # noqa: F401
from triton_dist_tpu.layers.tp_moe import TP_MoE  # noqa: F401
from triton_dist_tpu.layers.ep_moe import EP_MoE  # noqa: F401
from triton_dist_tpu.layers.sp_attn import (  # noqa: F401
    SPAttn,
    UlyssesAttn,
)
from triton_dist_tpu.layers.pp import PPipeline, train_1f1b  # noqa: F401
