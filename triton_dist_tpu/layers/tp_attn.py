"""Tensor-parallel attention (GQA) with the reference's mode switch.

TPU-native re-design of `python/triton_dist/layers/nvidia/tp_attn.py`
(`TP_Attn:80` — QKV AG-GEMM, flash attention, O-proj GEMM-RS :213; AR and
GEMM-AR variants :251-318; RoPE :165).

Head-parallel TP: each rank owns Hq/n query heads and Hkv/n KV heads.
The QKV projection is ONE ag_gemm over a packed [q_r | k_r | v_r] weight
(every rank's output slice is self-contained), attention runs locally on
the rank's heads over the full (gathered) sequence, and the O projection
reduces+scatters back to sequence sharding.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from triton_dist_tpu.kernels import (ag_gemm, all_reduce,
                                     create_ag_gemm_context,
                                     create_gemm_ar_context,
                                     create_gemm_rs_context, gemm_allreduce,
                                     gemm_rs)
from triton_dist_tpu.layers.common import (apply_rope, apply_rope_slots,
                                           rms_norm, shard_cols_packed)


def causal_attention(q, k, v, scale: float):
    """Causal GQA attention, one device's heads, full sequence.
    q: [S, Hq, d]; k, v: [T, Hkv, d] with T >= S (suffix alignment:
    query i attends to keys <= T - S + i). f32 softmax."""
    S, Hq, d = q.shape
    T, Hkv, _ = k.shape
    rep = Hq // Hkv
    if rep > 1:
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    logits = jnp.einsum("shd,thd->hst", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    qi = jax.lax.broadcasted_iota(jnp.int32, (S, T), 0)
    ki = jax.lax.broadcasted_iota(jnp.int32, (S, T), 1)
    mask = ki <= (qi + (T - S))
    logits = jnp.where(mask[None], logits, -jnp.inf)
    p = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("hst,thd->shd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TP_Attn:
    """Weights (pytree leaves) + static head/TP config.

    w_qkv: [D, (Hq + 2*Hkv) * hd] — n per-rank blocks [q_r | k_r | v_r].
    w_o:   [Hq * hd, D] — row-parallel.
    q_norm/k_norm: per-head-dim RMSNorm weights (Qwen3 QK-norm).
    """

    w_qkv: jax.Array
    w_o: jax.Array
    q_norm: Optional[jax.Array]
    k_norm: Optional[jax.Array]
    mesh: Mesh = dataclasses.field(metadata=dict(static=True))
    axis: str = dataclasses.field(metadata=dict(static=True))
    n_heads: int = dataclasses.field(metadata=dict(static=True))
    n_kv_heads: int = dataclasses.field(metadata=dict(static=True))
    head_dim: int = dataclasses.field(metadata=dict(static=True))

    @staticmethod
    def init(w_q, w_k, w_v, w_o, *, mesh: Mesh, axis: str = "tp",
             n_heads: int, n_kv_heads: int, head_dim: int,
             q_norm=None, k_norm=None):
        n = mesh.shape[axis]
        packed = shard_cols_packed([w_q, w_k, w_v], n)
        packed = jax.device_put(packed, NamedSharding(mesh, P(None, axis)))
        w_o = jax.device_put(jnp.asarray(w_o),
                             NamedSharding(mesh, P(axis, None)))
        return TP_Attn(w_qkv=packed, w_o=w_o,
                       q_norm=None if q_norm is None else jnp.asarray(q_norm),
                       k_norm=None if k_norm is None else jnp.asarray(k_norm),
                       mesh=mesh, axis=axis, n_heads=n_heads,
                       n_kv_heads=n_kv_heads, head_dim=head_dim)

    # per-rank sizes
    @property
    def _hq_loc(self):
        return self.n_heads // self.mesh.shape[self.axis]

    @property
    def _hkv_loc(self):
        return self.n_kv_heads // self.mesh.shape[self.axis]

    def _local_attn(self, qkv, cos, sin, positions, impl: str = "flash"):
        """Split a rank's packed [q|k|v] slice, QK-norm + RoPE, causal
        attention over the rank's heads (ref: tp_attn.py:165-213).

        impl="flash" runs the differentiable Pallas flash kernel
        (kernels/flash_attn_train.py) — training through the framework
        kernel, the role the reference's autograd-wrapped flash attention
        plays; impl="ref" is the jnp full-softmax oracle."""
        from triton_dist_tpu.kernels.flash_attn_train import flash_attention
        hq, hkv, hd = self._hq_loc, self._hkv_loc, self.head_dim
        scale = hd ** -0.5
        impl = self._flash_or_ref(impl, qkv.shape[0], hq // hkv, hd,
                                  qkv.dtype)

        @functools.partial(jax.shard_map, mesh=self.mesh,
                           in_specs=P(None, self.axis),
                           out_specs=P(None, self.axis), check_vma=False)
        def f(qkv_loc):
            S = qkv_loc.shape[0]
            q = qkv_loc[:, :hq * hd].reshape(S, hq, hd)
            k = qkv_loc[:, hq * hd:(hq + hkv) * hd].reshape(S, hkv, hd)
            v = qkv_loc[:, (hq + hkv) * hd:].reshape(S, hkv, hd)
            if self.q_norm is not None:
                q = rms_norm(q, self.q_norm)
            if self.k_norm is not None:
                k = rms_norm(k, self.k_norm)
            q = apply_rope(q, cos, sin, positions)
            k = apply_rope(k, cos, sin, positions)
            if impl == "flash":
                o = flash_attention(q[None], k.transpose(1, 0, 2)[None],
                                    v.transpose(1, 0, 2)[None],
                                    scale=scale)[0]
            else:
                o = causal_attention(q, k, v, scale)
            return o.reshape(S, hq * hd)

        return f(qkv)

    def fwd_xla(self, x, cos, sin, positions):
        """Pure-XLA oracle (reference: torch_fwd): jnp + XLA psum
        collective — the torch/NCCL role from the reference. QuantW
        weights dequant via qmm."""
        from triton_dist_tpu.kernels.quant import QuantW, qmm, qspec
        if isinstance(self.w_qkv, QuantW):
            @functools.partial(
                jax.shard_map, mesh=self.mesh,
                in_specs=(P(None, None),
                          qspec(self.w_qkv, P(None, self.axis),
                                P(self.axis))),
                out_specs=P(None, self.axis), check_vma=False)
            def up(x_r, w_loc):
                return qmm(x_r, w_loc)

            qkv = up(x, self.w_qkv)
        else:
            qkv = x @ self.w_qkv
        o = self._local_attn(qkv, cos, sin, positions, impl="ref")
        return self._down_psum(o)

    def _down_psum(self, o):
        """Partial O-projection + psum epilogue (the oracle down-proj;
        w_o may be int8-quantized — the flash decode path)."""
        from triton_dist_tpu.kernels.quant import qmm, qspec

        @functools.partial(jax.shard_map, mesh=self.mesh,
                           in_specs=(P(None, self.axis),
                                     qspec(self.w_o, P(self.axis, None),
                                           P(None))),
                           out_specs=P(None, None), check_vma=False)
        def down(o_loc, wo_loc):
            return jax.lax.psum(qmm(o_loc, wo_loc), self.axis)

        return down(o, self.w_o)

    @staticmethod
    def _flash_or_ref(impl: str, S: int, rep: int, hd: int, dtype) -> str:
        """Static guard: the flash forward keeps one query CHUNK
        (query_chunk rows) of a batch block resident in VMEM; fall back
        to the jnp path when even that does not fit, rather than failing
        inside pallas_call."""
        if impl != "flash":
            return impl
        from triton_dist_tpu.kernels.flash_attn import _pick_bx
        from triton_dist_tpu.kernels.flash_attn_train import (
            DEFAULT_BLOCK_R, DEFAULT_BLOCK_T, _pick_bx_bwd, query_chunk)
        try:
            _pick_bx(1, query_chunk(S, rep, DEFAULT_BLOCK_R) * rep, hd,
                     min(DEFAULT_BLOCK_T, S), jnp.dtype(dtype).itemsize, 1)
            # the backward allocates its own (larger) footprint: probe it
            # with the same default blocks so jax.grad falls back to the
            # ref path instead of raising at trace time
            _pick_bx_bwd(1, min(DEFAULT_BLOCK_R, S * rep),
                         min(DEFAULT_BLOCK_T, S), hd,
                         jnp.dtype(dtype).itemsize)
            return "flash"
        except ValueError:
            return "ref"

    def _local_attn_train(self, qkv, cos, sin, batch: int,
                          impl: str = "flash"):
        """Batched full-causal attention for training: each of `batch`
        sequences of length M//batch attends within itself.
        impl="flash" = the differentiable Pallas kernel; "ref" = the jnp
        oracle (flash_attention_ref)."""
        from triton_dist_tpu.kernels.flash_attn_train import (
            flash_attention, flash_attention_ref)
        hq, hkv, hd = self._hq_loc, self._hkv_loc, self.head_dim
        scale = hd ** -0.5
        impl = self._flash_or_ref(impl, qkv.shape[0] // batch, hq // hkv,
                                  hd, qkv.dtype)
        attend = flash_attention if impl == "flash" else flash_attention_ref
        # every trainable (or potentially updated) array must be a
        # shard_map ARGUMENT, not a closure: closures over
        # Explicit-sharded arrays are rejected, and the q/k-norm
        # cotangents must come back psum-replicated
        norms = [a for a in (self.q_norm, self.k_norm) if a is not None]

        @functools.partial(
            jax.shard_map, mesh=self.mesh,
            in_specs=(P(None, self.axis), P(None, None), P(None, None))
                     + (P(None),) * len(norms),
            out_specs=P(None, self.axis), check_vma=False)
        def f(qkv_loc, cos, sin, *norms):
            ni = iter(norms)
            M = qkv_loc.shape[0]
            S = M // batch
            q = qkv_loc[:, :hq * hd].reshape(batch, S, hq, hd)
            k = qkv_loc[:, hq * hd:(hq + hkv) * hd].reshape(batch, S, hkv, hd)
            v = qkv_loc[:, (hq + hkv) * hd:].reshape(batch, S, hkv, hd)
            if self.q_norm is not None:
                q = rms_norm(q, next(ni))
            if self.k_norm is not None:
                k = rms_norm(k, next(ni))
            positions = jnp.arange(S)
            q = apply_rope(q, cos, sin, positions)
            k = apply_rope(k, cos, sin, positions)
            o = attend(q, k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3),
                       scale=scale)
            return o.reshape(M, hq * hd)

        return f(qkv, cos, sin, *norms)

    def fwd_train(self, x, cos, sin, batch: int, impl: str = "flash"):
        """Differentiable TP attention block for training (no KV cache):
        custom-VJP AG-GEMM -> differentiable Pallas flash attention ->
        custom-VJP GEMM-RS — the whole block trains through framework
        kernels (reference analog: the autograd Function wrappers over
        the dist ops, layers/nvidia/tp_attn.py under torch.autograd).
        impl="ref" is the pure-XLA oracle (jnp matmuls + psum + jnp
        attention) for differential gradient tests.

        x: [B*S, D] row-sharded over tp (replicated for "ref");
        returns same sharding as input convention of each path."""
        from triton_dist_tpu.kernels.grad import ag_gemm_grad, gemm_rs_grad
        if impl == "flash":
            qkv = ag_gemm_grad(self.mesh, self.axis)(x, self.w_qkv)
            o = self._local_attn_train(qkv, cos, sin, batch, impl="flash")
            return gemm_rs_grad(self.mesh, self.axis)(o, self.w_o)
        qkv = x @ self.w_qkv
        o = self._local_attn_train(qkv, cos, sin, batch, impl="ref")
        return self._down_psum(o)

    def fwd_dist(self, x, cos, sin, positions):
        """AG-GEMM -> attention -> GEMM-RS (reference: dist_triton_fwd,
        tp_attn.py:213). x: [S, D] sharded on rows."""
        ag_ctx = create_ag_gemm_context(self.mesh, self.axis)
        rs_ctx = create_gemm_rs_context(self.mesh, self.axis)
        qkv = ag_gemm(x, self.w_qkv, ag_ctx)
        o = self._local_attn(qkv, cos, sin, positions)
        return gemm_rs(o, self.w_o, rs_ctx)

    def fwd_ar(self, x, cos, sin, positions):
        """Local QKV + attention + partial O-proj + AR kernel (reference:
        AR fwd, tp_attn.py:251). x replicated; returns replicated."""
        axis = self.axis
        hq, hd = self._hq_loc, self.head_dim

        from triton_dist_tpu.kernels.quant import qmm, qspec

        @functools.partial(jax.shard_map, mesh=self.mesh,
                           in_specs=(P(None, None),
                                     qspec(self.w_qkv, P(None, axis),
                                           P(axis))),
                           out_specs=P(None, axis), check_vma=False)
        def qkv_local(x_r, w_loc):
            return qmm(x_r, w_loc)

        qkv = qkv_local(x, self.w_qkv)
        o = self._local_attn(qkv, cos, sin, positions)

        @functools.partial(jax.shard_map, mesh=self.mesh,
                           in_specs=(P(None, axis),
                                     qspec(self.w_o, P(axis, None),
                                           P(None))),
                           out_specs=P(axis, None, None), check_vma=False)
        def o_partial(o_loc, wo_loc):
            return qmm(o_loc, wo_loc)[None]

        parts = o_partial(o, self.w_o)
        del hq, hd
        return all_reduce(parts, mesh=self.mesh, axis=axis)

    def fwd_gemm_ar(self, x, cos, sin, positions):
        """Fused GEMM+AR for the O projection (reference: tp_attn.py:318)."""
        axis = self.axis

        from triton_dist_tpu.kernels.quant import qmm, qspec

        @functools.partial(jax.shard_map, mesh=self.mesh,
                           in_specs=(P(None, None),
                                     qspec(self.w_qkv, P(None, axis),
                                           P(axis))),
                           out_specs=P(None, axis), check_vma=False)
        def qkv_local(x_r, w_loc):
            return qmm(x_r, w_loc)

        qkv = qkv_local(x, self.w_qkv)
        o = self._local_attn(qkv, cos, sin, positions)
        ctx = create_gemm_ar_context(self.mesh, axis)
        return gemm_allreduce(o, self.w_o, ctx)

    def __call__(self, x, cos, sin, positions, mode: str = "dist"):
        return dict(xla=self.fwd_xla, dist=self.fwd_dist, ar=self.fwd_ar,
                    gemm_ar=self.fwd_gemm_ar)[mode](x, cos, sin, positions)

    # ------------------------------------------------------------------
    # KV-cache paths (prefill fill + decode), used by models/engine
    # (reference: tp_attn.py decode with KV cache driven by
    # models/dense.py:101 + kv_cache.py:29)
    # ------------------------------------------------------------------

    def _attend_cached(self, qkv, cos, sin, batch: int, kv, kv_start,
                       impl: str = "flash"):
        """Split a rank's packed [q|k|v] slice, write K/V into this rank's
        cache shard at kv_start, attend against the cache.

        qkv: [B*S, qkv_cols] sharded P(None, tp);
        kv: (ck, cv) with ck/cv [B, Hkv, T, hd] sharded on the head axis
            — or (ck, cv, ks, vs) for an int8 cache with per-position
            f32 scales [B, Hkv, T] (kv_cache.py kv_dtype=int8; halves
            the decode step's dominant HBM read);
        kv_start: traced scalar (0 for prefill, pos for decode);
        impl: "flash" (Pallas flash-decode kernel) or "ref" (jnp oracle).
        Returns (o [B*S, hq_loc*hd] P(None, tp), updated kv).
        """
        from triton_dist_tpu.kernels.flash_attn import (attention_cached_ref,
                                                        flash_decode)
        hq, hkv, hd = self._hq_loc, self._hkv_loc, self.head_dim
        scale = hd ** -0.5
        quant = len(kv) == 4
        cache_spec = P(None, self.axis, None, None)
        scale_spec = P(None, self.axis, None)
        kv_specs = ((cache_spec, cache_spec, scale_spec, scale_spec)
                    if quant else (cache_spec, cache_spec))

        @functools.partial(
            jax.shard_map, mesh=self.mesh,
            in_specs=(P(None, self.axis),) + kv_specs + (P(),),
            out_specs=((P(None, self.axis),) + kv_specs),
            check_vma=False)
        def f(qkv_loc, ck_loc, cv_loc, *rest):
            *scales, kv_start = rest
            M = qkv_loc.shape[0]
            S = M // batch
            q = qkv_loc[:, :hq * hd].reshape(batch, S, hq, hd)
            k = qkv_loc[:, hq * hd:(hq + hkv) * hd].reshape(batch, S, hkv, hd)
            v = qkv_loc[:, (hq + hkv) * hd:].reshape(batch, S, hkv, hd)
            if self.q_norm is not None:
                q = rms_norm(q, self.q_norm)
            if self.k_norm is not None:
                k = rms_norm(k, self.k_norm)
            positions = kv_start + jnp.arange(S)
            # apply_rope expects [..., S, H, d]
            q = apply_rope(q, cos, sin, positions)
            k = apply_rope(k, cos, sin, positions)
            # cache layout is head-major [B, Hkv, T, hd]
            kT = k.transpose(0, 2, 1, 3)
            vT = v.transpose(0, 2, 1, 3)

            def dus(c, u, idx):
                return jax.lax.dynamic_update_slice(c, u, idx)

            def insert(c, u, pos):
                """KV-row insert. Tile-aligned whole-tile writes
                (S % 8 == 0 AND pos % 8 == 0 — e.g. prefill at offset 0)
                go through the aliased one-DMA kv_update; XLA's DUS on
                the multi-GB carried buffer costs ~30us per slice.
                pos is traced, so the alignment pick is a lax.cond —
                an unaligned multi-row write (chunked prefill at an odd
                offset) falls back to the correct DUS instead of
                silently flooring to a tile boundary."""
                from triton_dist_tpu.kernels.flash_attn import kv_update
                if u.shape[2] % 8:
                    return dus(c, u, (0, 0, pos, 0))
                return jax.lax.cond(
                    pos % 8 == 0,
                    lambda c_, u_, p: kv_update(c_, u_, p // 8),
                    lambda c_, u_, p: dus(c_, u_, (0, 0, p, 0)),
                    c, u, pos)

            if quant:
                ks_loc, vs_loc = scales

                # the repo-wide per-position KV quantizer
                # (kernels/quant.quantize_kv_int8 — shared with the
                # int8 paged pool, so the two layouts can never drift)
                from triton_dist_tpu.kernels.quant import \
                    quantize_kv_int8 as q8

                k8, k_s = q8(kT)
                v8, v_s = q8(vT)
                ck_loc = insert(ck_loc, k8, kv_start)
                cv_loc = insert(cv_loc, v8, kv_start)
                ks_loc = dus(ks_loc, k_s, (0, 0, kv_start))
                vs_loc = dus(vs_loc, v_s, (0, 0, kv_start))
                if impl == "flash":
                    # decode (S==1): one KV tile per x-block — the walk
                    # is grid-step-latency-bound at small tiles (~2.5us
                    # fixed cost/step vs ~1us of int8 KV traffic).
                    # Capped so _pick_bx's double-buffered KV term still
                    # fits VMEM for long caches (falls back to walking).
                    bt = min(ck_loc.shape[2], 2048) if S == 1 else 256
                    o = flash_decode(q.astype(jnp.bfloat16), ck_loc,
                                     cv_loc, kv_start + S, scale=scale,
                                     k_scale=ks_loc, v_scale=vs_loc,
                                     block_t=bt)
                else:
                    o = attention_cached_ref(
                        q.astype(jnp.float32),
                        ck_loc.astype(jnp.float32) * ks_loc[..., None],
                        cv_loc.astype(jnp.float32) * vs_loc[..., None],
                        kv_start + S, scale=scale)
                return (o.reshape(M, hq * hd).astype(qkv_loc.dtype),
                        ck_loc, cv_loc, ks_loc, vs_loc)

            ck_loc = insert(ck_loc, kT.astype(ck_loc.dtype), kv_start)
            cv_loc = insert(cv_loc, vT.astype(cv_loc.dtype), kv_start)
            attend = flash_decode if impl == "flash" else attention_cached_ref
            # cast the [S]-sized query side to the cache dtype — NEVER
            # the [T]-sized cache to the query dtype (a full-cache
            # convert per layer per step)
            o = attend(q.astype(ck_loc.dtype), ck_loc, cv_loc,
                       kv_start + S, scale=scale)
            return o.reshape(M, hq * hd), ck_loc, cv_loc

        out = f(qkv, *kv, jnp.asarray(kv_start, jnp.int32))
        return out[0], tuple(out[1:])

    def _attend_cached_slots(self, qkv, cos, sin, batch: int, kv, pos,
                             impl: str = "flash"):
        """Slot-variant of _attend_cached for the continuous-batching
        decode step (S == 1, per-row positions).

        qkv: [B, qkv_cols] sharded P(None, tp); pos: [B] int32 — row b
        writes its K/V at column pos[b] of ITS cache row (a per-row
        scatter; rows are independent (batch, head) streams, so a row's
        write never touches another slot's data) and attends its own
        columns [0, pos[b]] via the kernel's per-stream length mask
        (flash_decode kv_lens / attention_cached_ref vector kv_len).
        RoPE rotates row b at angle pos[b]. Returns (o, updated kv).
        """
        from triton_dist_tpu.kernels.flash_attn import (attention_cached_ref,
                                                        flash_decode)
        hq, hkv, hd = self._hq_loc, self._hkv_loc, self.head_dim
        scale = hd ** -0.5
        quant = len(kv) == 4
        cache_spec = P(None, self.axis, None, None)
        scale_spec = P(None, self.axis, None)
        kv_specs = ((cache_spec, cache_spec, scale_spec, scale_spec)
                    if quant else (cache_spec, cache_spec))

        @functools.partial(
            jax.shard_map, mesh=self.mesh,
            in_specs=(P(None, self.axis),) + kv_specs + (P(None),),
            out_specs=((P(None, self.axis),) + kv_specs),
            check_vma=False)
        def f(qkv_loc, ck_loc, cv_loc, *rest):
            *scales, pos = rest
            B = qkv_loc.shape[0]               # S == 1: one row per slot
            q = qkv_loc[:, :hq * hd].reshape(B, 1, hq, hd)
            k = qkv_loc[:, hq * hd:(hq + hkv) * hd].reshape(B, 1, hkv, hd)
            v = qkv_loc[:, (hq + hkv) * hd:].reshape(B, 1, hkv, hd)
            if self.q_norm is not None:
                q = rms_norm(q, self.q_norm)
            if self.k_norm is not None:
                k = rms_norm(k, self.k_norm)
            q = apply_rope_slots(q, cos, sin, pos)
            k = apply_rope_slots(k, cos, sin, pos)
            kT = k.transpose(0, 2, 1, 3)        # [B, hkv, 1, hd]
            vT = v.transpose(0, 2, 1, 3)
            rows = jnp.arange(B)
            lens = pos + 1

            def scat(c, u):
                # one row per (slot, head) stream at that slot's column
                return c.at[rows, :, pos].set(u[:, :, 0].astype(c.dtype))

            if quant:
                ks_loc, vs_loc = scales

                # the repo-wide per-position KV quantizer
                # (kernels/quant.quantize_kv_int8 — shared with the
                # int8 paged pool, so the two layouts can never drift)
                from triton_dist_tpu.kernels.quant import \
                    quantize_kv_int8 as q8

                k8, k_s = q8(kT)
                v8, v_s = q8(vT)
                ck_loc = scat(ck_loc, k8)
                cv_loc = scat(cv_loc, v8)
                ks_loc = ks_loc.at[rows, :, pos].set(k_s[:, :, 0])
                vs_loc = vs_loc.at[rows, :, pos].set(v_s[:, :, 0])
                if impl == "flash":
                    bt = min(ck_loc.shape[2], 2048)
                    o = flash_decode(q.astype(jnp.bfloat16), ck_loc,
                                     cv_loc, jnp.max(lens), scale=scale,
                                     k_scale=ks_loc, v_scale=vs_loc,
                                     block_t=bt, kv_lens=lens)
                else:
                    o = attention_cached_ref(
                        q.astype(jnp.float32),
                        ck_loc.astype(jnp.float32) * ks_loc[..., None],
                        cv_loc.astype(jnp.float32) * vs_loc[..., None],
                        lens, scale=scale)
                return (o.reshape(B, hq * hd).astype(qkv_loc.dtype),
                        ck_loc, cv_loc, ks_loc, vs_loc)

            ck_loc = scat(ck_loc, kT)
            cv_loc = scat(cv_loc, vT)
            if impl == "flash":
                o = flash_decode(q.astype(ck_loc.dtype), ck_loc, cv_loc,
                                 jnp.max(lens), scale=scale, kv_lens=lens)
            else:
                o = attention_cached_ref(q.astype(ck_loc.dtype), ck_loc,
                                         cv_loc, lens, scale=scale)
            return o.reshape(B, hq * hd), ck_loc, cv_loc

        out = f(qkv, *kv, jnp.asarray(pos, jnp.int32))
        return out[0], tuple(out[1:])

    def _attend_cached_slots_verify(self, qkv, cos, sin, batch: int, kv,
                                    pos, q_lens, impl: str = "flash"):
        """Speculative-verify variant of _attend_cached_slots
        (models/spec_decode.py): each slot feeds a variable-length
        draft window of up to S tokens in ONE forward. qkv:
        [B*S, qkv_cols] sharded P(None, tp); pos/q_lens: [B] int32 —
        slot b's q_lens[b] valid window rows sit at positions pos[b] ..
        pos[b] + q_lens[b] - 1 (RoPE-rotated there), write their K/V at
        those columns of the slot's cache row, and attend causally
        within the window (flash_decode q_lens / attention_cached_ref
        q_lens). Padded rows (s >= q_lens[b], or past the cache
        capacity) are DROPPED by the scatter (out-of-bounds update
        indices), so they can never clobber a live KV row; their
        attention outputs are computed-and-discarded. Returns
        (o [B*S, hq_loc*hd], updated kv)."""
        from triton_dist_tpu.kernels.flash_attn import (attention_cached_ref,
                                                        flash_decode)
        hq, hkv, hd = self._hq_loc, self._hkv_loc, self.head_dim
        scale = hd ** -0.5
        quant = len(kv) == 4
        cache_spec = P(None, self.axis, None, None)
        scale_spec = P(None, self.axis, None)
        kv_specs = ((cache_spec, cache_spec, scale_spec, scale_spec)
                    if quant else (cache_spec, cache_spec))

        @functools.partial(
            jax.shard_map, mesh=self.mesh,
            in_specs=(P(None, self.axis),) + kv_specs + (P(None), P(None)),
            out_specs=((P(None, self.axis),) + kv_specs),
            check_vma=False)
        def f(qkv_loc, ck_loc, cv_loc, *rest):
            *scales, pos, q_lens = rest
            M = qkv_loc.shape[0]
            B = batch
            S = M // B
            T = ck_loc.shape[2]
            q = qkv_loc[:, :hq * hd].reshape(B, S, hq, hd)
            k = qkv_loc[:, hq * hd:(hq + hkv) * hd].reshape(B, S, hkv, hd)
            v = qkv_loc[:, (hq + hkv) * hd:].reshape(B, S, hkv, hd)
            if self.q_norm is not None:
                q = rms_norm(q, self.q_norm)
            if self.k_norm is not None:
                k = rms_norm(k, self.k_norm)
            q = apply_rope_slots(q, cos, sin, pos)
            k = apply_rope_slots(k, cos, sin, pos)
            p = pos[:, None] + jnp.arange(S)[None]          # [B, S]
            valid = (jnp.arange(S)[None] < q_lens[:, None]) & (p < T)
            # invalid rows scatter OUT OF BOUNDS (column T) — jax drops
            # OOB scatter updates, so padding can never collide with a
            # live row's write (a clamped index could, at T - 1)
            wpos = jnp.where(valid, p, T)
            rows = jnp.arange(B)[:, None]
            lens = pos + q_lens

            def scat(c, u):   # u: [B, S, hkv, ...] matching c's cols
                return c.at[rows, :, wpos].set(u.astype(c.dtype))

            if quant:
                ks_loc, vs_loc = scales

                # the repo-wide per-position KV quantizer
                # (kernels/quant.quantize_kv_int8 — shared with the
                # int8 paged pool, so the two layouts can never drift)
                from triton_dist_tpu.kernels.quant import \
                    quantize_kv_int8 as q8

                k8, k_s = q8(k)
                v8, v_s = q8(v)
                ck_loc = scat(ck_loc, k8)
                cv_loc = scat(cv_loc, v8)
                ks_loc = ks_loc.at[rows, :, wpos].set(k_s)
                vs_loc = vs_loc.at[rows, :, wpos].set(v_s)
                if impl == "flash":
                    bt = min(T, 2048)
                    o = flash_decode(q.astype(jnp.bfloat16), ck_loc,
                                     cv_loc, jnp.max(lens), scale=scale,
                                     k_scale=ks_loc, v_scale=vs_loc,
                                     block_t=bt, kv_lens=lens,
                                     q_lens=q_lens)
                else:
                    o = attention_cached_ref(
                        q.astype(jnp.float32),
                        ck_loc.astype(jnp.float32) * ks_loc[..., None],
                        cv_loc.astype(jnp.float32) * vs_loc[..., None],
                        lens, scale=scale, q_lens=q_lens)
                return (o.reshape(M, hq * hd).astype(qkv_loc.dtype),
                        ck_loc, cv_loc, ks_loc, vs_loc)

            ck_loc = scat(ck_loc, k)
            cv_loc = scat(cv_loc, v)
            if impl == "flash":
                o = flash_decode(q.astype(ck_loc.dtype), ck_loc, cv_loc,
                                 jnp.max(lens), scale=scale, kv_lens=lens,
                                 q_lens=q_lens)
            else:
                o = attention_cached_ref(q.astype(ck_loc.dtype), ck_loc,
                                         cv_loc, lens, scale=scale,
                                         q_lens=q_lens)
            return o.reshape(M, hq * hd), ck_loc, cv_loc

        out = f(qkv, *kv, jnp.asarray(pos, jnp.int32),
                jnp.asarray(q_lens, jnp.int32))
        return out[0], tuple(out[1:])

    def fwd_cached_slots_verify(self, x, cos, sin, batch: int, kv, pos,
                                q_lens, mode: str = "dist"):
        """Speculative-verify attention block (spec decode,
        models/spec_decode.py): B slots x up to S draft-window tokens
        in ONE forward. x: [B*S, D]; pos/q_lens: [B] int32. Same mode
        dispatch as fwd_cached_slots."""
        impl = "ref" if mode == "xla" else "flash"
        qkv = self._qkv_proj(x, mode)
        o, kv = self._attend_cached_slots_verify(qkv, cos, sin, batch,
                                                 kv, pos, q_lens, impl)
        return self._o_proj(o, mode), kv

    def _paged_specs(self, quant: bool):
        """shard_map in/out specs of one layer's paged pool tuple:
        payloads [NP, G, page, d] and (int8) scale planes [NP, G, page]
        split on the HEAD-GROUP axis G (kv_cache.PagedSlotCache TP
        sharding) — each rank's plane holds its own kv heads' pages."""
        pool_spec = P(None, self.axis, None, None)
        sc_spec = P(None, self.axis, None)
        return ((pool_spec, pool_spec, sc_spec, sc_spec) if quant
                else (pool_spec, pool_spec))

    def _attend_paged_slots(self, qkv, cos, sin, batch: int, kv, table,
                            pos, impl: str = "flash"):
        """Paged-pool variant of _attend_cached_slots (prefix-cache
        serving, models/prefix_cache.py): row b's new K/V lands in the
        physical page its table row maps for position pos[b], and
        attention walks the pool through the table (flash_decode_paged,
        or a gather + contiguous oracle under impl="ref").

        kv: (pages_k, pages_v) [NP, G, page, d] — ONE layer's pool —
        or (pages_k, pages_v, scales_k, scales_v) for the INT8 pool
        (kv_cache.PagedSlotCache with dtype=int8): the new row
        quantizes per position (kernels/quant.quantize_kv_int8 — the
        contiguous cache's exact quantizer) and its scale lands in the
        [NP, G, page] scale plane at the SAME page/row/plane the
        payload takes, so scales follow pages through sharing, CoW,
        eviction and the host tier for free; attention dequants
        in-kernel (flash_decode_paged k_scale/v_scale).
        table: [B*Hkv, max_pages] int32 shared by all layers,
        replicated (the host owns it).

        TP-NATIVE (the head-sharded pool of kv_cache.PagedSlotCache —
        ROADMAP open item 1): this attend runs under jax.shard_map
        exactly like the contiguous _attend_cached_slots — each rank
        scatters its OWN kv heads' new rows into its local pool plane
        and walks only its local streams (its slice of the table), so
        a TP=N mesh reads 1/N of the KV and does 1/N of the attention
        FLOPs per chip while the page table, allocator and radix tree
        stay host-replicated and layout-oblivious."""
        from triton_dist_tpu.kernels.flash_attn import attention_cached_ref
        from triton_dist_tpu.kernels.paged_kv import flash_decode_paged
        from triton_dist_tpu.kernels.quant import (dequantize_kv_int8,
                                                   quantize_kv_int8)
        hq, hkv, hd = self._hq_loc, self._hkv_loc, self.head_dim
        Hkv = self.n_kv_heads
        scale = hd ** -0.5
        quant = len(kv) == 4
        kv_specs = self._paged_specs(quant)
        B = qkv.shape[0]
        maxp = table.shape[1]
        # table rows regrouped [B, Hkv, maxp] so the head axis blocks
        # contiguously per rank (row b*Hkv+h of the flat table is
        # stream (b, h); rank r owns heads [r*hkv, (r+1)*hkv))
        table3 = table.reshape(B, Hkv, maxp)

        @functools.partial(
            jax.shard_map, mesh=self.mesh,
            in_specs=(P(None, self.axis),) + kv_specs
                     + (P(None, self.axis, None), P(None)),
            out_specs=((P(None, self.axis),) + kv_specs),
            check_vma=False)
        def f(qkv_loc, ck4, cv4, *rest):
            *scales4, tbl, pos = rest
            ck, cv = ck4[:, 0], cv4[:, 0]          # local plane
            page = ck.shape[1]
            tbl = tbl.reshape(B * hkv, maxp)       # local streams
            q = qkv_loc[:, :hq * hd].reshape(B, 1, hq, hd)
            k = qkv_loc[:, hq * hd:(hq + hkv) * hd].reshape(B, 1, hkv, hd)
            v = qkv_loc[:, (hq + hkv) * hd:].reshape(B, 1, hkv, hd)
            if self.q_norm is not None:
                q = rms_norm(q, self.q_norm)
            if self.k_norm is not None:
                k = rms_norm(k, self.k_norm)
            q = apply_rope_slots(q, cos, sin, pos)
            k = apply_rope_slots(k, cos, sin, pos)
            X = B * hkv
            pos_x = jnp.repeat(pos, hkv)                     # [X]
            pidx = tbl[jnp.arange(X), pos_x // page]
            r = pos_x % page
            if quant:
                sk, sv = scales4[0][:, 0], scales4[1][:, 0]
                k8, k_s = quantize_kv_int8(k.reshape(X, hd))
                v8, v_s = quantize_kv_int8(v.reshape(X, hd))
                ck = ck.at[pidx, r].set(k8)
                cv = cv.at[pidx, r].set(v8)
                sk = sk.at[pidx, r].set(k_s)
                sv = sv.at[pidx, r].set(v_s)
            else:
                ck = ck.at[pidx, r].set(k.reshape(X, hd).astype(ck.dtype))
                cv = cv.at[pidx, r].set(v.reshape(X, hd).astype(cv.dtype))
                sk = sv = None
            lens = pos + 1
            qd = jnp.bfloat16 if quant else ck.dtype
            if impl == "flash":
                o = flash_decode_paged(q.astype(qd), ck, cv, tbl,
                                       jnp.max(lens), scale=scale,
                                       kv_lens=lens, k_scale=sk,
                                       v_scale=sv)
            else:
                T = maxp * page
                kd = dequantize_kv_int8(ck, sk) if quant else ck
                vd = dequantize_kv_int8(cv, sv) if quant else cv
                kfull = kd[tbl].reshape(B, hkv, T, hd)
                vfull = vd[tbl].reshape(B, hkv, T, hd)
                o = attention_cached_ref(q.astype(jnp.float32) if quant
                                         else q.astype(ck.dtype),
                                         kfull, vfull, lens, scale=scale)
            o = o.reshape(B, hq * hd)
            if quant:
                return (o.astype(qkv_loc.dtype), ck[:, None], cv[:, None],
                        sk[:, None], sv[:, None])
            return o, ck[:, None], cv[:, None]

        out = f(qkv, *kv, table3, jnp.asarray(pos, jnp.int32))
        return out[0], tuple(out[1:])

    def _attend_paged_slots_verify(self, qkv, cos, sin, batch: int, kv,
                                   table, pos, q_lens,
                                   impl: str = "flash"):
        """Paged-pool variant of _attend_cached_slots_verify (spec
        decode over the shared-prefix pool): slot b's draft-window K/V
        lands in the physical pages its table row maps for positions
        pos[b] .. pos[b] + q_lens[b] - 1; padded rows scatter to an
        out-of-bounds page id and are dropped, so they can never touch
        a live or cached page. Attention walks the pool through the
        table with per-slot kv_lens AND q_lens (flash_decode_paged).
        An INT8 pool (kv = 4-tuple with scale planes) quantizes the
        window per position and scatters the scales to the same
        (page, row) destinations — OOB-dropped alongside the payload —
        exactly like _attend_paged_slots. Runs under jax.shard_map on
        the head-sharded pool (see _attend_paged_slots): each rank
        writes and walks only its own kv-head plane."""
        from triton_dist_tpu.kernels.flash_attn import attention_cached_ref
        from triton_dist_tpu.kernels.paged_kv import flash_decode_paged
        from triton_dist_tpu.kernels.quant import (dequantize_kv_int8,
                                                   quantize_kv_int8)
        hq, hkv, hd = self._hq_loc, self._hkv_loc, self.head_dim
        Hkv = self.n_kv_heads
        scale = hd ** -0.5
        quant = len(kv) == 4
        kv_specs = self._paged_specs(quant)
        B = batch
        S = qkv.shape[0] // B
        NP = kv[0].shape[0]
        maxp = table.shape[1]
        table3 = table.reshape(B, Hkv, maxp)

        @functools.partial(
            jax.shard_map, mesh=self.mesh,
            in_specs=(P(None, self.axis),) + kv_specs
                     + (P(None, self.axis, None), P(None), P(None)),
            out_specs=((P(None, self.axis),) + kv_specs),
            check_vma=False)
        def f(qkv_loc, ck4, cv4, *rest):
            *scales4, tbl, pos, q_lens = rest
            ck, cv = ck4[:, 0], cv4[:, 0]
            page = ck.shape[1]
            tbl = tbl.reshape(B * hkv, maxp)
            M = qkv_loc.shape[0]
            q = qkv_loc[:, :hq * hd].reshape(B, S, hq, hd)
            k = qkv_loc[:, hq * hd:(hq + hkv) * hd].reshape(B, S, hkv, hd)
            v = qkv_loc[:, (hq + hkv) * hd:].reshape(B, S, hkv, hd)
            if self.q_norm is not None:
                q = rms_norm(q, self.q_norm)
            if self.k_norm is not None:
                k = rms_norm(k, self.k_norm)
            q = apply_rope_slots(q, cos, sin, pos)
            k = apply_rope_slots(k, cos, sin, pos)
            p = pos[:, None] + jnp.arange(S)[None]             # [B, S]
            valid = ((jnp.arange(S)[None] < q_lens[:, None])
                     & (p < maxp * page))
            streams = (jnp.arange(B) * hkv)[:, None, None] \
                + jnp.arange(hkv)[None, None, :]               # [B, 1, hkv]
            pidx = tbl[streams,
                       jnp.minimum(p // page, maxp - 1)[:, :, None]]
            # invalid rows scatter to page NP (out of bounds -> dropped)
            dest = jnp.where(valid[:, :, None], pidx, NP)      # [B, S, hkv]
            r = (p % page)[:, :, None]
            if quant:
                sk, sv = scales4[0][:, 0], scales4[1][:, 0]
                k8, k_s = quantize_kv_int8(k)      # [B, S, hkv, d] / [..]
                v8, v_s = quantize_kv_int8(v)
                ck = ck.at[dest, r].set(k8)
                cv = cv.at[dest, r].set(v8)
                sk = sk.at[dest, r].set(k_s)
                sv = sv.at[dest, r].set(v_s)
            else:
                ck = ck.at[dest, r].set(k.astype(ck.dtype))
                cv = cv.at[dest, r].set(v.astype(cv.dtype))
                sk = sv = None
            lens = pos + q_lens
            qd = jnp.bfloat16 if quant else ck.dtype
            if impl == "flash":
                o = flash_decode_paged(q.astype(qd), ck, cv, tbl,
                                       jnp.max(lens), scale=scale,
                                       kv_lens=lens, q_lens=q_lens,
                                       k_scale=sk, v_scale=sv)
            else:
                T = maxp * page
                kd = dequantize_kv_int8(ck, sk) if quant else ck
                vd = dequantize_kv_int8(cv, sv) if quant else cv
                kfull = kd[tbl].reshape(B, hkv, T, hd)
                vfull = vd[tbl].reshape(B, hkv, T, hd)
                o = attention_cached_ref(q.astype(jnp.float32) if quant
                                         else q.astype(ck.dtype),
                                         kfull, vfull, lens, scale=scale,
                                         q_lens=q_lens)
            o = o.reshape(M, hq * hd)
            if quant:
                return (o.astype(qkv_loc.dtype), ck[:, None], cv[:, None],
                        sk[:, None], sv[:, None])
            return o, ck[:, None], cv[:, None]

        out = f(qkv, *kv, table3, jnp.asarray(pos, jnp.int32),
                jnp.asarray(q_lens, jnp.int32))
        return out[0], tuple(out[1:])

    def _attend_paged_slots_sp(self, qkv, cos, sin, batch: int, kv,
                               table, pos, q_lens, sp_axis: str,
                               combine: str = "xla"):
        """SEQUENCE-PARALLEL paged slot attention (long-context
        serving — the serving promotion of kernels/sp_flash_decode.py;
        Ring Attention arXiv:2310.01889 sets the blockwise cross-chip
        pattern, Infinite-LLM/DistAttention arXiv:2401.02669 the
        cluster-wide paged-KV deployment): the pool's PAGE-ID space is
        sharded over the `sp_axis` mesh axis (kv_cache.PagedSlotCache
        SP SHARDING — chip s holds physical pages [s*pps, (s+1)*pps)),
        so under jax.shard_map each chip

        - scatters the new K/V rows of the pages IT owns (other
          chips' scatters redirect out of bounds and drop — the same
          OOB-drop contract padded verify rows use; a trash-mapped
          retired row's write lands only in shard 0's local trash
          sink),
        - walks ONLY its local pages through the split-KV partial
          kernel (flash_decode_paged_partial: the replicated table is
          redirected per chip — non-owned tiles point at the last
          owned local page so their surplus DMAs elide — and a
          per-tile ownership mask makes them accumulator no-ops), and
        - merges partials via the cross-chip LSE combine
          (sp_combine_partials -> lse_combine or the one-sided Pallas
          push kernel), yielding the bitwise-softmax output replicated
          over sp.

        Per-chip KV reads and attention FLOPs drop to ~1/S and a
        slot's max context is bounded by the MESH's pooled HBM, not
        one chip's. q_lens None = the decode tick (S == 1); a [B]
        vector = the verify/chunked-prefill window (per-slot kv_lens
        AND q_lens masks, padded rows dropped) — chunked prefill over
        this attend IS the blockwise ring-style prefill: each chunk's
        window attends the distributed pages through the same
        partial+combine. Single TP group only (sp + head-group hybrid
        is refused at construction)."""
        from triton_dist_tpu.kernels.paged_kv import \
            flash_decode_paged_partial
        from triton_dist_tpu.kernels.quant import quantize_kv_int8
        from triton_dist_tpu.kernels.sp_flash_decode import \
            sp_combine_partials
        from triton_dist_tpu.runtime import next_collective_id
        hq, hkv, hd = self._hq_loc, self._hkv_loc, self.head_dim
        Hkv = self.n_kv_heads
        scale = hd ** -0.5
        quant = len(kv) == 4
        B = batch
        M = qkv.shape[0]
        S = M // B
        verify = q_lens is not None
        NP = kv[0].shape[0]
        maxp = table.shape[1]
        nsp = self.mesh.shape[sp_axis]
        pps = NP // nsp
        cid = (next_collective_id() if combine == "dist" else None)
        pool_spec = P(sp_axis, None, None, None)
        sc_spec = P(sp_axis, None, None)
        kv_specs = ((pool_spec, pool_spec, sc_spec, sc_spec) if quant
                    else (pool_spec, pool_spec))
        rep2 = P(None, None)
        in_specs = ((rep2,) + kv_specs
                    + (P(None, None), P(None))
                    + ((P(None),) if verify else ()))
        out_specs = ((rep2,) + kv_specs)

        @functools.partial(
            jax.shard_map, mesh=self.mesh, in_specs=in_specs,
            out_specs=out_specs, check_vma=False)
        def f(qkv_loc, ck4, cv4, *rest):
            if verify:
                *scales4, tbl, pos_, ql = rest
            else:
                *scales4, tbl, pos_ = rest
                ql = None
            me = jax.lax.axis_index(sp_axis)
            ck, cv = ck4[:, 0], cv4[:, 0]       # local shard, plane 0
            NP_loc = ck.shape[0]
            page = ck.shape[1]
            X = B * hkv
            q = qkv_loc[:, :hq * hd].reshape(B, S, hq, hd)
            k = qkv_loc[:, hq * hd:(hq + hkv) * hd].reshape(B, S, hkv, hd)
            v = qkv_loc[:, (hq + hkv) * hd:].reshape(B, S, hkv, hd)
            if self.q_norm is not None:
                q = rms_norm(q, self.q_norm)
            if self.k_norm is not None:
                k = rms_norm(k, self.k_norm)
            q = apply_rope_slots(q, cos, sin, pos_)
            k = apply_rope_slots(k, cos, sin, pos_)
            # --- new-row scatter: only the owning chip writes ---
            if verify:
                p = pos_[:, None] + jnp.arange(S)[None]        # [B, S]
                valid = ((jnp.arange(S)[None] < ql[:, None])
                         & (p < maxp * page))
                streams = (jnp.arange(B) * hkv)[:, None, None] \
                    + jnp.arange(hkv)[None, None, :]
                pidx_g = tbl[streams,
                             jnp.minimum(p // page, maxp - 1)[:, :, None]]
                owned_w = valid[:, :, None] & ((pidx_g // pps) == me)
                dest = jnp.where(owned_w, pidx_g - me * pps, NP_loc)
                r = (p % page)[:, :, None]
                k_rows, v_rows = k, v
            else:
                pos_x = jnp.repeat(pos_, hkv)                  # [X]
                pidx_g = tbl[jnp.arange(X), pos_x // page]
                owned_w = (pidx_g // pps) == me
                dest = jnp.where(owned_w, pidx_g - me * pps, NP_loc)
                r = pos_x % page
                k_rows = k.reshape(X, hd)
                v_rows = v.reshape(X, hd)
            if quant:
                sk, sv = scales4[0][:, 0], scales4[1][:, 0]
                k8, k_s = quantize_kv_int8(k_rows)
                v8, v_s = quantize_kv_int8(v_rows)
                ck = ck.at[dest, r].set(k8)
                cv = cv.at[dest, r].set(v8)
                sk = sk.at[dest, r].set(k_s)
                sv = sv.at[dest, r].set(v_s)
            else:
                ck = ck.at[dest, r].set(k_rows.astype(ck.dtype))
                cv = cv.at[dest, r].set(v_rows.astype(cv.dtype))
                sk = sv = None
            lens = pos_ + (ql if verify else 1)
            # --- local redirected table + per-tile ownership mask:
            # non-owned tiles repeat the last owned local page (their
            # surplus DMAs elide) and mask to accumulator no-ops ---
            owned_t = (tbl // pps) == me                   # [X, maxp]
            ti = jax.lax.broadcasted_iota(jnp.int32, (X, maxp), 1)
            lastown = jax.lax.cummax(jnp.where(owned_t, ti, -1), axis=1)
            tbl_loc = jnp.take_along_axis(
                jnp.where(owned_t, tbl - me * pps, 0),
                jnp.maximum(lastown, 0), axis=1)
            qd = jnp.bfloat16 if quant else ck.dtype
            acc, m, l = flash_decode_paged_partial(
                q.astype(qd), ck, cv, tbl_loc, kv_lens=lens,
                q_lens=ql, scale=scale,
                tile_owned=owned_t.astype(jnp.int32),
                k_scale=sk, v_scale=sv)
            o = sp_combine_partials(acc, m, l, axis=sp_axis, n=nsp,
                                    combine=combine, collective_id=cid,
                                    out_dtype=jnp.float32)
            o = o.reshape(M, hq * hd).astype(qkv_loc.dtype)
            if quant:
                return (o, ck[:, None], cv[:, None],
                        sk[:, None], sv[:, None])
            return o, ck[:, None], cv[:, None]

        args = (qkv,) + tuple(kv) + (table, jnp.asarray(pos, jnp.int32))
        if verify:
            args = args + (jnp.asarray(q_lens, jnp.int32),)
        out = f(*args)
        return out[0], tuple(out[1:])

    def fwd_cached_slots_paged_sp(self, x, cos, sin, batch: int, kv,
                                  table, pos, sp_axis: str,
                                  mode: str = "flash",
                                  combine: str = "xla"):
        """Slot-masked decode attention block over the SP-sharded
        paged pool (sequence-parallel long-context serving): same
        contract as fwd_cached_slots_paged, with each chip walking
        only its local pages and the partial-softmax LSE combine
        merging across the sp axis (_attend_paged_slots_sp)."""
        qkv = self._qkv_proj(x, mode)
        o, kv = self._attend_paged_slots_sp(qkv, cos, sin, batch, kv,
                                            table, pos, None, sp_axis,
                                            combine)
        return self._o_proj(o, mode), kv

    def fwd_cached_slots_paged_verify_sp(self, x, cos, sin, batch: int,
                                         kv, table, pos, q_lens,
                                         sp_axis: str,
                                         mode: str = "flash",
                                         combine: str = "xla"):
        """Speculative-verify / chunked-prefill window attention over
        the SP-sharded paged pool: fwd_cached_slots_paged_verify's
        contract through the split-KV partial + cross-chip LSE merge
        (_attend_paged_slots_sp with per-slot q_lens)."""
        qkv = self._qkv_proj(x, mode)
        o, kv = self._attend_paged_slots_sp(qkv, cos, sin, batch, kv,
                                            table, pos, q_lens, sp_axis,
                                            combine)
        return self._o_proj(o, mode), kv

    def fwd_cached_slots_paged_verify(self, x, cos, sin, batch: int, kv,
                                      table, pos, q_lens,
                                      mode: str = "flash"):
        """Speculative-verify attention block over the PAGED pool: same
        contract as fwd_cached_slots_verify with the slot's KV resolved
        through the page table (models/spec_decode.py over the
        shared-prefix serving path)."""
        impl = "ref" if mode == "xla" else "flash"
        qkv = self._qkv_proj(x, mode)
        o, kv = self._attend_paged_slots_verify(qkv, cos, sin, batch, kv,
                                                table, pos, q_lens, impl)
        return self._o_proj(o, mode), kv

    def fwd_cached_slots_paged(self, x, cos, sin, batch: int, kv, table,
                               pos, mode: str = "flash"):
        """Slot-masked decode attention block over the PAGED pool
        (shared-prefix serving): same contract as fwd_cached_slots, but
        row b's KV cache is whatever physical pages its table row maps
        — possibly pages shared read-only with other slots' prefixes.
        Decode only ever writes at pos[b] (past any shared prefix), so
        read-only sharing needs no device-side enforcement."""
        impl = "ref" if mode == "xla" else "flash"
        qkv = self._qkv_proj(x, mode)
        o, kv = self._attend_paged_slots(qkv, cos, sin, batch, kv,
                                         table, pos, impl)
        return self._o_proj(o, mode), kv

    def _qkv_proj(self, x, mode: str):
        """Mode-dispatched QKV projection (the prologue both cached
        forwards share): "dist" = AG-GEMM on row-sharded x; every other
        mode = local qmm on replicated x."""
        if mode == "dist":
            ag_ctx = create_ag_gemm_context(self.mesh, self.axis)
            return ag_gemm(x, self.w_qkv, ag_ctx)
        from triton_dist_tpu.kernels.quant import qmm, qspec

        @functools.partial(jax.shard_map, mesh=self.mesh,
                           in_specs=(P(None, None),
                                     qspec(self.w_qkv, P(None, self.axis),
                                           P(self.axis))),
                           out_specs=P(None, self.axis), check_vma=False)
        def qkv_local(x_r, w_loc):
            return qmm(x_r, w_loc)

        return qkv_local(x, self.w_qkv)

    def _o_proj(self, o, mode: str):
        """Mode-dispatched O projection epilogue (shared by both cached
        forwards): "dist" = GEMM-RS, "gemm_ar" = fused GEMM+AR, "ar" =
        partial GEMM + AR kernel, "xla"/"flash" = partial GEMM + psum."""
        axis = self.axis
        if mode == "dist":
            rs_ctx = create_gemm_rs_context(self.mesh, axis)
            return gemm_rs(o, self.w_o, rs_ctx)
        if mode == "gemm_ar":
            ctx = create_gemm_ar_context(self.mesh, axis)
            return gemm_allreduce(o, self.w_o, ctx)
        if mode == "ar":
            from triton_dist_tpu.kernels.quant import qmm, qspec

            @functools.partial(jax.shard_map, mesh=self.mesh,
                               in_specs=(P(None, axis),
                                         qspec(self.w_o, P(axis, None),
                                               P(None))),
                               out_specs=P(axis, None, None),
                               check_vma=False)
            def o_partial(o_loc, wo_loc):
                return qmm(o_loc, wo_loc)[None]

            return all_reduce(o_partial(o, self.w_o), mesh=self.mesh,
                              axis=axis)
        # "xla" oracle and "flash": psum epilogue
        return self._down_psum(o)

    def fwd_cached(self, x, cos, sin, batch: int, kv, kv_start,
                   mode: str = "dist"):
        """Full attention block with KV cache: QKV proj -> cached attend
        -> O proj, per forward mode. x: [B*S, D] (row-sharded for "dist",
        replicated otherwise). kv: the per-layer cache tuple from
        KVCache.layer() — (ck, cv) bf16 or (ck, cv, ks, vs) int8.
        Returns (y, kv).

        Modes: "xla" (jnp oracle attention + psum), "flash" (Pallas
        flash-decode attention + psum — the single-chip framework path),
        "dist"/"ar"/"gemm_ar" (overlapped comm kernels + flash-decode).
        """
        impl = "ref" if mode == "xla" else "flash"
        qkv = self._qkv_proj(x, mode)
        o, kv = self._attend_cached(qkv, cos, sin, batch, kv,
                                    kv_start, impl)
        return self._o_proj(o, mode), kv

    def fwd_cached_slots(self, x, cos, sin, batch: int, kv, pos,
                         mode: str = "dist"):
        """Slot-masked decode attention block (continuous batching,
        models/scheduler.py): one token per batch row, each row at its
        OWN sequence position. x: [B, D]; pos: [B] int32 — row b's KV
        goes to column pos[b] of its cache row and it attends columns
        [0, pos[b]]. Same mode dispatch as fwd_cached; the decode step
        stays ONE program regardless of the per-slot position mix."""
        impl = "ref" if mode == "xla" else "flash"
        qkv = self._qkv_proj(x, mode)
        o, kv = self._attend_cached_slots(qkv, cos, sin, batch, kv,
                                          pos, impl)
        return self._o_proj(o, mode), kv
