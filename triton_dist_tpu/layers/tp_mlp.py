"""Tensor-parallel MLP (SwiGLU) with the reference's forward-mode switch.

TPU-native re-design of `python/triton_dist/layers/nvidia/tp_mlp.py`
(`TP_MLP:52` — torch_fwd / dist_triton_fwd (AG-GEMM -> GEMM-RS :143) /
AR fwd :177 / fused GEMM-AR fwd :205; weight sharding shard_local :38).

Forward modes:
  "xla"      — pure-XLA oracle (sharding-annotated jnp; XLA inserts the
               collectives). The role torch+NCCL plays in the reference.
  "dist"     — ag_gemm -> swiglu -> gemm_rs, comm hidden inside Pallas
               kernels (sequence-sharded activations).
  "ar"       — local partial GEMMs + explicit all_reduce kernel
               (replicated activations, decode-style).
  "gemm_ar"  — fused gemm_allreduce kernel for the down projection.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from triton_dist_tpu.kernels import (ag_gemm, all_reduce, create_ag_gemm_context,
                                     create_gemm_ar_context,
                                     create_gemm_rs_context, gemm_allreduce,
                                     gemm_rs)
from triton_dist_tpu.kernels.swiglu import swiglu_ref
from triton_dist_tpu.layers.common import shard_cols_packed


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TP_MLP:
    """Weights (pytree leaves) + static TP config.

    w_gate_up: [D, 2*I] — n per-rank blocks, each [gate_r | up_r]
               (column-parallel; built by `init` via shard_cols_packed).
    w_down:    [I, D]   — row-parallel.
    """

    w_gate_up: jax.Array
    w_down: jax.Array
    mesh: Mesh = dataclasses.field(metadata=dict(static=True))
    axis: str = dataclasses.field(metadata=dict(static=True))

    @staticmethod
    def init(w_gate, w_up, w_down, *, mesh: Mesh, axis: str = "tp"):
        """Shard+pack plain [D,I]/[D,I]/[I,D] weights onto the mesh
        (reference: shard_local, tp_mlp.py:38)."""
        n = mesh.shape[axis]
        packed = shard_cols_packed([w_gate, w_up], n)
        packed = jax.device_put(packed, NamedSharding(mesh, P(None, axis)))
        w_down = jax.device_put(jnp.asarray(w_down),
                                NamedSharding(mesh, P(axis, None)))
        return TP_MLP(w_gate_up=packed, w_down=w_down, mesh=mesh, axis=axis)

    # -- contexts are created lazily per call-site jit; they carry only
    # static config so this is free (unlike the reference's symmetric
    # buffer allocation, tp_mlp.py:116)
    def _ctxs(self):
        return (create_ag_gemm_context(self.mesh, self.axis),
                create_gemm_rs_context(self.mesh, self.axis))

    def _local_swiglu(self, c):
        """Apply SwiGLU on each rank's [gate_r | up_r] block."""
        import functools

        @functools.partial(jax.shard_map, mesh=self.mesh,
                           in_specs=P(None, self.axis),
                           out_specs=P(None, self.axis), check_vma=False)
        def f(c_loc):
            return swiglu_ref(c_loc)

        return f(c)

    def fwd_xla(self, x):
        """Pure-XLA oracle (reference: torch_fwd, tp_mlp.py:~100): jnp +
        XLA psum collective — the torch/NCCL role from the reference.
        QuantW weights dequant via qmm (the int8 decode config runs
        every mode)."""
        import functools
        from triton_dist_tpu.kernels.quant import QuantW, qmm, qspec
        if isinstance(self.w_gate_up, QuantW):
            @functools.partial(
                jax.shard_map, mesh=self.mesh,
                in_specs=(P(None, None),
                          qspec(self.w_gate_up, P(None, self.axis),
                                P(self.axis))),
                out_specs=P(None, self.axis), check_vma=False)
            def up(x_r, w_loc):
                return qmm(x_r, w_loc)

            c = up(x, self.w_gate_up)
        else:
            c = x @ self.w_gate_up
        h = self._local_swiglu(c)

        @functools.partial(jax.shard_map, mesh=self.mesh,
                           in_specs=(P(None, self.axis),
                                     qspec(self.w_down, P(self.axis, None),
                                           P(None))),
                           out_specs=P(None, None), check_vma=False)
        def down(h_loc, wd_loc):
            return jax.lax.psum(qmm(h_loc, wd_loc), self.axis)

        return down(h, self.w_down)

    def fwd_dist(self, x):
        """AG-GEMM -> SwiGLU -> GEMM-RS (reference: dist_triton_fwd,
        tp_mlp.py:143). x: [M, D] sharded on rows over the TP axis."""
        ag_ctx, rs_ctx = self._ctxs()
        c = ag_gemm(x, self.w_gate_up, ag_ctx)     # [M, 2I] P(None, tp)
        h = self._local_swiglu(c)                  # [M, I]  P(None, tp)
        return gemm_rs(h, self.w_down, rs_ctx)     # [M, D]  P(tp, None)

    def fwd_ar(self, x):
        """Local GEMMs + explicit AR kernel (reference: AR fwd,
        tp_mlp.py:177). x replicated; returns replicated."""
        n = self.mesh.shape[self.axis]
        axis = self.axis

        import functools
        from triton_dist_tpu.kernels.quant import qmm, qspec

        @functools.partial(jax.shard_map, mesh=self.mesh,
                           in_specs=(P(None, None),
                                     qspec(self.w_gate_up, P(None, axis),
                                           P(axis)),
                                     qspec(self.w_down, P(axis, None),
                                           P(None))),
                           out_specs=P(axis, None, None), check_vma=False)
        def partial_mlp(x_r, wgu_loc, wd_loc):
            c = qmm(x_r, wgu_loc)
            h = swiglu_ref(c)
            return qmm(h, wd_loc)[None]

        parts = partial_mlp(x, self.w_gate_up, self.w_down)  # [n, M, D]
        return all_reduce(parts, mesh=self.mesh, axis=axis)

    def fwd_gemm_ar(self, x):
        """Fused GEMM+AR for the down projection (reference: fused
        GEMM-AR fwd, tp_mlp.py:205)."""
        axis = self.axis

        import functools
        from triton_dist_tpu.kernels.quant import qmm, qspec

        @functools.partial(jax.shard_map, mesh=self.mesh,
                           in_specs=(P(None, None),
                                     qspec(self.w_gate_up, P(None, axis),
                                           P(axis))),
                           out_specs=P(None, axis), check_vma=False)
        def up(x_r, wgu_loc):
            return swiglu_ref(qmm(x_r, wgu_loc))

        h = up(x, self.w_gate_up)                   # [M, I] P(None, tp)
        ctx = create_gemm_ar_context(self.mesh, axis)
        return gemm_allreduce(h, self.w_down, ctx)  # [M, D] replicated

    def fwd_flash(self, x):
        """Single-chip framework path: local GEMMs with the fused Pallas
        SwiGLU kernel between them + psum epilogue (the mode the 1-chip
        bench runs; comm degenerates, the kernels don't). Weights may be
        int8-quantized (kernels/quant.py) — the decode bandwidth path."""
        from triton_dist_tpu.kernels.quant import qmm, qspec
        from triton_dist_tpu.kernels.swiglu import swiglu as swiglu_pallas
        axis = self.axis

        import functools
        @functools.partial(jax.shard_map, mesh=self.mesh,
                           in_specs=(P(None, None),
                                     qspec(self.w_gate_up, P(None, axis),
                                           P(axis)),
                                     qspec(self.w_down, P(axis, None),
                                           P(None))),
                           out_specs=P(None, None), check_vma=False)
        def f(x_r, wgu_loc, wd_loc):
            h = swiglu_pallas(qmm(x_r, wgu_loc))
            return jax.lax.psum(qmm(h, wd_loc), axis)

        return f(x, self.w_gate_up, self.w_down)

    def fwd_train(self, x, impl: str = "dist"):
        """Differentiable TP MLP for training: custom-VJP AG-GEMM ->
        SwiGLU -> custom-VJP GEMM-RS (kernels/grad.py); the backward of
        each projection is itself a fused comm kernel. impl="ref" is the
        pure-XLA oracle for differential gradient tests."""
        if impl != "dist":
            return self.fwd_xla(x)
        from triton_dist_tpu.kernels.grad import ag_gemm_grad, gemm_rs_grad
        c = ag_gemm_grad(self.mesh, self.axis)(x, self.w_gate_up)
        h = self._local_swiglu(c)
        return gemm_rs_grad(self.mesh, self.axis)(h, self.w_down)

    def __call__(self, x, mode: str = "dist"):
        """Mode switch (reference: DenseLLM set_fwd, models/dense.py:84)."""
        return dict(xla=self.fwd_xla, dist=self.fwd_dist, ar=self.fwd_ar,
                    gemm_ar=self.fwd_gemm_ar, flash=self.fwd_flash)[mode](x)
