"""Expert-parallel MoE layer: experts sharded across devices, tokens
routed to their experts' owners over ICI.

TPU-native re-design of the reference EP layers
(`python/triton_dist/layers/nvidia/ep_a2a_layer.py` `EpAll2AllOp`,
fused variant `ep_a2a_fused_layer.py`, low-latency inference variant
`ep_ll_a2a_layer.py`; training wrapper
`function/nvidia/ep_moe_fused.py:42`).

Forward = dispatch (one-sided a2a puts) -> grouped GEMM on each expert
owner -> combine (reverse puts + topk-weighted reduce), all inside ONE
shard_map over the ep axis — the shard_map body is the per-rank program
the reference writes per-GPU, with the Pallas a2a kernels as the data
plane (kernels/ep_a2a.py documents the capacity-based redesign of the
splits exchange)."""

from __future__ import annotations

import dataclasses
from typing import Optional
import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from triton_dist_tpu.kernels.ep_a2a import (combine_a2a, combine_from_slots,
                                            dispatch_a2a, dispatch_a2a_int8,
                                            expert_token_counts,
                                            fill_send_buffers,
                                            group_by_expert, pack_rows_int8,
                                            plan_dispatch,
                                            plan_dispatch_valid, route,
                                            unpack_rows_int8)
from triton_dist_tpu.kernels.group_gemm import grouped_gemm
from triton_dist_tpu.kernels.swiglu import swiglu_ref
from triton_dist_tpu.runtime import next_collective_id


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class EP_MoE:
    """Router + expert-sharded SwiGLU MLPs.

    w_router:  [D, E] replicated.
    w_gate_up: [E, D, 2I] sharded P(ep, None, None) — E/n experts per
               device, full intermediate (packed [gate | up]).
    w_down:    [E, I, D] sharded P(ep, None, None).
    """

    w_router: jax.Array
    w_gate_up: jax.Array
    w_down: jax.Array
    mesh: Mesh = dataclasses.field(metadata=dict(static=True))
    axis: str = dataclasses.field(metadata=dict(static=True))
    top_k: int = dataclasses.field(metadata=dict(static=True))
    capacity_factor: float = dataclasses.field(
        default=2.0, metadata=dict(static=True))
    # two-tier EP: experts sharded over (slice_axis, axis) with the DCN
    # hop on slice_axis (mode="ep_2d"); None = single-tier ICI EP
    slice_axis: Optional[str] = dataclasses.field(
        default=None, metadata=dict(static=True))
    # int8 token payloads on the wire (reference: the fp8 online quant
    # of the LL EP protocol, low_latency_all_to_all_v2.py:55,213):
    # dispatch AND combine rows travel packed (kernels/ep_a2a.py
    # pack_rows_int8) at half the bf16 bytes; on fwd_ep_2d the packed
    # rows cross DCN and ICI without an intermediate dequant. Lossy
    # (one int8 rounding per direction), like the reference's fp8 wire.
    payload_int8: bool = dataclasses.field(
        default=False, metadata=dict(static=True))

    @staticmethod
    def init(w_router, w_gate, w_up, w_down, *, mesh: Mesh,
             axis: str = "tp", top_k: int,
             capacity_factor: float = 2.0,
             slice_axis: Optional[str] = None,
             payload_int8: bool = False) -> "EP_MoE":
        import numpy as np
        E = np.shape(w_gate)[0]      # no device transfer for the check
        n_ep = mesh.shape[axis] * (mesh.shape[slice_axis]
                                   if slice_axis else 1)
        if E % n_ep:
            raise ValueError(
                f"EP_MoE needs the expert count ({E}) divisible by the "
                f"expert-parallel axis size ({n_ep}, mesh axis "
                f"{axis!r}" + (f" x {slice_axis!r}" if slice_axis else
                               "") + "): each device owns a whole group "
                "of expert panels — pad the expert set or shrink the "
                "ep axis")
        packed = jnp.concatenate([jnp.asarray(w_gate), jnp.asarray(w_up)],
                                 axis=-1)               # [E, D, 2I]
        espec = (P((slice_axis, axis), None, None) if slice_axis
                 else P(axis, None, None))
        packed = jax.device_put(packed, NamedSharding(mesh, espec))
        w_down = jax.device_put(jnp.asarray(w_down),
                                NamedSharding(mesh, espec))
        return EP_MoE(w_router=jnp.asarray(w_router), w_gate_up=packed,
                      w_down=w_down, mesh=mesh, axis=axis, top_k=top_k,
                      capacity_factor=capacity_factor,
                      slice_axis=slice_axis, payload_int8=payload_int8)

    @property
    def num_experts(self) -> int:
        return self.w_router.shape[1]

    def quantize_int8_experts(self) -> "EP_MoE":
        """Expert panels -> QuantW (int8 + per-expert per-output-column
        scales), for mode='ep_fused' — the fused kernel streams int8
        panels and dequants after each dot (its weight stream is the
        measured bandwidth bound at tiled shapes; reference analog: fp8
        weights through the fused grouped GEMM, ep_all2all_fused.py:599).
        The chain paths (fwd_ep/fwd_ep_2d/fwd_xla) do not take QuantW —
        quantize only the EP_MoE instance you run fused."""
        from triton_dist_tpu.kernels.quant import quantize_int8
        return dataclasses.replace(
            self, w_gate_up=quantize_int8(self.w_gate_up),
            w_down=quantize_int8(self.w_down))

    def _caps(self, t_loc: int):
        """(pair capacity, per-expert capacity): static shapes standing in
        for the reference's splits exchange.

        capacity_factor='dropless' sizes both to their provable
        worst-case bounds (every routed entry of a rank to one
        destination / one expert), trading memory for the reference's
        never-drop semantics (its exact splits exchange, ep_a2a.py:382)
        under static shapes. Any float factor is the fast capacity trade
        — then drops are COUNTED (DispatchPlan.dropped,
        group_by_expert's third output) and warned in-program."""
        n = self.mesh.shape[self.axis]
        epr = self.num_experts // n
        # a2a kernels slice send buffers at pl.ds(p * cap, cap), which
        # Mosaic requires sublane-tile-aligned on real TPUs: 8 rows for
        # f32/bf16 payloads, 32 for the packed int8 wire
        r = 32 if self.payload_int8 else 8
        if self.capacity_factor == "dropless":
            # all of a rank's entries to one destination / one expert
            pair = -(-t_loc * self.top_k // r) * r
            return pair, n * pair
        pair = int(self.capacity_factor * self.top_k * t_loc / n) + 1
        pair = min(max(r, -(-pair // r) * r),
                   -(-t_loc * self.top_k // r) * r)
        e_cap = int(self.capacity_factor * n * pair / epr) + 1
        e_cap = min(max(8, -(-e_cap // 8) * 8), n * pair)
        return pair, e_cap

    def fwd_ep(self, x, disp=None, comb=None, gemm=None,
               return_stats: bool = False, warn_drops: bool = True):
        """x: [T, D] row-sharded over the ep axis -> same sharding.
        disp/comb/gemm swap the a2a and grouped-GEMM callables (the
        train path passes the custom-VJP wrappers).

        return_stats=True additionally returns {"dropped": scalar,
        "expert_tokens": [E] int32} — the global count of routed
        entries lost to capacity this step (always 0 with
        capacity_factor='dropless') and the global per-expert routed
        load (the serving telemetry's `expert_tokens{expert=...}`
        gauges); warn_drops keeps an in-program warning on the others
        (dropless-or-loud)."""
        n = self.mesh.shape[self.axis]
        axis = self.axis
        epr = self.num_experts // n
        k = self.top_k
        T = x.shape[0]
        cap, e_cap = self._caps(T // n)
        assert (disp is None) == (comb is None), \
            "disp and comb must be overridden together"
        if disp is None:
            cid = next_collective_id()
            if self.payload_int8 and n > 1:
                D = x.shape[1]

                def disp(sx, sm):
                    rp, rm = dispatch_a2a_int8(
                        pack_rows_int8(sx), sm, n=n, axis=axis,
                        collective_id=cid)
                    return unpack_rows_int8(rp, D, sx.dtype), rm

                def comb(ys):
                    yp = combine_a2a(pack_rows_int8(ys), n=n, axis=axis,
                                     collective_id=cid)
                    return unpack_rows_int8(yp, D, ys.dtype)
            else:
                disp = functools.partial(dispatch_a2a, n=n, axis=axis,
                                         collective_id=cid)
                comb = functools.partial(combine_a2a, n=n, axis=axis,
                                         collective_id=cid)
        gemm = gemm or grouped_gemm

        @functools.partial(
            jax.shard_map, mesh=self.mesh,
            in_specs=(P(axis, None), P(None, None),
                      P(axis, None, None), P(axis, None, None)),
            out_specs=(P(axis, None), P(None), P(None)), check_vma=False)
        def _f(x_loc, router, wgu_loc, wd_loc):
            t_loc = x_loc.shape[0]
            topk_w, topk_idx = route(x_loc @ router.astype(x_loc.dtype), k)
            plan = plan_dispatch(topk_idx, n, epr, cap)
            send_x, send_meta = fill_send_buffers(x_loc, topk_idx, plan,
                                                  n, epr, cap)
            recv_x, recv_meta = disp(send_x, send_meta)
            x_e, inv_slot, r_drop = group_by_expert(recv_x, recv_meta,
                                                    epr, e_cap)
            h = gemm(x_e, wgu_loc.astype(x_e.dtype))
            h = swiglu_ref(h)
            y_e = gemm(h, wd_loc.astype(x_e.dtype))
            y_flat = y_e.reshape(epr * e_cap, -1)
            gathered = jnp.take(y_flat,
                                jnp.minimum(inv_slot, epr * e_cap - 1),
                                axis=0)
            y_slots = gathered * (inv_slot < epr * e_cap)[:, None].astype(
                gathered.dtype)
            y_back = comb(y_slots)
            y = combine_from_slots(y_back, plan, topk_w, t_loc)
            loud = (warn_drops and self.capacity_factor != "dropless")
            if loud or return_stats:
                dropped = jax.lax.psum(plan.dropped + r_drop, axis)
                if loud:
                    from triton_dist_tpu.kernels.ep_a2a import warn_on_drops
                    warn_on_drops(dropped, "EP_MoE.fwd_ep")
            else:
                # no observer: skip the per-step cross-rank scalar psum
                dropped = jnp.zeros((), jnp.int32)
            if return_stats:
                counts = jax.lax.psum(
                    expert_token_counts(topk_idx, self.num_experts),
                    axis)
            else:
                counts = jnp.zeros((self.num_experts,), jnp.int32)
            return y.astype(x_loc.dtype), dropped[None], counts

        y, dropped, counts = _f(x, self.w_router, self.w_gate_up,
                                self.w_down)
        if return_stats:
            return y, {"dropped": dropped[0], "expert_tokens": counts}
        return y

    def _cap_e(self, t_loc: int) -> int:
        """Per-(source, GLOBAL expert) capacity for the fused layout —
        rounded UP to 8-row tiles AFTER every clamp (the fused kernel's
        pl.ds slices need tile-aligned offsets on real TPUs)."""
        E, k = self.num_experts, self.top_k
        if self.capacity_factor == "dropless":
            cap = t_loc * k
        else:
            cap = min(int(self.capacity_factor * k * t_loc / E) + 1,
                      t_loc * k)
        return max(8, -(-cap // 8) * 8)

    def fwd_ep_2d(self, x, return_stats: bool = False,
                  warn_drops: bool = True):
        """Two-tier EP over a ("dcn", ep) mesh: the DCN hop is an XLA
        all_to_all across slices (DCN has no one-sided semantics), the
        intra-slice hop is the one-sided ICI a2a kernel — the TPU
        re-design of the reference's INTER-NODE EP dispatch/combine
        (ep_a2a.py:79 dispatch, :382 cross-node splits/offset exchange;
        VERDICT r3 missing #2). Each token crosses DCN exactly once per
        direction: route -> slice-capacity slots -> DCN a2a -> re-plan
        within the slice on arrived metadata (plan_dispatch_valid, the
        static-shape analog of the reference's post-exchange recv-offset
        pass) -> ICI one-sided a2a -> expert MLPs -> the exact reverse.

        x: [T, D] row-sharded over (slice_axis, axis) -> same."""
        assert self.slice_axis, "init with slice_axis= for mode='ep_2d'"
        sax, cax = self.slice_axis, self.axis
        n_s, n_c = self.mesh.shape[sax], self.mesh.shape[cax]
        E, k = self.num_experts, self.top_k
        eps_ = E // n_s                 # experts per slice
        epr = eps_ // n_c               # experts per chip
        T = x.shape[0]
        t_loc = T // (n_s * n_c)
        D = x.shape[1]
        q8 = self.payload_int8
        # int8 wire: ICI slices need 32-row sublane tiles (see _caps)
        _r = 32 if q8 else 8
        r8 = lambda v: max(_r, -(-v // _r) * _r)
        if self.capacity_factor == "dropless":
            cap_s = r8(t_loc * k)
            cap_c = r8(n_s * cap_s)       # all arrivals to one chip
            e_cap = n_c * cap_c           # .. and one expert
        else:
            cf = float(self.capacity_factor)
            cap_s = min(r8(int(cf * k * t_loc / n_s) + 1), r8(t_loc * k))
            cap_c = min(r8(int(cf * n_s * cap_s / n_c) + 1),
                        r8(n_s * cap_s))
            e_cap = min(r8(int(cf * n_c * cap_c / epr) + 1), n_c * cap_c)
        cid = next_collective_id()

        @functools.partial(
            jax.shard_map, mesh=self.mesh,
            in_specs=(P((sax, cax), None), P(None, None),
                      P((sax, cax), None, None),
                      P((sax, cax), None, None)),
            out_specs=(P((sax, cax), None), P(None), P(None)),
            check_vma=False)
        def _f(x_loc, router, wgu_loc, wd_loc):
            topk_w, topk_idx = route(x_loc @ router.astype(x_loc.dtype), k)
            # int8 wire (payload_int8): tokens pack ONCE here and cross
            # BOTH hops packed — the re-plan between tiers only permutes
            # rows, so no intermediate dequant/requant happens and the
            # per-direction loss is a single int8 rounding (reference:
            # the fp8 wire of low_latency_all_to_all_v2.py:55,213,
            # applied to the inter-node tier where bytes hurt most)
            wire_x = pack_rows_int8(x_loc) if q8 else x_loc
            Dw = wire_x.shape[1]
            # ---- tier 1 (DCN): group by destination SLICE; the meta
            # carries the within-slice expert id for tier 2
            plan1 = plan_dispatch(topk_idx, n_s, eps_, cap_s)
            send_x, send_meta = fill_send_buffers(
                wire_x, topk_idx, plan1, n_s, eps_, cap_s)
            rx = jax.lax.all_to_all(
                send_x.reshape(n_s, cap_s, Dw), sax, 0, 0
                ).reshape(n_s * cap_s, Dw)
            rm = jax.lax.all_to_all(
                send_meta.reshape(n_s, cap_s, 2), sax, 0, 0
                ).reshape(n_s * cap_s, 2)
            # ---- tier 2 (ICI): re-plan the arrived slots by owning chip
            e_slice = rm[:, 0]
            plan2, drop2 = plan_dispatch_valid(
                e_slice, rm[:, 1] > 0, n_c, epr, cap_c)
            send2_x, send2_m = fill_send_buffers(
                rx, e_slice[:, None], plan2, n_c, epr, cap_c)
            if q8:
                recv_p, recv_m = dispatch_a2a_int8(
                    send2_x, send2_m, n=n_c, axis=cax, collective_id=cid)
                recv_x = unpack_rows_int8(recv_p, D, x_loc.dtype)
            else:
                recv_x, recv_m = dispatch_a2a(send2_x, send2_m, n=n_c,
                                              axis=cax, collective_id=cid)
            x_e, inv_slot, r_drop = group_by_expert(recv_x, recv_m, epr,
                                                    e_cap)
            h = grouped_gemm(x_e, wgu_loc.astype(x_e.dtype))
            h = swiglu_ref(h)
            y_e = grouped_gemm(h, wd_loc.astype(x_e.dtype))
            y_flat = y_e.reshape(epr * e_cap, -1)
            gathered = jnp.take(y_flat,
                                jnp.minimum(inv_slot, epr * e_cap - 1),
                                axis=0)
            y_slots = gathered * (inv_slot < epr * e_cap)[:, None].astype(
                gathered.dtype)
            # combine wire: pack once, cross ICI then DCN packed,
            # unpack once before the weighted reduce
            y_wire = pack_rows_int8(y_slots) if q8 else y_slots
            y_back2 = combine_a2a(y_wire, n=n_c, axis=cax,
                                  collective_id=cid)
            # tier-2 slots -> arrived-row order (weights applied only at
            # the final tier-1 combine)
            y_arr = (jnp.take(y_back2,
                              jnp.minimum(plan2.slot, n_c * cap_c - 1),
                              axis=0)
                     * plan2.valid[:, None].astype(y_back2.dtype))
            y_back1 = jax.lax.all_to_all(
                y_arr.reshape(n_s, cap_s, Dw), sax, 0, 0
                ).reshape(n_s * cap_s, Dw)
            if q8:
                y_back1 = unpack_rows_int8(y_back1, D, x_loc.dtype)
            y = combine_from_slots(y_back1, plan1, topk_w, t_loc)
            loud = (warn_drops and self.capacity_factor != "dropless")
            if loud or return_stats:
                dropped = jax.lax.psum(
                    plan1.dropped + drop2 + r_drop, (sax, cax))
                if loud:
                    from triton_dist_tpu.kernels.ep_a2a import warn_on_drops
                    warn_on_drops(dropped, "EP_MoE.fwd_ep_2d")
            else:
                dropped = jnp.zeros((), jnp.int32)
            if return_stats:
                counts = jax.lax.psum(
                    expert_token_counts(topk_idx, E), (sax, cax))
            else:
                counts = jnp.zeros((E,), jnp.int32)
            return y.astype(x_loc.dtype), dropped[None], counts

        y, dropped, counts = _f(x, self.w_router, self.w_gate_up,
                                self.w_down)
        if return_stats:
            return y, {"dropped": dropped[0], "expert_tokens": counts}
        return y

    def fwd_ep_fused(self, x, return_stats: bool = False,
                     warn_drops: bool = True,
                     fused_block_i: Optional[int] = None,
                     fused_weight_buffers: int = 2,
                     fused_ablate: frozenset = frozenset(),
                     fused_straggler=None):
        """ONE-kernel EP MoE (reference: ep_all2all_fused.py:73-560,
        VERDICT r2 missing #3): dispatch puts -> per-arrival expert
        MLPs -> combine puts from the GEMM epilogue, one pallas_call
        instead of the fwd_ep chain (dispatch kernel + grouped GEMMs +
        combine kernel, each boundary an HBM round-trip + barrier).

        The grouping that the reference's tile scheduler does with
        dynamic gathers happens in the LAYOUT here: the plan assigns
        slots per GLOBAL expert (one destination per expert), so every
        peer's slab arrives pre-grouped (kernels/ep_fused.py). x: [T, D]
        row-sharded over the ep axis -> same sharding."""
        from triton_dist_tpu.kernels.ep_fused import ep_moe_fused_device
        from triton_dist_tpu.kernels.quant import QuantW, qspec
        n = self.mesh.shape[self.axis]
        axis = self.axis
        E = self.num_experts
        k = self.top_k
        T = x.shape[0]
        t_loc = T // n
        cap_e = self._cap_e(t_loc)
        cid = next_collective_id()
        wq = isinstance(self.w_gate_up, QuantW)

        @functools.partial(
            jax.shard_map, mesh=self.mesh,
            in_specs=(P(axis, None), P(None, None),
                      qspec(self.w_gate_up, P(axis, None, None),
                            P(axis, None)),
                      qspec(self.w_down, P(axis, None, None),
                            P(axis, None))),
            out_specs=(P(axis, None), P(None), P(None)), check_vma=False)
        def _f(x_loc, router, wgu_loc, wd_loc):
            topk_w, topk_idx = route(x_loc @ router.astype(x_loc.dtype), k)
            # one "destination" per GLOBAL expert: the slot layout IS
            # the expert grouping (experts are rank-major, so slab p =
            # slots of peer p's local experts)
            plan = plan_dispatch(topk_idx, E, 1, cap_e)
            send_x, _ = fill_send_buffers(x_loc, topk_idx, plan, E, 1,
                                          cap_e)
            yback = ep_moe_fused_device(
                send_x,
                wgu_loc if wq else wgu_loc.astype(x_loc.dtype),
                wd_loc if wq else wd_loc.astype(x_loc.dtype),
                n=n, axis=axis, cap_e=cap_e,
                collective_id=cid, block_i=fused_block_i,
                weight_buffers=fused_weight_buffers,
                ablate=fused_ablate, straggler=fused_straggler)
            y_flat = yback.reshape(E * cap_e, -1)
            y = combine_from_slots(y_flat, plan, topk_w, t_loc)
            # dropless-or-loud holds on this path too
            loud = (warn_drops and self.capacity_factor != "dropless")
            if loud or return_stats:
                dropped = jax.lax.psum(plan.dropped, axis)
                if loud:
                    from triton_dist_tpu.kernels.ep_a2a import warn_on_drops
                    warn_on_drops(dropped, "EP_MoE.fwd_ep_fused")
            else:
                dropped = jnp.zeros((), jnp.int32)
            if return_stats:
                counts = jax.lax.psum(expert_token_counts(topk_idx, E),
                                      axis)
            else:
                counts = jnp.zeros((E,), jnp.int32)
            return y.astype(x_loc.dtype), dropped[None], counts

        y, dropped, counts = _f(x, self.w_router, self.w_gate_up,
                                self.w_down)
        if return_stats:
            return y, {"dropped": dropped[0], "expert_tokens": counts}
        return y

    def fwd_xla(self, x, return_stats: bool = False):
        """Oracle (x row-sharded): dense all-experts math with XLA
        collectives — all_gather tokens, each device computes its experts
        densely, psum the weighted sum, slice back. The oracle never
        drops; its return_stats counts the routed load only (the gauge
        differential against the routed paths)."""
        axis = self.axis
        n = self.mesh.shape[axis]
        epr = self.num_experts // n
        k = self.top_k
        E = self.num_experts

        @functools.partial(
            jax.shard_map, mesh=self.mesh,
            in_specs=(P(axis, None), P(None, None),
                      P(axis, None, None), P(axis, None, None)),
            out_specs=(P(axis, None), P(None)), check_vma=False)
        def _f(x_loc, router, wgu_loc, wd_loc):
            me = jax.lax.axis_index(axis)
            xg = jax.lax.all_gather(x_loc, axis, axis=0, tiled=True)
            topk_w, topk_idx = route(xg @ router.astype(xg.dtype), k)
            h = jnp.einsum("md,edf->emf", xg, wgu_loc.astype(xg.dtype))
            h = swiglu_ref(h)
            y_all = jnp.einsum("emf,efd->emd", h, wd_loc.astype(xg.dtype))
            # weights restricted to this device's experts
            onehot = jax.nn.one_hot(topk_idx - me * epr, epr,
                                    dtype=jnp.float32)
            w_e = jnp.einsum("tk,tke->te", topk_w, onehot)
            y = jnp.einsum("te,etd->td", w_e, y_all.astype(jnp.float32))
            y = jax.lax.psum(y, axis)
            t_loc = x_loc.shape[0]
            # every rank routes the same gathered tokens -> replicated
            counts = expert_token_counts(topk_idx, E)
            return (jax.lax.dynamic_slice_in_dim(
                y, me * t_loc, t_loc).astype(x_loc.dtype), counts)

        y, counts = _f(x, self.w_router, self.w_gate_up, self.w_down)
        if return_stats:
            return y, {"dropped": jnp.zeros((), jnp.int32),
                       "expert_tokens": counts}
        return y

    def fwd_train(self, x):
        """Training path through the framework kernels (reference: the
        autograd Function over the fused EP ops,
        function/nvidia/ep_moe_fused.py:42): fwd_ep's per-rank program
        with custom-VJP a2a kernels (each a2a's adjoint IS the reverse
        a2a kernel) and custom-VJP grouped GEMMs. Gradients reach the
        router (via the top-k softmax weights), both expert
        projections, and x."""
        from triton_dist_tpu.kernels.grad import (combine_a2a_grad,
                                                  dispatch_a2a_grad,
                                                  grouped_gemm_grad)
        n = self.mesh.shape[self.axis]
        return self.fwd_ep(x, disp=dispatch_a2a_grad(n, self.axis),
                           comb=combine_a2a_grad(n, self.axis),
                           gemm=grouped_gemm_grad())

    def __call__(self, x, mode: str = "ep", **kw):
        if mode == "train":
            return self.fwd_train(x, **kw)
        if mode == "ep_fused":
            return self.fwd_ep_fused(x, **kw)
        if mode == "ep_2d":
            return self.fwd_ep_2d(x, **kw)
        if mode == "ep":
            return self.fwd_ep(x, **kw)
        if kw:
            raise TypeError(f"mode='xla' takes no extra kwargs: {kw}")
        return self.fwd_xla(x)
