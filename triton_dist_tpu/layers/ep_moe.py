"""Expert-parallel MoE layer: experts sharded across devices, tokens
routed to their experts' owners over ICI.

TPU-native re-design of the reference EP layers
(`python/triton_dist/layers/nvidia/ep_a2a_layer.py` `EpAll2AllOp`,
fused variant `ep_a2a_fused_layer.py`, low-latency inference variant
`ep_ll_a2a_layer.py`; training wrapper
`function/nvidia/ep_moe_fused.py:42`).

Forward = dispatch (one-sided a2a puts) -> grouped GEMM on each expert
owner -> combine (reverse puts + topk-weighted reduce), all inside ONE
shard_map over the ep axis — the shard_map body is the per-rank program
the reference writes per-GPU, with the Pallas a2a kernels as the data
plane (kernels/ep_a2a.py documents the capacity-based redesign of the
splits exchange)."""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from triton_dist_tpu.kernels.ep_a2a import (combine_a2a, combine_from_slots,
                                            dispatch_a2a, fill_send_buffers,
                                            group_by_expert, plan_dispatch,
                                            route)
from triton_dist_tpu.kernels.group_gemm import grouped_gemm
from triton_dist_tpu.kernels.swiglu import swiglu_ref
from triton_dist_tpu.runtime import next_collective_id


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class EP_MoE:
    """Router + expert-sharded SwiGLU MLPs.

    w_router:  [D, E] replicated.
    w_gate_up: [E, D, 2I] sharded P(ep, None, None) — E/n experts per
               device, full intermediate (packed [gate | up]).
    w_down:    [E, I, D] sharded P(ep, None, None).
    """

    w_router: jax.Array
    w_gate_up: jax.Array
    w_down: jax.Array
    mesh: Mesh = dataclasses.field(metadata=dict(static=True))
    axis: str = dataclasses.field(metadata=dict(static=True))
    top_k: int = dataclasses.field(metadata=dict(static=True))
    capacity_factor: float = dataclasses.field(
        default=2.0, metadata=dict(static=True))

    @staticmethod
    def init(w_router, w_gate, w_up, w_down, *, mesh: Mesh,
             axis: str = "tp", top_k: int,
             capacity_factor: float = 2.0) -> "EP_MoE":
        packed = jnp.concatenate([jnp.asarray(w_gate), jnp.asarray(w_up)],
                                 axis=-1)               # [E, D, 2I]
        packed = jax.device_put(packed,
                                NamedSharding(mesh, P(axis, None, None)))
        w_down = jax.device_put(jnp.asarray(w_down),
                                NamedSharding(mesh, P(axis, None, None)))
        return EP_MoE(w_router=jnp.asarray(w_router), w_gate_up=packed,
                      w_down=w_down, mesh=mesh, axis=axis, top_k=top_k,
                      capacity_factor=capacity_factor)

    @property
    def num_experts(self) -> int:
        return self.w_router.shape[1]

    def _caps(self, t_loc: int):
        """(pair capacity, per-expert capacity): static shapes standing in
        for the reference's splits exchange.

        capacity_factor='dropless' sizes both to their provable
        worst-case bounds (every routed entry of a rank to one
        destination / one expert), trading memory for the reference's
        never-drop semantics (its exact splits exchange, ep_a2a.py:382)
        under static shapes. Any float factor is the fast capacity trade
        — then drops are COUNTED (DispatchPlan.dropped,
        group_by_expert's third output) and warned in-program."""
        n = self.mesh.shape[self.axis]
        epr = self.num_experts // n
        if self.capacity_factor == "dropless":
            # all of a rank's entries to one destination / one expert;
            # rounded up to whole 8-row sublane tiles — the a2a kernels
            # slice send buffers at pl.ds(p * cap, cap), which Mosaic
            # requires tile-aligned on real TPUs
            pair = -(-t_loc * self.top_k // 8) * 8
            return pair, n * pair
        pair = int(self.capacity_factor * self.top_k * t_loc / n) + 1
        pair = min(max(8, -(-pair // 8) * 8), t_loc * self.top_k)
        e_cap = int(self.capacity_factor * n * pair / epr) + 1
        e_cap = min(max(8, -(-e_cap // 8) * 8), n * pair)
        return pair, e_cap

    def fwd_ep(self, x, disp=None, comb=None, gemm=None,
               return_stats: bool = False, warn_drops: bool = True):
        """x: [T, D] row-sharded over the ep axis -> same sharding.
        disp/comb/gemm swap the a2a and grouped-GEMM callables (the
        train path passes the custom-VJP wrappers).

        return_stats=True additionally returns {"dropped": scalar} — the
        global count of routed entries lost to capacity this step
        (always 0 with capacity_factor='dropless'); warn_drops keeps an
        in-program warning on the others (dropless-or-loud)."""
        n = self.mesh.shape[self.axis]
        axis = self.axis
        epr = self.num_experts // n
        k = self.top_k
        T = x.shape[0]
        cap, e_cap = self._caps(T // n)
        assert (disp is None) == (comb is None), \
            "disp and comb must be overridden together"
        if disp is None:
            cid = next_collective_id()
            disp = functools.partial(dispatch_a2a, n=n, axis=axis,
                                     collective_id=cid)
            comb = functools.partial(combine_a2a, n=n, axis=axis,
                                     collective_id=cid)
        gemm = gemm or grouped_gemm

        @functools.partial(
            jax.shard_map, mesh=self.mesh,
            in_specs=(P(axis, None), P(None, None),
                      P(axis, None, None), P(axis, None, None)),
            out_specs=(P(axis, None), P(None)), check_vma=False)
        def _f(x_loc, router, wgu_loc, wd_loc):
            t_loc = x_loc.shape[0]
            topk_w, topk_idx = route(x_loc @ router.astype(x_loc.dtype), k)
            plan = plan_dispatch(topk_idx, n, epr, cap)
            send_x, send_meta = fill_send_buffers(x_loc, topk_idx, plan,
                                                  n, epr, cap)
            recv_x, recv_meta = disp(send_x, send_meta)
            x_e, inv_slot, r_drop = group_by_expert(recv_x, recv_meta,
                                                    epr, e_cap)
            h = gemm(x_e, wgu_loc.astype(x_e.dtype))
            h = swiglu_ref(h)
            y_e = gemm(h, wd_loc.astype(x_e.dtype))
            y_flat = y_e.reshape(epr * e_cap, -1)
            gathered = jnp.take(y_flat,
                                jnp.minimum(inv_slot, epr * e_cap - 1),
                                axis=0)
            y_slots = gathered * (inv_slot < epr * e_cap)[:, None].astype(
                gathered.dtype)
            y_back = comb(y_slots)
            y = combine_from_slots(y_back, plan, topk_w, t_loc)
            loud = (warn_drops and self.capacity_factor != "dropless")
            if loud or return_stats:
                dropped = jax.lax.psum(plan.dropped + r_drop, axis)
                if loud:
                    from triton_dist_tpu.kernels.ep_a2a import warn_on_drops
                    warn_on_drops(dropped, "EP_MoE.fwd_ep")
            else:
                # no observer: skip the per-step cross-rank scalar psum
                dropped = jnp.zeros((), jnp.int32)
            return y.astype(x_loc.dtype), dropped[None]

        y, dropped = _f(x, self.w_router, self.w_gate_up, self.w_down)
        if return_stats:
            return y, {"dropped": dropped[0]}
        return y

    def _cap_e(self, t_loc: int) -> int:
        """Per-(source, GLOBAL expert) capacity for the fused layout —
        rounded UP to 8-row tiles AFTER every clamp (the fused kernel's
        pl.ds slices need tile-aligned offsets on real TPUs)."""
        E, k = self.num_experts, self.top_k
        if self.capacity_factor == "dropless":
            cap = t_loc * k
        else:
            cap = min(int(self.capacity_factor * k * t_loc / E) + 1,
                      t_loc * k)
        return max(8, -(-cap // 8) * 8)

    def fwd_ep_fused(self, x, return_stats: bool = False,
                     warn_drops: bool = True):
        """ONE-kernel EP MoE (reference: ep_all2all_fused.py:73-560,
        VERDICT r2 missing #3): dispatch puts -> per-arrival expert
        MLPs -> combine puts from the GEMM epilogue, one pallas_call
        instead of the fwd_ep chain (dispatch kernel + grouped GEMMs +
        combine kernel, each boundary an HBM round-trip + barrier).

        The grouping that the reference's tile scheduler does with
        dynamic gathers happens in the LAYOUT here: the plan assigns
        slots per GLOBAL expert (one destination per expert), so every
        peer's slab arrives pre-grouped (kernels/ep_fused.py). x: [T, D]
        row-sharded over the ep axis -> same sharding."""
        from triton_dist_tpu.kernels.ep_fused import ep_moe_fused_device
        n = self.mesh.shape[self.axis]
        axis = self.axis
        E = self.num_experts
        k = self.top_k
        T = x.shape[0]
        t_loc = T // n
        cap_e = self._cap_e(t_loc)
        cid = next_collective_id()

        @functools.partial(
            jax.shard_map, mesh=self.mesh,
            in_specs=(P(axis, None), P(None, None),
                      P(axis, None, None), P(axis, None, None)),
            out_specs=(P(axis, None), P(None)), check_vma=False)
        def _f(x_loc, router, wgu_loc, wd_loc):
            topk_w, topk_idx = route(x_loc @ router.astype(x_loc.dtype), k)
            # one "destination" per GLOBAL expert: the slot layout IS
            # the expert grouping (experts are rank-major, so slab p =
            # slots of peer p's local experts)
            plan = plan_dispatch(topk_idx, E, 1, cap_e)
            send_x, _ = fill_send_buffers(x_loc, topk_idx, plan, E, 1,
                                          cap_e)
            yback = ep_moe_fused_device(
                send_x, wgu_loc.astype(x_loc.dtype),
                wd_loc.astype(x_loc.dtype), n=n, axis=axis, cap_e=cap_e,
                collective_id=cid)
            y_flat = yback.reshape(E * cap_e, -1)
            y = combine_from_slots(y_flat, plan, topk_w, t_loc)
            # dropless-or-loud holds on this path too
            loud = (warn_drops and self.capacity_factor != "dropless")
            if loud or return_stats:
                dropped = jax.lax.psum(plan.dropped, axis)
                if loud:
                    from triton_dist_tpu.kernels.ep_a2a import warn_on_drops
                    warn_on_drops(dropped, "EP_MoE.fwd_ep_fused")
            else:
                dropped = jnp.zeros((), jnp.int32)
            return y.astype(x_loc.dtype), dropped[None]

        y, dropped = _f(x, self.w_router, self.w_gate_up, self.w_down)
        if return_stats:
            return y, {"dropped": dropped[0]}
        return y

    def fwd_xla(self, x):
        """Oracle (x row-sharded): dense all-experts math with XLA
        collectives — all_gather tokens, each device computes its experts
        densely, psum the weighted sum, slice back."""
        axis = self.axis
        n = self.mesh.shape[axis]
        epr = self.num_experts // n
        k = self.top_k
        E = self.num_experts

        @functools.partial(
            jax.shard_map, mesh=self.mesh,
            in_specs=(P(axis, None), P(None, None),
                      P(axis, None, None), P(axis, None, None)),
            out_specs=P(axis, None), check_vma=False)
        def _f(x_loc, router, wgu_loc, wd_loc):
            me = jax.lax.axis_index(axis)
            xg = jax.lax.all_gather(x_loc, axis, axis=0, tiled=True)
            topk_w, topk_idx = route(xg @ router.astype(xg.dtype), k)
            h = jnp.einsum("md,edf->emf", xg, wgu_loc.astype(xg.dtype))
            h = swiglu_ref(h)
            y_all = jnp.einsum("emf,efd->emd", h, wd_loc.astype(xg.dtype))
            # weights restricted to this device's experts
            onehot = jax.nn.one_hot(topk_idx - me * epr, epr,
                                    dtype=jnp.float32)
            w_e = jnp.einsum("tk,tke->te", topk_w, onehot)
            y = jnp.einsum("te,etd->td", w_e, y_all.astype(jnp.float32))
            y = jax.lax.psum(y, axis)
            t_loc = x_loc.shape[0]
            return jax.lax.dynamic_slice_in_dim(
                y, me * t_loc, t_loc).astype(x_loc.dtype)

        return _f(x, self.w_router, self.w_gate_up, self.w_down)

    def fwd_train(self, x):
        """Training path through the framework kernels (reference: the
        autograd Function over the fused EP ops,
        function/nvidia/ep_moe_fused.py:42): fwd_ep's per-rank program
        with custom-VJP a2a kernels (each a2a's adjoint IS the reverse
        a2a kernel) and custom-VJP grouped GEMMs. Gradients reach the
        router (via the top-k softmax weights), both expert
        projections, and x."""
        from triton_dist_tpu.kernels.grad import (combine_a2a_grad,
                                                  dispatch_a2a_grad,
                                                  grouped_gemm_grad)
        n = self.mesh.shape[self.axis]
        return self.fwd_ep(x, disp=dispatch_a2a_grad(n, self.axis),
                           comb=combine_a2a_grad(n, self.axis),
                           gemm=grouped_gemm_grad())

    def __call__(self, x, mode: str = "ep"):
        if mode == "train":
            return self.fwd_train(x)
        if mode == "ep_fused":
            return self.fwd_ep_fused(x)
        return self.fwd_ep(x) if mode == "ep" else self.fwd_xla(x)
