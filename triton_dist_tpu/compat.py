"""Back-compat shims for older jax releases (the container's baked
toolchain may lag the APIs this repo targets).

The codebase is written against the modern surface — `jax.shard_map`,
`jax.sharding.AxisType`, `Mesh.axis_types`, `pltpu.CompilerParams`,
`pltpu.MemorySpace`, `pltpu.InterpretParams` — and this module maps
each one back onto its older spelling when the installed jax predates
the rename, so the oracle ("xla") and basic Pallas paths — including
the megakernels, which run under the generic interpreter — work on a
jax-0.4.x stack too. (0.4.x `Mesh.axis_types` is None rather than a
tuple; the call sites guard with `or ()` instead of a shim, since the
attribute is per-instance.)
Installed once from the package __init__; every shim is a no-op on a
modern jax. The TPU-interpreter-specific features (remote DMA,
semaphores, race detection) have NO pre-0.5 equivalent — kernels that
need them still require a modern jax; `interpret_mode()` degrades to
the generic `interpret=True` (see runtime/bootstrap.py).
"""

from __future__ import annotations

import enum
import functools


def install() -> None:
    import jax

    # --- jax.shard_map (top-level since ~0.6; check_vma renamed from
    # check_rep) -------------------------------------------------------
    if not hasattr(jax, "shard_map"):
        from jax.experimental.shard_map import shard_map as _shard_map

        @functools.wraps(_shard_map)
        def shard_map(f, **kw):
            if "check_vma" in kw:
                kw["check_rep"] = kw.pop("check_vma")
            return _shard_map(f, **kw)

        jax.shard_map = shard_map

    # --- jax.lax.axis_size (newer convenience; psum of a literal folds
    # to the same concrete size under tracing) --------------------------
    if not hasattr(jax.lax, "axis_size"):
        jax.lax.axis_size = lambda axis: jax.lax.psum(1, axis)

    # --- jax.sharding.AxisType + Mesh.axis_types (explicit-sharding
    # meshes don't exist pre-0.6: report every axis as Auto) ------------
    if not hasattr(jax.sharding, "AxisType"):
        class AxisType(enum.Enum):
            Auto = "auto"
            Explicit = "explicit"
            Manual = "manual"

        jax.sharding.AxisType = AxisType
        # (0.4.x Mesh instances already carry an `axis_types` attribute
        # — a dict of its own AxisTypes enum; comparisons against the
        # stub are simply False, i.e. "not Explicit", which is right)

    # --- pltpu.CompilerParams (renamed from TPUCompilerParams; older
    # field sets lack e.g. has_side_effects — drop unknown kwargs, the
    # flag only guards DCE of pure-side-effect comm kernels, which need
    # the modern interpreter anyway) ------------------------------------
    from jax.experimental.pallas import tpu as pltpu

    # --- pltpu.MemorySpace (renamed from TPUMemorySpace ~0.5; the
    # megakernels pin their operand BlockSpecs to VMEM through it) ------
    if not hasattr(pltpu, "MemorySpace") and hasattr(pltpu,
                                                     "TPUMemorySpace"):
        pltpu.MemorySpace = pltpu.TPUMemorySpace
    if not hasattr(pltpu, "CompilerParams") and hasattr(
            pltpu, "TPUCompilerParams"):
        import dataclasses
        known = {f.name for f in dataclasses.fields(pltpu.TPUCompilerParams)}

        def CompilerParams(**kw):
            return pltpu.TPUCompilerParams(
                **{k: v for k, v in kw.items() if k in known})

        pltpu.CompilerParams = CompilerParams


def has_tpu_interpreter() -> bool:
    """True when this jax ships the full Pallas TPU interpreter
    (semaphores/remote-DMA simulation; jax >= ~0.5). Without it the CPU
    substrate can only run single-buffer kernels under the generic
    interpreter, and the comm-kernel tests must skip."""
    from jax.experimental.pallas import tpu as pltpu
    return hasattr(pltpu, "InterpretParams") or hasattr(
        pltpu, "TPUInterpretParams")
