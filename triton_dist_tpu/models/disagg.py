"""Prefill/decode disaggregation: dedicated prefill workers stream KV
pages to decode workers over the p2p tier (ROADMAP open item 1, second
half — the DistServe split, Zhong et al. 2401.09670; Mooncake's
KV-centric formulation of the same argument, PAPERS.md).

WHY: chunked prefill (models/scheduler.py step_mixed) BOUNDS the stall
a long admission's prefill puts on live decode streams, but does not
remove it — every mixed tick still carries up to `prefill_budget`
prompt tokens through the decode mesh's forward, so prefill traffic
sets the inter-token floor whenever admissions are hot. The production
topology separates the two regimes onto different hardware: PREFILL
WORKERS (compute-bound, batch=1 long forwards) compute a prompt's KV
into a staging paged pool and push the finished page-groups to the
DECODE workers (bandwidth-bound, q_len=1 forever), which install the
pages and arm the slot. Decode ticks never see a prefill q_len again:
`stats()["max_prefill_tokens_per_poll"]` is structurally 0 on the
decode mesh, and the measured win is `inter_token_p99_ms` under
long-prompt load.

THE TRANSFER PLANE — a transferred page is a demoted page with a
different destination: the PR-6 host-tier serialization pair
(`Engine.extract_pages_host` one-DMA gather / `restore_pages_host`
one-DMA scatter, raw pool-dtype bytes so the round trip is bitwise,
int8 scale planes riding the same ids, PR-9 owning-plane selection on
TP-sharded pools) is reused unchanged as the wire format. Transports:

- `HostTransport` (default): the extract/restore pair IS the
  transfer — d2h off the prefill pool, h2d into the decode pool
  (the same-host smoke, and the fallback tier anywhere).
- `ICITransport`: the payload rides `kernels/p2p.p2p_push_pages` —
  the paper's one-sided neighbor-put kernel (`p2p_shift`) hopping the
  bytes from the prefill chip's plane to the decode chip's over ICI.
- `DCNTransport`: cross-slice push via `kernels/two_tier.
  kv_push_slices` — the XLA-collective tier of the two-tier design
  (DCN has no one-sided semantics; the slice hop is a ppermute).

BITWISE CONTRACT (tests/test_disagg.py): the prefill worker runs the
SAME bucketed prefill program the fused admission runs
(`admit_slot_paged` at kv_start=0), the extract/restore pair moves raw
bytes, and the decode-side install maps the transferred pages exactly
where a fused admission's freshly written pages would sit — so decode
token streams are bitwise identical disagg vs fused across {greedy,
sampled, spec=K} x {prefix cache, preemption, host tier}, same tokens,
same PRNG chains, with ZERO new XLA programs per decode poll (the
install reuses the install/restore executables that already exist for
chunked admission and the host tier).

SCHEDULING (DisaggScheduler): admission becomes two-pool —
1. ROUTE: a fresh request leaves the queue for the prefill plane
   (no decode slot is held while it prefills); a RESUMED request
   (preemption) re-admits decode-side directly — its pages are in the
   radix tree, so the "prefill" is the 1-token suffix recompute.
2. PREFILL: a worker computes the FULL prompt KV into its own staging
   pool and extracts the page payload + the arming logits row. The
   staging pool is released in the same job (zero-leak on BOTH pools:
   `available + outstanding == num_pages` holds on the staging AND
   decode allocators — tests/test_disagg.py chaos matrix).
3. PUSH: the payload crosses the transfer plane (`kv_push` trace
   instant; `pages_transferred`/`transfer_bytes` counters;
   fault-injectable — runtime/chaos.py transfer faults: a DROPPED
   push re-queues the request to prefill, a DUPLICATED push is
   discarded idempotently at install, a prefill-worker DEATH
   mid-transfer releases staging and retries).
4. INSTALL: the decode side runs the normal `_reserve_pages` flow
   (prefix lookup, refcounts, eviction, CoW bookkeeping), restores
   the transferred payload into the fresh groups covering the
   uncached extent, installs the table, inserts the prompt into the
   radix tree (a transferred prefix is immediately shareable) and
   arms the slot with the transferred logits (`kv_install` instant,
   `kv_transfer_latency_ms` histogram). Pool pressure at install
   walks the SAME preempt-or-wait ladder as fused admission.

TTFT overlaps transfer with the tail of prefill: the push happens the
moment extraction lands, while other requests' prefills queue behind —
and with `threads=True` the prefill plane runs on its own thread(s),
so decode polls never block on a prefill forward at all (the CPU smoke
approximation of dedicated prefill chips; on a real deployment each
worker is its own mesh slice and `transport` picks ICI or DCN).

OBSERVABILITY (runtime/telemetry.py — PR 11): a disaggregated trace
is ONE merged timeline. Each prefill worker owns a named track
(`prefill:compute` / `kv_push` spans — inline and threaded alike),
and a request's trace context (`KVHandoff.flow_id`) propagates across
the transfer wire so its journey draws as a Chrome flow-arrow chain
route -> prefill compute -> kv_push -> kv_install joining both
planes; `tools/trace_view.py` reports per-plane time and per-request
transfer latency. The staging pools are gauge-visible per worker
(`staging_pages_resident{worker=...}` — 0 at idle IS the zero-leak
invariant — plus peak/occupancy), prefill-plane and transfer device
time land in their own `device_wait_s_by_kind` buckets, and SLO
classes (`Request.slo`) ride through unchanged. All host-side only:
trace-on == trace-off bitwise with zero new XLA programs
(tests/test_disagg.py churn guard, tests/test_observability.py).

When fused chunked prefill is still the right call: see the README
"Disaggregated serving" section — at low admission rates or tiny
prompts the transfer latency buys nothing and one mesh is simpler.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from triton_dist_tpu.models.scheduler import (ContinuousScheduler,
                                              Request, _TokenLog)


class PrefillWorkerDied(RuntimeError):
    """A prefill worker failed mid-job (chaos: runtime/chaos.py
    FaultInjector.kill_prefills). The job's staging pages are released
    by the worker's own cleanup and the request re-queues to the
    prefill plane — the decode mesh never notices."""


@dataclasses.dataclass
class KVHandoff:
    """One finished prefill in flight to the decode mesh: the request,
    the prompt's page payload in extract_pages_host wire format
    (k/v [L, npp*Hkv, page, d] raw pool-dtype bytes, ks/vs scale
    planes when the pool is int8), and the arming logits row the
    decode slot needs (the fused admission gets it from the same
    forward — the device transports ship it alongside the pages).
    `t_push` stamps the push for kv_transfer_latency_ms. `flow_id` is
    the request's TRACE CONTEXT, propagated across the prefill ->
    decode transfer wire: the decode-side install ends the same
    Chrome-trace flow chain the prefill plane started, so ONE merged
    trace shows the request's journey across both planes (0 = tracing
    off, no chain)."""
    req: Request
    n: int                              # prompt length
    npp: int                            # prompt page-groups staged
    payload: Dict[str, Optional[np.ndarray]]
    logits_row: np.ndarray              # [V] f32
    t_push: float = 0.0
    flow_id: int = 0

    def wire_arrays(self) -> Dict[str, Optional[np.ndarray]]:
        """Everything a device transport must move: the page payload
        AND the arming logits row (a decode worker on another chip
        cannot arm the slot from bytes that never crossed)."""
        return dict(self.payload, logits=self.logits_row)

    def with_wire(self, moved: Dict[str, Optional[np.ndarray]]
                  ) -> "KVHandoff":
        """Rebuild from a transport's moved arrays."""
        row = moved.pop("logits")
        return dataclasses.replace(self, payload=moved, logits_row=row)


class HostTransport:
    """The default (same-host / fallback) transfer tier: the payload
    is already serialized host bytes (extract_pages_host), so the push
    is the identity — d2h off the staging pool and h2d into the decode
    pool ARE the transfer. Exists so the fault hooks, counters and
    trace instants wrap one seam whatever the tier."""

    name = "host"

    def push(self, handoff: KVHandoff) -> KVHandoff:
        return handoff


class ICITransport:
    """On-slice device path: every payload array rides
    kernels/p2p.p2p_push_pages — the paper's one-sided neighbor-put
    kernel (`p2p_shift`) — from the prefill chip's mesh position to
    the decode chip's. Bitwise: the kernel moves raw bytes
    (tests/test_disagg.py pins payload equality through the hop)."""

    name = "ici"

    def __init__(self, mesh, *, axis: str = "tp", src: int = 0,
                 dst: Optional[int] = None):
        n = mesh.shape[axis]
        self.mesh, self.axis = mesh, axis
        self.src = int(src) % n
        self.dst = (self.src + 1) % n if dst is None else int(dst) % n

    def push(self, handoff: KVHandoff) -> KVHandoff:
        from triton_dist_tpu.kernels.p2p import p2p_push_pages
        moved = {
            k: (None if a is None else np.asarray(p2p_push_pages(
                a, mesh=self.mesh, axis=self.axis, src=self.src,
                dst=self.dst)))
            for k, a in handoff.wire_arrays().items()}
        return handoff.with_wire(moved)


class DCNTransport:
    """Cross-slice device path: the payload crosses the slice boundary
    via kernels/two_tier.kv_push_slices — an XLA ppermute on the DCN
    axis, the tier XLA owns (two_tier.py design rule: one-sided Pallas
    inside a slice, XLA collectives across slices)."""

    name = "dcn"

    def __init__(self, mesh, *, slice_axis: str = "dcn", src: int = 0,
                 dst: Optional[int] = None):
        n = mesh.shape[slice_axis]
        self.mesh, self.slice_axis = mesh, slice_axis
        self.src = int(src) % n
        self.dst = (self.src + 1) % n if dst is None else int(dst) % n

    def push(self, handoff: KVHandoff) -> KVHandoff:
        from triton_dist_tpu.kernels.two_tier import kv_push_slices
        moved = {
            k: (None if a is None else np.asarray(kv_push_slices(
                a, mesh=self.mesh, slice_axis=self.slice_axis,
                src=self.src, dst=self.dst)))
            for k, a in handoff.wire_arrays().items()}
        return handoff.with_wire(moved)


def _sibling_engine(engine):
    """A prefill-plane Engine over the SAME model (weights shared
    read-only, jitted programs shared process-wide via
    engine._jit_programs) but with its OWN mutable scratch state, so a
    threaded prefill worker never races the decode engine's
    per-instance scratch caches. On a real deployment this is the
    worker's own mesh slice; on the smoke it is the same chips."""
    from triton_dist_tpu.models.engine import Engine
    p = engine._sample_params
    return Engine(engine.model, max_seq=engine.max_seq,
                  backend=engine.backend,
                  prefill_backend=engine.prefill_backend,
                  kv_dtype=engine.kv_dtype, sampling=engine.sampling,
                  temperature=p["temperature"], top_k=p["k"],
                  top_p=p["p"])


class PrefillWorker:
    """One dedicated prefill worker: its own staging paged pool + the
    existing bucketed prefill program (`Engine.admit_slot_paged` at
    kv_start=0 — the SAME executable the fused admission runs, which
    is what makes the handoff bitwise), one job at a time. A job
    allocates the prompt's page groups, runs the forward, extracts the
    payload (+ arming logits) and ALWAYS releases the staging groups —
    the staging allocator's zero-leak invariant
    (available + outstanding == num_pages) holds between jobs even
    under injected worker death (tests/test_disagg.py)."""

    def __init__(self, engine, *, page: int = 16,
                 num_pages: Optional[int] = None, fault=None,
                 name: str = "prefill-worker-0"):
        from triton_dist_tpu.models.prefix_cache import RefcountedPages
        self.engine = engine
        self.page = page
        self.name = name             # trace track + gauge label
        # for_ticks=False: the staging pool only runs the bucketed
        # admit forward (whose row count is the EP-aligned pad bucket),
        # never a decode tick — the MoE-family batch gate must not
        # refuse a 1-slot staging pool on an EP mesh
        self.cache = engine.make_paged_slot_cache(1, page=page,
                                                  num_pages=num_pages,
                                                  for_ticks=False)
        Hkv = engine.model.config.num_kv_heads
        self.hkv = Hkv
        self.pool = RefcountedPages(self.cache.num_pages, Hkv)
        assert self.pool.trash == self.cache.trash
        self.fault = fault
        self.prefill_tokens = 0      # prompt tokens this worker forwarded
        # staging-pool visibility (the decode pool's gauges exist; this
        # is the other half of the zero-leak invariant): pages held NOW
        # (0 between jobs — a nonzero idle value IS a leak) and the
        # high-water mark across jobs, surfaced per worker by
        # DisaggScheduler.stats()
        self.pages_peak = 0
        # wall time this worker spent blocked on its plane's device
        # programs (prefill forward + payload extraction) — the
        # "prefill" bucket of device_wait_s_by_kind
        self.device_s = 0.0

    @property
    def capacity(self) -> int:
        """Longest prompt one job can stage."""
        usable = (self.pool.num_pages - 1) // self.hkv
        return min(self.cache.capacity, usable * self.page)

    def prefill(self, req: Request) -> KVHandoff:
        """Run one job: full-prompt prefill into staging pages, then
        extract the payload in the host-tier wire format (per-page
        owning-plane gather on TP-sharded pools) and the arming
        logits. Staging groups are released on every exit path."""
        import jax
        tokens = np.asarray(req.ids, np.int32).reshape(-1)
        n = len(tokens)
        if n == 0:
            raise ValueError(f"request {req.rid!r}: empty prompt")
        if n > self.capacity:
            raise ValueError(
                f"request {req.rid!r}: prompt {n} exceeds prefill "
                f"staging capacity {self.capacity}")
        npp = -(-n // self.page)
        groups: List[np.ndarray] = []
        t_dev = time.perf_counter()
        try:
            for _ in range(npp):
                groups.append(self.pool.alloc_group())
            if self.pool.pages_in_use > self.pages_peak:
                self.pages_peak = self.pool.pages_in_use
            maxp = self.cache.table.shape[1]
            rows = np.full((self.hkv, maxp), self.cache.trash, np.int32)
            for j, g in enumerate(groups):
                rows[:, j] = g
            trash_vec = np.full((self.hkv,), self.cache.trash, np.int32)
            row, self.cache = self.engine.admit_slot_paged(
                self.cache, 0, tokens, rows, 0, trash_vec, trash_vec, 0)
            if self.fault is not None and getattr(
                    self.fault, "prefill_worker", None) is not None \
                    and self.fault.prefill_worker(req.rid):
                raise PrefillWorkerDied(
                    f"request {req.rid!r}: prefill worker killed "
                    f"mid-transfer (chaos injection)")
            ids = np.concatenate(groups)
            heads = np.tile(np.arange(self.hkv, dtype=np.int32), npp)
            out = self.engine.extract_pages_host(self.cache, ids,
                                                 heads=heads)
            payload = dict(zip(("k", "v", "ks", "vs"), out))
            payload.setdefault("ks", None)
            payload.setdefault("vs", None)
            logits_np = np.asarray(jax.device_get(row), np.float32)
        finally:
            self.device_s += time.perf_counter() - t_dev
            for g in groups:
                self.pool.release(g)
        self.prefill_tokens += n
        return KVHandoff(req=req, n=n, npp=npp, payload=payload,
                         logits_row=logits_np)


class DisaggScheduler(ContinuousScheduler):
    """ContinuousScheduler in DISAGGREGATED mode (module docstring):
    the decode mesh runs pure decode ticks while a prefill plane —
    `prefill_workers` PrefillWorker instances, inline (deterministic,
    the default) or on their own threads (`threads=True`) — computes
    admissions' KV and streams the pages across `transport`. Always
    paged (the page-granular pool IS what makes the transfer cheap);
    `prefill_budget` is meaningless here and rejected — chunked
    prefill is the fused alternative this mode replaces.

    Decode streams are bitwise identical to the fused scheduler at the
    same seeds (tests/test_disagg.py), so every downstream mode —
    sampled chains, spec=K, preemption/resume, host tier, overlap —
    composes unchanged."""

    def __init__(self, engine, *, batch: int, chunk: int = 4,
                 prefix_cache: bool = True, page: int = 16,
                 num_pages: Optional[int] = None, spec: int = 0,
                 drafter=None, max_queue: Optional[int] = None,
                 watchdog_s: Optional[float] = None,
                 preempt: bool = True, fault=None,
                 host_pool_pages: int = 0, overlap: bool = False,
                 telemetry=None, trace: Optional[bool] = None,
                 prefill_workers: int = 1, threads: bool = False,
                 transport=None, staging_pages: Optional[int] = None,
                 prefill_jobs_per_poll: int = 1,
                 slo_classes: Optional[dict] = None):
        """prefill_workers: dedicated prefill workers, each with its
        own staging pool and engine facade — a THREAD-MODE knob.
        threads=True runs them on daemon threads so decode polls never
        block on a prefill forward (call close() — or let
        TokenServer.stop() do it — when done); threads=False (default)
        services up to `prefill_jobs_per_poll` jobs inline per poll on
        ONE worker (serial on the driver thread, so extra workers
        would only be extra idle staging pools), deterministic for the
        differential tests. transport: HostTransport (default),
        ICITransport or DCNTransport. staging_pages sizes each
        worker's staging pool (default: one full slot)."""
        if prefill_workers < 1:
            raise ValueError(f"prefill_workers must be >= 1, got "
                             f"{prefill_workers}")
        super().__init__(engine, batch=batch, chunk=chunk, paged=True,
                         prefix_cache=prefix_cache, page=page,
                         num_pages=num_pages, spec=spec, drafter=drafter,
                         max_queue=max_queue, watchdog_s=watchdog_s,
                         preempt=preempt, fault=fault,
                         host_pool_pages=host_pool_pages,
                         overlap=overlap, telemetry=telemetry,
                         trace=trace, slo_classes=slo_classes)
        self.engine = engine
        self.transport = transport if transport is not None \
            else HostTransport()
        self.threads = bool(threads)
        self.prefill_jobs_per_poll = int(prefill_jobs_per_poll)
        # the prefill plane: queue of routed requests, arrived
        # handoffs, and the ownership set (_pending maps every rid the
        # plane currently owns — queued, computing, or in transfer —
        # to its Request; an arrival whose rid is no longer pending is
        # a duplicate or a cancelled/expired transfer and is discarded
        # idempotently). One condition guards all three; lock order is
        # always scheduler._lock OUTSIDE _pf_cond.
        self._pf_cond = threading.Condition()
        self._prefill_q: deque = deque()
        self._transfers: deque = deque()
        self._pending: Dict[object, Request] = {}
        self._async_done: deque = deque()   # worker-thread rejects
        # inline mode serializes every job on the driver thread, so
        # extra workers would only be extra idle staging pools —
        # build one (prefill_workers is a thread-mode knob)
        n_workers = prefill_workers if self.threads else 1
        self._workers = [
            PrefillWorker(_sibling_engine(engine) if self.threads
                          else engine, page=page,
                          num_pages=staging_pages, fault=fault,
                          name=f"prefill-worker-{i}")
            for i in range(n_workers)]
        # cross-plane trace context: rid -> flow id, allocated at
        # ROUTING when tracing is on; the id rides the KVHandoff over
        # the transfer wire and the decode-side install ends the chain
        # (route -> prefill compute -> kv_push -> kv_install as flow
        # arrows in ONE merged trace). Mutations under _pf_cond.
        self._flow_ids: Dict[object, int] = {}
        self._flow_seq = 0
        reg = self.tele.registry
        reg.gauge("disagg", "1 = prefill/decode disaggregation on"
                  ).set(1)
        reg.gauge("prefill_workers").set(n_workers)
        self._h_transfer = reg.histogram(
            "kv_transfer_latency_ms",
            "KV page push -> decode-side install, per transfer")
        self._c_transfers = reg.counter(
            "kv_transfers", "page payloads installed on the decode "
                            "mesh")
        self._c_pages = reg.counter(
            "pages_transferred", "physical pages pushed across the "
                                 "transfer plane")
        self._c_bytes = reg.counter(
            "transfer_bytes", "payload bytes pushed across the "
                              "transfer plane")
        self._c_drops = reg.counter(
            "transfer_drops", "pushes lost in flight (chaos/fabric)")
        self._c_dups = reg.counter(
            "transfer_dups", "duplicate pushes delivered")
        self._c_discards = reg.counter(
            "transfers_discarded", "arrivals dropped at install "
                                   "(duplicate / cancelled / expired)")
        self._c_retries = reg.counter(
            "transfer_retries", "requests re-queued to prefill after "
                                "a failed transfer")
        self._c_deaths = reg.counter(
            "prefill_worker_deaths", "workers lost mid-job")
        self._c_plane_tokens = reg.counter(
            "prefill_plane_tokens", "prompt tokens forwarded on the "
                                    "prefill plane (off the decode "
                                    "mesh)")
        self._stop_workers = False
        self._threads: List[threading.Thread] = []
        if self.threads:
            for i, w in enumerate(self._workers):
                t = threading.Thread(target=self._worker_loop,
                                     args=(w,), daemon=True,
                                     name=f"prefill-worker-{i}")
                t.start()
                self._threads.append(t)

    # ------------------------------------------------------------------
    # prefill plane
    # ------------------------------------------------------------------

    def close(self) -> None:
        """Stop the worker threads (no-op inline). Idempotent."""
        self._stop_workers = True
        with self._pf_cond:
            self._pf_cond.notify_all()
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads = []

    def _worker_loop(self, worker: PrefillWorker) -> None:
        while not self._stop_workers:
            with self._pf_cond:
                while not self._prefill_q and not self._stop_workers:
                    self._pf_cond.wait(0.05)
                if self._stop_workers:
                    return
                req = self._prefill_q.popleft()
            self._run_prefill_job(worker, req)

    def _submit_prefill(self, req: Request, *, front: bool = False
                        ) -> None:
        """Hand a request to the prefill plane (rid must already be in
        _pending — a cancelled/expired rid silently drops here)."""
        with self._pf_cond:
            if req.rid not in self._pending:
                return
            (self._prefill_q.appendleft if front
             else self._prefill_q.append)(req)
            self._pf_cond.notify()

    def _run_prefill_job(self, worker: PrefillWorker, req: Request
                         ) -> None:
        """One job end-to-end: forward + extract (worker), fault
        consult, transport push, delivery. Runs on a worker thread
        (threads=True) or the driver thread (inline)."""
        rid = req.rid
        if rid not in self._pending:       # cancelled while queued
            return
        # cross-plane tracing: this job's spans land on the WORKER's
        # own timeline track, joined to the decode plane by the
        # request's flow chain (flow id allocated at routing)
        tele = self.tele
        tid = tele.track(worker.name) if tele.trace else 0
        fid = self._flow_ids.get(rid, 0)
        t_job = time.monotonic()
        try:
            handoff = worker.prefill(req)
        except PrefillWorkerDied:
            # staging released by the worker's cleanup; the request
            # retries — the decode mesh never noticed
            self._c_deaths.inc()
            self._c_retries.inc()
            self.tele.instant("prefill_worker_death", str(rid))
            self._submit_prefill(req, front=True)
            return
        except ValueError as e:
            with self._lock:
                with self._pf_cond:
                    self._pending.pop(rid, None)
                    self._flow_ids.pop(rid, None)
                self._reject(rid, str(e))
                self._async_done.append(rid)
            return
        handoff.flow_id = fid
        tele.span("prefill:compute", t_job, time.monotonic(), tid=tid,
                  args={"rid": str(rid), "tokens": handoff.n})
        if fid:
            tele.flow("kv_transfer", fid, phase="t", tid=tid,
                      args={"rid": str(rid)})
        self._c_plane_tokens.inc(handoff.n)
        action = None
        if self.fault is not None:
            tf = getattr(self.fault, "transfer", None)
            if tf is not None:
                action = tf(rid)
        if action == "drop":
            # the push was lost in flight: nothing reached the decode
            # mesh, staging is already released — re-queue to prefill
            self._c_drops.inc()
            self._c_retries.inc()
            self.tele.instant("kv_transfer_drop", str(rid))
            self._submit_prefill(req, front=True)
            return
        # stamp BEFORE the wire push: with the device transports the
        # push IS the transfer, and kv_transfer_latency_ms exists to
        # show an operator a slow fabric
        t_push = time.perf_counter()
        t_span = time.monotonic()
        handoff = self.transport.push(handoff)
        handoff.t_push = t_push
        if fid:
            tele.flow("kv_transfer", fid, phase="t", tid=tid,
                      args={"rid": str(rid), "at": "kv_push"})
        tele.span("kv_push", t_span, time.monotonic(), tid=tid,
                  args={"rid": str(rid),
                        "transport": getattr(self.transport, "name",
                                             "?")})
        self._c_pages.inc(handoff.npp * worker.hkv)
        self._c_bytes.inc(sum(a.nbytes for a in
                              handoff.wire_arrays().values()
                              if a is not None))
        self.tele.instant("kv_push", str(rid), tid=tid)
        with self._pf_cond:
            self._transfers.append(handoff)
            if action == "dup":
                self._c_dups.inc()
                # installs only read the handoff, so the duplicate can
                # be the same object — the second arrival's rid is no
                # longer pending and discards idempotently
                self._transfers.append(handoff)
            self._pf_cond.notify_all()

    def _pop_transfer(self) -> Optional[KVHandoff]:
        """Next installable handoff; duplicate/cancelled/expired
        arrivals are discarded idempotently (their rid is no longer
        pending)."""
        with self._pf_cond:
            while self._transfers:
                h = self._transfers.popleft()
                if h.req.rid in self._pending:
                    return h
                self._c_discards.inc()
            return None

    def _validate(self, req: Request, tokens: np.ndarray) -> None:
        """Run at ROUTING so a request that can never be admitted is
        rejected before any prefill-plane work: the fused scheduler's
        own upfront refusals (ONE shared implementation —
        PagedDecodeSlots.validate_admission) plus the plane's staging
        bound."""
        self.slots.validate_admission(req, tokens)
        n = len(tokens)
        if n > self._workers[0].capacity:
            raise ValueError(
                f"request {req.rid!r}: prompt {n} exceeds prefill "
                f"staging capacity {self._workers[0].capacity}")

    # ------------------------------------------------------------------
    # decode-side install
    # ------------------------------------------------------------------

    def _install(self, slot: int, handoff: KVHandoff) -> None:
        """Admit a transferred prefill into a decode slot: the normal
        paged reservation (prefix lookup / refcounts / eviction), then
        table install + payload restore IN PLACE OF the boundary CoW +
        suffix forward — the transferred pages hold bytes the fused
        path would have computed (cache-on==off bitwise), so the
        stream cannot tell the difference. Raises PoolExhausted with
        everything released (the caller walks the preempt ladder)."""
        import jax.numpy as jnp
        slots = self.slots
        req, n = handoff.req, handoff.n
        tokens = np.asarray(req.ids, np.int32).reshape(-1)
        slot_groups, m, rows, _cs, _cd, r, boundary = \
            slots._reserve_pages(req, tokens)
        pool = slots.prefix.pool
        if boundary is not None:
            # the fused path CoWs the boundary page; here the whole
            # page arrives in the payload — the cached source is not
            # read at all
            pool.release(boundary)
        hkv = pool.n_kv_heads
        npp = -(-n // slots.page)
        full = m // slots.page
        t_dev = time.perf_counter()
        t_span = time.monotonic()
        try:
            trash_vec = np.full((hkv,), slots.cache.trash, np.int32)
            slots.cache = self.engine.install_slot_paged(
                slots.cache, slot, rows, trash_vec, trash_vec, 0)
            target = slot_groups[full:npp]
            if target:
                ids = np.concatenate(target)
                sl = slice(full * hkv, npp * hkv)
                pl = handoff.payload
                slots.cache = self.engine.restore_pages_host(
                    slots.cache, ids, pl["k"][:, sl], pl["v"][:, sl],
                    None if pl["ks"] is None else pl["ks"][:, sl],
                    None if pl["vs"] is None else pl["vs"][:, sl])
        except Exception:
            for g in slot_groups:
                pool.release(g)
            raise
        # the table install + payload restore are the transfer plane's
        # device programs — attributed to the "transfer" bucket of
        # device_wait_s_by_kind (the decode/verify buckets stay pure)
        slots.device_wait_by_kind["transfer"] = \
            slots.device_wait_by_kind.get("transfer", 0.0) \
            + (time.perf_counter() - t_dev)
        slots._groups[slot] = slot_groups
        slots._tokens[slot] = _TokenLog(tokens)
        slots.prefix.record(n, m)
        # a transferred prefix is immediately shareable: the next
        # admission — even one installing in the same poll — maps it
        slots.prefix.insert(tokens, slot_groups[:npp])
        slots._arm_slot(slot, req, jnp.asarray(handoff.logits_row), n)
        self._c_transfers.inc()
        if handoff.t_push:
            self._h_transfer.record(
                (time.perf_counter() - handoff.t_push) * 1e3)
        # end the cross-plane flow chain on the host track: the
        # kv_install span + "f" arrowhead the prefill plane's
        # kv_push points at (ONE merged trace, both planes)
        if handoff.flow_id:
            self.tele.flow("kv_transfer", handoff.flow_id, phase="f",
                           args={"rid": str(req.rid),
                                 "at": "kv_install"})
        self.tele.span("kv_install", t_span, time.monotonic(),
                       args={"rid": str(req.rid), "slot": slot})
        self.tele.instant("kv_install", str(req.rid))

    # ------------------------------------------------------------------
    # scheduler overrides
    # ------------------------------------------------------------------

    def _admit(self, done: List[object], out_acc=None) -> None:
        """Two-pool admission (module docstring): drain worker-thread
        rejects, ROUTE fresh queue heads to the prefill plane, run the
        inline prefill service (threads=False), then INSTALL arrived
        transfers / direct-admit resumed requests into free decode
        slots with the same preempt-or-wait ladder as fused
        admission. Runs under self._lock (the superclass callers hold
        it)."""
        from triton_dist_tpu.models.prefix_cache import PoolExhausted
        while self._async_done:
            done.append(self._async_done.popleft())
        # ROUTE: fresh requests leave the queue for the prefill plane
        # without waiting for a slot; resumed requests stay (they
        # re-admit decode-side below, FIFO with the transfers). With
        # max_queue set, the PLANE is bounded to max_queue requests
        # too — otherwise routing would drain the queue every poll and
        # submit()'s busy/{retry_after_ms} backpressure would never
        # fire while finished handoffs (whole prompt-KV payloads in
        # host RAM) piled up unboundedly behind full decode slots.
        i = 0
        while i < len(self._queue):
            if self.max_queue is not None \
                    and len(self._pending) >= self.max_queue:
                break
            req = self._queue[i]
            if req.resume is not None:
                i += 1
                continue
            tokens = np.asarray(req.ids, np.int32).reshape(-1)
            try:
                self._validate(req, tokens)
            except ValueError as e:
                del self._queue[i]
                self._reject(req.rid, str(e))
                done.append(req.rid)
                continue
            del self._queue[i]
            with self._pf_cond:
                self._pending[req.rid] = req
                if self.tele.trace:
                    # start the request's cross-plane flow chain on
                    # the host track (inside the bookkeep span): the
                    # worker's compute/push and the decode-side
                    # install continue it
                    self._flow_seq += 1
                    self._flow_ids[req.rid] = self._flow_seq
                    self.tele.flow("kv_transfer", self._flow_seq,
                                   phase="s",
                                   args={"rid": str(req.rid),
                                         "at": "route"})
            self._submit_prefill(req)
        # inline prefill service: the driver stands in for the worker
        # pool, bounded per poll so a deep admission burst cannot
        # starve the decode tick forever
        if not self.threads:
            for _ in range(self.prefill_jobs_per_poll):
                with self._pf_cond:
                    if not self._prefill_q:
                        break
                    req = self._prefill_q.popleft()
                self._run_prefill_job(self._workers[0], req)
        elif (not self.slots.occupied and not self._transfers
              and self._pending):
            # decode mesh idle, plane busy: yield briefly instead of
            # spinning the poll loop against the worker threads
            with self._pf_cond:
                if not self._transfers:
                    self._pf_cond.wait(0.002)
        # INSTALL: arrived transfers and resumed requests fill free
        # slots; pool pressure preempts an eligible victim (or waits)
        # exactly like the fused scheduler
        preempted_now: set = set()
        while True:
            free = self.slots.free
            if not free:
                return
            handoff = self._pop_transfer()
            if handoff is not None:
                rid = handoff.req.rid
                try:
                    if self.fault is not None:
                        self.fault.admission(handoff.req)
                    self._install(free[0], handoff)
                    with self._pf_cond:
                        self._pending.pop(rid, None)
                        self._flow_ids.pop(rid, None)
                    self.tele.req_event(rid, "admitted", free[0])
                    continue
                except PoolExhausted as e:
                    with self._pf_cond:
                        self._transfers.appendleft(handoff)
                    if self.overlap and not self._pipeline_idle():
                        self._drain(self._carry_out if out_acc is None
                                    else out_acc, done)
                        continue

                    def _drop_transfer(reason):
                        h = self._pop_transfer()
                        if h is None:
                            return
                        with self._pf_cond:
                            self._pending.pop(h.req.rid, None)
                            self._flow_ids.pop(h.req.rid, None)
                        self._reject(h.req.rid, reason)
                        done.append(h.req.rid)

                    if not self._preempt_for(rid, preempted_now,
                                             str(e),
                                             drop=_drop_transfer,
                                             requeue_at=0):
                        return
                    continue
                except ValueError as e:
                    with self._pf_cond:
                        self._pending.pop(rid, None)
                        self._flow_ids.pop(rid, None)
                    self._reject(rid, str(e))
                    done.append(rid)
                    continue
            if self._queue and self._queue[0].resume is not None:
                req = self._queue[0]
                try:
                    if self.fault is not None:
                        self.fault.admission(req)
                    self.slots.admit(free[0], req)
                    self._queue.popleft()
                    self.tele.req_event(req.rid, "resume", free[0])
                    continue
                except PoolExhausted as e:
                    if self.overlap and not self._pipeline_idle():
                        self._drain(self._carry_out if out_acc is None
                                    else out_acc, done)
                        continue

                    def _drop_resume(reason, req=req):
                        self._queue.popleft()
                        self._reject(req.rid, reason)
                        done.append(req.rid)

                    if not self._preempt_for(req.rid, preempted_now,
                                             str(e), drop=_drop_resume,
                                             requeue_at=1):
                        return
                    continue
                except ValueError as e:
                    self._queue.popleft()
                    self._reject(req.rid, str(e))
                    done.append(req.rid)
                    continue
            return

    # the PoolExhausted preempt-or-wait ladder is the inherited
    # ContinuousScheduler._preempt_for — ONE copy for both schedulers
    # (the install path passes requeue_at=0: its displacer is a
    # handoff, which installs ahead of the queue anyway)

    def _expire_deadlines(self, done: List[object]) -> None:
        """Fused expiry (queue + slots) plus the prefill plane: an
        expired rid anywhere in queue/compute/transfer is dropped with
        the usual visible reason; its arrival (if the payload was
        already in flight) is discarded idempotently at install."""
        super()._expire_deadlines(done)
        if not self._deadline:
            return
        now = time.monotonic()
        expired = {rid for rid, dl in self._deadline.items()
                   if now >= dl}
        if not expired:
            return
        victims: List[Request] = []
        with self._pf_cond:
            for rid in expired:
                req = self._pending.pop(rid, None)
                self._flow_ids.pop(rid, None)
                if req is not None:
                    victims.append(req)
            if victims:
                keep = deque(r for r in self._prefill_q
                             if r.rid not in expired)
                self._prefill_q = keep
        for req in victims:
            self._c_deadline_expired.inc()
            self._reject(req.rid,
                         f"deadline_ms={req.deadline_ms:g} expired "
                         f"during prefill/transfer",
                         status="expired")
            done.append(req.rid)

    def cancel(self, rid) -> bool:
        """Cancel-on-disconnect across all three pools: queued (super),
        owned by the prefill plane (dropped here — an in-flight
        payload's arrival discards idempotently), or in a decode slot
        (super)."""
        with self._lock:
            with self._pf_cond:
                if rid in self._pending:
                    self._pending.pop(rid)
                    self._flow_ids.pop(rid, None)
                    self._prefill_q = deque(
                        r for r in self._prefill_q if r.rid != rid)
                    self._deadline.pop(rid, None)
                    self.tele.retire(rid, "cancelled")
                    return True
        return super().cancel(rid)

    @property
    def idle(self) -> bool:
        return super().idle and not self._pending

    def stats(self) -> dict:
        reg = self.tele.registry
        # the prefill plane's device time rolls into the attribution
        # split BEFORE the superclass snapshots it (threads=True: this
        # is plane-busy time, not driver wait — same bucket either way)
        self.slots.device_wait_by_kind["prefill"] = round(
            sum(w.device_s for w in self._workers), 4)
        with self._lock, reg.lock:
            with self._pf_cond:
                reg.gauge("prefill_queue_depth",
                          "requests waiting for a prefill worker"
                          ).set(len(self._prefill_q))
                reg.gauge("transfers_in_flight",
                          "payloads pushed but not yet installed"
                          ).set(len(self._transfers))
                pend = len(self._pending)
            reg.gauge("prefill_pending",
                      "requests owned by the prefill plane").set(pend)
            # staging-pool gauges, per worker (decode pool gauges
            # already exist — this is the other half of the zero-leak
            # invariant made visible: resident must be 0 between jobs)
            staging_resident = 0
            staging_peak = 0
            for w in self._workers:
                usable = max(1, w.pool.num_pages - 1)  # minus trash
                in_use = w.pool.pages_in_use
                lb = {"worker": w.name}
                reg.gauge("staging_pages_resident",
                          "staging pages held right now (nonzero at "
                          "idle = leak)", labels=lb).set(in_use)
                reg.gauge("staging_pages_peak",
                          "staging high-water mark across jobs",
                          labels=lb).set(w.pages_peak)
                reg.gauge("staging_occupancy",
                          "resident / usable staging pages",
                          labels=lb).set(round(in_use / usable, 4))
                staging_resident += in_use
                staging_peak = max(staging_peak, w.pages_peak)
            out = super().stats()
        out.update({
            "disagg": True,
            "transport": getattr(self.transport, "name",
                                 type(self.transport).__name__),
            "prefill_workers": len(self._workers),
            "prefill_plane_tokens": self._c_plane_tokens.value,
            "kv_transfers": self._c_transfers.value,
            "pages_transferred": self._c_pages.value,
            "transfer_bytes": self._c_bytes.value,
            "transfer_drops": self._c_drops.value,
            "transfer_retries": self._c_retries.value,
            "prefill_worker_deaths": self._c_deaths.value,
            "staging_pages_resident": staging_resident,
            "staging_pages_peak": staging_peak,
        })
        return out
