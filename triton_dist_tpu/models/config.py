"""Model configuration (reference: `python/triton_dist/models/config.py`
`ModelConfig:31` — hidden sizes, head counts, rope theta, loaded from HF
config.json when available)."""

from __future__ import annotations

import dataclasses
import json
import os

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    hidden_size: int = 1024
    intermediate_size: int = 3072
    num_layers: int = 28
    num_heads: int = 16
    num_kv_heads: int = 8
    head_dim: int = 128
    vocab_size: int = 151936
    max_position_embeddings: int = 40960
    rope_theta: float = 1e6
    rms_norm_eps: float = 1e-6
    # default False to agree with the from_hf_config fallback; only the
    # <=4B Qwen3 models tie embeddings and they pass True explicitly
    tie_word_embeddings: bool = False
    model_type: str = "qwen3"
    # MoE (Qwen3-MoE family; 0 experts => dense)
    num_experts: int = 0
    num_experts_per_tok: int = 0
    moe_intermediate_size: int = 0
    dtype: str = "bfloat16"

    @property
    def jax_dtype(self):
        return jnp.dtype(self.dtype)

    @property
    def is_moe(self) -> bool:
        return self.num_experts > 0

    @staticmethod
    def from_hf_config(path_or_dict) -> "ModelConfig":
        """Build from a HF config.json (reference: config.py loads HF
        configs by model name)."""
        if isinstance(path_or_dict, dict):
            d = path_or_dict
        else:
            p = path_or_dict
            if os.path.isdir(p):
                p = os.path.join(p, "config.json")
            with open(p) as f:
                d = json.load(f)
        return ModelConfig(
            hidden_size=d["hidden_size"],
            intermediate_size=d.get("intermediate_size", 0),
            num_layers=d["num_hidden_layers"],
            num_heads=d["num_attention_heads"],
            num_kv_heads=d.get("num_key_value_heads", d["num_attention_heads"]),
            head_dim=d.get("head_dim",
                           d["hidden_size"] // d["num_attention_heads"]),
            vocab_size=d["vocab_size"],
            max_position_embeddings=d.get("max_position_embeddings", 40960),
            rope_theta=d.get("rope_theta", 1e6),
            rms_norm_eps=d.get("rms_norm_eps", 1e-6),
            tie_word_embeddings=d.get("tie_word_embeddings", False),
            model_type=d.get("model_type", "qwen3"),
            num_experts=d.get("num_experts", 0),
            num_experts_per_tok=d.get("num_experts_per_tok", 0),
            moe_intermediate_size=d.get("moe_intermediate_size", 0),
        )


def tiny_qwen3(n: int = 8, **overrides) -> ModelConfig:
    """A tiny Qwen3-shaped config divisible by an n-way TP mesh — the
    test-model role of the reference's small test shapes."""
    base = dict(hidden_size=64, intermediate_size=128, num_layers=2,
                num_heads=2 * n, num_kv_heads=n, head_dim=32,
                vocab_size=256, max_position_embeddings=128,
                dtype="float32")
    base.update(overrides)
    return ModelConfig(**base)


def tiny_qwen3_moe(n: int = 8, **overrides) -> ModelConfig:
    """A tiny Qwen3-MoE-shaped config divisible by an n-way mesh."""
    base = dict(hidden_size=64, intermediate_size=0, num_layers=2,
                num_heads=2 * n, num_kv_heads=n, head_dim=32,
                vocab_size=256, max_position_embeddings=128,
                num_experts=2 * n, num_experts_per_tok=2,
                moe_intermediate_size=32, dtype="float32")
    base.update(overrides)
    return ModelConfig(**base)


def qwen3_30b_a3b() -> ModelConfig:
    """Qwen3-30B-A3B shapes (the MoE family's flagship; reference:
    models/qwen_moe.py targets Qwen3-MoE checkpoints)."""
    return ModelConfig(hidden_size=2048, intermediate_size=6144,
                       num_layers=48, num_heads=32, num_kv_heads=4,
                       head_dim=128, vocab_size=151936,
                       num_experts=128, num_experts_per_tok=8,
                       moe_intermediate_size=768)


def qwen3_1p7b() -> ModelConfig:
    """Qwen3-1.7B shapes — the single-chip bench model (fits a v5e)."""
    return ModelConfig(hidden_size=2048, intermediate_size=6144,
                       num_layers=28, num_heads=16, num_kv_heads=8,
                       head_dim=128, vocab_size=151936,
                       tie_word_embeddings=True)


def qwen3_32b() -> ModelConfig:
    """Qwen3-32B shapes (the reference megakernel/e2e target,
    docs/getting-started/megakernel/megakernel.md:29)."""
    return ModelConfig(hidden_size=5120, intermediate_size=25600,
                       num_layers=64, num_heads=64, num_kv_heads=8,
                       head_dim=128, vocab_size=151936)
