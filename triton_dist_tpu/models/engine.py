"""Inference engine (reference: `python/triton_dist/models/engine.py`
`Engine:37` — `serve():113` = prefill -> backend switch :126-135 ->
CUDA-graph capture `_init_cuda_graph:75` -> decode loop :166).

TPU re-design of the decode hot loop: the CUDA-graph analog is a single
jitted `lax.scan` over decode steps with a donated KV cache — one XLA
program for the whole generation, zero per-step host round-trips
(strictly stronger than graph replay, which still launches per step).

Backends (reference backend strings engine.py:126-135):
  "xla"     <- torch            (oracle)
  "flash"   <- single-chip framework path: Pallas flash-decode +
               fused SwiGLU kernels, no comm kernels
  "dist"    <- triton_dist      (AG-GEMM / GEMM-RS)
  "ar"      <- triton_dist_AR   (partial GEMMs + AR kernel)
  "gemm_ar" <- triton_dist_gemm_ar (fused GEMM+AR)
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from triton_dist_tpu.models.kv_cache import KVCache


class Engine:
    def __init__(self, model, *, max_seq: int = 256, backend: str = "gemm_ar",
                 prefill_backend: Optional[str] = None):
        self.model = model
        self.max_seq = max_seq
        self.backend = backend
        # the reference prefills with the torch fwd (engine.py:121); the
        # analog here is the XLA-collective mode unless overridden
        self.prefill_backend = prefill_backend or (
            backend if backend in ("dist", "flash") else "xla")
        # The model is a jit ARGUMENT (weights must not be captured as
        # program constants — that would bake GBs into the executable)
        self._prefill = jax.jit(functools.partial(
            _prefill_fn, mode=self.prefill_backend))
        self._decode_scan = jax.jit(
            functools.partial(_scan_decode_fn, backend),
            static_argnames=("gen_len",), donate_argnums=(2,))

    def prefill(self, input_ids):
        """Run the prefill pass on a fresh cache; returns (logits, cache)."""
        input_ids = jnp.asarray(input_ids, dtype=jnp.int32)
        cache = self.model.make_cache(input_ids.shape[0], self.max_seq)
        return self._prefill(self.model, input_ids, cache)

    def decode(self, logits, cache, gen_len: int):
        """Greedy decode from prefill state: one jitted lax.scan over
        gen_len steps with a donated cache. Returns tokens [B, gen_len].
        The benchmark times this call alone — it is the reference's
        measured decode loop (engine.py:166)."""
        toks, _, _ = self._decode_scan(self.model, logits, cache,
                                       gen_len=gen_len)
        return toks

    def serve(self, input_ids, gen_len: int):
        """Generate greedily (reference: Engine.serve, engine.py:113).
        input_ids: [B, S] int32. Returns generated tokens [B, gen_len].
        """
        logits, cache = self.prefill(input_ids)
        return self.decode(logits, cache, gen_len)


def _prefill_fn(model, ids, cache, *, mode):
    return model.forward_tokens(ids, cache, mode=mode)


def _scan_decode_fn(backend, model, logits0, cache, *, gen_len: int):
    def step(carry, _):
        logits, cache = carry
        tok = jnp.argmax(logits, axis=-1)           # greedy [B]
        logits, cache = model.forward_tokens(tok[:, None], cache,
                                             mode=backend)
        return (logits, cache), tok

    (logits, cache), toks = jax.lax.scan(
        step, (logits0, cache), None, length=gen_len)
    return toks.T, logits, cache                     # [B, gen_len]
