"""Inference engine (reference: `python/triton_dist/models/engine.py`
`Engine:37` — `serve():113` = prefill -> backend switch :126-135 ->
CUDA-graph capture `_init_cuda_graph:75` -> decode loop :166).

TPU re-design of the decode hot loop: the CUDA-graph analog is a single
jitted `lax.scan` over decode steps with a donated KV cache — one XLA
program for the whole generation, zero per-step host round-trips
(strictly stronger than graph replay, which still launches per step).

Backends (reference backend strings engine.py:126-135):
  "xla"     <- torch            (oracle)
  "flash"   <- single-chip framework path: Pallas flash-decode +
               fused SwiGLU kernels, no comm kernels
  "dist"    <- triton_dist      (AG-GEMM / GEMM-RS)
  "ar"      <- triton_dist_AR   (partial GEMMs + AR kernel)
  "gemm_ar" <- triton_dist_gemm_ar (fused GEMM+AR)
  "mega"    <- mega_triton_kernel (models/engine.py backend "mega",
               mega_triton_kernel/models/model_builder.py:86): each
               decode layer is ONE Pallas megakernel
               (mega/decode_layer.py); single chip, decode only
               (prefill runs the flash backend). Measured on a v5e with
               Qwen3-1.7B bsz=128: ~21 ms/step vs ~12.5 for "flash" —
               on TPU the XLA scan already fuses and software-pipelines
               across ops/layers, so the hand-scheduled megakernel is
               the architecture-parity path, not the fast path (the
               reference's megakernel wins by eliminating GPU launch
               overhead, which the TPU path never pays).
"""

from __future__ import annotations

import functools
import threading
from typing import Optional

import jax
import jax.numpy as jnp

from triton_dist_tpu.runtime.telemetry import default_registry


class Engine:
    def __init__(self, model, *, max_seq: int = 256, backend: str = "gemm_ar",
                 prefill_backend: Optional[str] = None,
                 kv_dtype=None, sampling: str = "greedy",
                 temperature: float = 1.0, top_k: int = 50,
                 top_p: float = 0.9):
        """kv_dtype=jnp.int8 stores the KV cache quantized (per-position
        scales; kv_cache.py) — half the decode step's dominant HBM read.
        Pair with model.quantize_int8() for the full bandwidth-bound
        decode configuration.

        sampling: "greedy" (default), "top_k" or "top_p" (reference:
        the sampling helpers of models/utils.py driven by the chat
        server, mega_triton_kernel/test/models/model_server.py). The
        non-greedy paths thread a PRNG key through the decode scan's
        carry (split per step); greedy keeps the key-free carry so the
        bench path is untouched. temperature=0 collapses every sampler
        to greedy."""
        self.model = model
        self.max_seq = max_seq
        self.backend = backend
        self.kv_dtype = kv_dtype
        if sampling not in ("greedy", "top_k", "top_p"):
            raise ValueError(f"unknown sampling mode {sampling!r}")
        # MODEL/BACKEND capability gate (ISSUE 13): every unsupported
        # combination refuses HERE, at construction, naming the missing
        # capability — not as a shape/attribute error deep inside the
        # first jitted forward.
        known = ("xla", "flash", "dist", "ar", "gemm_ar", "ep",
                 "ep_flash", "mega")
        if backend not in known:
            raise ValueError(f"unknown backend {backend!r}; this engine "
                             f"serves {known}")
        self.moe_family = bool(getattr(model.config, "is_moe", False))
        # SEQUENCE-PARALLEL serving (long-context — the sp-sharded
        # paged pool, kv_cache.PagedSlotCache SP SHARDING): capability
        # gates live HERE, at construction, naming what is missing —
        # the PR-13 pattern — instead of shape errors deep in jit.
        sp_ax = getattr(model, "sp_axis", None)
        self.sp_size = int(model.mesh.shape[sp_ax]) if sp_ax else 1
        if self.sp_size > 1:
            tp = model.mesh.shape[model.axis]
            if tp > 1:
                raise ValueError(
                    f"sequence-parallel serving (sp_axis={sp_ax!r}, "
                    f"size {self.sp_size}) cannot combine with a TP "
                    f"head-group split (axis {model.axis!r}, size "
                    f"{tp}) yet (missing capability: sp + TP hybrid "
                    f"paged pool) — size one of the axes to 1")
            if backend == "mega":
                raise ValueError(
                    "backend='mega' fuses the paged tick single-chip "
                    "only; the sp-sharded pool's split-KV partial + "
                    "cross-chip LSE combine stay on the per-op "
                    "shard_map path (missing capability: megakernel "
                    "sp combine) — serve sp meshes with "
                    "backend='flash'")
            if backend not in ("flash",):
                raise ValueError(
                    f"backend={backend!r} routes projections through "
                    f"the TP comm kernels; sequence-parallel serving "
                    f"replicates weights over the sp axis and serves "
                    f"on backend='flash' (missing capability: sp + "
                    f"comm-kernel hybrid projections)")
        if backend in ("ep", "ep_flash"):
            if not self.moe_family:
                raise ValueError(
                    f"backend={backend!r} routes the FFN through the EP "
                    "dispatch/combine kernels; "
                    f"{type(model).__name__} has no routed experts "
                    "(missing capability: expert-parallel FFN) — dense "
                    "models serve on 'flash'/'dist'/'ar'/'gemm_ar'")
            if getattr(model, "moe_impl", None) != "ep":
                raise ValueError(
                    f"backend={backend!r} needs an expert-SHARDED model "
                    f"(moe_impl='ep'); this Qwen3MoE was built "
                    f"moe_impl={model.moe_impl!r} — TP-MoE serves its "
                    "grouped-GEMM dispatch on 'flash' (or 'dist' for "
                    "the comm-kernel attention)")
        # An expert-SHARDED model feeds row-sharded token batches to
        # the EP FFN (the a2a dispatch on the ep backends, the
        # all-gather oracle on the rest): every forward's row count
        # must divide by the ep axis, so the prefill pad buckets align
        # to lcm(8, ep) and max_seq (the bucket clamp) rounds up to it.
        ep = getattr(model, "ep_size", 1)
        self._ep_rows = 1
        if ep > 1:
            import math
            self._ep_rows = math.lcm(8, ep)
            self.max_seq = -(-self.max_seq // self._ep_rows) \
                * self._ep_rows
        if sampling != "greedy" and backend == "mega":
            raise ValueError(
                "backend='mega' serves GREEDY streams only (the fused "
                "tick and the decode scan both carry the argmax token); "
                "sampled decode is still unsupported — use the per-op "
                "backends for sampled generation")
        self.sampling = sampling
        self._sample_params = dict(temperature=temperature, k=top_k,
                                   p=top_p)
        # process-global dispatch counters (runtime/telemetry.py): the
        # device-program mix every scheduler on this engine drives —
        # prefills vs decode vs verify vs mixed ticks — surfaced by
        # the TokenServer's /metrics listener next to each scheduler's
        # own registry. Cached Counter handles: inc() on the dispatch
        # path is one int add, no registry lock.
        _reg = default_registry()
        self._c_prefills = _reg.counter(
            "engine_prefill_dispatches", "prefill/admit forwards")
        self._c_decode = _reg.counter(
            "engine_decode_dispatches", "slot-scan decode chunks")
        self._c_verify = _reg.counter(
            "engine_verify_dispatches", "spec verify forwards")
        self._c_mixed = _reg.counter(
            "engine_mixed_dispatches", "mixed prefill+decode ticks")
        # TP comm-backend dispatch counter: every slot/verify/mixed
        # tick whose backend routes the projections through the
        # distributed comm kernels (AG-GEMM / GEMM-RS / AR / fused
        # GEMM+AR) counts here — the observable proof that multi-chip
        # serving actually exercises the paper's kernels (the TP=N
        # differential suite asserts it > 0). Complemented by
        # `comm_kernel_traces` (kernels/*) counting each comm kernel
        # BUILT into a program at trace time.
        self._c_comm = _reg.counter(
            "comm_kernel_dispatches", "slot-path dispatches through "
                                      "the dist/ar/gemm_ar backends")
        self._comm_backend = backend in ("dist", "ar", "gemm_ar")
        # int8-quantized models run on EVERY backend: the comm-kernel
        # GEMMs (ag_gemm/gemm_rs/gemm_allreduce) stream int8 weight
        # panels and dequant per column after the dot (exact), so the
        # bandwidth win survives multi-chip TP decode (reference analog:
        # quantized comm payloads, low_latency_all_to_all_v2.py:213).
        if backend == "mega":
            if self.moe_family or not all(hasattr(l, "mlp")
                                          for l in model.layers):
                raise ValueError(
                    "backend='mega' fuses dense (attention + MLP) "
                    "decode layers only (missing capability: megakernel "
                    "routed-expert FFN) — MoE models serve their "
                    "grouped-GEMM tick on backend='flash' (TP-MoE) or "
                    "'ep'/'ep_flash' (expert-sharded)")
            from triton_dist_tpu.kernels.quant import QuantW
            if model.layers and isinstance(model.layers[0].attn.w_qkv,
                                           QuantW):
                raise ValueError(
                    "backend='mega' repacks raw bf16 weight panels and "
                    "has no WEIGHT dequant path; int8-weight models run "
                    "on the other backends (int8 paged KV is fine — "
                    "the fused tick dequants the pool in-kernel)")
            if kv_dtype is not None and jnp.dtype(kv_dtype) != jnp.int8:
                raise ValueError(
                    f"backend='mega' supports kv_dtype=None (pool "
                    f"dtype) or jnp.int8 (in-kernel scale-plane "
                    f"dequant), not {jnp.dtype(kv_dtype)}")
            n_mega = model.mesh.shape[model.mesh.axis_names[0]]
            if n_mega > 1 and (
                    model.config.num_heads % n_mega
                    or model.config.num_kv_heads % n_mega
                    or model.config.intermediate_size % n_mega):
                raise ValueError(
                    "backend='mega' TP needs heads/kv-heads/ffn "
                    "divisible by the mesh size (single-chip decode "
                    "has no such constraint)")
            # the megakernel's flash loop walks the cache in
            # block_t-sized tiles; round the cache capacity up
            from triton_dist_tpu.mega import MegaDecodeLayer
            bt = MegaDecodeLayer.block_t
            self.max_seq = -(-max_seq // bt) * bt
        # the reference prefills with the torch fwd (engine.py:121); the
        # analog here is the XLA-collective mode unless overridden.
        # The ep backends prefill through THEMSELVES: chunked-prefill
        # differentials need the admit forward and the mixed tick on
        # one numerics path (the same reason "dist"/"flash" do).
        self.prefill_backend = prefill_backend or (
            backend if backend in ("dist", "flash", "ep", "ep_flash")
            else "flash" if backend == "mega" else "xla")
        # MoE-family serving telemetry (ISSUE 13): every slot-tick
        # program additionally returns the tick's routing-load vector
        # [expert_tokens[0..E-1], capacity_dropped]; the engine stashes
        # the device array FIFO here and the scheduler's coalesced
        # readback (DecodeSlots._fetch) pops exactly one per landed
        # tick — no extra sync, and the overlap pipeline never blocks
        # on a still-in-flight tick's stats.
        import collections
        self._moe_pending = collections.deque()
        # The model is a jit ARGUMENT (weights must not be captured as
        # program constants — that would bake GBs into the executable).
        # The jitted program set is SHARED across Engine instances with
        # the same (backend, sampling, params, prefill mode) via a
        # process-wide factory (_jit_programs): jax's compile cache
        # keys on the python callable, so per-instance functools
        # partials used to recompile every executable once per engine
        # — a server fleet (or a test suite) building several engines
        # over the same model shapes paid the whole compile bill each
        # time. Sharing is safe because every per-engine mutable piece
        # (scratch caches, dispatch counters) stays on the instance and
        # the model rides in as a traced argument.
        progs = _jit_programs(backend, sampling,
                              _params_key(self._sample_params),
                              self.prefill_backend)
        # AOT WARM START (ISSUE 12 / ROADMAP item 5): with
        # TDTPU_AOT_CACHE=dir set, every serving program below is
        # wrapped by a disk cache of jax.export blobs keyed on
        # (backend, sampling, params, prefill mode, jax version, arg
        # shapes) — a restarted server (or an elastically added
        # worker) deserializes the lowered program instead of
        # retracing it, and the XLA executable comes out of the
        # persistent compilation cache pointed at the same directory
        # (tools/aot.py AOTProgramCache). Programs the host cannot
        # serialize (Pallas interpreter callbacks off-TPU) fall back
        # to their jit wrappers, counted in the cache stats.
        from triton_dist_tpu.tools.aot import wrap_serving_programs
        progs, self._aot = wrap_serving_programs(
            progs, context=(backend, sampling,
                            _params_key(self._sample_params),
                            self.prefill_backend))
        self._prefill = progs["prefill"]
        self._decode_scan = progs["decode_scan"]
        # slot-masked chunked decode (continuous batching,
        # models/scheduler.py) + the paged/verify/mixed program
        # family — all lazy-compiled on first use (the program
        # roles are documented on _jit_programs). backend='mega'
        # carries the SAME per-op family (built at its prefill
        # backend) as the admission/mixed/tier fallback plus the
        # fused paged tick program (paged_slot_mega).
        self._slot_scan = progs["slot_scan"]
        self._prefill_slot = progs["prefill_slot"]
        self._write_slot = progs["write_slot"]
        # persistent 1-row scratch for prefill_into_slot, donated
        # through each admission instead of reallocated per request.
        # The scratch is engine-owned while caches are caller-owned,
        # so when several servers share one engine (fleet replicas),
        # concurrent admissions would donate the SAME scratch buffer
        # twice — the lock serializes the scratch-donating section
        # only (decode ticks touch caller-owned state and stay
        # lock-free).
        self._scratch_lock = threading.Lock()
        self._slot_scratch = None
        self._paged_slot_scan = progs["paged_slot_scan"]
        self._paged_admit = progs["paged_admit"]
        self._paged_set_table = progs["paged_set_table"]
        self._paged_scratch = None
        if sampling != "greedy":
            self._spec_seed = progs["spec_seed"]
        self._slot_verify = progs["slot_verify"]
        self._paged_slot_verify = progs["paged_slot_verify"]
        self._slot_mixed = progs["slot_mixed"]
        self._paged_slot_mixed = progs["paged_slot_mixed"]
        self._slot_mixed_verify = progs["slot_mixed_verify"]
        self._paged_slot_mixed_verify = \
            progs["paged_slot_mixed_verify"]
        self._paged_install = progs["paged_install"]
        self._gather_pages = progs["gather_pages"]
        self._restore_pages = progs["restore_pages"]
        if backend == "mega":
            self._paged_slot_mega = progs["paged_slot_mega"]
            self._c_mega = _reg.counter(
                "engine_mega_dispatches", "fused paged mega decode "
                                          "ticks")

    def prefill(self, input_ids):
        """Run the prefill pass on a fresh cache; returns (logits, cache)."""
        input_ids = jnp.asarray(input_ids, dtype=jnp.int32)
        cache = self.model.make_cache(input_ids.shape[0], self.max_seq,
                                      dtype=self.kv_dtype)
        return self._prefill(self.model, input_ids, cache)

    def decode(self, logits, cache, gen_len: int, *, seed: int = 0):
        """Decode from prefill state: one jitted lax.scan over gen_len
        steps with a donated cache. Returns tokens [B, gen_len]. The
        benchmark times this call alone — it is the reference's measured
        decode loop (engine.py:166). `seed` feeds the sampler key for
        the non-greedy modes (ignored under greedy)."""
        if self.backend == "mega" and self.kv_dtype is not None:
            raise ValueError(
                "backend='mega' dequants int8 KV only on the PAGED "
                "pool (the fused tick's scale-plane dequant); the "
                "contiguous decode scan reads the cache directly — "
                "serve int8 through ContinuousScheduler(paged=True), "
                "or use kv_dtype=None here")
        if self.sampling == "greedy" or self.backend == "mega":
            toks, _, _ = self._decode_scan(self.model, logits, cache,
                                           gen_len=gen_len)
        else:
            toks, _, _, _ = self._decode_scan(
                self.model, logits, cache, jax.random.key(seed),
                gen_len=gen_len)
        return toks

    def serve(self, input_ids, gen_len: int, *, seed: int = 0):
        """Generate (reference: Engine.serve, engine.py:113).
        input_ids: [B, S] int32. Returns generated tokens [B, gen_len].
        """
        logits, cache = self.prefill(input_ids)
        return self.decode(logits, cache, gen_len, seed=seed)

    # ------------------------------------------------------------------
    # continuous-batching slot decode (models/scheduler.py drives these)
    # ------------------------------------------------------------------

    def _note_moe_load(self, out: tuple) -> tuple:
        """Strip + stash the routing-load vector every MoE-family slot
        program appends as its LAST output ([E+1] int32 device array:
        per-expert routed entries + capacity drops, summed over layers
        and scan steps). FIFO order matches tick dispatch order —
        scheduler._fetch pops one per landed tick and folds it into
        the expert_tokens/moe_capacity_drops/expert_load_imbalance
        metrics. Dense engines pass through untouched."""
        if not self.moe_family:
            return out
        self._moe_pending.append(out[-1])
        return out[:-1]

    def pop_moe_load(self):
        """The oldest undrained routing-load device array (or None).
        Callers must only pop a tick they are about to LAND (its
        outputs computed) — a device_get on it is then a plain d2h
        copy, never a pipeline stall."""
        if self.moe_family and self._moe_pending:
            return self._moe_pending.popleft()
        return None

    def _moe_batch_check(self, batch: int) -> None:
        """EP slot serving feeds [batch(*window), D] token rows to the
        row-sharded expert dispatch: refuse a scheduler batch the ep
        axis cannot split, at cache construction instead of as a
        shard_map divisibility error deep in the first tick."""
        ep = getattr(self.model, "ep_size", 1)
        if ep > 1 and batch % ep:
            raise ValueError(
                f"EP serving needs the slot batch ({batch}) divisible "
                f"by the expert-parallel axis size ({ep}): each tick "
                f"row-shards its token batch over the ep mesh axis "
                f"{self.model.ep_axis!r} — pad the batch or shrink "
                f"the ep axis")

    def make_slot_cache(self, batch: int):
        """Fresh cache whose batch rows are independent decode SLOTS."""
        if self.sp_size > 1:
            raise ValueError(
                "sequence-parallel serving shards the PAGE-ID space — "
                "contiguous slot caches have no pages to shard "
                "(missing capability: sp contiguous slots); construct "
                "ContinuousScheduler(paged=True) so the sp pool "
                "serves through the partial+LSE-combine attends")
        self._moe_batch_check(batch)
        return self.model.make_cache(batch, self.max_seq,
                                     dtype=self.kv_dtype)

    def prefill_into_slot(self, cache, slot, ids, *, pad_to: int = 8):
        """Prefill ONE new request and write its KV into batch row
        `slot` of the shared cache without touching live slots.

        The prompt runs as a batch-1 forward into a persistent 1-row
        scratch cache (allocated once per engine, donated through the
        jitted prefill each admission), padded up to a multiple of
        `pad_to` — clamped to max_seq — so the number of prefill
        programs is bounded by the bucket count, not the number of
        distinct prompt lengths (padded positions write garbage KV
        past the real length — never attended, because the slot's
        per-row length masks them, and overwritten as decode advances;
        the same masking makes scratch reuse across admissions safe).
        The scratch row is then copied over the slot's row — ONE
        dynamic-update-slice per layer buffer on the donated cache.
        Returns (next-token logits [V], cache).
        """
        ids = jnp.asarray(ids, jnp.int32).reshape(-1)
        n = ids.shape[0]
        if n > self.max_seq:
            raise ValueError(
                f"prompt length {n} exceeds slot capacity {self.max_seq}")
        self._c_prefills.inc()
        if self._ep_rows > 1:
            # EP models: the prefill's row count feeds the row-sharded
            # expert dispatch — buckets align to lcm(8, ep). max_seq
            # was rounded up to the same at __init__, so the clamp
            # below stays divisible.
            import math
            pad_to = math.lcm(pad_to, self._ep_rows)
        # the pad bucket must never write past the cache capacity
        # (max_seq need not be a pad_to multiple)
        P = min(-(-n // pad_to) * pad_to, self.max_seq)
        padded = jnp.zeros((1, P), jnp.int32).at[0, :n].set(ids)
        with self._scratch_lock:
            if self._slot_scratch is None:
                self._slot_scratch = self.model.make_cache(
                    1, self.max_seq, dtype=self.kv_dtype)
            logits, self._slot_scratch = self._prefill_slot(
                self.model, padded, self._slot_scratch,
                jnp.int32(n - 1))
            cache = self._write_slot(cache, self._slot_scratch,
                                     jnp.int32(slot))
        return logits[0], cache

    def slot_chunk(self, logits, cache, pos, active, *, chunk: int,
                   keys=None, mask=None):
        """One chunk of slot-masked decode: `chunk` scan steps where
        row b samples from its own logits, appends KV at its own
        pos[b], and advances only if active[b] (inactive slots write
        into their own dead rows — harmless, overwritten on admit).
        ONE XLA program per chunk length; admission/retirement happen
        between chunks on the host. keys: per-slot PRNG keys [B]
        (typed key array) for the sampled modes; None under greedy.
        Returns (toks [B, chunk], logits, cache, pos, keys).

        Dispatch contract (the overlap scheduler rides this): the call
        returns device FUTURES — the donated carry (logits/cache/pos/
        keys) can be fed straight into the next chunk's dispatch with
        NO host round-trip, and only reading `toks` blocks. The same
        holds for every slot program below (verify, mixed, paged):
        scheduler.DecodeSlots defers that read to one coalesced
        device_get per poll (_fetch), and overlap=True moves it past
        the next dispatch.

        mask: [B, V] bool grammar masks (models/structured.py) riding
        the existing operands — requires chunk == 1 (the mask is a
        scan constant); mask=None leaves every call expression
        byte-identical, so unconstrained serving never retraces."""
        if self.backend == "mega":
            raise ValueError(
                "backend='mega' fuses the PAGED decode tick only "
                "(paged_slot_chunk); contiguous slot serving runs the "
                "per-op backends — use ContinuousScheduler(paged=True) "
                "or backend='flash'")
        if mask is not None and chunk != 1:
            raise ValueError(
                f"grammar masks are per-step (scan constants): serve "
                f"constrained slots at chunk == 1, got chunk={chunk}")
        self._c_decode.inc()
        if self._comm_backend:
            self._c_comm.inc()
        if self.sampling == "greedy":
            assert keys is None
            if mask is not None:
                toks, logits, cache, pos = self._note_moe_load(
                    self._slot_scan(self.model, logits, cache, pos,
                                    active, jnp.asarray(mask, bool),
                                    gen_len=chunk))
                return toks, logits, cache, pos, None
            toks, logits, cache, pos = self._note_moe_load(
                self._slot_scan(self.model, logits, cache, pos, active,
                                gen_len=chunk))
            return toks, logits, cache, pos, None
        if mask is not None:
            toks, logits, cache, pos, keys = self._note_moe_load(
                self._slot_scan(self.model, logits, cache, pos, active,
                                keys, jnp.asarray(mask, bool),
                                gen_len=chunk))
            return toks, logits, cache, pos, keys
        toks, logits, cache, pos, keys = self._note_moe_load(
            self._slot_scan(self.model, logits, cache, pos, active,
                            keys, gen_len=chunk))
        return toks, logits, cache, pos, keys


    # ------------------------------------------------------------------
    # speculative decoding (models/spec_decode.py policy; the
    # scheduler's spec=K mode drives these)
    # ------------------------------------------------------------------

    def spec_seed(self, row_logits, key, mask=None):
        """Draw the pending seed token for a freshly admitted slot from
        its prefill logits (sampled modes only; greedy admission takes
        the host argmax). mask [V] bool: grammar-legal support for a
        constrained slot. Returns (token, evolved key)."""
        assert self.sampling != "greedy"
        if mask is not None:
            return self._spec_seed(row_logits, key,
                                   jnp.asarray(mask, bool))
        return self._spec_seed(row_logits, key)

    def slot_verify_chunk(self, cache, pos, active, tokens, q_lens, *,
                          keys=None, mask=None):
        """One speculative verify step over the CONTIGUOUS slot cache:
        score every slot's draft window (tokens [B, S] — the pending
        seed token at column 0, up to S-1 drafts after, padded; q_lens
        [B] valid lengths) in ONE forward at per-slot positions pos,
        run the acceptance rule (greedy: longest argmax-matching
        prefix; sampled: leftover rejection sampling through the
        per-slot PRNG chains `keys`), write the window KV, and advance
        each slot by its accepted count — the rejected suffix stays as
        dead rows past the rewound length, overwritten by the next
        step. Returns (n_emit [B] — tokens kept from the window,
        t0_next [B] — the corrected next seed token, cache, pos, keys).
        mask: [B, S, V] bool per-position grammar masks
        (structured.window_masks) constraining acceptance + reseed.
        """
        if self.backend == "mega":
            raise ValueError(
                "backend='mega' does not fuse the spec-decode verify "
                "window yet (per-slot q_lens stay on the per-op "
                "programs); serve spec=K on the per-op backends")
        tokens = jnp.asarray(tokens, jnp.int32)
        q_lens = jnp.asarray(q_lens, jnp.int32)
        self._c_verify.inc()
        if self._comm_backend:
            self._c_comm.inc()
        if self.sampling == "greedy":
            assert keys is None
            if mask is not None:
                n_emit, t0n, cache, pos = self._note_moe_load(
                    self._slot_verify(self.model, cache, pos, active,
                                      tokens, q_lens,
                                      jnp.asarray(mask, bool)))
                return n_emit, t0n, cache, pos, None
            n_emit, t0n, cache, pos = self._note_moe_load(
                self._slot_verify(self.model, cache, pos, active,
                                  tokens, q_lens))
            return n_emit, t0n, cache, pos, None
        if mask is not None:
            n_emit, t0n, cache, pos, keys = self._note_moe_load(
                self._slot_verify(self.model, cache, pos, active,
                                  tokens, q_lens, keys,
                                  jnp.asarray(mask, bool)))
            return n_emit, t0n, cache, pos, keys
        n_emit, t0n, cache, pos, keys = self._note_moe_load(
            self._slot_verify(self.model, cache, pos, active, tokens,
                              q_lens, keys))
        return n_emit, t0n, cache, pos, keys

    def paged_slot_verify_chunk(self, pcache, pos, active, tokens,
                                q_lens, *, keys=None, mask=None):
        """slot_verify_chunk over the PAGED pool: identical contract,
        with the window KV scatter and attention resolved through the
        page table (a padded row's write drops out of bounds, so it can
        never touch a live or cached page; rejected rows stay in the
        slot's own mapped pages until the next window overwrites them).
        """
        if self.backend == "mega":
            raise ValueError(
                "backend='mega' does not fuse the spec-decode verify "
                "window yet (the fused tick is the greedy S == 1 "
                "paged step); serve spec=K on the per-op backends")
        tokens = jnp.asarray(tokens, jnp.int32)
        q_lens = jnp.asarray(q_lens, jnp.int32)
        self._c_verify.inc()
        if self._comm_backend:
            self._c_comm.inc()
        if self.sampling == "greedy":
            assert keys is None
            if mask is not None:
                n_emit, t0n, pcache, pos = self._note_moe_load(
                    self._paged_slot_verify(self.model, pcache, pos,
                                            active, tokens, q_lens,
                                            jnp.asarray(mask, bool)))
                return n_emit, t0n, pcache, pos, None
            n_emit, t0n, pcache, pos = self._note_moe_load(
                self._paged_slot_verify(self.model, pcache, pos, active,
                                        tokens, q_lens))
            return n_emit, t0n, pcache, pos, None
        if mask is not None:
            n_emit, t0n, pcache, pos, keys = self._note_moe_load(
                self._paged_slot_verify(self.model, pcache, pos, active,
                                        tokens, q_lens, keys,
                                        jnp.asarray(mask, bool)))
            return n_emit, t0n, pcache, pos, keys
        n_emit, t0n, pcache, pos, keys = self._note_moe_load(
            self._paged_slot_verify(self.model, pcache, pos, active,
                                    tokens, q_lens, keys))
        return n_emit, t0n, pcache, pos, keys

    # ------------------------------------------------------------------
    # chunked prefill (Sarathi-Serve, 2403.02310 — PAPERS.md): the
    # scheduler's mixed prefill+decode ticks. One forward covers every
    # live decode slot (q_len = 1) AND up to prefill_budget tokens of
    # in-progress prefills (q_len = chunk), riding the verify paths'
    # per-slot q_lens/kv_lens masks: chunk rows write their KV
    # (contiguous columns or pages) exactly like a verify window, but
    # their "acceptance" is unconditional (they are prompt tokens) and
    # they emit a next-token logit only when the final chunk lands —
    # the slot then arms and joins decode (scheduler._arm_slot).
    # ------------------------------------------------------------------

    def slot_mixed_chunk(self, logits, cache, pos, active, prefilling,
                         tokens, q_lens, *, keys=None, mask=None):
        """One MIXED prefill+decode tick over the CONTIGUOUS slot cache.

        tokens [B, S] / q_lens [B]: row b of a PREFILLING slot holds
        its next q_lens[b] prompt tokens (positions pos[b] ..
        pos[b] + q_lens[b] - 1; q_lens[b] == 0 is a budget-starved
        prefill that makes no progress this tick); a decode row's
        column 0 is filled IN-PROGRAM from its own carry logits
        (argmax, or one per-slot key split — exactly one scan step of
        the plain chunk path) and q_lens[b] == 1. prefilling [B] bool
        marks the chunk rows (always disjoint from `active`: a
        prefilling slot is not armed). Returns (tok [B] — the token
        each decode row emitted this tick, sel_logits [B, V] — the
        logits at each row's last valid window position (a decode
        row's next carry; a final-chunk prefill row's ARMING logits),
        cache, pos, keys). pos advances by q_lens for prefill rows and
        by 1 for active decode rows. mask: [B, V] grammar masks over
        the decode rows' token selection (sel_logits stay raw)."""
        if self.backend == "mega":
            raise ValueError(
                "backend='mega' fuses the PAGED decode tick only; "
                "contiguous mixed ticks run the per-op backends (the "
                "paged mixed tick falls back automatically)")
        tokens = jnp.asarray(tokens, jnp.int32)
        q_lens = jnp.asarray(q_lens, jnp.int32)
        prefilling = jnp.asarray(prefilling, bool)
        if self.sampling == "greedy":
            assert keys is None
        self._c_mixed.inc()
        if self._comm_backend:
            self._c_comm.inc()
        if mask is not None:
            return self._note_moe_load(
                self._slot_mixed(self.model, logits, cache, pos, active,
                                 prefilling, tokens, q_lens, keys,
                                 jnp.asarray(mask, bool)))
        return self._note_moe_load(
            self._slot_mixed(self.model, logits, cache, pos, active,
                             prefilling, tokens, q_lens, keys))

    def paged_slot_mixed_chunk(self, logits, pcache, pos, active,
                               prefilling, tokens, q_lens, *, keys=None,
                               mask=None):
        """slot_mixed_chunk over the PAGED pool: identical contract,
        chunk rows scatter their KV through the page table (padded rows
        drop out of bounds) and attention walks the pool with per-slot
        kv_lens AND q_lens."""
        tokens = jnp.asarray(tokens, jnp.int32)
        q_lens = jnp.asarray(q_lens, jnp.int32)
        prefilling = jnp.asarray(prefilling, bool)
        if self.sampling == "greedy":
            assert keys is None
        self._c_mixed.inc()
        if self._comm_backend:
            self._c_comm.inc()
        if mask is not None:
            return self._note_moe_load(
                self._paged_slot_mixed(self.model, logits, pcache, pos,
                                       active, prefilling, tokens,
                                       q_lens, keys,
                                       jnp.asarray(mask, bool)))
        return self._note_moe_load(
            self._paged_slot_mixed(self.model, logits, pcache, pos,
                                   active, prefilling, tokens, q_lens,
                                   keys))

    def slot_mixed_verify_chunk(self, cache, pos, active, prefilling,
                                tokens, q_lens, *, keys=None, mask=None):
        """Spec-mode mixed tick (CONTIGUOUS): decode rows carry their
        draft-verify windows (seed at column 0, q_lens up to spec+1 —
        the _slot_verify contract) while prefill rows carry prompt
        chunks; ONE forward scores everything. The acceptance epilogue
        runs for decode rows only; prefill rows advance by their full
        chunk unconditionally. Returns (n_emit [B], t0_next [B],
        sel_logits [B, V] — arming logits at each row's last valid
        window position, cache, pos, keys). mask: [B, S, V] grammar
        window masks over acceptance (sel_logits stay raw)."""
        if self.backend == "mega":
            raise ValueError(
                "backend='mega' does not fuse the spec-decode verify "
                "window yet; serve spec=K on the per-op backends")
        tokens = jnp.asarray(tokens, jnp.int32)
        q_lens = jnp.asarray(q_lens, jnp.int32)
        prefilling = jnp.asarray(prefilling, bool)
        if self.sampling == "greedy":
            assert keys is None
        self._c_mixed.inc()
        if self._comm_backend:
            self._c_comm.inc()
        if mask is not None:
            return self._note_moe_load(
                self._slot_mixed_verify(self.model, cache, pos, active,
                                        prefilling, tokens, q_lens,
                                        keys, jnp.asarray(mask, bool)))
        return self._note_moe_load(
            self._slot_mixed_verify(self.model, cache, pos, active,
                                    prefilling, tokens, q_lens, keys))

    def paged_slot_mixed_verify_chunk(self, pcache, pos, active,
                                      prefilling, tokens, q_lens, *,
                                      keys=None, mask=None):
        """slot_mixed_verify_chunk over the PAGED pool."""
        tokens = jnp.asarray(tokens, jnp.int32)
        q_lens = jnp.asarray(q_lens, jnp.int32)
        prefilling = jnp.asarray(prefilling, bool)
        if self.sampling == "greedy":
            assert keys is None
        self._c_mixed.inc()
        if self._comm_backend:
            self._c_comm.inc()
        if mask is not None:
            return self._note_moe_load(
                self._paged_slot_mixed_verify(self.model, pcache, pos,
                                              active, prefilling,
                                              tokens, q_lens, keys,
                                              jnp.asarray(mask, bool)))
        return self._note_moe_load(
            self._paged_slot_mixed_verify(self.model, pcache, pos,
                                          active, prefilling, tokens,
                                          q_lens, keys))

    def install_slot_paged(self, pcache, slot: int, rows, cow_src,
                           cow_dst, cow_rows: int):
        """Chunk 0 of a CHUNKED paged admission: install the slot's
        table row block and copy-on-write the partially matched
        boundary page — the one-time half of admit_slot_paged, with the
        suffix prefill left to the mixed-chunk ticks (which resolve
        their KV scatter and attention through the table just
        installed). Same rows/cow contract as admit_slot_paged."""
        return self._paged_install(
            self.model, pcache, jnp.asarray(rows, jnp.int32),
            jnp.int32(slot), jnp.asarray(cow_src, jnp.int32),
            jnp.asarray(cow_dst, jnp.int32), jnp.int32(cow_rows))

    # ------------------------------------------------------------------
    # paged slot path (shared-prefix serving; models/prefix_cache.py
    # owns the policy — radix tree, refcounts, eviction — and drives
    # these device-side entry points through the scheduler).
    #
    # The slot lifecycle these programs implement is PREEMPTIBLE
    # (models/scheduler.py resilience): a preemption is exactly a
    # retire (retire_slot_paged — tree insert is host bookkeeping,
    # table rows to trash) followed later by a re-admission of the
    # prompt + generated sequence through admit_slot_paged, whose
    # prefix match caps at n-1 so only the last token's KV recomputes
    # while the tree still holds the pages. No preemption-specific
    # device program exists — that is the point.
    # ------------------------------------------------------------------

    def make_paged_slot_cache(self, batch: int, *, page: int = 16,
                              num_pages: Optional[int] = None,
                              for_ticks: bool = True):
        """Paged slot cache: per-layer physical pools behind ONE shared
        page table (kv_cache.PagedSlotCache). num_pages defaults to the
        no-sharing worst case (every slot full) + the reserved trash
        page; pass fewer to let prefix sharing carry the load (and the
        LRU evictor handle the pressure).

        kv_dtype=int8 engines get the INT8 POOL (per-position scale
        planes riding the page payload — kv_cache.PagedSlotCache):
        half the decode KV read, double the resident pages, streams
        bitwise equal to the contiguous int8 cache.

        TP: the pool's page payloads are HEAD-SHARDED over the model's
        mesh (kv_cache.PagedSlotCache TP SHARDING) and the slot
        programs run each chip's attention over its local kv-head
        shard under shard_map — one scheduler drives the whole TP=N
        mesh. The mesh size must divide n_kv_heads (validated here
        with a real error instead of a shard shape mismatch deep in
        compile); GQA replication (num_heads > num_kv_heads) is a
        query-side property and changes nothing about the pool split."""
        from triton_dist_tpu.models.kv_cache import PagedSlotCache
        if self.backend == "mega" and \
                self.model.mesh.shape[self.model.axis] > 1:
            raise ValueError(
                "backend='mega' fuses the paged tick single-chip only "
                "(the TP pool's head-group plane split stays on the "
                "per-op shard_map path); serve TP meshes with "
                "backend='flash'/'dist'/'ar'/'gemm_ar'")
        if not hasattr(self.model, "forward_tokens_slots_paged"):
            raise ValueError(
                f"{type(self.model).__name__} has no paged slot decode "
                "path (DenseLLM and Qwen3MoE carry the serving "
                "surface)")
        if for_ticks:
            # a pool that will DRIVE decode/verify/mixed ticks feeds
            # its batch rows to the row-sharded EP dispatch; staging
            # pools (disagg prefill workers, for_ticks=False) only run
            # bucketed admit forwards and skip the batch gate
            self._moe_batch_check(batch)
        cfg = self.model.config
        tp = self.model.mesh.shape[self.model.axis]
        if cfg.num_kv_heads % tp:
            rep = cfg.num_heads // max(cfg.num_kv_heads, 1)
            raise ValueError(
                f"paged TP serving needs num_kv_heads "
                f"({cfg.num_kv_heads}) divisible by the TP mesh size "
                f"({tp}); this model's GQA replication factor is {rep} "
                f"(query heads replicate per kv head, but the KV pool "
                f"itself splits on kv heads) — serve on a mesh whose "
                f"size divides {cfg.num_kv_heads}, or replicate kv "
                f"heads in the checkpoint")
        maxp = -(-self.max_seq // page)
        sp_ax = getattr(self.model, "sp_axis", None)
        if num_pages is None:
            num_pages = batch * cfg.num_kv_heads * maxp + 1
            if self.sp_size > 1:
                # the default rounds UP to the sp partition (each chip
                # owns a whole contiguous id block)
                num_pages = -(-num_pages // self.sp_size) * self.sp_size
        elif self.sp_size > 1 and num_pages % self.sp_size:
            raise ValueError(
                f"sequence-parallel pool needs num_pages ({num_pages}) "
                f"divisible by the sp mesh size ({self.sp_size}): the "
                f"page-id space partitions into equal per-chip blocks "
                f"— round num_pages up to a multiple of {self.sp_size} "
                f"or shrink the sp axis")
        return PagedSlotCache.create(
            cfg.num_layers, batch, self.max_seq, cfg.num_kv_heads,
            cfg.head_dim, page=page, num_pages=num_pages,
            mesh=self.model.mesh, axis=self.model.axis,
            dtype=self.kv_dtype or cfg.jax_dtype,
            sp_axis=sp_ax if self.sp_size > 1 else None)

    def admit_slot_paged(self, pcache, slot: int, ids, rows,
                         kv_start: int, cow_src, cow_dst, cow_rows: int,
                         *, pad_to: int = 8):
        """Admit one request into paged slot `slot`, reusing a cached
        prefix of `kv_start` tokens (prefill-from-offset: ONLY the
        n - kv_start uncached suffix tokens are computed, bucketed to
        `pad_to` like prefill_into_slot).

        rows: [Hkv, max_pages] int32 — the slot's full table row block
        (shared prefix pages + fresh writable pages, trash-padded).
        cow_src/cow_dst: [Hkv] page groups for the copy-on-write of a
        partially-matched boundary page (cow_rows valid rows are copied
        src -> dst before anything reads the slot's table; pass the
        trash page for both when kv_start is page-aligned).

        Returns (next-token logits [V], pcache). One XLA program per
        suffix bucket; kv_start/slot/cow are traced data.
        """
        ids = jnp.asarray(ids, jnp.int32).reshape(-1)
        if self._ep_rows > 1:
            # suffix buckets feed the row-sharded expert dispatch too
            import math
            pad_to = math.lcm(pad_to, self._ep_rows)
        n = int(ids.shape[0])
        m = int(kv_start)
        if not 0 <= m < n:
            raise ValueError(f"kv_start {m} out of range for prompt {n}"
                             " (the last token is always recomputed)")
        T_pool = pcache.capacity
        if n > T_pool:
            raise ValueError(
                f"prompt length {n} exceeds slot capacity {T_pool}")
        s = n - m
        P = -(-s // pad_to) * pad_to
        padded = jnp.zeros((1, P), jnp.int32).at[0, :s].set(ids[m:])
        self._c_prefills.inc()
        with self._scratch_lock:
            scr = self._paged_scratch
            if scr is None or scr.k[0].shape[2] != T_pool + pad_to:
                # scratch holds [prefix | suffix bucket]; the + pad_to
                # tail keeps the bucketed DUS in range at every
                # kv_start
                self._paged_scratch = self.model.make_cache(
                    1, T_pool + pad_to, dtype=self.kv_dtype)
            logits, self._paged_scratch, pcache = self._paged_admit(
                self.model, padded, self._paged_scratch, pcache,
                jnp.asarray(rows, jnp.int32), jnp.int32(slot),
                jnp.int32(m), jnp.int32(n),
                jnp.asarray(cow_src, jnp.int32),
                jnp.asarray(cow_dst, jnp.int32), jnp.int32(cow_rows))
        return logits[0], pcache

    def paged_slot_chunk(self, logits, pcache, pos, active, *,
                         chunk: int, keys=None, mask=None):
        """slot_chunk over the paged pool: identical contract, but each
        row's KV scatter resolves through the page table (a retired
        row's table maps the trash page, so its masked-out writes can
        never touch a live or cached page).

        backend='mega' routes this tick through the FUSED program
        (_paged_slot_mega_scan_fn — one MegaPagedDecodeLayer kernel
        per layer per step instead of the per-op dispatch chain),
        greedy-only by construction; same contract, same carry.

        mask: [B, V] grammar masks (chunk == 1 required, see
        slot_chunk); the fused mega tick does not take them — its
        in-kernel argmax never sees a mask operand."""
        self._c_decode.inc()
        if self._comm_backend:
            self._c_comm.inc()
        if self.backend == "mega":
            if mask is not None:
                raise ValueError(
                    "backend='mega' fuses the greedy paged tick with "
                    "an in-kernel argmax and takes no grammar mask "
                    "operand; serve constrained requests on the "
                    "per-op backends (backend='flash'/'dist'/...)")
            assert keys is None   # greedy enforced at __init__
            self._c_mega.inc()
            toks, logits, pcache, pos = self._paged_slot_mega(
                self.model, logits, pcache, pos, active, gen_len=chunk)
            return toks, logits, pcache, pos, None
        if mask is not None and chunk != 1:
            raise ValueError(
                f"grammar masks are per-step (scan constants): serve "
                f"constrained slots at chunk == 1, got chunk={chunk}")
        if self.sampling == "greedy":
            assert keys is None
            if mask is not None:
                toks, logits, pcache, pos = self._note_moe_load(
                    self._paged_slot_scan(self.model, logits, pcache,
                                          pos, active,
                                          jnp.asarray(mask, bool),
                                          gen_len=chunk))
                return toks, logits, pcache, pos, None
            toks, logits, pcache, pos = self._note_moe_load(
                self._paged_slot_scan(self.model, logits, pcache, pos,
                                      active, gen_len=chunk))
            return toks, logits, pcache, pos, None
        if mask is not None:
            toks, logits, pcache, pos, keys = self._note_moe_load(
                self._paged_slot_scan(self.model, logits, pcache, pos,
                                      active, keys,
                                      jnp.asarray(mask, bool),
                                      gen_len=chunk))
            return toks, logits, pcache, pos, keys
        toks, logits, pcache, pos, keys = self._note_moe_load(
            self._paged_slot_scan(self.model, logits, pcache, pos,
                                  active, keys, gen_len=chunk))
        return toks, logits, pcache, pos, keys

    def retire_slot_paged(self, pcache, slot: int):
        """Point the whole table row block of a retired slot at the
        trash page (the write sink): the slot scan keeps stepping
        masked rows, and their scatters must never land on a page the
        allocator may have handed to someone else."""
        Hkv = self.model.config.num_kv_heads
        rows = jnp.full((Hkv, pcache.table.shape[1]), pcache.trash,
                        jnp.int32)
        return self._paged_set_table(pcache, rows, jnp.int32(slot))

    # ------------------------------------------------------------------
    # host KV tier (models/kv_tier.py): demote/promote page spans
    # between the device pools and pinned host RAM. The prefix cache's
    # residency machine (models/prefix_cache.py) drives these through
    # the PagedDecodeSlots callbacks.
    # ------------------------------------------------------------------

    def extract_pages_host(self, pcache, page_ids, *, heads=None,
                           pad_to: int = 8):
        """DEMOTION d2h (also the disaggregated-serving WIRE FORMAT —
        models/disagg.py ships exactly these arrays from the prefill
        plane's staging pool to the decode pool, a transferred page
        being a demoted page with a different destination): gather the
        listed physical pages out of every
        layer's K/V pool and return them as host arrays
        (k, v each [L, N, page, d], pool dtype — the raw bytes, so a
        later restore is bitwise; an int8 pool appends its scale
        planes (k, v, ks, vs) so the scales ride the same transfer).
        The id list is trash-padded to a pad_to bucket (bounded
        executable count; the padded reads are sliced off before
        returning). The gather is dispatched async — the device_get
        below is the synchronization point, i.e. the copy overlaps
        whatever was already in flight.

        heads: the kv-head index behind each page id (page groups are
        head-ordered, so callers always know it — the scheduler's tier
        callback passes tile(arange(Hkv))). REQUIRED on a TP-sharded
        pool (head_groups > 1): it selects each page's owning payload
        plane so the gathered bytes are the true ones; ignored on a
        single-group pool."""
        import numpy as np
        ids = np.asarray(page_ids, np.int32).reshape(-1)
        n = len(ids)
        G = pcache.head_groups
        if G > 1 and heads is None:
            raise ValueError(
                "extract_pages_host on a TP-sharded pool needs the "
                "per-page kv-head indices (heads=...) to pick each "
                "page's owning payload plane")
        P = max(-(-n // pad_to) * pad_to, pad_to)
        padded = np.full((P,), pcache.trash, np.int32)
        padded[:n] = ids
        owners = np.zeros((P,), np.int32)
        if heads is not None and G > 1:
            hkv_loc = self.model.config.num_kv_heads // G
            owners[:n] = np.asarray(heads, np.int32) // hkv_loc
        out = self._gather_pages(self.model, pcache, jnp.asarray(padded),
                                 jnp.asarray(owners))
        # one device_get over every array: the K/V (and scale) d2h
        # transfers overlap instead of serializing on the eviction
        # critical path
        out = jax.device_get(out)
        return tuple(np.asarray(a)[:, :n].copy() for a in out)

    def restore_pages_host(self, pcache, page_ids, host_k, host_v,
                           host_ks=None, host_vs=None, *,
                           pad_to: int = 8):
        """PROMOTION h2d: install previously extracted page contents
        (extract_pages_host's k/v arrays — plus its ks/vs scale planes
        for an int8 pool) into the listed freshly allocated physical
        pages of every layer's pool — one scatter program per bucket
        on the donated cache, run BEFORE the promoted prefix is mapped
        into any slot's table. Padded tail ids point at the trash page
        (zero payload — harmless)."""
        import numpy as np
        ids = np.asarray(page_ids, np.int32).reshape(-1)
        n = len(ids)
        if host_k.shape[1] != n or host_v.shape[1] != n:
            raise ValueError(
                f"payload covers {host_k.shape[1]} pages, ids list "
                f"{n}")
        if bool(pcache.scales_k) != (host_ks is not None):
            raise ValueError(
                "int8 pools restore payloads WITH scale planes; bf16 "
                "pools without — the payload does not match this pool")
        P = max(-(-n // pad_to) * pad_to, pad_to)
        padded = np.full((P,), pcache.trash, np.int32)
        padded[:n] = ids
        L, _, page, d = host_k.shape
        hk = np.zeros((L, P, page, d), host_k.dtype)
        hv = np.zeros((L, P, page, d), host_v.dtype)
        hk[:, :n] = host_k
        hv[:, :n] = host_v
        hsk = hsv = None
        if host_ks is not None:
            hsk = np.zeros((L, P, page), host_ks.dtype)
            hsv = np.zeros((L, P, page), host_vs.dtype)
            hsk[:, :n] = host_ks
            hsv[:, :n] = host_vs
            hsk, hsv = jnp.asarray(hsk), jnp.asarray(hsv)
        return self._restore_pages(self.model, pcache,
                                   jnp.asarray(padded),
                                   jnp.asarray(hk), jnp.asarray(hv),
                                   hsk, hsv)


def _params_key(params: dict) -> tuple:
    """Hashable key of the sampling params dict (the _jit_programs
    cache key component)."""
    return (params["temperature"], params["k"], params["p"])


@functools.lru_cache(maxsize=None)
def _jit_programs(backend: str, sampling: str, pkey: tuple,
                  prefill_mode: str) -> dict:
    """The engine's jitted program set, ONE per (backend, sampling,
    params, prefill-mode) configuration process-wide.

    jax's executable cache keys on the python callable object, so
    building these per Engine instance (the old per-__init__ partials)
    recompiled every program once per engine — serving restarts, test
    suites, and TP-vs-single-chip differentials all paid the whole
    compile bill repeatedly for identical configurations. The model is
    a traced ARGUMENT of every program (weights never bake in), and
    all mutable per-engine state (scratch caches, counters) lives on
    the instance, so sharing the jit wrappers is purely a
    compile-cache win. Contents:

    - prefill / decode_scan: the uniform-batch serve() pair;
    - slot_scan / prefill_slot / write_slot: continuous batching
      (models/scheduler.py) — slot-masked chunked decode + the
      bucketed prefill-into-slot pair;
    - paged_slot_scan / paged_admit / paged_set_table /
      paged_install: the shared-prefix paged pool family (admission =
      table install + CoW + prefix gather + suffix
      prefill-from-offset + KV scatter; retire = table reset);
    - slot_verify / paged_slot_verify (+ spec_seed under sampling):
      speculative-decoding verify forwards with the on-device accept;
    - slot_mixed / paged_slot_mixed (+ _verify twins): the chunked-
      prefill mixed prefill+decode ticks;
    - gather_pages / restore_pages: the host-KV-tier d2h/h2d pair.

    MODEL FAMILIES (ISSUE 13): the same jit wrappers serve the dense
    AND the `moe` model family — the model rides in as a traced
    argument and its static config picks the trace (_is_moe), so a
    Qwen3MoE compiles slot programs that run per-slot top-k routing +
    grouped-GEMM expert dispatch inside every tick and append the
    routing-load vector as one extra output (Engine._note_moe_load
    strips and stashes it), while dense models' traces stay
    byte-identical. ep/ep_flash backends (expert-sharded FFN over the
    a2a kernels) flow through the same program set as a mode string.

    backend='mega' (the fused paged decode tick — ISSUE 12): the
    per-op family above is built at the FALLBACK backend ("flash" —
    the mega engine's prefill/mixed/admission programs are per-op by
    design), decode_scan is the contiguous megakernel loop, and
    paged_slot_mega is the fused greedy paged tick (one
    MegaPagedDecodeLayer kernel per layer per step, scanned with a
    donated pool).

    All lazy-compiled: a path never exercised costs nothing."""
    params = dict(temperature=pkey[0], k=pkey[1], p=pkey[2])
    greedy = sampling == "greedy"
    # the per-op fallback backend: mega serves its admissions, mixed
    # prefill+decode ticks and host-tier hops through these programs
    fb = "flash" if backend == "mega" else backend
    P = {}
    P["prefill"] = jax.jit(functools.partial(_prefill_fn,
                                             mode=prefill_mode))
    if backend == "mega":
        P["decode_scan"] = jax.jit(
            _mega_scan_decode_fn, static_argnames=("gen_len",),
            donate_argnums=(2,))
        P["paged_slot_mega"] = jax.jit(
            _paged_slot_mega_scan_fn, static_argnames=("gen_len",),
            donate_argnums=(2,))
    else:
        scan_fn = (functools.partial(_scan_decode_fn, backend) if greedy
                   else functools.partial(_sampled_scan_decode_fn,
                                          backend, sampling, params))
        P["decode_scan"] = jax.jit(scan_fn,
                                   static_argnames=("gen_len",),
                                   donate_argnums=(2,))
    slot_fn = (functools.partial(_slot_scan_decode_fn, fb)
               if greedy else
               functools.partial(_sampled_slot_scan_decode_fn, fb,
                                 sampling, params))
    P["slot_scan"] = jax.jit(slot_fn, static_argnames=("gen_len",),
                             donate_argnums=(2,))
    P["prefill_slot"] = jax.jit(
        functools.partial(_prefill_slot_fn, mode=prefill_mode),
        donate_argnums=(2,))
    P["write_slot"] = jax.jit(_write_slot_fn, donate_argnums=(0,))
    paged_fn = (functools.partial(_paged_slot_scan_decode_fn, fb)
                if greedy else
                functools.partial(_sampled_paged_slot_scan_fn, fb,
                                  sampling, params))
    P["paged_slot_scan"] = jax.jit(paged_fn,
                                   static_argnames=("gen_len",),
                                   donate_argnums=(2,))
    P["paged_admit"] = jax.jit(
        functools.partial(_paged_admit_fn, mode=prefill_mode),
        donate_argnums=(2, 3))
    P["paged_set_table"] = jax.jit(_paged_set_table_fn,
                                   donate_argnums=(0,))
    if greedy:
        vfn = functools.partial(_slot_verify_fn, fb)
        pvfn = functools.partial(_paged_slot_verify_fn, fb)
    else:
        vfn = functools.partial(_sampled_slot_verify_fn, fb,
                                sampling, params)
        pvfn = functools.partial(_sampled_paged_slot_verify_fn, fb,
                                 sampling, params)
        P["spec_seed"] = jax.jit(functools.partial(_spec_seed_fn,
                                                   sampling, params))
    P["slot_verify"] = jax.jit(vfn, donate_argnums=(1,))
    P["paged_slot_verify"] = jax.jit(pvfn, donate_argnums=(1,))
    samp = None if greedy else sampling
    P["slot_mixed"] = jax.jit(
        functools.partial(_mixed_step_fn, fb, samp, params, False),
        donate_argnums=(2,))
    P["paged_slot_mixed"] = jax.jit(
        functools.partial(_mixed_step_fn, fb, samp, params, True),
        donate_argnums=(2,))
    P["slot_mixed_verify"] = jax.jit(
        functools.partial(_mixed_verify_fn, fb, samp, params,
                          False),
        donate_argnums=(1,))
    P["paged_slot_mixed_verify"] = jax.jit(
        functools.partial(_mixed_verify_fn, fb, samp, params,
                          True),
        donate_argnums=(1,))
    P["paged_install"] = jax.jit(_paged_install_fn, donate_argnums=(1,))
    P["gather_pages"] = jax.jit(_gather_pages_fn)
    P["restore_pages"] = jax.jit(_restore_pages_fn, donate_argnums=(1,))
    return P


def _prefill_fn(model, ids, cache, *, mode):
    return model.forward_tokens(ids, cache, mode=mode)


def _prefill_slot_fn(model, ids, cache, last_pos, *, mode):
    """Bucketed batch-1 prefill: logits taken at the last REAL prompt
    position (the pad tail's logits are garbage and discarded). The
    scratch cache is REUSED across admissions (donated through), so its
    offset must restart at 0 every time."""
    import dataclasses
    cache = dataclasses.replace(cache, offset=jnp.int32(0))
    return model.forward_tokens(ids, cache, mode=mode, last_pos=last_pos)


def _write_slot_fn(cache, scratch, slot):
    """Copy a 1-row scratch cache over batch row `slot` of the shared
    slot cache (donated): one DUS per layer buffer. The whole row is
    replaced — including the zero tail — so stale KV from a retired
    request cannot leak into the new occupant's masked-out columns."""
    import dataclasses

    def put(bufs, rows):
        return tuple(
            jax.lax.dynamic_update_slice(
                b, r.astype(b.dtype), (slot,) + (0,) * (b.ndim - 1))
            for b, r in zip(bufs, rows))

    out = dataclasses.replace(
        cache, k=put(cache.k, scratch.k), v=put(cache.v, scratch.v))
    if cache.ks:
        out = dataclasses.replace(out, ks=put(cache.ks, scratch.ks),
                                  vs=put(cache.vs, scratch.vs))
    return out


def _is_moe(model) -> bool:
    """Static (trace-time) family switch of the slot programs below:
    a MoE-family model's slot forwards additionally return the tick's
    routing-load vector (the `moe` model family of _jit_programs —
    same jit wrappers, the model's static config picks the trace).
    config is static pytree metadata, so this never retraces a given
    model inconsistently."""
    return bool(getattr(model.config, "is_moe", False)) \
        and hasattr(model, "forward_tokens_slots")


def _slot_scan_decode_fn(backend, model, logits0, cache, pos, active,
                         mask=None, *, gen_len: int):
    """Slot-masked greedy decode chunk (continuous batching): same
    shape as _scan_decode_fn, but each batch row is an independent
    request at its own position. Inactive rows still flow through the
    program (masking keeps it ONE executable for every occupancy mix);
    their writes land in their own dead cache rows and their tokens are
    discarded by the scheduler. MoE family: the routing-load vector
    rides the scan carry and returns as one extra output (the dense
    trace is untouched).

    mask [B, V] bool (models/structured.py grammar masks): token
    selection argmaxes over where(mask, logits, -inf) — constant
    across the scan, so grammar serving runs chunk == 1 (the
    scheduler's _eff_chunk); mask=None leaves the trace byte-identical
    to before the grammar subsystem existed."""
    act = active.astype(jnp.int32)
    moe = _is_moe(model)

    def step(carry, _):
        if moe:
            logits, cache, pos, load = carry
        else:
            logits, cache, pos = carry
        sel = logits if mask is None else \
            jnp.where(mask, logits, -jnp.inf)
        tok = jnp.argmax(sel, axis=-1)              # greedy [B]
        tok = jnp.where(active, tok, 0)
        if moe:
            logits, cache, st = model.forward_tokens_slots(
                tok[:, None], cache, pos, mode=backend,
                return_moe_stats=True)
        else:
            logits, cache = model.forward_tokens_slots(
                tok[:, None], cache, pos, mode=backend)
        # clamp: a slot that finished mid-chunk keeps stepping until the
        # chunk boundary; its surplus writes stay inside its own row
        pos = jnp.minimum(pos + act, cache.k[0].shape[2] - 1)
        if moe:
            return (logits, cache, pos, load + st), tok
        return (logits, cache, pos), tok

    init = ((logits0, cache, pos, model._zero_load()) if moe
            else (logits0, cache, pos))
    out, toks = jax.lax.scan(step, init, None, length=gen_len)
    if moe:
        logits, cache, pos, load = out
        return toks.T, logits, cache, pos, load      # [B, gen_len]
    logits, cache, pos = out
    return toks.T, logits, cache, pos                # [B, gen_len]


def _sampled_slot_scan_decode_fn(backend, sampling, params, model,
                                 logits0, cache, pos, active, keys,
                                 mask=None, *, gen_len: int):
    """Sampled slot decode chunk: per-slot PRNG keys split once per
    step, so each slot's sampled chain equals a single-request
    Engine.serve() at that slot's seed — and is invariant to chunk
    boundaries and to whatever the other slots are doing.

    mask [B, V] bool: grammar-illegal logits drop to -inf BEFORE the
    top-k/top-p sampler, so the emitted marginal is the sampler's
    renormalized over the legal support (mask=None: untouched trace)."""
    from triton_dist_tpu.models.utils import sample_top_k, sample_top_p

    temp = max(params["temperature"], 0.0)
    act = active.astype(jnp.int32)

    def sample_one(k, logits):
        if temp == 0.0:
            return jnp.argmax(logits, axis=-1)
        if sampling == "top_k":
            return sample_top_k(k, logits, k=params["k"],
                                temperature=temp)
        return sample_top_p(k, logits, p=params["p"], temperature=temp)

    moe = _is_moe(model)

    def step(carry, _):
        if moe:
            logits, cache, pos, keys, load = carry
        else:
            logits, cache, pos, keys = carry
        split = jax.vmap(functools.partial(jax.random.split, num=2))
        ks = split(keys)
        keys, subs = ks[:, 0], ks[:, 1]
        sel = logits if mask is None else \
            jnp.where(mask, logits, -jnp.inf)
        tok = jax.vmap(sample_one)(subs, sel)       # [B]
        tok = jnp.where(active, tok, 0)
        if moe:
            logits, cache, st = model.forward_tokens_slots(
                tok[:, None], cache, pos, mode=backend,
                return_moe_stats=True)
        else:
            logits, cache = model.forward_tokens_slots(
                tok[:, None], cache, pos, mode=backend)
        pos = jnp.minimum(pos + act, cache.k[0].shape[2] - 1)
        if moe:
            return (logits, cache, pos, keys, load + st), tok
        return (logits, cache, pos, keys), tok

    init = ((logits0, cache, pos, keys, model._zero_load()) if moe
            else (logits0, cache, pos, keys))
    out, toks = jax.lax.scan(step, init, None, length=gen_len)
    if moe:
        logits, cache, pos, keys, load = out
        return toks.T, logits, cache, pos, keys, load
    logits, cache, pos, keys = out
    return toks.T, logits, cache, pos, keys          # [B, gen_len]


def _spec_seed_fn(sampling, params, logits, key, mask=None):
    """Sample the pending seed token for a fresh spec-mode slot from
    its prefill logits, consuming one split of the slot's PRNG chain
    (models/spec_decode.py; greedy admission argmaxes on the host).
    mask [V] bool: grammar-legal support for a constrained slot's
    arming draw (None: untouched trace)."""
    from triton_dist_tpu.models.utils import sample_top_k, sample_top_p
    temp = max(params["temperature"], 0.0)
    key, sub = jax.random.split(key)
    if mask is not None:
        logits = jnp.where(mask, logits, -jnp.inf)
    if temp == 0.0:
        tok = jnp.argmax(logits, axis=-1)
    elif sampling == "top_k":
        tok = sample_top_k(sub, logits, k=params["k"], temperature=temp)
    else:
        tok = sample_top_p(sub, logits, p=params["p"], temperature=temp)
    return tok.astype(jnp.int32), key


def _verify_accept(sampling, params, logits_all, tokens, q_lens, active,
                   pos, cap, keys=None):
    """Shared acceptance epilogue of the four verify programs
    (models/spec_decode.py): greedy = longest argmax-matching prefix +
    corrected token; sampled = leftover rejection sampling through the
    per-slot PRNG chains (emitted marginal equals the spec-off
    sampler's at every position; temperature=0 collapses to greedy,
    mirroring the samplers' degeneracy). Inactive slots report
    n_emit == 0; pos advances by the accepted count, clamped to the
    cache capacity — the rejected suffix stays as dead rows past the
    rewound length. Returns (n_emit, t0_next, pos, keys)."""
    from triton_dist_tpu.models.spec_decode import (accept_greedy,
                                                    accept_sampled,
                                                    target_probs)
    if sampling is None or max(params["temperature"], 0.0) == 0.0:
        nxt = jnp.argmax(logits_all, axis=-1).astype(jnp.int32)
        n_emit, t0n = accept_greedy(tokens, nxt, q_lens)
    else:
        probs = target_probs(logits_all, sampling, params)
        n_emit, t0n, keys = accept_sampled(keys, probs, tokens, q_lens)
    n_emit = n_emit * active.astype(jnp.int32)
    pos = jnp.minimum(pos + n_emit, cap - 1)
    return n_emit, t0n, pos, keys


def _verify_forward(backend, paged, model, cache, pos, tokens, q_lens):
    """The verify-window forward shared by the verify AND mixed
    programs (contiguous or paged), MoE-family aware: returns
    (per-position logits [B, S, V], cache, capacity, load) where load
    is the routing-load vector for MoE models and None for dense —
    the dense traces are byte-identical to before the MoE family
    existed."""
    moe = _is_moe(model)
    if paged:
        if moe:
            logits_all, cache, load = \
                model.forward_tokens_slots_paged_verify(
                    tokens, cache, pos, q_lens, mode=backend,
                    return_moe_stats=True)
        else:
            logits_all, cache = model.forward_tokens_slots_paged_verify(
                tokens, cache, pos, q_lens, mode=backend)
            load = None
        return logits_all, cache, cache.capacity, load
    if moe:
        logits_all, cache, load = model.forward_tokens_slots_verify(
            tokens, cache, pos, q_lens, mode=backend,
            return_moe_stats=True)
    else:
        logits_all, cache = model.forward_tokens_slots_verify(
            tokens, cache, pos, q_lens, mode=backend)
        load = None
    return logits_all, cache, cache.k[0].shape[2], load


def _slot_verify_fn(backend, model, cache, pos, active, tokens, q_lens,
                    mask=None):
    """Greedy speculative verify (contiguous cache): one forward over
    every slot's padded draft window + the shared on-device acceptance
    epilogue (_verify_accept). Inactive slots flow through masked
    (q_lens handed in as 1, writes land in their own dead rows).

    mask [B, S, V] bool (structured.window_masks): the acceptance rule
    — argmax matching and the corrected seed — runs over
    where(mask, logits, -inf), so a grammar slot only ever accepts or
    reseeds grammar-legal tokens; None = byte-identical trace."""
    logits_all, cache, cap, load = _verify_forward(
        backend, False, model, cache, pos, tokens, q_lens)
    acc = logits_all if mask is None else \
        jnp.where(mask, logits_all, -jnp.inf)
    n_emit, t0n, pos, _ = _verify_accept(
        None, None, acc, tokens, q_lens, active, pos, cap)
    if load is not None:
        return n_emit, t0n, cache, pos, load
    return n_emit, t0n, cache, pos


def _sampled_slot_verify_fn(backend, sampling, params, model, cache, pos,
                            active, tokens, q_lens, keys, mask=None):
    """Sampled _slot_verify_fn: leftover rejection sampling through the
    per-slot PRNG chains (see _verify_accept); a grammar mask zeroes
    the illegal tokens' target probabilities before acceptance."""
    logits_all, cache, cap, load = _verify_forward(
        backend, False, model, cache, pos, tokens, q_lens)
    acc = logits_all if mask is None else \
        jnp.where(mask, logits_all, -jnp.inf)
    n_emit, t0n, pos, keys = _verify_accept(
        sampling, params, acc, tokens, q_lens, active, pos,
        cap, keys)
    if load is not None:
        return n_emit, t0n, cache, pos, keys, load
    return n_emit, t0n, cache, pos, keys


def _paged_slot_verify_fn(backend, model, pcache, pos, active, tokens,
                          q_lens, mask=None):
    """_slot_verify_fn over the PAGED pool (the prefix-cache serving
    path): identical acceptance, KV resolved through the page table."""
    logits_all, pcache, cap, load = _verify_forward(
        backend, True, model, pcache, pos, tokens, q_lens)
    acc = logits_all if mask is None else \
        jnp.where(mask, logits_all, -jnp.inf)
    n_emit, t0n, pos, _ = _verify_accept(
        None, None, acc, tokens, q_lens, active, pos, cap)
    if load is not None:
        return n_emit, t0n, pcache, pos, load
    return n_emit, t0n, pcache, pos


def _sampled_paged_slot_verify_fn(backend, sampling, params, model,
                                  pcache, pos, active, tokens, q_lens,
                                  keys, mask=None):
    """Sampled _paged_slot_verify_fn (see _verify_accept)."""
    logits_all, pcache, cap, load = _verify_forward(
        backend, True, model, pcache, pos, tokens, q_lens)
    acc = logits_all if mask is None else \
        jnp.where(mask, logits_all, -jnp.inf)
    n_emit, t0n, pos, keys = _verify_accept(
        sampling, params, acc, tokens, q_lens, active, pos,
        cap, keys)
    if load is not None:
        return n_emit, t0n, pcache, pos, keys, load
    return n_emit, t0n, pcache, pos, keys


def _mixed_step_fn(backend, sampling, params, paged, model, logits0,
                   cache, pos, active, prefilling, tokens, q_lens, keys,
                   mask=None):
    """Non-spec MIXED prefill+decode tick (chunked prefill,
    models/scheduler.py step_mixed): decode rows behave as exactly one
    step of the plain slot scan (sample from the carry logits — one key
    split per row under the sampled modes, same chain as
    _sampled_slot_scan_decode_fn — write KV at pos, advance 1); prefill
    rows feed their prompt chunk through the verify-window machinery
    (KV written at pos .. pos + q_len - 1, attention over the kv_len
    prior tokens + causal within the window) and advance by q_len. The
    returned sel_logits take each row's LAST valid window position:
    a decode row's next carry, a final-chunk prefill row's arming
    logits (non-final chunks return live-but-unused logits the
    scheduler overwrites on the next tick). A budget-starved prefill
    row (q_len == 0) writes nothing (its padded rows scatter out of
    bounds) and advances 0.

    mask [B, V] bool: constrains the decode rows' token selection from
    the carry logits only — sel_logits stay RAW (a prefill row's
    arming logits must be the unconstrained model output; the grammar
    mask applies at every SELECTION from them, never to the carry)."""
    from triton_dist_tpu.models.utils import sample_top_k, sample_top_p
    B, S = tokens.shape
    sel0 = logits0 if mask is None else \
        jnp.where(mask, logits0, -jnp.inf)
    if sampling is None or max(params["temperature"], 0.0) == 0.0:
        tok = jnp.argmax(sel0, axis=-1).astype(jnp.int32)
    else:
        temp = max(params["temperature"], 0.0)

        def sample_one(k, logits):
            if sampling == "top_k":
                return sample_top_k(k, logits, k=params["k"],
                                    temperature=temp)
            return sample_top_p(k, logits, p=params["p"],
                                temperature=temp)

        split = jax.vmap(functools.partial(jax.random.split, num=2))
        ks = split(keys)
        keys, subs = ks[:, 0], ks[:, 1]
        tok = jax.vmap(sample_one)(subs, sel0).astype(jnp.int32)
    tok = jnp.where(active, tok, 0)
    toks = tokens.at[:, 0].set(jnp.where(active, tok, tokens[:, 0]))
    logits_all, cache, cap, load = _verify_forward(
        backend, paged, model, cache, pos, toks, q_lens)
    sel = jnp.maximum(q_lens - 1, 0)
    sel_logits = logits_all[jnp.arange(B), sel]            # [B, V]
    adv = jnp.where(prefilling, q_lens, active.astype(jnp.int32))
    pos = jnp.minimum(pos + adv, cap - 1)
    if load is not None:
        return tok, sel_logits, cache, pos, keys, load
    return tok, sel_logits, cache, pos, keys


def _mixed_verify_fn(backend, sampling, params, paged, model, cache, pos,
                     active, prefilling, tokens, q_lens, keys,
                     mask=None):
    """Spec-mode mixed tick: one verify-shaped forward over decode
    draft windows AND prefill chunks; the acceptance epilogue
    (_verify_accept) applies to decode rows only (n_emit masked by
    `active`, which is False for prefilling slots), then prefill rows
    advance unconditionally by their chunk length. sel_logits are the
    per-row last-valid-position logits (the arming logits when a final
    chunk lands). mask [B, S, V]: acceptance only — sel_logits stay
    RAW (see _mixed_step_fn)."""
    B, S = tokens.shape
    logits_all, cache, cap, load = _verify_forward(
        backend, paged, model, cache, pos, tokens, q_lens)
    acc = logits_all if mask is None else \
        jnp.where(mask, logits_all, -jnp.inf)
    n_emit, t0n, pos, keys = _verify_accept(
        sampling, params, acc, tokens, q_lens, active, pos, cap,
        keys)
    pos = jnp.minimum(pos + jnp.where(prefilling, q_lens, 0), cap - 1)
    sel = jnp.maximum(q_lens - 1, 0)
    sel_logits = logits_all[jnp.arange(B), sel]            # [B, V]
    if load is not None:
        return n_emit, t0n, sel_logits, cache, pos, keys, load
    return n_emit, t0n, sel_logits, cache, pos, keys


def _pool_gather_heads(mesh, axis, pool, rows):
    """Head-aligned pool gather (the admit program's prefix read on
    the TP-sharded pool): rows [Hkv, maxp] page ids -> the mapped
    pages' bytes [Hkv, maxp*page(, d)], each rank reading its OWN
    kv-head group's plane of the [NP, G, page(, d)] pool. Comm-free by
    construction — the output is head-sharded exactly like the
    contiguous scratch it fills."""
    from jax.sharding import PartitionSpec as P
    if pool.ndim == 4:
        in_p, out_p = P(None, axis, None, None), P(axis, None, None)
    else:
        in_p, out_p = P(None, axis, None), P(axis, None)

    @functools.partial(jax.shard_map, mesh=mesh,
                       in_specs=(in_p, P(axis, None)), out_specs=out_p,
                       check_vma=False)
    def f(p_loc, rows_loc):
        g = p_loc[:, 0][rows_loc]        # [h_loc, maxp, page(, d)]
        return g.reshape((g.shape[0], -1) + g.shape[3:])

    return f(pool, rows)


def _pool_scatter_heads(mesh, axis, pool, dest, ri, u):
    """Head-aligned pool scatter (the admit program's suffix
    write-back): u [Hkv, S(, d)] — a head-sharded scratch slice — lands
    at (dest [Hkv, S] page ids, ri [S] in-page rows) of each rank's
    own plane of the [NP, G, page(, d)] pool. Trash dest ids are the
    sanctioned sink (pad-bucket tail rows)."""
    from jax.sharding import PartitionSpec as P
    if pool.ndim == 4:
        in_p, u_p = P(None, axis, None, None), P(axis, None, None)
    else:
        in_p, u_p = P(None, axis, None), P(axis, None)

    @functools.partial(jax.shard_map, mesh=mesh,
                       in_specs=(in_p, P(axis, None), P(None), u_p),
                       out_specs=in_p, check_vma=False)
    def f(p_loc, dest_loc, ri, u_loc):
        p2 = p_loc[:, 0].at[dest_loc, ri[None]].set(
            u_loc.astype(p_loc.dtype))
        return p2[:, None]

    return f(pool, dest, ri, u)


def _sp_owned_local(ids, pps, me, *, oob=None):
    """THE sp page-id partition rule, one copy (mirrored device-side
    by layers/tp_attn._attend_paged_slots_sp): global page id p lives
    on shard p // pps in contiguous blocks. Returns (owned mask,
    local ids) — for GATHERS (oob=None) non-owned ids clamp in range
    (their values are masked to zero before the psum); for SCATTERS
    (oob=<local pool size>) they redirect out of range so the write
    drops."""
    owned = (ids // pps) == me
    if oob is None:
        loc = jnp.clip(ids - me * pps, 0, pps - 1)
    else:
        loc = jnp.where(owned, ids - me * pps, oob)
    return owned, loc


def _pool_gather_sp(mesh, sp_axis, pool, rows):
    """Page gather on the SP-sharded pool (the admit program's prefix
    read — kv_cache.PagedSlotCache SP SHARDING): rows [Hkv, maxp]
    GLOBAL page ids -> the mapped pages' bytes [Hkv, maxp*page(, d)]
    REPLICATED over sp. Each chip reads the pages it owns (others
    contribute zeros) and one psum assembles the full span — traffic
    is exactly the gathered bytes, never the pool (floats sum x+0+...
    exactly, so the assembly is bitwise)."""
    from jax.sharding import PartitionSpec as P
    if pool.ndim == 4:
        in_p, out_p = P(sp_axis, None, None, None), P(None, None, None)
    else:
        in_p, out_p = P(sp_axis, None, None), P(None, None)

    @functools.partial(jax.shard_map, mesh=mesh,
                       in_specs=(in_p, P(None, None)), out_specs=out_p,
                       check_vma=False)
    def f(p_loc, rows_loc):
        pps = p_loc.shape[0]
        me = jax.lax.axis_index(sp_axis)
        owned, loc = _sp_owned_local(rows_loc, pps, me)
        g = p_loc[:, 0][loc]             # [Hkv, maxp, page(, d)]
        mask = owned.reshape(owned.shape + (1,) * (g.ndim - 2))
        g = jnp.where(mask, g, 0).astype(p_loc.dtype)
        g = jax.lax.psum(g, sp_axis)
        return g.reshape((g.shape[0], -1) + g.shape[3:])

    return f(pool, rows)


def _pool_scatter_sp(mesh, sp_axis, pool, dest, ri, u):
    """Page-row scatter on the SP-sharded pool (the admit program's
    suffix write-back): u [Hkv, S(, d)] replicated rows land at
    (dest [Hkv, S] GLOBAL page ids, ri [S] in-page rows). Each chip
    writes ONLY the pages it owns — non-owned (and deliberately
    out-of-range) destinations redirect past the local shard and the
    scatter drops them, so the write is comm-free. Global trash ids
    land in shard 0's local trash page, the sanctioned sink."""
    from jax.sharding import PartitionSpec as P
    if pool.ndim == 4:
        in_p, u_p = P(sp_axis, None, None, None), P(None, None, None)
    else:
        in_p, u_p = P(sp_axis, None, None), P(None, None)

    @functools.partial(jax.shard_map, mesh=mesh,
                       in_specs=(in_p, P(None, None), P(None), u_p),
                       out_specs=in_p, check_vma=False)
    def f(p_loc, dest_loc, ri_, u_loc):
        pps = p_loc.shape[0]
        me = jax.lax.axis_index(sp_axis)
        _, loc = _sp_owned_local(dest_loc, pps, me, oob=pps)
        p2 = p_loc[:, 0].at[loc, ri_[None]].set(
            u_loc.astype(p_loc.dtype))
        return p2[:, None]

    return f(pool, dest, ri, u)


def _cow_pages_sp(mesh, sp_axis, pool, cow_src, cow_dst, cow_r, page):
    """Boundary-page copy-on-write on the SP-sharded pool: the source
    group's valid rows [0, cow_r) copy into the destination group —
    src and dst may live on DIFFERENT shards (the allocator rotates
    fresh groups), so the copy is one owned-page gather (+psum) and
    one owned-page scatter. cow_r == 0 (page-aligned match) writes
    nothing: every destination redirects out of range."""
    NP = pool.shape[0]
    src = _pool_gather_sp(mesh, sp_axis, pool, cow_src[:, None])
    # [Hkv, page(, d)] — the boundary page's bytes, replicated
    if pool.ndim == 4:
        src = src.reshape(cow_src.shape[0], page, -1)
    dest = jnp.where(jnp.arange(page)[None, :] < cow_r,
                     cow_dst[:, None], NP)        # global OOB = no-op
    return _pool_scatter_sp(mesh, sp_axis, pool, dest,
                            jnp.arange(page), src)


def _paged_install_fn(model, pcache, rows, slot, cow_src, cow_dst,
                      cow_r):
    """Table install + boundary-page copy-on-write for a CHUNKED paged
    admission (chunk 0): exactly the pre-forward half of
    _paged_admit_fn. The CoW must happen before ANY chunk forward reads
    the slot's table — the boundary page's valid rows [0, cow_r) are
    copied from the shared original into the slot's own fresh page,
    which then receives the request's diverging writes. An int8 pool
    copies the boundary page's scale rows alongside.

    TP pool ([NP, G, page, d]): the CoW copies ALL G planes of the
    boundary page — only the owning head's plane holds real bytes, but
    copying the others' garbage is harmless (never read) and keeps the
    copy a plain plane-aligned gather/scatter GSPMD keeps local.

    SP pool (model.sp_axis set — the page-id space sharded over sp):
    src and dst groups may live on different chips, so the CoW runs as
    one owned-page gather + one owned-page scatter (_cow_pages_sp).

    `model` rides in ONLY for the mesh/sp_axis statics (its weights
    are dead arguments XLA prunes): a Mesh cannot live on the cache as
    static aux — the AOT exporter JSON-encodes pytree auxdata
    (tools/aot.py), and Mesh has no JSON form — so the three
    cache-movement programs (install/gather/restore) take the model
    like every other serving program does."""
    import dataclasses
    page = pcache.page
    Hkv = rows.shape[0]
    sp_ax = getattr(model, "sp_axis", None) if pcache.sp > 1 else None
    table = jax.lax.dynamic_update_slice(pcache.table, rows,
                                         (slot * Hkv, 0))
    rowmask = (jnp.arange(page) < cow_r)[None, None, :, None]
    rowmask2 = rowmask[..., 0]
    pk, pv, psk, psv = [], [], [], []
    for li in range(len(pcache.pages_k)):
        k, v = pcache.pages_k[li], pcache.pages_v[li]
        if sp_ax is not None:
            pk.append(_cow_pages_sp(model.mesh, sp_ax, k, cow_src,
                                    cow_dst, cow_r, page))
            pv.append(_cow_pages_sp(model.mesh, sp_ax, v, cow_src,
                                    cow_dst, cow_r, page))
        else:
            pk.append(k.at[cow_dst].set(
                jnp.where(rowmask, k[cow_src], k[cow_dst])))
            pv.append(v.at[cow_dst].set(
                jnp.where(rowmask, v[cow_src], v[cow_dst])))
        if pcache.scales_k:
            s_k, s_v = pcache.scales_k[li], pcache.scales_v[li]
            if sp_ax is not None:
                psk.append(_cow_pages_sp(model.mesh, sp_ax, s_k,
                                         cow_src, cow_dst, cow_r, page))
                psv.append(_cow_pages_sp(model.mesh, sp_ax, s_v,
                                         cow_src, cow_dst, cow_r, page))
            else:
                psk.append(s_k.at[cow_dst].set(
                    jnp.where(rowmask2, s_k[cow_src], s_k[cow_dst])))
                psv.append(s_v.at[cow_dst].set(
                    jnp.where(rowmask2, s_v[cow_src], s_v[cow_dst])))
    return dataclasses.replace(pcache, pages_k=tuple(pk),
                               pages_v=tuple(pv), scales_k=tuple(psk),
                               scales_v=tuple(psv), table=table)


def _paged_admit_fn(model, ids, scratch, pcache, rows, slot, m, n,
                    cow_src, cow_dst, cow_r, *, mode):
    """Paged admission program (one per suffix bucket): install the
    slot's table rows, copy-on-write the partially-matched boundary
    page, gather the slot's mapped pages into the contiguous scratch,
    run the suffix forward from offset m (the prefill-from-offset —
    positions [m, n) only), and scatter the computed suffix KV back
    into the slot's writable pages (pad-bucket tail rows are redirected
    to the trash page).

    INT8 pool: the scale planes ride every hop — boundary-page CoW
    copies the scale rows with the payload rows, the gather fills the
    int8 scratch's ks/vs (so the suffix forward attends the prefix
    through the contiguous int8 dequant path), and the suffix scatter
    writes the scales the forward's quantizer produced back beside the
    payload. The scratch is an int8 KVCache whenever the pool is (both
    derive from engine.kv_dtype), so the two branches can never be
    mismatched.

    TP pool ([NP, G, page, d] head-sharded): the prefix gather and the
    suffix scatter run HEAD-ALIGNED under shard_map
    (_pool_gather_heads / _pool_scatter_heads) — each rank moves its
    own kv heads' page bytes between its pool plane and its shard of
    the (head-sharded) contiguous scratch, so the whole admission
    stays ONE sharded program with zero cross-chip page traffic; the
    CoW copies all planes (garbage planes are never read).

    SP pool (model.sp_axis — the page-id space sharded over sp,
    kv_cache.PagedSlotCache SP SHARDING): the prefix gather assembles
    each chip's owned pages with one psum (_pool_gather_sp — traffic
    is the gathered span, never the pool), the suffix forward runs on
    the replicated contiguous scratch, and the suffix scatter is
    comm-free (each chip keeps only the rows of pages it owns,
    _pool_scatter_sp); the boundary CoW crosses shards as a gather +
    scatter (the allocator rotates groups, so src and dst need not be
    co-resident)."""
    import dataclasses
    page = pcache.page
    Hkv, maxp = rows.shape
    T_pool = maxp * page
    d = pcache.pages_k[0].shape[3]
    mesh, axis = model.mesh, model.axis
    sp_ax = getattr(model, "sp_axis", None) if pcache.sp > 1 else None
    quant = bool(pcache.scales_k)
    table = jax.lax.dynamic_update_slice(pcache.table, rows,
                                         (slot * Hkv, 0))
    rowmask = (jnp.arange(page) < cow_r)[None, None, :, None]
    rowmask2 = rowmask[..., 0]                  # [1, 1, page] (scales)
    S_pad = ids.shape[1]
    p = m + jnp.arange(S_pad)
    valid = p < n
    pi = jnp.minimum(p // page, maxp - 1)
    ri = p % page
    dest = jnp.where(valid[None], rows[:, pi], pcache.trash)  # [Hkv, S_pad]

    def cow(pool, mask):
        if sp_ax is not None:
            return _cow_pages_sp(mesh, sp_ax, pool, cow_src, cow_dst,
                                 cow_r, page)
        return pool.at[cow_dst].set(
            jnp.where(mask, pool[cow_src], pool[cow_dst]))

    def gather(pool):
        if sp_ax is not None:
            return _pool_gather_sp(mesh, sp_ax, pool, rows)
        return _pool_gather_heads(mesh, axis, pool, rows)

    def scatter(pool, u):
        if sp_ax is not None:
            return _pool_scatter_sp(mesh, sp_ax, pool, dest, ri, u)
        return _pool_scatter_heads(mesh, axis, pool, dest, ri, u)

    pk, pv = list(pcache.pages_k), list(pcache.pages_v)
    psk, psv = list(pcache.scales_k), list(pcache.scales_v)
    sk, sv = list(scratch.k), list(scratch.v)
    ssk, ssv = list(scratch.ks), list(scratch.vs)
    for li in range(len(pk)):
        pk[li] = cow(pk[li], rowmask)
        pv[li] = cow(pv[li], rowmask)
        kf = gather(pk[li])[None]
        vf = gather(pv[li])[None]
        sk[li] = jax.lax.dynamic_update_slice(
            sk[li], kf.astype(sk[li].dtype), (0, 0, 0, 0))
        sv[li] = jax.lax.dynamic_update_slice(
            sv[li], vf.astype(sv[li].dtype), (0, 0, 0, 0))
        if quant:
            psk[li] = cow(psk[li], rowmask2)
            psv[li] = cow(psv[li], rowmask2)
            ksf = gather(psk[li])[None]
            vsf = gather(psv[li])[None]
            ssk[li] = jax.lax.dynamic_update_slice(ssk[li], ksf,
                                                   (0, 0, 0))
            ssv[li] = jax.lax.dynamic_update_slice(ssv[li], vsf,
                                                   (0, 0, 0))
    scratch = dataclasses.replace(scratch, k=tuple(sk), v=tuple(sv),
                                  ks=tuple(ssk), vs=tuple(ssv),
                                  offset=m)
    logits, scratch = model.forward_tokens(ids, scratch, mode=mode,
                                           last_pos=(n - 1) - m)
    pk2, pv2, psk2, psv2 = [], [], [], []
    for li in range(len(pk)):
        ks = jax.lax.dynamic_slice(scratch.k[li], (0, 0, m, 0),
                                   (1, Hkv, S_pad, d))[0]
        vs = jax.lax.dynamic_slice(scratch.v[li], (0, 0, m, 0),
                                   (1, Hkv, S_pad, d))[0]
        pk2.append(scatter(pk[li], ks))
        pv2.append(scatter(pv[li], vs))
        if quant:
            kss = jax.lax.dynamic_slice(scratch.ks[li], (0, 0, m),
                                        (1, Hkv, S_pad))[0]
            vss = jax.lax.dynamic_slice(scratch.vs[li], (0, 0, m),
                                        (1, Hkv, S_pad))[0]
            psk2.append(scatter(psk[li], kss))
            psv2.append(scatter(psv[li], vss))
    pcache = dataclasses.replace(pcache, pages_k=tuple(pk2),
                                 pages_v=tuple(pv2),
                                 scales_k=tuple(psk2),
                                 scales_v=tuple(psv2), table=table)
    return logits, scratch, pcache


def _paged_set_table_fn(pcache, rows, slot):
    import dataclasses
    Hkv = rows.shape[0]
    table = jax.lax.dynamic_update_slice(pcache.table, rows,
                                         (slot * Hkv, 0))
    return dataclasses.replace(pcache, table=table)


def _gather_pages_fn(model, pcache, ids, owners):
    """Host-tier demotion gather: the listed pages of every layer's
    pool, stacked [L, N, page, d] (one program per id-bucket shape).
    An int8 pool also gathers the scale planes [L, N, page] — a
    demoted page's scales are part of its bytes.

    TP pool: `owners` [N] int32 is each page's owning HEAD-GROUP plane
    (the caller knows the kv head behind every id — page groups are
    head-ordered); the gather selects that plane, so the returned
    bytes are the TRUE payload whatever the mesh (take_along_axis
    moves bytes — no arithmetic — so the d2h/h2d round trip stays
    bitwise on sharded pools).

    SP pool: a demoted span's pages live on S different chips (the
    allocator rotates groups), so ONE span is assembled from S
    per-chip contributions — each chip supplies the pages it owns and
    a psum puts the span together (_pool_gather_sp's rule: x + 0 + ..
    is exact, the round trip stays bitwise)."""
    if pcache.sp > 1:
        # the SAME owned-gather + psum program the admit path uses
        # (_pool_gather_sp — a flat id list is a [N, 1] rows block)
        def pick(p):
            return _pool_gather_sp(model.mesh, model.sp_axis, p,
                                   ids[:, None])
    else:
        def pick(p):
            g = p[ids]                        # [N, G, page(, d)]
            idx = owners.reshape((-1, 1) + (1,) * (g.ndim - 2))
            return jnp.take_along_axis(g, idx, axis=1)[:, 0]

    k = jnp.stack([pick(p) for p in pcache.pages_k])
    v = jnp.stack([pick(p) for p in pcache.pages_v])
    if pcache.scales_k:
        sk = jnp.stack([pick(s) for s in pcache.scales_k])
        sv = jnp.stack([pick(s) for s in pcache.scales_v])
        return k, v, sk, sv
    return k, v


def _restore_pages_fn(model, pcache, ids, hk, hv, hsk=None, hsv=None):
    """Host-tier promotion scatter: write hk/hv [L, N, page, d] into
    the listed pages of every layer's pool (donated). Padded tail ids
    all point at the trash page — duplicate scatter targets there are
    fine, trash content is never read. Int8 pools restore the scale
    planes from hsk/hsv [L, N, page] in the same program.

    TP pool: the payload broadcasts into ALL G head-group planes of
    each restored page — the owning plane gets the true bytes and the
    others hold copies nothing ever reads (freshly allocated pages are
    garbage until written anyway), which keeps the scatter plane-
    aligned and comm-free instead of needing per-rank owner masks.

    SP pool: each chip keeps only the pages it owns (non-owned ids
    redirect out of local range and drop) — a restored span scatters
    back onto its S chips comm-free, the inverse of the gather."""
    import dataclasses
    sp_ax = model.sp_axis if pcache.sp > 1 else None

    if sp_ax is not None:
        # the SAME owned-scatter program the admit path uses
        # (_pool_scatter_sp): a whole-page install is the row scatter
        # with every in-page row addressed
        def put(p, h):
            page = p.shape[2]
            dest = jnp.broadcast_to(ids[:, None],
                                    (ids.shape[0], page))
            return _pool_scatter_sp(model.mesh, sp_ax, p, dest,
                                    jnp.arange(page), h)
    else:
        def put(p, h):
            u = jnp.broadcast_to(h[:, None],
                                 (h.shape[0], p.shape[1]) + h.shape[1:])
            return p.at[ids].set(u.astype(p.dtype))

    pk = tuple(put(p, hk[li]) for li, p in enumerate(pcache.pages_k))
    pv = tuple(put(p, hv[li]) for li, p in enumerate(pcache.pages_v))
    out = dataclasses.replace(pcache, pages_k=pk, pages_v=pv)
    if pcache.scales_k:
        psk = tuple(put(s, hsk[li])
                    for li, s in enumerate(pcache.scales_k))
        psv = tuple(put(s, hsv[li])
                    for li, s in enumerate(pcache.scales_v))
        out = dataclasses.replace(out, scales_k=psk, scales_v=psv)
    return out


def _paged_slot_scan_decode_fn(backend, model, logits0, pcache, pos,
                               active, mask=None, *, gen_len: int):
    """Greedy slot-masked decode chunk over the PAGED pool: same shape
    as _slot_scan_decode_fn with the per-row KV scatter and attention
    resolved through the page table (and the same [B, V] grammar-mask
    contract)."""
    act = active.astype(jnp.int32)
    cap = pcache.capacity
    moe = _is_moe(model)

    def step(carry, _):
        if moe:
            logits, pc, pos, load = carry
        else:
            logits, pc, pos = carry
        sel = logits if mask is None else \
            jnp.where(mask, logits, -jnp.inf)
        tok = jnp.argmax(sel, axis=-1)
        tok = jnp.where(active, tok, 0)
        if moe:
            logits, pc, st = model.forward_tokens_slots_paged(
                tok[:, None], pc, pos, mode=backend,
                return_moe_stats=True)
        else:
            logits, pc = model.forward_tokens_slots_paged(
                tok[:, None], pc, pos, mode=backend)
        pos = jnp.minimum(pos + act, cap - 1)
        if moe:
            return (logits, pc, pos, load + st), tok
        return (logits, pc, pos), tok

    init = ((logits0, pcache, pos, model._zero_load()) if moe
            else (logits0, pcache, pos))
    out, toks = jax.lax.scan(step, init, None, length=gen_len)
    if moe:
        logits, pcache, pos, load = out
        return toks.T, logits, pcache, pos, load      # [B, gen_len]
    logits, pcache, pos = out
    return toks.T, logits, pcache, pos                # [B, gen_len]


def _sampled_paged_slot_scan_fn(backend, sampling, params, model,
                                logits0, pcache, pos, active, keys,
                                mask=None, *, gen_len: int):
    """Sampled paged slot chunk: per-slot PRNG chains exactly as in
    _sampled_slot_scan_decode_fn — the sampler never sees the cache
    layout, so paged streams equal contiguous streams token for token
    whenever the logits do."""
    from triton_dist_tpu.models.utils import sample_top_k, sample_top_p

    temp = max(params["temperature"], 0.0)
    act = active.astype(jnp.int32)
    cap = pcache.capacity

    def sample_one(k, logits):
        if temp == 0.0:
            return jnp.argmax(logits, axis=-1)
        if sampling == "top_k":
            return sample_top_k(k, logits, k=params["k"],
                                temperature=temp)
        return sample_top_p(k, logits, p=params["p"], temperature=temp)

    moe = _is_moe(model)

    def step(carry, _):
        if moe:
            logits, pc, pos, keys, load = carry
        else:
            logits, pc, pos, keys = carry
        split = jax.vmap(functools.partial(jax.random.split, num=2))
        ks = split(keys)
        keys, subs = ks[:, 0], ks[:, 1]
        sel = logits if mask is None else \
            jnp.where(mask, logits, -jnp.inf)
        tok = jax.vmap(sample_one)(subs, sel)
        tok = jnp.where(active, tok, 0)
        if moe:
            logits, pc, st = model.forward_tokens_slots_paged(
                tok[:, None], pc, pos, mode=backend,
                return_moe_stats=True)
        else:
            logits, pc = model.forward_tokens_slots_paged(
                tok[:, None], pc, pos, mode=backend)
        pos = jnp.minimum(pos + act, cap - 1)
        if moe:
            return (logits, pc, pos, keys, load + st), tok
        return (logits, pc, pos, keys), tok

    init = ((logits0, pcache, pos, keys, model._zero_load()) if moe
            else (logits0, pcache, pos, keys))
    out, toks = jax.lax.scan(step, init, None, length=gen_len)
    if moe:
        logits, pcache, pos, keys, load = out
        return toks.T, logits, pcache, pos, keys, load
    logits, pcache, pos, keys = out
    return toks.T, logits, pcache, pos, keys          # [B, gen_len]


def _scan_decode_fn(backend, model, logits0, cache, *, gen_len: int):
    # NOTE: the logits carry is deliberate — a tok-only carry measured
    # ~3% SLOWER on-chip (XLA schedules the argmax off the critical
    # path this way)
    def step(carry, _):
        logits, cache = carry
        tok = jnp.argmax(logits, axis=-1)           # greedy [B]
        logits, cache = model.forward_tokens(tok[:, None], cache,
                                             mode=backend)
        return (logits, cache), tok

    (logits, cache), toks = jax.lax.scan(
        step, (logits0, cache), None, length=gen_len)
    return toks.T, logits, cache                     # [B, gen_len]


def _sampled_scan_decode_fn(backend, sampling, params, model, logits0,
                            cache, key, *, gen_len: int):
    """Sampled decode scan: same structure as _scan_decode_fn with a
    PRNG key in the carry, split once per step (reference: the sampling
    loop of the chat server, model_server.py + models/utils.py).
    temperature=0 degenerates to argmax so servers can flip modes
    without recompiling a separate greedy engine. The evolved key is
    RETURNED so chunked callers (serving.decode_stream) continue the
    exact chain — a resumed scan samples the same tokens as one long
    scan at the same seed."""
    from triton_dist_tpu.models.utils import sample_top_k, sample_top_p

    temp = max(params["temperature"], 0.0)

    def sample(k, logits):
        if temp == 0.0:
            return jnp.argmax(logits, axis=-1)
        if sampling == "top_k":
            return sample_top_k(k, logits, k=params["k"],
                                temperature=temp)
        return sample_top_p(k, logits, p=params["p"], temperature=temp)

    def step(carry, _):
        logits, cache, key = carry
        key, sub = jax.random.split(key)
        tok = sample(sub, logits)                   # [B]
        logits, cache = model.forward_tokens(tok[:, None], cache,
                                             mode=backend)
        return (logits, cache, key), tok

    (logits, cache, key), toks = jax.lax.scan(
        step, (logits0, cache, key), None, length=gen_len)
    return toks.T, logits, cache, key                # [B, gen_len]


def _pick_mega_bn(cfg, n: int = 1) -> int:
    """Largest 128-multiple weight tile dividing the LOCAL projection
    widths the megakernel asserts on (D, ffn/n, Hq*hd/n); the qkv
    matmul down-tiles its own width independently (decode_layer.py
    _pick_bn). A swept "mega_decode" tune-cache entry (tools/sweep)
    overrides the ladder when it divides the widths — block_n tiles
    output columns only, so the tick stays bitwise-identical."""
    widths = (cfg.hidden_size, cfg.intermediate_size // n,
              cfg.num_heads * cfg.head_dim // n)
    from triton_dist_tpu.tools.sweep import resolve_config
    tuned = resolve_config("mega_decode", widths).get("block_n")
    if tuned and tuned % 128 == 0 and all(w % tuned == 0
                                          for w in widths):
        return int(tuned)
    for bn in (512, 384, 256, 128):
        if all(w % bn == 0 for w in widths):
            return bn
    raise ValueError(
        f"no 128-multiple tile divides the projection widths {widths}; "
        "backend='mega' needs 128-aligned layer geometry")


def _mega_scan_decode_fn(model, logits0, cache, *, gen_len: int):
    """Megakernel decode loop: one Pallas kernel per layer per step
    (reference: the megakernel engine backend replaying the built task
    graph, mega_triton_kernel/models/model_builder.py:86). Weights are
    repacked into the megakernel's layout ONCE (outside the scan); the
    KV cache converts to the head-major [Hkv, B, T, hd] layout the
    kernel's per-head DMA walk wants."""
    from triton_dist_tpu.layers.common import rms_norm
    from triton_dist_tpu.mega import MegaDecodeLayer

    cfg = model.config
    hd = cfg.head_dim
    T = cache.k[0].shape[2]
    # TP (n > 1): the layer runs on LOCAL head/ffn shards with the two
    # cross-chip reductions as in-kernel AR tasks (decode_layer.py
    # module docstring — the reference's flagship TP megakernel). The
    # model's packed weights are already per-rank-block layouts
    # ([q_r|k_r|v_r], [gate_r|up_r]), so a contiguous column split IS
    # the right shard.
    ax_mega = model.mesh.axis_names[0]
    n_mega = model.mesh.shape[ax_mega]
    mega = MegaDecodeLayer(
        d_model=cfg.hidden_size, n_heads=cfg.num_heads // n_mega,
        n_kv_heads=cfg.num_kv_heads // n_mega, head_dim=hd,
        ffn=cfg.intermediate_size // n_mega, T=T, eps=cfg.rms_norm_eps,
        block_n=_pick_mega_bn(cfg, n_mega),
        qk_norm=model.layers[0].attn.q_norm is not None,
        tp=n_mega, axis=ax_mega)
    ones = jnp.ones((1, hd), jnp.float32)
    bf = jnp.bfloat16
    weights = []
    for layer in model.layers:
        attn, mlp = layer.attn, layer.mlp
        weights.append(dict(
            w_ln1=layer.ln_attn[None].astype(jnp.float32),
            w_qkv=attn.w_qkv.astype(bf),
            q_norm=(ones if attn.q_norm is None
                    else attn.q_norm[None].astype(jnp.float32)),
            k_norm=(ones if attn.k_norm is None
                    else attn.k_norm[None].astype(jnp.float32)),
            w_o=attn.w_o.astype(bf),
            w_ln2=layer.ln_mlp[None].astype(jnp.float32),
            w_gu=mlp.w_gate_up.astype(bf),
            w_d=mlp.w_down.astype(bf),
        ))
    from jax.sharding import AxisType, NamedSharding, PartitionSpec as _P

    def _replicate(a):
        # the cache arrives head-sharded over the (size-1) tp axis; the
        # megakernel outputs are replicated — pin the scan carry to one
        # consistent (replicated) type under explicit-sharding meshes
        # (axis_types is None on jax 0.4.x meshes — treat as non-explicit)
        if any(t == AxisType.Explicit
               for t in (model.mesh.axis_types or ())):
            return jax.sharding.reshard(a, NamedSharding(model.mesh, _P()))
        return a

    ks = tuple(jnp.transpose(k, (1, 0, 2, 3)) for k in cache.k)
    vs = tuple(jnp.transpose(v, (1, 0, 2, 3)) for v in cache.v)
    if n_mega == 1:
        ks = tuple(_replicate(k) for k in ks)
        vs = tuple(_replicate(v) for v in vs)

    # pallas_call needs Manual mesh axes: run each layer's megakernel
    # under a shard_map, with every array an ARGUMENT (closures over
    # sharded arrays are rejected in explicit-sharding mode). tp=1:
    # fully replicated; tp>1: head/ffn-sharded weights + head-sharded
    # cache, replicated activations (the TP mega layout).
    from jax.sharding import PartitionSpec as P
    if n_mega > 1:
        ax = ax_mega
        rep2 = P(None, None)
        cspec = P(ax, None, None, None)
        wspec = {"w_ln1": rep2, "w_qkv": P(None, ax), "q_norm": rep2,
                 "k_norm": rep2, "w_o": P(ax, None), "w_ln2": rep2,
                 "w_gu": P(None, ax), "w_d": P(ax, None),
                 "cos_row": rep2, "sin_row": rep2}
        in_specs = (rep2, P(), wspec, cspec, cspec)
        out_specs = (rep2, cspec, cspec)
    else:
        in_specs = (P(), P(), P(), P(), P())
        out_specs = (P(), P(), P())
    mega_call = jax.shard_map(
        lambda x, pos, wd, ck, cv: mega(x, pos, wd, ck, cv),
        mesh=model.mesh, in_specs=in_specs, out_specs=out_specs,
        check_vma=False)

    def step(carry, _):
        tok, pos, ks, vs = carry
        x = model.embed[tok].astype(jnp.float32)    # [B, D]
        crow = model.cos[pos][None]
        srow = model.sin[pos][None]
        new_ks, new_vs = [], []
        for li, w in enumerate(weights):
            wd = dict(w, cos_row=crow, sin_row=srow)
            x, ck, cv = mega_call(x, pos, wd, ks[li], vs[li])
            new_ks.append(ck)
            new_vs.append(cv)
        xf = rms_norm(x, model.final_norm.astype(jnp.float32),
                      cfg.rms_norm_eps)
        logits = jnp.dot(xf.astype(model.lm_head.dtype), model.lm_head,
                         preferred_element_type=jnp.float32)
        return (jnp.argmax(logits, axis=-1), pos + 1,
                tuple(new_ks), tuple(new_vs)), tok

    (tok, _, ks, vs), toks = jax.lax.scan(
        step, (jnp.argmax(logits0, axis=-1), cache.offset, ks, vs),
        None, length=gen_len)
    return toks.T, tok, None                         # [B, gen_len]


def _paged_slot_mega_scan_fn(model, logits0, pcache, pos, active, *,
                             gen_len: int):
    """FUSED paged greedy decode tick (ISSUE 12 / ROADMAP item 5): the
    paged_slot_chunk contract — same carry (logits, pcache, pos), same
    masking, same token stream — with each scan step running ONE
    MegaPagedDecodeLayer kernel per layer (mega/decode_layer.py: the
    paged table walk, per-slot kv_lens, the trash-page write sink and
    the int8 scale-plane dequant all inside the fused layer) instead
    of the per-op dispatch chain. Weights repack into the megakernel
    layout ONCE outside the scan; per-slot rope rows gather at each
    slot's own position. Greedy only (the carry is the argmax chain);
    single chip (make_paged_slot_cache refuses TP meshes up front)."""
    from jax.sharding import PartitionSpec as P
    from triton_dist_tpu.layers.common import rms_norm
    from triton_dist_tpu.mega import MegaPagedDecodeLayer

    cfg = model.config
    maxp = pcache.table.shape[1]
    quant = pcache.quantized
    layer = MegaPagedDecodeLayer(
        d_model=cfg.hidden_size, n_heads=cfg.num_heads,
        n_kv_heads=cfg.num_kv_heads, head_dim=cfg.head_dim,
        ffn=cfg.intermediate_size, page=pcache.page, maxp=maxp,
        eps=cfg.rms_norm_eps, block_n=_pick_mega_bn(cfg),
        qk_norm=model.layers[0].attn.q_norm is not None)
    ones = jnp.ones((1, cfg.head_dim), jnp.float32)
    bf = jnp.bfloat16
    weights = []
    for ly in model.layers:
        attn, mlp = ly.attn, ly.mlp
        weights.append(dict(
            w_ln1=ly.ln_attn[None].astype(jnp.float32),
            w_qkv=attn.w_qkv.astype(bf),
            q_norm=(ones if attn.q_norm is None
                    else attn.q_norm[None].astype(jnp.float32)),
            k_norm=(ones if attn.k_norm is None
                    else attn.k_norm[None].astype(jnp.float32)),
            w_o=attn.w_o.astype(bf),
            w_ln2=ly.ln_mlp[None].astype(jnp.float32),
            w_gu=mlp.w_gate_up.astype(bf),
            w_d=mlp.w_down.astype(bf)))
    act = active.astype(jnp.int32)
    cap = pcache.capacity
    # pallas_call needs Manual mesh axes (the contiguous mega scan's
    # rule): each layer call runs under shard_map, pool operands on
    # the head-group sharding they were created with (size-1 plane at
    # tp=1 — TP meshes are refused at pool construction)
    ax = model.axis
    pool4 = P(None, ax, None, None)
    sc3 = P(None, ax, None)
    rep2 = P(None, None)
    wspec = {k: rep2 for k in ("w_ln1", "w_qkv", "q_norm", "k_norm",
                               "w_o", "w_ln2", "w_gu", "w_d",
                               "cos_row", "sin_row")}
    in_specs = (rep2, P(None), wspec, pool4, pool4, rep2) + (
        (sc3, sc3) if quant else ())
    out_specs = (rep2, pool4, pool4) + ((sc3, sc3) if quant else ())
    mega_call = jax.shard_map(
        lambda x, p, wd, *kv: layer(x, p, wd, *kv),
        mesh=model.mesh, in_specs=in_specs, out_specs=out_specs,
        check_vma=False)

    def step(carry, _):
        logits, pc, pos_ = carry
        tok = jnp.where(active, jnp.argmax(logits, axis=-1), 0)
        x = model.embed[tok].astype(jnp.float32)       # [B, D]
        crow = model.cos[pos_]                         # [B, hd//2]
        srow = model.sin[pos_]
        for li, w in enumerate(weights):
            wd = dict(w, cos_row=crow, sin_row=srow)
            extra = ((pc.scales_k[li], pc.scales_v[li]) if quant
                     else ())
            outs = mega_call(x, pos_, wd, pc.pages_k[li],
                             pc.pages_v[li], pc.table, *extra)
            x = outs[0]
            pc = pc.set_layer(li, *outs[1:])
        xf = rms_norm(x, model.final_norm.astype(jnp.float32),
                      cfg.rms_norm_eps)
        logits = jnp.dot(xf.astype(model.lm_head.dtype), model.lm_head,
                         preferred_element_type=jnp.float32)
        pos_ = jnp.minimum(pos_ + act, cap - 1)
        return (logits, pc, pos_), tok

    (logits, pcache, pos), toks = jax.lax.scan(
        step, (logits0, pcache, pos), None, length=gen_len)
    return toks.T, logits, pcache, pos               # [B, gen_len]
