"""Host-RAM KV tier: the capacity layer under the radix prefix cache.

At production scale the shared-prefix working set exceeds HBM by orders
of magnitude, so the radix tree's LRU eviction (models/prefix_cache.py)
used to throw away KV the next request would recompute from scratch.
This module is the second tier of the SGLang/HiCache hierarchical-cache
design (and the pattern Mooncake, arXiv:2407.00079, runs in production
KV-centric serving; CachedAttention, arXiv:2403.19708, is the same idea
for multi-turn sessions): eviction DEMOTES an unreferenced page-group
span to pinned host memory (one d2h gather of the group's pages across
every layer's pool) instead of dropping it, and a later prefix match on
a host-resident path PROMOTES it back — fresh device pages are
allocated and filled by one h2d install program before the uncached
suffix prefill runs. Only the host tier's own LRU (bounded by
``host_pool_pages``) truly drops KV.

`HostKVPool` is the host half: a bounded store of demoted page-group
payloads (per-layer K/V extracted from the device pools, kept in the
pool dtype so the d2h -> h2d round trip is BITWISE exact) with
second-level LRU ordering and page-denominated accounting. On a
SEQUENCE-PARALLEL pool (ISSUE 14 — kv_cache.PagedSlotCache SP
SHARDING) a demoted span is really S per-chip page sets: the d2h
gather assembles each page from its owning sp shard (one psum of
owned-or-zero contributions — exact) and the h2d restore scatters
owned pages back comm-free (engine._gather_pages_fn /
_restore_pages_fn sp branches), so the tier stays bitwise and
layout-blind whatever the mesh. It is
policy-free about tree structure — the residency state machine lives in
`models/prefix_cache.py` (`_Node.host`, demote-on-evict,
promote-on-match), which owns the handle -> node map and drives drops
through `victim()`.

Zero-leak contract across both tiers (tests/test_kv_tier.py,
tests/test_resilience.py): the device invariant
``available + outstanding == num_pages`` is untouched (demotion
releases device refs like a drop did), and the host invariant
``pages_resident == sum(entry pages) <= capacity`` holds after any
sequence of demotions, promotions, drops, and injected faults
(runtime/chaos.py::FaultInjector.host_demotion).

Telemetry (runtime/telemetry.py): the counters below stay plain ints
because ``pages_resident``/``room`` gate the demote/promote logic and
the invariant checks compare them directly; ``PrefixCache.stats()``
publishes `stats()`'s key set into the owning scheduler's metrics
registry as gauges on every snapshot (so `/metrics` and the stats()
registry cut carry ``host_pages_resident`` / ``host_puts`` /
``host_pops`` / ``host_drops_pool`` live), and demote/promote/drop
fire timeline instants on the tree's telemetry hook when tracing.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Iterable, Optional


class _HostEntry:
    """One demoted span: an opaque payload (the engine's extracted
    per-layer K/V arrays) plus the page accounting the pool needs."""

    __slots__ = ("payload", "n_pages", "n_groups")

    def __init__(self, payload, n_pages: int, n_groups: int):
        self.payload = payload
        self.n_pages = n_pages
        self.n_groups = n_groups


class HostKVPool:
    """Bounded host-RAM store of demoted page-group payloads with LRU
    ordering (the capacity tier's own second-level LRU: a true drop
    happens only here). Sizes are in DEVICE PAGES so ``host_pool_pages``
    composes directly with the device pool's ``num_pages`` — the
    effective cache is ``num_pages + host_pool_pages`` pages.

    The pool never decides WHAT to drop into the void: the radix tree
    asks ``victim()`` for the least-recently-used unpinned handle and
    removes the corresponding subtree itself (a dropped interior span
    orphans its host-resident descendants, which must go with it)."""

    def __init__(self, capacity_pages: int):
        if capacity_pages < 1:
            raise ValueError(
                f"host_pool_pages must be >= 1, got {capacity_pages}")
        self.capacity = int(capacity_pages)
        self._entries: "OrderedDict[int, _HostEntry]" = OrderedDict()
        self._next = 0
        self.pages_resident = 0
        # lifetime counters (PrefixCache.stats() surfaces these)
        self.puts = 0
        self.pops = 0
        self.drops = 0

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def room(self) -> int:
        return self.capacity - self.pages_resident

    def _check(self) -> None:
        """Host-tier conservation bound, O(1) so mass demotion stays
        linear; the exhaustive form (resident pages == sum of live
        entries) is recomputed by the chaos/no-leak tests."""
        assert 0 <= self.pages_resident <= self.capacity, \
            f"host pool over capacity: {self.pages_resident}" \
            f"/{self.capacity}"

    def victim(self, pinned: Iterable[int] = ()) -> Optional[int]:
        """Least-recently-used handle not in `pinned` (the promotion
        path's in-flight handles), or None when nothing is droppable."""
        pinned = set(pinned)
        for h in self._entries:          # OrderedDict: LRU first
            if h not in pinned:
                return h
        return None

    def put(self, payload, *, n_pages: int, n_groups: int) -> int:
        """Store one demoted span; the caller has already made room
        (victim()/drop()). Returns the handle the tree keys its
        residency bit on."""
        if n_pages > self.room:
            raise ValueError(
                f"host pool exhausted: want {n_pages} pages, have "
                f"{self.room} of {self.capacity}")
        h = self._next
        self._next += 1
        self._entries[h] = _HostEntry(payload, int(n_pages),
                                      int(n_groups))
        self.pages_resident += int(n_pages)
        self.puts += 1
        self._check()
        return h

    def get(self, handle: int) -> _HostEntry:
        """Read an entry and touch its LRU position (a matched span is
        hot — keep it resident if promotion fails this time)."""
        e = self._entries[handle]
        self._entries.move_to_end(handle)
        return e

    def pop(self, handle: int) -> _HostEntry:
        """Remove an entry on successful PROMOTION (its bytes now live
        in freshly allocated device pages)."""
        e = self._entries.pop(handle)
        self.pages_resident -= e.n_pages
        self.pops += 1
        self._check()
        return e

    def drop(self, handle: int) -> None:
        """TRUE DROP: the only place in the two-tier cache where KV is
        actually forgotten (the tree removes the node; a later request
        recomputes)."""
        e = self._entries.pop(handle)
        self.pages_resident -= e.n_pages
        self.drops += 1
        self._check()

    @classmethod
    def empty_stats(cls) -> dict:
        """The gauge key set at zero — what PrefixCache.stats() reports
        with the tier off, kept here so tier-off and tier-on stats can
        never drift apart."""
        return {
            "host_pool_pages": 0,
            "host_pages_resident": 0,
            "host_entries": 0,
            "host_puts": 0,
            "host_pops": 0,
            "host_drops_pool": 0,
        }

    def stats(self) -> dict:
        out = self.empty_stats()
        out.update({
            "host_pool_pages": self.capacity,
            "host_pages_resident": self.pages_resident,
            "host_entries": len(self._entries),
            "host_puts": self.puts,
            "host_pops": self.pops,
            "host_drops_pool": self.drops,
        })
        return out
