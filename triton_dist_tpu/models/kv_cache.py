"""KV cache (reference: `python/triton_dist/models/kv_cache.py`
`KV_Cache:29` — contiguous per-layer K/V buffers + a shared offset).

TPU re-design: per-layer pairs of arrays [B, Hkv, T, hd] sharded on the
KV-head axis over TP (each rank caches only its heads — same memory
split as the reference's per-rank cache), updated functionally
(`jax.lax.dynamic_update_slice`) so the decode step can donate the cache
and XLA updates it in place.

Two deliberate layout choices:
- per-layer tuple (NOT one stacked [L, ...] array): a stacked array
  would make every layer update an update-slice on the whole multi-GB
  buffer and every kernel read a materialized slice copy; separate
  buffers update in place under donation and feed Pallas directly.
- head-major [Hkv, T, hd]: each head's KV is contiguous, which is the
  read order of the flash-decode kernel (kernels/flash_attn.py) — no
  transpose on the hot path.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class KVCache:
    k: Tuple[jax.Array, ...]   # L x [B, Hkv, T, hd]
    v: Tuple[jax.Array, ...]
    offset: jax.Array  # scalar int32: number of valid positions

    @staticmethod
    def create(num_layers: int, batch: int, max_seq: int, n_kv_heads: int,
               head_dim: int, *, mesh: Mesh, axis: str = "tp",
               dtype=jnp.bfloat16) -> "KVCache":
        shape = (batch, n_kv_heads, max_seq, head_dim)
        sharding = NamedSharding(mesh, P(None, axis, None, None))
        k = tuple(jax.device_put(jnp.zeros(shape, dtype), sharding)
                  for _ in range(num_layers))
        v = tuple(jax.device_put(jnp.zeros(shape, dtype), sharding)
                  for _ in range(num_layers))
        return KVCache(k=k, v=v, offset=jnp.int32(0))

    def layer(self, idx: int):
        """Per-layer buffers passed into TP_Attn.fwd_cached."""
        return self.k[idx], self.v[idx]

    def set_layer(self, idx: int, ck, cv) -> "KVCache":
        return dataclasses.replace(
            self,
            k=self.k[:idx] + (ck,) + self.k[idx + 1:],
            v=self.v[:idx] + (cv,) + self.v[idx + 1:])

    def advance(self, n) -> "KVCache":
        return dataclasses.replace(self, offset=self.offset + n)
