"""KV cache (reference: `python/triton_dist/models/kv_cache.py`
`KV_Cache:29` — contiguous per-layer K/V buffers + a shared offset).

TPU re-design: per-layer pairs of arrays [B, Hkv, T, hd] sharded on the
KV-head axis over TP (each rank caches only its heads — same memory
split as the reference's per-rank cache), updated functionally
(`jax.lax.dynamic_update_slice`) so the decode step can donate the cache
and XLA updates it in place.

Two deliberate layout choices:
- per-layer tuple (NOT one stacked [L, ...] array): a stacked array
  would make every layer update an update-slice on the whole multi-GB
  buffer and every kernel read a materialized slice copy; separate
  buffers update in place under donation and feed Pallas directly.
- head-major [Hkv, T, hd]: each head's KV is contiguous, which is the
  read order of the flash-decode kernel (kernels/flash_attn.py) — no
  transpose on the hot path.

Slot mode (continuous batching, models/scheduler.py): each batch row
is an independent decode SLOT holding a different request. The shared
`offset` is then meaningless and stays untouched — per-slot positions
live in the scheduler's carry, rows are written by per-row scatter
(TP_Attn._attend_cached_slots) and admission replaces a whole row
(engine._write_slot_fn), so one row's request can never read another's
KV (per-row attention lengths mask the rest).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class KVCache:
    k: Tuple[jax.Array, ...]   # L x [B, Hkv, T, hd]
    v: Tuple[jax.Array, ...]
    offset: jax.Array  # scalar int32: number of valid positions
    # int8 cache only: per-position dequant scales, L x [B, Hkv, T] f32
    # (empty tuples for the bf16 cache — a pytree-stable "absent")
    ks: Tuple[jax.Array, ...] = ()
    vs: Tuple[jax.Array, ...] = ()

    @staticmethod
    def create(num_layers: int, batch: int, max_seq: int, n_kv_heads: int,
               head_dim: int, *, mesh: Mesh, axis: str = "tp",
               dtype=jnp.bfloat16) -> "KVCache":
        """dtype=jnp.int8 stores K/V quantized with per-position scales
        — half the HBM read of the decode step's dominant traffic; the
        flash kernel dequants exactly via logit/P scaling
        (kernels/flash_attn.py)."""
        shape = (batch, n_kv_heads, max_seq, head_dim)
        sharding = NamedSharding(mesh, P(None, axis, None, None))
        k = tuple(jax.device_put(jnp.zeros(shape, dtype), sharding)
                  for _ in range(num_layers))
        v = tuple(jax.device_put(jnp.zeros(shape, dtype), sharding)
                  for _ in range(num_layers))
        ks = vs = ()
        if jnp.dtype(dtype) == jnp.int8:
            s_shd = NamedSharding(mesh, P(None, axis, None))
            mk = lambda: tuple(
                jax.device_put(jnp.zeros(shape[:3], jnp.float32), s_shd)
                for _ in range(num_layers))
            ks, vs = mk(), mk()
        return KVCache(k=k, v=v, offset=jnp.int32(0), ks=ks, vs=vs)

    @property
    def quantized(self) -> bool:
        return bool(self.ks)

    def layer(self, idx: int):
        """Per-layer cache tuple passed into TP_Attn.fwd_cached:
        (k, v) or (k, v, ks, vs) when int8."""
        if self.quantized:
            return (self.k[idx], self.v[idx], self.ks[idx], self.vs[idx])
        return self.k[idx], self.v[idx]

    def set_layer(self, idx: int, kv) -> "KVCache":
        def put(t, x):
            return t[:idx] + (x,) + t[idx + 1:]

        out = dataclasses.replace(
            self, k=put(self.k, kv[0]), v=put(self.v, kv[1]))
        if len(kv) == 4:
            out = dataclasses.replace(out, ks=put(self.ks, kv[2]),
                                      vs=put(self.vs, kv[3]))
        return out

    def advance(self, n) -> "KVCache":
        return dataclasses.replace(self, offset=self.offset + n)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class PagedSlotCache:
    """Multi-layer paged KV cache for the continuous-batching slot path
    (models/prefix_cache.py policy over kernels/paged_kv.py mechanics).

    Per-layer physical pools pages_k/v [NP, G, page, d] (one page =
    `page` contiguous positions of ONE (slot, kv-head) stream; G is
    the TP head-group axis — see TP SHARDING below) behind ONE
    shared page table [B*Hkv, max_pages]: a physical page id means the
    same row in EVERY layer's pool, so the host allocator hands out one
    [Hkv] page-id group per logical tile and it covers all layers.
    That is what makes cross-request prefix sharing cheap: mapping a
    cached prefix into a slot is a table edit, not a KV copy.

    Page id `trash` (row 0 by convention, reserved by the allocator) is
    the write sink for retired/dead slots: the slot scan keeps stepping
    masked-out rows, and their KV scatter must land somewhere that no
    live slot ever maps — retiring a slot points its whole table row at
    trash so its surplus writes can never corrupt a reused page. The
    same property is what makes PREEMPTION (models/scheduler.py) safe:
    a preempted slot's pages live on inside the radix tree while its
    table row points at trash, so the still-stepping masked row cannot
    scribble on KV a future re-admission will map back.

    INT8 POOL (dtype=jnp.int8 — the KV-quantization design of KIVI,
    arXiv:2402.02750, specialized to per-position symmetric scales;
    PAPERS.md): the page payload stores int8 and per-layer scale
    planes scales_k/scales_v [NP, G, page] f32 ride ALONGSIDE it — a
    physical page id addresses its payload AND its scales in every
    layer, so the host allocator, the radix prefix tree, the
    copy-on-write boundary copy and the host-tier d2h/h2d extract/
    restore (models/kv_tier.py) are all layout-oblivious: whatever
    moves a page moves its scales with the same id. Quantization is
    kernels/quant.quantize_kv_int8 — the exact quantizer of the
    contiguous int8 cache — and kernels/paged_kv.flash_decode_paged
    dequants in-kernel by logit/P scaling, so paged-int8 streams are
    bitwise identical to the contiguous-int8 reference while the
    decode step's dominant HBM read halves and the same pool holds
    ~2x the resident pages.

    TP SHARDING (the multi-chip serving layout — ROADMAP open item 1;
    the head-axis split of the contiguous KVCache carried over to the
    paged pool): page payloads carry a HEAD-GROUP axis G = the TP
    mesh size, [NP, G, page, d] sharded NamedSharding(mesh, P(None,
    axis, None, None)) — chip g's plane holds the page bytes of ITS
    Hkv/G kv heads and nothing else ever reads or writes it. The
    page-id space is NOT split: the host allocator, refcounts, radix
    tree, CoW and LRU policy (models/prefix_cache.py) hand out the
    same ids whatever the mesh, and the replicated page table
    resolves a (slot, head) stream to a page id exactly as on one
    chip — the stream's kv head decides the PLANE, and that decision
    is static per stream, so the slot attends (layers/tp_attn.py
    _attend_paged_slots*) run under jax.shard_map with each chip
    walking only its local shard: 1/G of the decode step's KV read
    and attention FLOPs per chip, with the QKV/O projections riding
    the TP comm backends (kernels/gemm_allreduce.py et al.). Planes
    of a page outside its owning head's group hold garbage by design
    (never read — the same argument that lets retired pages keep
    stale bytes); the host-tier d2h gather selects the owning plane
    per page (Engine.extract_pages_host heads=...).

    MEGAKERNEL TICK (mega/decode_layer.py MegaPagedDecodeLayer —
    ISSUE 12): the fused decode tick consumes this exact layout —
    [NP, 1, page, d] single-plane pools + the shared trash-padded
    table as a scalar-prefetch operand, scale planes riding the same
    page id — so everything host-side (allocator, radix tree, CoW,
    preemption, host tier) is oblivious to WHICH program walks the
    pool; the engine swaps the tick per poll
    (engine.paged_slot_chunk). The fused tick is single-plane by
    contract: TP pools (G > 1) stay on the per-op shard_map path.

    SP SHARDING (sequence-parallel long-context serving — ROADMAP
    long-context item; the promotion of kernels/sp_flash_decode.py
    into the serving path, Ring Attention arXiv:2310.01889 /
    Infinite-LLM arXiv:2401.02669 being the deployment story): with
    `sp` > 1 the PAGE-ID SPACE is partitioned — the pools' leading
    [NP] axis shards over the sp mesh axis in contiguous blocks, chip
    s holding physical pages [s*NP/S, (s+1)*NP/S) of EVERY layer, so
    a slot's max context is bounded by the WHOLE mesh's paged HBM
    instead of one chip's. The page table, allocator free lists,
    refcounts, radix tree, CoW and host-tier bookkeeping stay
    host-side and layout-blind exactly as under the TP head-group
    split — the allocator (kernels/paged_kv.PageAllocator shards=)
    merely rotates fresh groups across shards so consecutive logical
    tiles interleave chips. A decode tick runs under shard_map with
    each chip walking ONLY its local pages through the split-KV
    partial kernel (kernels/paged_kv.flash_decode_paged_partial) and
    the partials merging via the cross-chip LSE combine
    (kernels/sp_flash_decode.sp_combine_partials): per-chip KV reads
    and attention FLOPs drop to ~1/S. sp composes with int8 scale
    planes (they shard alongside the payload) but not (yet) with the
    TP head-group split or the fused megakernel tick — both refused
    capability-named at Engine construction."""

    pages_k: Tuple[jax.Array, ...]   # L x [NP, G, page, d]
    pages_v: Tuple[jax.Array, ...]
    table: jax.Array                 # [B*Hkv, max_pages] int32
    # int8 pool only: per-position dequant scales, L x [NP, G, page]
    # f32 (empty tuples for the bf16 pool — a pytree-stable "absent")
    scales_k: Tuple[jax.Array, ...] = ()
    scales_v: Tuple[jax.Array, ...] = ()
    trash: int = dataclasses.field(default=0, metadata=dict(static=True))
    # sp mesh size the pool's page-id space is partitioned over (1 =
    # the historical single-shard pool; static so programs branch on
    # it at trace time)
    sp: int = dataclasses.field(default=1, metadata=dict(static=True))

    @staticmethod
    def create(num_layers: int, batch: int, max_seq: int, n_kv_heads: int,
               head_dim: int, *, page: int, num_pages: int, mesh: Mesh,
               axis: str = "tp", dtype=jnp.bfloat16,
               trash: int = 0,
               sp_axis: Optional[str] = None) -> "PagedSlotCache":
        maxp = -(-max_seq // page)
        X = batch * n_kv_heads
        G = mesh.shape[axis]
        if n_kv_heads % G:
            raise ValueError(
                f"paged pool needs n_kv_heads ({n_kv_heads}) divisible "
                f"by the TP mesh size ({G}): each chip owns a whole "
                f"kv-head group of the page payloads. GQA replication "
                f"(Hq > Hkv) lives on the QUERY side and does not "
                f"relax this — replicate KV heads in the checkpoint "
                f"or shrink the mesh.")
        sp = 1
        if sp_axis is not None:
            sp = mesh.shape[sp_axis]
            if sp > 1 and G > 1:
                raise ValueError(
                    "paged pool cannot shard pages over "
                    f"{sp_axis!r} AND kv-head groups over {axis!r} in "
                    "one pool (missing capability: sp + TP hybrid "
                    "serving) — size one of the axes to 1")
            if num_pages % sp:
                raise ValueError(
                    f"sequence-parallel pool needs num_pages "
                    f"({num_pages}) divisible by the sp mesh size "
                    f"({sp}): each chip owns a contiguous block of the "
                    f"page-id space — round num_pages up or shrink "
                    f"the axis")
        page_spec = (P(None, axis, None, None) if sp == 1
                     else P(sp_axis, axis, None, None))
        sc_spec = (P(None, axis, None) if sp == 1
                   else P(sp_axis, axis, None))
        shd = NamedSharding(mesh, page_spec)
        mk = lambda: tuple(
            jax.device_put(
                jnp.zeros((num_pages, G, page, head_dim), dtype), shd)
            for _ in range(num_layers))
        sk = sv = ()
        if jnp.dtype(dtype) == jnp.int8:
            s_shd = NamedSharding(mesh, sc_spec)
            mks = lambda: tuple(
                jax.device_put(
                    jnp.zeros((num_pages, G, page), jnp.float32), s_shd)
                for _ in range(num_layers))
            sk, sv = mks(), mks()
        table = jax.device_put(
            jnp.full((X, maxp), trash, jnp.int32),
            NamedSharding(mesh, P(None, None)))
        return PagedSlotCache(pages_k=mk(), pages_v=mk(), table=table,
                              scales_k=sk, scales_v=sv, trash=trash,
                              sp=sp)

    @property
    def quantized(self) -> bool:
        return bool(self.scales_k)

    @property
    def page(self) -> int:
        return self.pages_k[0].shape[2]

    @property
    def num_pages(self) -> int:
        return self.pages_k[0].shape[0]

    @property
    def head_groups(self) -> int:
        """The TP head-group axis G (mesh size at creation): payload
        plane g holds the bytes of kv-head group g's pages."""
        return self.pages_k[0].shape[1]

    @property
    def pages_per_shard(self) -> int:
        """Physical pages per sp shard (== num_pages at sp == 1):
        chip s owns ids [s*pps, (s+1)*pps) — the id partition the
        allocator, the sp attends and the admit programs all share."""
        return self.pages_k[0].shape[0] // self.sp

    @property
    def capacity(self) -> int:
        """Logical positions addressable per slot (table width x page)."""
        return self.table.shape[1] * self.page

    def layer(self, idx: int):
        """Per-layer pool tuple for the paged attends: (pages_k,
        pages_v) — or (pages_k, pages_v, scales_k, scales_v) when
        int8 (mirrors KVCache.layer's 2-vs-4 contract)."""
        if self.quantized:
            return (self.pages_k[idx], self.pages_v[idx],
                    self.scales_k[idx], self.scales_v[idx])
        return self.pages_k[idx], self.pages_v[idx]

    def set_layer(self, idx: int, *kv) -> "PagedSlotCache":
        def put(t, x):
            return t[:idx] + (x,) + t[idx + 1:]

        out = dataclasses.replace(self, pages_k=put(self.pages_k, kv[0]),
                                  pages_v=put(self.pages_v, kv[1]))
        if len(kv) == 4:
            out = dataclasses.replace(
                out, scales_k=put(self.scales_k, kv[2]),
                scales_v=put(self.scales_v, kv[3]))
        return out
