"""KV cache (reference: `python/triton_dist/models/kv_cache.py`
`KV_Cache:29` — contiguous per-layer K/V buffers + a shared offset).

TPU re-design: per-layer pairs of arrays [B, Hkv, T, hd] sharded on the
KV-head axis over TP (each rank caches only its heads — same memory
split as the reference's per-rank cache), updated functionally
(`jax.lax.dynamic_update_slice`) so the decode step can donate the cache
and XLA updates it in place.

Two deliberate layout choices:
- per-layer tuple (NOT one stacked [L, ...] array): a stacked array
  would make every layer update an update-slice on the whole multi-GB
  buffer and every kernel read a materialized slice copy; separate
  buffers update in place under donation and feed Pallas directly.
- head-major [Hkv, T, hd]: each head's KV is contiguous, which is the
  read order of the flash-decode kernel (kernels/flash_attn.py) — no
  transpose on the hot path.

Slot mode (continuous batching, models/scheduler.py): each batch row
is an independent decode SLOT holding a different request. The shared
`offset` is then meaningless and stays untouched — per-slot positions
live in the scheduler's carry, rows are written by per-row scatter
(TP_Attn._attend_cached_slots) and admission replaces a whole row
(engine._write_slot_fn), so one row's request can never read another's
KV (per-row attention lengths mask the rest).
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class KVCache:
    k: Tuple[jax.Array, ...]   # L x [B, Hkv, T, hd]
    v: Tuple[jax.Array, ...]
    offset: jax.Array  # scalar int32: number of valid positions
    # int8 cache only: per-position dequant scales, L x [B, Hkv, T] f32
    # (empty tuples for the bf16 cache — a pytree-stable "absent")
    ks: Tuple[jax.Array, ...] = ()
    vs: Tuple[jax.Array, ...] = ()

    @staticmethod
    def create(num_layers: int, batch: int, max_seq: int, n_kv_heads: int,
               head_dim: int, *, mesh: Mesh, axis: str = "tp",
               dtype=jnp.bfloat16) -> "KVCache":
        """dtype=jnp.int8 stores K/V quantized with per-position scales
        — half the HBM read of the decode step's dominant traffic; the
        flash kernel dequants exactly via logit/P scaling
        (kernels/flash_attn.py)."""
        shape = (batch, n_kv_heads, max_seq, head_dim)
        sharding = NamedSharding(mesh, P(None, axis, None, None))
        k = tuple(jax.device_put(jnp.zeros(shape, dtype), sharding)
                  for _ in range(num_layers))
        v = tuple(jax.device_put(jnp.zeros(shape, dtype), sharding)
                  for _ in range(num_layers))
        ks = vs = ()
        if jnp.dtype(dtype) == jnp.int8:
            s_shd = NamedSharding(mesh, P(None, axis, None))
            mk = lambda: tuple(
                jax.device_put(jnp.zeros(shape[:3], jnp.float32), s_shd)
                for _ in range(num_layers))
            ks, vs = mk(), mk()
        return KVCache(k=k, v=v, offset=jnp.int32(0), ks=ks, vs=vs)

    @property
    def quantized(self) -> bool:
        return bool(self.ks)

    def layer(self, idx: int):
        """Per-layer cache tuple passed into TP_Attn.fwd_cached:
        (k, v) or (k, v, ks, vs) when int8."""
        if self.quantized:
            return (self.k[idx], self.v[idx], self.ks[idx], self.vs[idx])
        return self.k[idx], self.v[idx]

    def set_layer(self, idx: int, kv) -> "KVCache":
        def put(t, x):
            return t[:idx] + (x,) + t[idx + 1:]

        out = dataclasses.replace(
            self, k=put(self.k, kv[0]), v=put(self.v, kv[1]))
        if len(kv) == 4:
            out = dataclasses.replace(out, ks=put(self.ks, kv[2]),
                                      vs=put(self.vs, kv[3]))
        return out

    def advance(self, n) -> "KVCache":
        return dataclasses.replace(self, offset=self.offset + n)
