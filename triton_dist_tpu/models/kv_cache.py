"""KV cache (reference: `python/triton_dist/models/kv_cache.py`
`KV_Cache:29` — contiguous per-layer K/V buffers + a shared offset).

TPU re-design: one stacked pair of arrays [L, B, T, Hkv, hd] sharded on
the KV-head axis over TP (each rank caches only its heads — same memory
split as the reference's per-rank cache), updated functionally
(`jax.lax.dynamic_update_slice`) so the decode step can donate the cache
and XLA updates it in place.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class KVCache:
    k: jax.Array   # [L, B, T, Hkv, hd]
    v: jax.Array
    offset: jax.Array  # scalar int32: number of valid positions

    @staticmethod
    def create(num_layers: int, batch: int, max_seq: int, n_kv_heads: int,
               head_dim: int, *, mesh: Mesh, axis: str = "tp",
               dtype=jnp.bfloat16) -> "KVCache":
        shape = (num_layers, batch, max_seq, n_kv_heads, head_dim)
        sharding = NamedSharding(mesh, P(None, None, None, axis, None))
        z = jax.device_put(jnp.zeros(shape, dtype), sharding)
        return KVCache(k=z, v=jax.device_put(jnp.zeros(shape, dtype),
                                             sharding),
                       offset=jnp.int32(0))

    def layer(self, idx: int):
        """Per-layer views passed into TP_Attn.fwd_cached."""
        return self.k[idx], self.v[idx]

    def set_layer(self, idx: int, ck, cv) -> "KVCache":
        return dataclasses.replace(
            self, k=self.k.at[idx].set(ck), v=self.v.at[idx].set(cv))

    def advance(self, n) -> "KVCache":
        return dataclasses.replace(self, offset=self.offset + n)
