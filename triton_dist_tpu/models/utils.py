"""Model-side utilities (reference: `python/triton_dist/models/utils.py`
— sampling helpers + emoji logger)."""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp

logger = logging.getLogger("triton_dist_tpu")
if not logger.handlers:
    _h = logging.StreamHandler()
    _h.setFormatter(logging.Formatter("[tdtpu] %(levelname)s %(message)s"))
    logger.addHandler(_h)
    logger.setLevel(logging.INFO)


def sample_greedy(logits):
    return jnp.argmax(logits, axis=-1)


def top_k_support(logits, k: int, temperature: float):
    """Temperature-scaled logits restricted to the top-k support:
    (values [..., k], vocab indices [..., k]). SHARED by sample_top_k
    and the speculative-verify target distribution
    (models/spec_decode.py target_probs) — the leftover rejection
    sampling is exact only if both draw from the same support."""
    return jax.lax.top_k(logits / temperature, k)


def top_p_masked_logits(logits, p: float, temperature: float):
    """Temperature-scaled logits with the nucleus tail (cumulative
    prob > p) masked to -inf. SHARED by sample_top_p and the
    speculative-verify target distribution (same exactness contract as
    top_k_support)."""
    logits = logits / temperature
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    cutoff_idx = jnp.sum(cum < p, axis=-1, keepdims=True)
    cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
    return jnp.where(logits < cutoff, -jnp.inf, logits)


def sample_top_k(key, logits, k: int = 50, temperature: float = 1.0):
    """Top-k sampling (reference: models/utils.py sampling helpers)."""
    topv, topi = top_k_support(logits, k, temperature)
    idx = jax.random.categorical(key, topv)
    return jnp.take_along_axis(topi, idx[..., None], axis=-1)[..., 0]


def sample_top_p(key, logits, p: float = 0.9, temperature: float = 1.0):
    """Nucleus sampling: mask the tail whose cumulative prob > p."""
    return jax.random.categorical(
        key, top_p_masked_logits(logits, p, temperature))
