"""Model-side utilities (reference: `python/triton_dist/models/utils.py`
— sampling helpers + emoji logger)."""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp

logger = logging.getLogger("triton_dist_tpu")
if not logger.handlers:
    _h = logging.StreamHandler()
    _h.setFormatter(logging.Formatter("[tdtpu] %(levelname)s %(message)s"))
    logger.addHandler(_h)
    logger.setLevel(logging.INFO)


def sample_greedy(logits):
    return jnp.argmax(logits, axis=-1)


def sample_top_k(key, logits, k: int = 50, temperature: float = 1.0):
    """Top-k sampling (reference: models/utils.py sampling helpers)."""
    topv, topi = jax.lax.top_k(logits / temperature, k)
    idx = jax.random.categorical(key, topv)
    return jnp.take_along_axis(topi, idx[..., None], axis=-1)[..., 0]


def sample_top_p(key, logits, p: float = 0.9, temperature: float = 1.0):
    """Nucleus sampling: mask the tail whose cumulative prob > p."""
    logits = logits / temperature
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    cutoff_idx = jnp.sum(cum < p, axis=-1, keepdims=True)
    cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
    masked = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(key, masked)
