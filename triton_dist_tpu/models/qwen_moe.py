"""Qwen3-MoE model (reference: `python/triton_dist/models/qwen_moe.py`
`Qwen3MoE:108` — Qwen3 attention blocks + routed-expert SwiGLU FFNs).

Functional pytree model mirroring DenseLLM; the FFN is either a TP_MoE
(experts replicated, intermediate sharded — the reference's TP-MoE
AG-GroupGEMM/MoE-reduce-RS path) or an EP_MoE (experts sharded, tokens
routed over ICI — the reference's EP a2a path), chosen at construction
(`moe_impl`), since the two shard the same weights differently.

Forward modes:
  "xla"      — oracle (dense all-experts MoE + psum attention).
  "flash"    — single-chip framework kernels (flash-decode + grouped
               GEMM expert dispatch).
  "dist"     — TP overlap kernels: AG-GEMM/GEMM-RS attention +
               AG-GroupGEMM + MoE-reduce-RS FFN (moe_impl="tp").
  "ep"       — AG-GEMM/GEMM-RS attention + EP dispatch/combine FFN
               (moe_impl="ep"); activations row-sharded end to end.
  "ep_flash" — framework attention kernels + EP dispatch/combine FFN
               (moe_impl="ep"): the EP SERVING mode on meshes whose
               attention rides "flash" (single chip, or the EP+TP
               hybrid below) — experts stay sharded and tokens still
               cross the a2a wire, without the comm-kernel attention.

SERVING (ISSUE 13 — the MoE paged serving subsystem): the model now
carries the FULL slot surface the continuous-batching scheduler
requires — `forward_tokens_slots` (+`_verify`),
`forward_tokens_slots_paged` (+`_verify`) — mirroring DenseLLM exactly:
attention layers are TP_Attn, so the paged/contiguous slot attends,
per-slot `kv_lens`+`q_lens` verify masks and the KV-head-group pool
split (PR 9) are REUSED unchanged; only the FFN differs — per-slot
top-k routing runs INSIDE the tick and the expert MLPs dispatch
through the grouped-GEMM kernel (kernels/group_gemm.py via
layers/tp_moe.py fwd_local, or the EP a2a path via layers/ep_moe.py).
`return_moe_stats=True` additionally returns the tick's routing-load
vector [expert_tokens[0..E-1], capacity_dropped] (int32 [E+1]) that
engine/scheduler surface as `expert_tokens{expert=...}` gauges,
`moe_capacity_drops` and `expert_load_imbalance` — the loud half of
dropless-or-loud, observable.

EP+TP HYBRID MESH: `moe_axis` names the mesh axis the experts shard
over (default: the attention `axis`). On a 2-D mesh like
make_mesh((2, 4), ("expert", "tp")), attention KV head-groups split on
"tp" exactly as PR 9 laid them out (the paged pool's G axis) while
expert panels and the a2a dispatch ride "expert" — one scheduler
drives the whole hybrid mesh through ONE sharded program per tick.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from triton_dist_tpu.layers import TP_Attn, precompute_rope, rms_norm
from triton_dist_tpu.layers.ep_moe import EP_MoE
from triton_dist_tpu.layers.tp_moe import TP_MoE
from triton_dist_tpu.models.config import ModelConfig
from triton_dist_tpu.models.kv_cache import KVCache


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class MoELayer:
    attn: TP_Attn
    moe: TP_MoE | EP_MoE
    ln_attn: jax.Array
    ln_mlp: jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class Qwen3MoE:
    embed: jax.Array
    layers: Tuple[MoELayer, ...]
    final_norm: jax.Array
    lm_head: jax.Array
    cos: jax.Array
    sin: jax.Array
    config: ModelConfig = dataclasses.field(metadata=dict(static=True))
    mesh: Mesh = dataclasses.field(metadata=dict(static=True))
    axis: str = dataclasses.field(metadata=dict(static=True))
    moe_impl: str = dataclasses.field(default="tp",
                                      metadata=dict(static=True))
    # expert-parallel mesh axis (EP+TP hybrid serving): experts shard
    # over THIS axis while attention KV head-groups stay on `axis`.
    # None = same axis as attention (the single-axis meshes every
    # pre-hybrid caller builds).
    moe_axis: str = dataclasses.field(default=None,
                                      metadata=dict(static=True))

    @property
    def ep_axis(self) -> str:
        """The mesh axis expert panels shard over."""
        return self.moe_axis or self.axis

    @property
    def ep_size(self) -> int:
        """Expert-parallel degree: rows fed to an EP FFN must divide by
        this (engine.make_*_cache validates the scheduler batch)."""
        if self.moe_impl != "ep":
            return 1
        return self.mesh.shape[self.ep_axis]

    # ------------------------------------------------------------------
    # construction
    # ------------------------------------------------------------------

    @staticmethod
    def random_init(cfg: ModelConfig, mesh: Mesh, axis: str = "tp",
                    seed: int = 0, moe_impl: str = "tp",
                    moe_axis: str = None,
                    capacity_factor=2.0) -> "Qwen3MoE":
        key = jax.random.key(seed)
        D, I = cfg.hidden_size, cfg.moe_intermediate_size
        E, k = cfg.num_experts, cfg.num_experts_per_tok
        Hq, Hkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
        dt = cfg.jax_dtype
        kit = iter(jax.random.split(key, 65536))

        def w(*shape, scale=None):
            s = scale if scale is not None else (shape[-2] ** -0.5)
            return jax.random.normal(next(kit), shape,
                                     dtype=dt) * jnp.asarray(s, dtype=dt)

        moe_cls = TP_MoE if moe_impl == "tp" else EP_MoE
        layers = []
        for _ in range(cfg.num_layers):
            attn = TP_Attn.init(
                w(D, Hq * hd), w(D, Hkv * hd), w(D, Hkv * hd),
                w(Hq * hd, D), mesh=mesh, axis=axis, n_heads=Hq,
                n_kv_heads=Hkv, head_dim=hd,
                q_norm=np.ones(hd, np.float32),
                k_norm=np.ones(hd, np.float32))
            moe = moe_cls.init(
                w(D, E, scale=0.02), w(E, D, I), w(E, D, I), w(E, I, D),
                mesh=mesh, axis=moe_axis or axis, top_k=k,
                capacity_factor=capacity_factor)
            layers.append(MoELayer(
                attn=attn, moe=moe,
                ln_attn=jnp.ones((D,), dt), ln_mlp=jnp.ones((D,), dt)))
        cos, sin = precompute_rope(hd, cfg.max_position_embeddings,
                                   cfg.rope_theta)
        embed = w(cfg.vocab_size, D, scale=0.02)
        return Qwen3MoE(
            embed=embed, layers=tuple(layers),
            final_norm=jnp.ones((D,), dt),
            lm_head=(embed.T if cfg.tie_word_embeddings
                     else w(D, cfg.vocab_size, scale=0.02)),
            cos=cos, sin=sin, config=cfg, mesh=mesh, axis=axis,
            moe_impl=moe_impl, moe_axis=moe_axis)

    @staticmethod
    def from_hf(path: str, mesh: Mesh, axis: str = "tp",
                moe_impl: str = "tp", moe_axis: str = None,
                capacity_factor=2.0) -> "Qwen3MoE":
        """Load HF Qwen3-MoE safetensors, stacking per-expert projections
        (reference: models/qwen_moe.py HF loading + TP shard at load)."""
        from safetensors import safe_open

        cfg = ModelConfig.from_hf_config(path)
        Hq, Hkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
        dt = cfg.jax_dtype
        tensors = {}
        for fn in sorted(os.listdir(path)):
            if fn.endswith(".safetensors"):
                with safe_open(os.path.join(path, fn), framework="np") as f:
                    for key in f.keys():
                        tensors[key] = f.get_tensor(key)

        def t(name):
            return jnp.asarray(tensors[name], dtype=dt)

        moe_cls = TP_MoE if moe_impl == "tp" else EP_MoE
        layers = []
        for li in range(cfg.num_layers):
            p = f"model.layers.{li}."
            attn = TP_Attn.init(
                t(p + "self_attn.q_proj.weight").T,
                t(p + "self_attn.k_proj.weight").T,
                t(p + "self_attn.v_proj.weight").T,
                t(p + "self_attn.o_proj.weight").T,
                mesh=mesh, axis=axis, n_heads=Hq, n_kv_heads=Hkv,
                head_dim=hd,
                q_norm=tensors.get(p + "self_attn.q_norm.weight"),
                k_norm=tensors.get(p + "self_attn.k_norm.weight"))
            gate = jnp.stack([
                t(p + f"mlp.experts.{e}.gate_proj.weight").T
                for e in range(cfg.num_experts)])
            up = jnp.stack([
                t(p + f"mlp.experts.{e}.up_proj.weight").T
                for e in range(cfg.num_experts)])
            down = jnp.stack([
                t(p + f"mlp.experts.{e}.down_proj.weight").T
                for e in range(cfg.num_experts)])
            moe = moe_cls.init(
                t(p + "mlp.gate.weight").T, gate, up, down,
                mesh=mesh, axis=moe_axis or axis,
                top_k=cfg.num_experts_per_tok,
                capacity_factor=capacity_factor)
            layers.append(MoELayer(
                attn=attn, moe=moe,
                ln_attn=t(p + "input_layernorm.weight"),
                ln_mlp=t(p + "post_attention_layernorm.weight")))
        cos, sin = precompute_rope(hd, cfg.max_position_embeddings,
                                   cfg.rope_theta)
        embed = t("model.embed_tokens.weight")
        return Qwen3MoE(
            embed=embed, layers=tuple(layers),
            final_norm=t("model.norm.weight"),
            lm_head=(embed.T if cfg.tie_word_embeddings
                     else t("lm_head.weight").T),
            cos=cos, sin=sin, config=cfg, mesh=mesh, axis=axis,
            moe_impl=moe_impl, moe_axis=moe_axis)

    # ------------------------------------------------------------------
    # forward (mirrors DenseLLM.forward_tokens)
    # ------------------------------------------------------------------

    def _moe_modes(self, mode: str):
        """(attention mode, FFN mode) for one model-level mode string.
        "ep" pairs the comm-kernel attention (AG-GEMM/GEMM-RS) with the
        EP dispatch; "ep_flash" pairs the framework attention kernels
        with the SAME EP dispatch — the serving spelling for meshes
        whose attention path is "flash" (single chip / hybrid EP+TP).
        Every other mode runs the EP model's FFN through the dense
        all-experts oracle (the differential-test arm)."""
        attn_mode = ("dist" if mode == "ep" else
                     "flash" if mode == "ep_flash" else mode)
        if self.moe_impl == "ep":
            moe_mode = "ep" if mode in ("ep", "ep_flash") else "xla"
        else:
            moe_mode = "dist" if mode in ("ep", "ep_flash") else mode
        return attn_mode, moe_mode

    def _zero_load(self):
        """Fresh routing-load accumulator: [expert_tokens[0..E-1],
        capacity_dropped] — the serving tick's telemetry payload."""
        return jnp.zeros((self.config.num_experts + 1,), jnp.int32)

    def _moe_ffn(self, layer, h, moe_mode, load):
        """One routed FFN call; accumulates the routing-load vector
        when the caller asked for stats (load is None otherwise)."""
        if load is None:
            return layer.moe(h, moe_mode), None
        y, st = layer.moe(h, moe_mode, return_stats=True)
        upd = jnp.concatenate([st["expert_tokens"],
                               st["dropped"].reshape(1)])
        return y, load + upd

    def forward_tokens(self, ids, cache: KVCache, mode: str = "dist",
                       last_pos=None):
        B, S = ids.shape
        attn_mode, moe_mode = self._moe_modes(mode)
        x = self.embed[ids].reshape(B * S, self.config.hidden_size)
        kv_start = cache.offset
        for li, layer in enumerate(self.layers):
            kv = cache.layer(li)
            h = rms_norm(x, layer.ln_attn, self.config.rms_norm_eps)
            a, kv = layer.attn.fwd_cached(
                h, self.cos, self.sin, B, kv, kv_start, attn_mode)
            cache = cache.set_layer(li, kv)
            x = x + a
            h = rms_norm(x, layer.ln_mlp, self.config.rms_norm_eps)
            x = x + layer.moe(h, moe_mode).astype(x.dtype)
        cache = cache.advance(S)
        x = rms_norm(x, self.final_norm, self.config.rms_norm_eps)
        if mode in ("dist", "ep"):
            x = self._gather_rows(x)
        xr = x.reshape(B, S, -1)
        last = xr[:, -1] if last_pos is None else jnp.take(
            xr, last_pos, axis=1)
        logits = jnp.dot(last, self.lm_head,
                         preferred_element_type=jnp.float32)
        return logits, cache

    def forward_tokens_slots(self, ids, cache: KVCache, pos,
                             mode: str = "dist",
                             return_moe_stats: bool = False):
        """Slot-masked decode forward (continuous batching; mirrors
        DenseLLM.forward_tokens_slots): ids [B, 1], pos [B] int32 —
        row b decodes at its own position. cache.offset is untouched.
        return_moe_stats=True appends the tick's routing-load vector
        (engine/scheduler telemetry — see the module docstring)."""
        B, S = ids.shape
        assert S == 1, "slot decode feeds one token per slot"
        attn_mode, moe_mode = self._moe_modes(mode)
        load = self._zero_load() if return_moe_stats else None
        x = self.embed[ids].reshape(B, self.config.hidden_size)
        for li, layer in enumerate(self.layers):
            kv = cache.layer(li)
            h = rms_norm(x, layer.ln_attn, self.config.rms_norm_eps)
            a, kv = layer.attn.fwd_cached_slots(
                h, self.cos, self.sin, B, kv, pos, attn_mode)
            cache = cache.set_layer(li, kv)
            x = x + a
            h = rms_norm(x, layer.ln_mlp, self.config.rms_norm_eps)
            y, load = self._moe_ffn(layer, h, moe_mode, load)
            x = x + y.astype(x.dtype)
        x = rms_norm(x, self.final_norm, self.config.rms_norm_eps)
        if mode in ("dist", "ep"):
            x = self._gather_rows(x)
        logits = jnp.dot(x, self.lm_head,
                         preferred_element_type=jnp.float32)
        if return_moe_stats:
            return logits, cache, load
        return logits, cache

    def forward_tokens_slots_verify(self, ids, cache: KVCache, pos,
                                    q_lens, mode: str = "dist",
                                    return_moe_stats: bool = False):
        """Speculative-verify forward over the CONTIGUOUS slot cache
        (mirrors DenseLLM.forward_tokens_slots_verify): each batch row
        scores a variable-length draft window in ONE pass via the
        per-slot `q_lens`+`kv_lens` masks — the PR-3 machinery, reused
        byte-for-byte since attention layers are TP_Attn. The routed
        FFN sees the window rows exactly like decode rows (padded rows
        are computed-and-discarded; their routed entries count toward
        the load gauges — compute load, not emitted tokens)."""
        B, S = ids.shape
        attn_mode, moe_mode = self._moe_modes(mode)
        load = self._zero_load() if return_moe_stats else None
        x = self.embed[ids].reshape(B * S, self.config.hidden_size)
        for li, layer in enumerate(self.layers):
            kv = cache.layer(li)
            h = rms_norm(x, layer.ln_attn, self.config.rms_norm_eps)
            a, kv = layer.attn.fwd_cached_slots_verify(
                h, self.cos, self.sin, B, kv, pos, q_lens, attn_mode)
            cache = cache.set_layer(li, kv)
            x = x + a
            h = rms_norm(x, layer.ln_mlp, self.config.rms_norm_eps)
            y, load = self._moe_ffn(layer, h, moe_mode, load)
            x = x + y.astype(x.dtype)
        x = rms_norm(x, self.final_norm, self.config.rms_norm_eps)
        if mode in ("dist", "ep"):
            x = self._gather_rows(x)
        logits = jnp.dot(x, self.lm_head,
                         preferred_element_type=jnp.float32)
        if return_moe_stats:
            return logits.reshape(B, S, -1), cache, load
        return logits.reshape(B, S, -1), cache

    def forward_tokens_slots_paged(self, ids, pcache, pos,
                                   mode: str = "flash",
                                   return_moe_stats: bool = False):
        """Slot-masked decode forward over the PAGED KV pool (mirrors
        DenseLLM.forward_tokens_slots_paged — the shared-prefix serving
        tick): identical attention math through the page table (slot b
        attends whatever pages its table row maps, including pages
        shared read-only with other slots' cached prefixes), with
        PER-SLOT TOP-K ROUTING inside the tick and grouped-GEMM expert
        dispatch replacing the per-expert dense loop. ids [B, 1];
        pos [B] int32; pcache: PagedSlotCache."""
        B, S = ids.shape
        assert S == 1, "slot decode feeds one token per slot"
        attn_mode, moe_mode = self._moe_modes(mode)
        load = self._zero_load() if return_moe_stats else None
        x = self.embed[ids].reshape(B, self.config.hidden_size)
        for li, layer in enumerate(self.layers):
            h = rms_norm(x, layer.ln_attn, self.config.rms_norm_eps)
            a, kv = layer.attn.fwd_cached_slots_paged(
                h, self.cos, self.sin, B, pcache.layer(li),
                pcache.table, pos, attn_mode)
            pcache = pcache.set_layer(li, *kv)
            x = x + a
            h = rms_norm(x, layer.ln_mlp, self.config.rms_norm_eps)
            y, load = self._moe_ffn(layer, h, moe_mode, load)
            x = x + y.astype(x.dtype)
        x = rms_norm(x, self.final_norm, self.config.rms_norm_eps)
        if mode in ("dist", "ep"):
            x = self._gather_rows(x)
        logits = jnp.dot(x, self.lm_head,
                         preferred_element_type=jnp.float32)
        if return_moe_stats:
            return logits, pcache, load
        return logits, pcache

    def forward_tokens_slots_paged_verify(self, ids, pcache, pos,
                                          q_lens, mode: str = "flash",
                                          return_moe_stats: bool = False):
        """forward_tokens_slots_verify over the PAGED pool (mirrors the
        dense twin): the draft window's K/V resolves through the page
        table (padded rows scatter out of bounds and are dropped) and
        attention walks the pool with per-slot kv_lens AND q_lens; the
        routed FFN dispatches the whole mixed window through the
        grouped GEMMs. This is ALSO the chunked-prefill mixed tick's
        forward (engine._mixed_forward) — prefill chunk rows route
        through the experts alongside live decode rows."""
        B, S = ids.shape
        attn_mode, moe_mode = self._moe_modes(mode)
        load = self._zero_load() if return_moe_stats else None
        x = self.embed[ids].reshape(B * S, self.config.hidden_size)
        for li, layer in enumerate(self.layers):
            h = rms_norm(x, layer.ln_attn, self.config.rms_norm_eps)
            a, kv = layer.attn.fwd_cached_slots_paged_verify(
                h, self.cos, self.sin, B, pcache.layer(li),
                pcache.table, pos, q_lens, attn_mode)
            pcache = pcache.set_layer(li, *kv)
            x = x + a
            h = rms_norm(x, layer.ln_mlp, self.config.rms_norm_eps)
            y, load = self._moe_ffn(layer, h, moe_mode, load)
            x = x + y.astype(x.dtype)
        x = rms_norm(x, self.final_norm, self.config.rms_norm_eps)
        if mode in ("dist", "ep"):
            x = self._gather_rows(x)
        logits = jnp.dot(x, self.lm_head,
                         preferred_element_type=jnp.float32)
        if return_moe_stats:
            return logits.reshape(B, S, -1), pcache, load
        return logits.reshape(B, S, -1), pcache

    def forward_train(self, ids, mode: str = "train"):
        """Training forward (no KV cache), mirroring
        DenseLLM.forward_train: full-causal attention, all-position
        logits [B, S, V].

        mode="train": attention through the custom-VJP ag_gemm/gemm_rs +
        Pallas flash kernels; the MoE FFN through custom-VJP
        all_gather/grouped-GEMM/reduce_scatter (moe_impl="tp",
        layers/tp_moe.py::fwd_train) or custom-VJP a2a dispatch/combine
        + grouped GEMMs (moe_impl="ep", layers/ep_moe.py::fwd_train) —
        the reference's autograd Function over the fused MoE ops,
        function/nvidia/ep_moe_fused.py:42.
        mode="xla": the dense all-experts oracle for gradient tests.
        """
        B, S = ids.shape
        impl = "flash" if mode == "train" else "ref"
        moe_mode = "train" if mode == "train" else "xla"
        x = self.embed[ids].reshape(B * S, self.config.hidden_size)
        from jax.sharding import AxisType, NamedSharding
        if any(t == AxisType.Explicit
               for t in (self.mesh.axis_types or ())):
            # pin the embed-gather cotangent replicated (see
            # models/dense.py::forward_train)
            x = jax.sharding.reshard(
                x, NamedSharding(self.mesh, P(None, None)))
        for layer in self.layers:
            h = rms_norm(x, layer.ln_attn, self.config.rms_norm_eps)
            x = x + layer.attn.fwd_train(h, self.cos, self.sin, B, impl)
            h = rms_norm(x, layer.ln_mlp, self.config.rms_norm_eps)
            x = x + layer.moe(h, moe_mode).astype(x.dtype)
        x = rms_norm(x, self.final_norm, self.config.rms_norm_eps)
        if mode == "train":
            x = self._gather_rows(x)
        logits = jnp.dot(x, self.lm_head,
                         preferred_element_type=jnp.float32)
        return logits.reshape(B, S, -1)

    def _gather_rows(self, x):
        """Row-sharded [M, D] -> replicated (the LM-head prologue; same
        helper as DenseLLM._gather_rows)."""
        import functools

        @functools.partial(
            jax.shard_map, mesh=self.mesh,
            in_specs=P(self.axis, None), out_specs=P(None, None),
            check_vma=False)
        def gather_rows(x_loc):
            return jax.lax.all_gather(x_loc, self.axis, axis=0,
                                      tiled=True)

        return gather_rows(x)

    def make_cache(self, batch: int, max_seq: int, dtype=None) -> KVCache:
        cfg = self.config
        return KVCache.create(cfg.num_layers, batch, max_seq,
                              cfg.num_kv_heads, cfg.head_dim,
                              mesh=self.mesh, axis=self.axis,
                              dtype=dtype or cfg.jax_dtype)
