"""Dense Qwen3-family LLM (reference: `python/triton_dist/models/dense.py`
`DenseLLM:117`, per-layer `set_fwd` mode switch :84-100, TP context init
:169-209; HF weight loading + TP sharding at load :150-168).

Functional pytree model: weights are leaves, mode is an argument (the
reference mutates per-layer fwd pointers; here the mode string selects
the path inside one jitted function — same switch, jit-compatible).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from triton_dist_tpu.layers import TP_Attn, TP_MLP, precompute_rope, rms_norm
from triton_dist_tpu.models.config import ModelConfig
from triton_dist_tpu.models.kv_cache import KVCache


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class DenseLayer:
    attn: TP_Attn
    mlp: TP_MLP
    ln_attn: jax.Array
    ln_mlp: jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class DenseLLM:
    embed: jax.Array            # [V, D]
    layers: Tuple[DenseLayer, ...]
    final_norm: jax.Array       # [D]
    lm_head: jax.Array          # [D, V]
    cos: jax.Array
    sin: jax.Array
    config: ModelConfig = dataclasses.field(metadata=dict(static=True))
    mesh: Mesh = dataclasses.field(metadata=dict(static=True))
    axis: str = dataclasses.field(metadata=dict(static=True))
    # SEQUENCE-PARALLEL serving (long-context — kv_cache.PagedSlotCache
    # SP SHARDING): the mesh axis the paged pool's page-id space
    # shards over (None = single-chip pools). The paged slot forwards
    # then attend through the split-KV partial + cross-chip LSE
    # combine (layers/tp_attn.py fwd_cached_slots_paged_sp);
    # sp_combine picks the merge ("xla" = all_gather + lse_combine,
    # "dist" = the one-sided Pallas push kernel of
    # kernels/sp_flash_decode.py).
    sp_axis: Optional[str] = dataclasses.field(
        default=None, metadata=dict(static=True))
    sp_combine: str = dataclasses.field(
        default="xla", metadata=dict(static=True))

    @property
    def sp_size(self) -> int:
        """Sequence-parallel mesh size (1 = no page sharding)."""
        return self.mesh.shape[self.sp_axis] if self.sp_axis else 1

    # ------------------------------------------------------------------
    # construction
    # ------------------------------------------------------------------

    @staticmethod
    def random_init(cfg: ModelConfig, mesh: Mesh, axis: str = "tp",
                    seed: int = 0, sp_axis: Optional[str] = None,
                    sp_combine: str = "xla") -> "DenseLLM":
        """Random weights with Qwen3 shapes — the harness/test model.
        Generated device-side (jax.random): host-numpy generation of
        billion-parameter models takes minutes on one core.

        sp_axis: mesh axis for SEQUENCE-PARALLEL paged serving (the
        long-context layout — weights replicate over it, only the
        paged pool shards; build the mesh as e.g.
        jax.make_mesh((1, 4), ("tp", "sp")) and pass sp_axis="sp")."""
        key = jax.random.key(seed)
        D, I = cfg.hidden_size, cfg.intermediate_size
        Hq, Hkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
        dt = cfg.jax_dtype
        kit = iter(jax.random.split(key, 16384))

        def w(*shape, scale=None):
            s = scale if scale is not None else (shape[0] ** -0.5)
            return jax.random.normal(next(kit), shape, dtype=dt) * jnp.asarray(
                s, dtype=dt)

        layers = []
        for _ in range(cfg.num_layers):
            attn = TP_Attn.init(
                w(D, Hq * hd), w(D, Hkv * hd), w(D, Hkv * hd),
                w(Hq * hd, D), mesh=mesh, axis=axis, n_heads=Hq,
                n_kv_heads=Hkv, head_dim=hd,
                q_norm=np.ones(hd, np.float32),
                k_norm=np.ones(hd, np.float32))
            mlp = TP_MLP.init(w(D, I), w(D, I), w(I, D), mesh=mesh,
                              axis=axis)
            layers.append(DenseLayer(
                attn=attn, mlp=mlp,
                ln_attn=jnp.ones((D,), dt), ln_mlp=jnp.ones((D,), dt)))
        cos, sin = precompute_rope(hd, cfg.max_position_embeddings,
                                   cfg.rope_theta)
        embed = w(cfg.vocab_size, D, scale=0.02)
        return DenseLLM(
            embed=embed, layers=tuple(layers),
            final_norm=jnp.ones((D,), dt),
            lm_head=(embed.T if cfg.tie_word_embeddings
                     else w(D, cfg.vocab_size, scale=0.02)),
            cos=cos, sin=sin, config=cfg, mesh=mesh, axis=axis,
            sp_axis=sp_axis, sp_combine=sp_combine)

    @staticmethod
    def from_hf(path: str, mesh: Mesh, axis: str = "tp",
                sp_axis: Optional[str] = None,
                sp_combine: str = "xla") -> "DenseLLM":
        """Load HF Qwen3 safetensors and shard at load (reference:
        models/dense.py:150-168). Requires a local checkpoint dir."""
        from safetensors import safe_open

        cfg = ModelConfig.from_hf_config(path)
        D, Hq, Hkv, hd = (cfg.hidden_size, cfg.num_heads, cfg.num_kv_heads,
                          cfg.head_dim)
        dt = cfg.jax_dtype
        tensors = {}
        for fn in sorted(os.listdir(path)):
            if fn.endswith(".safetensors"):
                with safe_open(os.path.join(path, fn), framework="np") as f:
                    for key in f.keys():
                        tensors[key] = f.get_tensor(key)

        def t(name):
            return jnp.asarray(tensors[name], dtype=dt)

        layers = []
        for li in range(cfg.num_layers):
            p = f"model.layers.{li}."
            # HF stores projections transposed ([out, in])
            attn = TP_Attn.init(
                t(p + "self_attn.q_proj.weight").T,
                t(p + "self_attn.k_proj.weight").T,
                t(p + "self_attn.v_proj.weight").T,
                t(p + "self_attn.o_proj.weight").T,
                mesh=mesh, axis=axis, n_heads=Hq, n_kv_heads=Hkv,
                head_dim=hd,
                q_norm=tensors.get(p + "self_attn.q_norm.weight"),
                k_norm=tensors.get(p + "self_attn.k_norm.weight"))
            mlp = TP_MLP.init(
                t(p + "mlp.gate_proj.weight").T,
                t(p + "mlp.up_proj.weight").T,
                t(p + "mlp.down_proj.weight").T, mesh=mesh, axis=axis)
            layers.append(DenseLayer(
                attn=attn, mlp=mlp,
                ln_attn=t(p + "input_layernorm.weight"),
                ln_mlp=t(p + "post_attention_layernorm.weight")))
        cos, sin = precompute_rope(hd, cfg.max_position_embeddings,
                                   cfg.rope_theta)
        embed = t("model.embed_tokens.weight")
        lm_head = (embed.T if cfg.tie_word_embeddings
                   else t("lm_head.weight").T)
        return DenseLLM(embed=embed, layers=tuple(layers),
                        final_norm=t("model.norm.weight"),
                        lm_head=lm_head, cos=cos, sin=sin, config=cfg,
                        mesh=mesh, axis=axis, sp_axis=sp_axis,
                        sp_combine=sp_combine)

    def quantize_int8(self) -> "DenseLLM":
        """Weight-only int8 copy for the bandwidth-bound decode regime
        (kernels/quant.py): projection weights and the lm_head become
        QuantW (int8 + per-column scale), halving the per-step weight
        read. Valid for EVERY forward mode: "flash"/"xla" dequant via
        qmm, and the comm-kernel modes ("dist"/"ar"/"gemm_ar") stream
        int8 weight panels through ag_gemm/gemm_rs/gemm_allreduce with
        the per-column dequant fused after each dot (exact). Embed
        stays bf16 (it is a gather, not a GEMM)."""
        from triton_dist_tpu.kernels.quant import quantize_int8 as q8
        layers = tuple(
            dataclasses.replace(
                ly,
                attn=dataclasses.replace(ly.attn, w_qkv=q8(ly.attn.w_qkv),
                                         w_o=q8(ly.attn.w_o)),
                mlp=dataclasses.replace(ly.mlp,
                                        w_gate_up=q8(ly.mlp.w_gate_up),
                                        w_down=q8(ly.mlp.w_down)))
            for ly in self.layers)
        return dataclasses.replace(self, layers=layers,
                                   lm_head=q8(self.lm_head))

    # ------------------------------------------------------------------
    # forward
    # ------------------------------------------------------------------

    def forward_tokens(self, ids, cache: KVCache, mode: str = "dist",
                       mlp_mode: Optional[str] = None, last_pos=None):
        """One forward pass over `ids` [B, S] starting at cache.offset;
        fills the cache and returns (last-position logits [B, V], cache).

        mode: attention forward mode; mlp_mode defaults to mode. For
        "dist", B*S must be divisible by the TP size (reference contract:
        max_M-padded symmetric workspaces, allgather_gemm.py:447).

        last_pos: optional traced scalar — take the logits at THIS
        sequence position instead of S-1 (the bucketed prefill-into-slot
        path pads prompts to a fixed S and reads the last REAL position,
        engine.prefill_into_slot).
        """
        B, S = ids.shape
        mlp_mode = mlp_mode or mode
        x = self.embed[ids].reshape(B * S, self.config.hidden_size)
        kv_start = cache.offset
        for li, layer in enumerate(self.layers):
            kv = cache.layer(li)
            h = rms_norm(x, layer.ln_attn, self.config.rms_norm_eps)
            a, kv = layer.attn.fwd_cached(
                h, self.cos, self.sin, B, kv, kv_start, mode)
            cache = cache.set_layer(li, kv)
            x = x + a
            h = rms_norm(x, layer.ln_mlp, self.config.rms_norm_eps)
            x = x + layer.mlp(h, mlp_mode)
        cache = cache.advance(S)
        x = rms_norm(x, self.final_norm, self.config.rms_norm_eps)
        if mode == "dist":
            # activations are row-sharded; gather for the LM head tail
            x = self._gather_rows(x)
        xr = x.reshape(B, S, -1)
        last = xr[:, -1] if last_pos is None else jnp.take(
            xr, last_pos, axis=1)
        # bf16 x bf16 -> f32 on the MXU; casting the [D, V] weight to f32
        # would materialize (and re-read) gigabytes per decode step.
        # lm_head may be int8-quantized (the single biggest weight read
        # of a decode step) — qmm dequants after the dot.
        from triton_dist_tpu.kernels.quant import qmm
        logits = qmm(last, self.lm_head,
                     preferred_element_type=jnp.float32)
        return logits, cache

    def forward_tokens_slots(self, ids, cache: KVCache, pos,
                             mode: str = "dist",
                             mlp_mode: Optional[str] = None):
        """Slot-masked decode forward (continuous batching): one token
        per batch row, row b at its OWN position pos[b] (models/
        scheduler.py). ids: [B, 1]; pos: [B] int32. Writes each row's
        K/V at its own cache column and attends per-row lengths; the
        shared cache.offset is NOT advanced — per-slot positions live
        with the scheduler. Returns (logits [B, V], cache)."""
        B, S = ids.shape
        assert S == 1, "slot decode feeds one token per slot"
        mlp_mode = mlp_mode or mode
        x = self.embed[ids].reshape(B, self.config.hidden_size)
        for li, layer in enumerate(self.layers):
            kv = cache.layer(li)
            h = rms_norm(x, layer.ln_attn, self.config.rms_norm_eps)
            a, kv = layer.attn.fwd_cached_slots(
                h, self.cos, self.sin, B, kv, pos, mode)
            cache = cache.set_layer(li, kv)
            x = x + a
            h = rms_norm(x, layer.ln_mlp, self.config.rms_norm_eps)
            x = x + layer.mlp(h, mlp_mode)
        x = rms_norm(x, self.final_norm, self.config.rms_norm_eps)
        if mode == "dist":
            x = self._gather_rows(x)
        from triton_dist_tpu.kernels.quant import qmm
        logits = qmm(x, self.lm_head, preferred_element_type=jnp.float32)
        return logits, cache

    def forward_tokens_slots_verify(self, ids, cache: KVCache, pos,
                                    q_lens, mode: str = "dist",
                                    mlp_mode: Optional[str] = None):
        """Speculative-verify forward (models/spec_decode.py): each
        batch row is a slot scoring a variable-length draft window in
        ONE pass. ids: [B, S] — slot b's first q_lens[b] tokens occupy
        positions pos[b] .. pos[b] + q_lens[b] - 1 (padding past
        q_lens[b] is computed-and-discarded); K/V of the valid window
        rows are written at those cache columns (a rejected suffix is
        simply overwritten by the next step). Returns (per-position
        logits [B, S, V], cache)."""
        B, S = ids.shape
        mlp_mode = mlp_mode or mode
        x = self.embed[ids].reshape(B * S, self.config.hidden_size)
        for li, layer in enumerate(self.layers):
            kv = cache.layer(li)
            h = rms_norm(x, layer.ln_attn, self.config.rms_norm_eps)
            a, kv = layer.attn.fwd_cached_slots_verify(
                h, self.cos, self.sin, B, kv, pos, q_lens, mode)
            cache = cache.set_layer(li, kv)
            x = x + a
            h = rms_norm(x, layer.ln_mlp, self.config.rms_norm_eps)
            x = x + layer.mlp(h, mlp_mode)
        x = rms_norm(x, self.final_norm, self.config.rms_norm_eps)
        if mode == "dist":
            x = self._gather_rows(x)
        from triton_dist_tpu.kernels.quant import qmm
        logits = qmm(x, self.lm_head, preferred_element_type=jnp.float32)
        return logits.reshape(B, S, -1), cache

    def forward_tokens_slots_paged_verify(self, ids, pcache, pos, q_lens,
                                          mode: str = "flash",
                                          mlp_mode: Optional[str] = None):
        """forward_tokens_slots_verify over the PAGED KV pool: the
        draft window's K/V resolves through the page table (padded rows
        scatter out of bounds and are dropped), and attention walks the
        pool with per-slot kv_lens AND q_lens. Returns (per-position
        logits [B, S, V], pcache)."""
        B, S = ids.shape
        mlp_mode = mlp_mode or mode
        x = self.embed[ids].reshape(B * S, self.config.hidden_size)
        for li, layer in enumerate(self.layers):
            h = rms_norm(x, layer.ln_attn, self.config.rms_norm_eps)
            if self.sp_axis is not None:
                a, kv = layer.attn.fwd_cached_slots_paged_verify_sp(
                    h, self.cos, self.sin, B, pcache.layer(li),
                    pcache.table, pos, q_lens, self.sp_axis, mode,
                    self.sp_combine)
            else:
                a, kv = layer.attn.fwd_cached_slots_paged_verify(
                    h, self.cos, self.sin, B, pcache.layer(li),
                    pcache.table, pos, q_lens, mode)
            pcache = pcache.set_layer(li, *kv)
            x = x + a
            h = rms_norm(x, layer.ln_mlp, self.config.rms_norm_eps)
            x = x + layer.mlp(h, mlp_mode)
        x = rms_norm(x, self.final_norm, self.config.rms_norm_eps)
        if mode == "dist":
            x = self._gather_rows(x)
        from triton_dist_tpu.kernels.quant import qmm
        logits = qmm(x, self.lm_head, preferred_element_type=jnp.float32)
        return logits.reshape(B, S, -1), pcache

    def forward_tokens_slots_paged(self, ids, pcache, pos,
                                   mode: str = "flash",
                                   mlp_mode: Optional[str] = None):
        """Slot-masked decode forward over the PAGED KV pool
        (shared-prefix serving, models/prefix_cache.py): identical math
        to forward_tokens_slots, but each layer's KV lives in physical
        pages behind the shared page table — slot b attends whatever
        pages its table row maps, including pages shared read-only with
        other slots' cached prefixes. ids: [B, 1]; pos: [B] int32;
        pcache: PagedSlotCache. Returns (logits [B, V], pcache)."""
        B, S = ids.shape
        assert S == 1, "slot decode feeds one token per slot"
        mlp_mode = mlp_mode or mode
        x = self.embed[ids].reshape(B, self.config.hidden_size)
        for li, layer in enumerate(self.layers):
            h = rms_norm(x, layer.ln_attn, self.config.rms_norm_eps)
            if self.sp_axis is not None:
                # sequence-parallel paged decode: each chip walks its
                # own page shard, partials LSE-merge across sp
                a, kv = layer.attn.fwd_cached_slots_paged_sp(
                    h, self.cos, self.sin, B, pcache.layer(li),
                    pcache.table, pos, self.sp_axis, mode,
                    self.sp_combine)
            else:
                a, kv = layer.attn.fwd_cached_slots_paged(
                    h, self.cos, self.sin, B, pcache.layer(li),
                    pcache.table, pos, mode)
            pcache = pcache.set_layer(li, *kv)
            x = x + a
            h = rms_norm(x, layer.ln_mlp, self.config.rms_norm_eps)
            x = x + layer.mlp(h, mlp_mode)
        x = rms_norm(x, self.final_norm, self.config.rms_norm_eps)
        if mode == "dist":
            x = self._gather_rows(x)
        from triton_dist_tpu.kernels.quant import qmm
        logits = qmm(x, self.lm_head, preferred_element_type=jnp.float32)
        return logits, pcache

    def forward_train(self, ids, mode: str = "train"):
        """Training forward (no KV cache): full-causal attention over
        each sequence, all-position logits [B, S, V].

        mode="train": every projection and the attention run through the
        framework's differentiable kernels (custom-VJP ag_gemm/gemm_rs +
        Pallas flash attention, kernels/grad.py + flash_attn_train.py) —
        the reference's autograd-wrapped dist path
        (layers/nvidia/tp_attn.py under torch.autograd).
        mode="xla": pure-XLA oracle for differential gradient tests.
        B*S must be divisible by the TP size for "train".
        """
        B, S = ids.shape
        impl = "flash" if mode == "train" else "ref"
        mlp_impl = "dist" if mode == "train" else "xla"
        x = self.embed[ids].reshape(B * S, self.config.hidden_size)
        from jax.sharding import AxisType
        if any(t == AxisType.Explicit
               for t in (self.mesh.axis_types or ())):
            # pin the embed-gather cotangent to replicated: its transpose
            # is a scatter-add into the (replicated) table, which
            # explicit-sharding mode rejects for a tp-sharded cotangent
            x = jax.sharding.reshard(
                x, NamedSharding(self.mesh, P(None, None)))
        for layer in self.layers:
            h = rms_norm(x, layer.ln_attn, self.config.rms_norm_eps)
            x = x + layer.attn.fwd_train(h, self.cos, self.sin, B, impl)
            h = rms_norm(x, layer.ln_mlp, self.config.rms_norm_eps)
            x = x + layer.mlp.fwd_train(h, mlp_impl)
        x = rms_norm(x, self.final_norm, self.config.rms_norm_eps)
        if mode == "train":
            # activations are row-sharded; gather for the LM head so the
            # head dot (and its transpose, d lm_head = x^T @ dlogits)
            # contracts a replicated dimension
            x = self._gather_rows(x)
        logits = jnp.dot(x, self.lm_head,
                         preferred_element_type=jnp.float32)
        return logits.reshape(B, S, -1)

    def _gather_rows(self, x):
        """Row-sharded [M, D] -> replicated (the LM-head prologue)."""
        import functools

        @functools.partial(
            jax.shard_map, mesh=self.mesh,
            in_specs=P(self.axis, None), out_specs=P(None, None),
            check_vma=False)
        def gather_rows(x_loc):
            return jax.lax.all_gather(x_loc, self.axis, axis=0,
                                      tiled=True)

        return gather_rows(x)

    def make_cache(self, batch: int, max_seq: int,
                   dtype=None) -> KVCache:
        cfg = self.config
        return KVCache.create(cfg.num_layers, batch, max_seq,
                              cfg.num_kv_heads, cfg.head_dim,
                              mesh=self.mesh, axis=self.axis,
                              dtype=dtype or cfg.jax_dtype)
