"""Speculative decoding: n-gram self-drafting + batched multi-token
verify over the slot caches (contiguous AND paged).

Decode at serving batch sizes is weight-bandwidth-bound: every forward
reads the whole model to emit ONE token per slot. Speculative sampling
(Leviathan et al., ICML 2023 — PAPERS.md) emits several: a cheap
DRAFTER guesses the next K tokens, one target-model forward scores all
of them in parallel (the verify), and an acceptance rule keeps the
longest prefix the target agrees with — provably without changing the
target distribution. Prompt-lookup / n-gram decoding (Saxena, 2023)
supplies a model-free drafter: LLM output constantly re-quotes its own
context (summarization, code edits, chat with retrieved documents), so
matching the last n-gram of the slot's prompt+generated history and
proposing the tokens that followed it last time is free and often
right — and, being deterministic, fits this repo's bitwise-differential
test style.

Division of labor:
- host (this module + models/scheduler.py `spec=K` mode): per-slot
  token history, the `Drafter` (pluggable — a small draft MODEL can
  implement the same protocol later), window padding/len bookkeeping,
  accept counters;
- device (models/engine.py slot_verify_chunk / paged_slot_verify_chunk
  over dense.forward_tokens_slots_verify): ONE forward scores all B
  slots' variable-length windows (0..K drafts each, padded + masked via
  per-slot q_lens alongside kv_lens in kernels/flash_attn.py and
  kernels/paged_kv.py), then the acceptance functions below pick the
  kept prefix and the next seed token without a second forward.

Acceptance:
- greedy (`accept_greedy`): keep drafts while they equal the verify
  argmax; the next seed token is the argmax AFTER the kept prefix (the
  "corrected" token) — so every emitted token is an argmax of target
  logits and the stream is bitwise identical to spec=0.
- sampled (`accept_sampled`): leftover-distribution rejection sampling.
  The n-gram draft is a point mass, so draft d at target distribution p
  is accepted with probability p(d); on rejection the replacement is
  drawn from p with d zeroed and renormalized (the leftover), which
  makes the emitted marginal EXACTLY p at every position regardless of
  draft quality (tests/test_spec_decode.py checks the marginal).

Grammar jump-ahead (models/structured.py, ISSUE 17) rides this module
unchanged: a constrained slot's deterministic automaton continuation
(closing braces, literal JSON keys) becomes its draft window —
`structured.constrained_draft` filters any base drafter's proposal at
the first grammar-illegal token and extends with the forced run, and
`structured.GrammarDrafter` wraps the same walk behind the `Drafter`
protocol below for schedulers that compose drafters externally. The
verify forward scores those windows through the exact programs above
(with per-position grammar masks on the verify logits,
`structured.window_masks`), so constrained streams under spec=K stay
bitwise identical to spec=0 while the forced segments land several
tokens per forward (`jump_ahead_tokens` counter;
tests/test_structured.py).

Rollback is positional: the verify wrote KV for every window row, but a
rejected suffix just stays as dead rows past the slot's rewound length
— never attended (per-slot kv_lens masks) and overwritten by the next
step's window (paged: the pages stay mapped; contiguous: same cache
row).
"""

from __future__ import annotations

from typing import List, Protocol, Sequence

import numpy as np


class Drafter(Protocol):
    """Draft source protocol: given a slot's full token history
    (prompt + everything emitted so far, INCLUDING the pending next
    token), propose up to k likely continuation tokens. May return
    fewer (or none) — the scheduler pads and masks. Implementations
    must be deterministic for the differential tests; a small draft
    model can implement this by greedy-decoding k tokens."""

    def propose(self, history: Sequence[int], k: int) -> List[int]:
        ...


class NgramDrafter:
    """Prompt-lookup / n-gram self-drafting (Saxena, 2023): find the
    most recent earlier occurrence of the history's trailing n-gram
    (longest n first) and propose the tokens that followed it. Free
    (no model), deterministic, and strong exactly where speculative
    decoding pays best: repetitive/summarization-style generation that
    re-quotes its own context.

    `window` bounds the lookup to the last `window` history tokens, so
    the host work between verify forwards stays O(max_n * window) per
    slot regardless of sequence length (an unbounded scan on a long
    chat history can out-cost the device forward it is meant to
    hide)."""

    def __init__(self, max_n: int = 3, min_n: int = 1,
                 window: int = 1024):
        assert 1 <= min_n <= max_n
        self.max_n = max_n
        self.min_n = min_n
        self.window = window

    def propose(self, history: Sequence[int], k: int) -> List[int]:
        # numpy-native: the scheduler hands a ZERO-COPY int32 window
        # over its per-slot token log (scheduler._TokenLog) — building
        # a python list of the whole history here would cost O(len)
        # per draft, O(generated^2) over a stream's life. The windowed
        # equality below is vectorized per n (O(max_n * window) work,
        # same bound as the scalar scan) and proposes EXACTLY what the
        # scalar scan did: the continuation of the most recent prior
        # occurrence of the longest matching trailing n-gram.
        h = np.asarray(history)
        L = len(h)
        if k <= 0 or L < self.min_n + 1:
            return []
        base = max(0, L - self.window)
        win = h[base:]
        W = len(win)
        for n in range(min(self.max_n, L - base - 1),
                       self.min_n - 1, -1):
            tail = win[-n:]
            # candidate starts j = 0 .. W - n - 1 (the tail itself,
            # at j = W - n, is excluded); hit <=> win[j:j+n] == tail
            hit = np.ones((W - n,), bool)
            for o in range(n):
                hit &= win[o:W - n + o] == tail[o]
            idx = np.nonzero(hit)[0]
            if len(idx):
                j = int(idx[-1])           # most recent occurrence
                return [int(t) for t in win[j + n:j + n + k]]
        return []


# ----------------------------------------------------------------------
# device-side acceptance (called inside the engine's jitted verify
# programs; jax imported lazily so host-only users of this module —
# the drafter — stay jax-free)
# ----------------------------------------------------------------------


def accept_greedy(tokens, nxt, q_lens):
    """Greedy acceptance over one verify window. tokens: [B, S] — the
    window fed to the forward (seed token at column 0, drafts after);
    nxt: [B, S] — per-position argmax of the verify logits (nxt[:, s]
    is the model's token AFTER consuming tokens[:, :s+1]); q_lens: [B]
    valid window lengths. Returns (n_emit [B] — seed + accepted-draft
    count, 1..q_lens; t0_next [B] — the corrected token following the
    kept prefix, the next step's seed)."""
    import jax.numpy as jnp
    B, S = tokens.shape
    ok = (tokens[:, 1:] == nxt[:, :-1]) \
        & (jnp.arange(1, S)[None] < q_lens[:, None])
    acc = jnp.sum(jnp.cumprod(ok.astype(jnp.int32), axis=1), axis=1)
    n_emit = 1 + acc
    t0_next = jnp.take_along_axis(nxt, acc[:, None], axis=1)[:, 0]
    return n_emit, t0_next


def target_probs(logits, sampling: str, params: dict):
    """The TARGET next-token distribution the spec-off sampler defines:
    temperature-scaled softmax over the filtered support, built from
    the SAME filtering helpers the samplers use (models/utils.py
    top_k_support / top_p_masked_logits) so the two can never
    desynchronize — leftover rejection sampling is exact only against
    the exact sampler distribution. logits: [..., V] (any leading
    batch dims). temperature must be > 0 (0 degenerates to the greedy
    path, handled by the caller)."""
    import jax
    import jax.numpy as jnp
    from triton_dist_tpu.models.utils import (top_k_support,
                                              top_p_masked_logits)
    temp = max(params["temperature"], 0.0)
    assert temp > 0.0, "temperature 0 is the greedy acceptance path"
    if sampling == "top_k":
        topv, topi = top_k_support(logits, params["k"], temp)
        p = jax.nn.softmax(topv, axis=-1)
        return jnp.put_along_axis(jnp.zeros_like(logits), topi, p,
                                  axis=-1, inplace=False)
    if sampling == "top_p":
        return jax.nn.softmax(
            top_p_masked_logits(logits, params["p"], temp), axis=-1)
    raise ValueError(f"unknown sampling mode {sampling!r}")


def accept_sampled(keys, probs, tokens, q_lens):
    """Leftover-distribution rejection sampling over one verify window
    (Leviathan et al. specialized to a point-mass draft). keys: [B]
    per-slot PRNG keys; probs: [B, S, V] — probs[b, s] is the target
    distribution AFTER consuming tokens[b, :s+1]; tokens: [B, S]
    window (seed + drafts); q_lens: [B]. Per slot: draft d_i
    (= tokens[:, i], i >= 1) is accepted while u_i < p_{i-1}(d_i); the
    next seed token is drawn from p_{acc} — zeroed at the rejected
    draft and renormalized when one was rejected (the leftover), plain
    p_{acc} when every draft was accepted. Returns (n_emit [B],
    t0_next [B], keys' [B])."""
    import jax
    import jax.numpy as jnp
    B, S, V = probs.shape

    def one(key, p, toks, qlen):
        key, ku, ks = jax.random.split(key, 3)
        u = jax.random.uniform(ku, (S - 1,))
        d = toks[1:]
        p_d = jnp.take_along_axis(p[:-1], d[:, None], axis=1)[:, 0]
        ok = (jnp.arange(1, S) < qlen) & (u < p_d)
        acc = jnp.sum(jnp.cumprod(ok.astype(jnp.int32)))
        all_acc = acc == qlen - 1
        p_next = p[acc]
        rej = toks[jnp.minimum(acc + 1, S - 1)]
        p_left = jnp.where(all_acc, p_next,
                           p_next * (jnp.arange(V) != rej))
        p_left = p_left / jnp.maximum(jnp.sum(p_left), 1e-30)
        t0n = jax.random.categorical(ks, jnp.log(p_left))
        return 1 + acc, t0n.astype(toks.dtype), key

    return jax.vmap(one)(keys, probs, tokens, q_lens)
