"""Shared-prefix KV cache: radix-tree page reuse with refcounts,
copy-on-write, and LRU eviction.

The serving stack's missing policy layer over the paged pool
(kernels/paged_kv.py mechanics + kv_cache.PagedSlotCache layout): in a
multi-tenant server most prefill work is re-computing KV for prompts
that share a system prompt or few-shot header. vLLM's PagedAttention
makes physical sharing cheap (a page-granular pool behind per-slot
tables); SGLang's RadixAttention turns that sharing into AUTOMATIC
cross-request reuse by keying a radix tree on token ids. This module is
that pair for the TPU serving stack:

- `RefcountedPages`: a refcount layer over the hardened `PageAllocator`
  free list. A physical page may back many slots' page tables AND many
  tree nodes at once; it returns to the free list only at refcount
  zero. Pages are handed out in [Hkv] GROUPS (one page per kv-head
  stream of a logical tile) because one page id means the same row in
  every layer's pool (PagedSlotCache) — a group is the sharing unit.

- `RadixPrefixTree`: token-granular radix tree whose nodes carry the
  page groups backing their span. Matching a new prompt returns the
  longest cached prefix and the groups to map read-only into the
  slot's table; the LAST group is only partially valid when the match
  ends mid-page — the admission copy-on-writes it into a fresh page
  (the boundary page will receive the diverging request's own writes,
  which must never touch the shared original). Node splits on insert
  may leave a boundary page referenced by two nodes — refcounts make
  that safe. Retired sequences (prompt + generated) are inserted back,
  donating the slot's page refs to the tree.

- LRU eviction: when an admission would exhaust the pool, the least
  recently matched leaves are evicted until enough pages free up (or
  nothing evictable remains, and the admission is rejected). Evicting
  a node only drops the TREE's refs — pages still mapped by in-flight
  slots survive until those slots retire.

Exactness contract (tests/test_prefix_cache.py): reused prefix KV is
bitwise the KV the donor request computed for the same (token, position)
pairs, and the suffix forward runs the same program as a cache-off
admission with kv_start as traced data — so cache-on token streams are
bitwise identical to cache-off, greedy and sampled, including under
eviction pressure.

All host-side numpy: policy changes page TABLES (data), never programs.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from triton_dist_tpu.kernels.paged_kv import PageAllocator


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


class PoolExhausted(ValueError):
    """A paged admission could not get pages even after LRU eviction.

    Raised (instead of a generic ValueError) so the scheduler can tell
    RECOVERABLE pressure — preempt a victim slot and retry — from the
    hard rejections (over-capacity request, empty prompt) that no
    amount of preemption can fix. The chaos harness
    (runtime/chaos.py::FaultInjector) raises it too, to force the
    preemption path without actually draining the pool."""


def _common_prefix(a: np.ndarray, b: np.ndarray) -> int:
    L = min(len(a), len(b))
    if L == 0:
        return 0
    neq = np.nonzero(a[:L] != b[:L])[0]
    return int(neq[0]) if len(neq) else L


class RefcountedPages:
    """Refcounting layer over the PageAllocator free list (the
    "physical page backs many tables" half of the design). The trash
    page is reserved at construction and never refcounted — it is the
    write sink for retired slots, not storage."""

    def __init__(self, num_pages: int, n_kv_heads: int):
        self._alloc = PageAllocator(num_pages)
        self.n_kv_heads = n_kv_heads
        self._ref: Dict[int, int] = {}
        self.trash = self._alloc.alloc(1)[0]

    @property
    def num_pages(self) -> int:
        return self._alloc.num_pages

    @property
    def available(self) -> int:
        return self._alloc.available

    @property
    def pages_in_use(self) -> int:
        return len(self._ref)

    @property
    def outstanding(self) -> int:
        """Pages held out of the free list (refcounted pages + the
        reserved trash page). Conservation invariant — the chaos
        harness's no-leak check (tests/test_resilience.py):
        ``available + outstanding == num_pages`` after ANY sequence of
        admissions, retirements, preemptions, evictions, and faults."""
        return self._alloc.outstanding

    def alloc_group(self) -> np.ndarray:
        """One fresh writable group ([Hkv] page ids at refcount 1)."""
        g = np.asarray(self._alloc.alloc(self.n_kv_heads), np.int32)
        for p in g:
            self._ref[int(p)] = 1
        return g

    def retain(self, group) -> None:
        for p in group:
            self._ref[int(p)] += 1

    def release(self, group) -> None:
        """Drop one ref per page of the group; pages at zero go back to
        the free list (the allocator re-checks double-frees)."""
        freed = []
        for p in group:
            p = int(p)
            c = self._ref[p] - 1
            if c:
                self._ref[p] = c
            else:
                del self._ref[p]
                freed.append(p)
        if freed:
            self._alloc.free(freed)

    def refcount(self, page) -> int:
        return self._ref.get(int(page), 0)


class _Node:
    """One radix-tree edge: tokens `key` spanning absolute positions
    [start, start + len(key)), backed by `groups` — one [Hkv] page
    group per page index floor(start/page) .. ceil(end/page)-1. When
    start is mid-page the first group is a page SHARED in span with the
    parent's last group (the same physical page after a pure split, or
    the diverging request's copy-on-write page)."""

    __slots__ = ("parent", "children", "start", "key", "groups",
                 "last_use")

    def __init__(self, parent: Optional["_Node"], start: int,
                 key: np.ndarray, groups: List[np.ndarray]):
        self.parent = parent
        self.children: Dict[int, "_Node"] = {}
        self.start = start
        self.key = key
        self.groups = groups
        self.last_use = 0


class RadixPrefixTree:
    """Token-keyed radix tree over the refcounted page pool. Each node
    holds one pool ref per group it references; matching never touches
    refcounts (callers retain what they map)."""

    def __init__(self, pool: RefcountedPages, page: int):
        self.pool = pool
        self.page = page
        self.root = _Node(None, 0, np.zeros((0,), np.int32), [])
        self._tick = 0
        self.evictions = 0

    def _touch(self, node: _Node) -> None:
        self._tick += 1
        node.last_use = self._tick

    # ------------------------------------------------------------------
    # match
    # ------------------------------------------------------------------

    def match(self, tokens, cap: Optional[int] = None
              ) -> Tuple[int, List[np.ndarray]]:
        """Longest cached prefix of `tokens` (≤ cap): returns
        (m, groups) with groups covering page indices
        0 .. ceil(m/page)-1. When m is mid-page the last group is only
        partially valid — the caller must copy-on-write it before the
        slot writes anything. Touches the matched path for LRU."""
        tokens = np.asarray(tokens, np.int32)
        node = self.root
        m = 0
        groups: List[np.ndarray] = []
        while m < len(tokens):
            child = node.children.get(int(tokens[m]))
            if child is None:
                break
            L = _common_prefix(child.key, tokens[m:m + len(child.key)])
            if child.start % self.page:
                # the child's first group is its own complete version
                # of the boundary page (see _Node docstring) — it
                # overrides the parent's
                groups.pop()
            first_pg = child.start // self.page
            n_pg = _ceil_div(child.start + L, self.page) - first_pg
            groups.extend(child.groups[:n_pg])
            m += L
            self._touch(child)
            if L < len(child.key):
                break
            node = child
        if cap is not None and m > cap:
            m = cap
            groups = groups[:_ceil_div(m, self.page)]
        return m, groups

    # ------------------------------------------------------------------
    # insert
    # ------------------------------------------------------------------

    def insert(self, tokens, groups_by_page: List[np.ndarray]) -> int:
        """Insert a finished sequence (prompt + generated): walk the
        matched path, split a node if the sequence diverges inside it,
        and attach the unmatched suffix as a new leaf whose groups are
        the caller's pages for that span (the tree RETAINS them — the
        caller keeps its own refs and releases them at retire). Returns
        the number of newly cached tokens."""
        tokens = np.asarray(tokens, np.int32)
        node = self.root
        m = 0
        while m < len(tokens):
            child = node.children.get(int(tokens[m]))
            if child is None:
                leaf_groups = [
                    np.asarray(g, np.int32).copy()
                    for g in groups_by_page[m // self.page:
                                            _ceil_div(len(tokens),
                                                      self.page)]]
                leaf = _Node(node, m, tokens[m:].copy(), leaf_groups)
                for g in leaf_groups:
                    self.pool.retain(g)
                node.children[int(tokens[m])] = leaf
                self._touch(leaf)
                return len(tokens) - m
            L = _common_prefix(child.key, tokens[m:m + len(child.key)])
            self._touch(child)
            if L < len(child.key):
                if m + L == len(tokens):
                    return 0          # sequence ends inside the node
                child = self._split(child, L)    # descend into the head
            m += L
            node = child
        return 0

    def _split(self, child: _Node, L: int) -> "_Node":
        """Split `child` at key offset L into head [start, start+L) +
        tail [start+L, end): the tail keeps the node object (so its
        children stay wired), the head takes its place under the
        parent. A mid-page split leaves the boundary page referenced by
        BOTH nodes — one extra pool ref covers the second reference."""
        s = child.start
        cut = s + L
        first_pg = s // self.page
        head_groups = child.groups[:_ceil_div(cut, self.page) - first_pg]
        head = _Node(child.parent, s, child.key[:L], head_groups)
        head.last_use = child.last_use
        child.parent.children[int(child.key[0])] = head
        tail_first = cut // self.page
        child.groups = child.groups[tail_first - first_pg:]
        child.start = cut
        child.key = child.key[L:]
        child.parent = head
        head.children[int(child.key[0])] = child
        if cut % self.page:
            # boundary page now appears in head.groups[-1] AND
            # child.groups[0] (same physical page)
            self.pool.retain(head.groups[-1])
        return head

    # ------------------------------------------------------------------
    # LRU eviction
    # ------------------------------------------------------------------

    def evict_until(self, pages_needed: int) -> bool:
        """Evict least-recently-matched leaves until the allocator has
        `pages_needed` free pages (or nothing evictable remains —
        returns False, the admission's rejection signal). Releasing a
        leaf's groups only drops the tree's refs; a page still mapped
        read-only by an in-flight slot stays allocated until that slot
        retires.

        One tree walk seeds a min-heap of leaves by last_use; a parent
        joins the heap the moment its last child is evicted — O(n +
        k log n) for k evictions instead of a full rescan per leaf."""
        import heapq
        if self.pool.available >= pages_needed:
            return True
        heap = []
        stack = [self.root]
        while stack:
            nd = stack.pop()
            if nd is not self.root and not nd.children:
                heap.append((nd.last_use, id(nd), nd))
            stack.extend(nd.children.values())
        heapq.heapify(heap)
        while self.pool.available < pages_needed and heap:
            _, _, leaf = heapq.heappop(heap)
            parent = leaf.parent
            for g in leaf.groups:
                self.pool.release(g)
            del parent.children[int(leaf.key[0])]
            self.evictions += 1
            if parent is not self.root and not parent.children:
                heapq.heappush(heap, (parent.last_use, id(parent),
                                      parent))
        return self.pool.available >= pages_needed

    # introspection (tests)

    def nodes(self) -> List[_Node]:
        out = []
        stack = [self.root]
        while stack:
            nd = stack.pop()
            if nd is not self.root:
                out.append(nd)
            stack.extend(nd.children.values())
        return out


class PrefixCache:
    """The serving-facing facade: pool + tree + hit/skip counters.
    `enabled=False` keeps the identical pool/alloc path but never
    matches or inserts — the cache-off configuration runs the SAME
    device programs, which is what makes the bitwise cache-on/off
    comparison meaningful."""

    def __init__(self, num_pages: int, n_kv_heads: int, page: int, *,
                 enabled: bool = True):
        self.pool = RefcountedPages(num_pages, n_kv_heads)
        self.page = page
        self.enabled = enabled
        self.tree = RadixPrefixTree(self.pool, page)
        self.admissions = 0
        self.hits = 0
        self.prompt_tokens = 0
        self.prefill_tokens_skipped = 0
        self.tokens_inserted = 0

    def lookup(self, prompt) -> Tuple[int, List[np.ndarray]]:
        """Longest cached prefix for an admission (capped to n-1: the
        last prompt token is always recomputed so the slot has fresh
        next-token logits)."""
        if not self.enabled:
            return 0, []
        return self.tree.match(prompt, cap=max(len(prompt) - 1, 0))

    def record(self, n_prompt: int, n_matched: int) -> None:
        """Count one SUCCESSFUL admission (rejected requests don't
        skew the hit/skip rates)."""
        self.admissions += 1
        self.prompt_tokens += n_prompt
        self.prefill_tokens_skipped += n_matched
        self.hits += bool(n_matched)

    def insert(self, tokens, groups_by_page) -> int:
        if not self.enabled:
            return 0
        new = self.tree.insert(tokens, groups_by_page)
        self.tokens_inserted += new
        return new

    def ensure_pages(self, n_pages: int) -> bool:
        """Free-list headroom for an admission: evict LRU leaves when
        short. False = not satisfiable (reject the admission)."""
        if self.pool.available >= n_pages:
            return True
        if not self.enabled:
            return False
        return self.tree.evict_until(n_pages)

    def stats(self) -> dict:
        total = max(self.prompt_tokens, 1)
        return {
            "enabled": self.enabled,
            "admissions": self.admissions,
            "hits": self.hits,
            "hit_rate": self.hits / max(self.admissions, 1),
            "prompt_tokens": self.prompt_tokens,
            "prefill_tokens_skipped": self.prefill_tokens_skipped,
            "prefill_skip_frac": self.prefill_tokens_skipped / total,
            "evictions": self.tree.evictions,
            "pages_in_use": self.pool.pages_in_use,
            "pages_free": self.pool.available,
            "pages_outstanding": self.pool.outstanding,
        }
