"""Shared-prefix KV cache: radix-tree page reuse with refcounts,
copy-on-write, and LRU eviction.

The serving stack's missing policy layer over the paged pool
(kernels/paged_kv.py mechanics + kv_cache.PagedSlotCache layout): in a
multi-tenant server most prefill work is re-computing KV for prompts
that share a system prompt or few-shot header. vLLM's PagedAttention
makes physical sharing cheap (a page-granular pool behind per-slot
tables); SGLang's RadixAttention turns that sharing into AUTOMATIC
cross-request reuse by keying a radix tree on token ids. This module is
that pair for the TPU serving stack:

- `RefcountedPages`: a refcount layer over the hardened `PageAllocator`
  free list. A physical page may back many slots' page tables AND many
  tree nodes at once; it returns to the free list only at refcount
  zero. Pages are handed out in [Hkv] GROUPS (one page per kv-head
  stream of a logical tile) because one page id means the same row in
  every layer's pool (PagedSlotCache) — a group is the sharing unit.

- `RadixPrefixTree`: token-granular radix tree whose nodes carry the
  page groups backing their span. Matching a new prompt returns the
  longest cached prefix and the groups to map read-only into the
  slot's table; the LAST group is only partially valid when the match
  ends mid-page — the admission copy-on-writes it into a fresh page
  (the boundary page will receive the diverging request's own writes,
  which must never touch the shared original). Node splits on insert
  may leave a boundary page referenced by two nodes — refcounts make
  that safe. Retired sequences (prompt + generated) are inserted back,
  donating the slot's page refs to the tree.

- LRU eviction: when an admission would exhaust the pool, the least
  recently matched leaves are evicted until enough pages free up (or
  nothing evictable remains, and the admission is rejected). Evicting
  a node only drops the TREE's refs — pages still mapped by in-flight
  slots survive until those slots retire.

- Host-RAM tier (models/kv_tier.py `HostKVPool` — the SGLang/HiCache
  hierarchical-cache layer; the design Mooncake, arXiv:2407.00079,
  runs in production KV-centric serving and CachedAttention,
  arXiv:2403.19708, applies to multi-turn sessions): with
  `host_pool_pages` set, eviction DEMOTES a span instead of dropping
  it — the node's page content is extracted to pinned host memory
  (one d2h gather across every layer's pool, Engine.extract_pages_
  host) and its device refs released; the node stays in the tree with
  a HOST residency bit (`_Node.host` = the pool handle). A later
  lookup on a host-resident path PROMOTES before matching: fresh
  device pages are allocated (evicting/demoting colder spans if
  needed — the matched path is pinned) and filled by one h2d install
  program (Engine.restore_pages_host), after which the node is an
  ordinary DEVICE node again and the existing CoW/refcount machinery
  applies untouched. True drop happens only from the host tier's own
  LRU (bounded by host_pool_pages). The d2h -> h2d round trip moves
  raw pool-dtype bytes, so warm-from-host streams are BITWISE equal
  to HBM-hit and cold-recompute streams (tests/test_kv_tier.py).

- KV FORK (parallel sampling, models/structured.py + scheduler
  `Request(n=N)`): `PagedDecodeSlots.fork` is the third consumer of
  this module's refcount/CoW machinery — a fork child RETAINS the
  parent slot's full prompt page groups (refcount+1, mapped into its
  own table exactly like a tree hit) and copy-on-writes the
  partially-filled boundary page, so n decode streams share one
  prompt's physical KV. The fork records its skipped prefill through
  the same `record()` accounting a tree hit uses, and a fork child
  that cannot fork NOW falls back to ordinary admission whose tree
  match rebuilds the identical mapping — which is what keeps forked
  and sequential streams bitwise (tests/test_structured.py).

Exactness contract (tests/test_prefix_cache.py): reused prefix KV is
bitwise the KV the donor request computed for the same (token, position)
pairs, and the suffix forward runs the same program as a cache-off
admission with kv_start as traced data — so cache-on token streams are
bitwise identical to cache-off, greedy and sampled, including under
eviction pressure.

All host-side numpy: policy changes page TABLES (data), never programs.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from triton_dist_tpu.kernels.paged_kv import PageAllocator
from triton_dist_tpu.models.kv_tier import HostKVPool


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


class PoolExhausted(ValueError):
    """A paged admission could not get pages even after LRU eviction.

    Raised (instead of a generic ValueError) so the scheduler can tell
    RECOVERABLE pressure — preempt a victim slot and retry — from the
    hard rejections (over-capacity request, empty prompt) that no
    amount of preemption can fix. The chaos harness
    (runtime/chaos.py::FaultInjector) raises it too, to force the
    preemption path without actually draining the pool."""


def _common_prefix(a: np.ndarray, b: np.ndarray) -> int:
    L = min(len(a), len(b))
    if L == 0:
        return 0
    neq = np.nonzero(a[:L] != b[:L])[0]
    return int(neq[0]) if len(neq) else L


class RefcountedPages:
    """Refcounting layer over the PageAllocator free list (the
    "physical page backs many tables" half of the design). The trash
    page is reserved at construction and never refcounted — it is the
    write sink for retired slots, not storage.

    shards > 1 (sequence-parallel serving): the allocator partitions
    the id space per sp shard and rotates fresh groups across shards
    (kernels/paged_kv.PageAllocator) — this layer stays id-blind, it
    only surfaces the per-shard accounting the telemetry and the
    per-shard zero-leak invariant read."""

    def __init__(self, num_pages: int, n_kv_heads: int,
                 shards: int = 1):
        self._alloc = PageAllocator(num_pages, shards=shards)
        self.n_kv_heads = n_kv_heads
        self._ref: Dict[int, int] = {}
        # shard 0 allocates first, so the trash is page 0 of shard 0
        # whatever the shard count
        self.trash = self._alloc.alloc(1)[0]

    @property
    def num_pages(self) -> int:
        return self._alloc.num_pages

    @property
    def shards(self) -> int:
        return self._alloc.shards

    @property
    def pages_per_shard(self) -> int:
        return self._alloc.pages_per_shard

    @property
    def available(self) -> int:
        return self._alloc.available

    @property
    def available_by_shard(self):
        return self._alloc.available_by_shard

    @property
    def outstanding_by_shard(self):
        return self._alloc.outstanding_by_shard

    @property
    def pages_in_use(self) -> int:
        return len(self._ref)

    @property
    def pages_in_use_by_shard(self):
        """Refcounted (slot- or tree-referenced) pages per sp shard —
        the `sp_pages_resident{shard=}` gauge; 0 on every shard at
        idle IS the per-shard zero-leak invariant."""
        out = [0] * self._alloc.shards
        for p in self._ref:
            out[self._alloc.shard_of(p)] += 1
        return out

    @property
    def outstanding(self) -> int:
        """Pages held out of the free list (refcounted pages + the
        reserved trash page). Conservation invariant — the chaos
        harness's no-leak check (tests/test_resilience.py):
        ``available + outstanding == num_pages`` after ANY sequence of
        admissions, retirements, preemptions, evictions, and faults."""
        return self._alloc.outstanding

    def alloc_group(self) -> np.ndarray:
        """One fresh writable group ([Hkv] page ids at refcount 1)."""
        g = np.asarray(self._alloc.alloc(self.n_kv_heads), np.int32)
        for p in g:
            self._ref[int(p)] = 1
        return g

    def retain(self, group) -> None:
        for p in group:
            p = int(p)
            if p not in self._ref:
                raise ValueError(
                    f"retain of unreferenced page {p}: only pages live "
                    f"from alloc_group (refcount >= 1) can gain refs — "
                    f"a retain after the last release would resurrect "
                    f"a page the allocator may have re-issued")
            self._ref[p] += 1

    def release(self, group) -> None:
        """Drop one ref per page of the group; pages at zero go back to
        the free list (the allocator re-checks double-frees). A release
        past zero raises BEFORE touching the pool — the silent failure
        mode is a page freed while a radix-tree node still maps it."""
        freed = []
        for p in group:
            p = int(p)
            if p not in self._ref:
                raise ValueError(
                    f"refcount underflow: release of page {p} at "
                    f"refcount 0 (already fully released, or never "
                    f"allocated) — some holder released a group twice")
            c = self._ref[p] - 1
            if c:
                self._ref[p] = c
            else:
                del self._ref[p]
                freed.append(p)
        if freed:
            self._alloc.free(freed)

    def refcount(self, page) -> int:
        return self._ref.get(int(page), 0)


class _Node:
    """One radix-tree edge: tokens `key` spanning absolute positions
    [start, start + len(key)), backed by `groups` — one [Hkv] page
    group per page index floor(start/page) .. ceil(end/page)-1. When
    start is mid-page the first group is a page SHARED in span with the
    parent's last group (the same physical page after a pure split, or
    the diverging request's copy-on-write page).

    Residency state machine (host tier, models/kv_tier.py): `host` is
    None for a DEVICE-resident node (groups hold device page ids) and
    a HostKVPool handle for a HOST-resident one (groups is empty — the
    span's bytes live in the host pool until promotion restores them
    into fresh device pages, or the host LRU truly drops them). Host
    nodes are opaque to insert (no descend, no split), so no DEVICE
    descendant can ever appear below one — the invariant that makes a
    host drop a clean subtree removal."""

    __slots__ = ("parent", "children", "start", "key", "groups",
                 "last_use", "host")

    def __init__(self, parent: Optional["_Node"], start: int,
                 key: np.ndarray, groups: List[np.ndarray]):
        self.parent = parent
        self.children: Dict[int, "_Node"] = {}
        self.start = start
        self.key = key
        self.groups = groups
        self.last_use = 0
        self.host: Optional[int] = None


class RadixPrefixTree:
    """Token-keyed radix tree over the refcounted page pool. Each node
    holds one pool ref per group it references; matching never touches
    refcounts (callers retain what they map)."""

    def __init__(self, pool: RefcountedPages, page: int, *,
                 host_pool=None, fault=None, telemetry=None):
        self.pool = pool
        self.page = page
        # optional runtime/telemetry.py bundle: demote/promote/drop
        # show up as timeline instants when tracing is on (trace-off
        # is a guarded no-op inside Telemetry.instant)
        self.tele = telemetry
        self.root = _Node(None, 0, np.zeros((0,), np.int32), [])
        self._tick = 0
        self.evictions = 0
        # host tier (models/kv_tier.py): the bounded host pool, the
        # engine-wired copy callbacks (PrefixCache.attach_host_tier),
        # the handle -> node map driving true drops, and the pin set
        # protecting a promotion's matched path from the demotions its
        # own page allocation can trigger. fault: chaos hook
        # (runtime/chaos.py::FaultInjector.host_demotion) forcing the
        # true-drop path without actually filling the host pool.
        self.host_pool = host_pool
        self.fault = fault
        self._extract_fn = None    # groups -> payload (d2h gather)
        self._restore_fn = None    # (payload, groups) -> None (h2d)
        # per-promote_path restore time (alloc + h2d install only —
        # NOT the victim demotions evict_until may run to make room),
        # accumulated here so PrefixCache's EMA reports what the
        # gauge's name claims
        self.restore_ms_accum = 0.0
        self._host_nodes: Dict[int, _Node] = {}
        self._pinned: Dict[int, _Node] = {}
        self.demotions = 0
        self.promotions = 0
        self.host_drops = 0

    def _touch(self, node: _Node) -> None:
        self._tick += 1
        node.last_use = self._tick

    # ------------------------------------------------------------------
    # match
    # ------------------------------------------------------------------

    def match(self, tokens, cap: Optional[int] = None
              ) -> Tuple[int, List[np.ndarray]]:
        """Longest cached prefix of `tokens` (≤ cap): returns
        (m, groups) with groups covering page indices
        0 .. ceil(m/page)-1. When m is mid-page the last group is only
        partially valid — the caller must copy-on-write it before the
        slot writes anything. Touches the matched path for LRU.

        A HOST-resident child ends the match (its pages are not on the
        device): callers that want host spans promoted first run
        promote_path (PrefixCache.lookup does) — after promotion the
        node is an ordinary device node and matches normally."""
        tokens = np.asarray(tokens, np.int32)
        node = self.root
        m = 0
        groups: List[np.ndarray] = []
        while m < len(tokens):
            child = node.children.get(int(tokens[m]))
            if child is None or child.host is not None:
                break
            L = _common_prefix(child.key, tokens[m:m + len(child.key)])
            if child.start % self.page:
                # the child's first group is its own complete version
                # of the boundary page (see _Node docstring) — it
                # overrides the parent's
                groups.pop()
            first_pg = child.start // self.page
            n_pg = _ceil_div(child.start + L, self.page) - first_pg
            groups.extend(child.groups[:n_pg])
            m += L
            self._touch(child)
            if L < len(child.key):
                break
            node = child
        if cap is not None and m > cap:
            m = cap
            groups = groups[:_ceil_div(m, self.page)]
        return m, groups

    # ------------------------------------------------------------------
    # insert
    # ------------------------------------------------------------------

    def insert(self, tokens, groups_by_page: List[np.ndarray]) -> int:
        """Insert a finished sequence (prompt + generated): walk the
        matched path, split a node if the sequence diverges inside it,
        and attach the unmatched suffix as a new leaf whose groups are
        the caller's pages for that span (the tree RETAINS them — the
        caller keeps its own refs and releases them at retire). Returns
        the number of newly cached tokens."""
        tokens = np.asarray(tokens, np.int32)
        node = self.root
        m = 0
        while m < len(tokens):
            child = node.children.get(int(tokens[m]))
            if child is not None and child.host is not None:
                # host-resident nodes are opaque to insert (splitting
                # or descending would need pages that are not on the
                # device): stop — caching the remainder is best-effort
                # bookkeeping, never a correctness requirement
                return 0
            if child is None:
                leaf_groups = [
                    np.asarray(g, np.int32).copy()
                    for g in groups_by_page[m // self.page:
                                            _ceil_div(len(tokens),
                                                      self.page)]]
                leaf = _Node(node, m, tokens[m:].copy(), leaf_groups)
                for g in leaf_groups:
                    self.pool.retain(g)
                node.children[int(tokens[m])] = leaf
                self._touch(leaf)
                return len(tokens) - m
            L = _common_prefix(child.key, tokens[m:m + len(child.key)])
            self._touch(child)
            if L < len(child.key):
                if m + L == len(tokens):
                    return 0          # sequence ends inside the node
                child = self._split(child, L)    # descend into the head
            m += L
            node = child
        return 0

    def _split(self, child: _Node, L: int) -> "_Node":
        """Split `child` at key offset L into head [start, start+L) +
        tail [start+L, end): the tail keeps the node object (so its
        children stay wired), the head takes its place under the
        parent. A mid-page split leaves the boundary page referenced by
        BOTH nodes — one extra pool ref covers the second reference."""
        s = child.start
        cut = s + L
        first_pg = s // self.page
        head_groups = child.groups[:_ceil_div(cut, self.page) - first_pg]
        head = _Node(child.parent, s, child.key[:L], head_groups)
        head.last_use = child.last_use
        child.parent.children[int(child.key[0])] = head
        tail_first = cut // self.page
        child.groups = child.groups[tail_first - first_pg:]
        child.start = cut
        child.key = child.key[L:]
        child.parent = head
        head.children[int(child.key[0])] = child
        if cut % self.page:
            # boundary page now appears in head.groups[-1] AND
            # child.groups[0] (same physical page)
            self.pool.retain(head.groups[-1])
        return head

    # ------------------------------------------------------------------
    # LRU eviction
    # ------------------------------------------------------------------

    def evict_until(self, pages_needed: int) -> bool:
        """Evict least-recently-matched device spans until the
        allocator has `pages_needed` free pages (or nothing evictable
        remains — returns False, the admission's rejection signal).
        With a host tier attached each victim is DEMOTED (d2h snapshot
        + device refs released, node stays in the tree host-resident)
        and only falls back to a true drop when demotion is refused
        (host pool too small for the span, or a chaos fault).
        Releasing a span's groups only drops the tree's refs; a page
        still mapped read-only by an in-flight slot stays allocated
        until that slot retires.

        One tree walk seeds a min-heap of nodes whose SUBTREES hold no
        other device pages (plain leaves, and parents whose children
        were all demoted earlier) by last_use; a parent joins the heap
        the moment its last device-holding child is demoted or dropped
        — O(n + k log n) for k evictions instead of a full rescan.
        Nodes pinned by an in-flight promotion are skipped."""
        import heapq
        if self.pool.available >= pages_needed:
            return True
        heap = []
        order = []
        stack = [self.root]
        while stack:
            nd = stack.pop()
            order.append(nd)
            stack.extend(nd.children.values())
        # children appear after their parent in the DFS order, so the
        # reverse sweep sees children first: a node "blocks" its parent
        # while its subtree still holds device pages
        blockers: Dict[int, int] = {}
        subtree_dev: Dict[int, bool] = {}
        for nd in reversed(order):
            pend = sum(1 for c in nd.children.values()
                       if subtree_dev[id(c)])
            blockers[id(nd)] = pend
            subtree_dev[id(nd)] = bool(nd.groups) or pend > 0
            if nd is not self.root and nd.groups and pend == 0:
                heap.append((nd.last_use, id(nd), nd))
        heapq.heapify(heap)
        while self.pool.available < pages_needed and heap:
            _, _, nd = heapq.heappop(heap)
            if id(nd) in self._pinned:
                continue
            parent = nd.parent
            if self._try_demote(nd):
                self.demotions += 1
                if self.tele is not None:
                    self.tele.instant("kv_demote")
            else:
                self._drop_node(nd)
                self.evictions += 1
                if self.tele is not None:
                    self.tele.instant("kv_evict")
            blockers[id(parent)] -= 1
            if parent is not self.root and parent.groups \
                    and blockers[id(parent)] == 0:
                heapq.heappush(heap, (parent.last_use, id(parent),
                                      parent))
        return self.pool.available >= pages_needed

    def _try_demote(self, nd: _Node) -> bool:
        """Demote one device span to the host tier: make room in the
        host pool (true-dropping ITS least-recently-used spans — the
        only place KV is actually forgotten), snapshot the span's pages
        (the wired d2h gather), release the device refs, and flip the
        node's residency bit. False = demotion unavailable (no tier,
        span too big for the whole host pool, everything pinned, or a
        chaos-injected host exhaustion) — the caller true-drops."""
        hp = self.host_pool
        if hp is None or self._extract_fn is None or not nd.groups:
            return False
        n_pages = sum(len(g) for g in nd.groups)
        if n_pages > hp.capacity:
            return False
        if self.fault is not None and \
                not getattr(self.fault, "host_demotion",
                            lambda n: True)(n_pages):
            return False
        pinned_handles = {n.host for n in self._pinned.values()
                          if n.host is not None}
        while hp.room < n_pages:
            h = hp.victim(pinned=pinned_handles)
            if h is None:
                return False
            self._drop_host_subtree(self._host_nodes[h])
        payload = self._extract_fn(nd.groups)
        h = hp.put(payload, n_pages=n_pages, n_groups=len(nd.groups))
        self._host_nodes[h] = nd
        for g in nd.groups:
            self.pool.release(g)
        nd.groups = []
        nd.host = h
        return True

    def _drop_node(self, nd: _Node) -> None:
        """True-drop a device span (no tier, or demotion refused):
        release its device refs and remove it from the tree. Any
        children are host-resident (the eligibility sweep guarantees
        the subtree holds no other device pages) and go with it —
        orphaned host spans could never be matched again."""
        for g in nd.groups:
            self.pool.release(g)
        nd.groups = []
        for c in list(nd.children.values()):
            self._drop_host_subtree(c)
        del nd.parent.children[int(nd.key[0])]

    def _drop_host_subtree(self, nd: _Node) -> None:
        """Remove a host-resident node AND its subtree from tree and
        host pool (descendants of a host node are host-resident by the
        insert-opacity invariant — see _Node)."""
        del nd.parent.children[int(nd.key[0])]
        stack = [nd]
        while stack:
            x = stack.pop()
            stack.extend(x.children.values())
            if x.groups:         # defensive: never true by invariant
                for g in x.groups:
                    self.pool.release(g)
                x.groups = []
                self.evictions += 1
            if x.host is not None:
                self.host_pool.drop(x.host)
                del self._host_nodes[x.host]
                x.host = None
                self.host_drops += 1

    # ------------------------------------------------------------------
    # promotion (host -> device)
    # ------------------------------------------------------------------

    def promote_path(self, tokens, cap: int) -> int:
        """Walk the match path of `tokens` (up to `cap`) and PROMOTE
        every host-resident node on it back to device residency, in
        path order, so the match that follows sees ordinary device
        nodes. The whole visited path is PINNED while promoting: the
        page allocation a promotion needs may itself evict/demote, and
        must not cannibalize the spans this lookup is about to map.
        Returns the number of nodes promoted (0 = pure HBM path).
        Stops early when a promotion fails (device pool too small even
        after eviction) — the match then ends at that node, exactly as
        if the span had been dropped."""
        if self.host_pool is None or not self._host_nodes:
            return 0           # nothing demoted: skip the extra walk
        tokens = np.asarray(tokens, np.int32)
        # pre-walk the WHOLE path and pin it before promoting anything:
        # an early promotion's room-making may otherwise true-drop the
        # deeper host spans this same lookup is about to restore
        node, m = self.root, 0
        path: List[_Node] = []
        while m < cap:
            child = node.children.get(int(tokens[m]))
            if child is None:
                break
            L = _common_prefix(child.key, tokens[m:m + len(child.key)])
            if L == 0:
                break
            path.append(child)
            m += L
            if L < len(child.key):
                break
            node = child
        if not any(c.host is not None for c in path):
            return 0
        self._pinned = {id(c): c for c in path}
        try:
            promoted = 0
            for child in path:
                if child.host is not None:
                    if not self._promote(child):
                        break
                    promoted += 1
            return promoted
        finally:
            self._pinned = {}

    def _promote(self, nd: _Node) -> bool:
        """Restore one host span into fresh device pages: free-list
        headroom (evicting/demoting unpinned spans), alloc the groups,
        run the wired h2d install, and flip residency. The host entry
        is popped only after the install is dispatched — a failure
        leaves the span host-resident (and LRU-touched) for the next
        attempt."""
        if self._restore_fn is None:
            return False
        entry = self.host_pool.get(nd.host)        # touches host LRU
        need = entry.n_groups * self.pool.n_kv_heads
        if not self.evict_until(need):
            return False
        groups: List[np.ndarray] = []
        t0 = time.perf_counter()
        try:
            for _ in range(entry.n_groups):
                groups.append(self.pool.alloc_group())
            self._restore_fn(entry.payload, groups)
        except Exception:
            # release-before-raise (the _reserve_pages convention):
            # groups referenced by neither the node nor any slot would
            # otherwise leak past every drain
            for g in groups:
                self.pool.release(g)
            raise
        self.restore_ms_accum += (time.perf_counter() - t0) * 1e3
        self.host_pool.pop(nd.host)
        del self._host_nodes[nd.host]
        nd.host = None
        nd.groups = groups
        self.promotions += 1
        if self.tele is not None:
            self.tele.instant("kv_promote")
        self._touch(nd)
        return True

    # introspection (tests)

    def nodes(self) -> List[_Node]:
        out = []
        stack = [self.root]
        while stack:
            nd = stack.pop()
            if nd is not self.root:
                out.append(nd)
            stack.extend(nd.children.values())
        return out


class PrefixCache:
    """The serving-facing facade: pool + tree + hit/skip counters.
    `enabled=False` keeps the identical pool/alloc path but never
    matches or inserts — the cache-off configuration runs the SAME
    device programs, which is what makes the bitwise cache-on/off
    comparison meaningful."""

    def __init__(self, num_pages: int, n_kv_heads: int, page: int, *,
                 enabled: bool = True, host_pool_pages: int = 0,
                 fault=None, telemetry=None, shards: int = 1):
        """host_pool_pages > 0 attaches the host-RAM capacity tier
        (models/kv_tier.py): eviction demotes spans to a host pool of
        that many (device-page-sized) buffers instead of dropping, and
        lookups on host-resident paths promote them back. The owner
        must also wire the device copy callbacks (attach_host_tier) —
        until then demotion stays disabled and eviction drops as
        before. fault: chaos hook (runtime/chaos.py::FaultInjector)
        whose host_demotion() can force the true-drop path.

        telemetry (runtime/telemetry.py): the hit/skip counters below
        live in its metrics registry — PagedDecodeSlots passes the
        scheduler's bundle so one stats() registry snapshot covers
        the cache; a bare PrefixCache gets a private registry.

        shards: the sp mesh size of a SEQUENCE-PARALLEL pool
        (kv_cache.PagedSlotCache SP SHARDING) — the allocator then
        partitions the page-id space per shard and rotates fresh
        groups across shards, and stats() grows per-shard
        `sp_pages_resident{shard=}` gauges (resident 0 on every shard
        at idle is the per-shard zero-leak invariant)."""
        from triton_dist_tpu.runtime.telemetry import Telemetry
        self.pool = RefcountedPages(num_pages, n_kv_heads,
                                    shards=shards)
        self.page = page
        self.enabled = enabled
        self.tele = telemetry if telemetry is not None else Telemetry()
        self.host = HostKVPool(host_pool_pages) if host_pool_pages \
            else None
        self.tree = RadixPrefixTree(self.pool, page,
                                    host_pool=self.host, fault=fault,
                                    telemetry=self.tele)
        reg = self.tele.registry
        self.admissions = reg.counter(
            "admissions", "successful paged admissions")
        self.hits = reg.counter(
            "hits", "admissions with a non-empty prefix match")
        self.host_hits = reg.counter(
            "host_hits", "lookups that promoted host-resident spans")
        self._g_restore = reg.gauge(
            "restore_latency_ms", "EMA over promoting lookups' h2d "
                                  "restore work")
        self.prompt_tokens = reg.counter(
            "prompt_tokens", "prompt tokens across admissions")
        self.prefill_tokens_skipped = reg.counter(
            "prefill_tokens_skipped", "prompt tokens served from "
                                      "cached prefixes")
        self.tokens_inserted = reg.counter(
            "tokens_inserted", "new tokens donated to the radix tree")

    def attach_host_tier(self, extract, restore) -> None:
        """Wire the device-side copy callbacks into the residency
        machine: `extract(groups) -> payload` gathers the groups'
        pages to host memory (demotion), `restore(payload, groups)`
        installs a payload into freshly allocated device pages
        (promotion). PagedDecodeSlots binds these to
        Engine.extract_pages_host / restore_pages_host over its own
        paged cache."""
        self.tree._extract_fn = extract
        self.tree._restore_fn = restore

    def lookup(self, prompt) -> Tuple[int, List[np.ndarray]]:
        """Longest cached prefix for an admission (capped to n-1: the
        last prompt token is always recomputed so the slot has fresh
        next-token logits). With the host tier attached, host-resident
        spans on the path are PROMOTED first (h2d install into fresh
        pages), so the returned groups are always device pages and the
        caller's CoW/refcount flow is tier-oblivious."""
        if not self.enabled:
            return 0, []
        cap = max(len(prompt) - 1, 0)
        if self.host is not None:
            self.tree.restore_ms_accum = 0.0
            if self.tree.promote_path(prompt, cap):
                self.host_hits.inc()
                # EMA over the pure restore work (alloc + h2d install)
                # of this lookup's promotions — victim-demotion time
                # evict_until spends making room is excluded, so the
                # gauge reports what its name claims
                dt = self.tree.restore_ms_accum
                cur = self._g_restore.value
                self._g_restore.set(dt if cur == 0.0
                                    else 0.9 * cur + 0.1 * dt)
        return self.tree.match(prompt, cap=cap)

    def record(self, n_prompt: int, n_matched: int) -> None:
        """Count one SUCCESSFUL admission (rejected requests don't
        skew the hit/skip rates)."""
        self.admissions.inc()
        self.prompt_tokens.inc(n_prompt)
        self.prefill_tokens_skipped.inc(n_matched)
        self.hits.inc(int(bool(n_matched)))

    def insert(self, tokens, groups_by_page) -> int:
        if not self.enabled:
            return 0
        new = self.tree.insert(tokens, groups_by_page)
        self.tokens_inserted.inc(new)
        return new

    def ensure_pages(self, n_pages: int) -> bool:
        """Free-list headroom for an admission: evict LRU leaves when
        short. False = not satisfiable (reject the admission)."""
        if self.pool.available >= n_pages:
            return True
        if not self.enabled:
            return False
        return self.tree.evict_until(n_pages)

    @property
    def restore_latency_ms(self) -> float:
        """EMA over promoting lookups (registry gauge; the old float
        attribute's read API, kept for callers)."""
        return self._g_restore.value

    def stats(self) -> dict:
        """Hit/skip counters + structural gauges. The counters live in
        the telemetry registry; the structural values (pool occupancy,
        tree/tier counters) are refreshed into registry gauges here so
        a registry snapshot taken right after (ContinuousScheduler.
        stats(), the /metrics exposition) is one consistent cut."""
        reg = self.tele.registry
        total = max(self.prompt_tokens.value, 1)
        with reg.lock:
            reg.gauge("pages_in_use").set(self.pool.pages_in_use)
            reg.gauge("pages_free").set(self.pool.available)
            reg.gauge("pages_outstanding").set(self.pool.outstanding)
            reg.gauge("evictions").set(self.tree.evictions)
            reg.gauge("demotions").set(self.tree.demotions)
            reg.gauge("promotions").set(self.tree.promotions)
            reg.gauge("host_drops").set(self.tree.host_drops)
            host = (self.host.stats() if self.host is not None
                    else HostKVPool.empty_stats())
            for k, v in host.items():
                reg.gauge(k).set(v)
            if self.pool.shards > 1:
                # per-shard residency (sp pools): refcounted pages on
                # each sp shard — resident 0 everywhere at idle IS the
                # per-shard zero-leak invariant
                for s, npg in enumerate(self.pool.pages_in_use_by_shard):
                    reg.gauge(
                        "sp_pages_resident",
                        "refcounted pages per sp shard",
                        labels={"shard": str(s)}).set(npg)
        out = {
            "enabled": self.enabled,
            "admissions": self.admissions.value,
            "hits": self.hits.value,
            "hit_rate": self.hits.value / max(self.admissions.value, 1),
            "prompt_tokens": self.prompt_tokens.value,
            "prefill_tokens_skipped":
                self.prefill_tokens_skipped.value,
            "prefill_skip_frac":
                self.prefill_tokens_skipped.value / total,
            "evictions": self.tree.evictions,
            "pages_in_use": self.pool.pages_in_use,
            "pages_free": self.pool.available,
            "pages_outstanding": self.pool.outstanding,
            # host tier gauges (zeros when the tier is off, via the
            # pool's canonical key set) — the operator's live view of
            # demote/promote behaviour
            **HostKVPool.empty_stats(),
            "host_hits": self.host_hits.value,
            "demotions": self.tree.demotions,
            "promotions": self.tree.promotions,
            "host_drops": self.tree.host_drops,
            "restore_latency_ms": round(self._g_restore.value, 3),
        }
        if self.pool.shards > 1:
            out["sp_pages_resident"] = self.pool.pages_in_use_by_shard
            out["sp_pages_free_by_shard"] = self.pool.available_by_shard
        # NB the pool defines __len__, so this must test `is not None`
        # (an EMPTY pool is falsy)
        if self.host is not None:
            out.update(self.host.stats())
        return out
