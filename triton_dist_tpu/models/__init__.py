"""Models + inference engine (reference: python/triton_dist/models/,
SURVEY.md §2.4). `AutoLLM` dispatches by model name/config the way the
reference does (models/__init__.py:33-59: Qwen3 -> DenseLLM,
Qwen3-MoE -> Qwen3MoE)."""

from triton_dist_tpu.models.config import (ModelConfig, qwen3_30b_a3b,  # noqa: F401
                                           qwen3_32b, tiny_qwen3,
                                           tiny_qwen3_moe)
from triton_dist_tpu.models.dense import DenseLLM  # noqa: F401
from triton_dist_tpu.models.disagg import (DCNTransport,  # noqa: F401
                                           DisaggScheduler,
                                           HostTransport, ICITransport,
                                           KVHandoff, PrefillWorker,
                                           PrefillWorkerDied)
from triton_dist_tpu.models.engine import Engine  # noqa: F401
from triton_dist_tpu.models.kv_cache import KVCache, PagedSlotCache  # noqa: F401
from triton_dist_tpu.models.prefix_cache import (PoolExhausted,  # noqa: F401
                                                 PrefixCache)
from triton_dist_tpu.models.scheduler import (ContinuousScheduler,  # noqa: F401
                                              DecodeSlots,
                                              PagedDecodeSlots, Request,
                                              ResumeState)
from triton_dist_tpu.models.spec_decode import (Drafter,  # noqa: F401
                                                NgramDrafter)


class AutoLLM:
    """Name-based dispatch (reference: AutoLLM.from_pretrained,
    models/__init__.py:33-59)."""

    @staticmethod
    def from_pretrained(path: str, mesh, axis: str = "tp", **kw):
        cfg = ModelConfig.from_hf_config(path)
        if cfg.is_moe:
            from triton_dist_tpu.models.qwen_moe import Qwen3MoE
            return Qwen3MoE.from_hf(path, mesh, axis, **kw)
        _dense_kw_check(kw)
        return DenseLLM.from_hf(path, mesh, axis, **kw)

    @staticmethod
    def from_config(cfg: ModelConfig, mesh, axis: str = "tp", seed: int = 0,
                    **kw):
        if cfg.is_moe:
            from triton_dist_tpu.models.qwen_moe import Qwen3MoE
            return Qwen3MoE.random_init(cfg, mesh, axis, seed, **kw)
        _dense_kw_check(kw)
        return DenseLLM.random_init(cfg, mesh, axis, seed, **kw)


def _dense_kw_check(kw) -> None:
    """Dense models take the sequence-parallel kwargs only (the sp
    serving layout — models/dense.py); everything else is MoE-only."""
    extra = set(kw) - {"sp_axis", "sp_combine"}
    assert not extra, f"MoE-only kwargs {sorted(extra)} on a dense config"
