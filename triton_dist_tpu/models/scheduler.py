"""Continuous-batching decode scheduler: slot-based multi-request
serving over the shared KV pool.

The reference's serving loop (`mega_triton_kernel/test/models/
model_server.py:265`) handles one prompt at a time, and the old
TokenServer tiled that single prompt across every decode row — B-1 of
B slots doing duplicate work in a regime that is weight-bandwidth
bound, where tok/s/chip scales with the number of DISTINCT occupied
slots. This module is the Orca-style iteration-level scheduler (the
role vLLM's continuous batching plays over paged attention —
PAPERS.md): up to `batch` concurrent requests occupy distinct decode
slots, a freed slot is refilled from the queue between chunked decode
scans, and the decode hot loop stays ONE XLA program per chunk shape
regardless of the occupancy mix — admission changes DATA (masks,
positions, per-slot keys), never the program.

Mechanics (engine.py slot path):
- each batch row of the cache is an independent slot; a new request
  prefills into a scratch row and is copied over its slot
  (Engine.prefill_into_slot) without touching live slots;
- decode chunks run Engine.slot_chunk: per-row sampling keyed by
  per-slot PRNG chains, per-row KV append at per-slot positions, and
  per-row attention lengths (flash_decode kv_lens) — so every slot's
  token chain is exactly a single-request Engine.serve() at its seed;
- between chunks the host trims each slot's tokens to its remaining
  budget, retires finished slots, and admits queued requests into the
  freed rows while the other slots keep decoding mid-stream.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Dict, List, Optional, Tuple

import numpy as np


@dataclasses.dataclass
class Request:
    """One generation request (the scheduler's admission unit)."""
    rid: object                    # caller's id (any hashable)
    ids: np.ndarray                # prompt token ids [S]
    gen_len: int
    seed: int = 0


class DecodeSlots:
    """Per-slot decode state: device-side carry (last logits, per-slot
    position, active mask, per-slot PRNG keys) + host-side bookkeeping
    (remaining gen budget, owning request). The device arrays are the
    slot scan's carry — admission and retirement edit rows of them
    between chunks."""

    def __init__(self, engine, batch: int):
        import jax
        import jax.numpy as jnp
        self.engine = engine
        self.batch = batch
        V = engine.model.config.vocab_size
        self.cache = engine.make_slot_cache(batch)
        self.logits = jnp.zeros((batch, V), jnp.float32)
        self.pos = jnp.zeros((batch,), jnp.int32)
        self.active = jnp.zeros((batch,), bool)
        self.keys = (None if engine.sampling == "greedy"
                     else jax.random.split(jax.random.key(0), batch))
        # host mirrors (scheduling is host-side; the model never syncs)
        self.remaining = np.zeros((batch,), np.int64)
        self.rids: List[Optional[object]] = [None] * batch

    @property
    def free(self) -> List[int]:
        return [b for b in range(self.batch) if self.rids[b] is None]

    @property
    def occupied(self) -> List[int]:
        return [b for b in range(self.batch) if self.rids[b] is not None]

    def admit(self, slot: int, req: Request) -> None:
        """Prefill req into `slot` and arm its row of the carry. Only
        the slot's rows change — live slots decode on, unaware."""
        import jax
        assert self.rids[slot] is None, f"slot {slot} is occupied"
        n = len(req.ids)
        cap = self.cache.k[0].shape[2]
        if n + req.gen_len > cap:
            raise ValueError(
                f"request {req.rid!r}: prompt {n} + gen {req.gen_len} "
                f"exceeds slot capacity {cap}")
        row, self.cache = self.engine.prefill_into_slot(
            self.cache, slot, req.ids)
        self.logits = self.logits.at[slot].set(row)
        self.pos = self.pos.at[slot].set(n)
        self.active = self.active.at[slot].set(True)
        if self.keys is not None:
            self.keys = self.keys.at[slot].set(jax.random.key(req.seed))
        self.remaining[slot] = req.gen_len
        self.rids[slot] = req.rid

    def retire(self, slot: int) -> None:
        """Free a slot: mask it out of the scan. Its cache row and
        carry rows stay as dead data until the next admit overwrites
        them."""
        self.active = self.active.at[slot].set(False)
        self.remaining[slot] = 0
        self.rids[slot] = None

    def step_chunk(self, chunk: int) -> Tuple[Dict[int, np.ndarray],
                                              List[Tuple[int, object]]]:
        """Run one `chunk`-step slot scan. Returns ({slot: kept tokens
        (trimmed to the slot's remaining budget)}, [(slot, rid) of
        requests that just finished]). Finished slots are NOT retired
        here — the caller streams their tail first, then retires."""
        toks, self.logits, self.cache, self.pos, self.keys = \
            self.engine.slot_chunk(self.logits, self.cache, self.pos,
                                   self.active, chunk=chunk,
                                   keys=self.keys)
        toks = np.asarray(toks)
        out: Dict[int, np.ndarray] = {}
        finished: List[Tuple[int, object]] = []
        for b in self.occupied:
            keep = int(min(self.remaining[b], chunk))
            if keep:
                out[b] = toks[b, :keep]
                self.remaining[b] -= keep
            if self.remaining[b] == 0:
                finished.append((b, self.rids[b]))
        return out, finished


class ContinuousScheduler:
    """Admit-from-queue / step_chunk / retire loop over DecodeSlots
    (Orca iteration-level scheduling). Single-threaded on the model:
    callers enqueue requests from any thread; one driver thread calls
    poll() (or run()) and owns every jax dispatch."""

    def __init__(self, engine, *, batch: int, chunk: int = 4):
        self.slots = DecodeSlots(engine, batch)
        self.chunk = chunk
        self._queue: deque = deque()

    def submit(self, req: Request) -> None:
        self._queue.append(req)

    @property
    def idle(self) -> bool:
        return not self._queue and not self.slots.occupied

    def poll(self) -> Tuple[Dict[object, np.ndarray], List[object]]:
        """One scheduling iteration: refill free slots from the queue,
        run one decode chunk, retire what finished. Returns
        ({rid: new tokens}, [rids finished this chunk]). A request the
        slots REJECT (e.g. prompt + gen beyond capacity) is reported as
        finished with no tokens — one bad request must never take down
        the serving loop (the old per-request server survived bad
        clients too)."""
        rejected: List[object] = []
        for slot in self.slots.free:
            if not self._queue:
                break
            req = self._queue.popleft()
            try:
                self.slots.admit(slot, req)
            except ValueError as e:
                import sys
                print(f"[scheduler] rejected request {req.rid!r}: {e}",
                      file=sys.stderr)
                rejected.append(req.rid)
        if not self.slots.occupied:
            return {}, rejected
        by_slot, finished = self.slots.step_chunk(self.chunk)
        rid_of = self.slots.rids
        out = {rid_of[b]: t for b, t in by_slot.items()}
        done = rejected
        for b, rid in finished:
            self.slots.retire(b)
            done.append(rid)
        return out, done

    def run(self, requests) -> Dict[object, np.ndarray]:
        """Drive a batch of requests to completion (the test/bench
        harness loop; a server calls poll() itself to interleave
        streaming I/O). Returns {rid: tokens [gen_len]}."""
        for r in requests:
            self.submit(r)
        acc: Dict[object, list] = {r.rid: [] for r in requests}
        while not self.idle:
            out, _ = self.poll()
            for rid, toks in out.items():
                acc[rid].extend(toks.tolist())
        return {rid: np.asarray(t, np.int64) for rid, t in acc.items()}
