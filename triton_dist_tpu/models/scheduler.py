"""Continuous-batching decode scheduler: slot-based multi-request
serving over the shared KV pool.

The reference's serving loop (`mega_triton_kernel/test/models/
model_server.py:265`) handles one prompt at a time, and the old
TokenServer tiled that single prompt across every decode row — B-1 of
B slots doing duplicate work in a regime that is weight-bandwidth
bound, where tok/s/chip scales with the number of DISTINCT occupied
slots. This module is the Orca-style iteration-level scheduler (the
role vLLM's continuous batching plays over paged attention —
PAPERS.md): up to `batch` concurrent requests occupy distinct decode
slots, a freed slot is refilled from the queue between chunked decode
scans, and the decode hot loop stays ONE XLA program per chunk shape
regardless of the occupancy mix — admission changes DATA (masks,
positions, per-slot keys), never the program.

Mechanics (engine.py slot path):
- each batch row of the cache is an independent slot; a new request
  prefills into a scratch row and is copied over its slot
  (Engine.prefill_into_slot) without touching live slots;
- decode chunks run Engine.slot_chunk: per-row sampling keyed by
  per-slot PRNG chains, per-row KV append at per-slot positions, and
  per-row attention lengths (flash_decode kv_lens) — so every slot's
  token chain is exactly a single-request Engine.serve() at its seed;
- between chunks the host trims each slot's tokens to its remaining
  budget, retires finished slots, and admits queued requests into the
  freed rows while the other slots keep decoding mid-stream.

Speculative decoding (spec=K, models/spec_decode.py): each step is one
draft-then-verify iteration instead of a 1-token scan step — the host
drafter proposes up to K continuations per slot by n-gram prompt
lookup, ONE verify forward (Engine.slot_verify_chunk /
paged_slot_verify_chunk) scores every slot's padded window, and each
slot emits its seed token plus the accepted prefix (1..K+1 tokens per
forward). Greedy streams stay bitwise identical to spec=0.

Chunked prefill (Sarathi-Serve, Agrawal et al. 2403.02310 — PAPERS.md):
with `prefill_budget` set, an admission no longer runs its prompt's
prefill as one monolithic program that stalls every live decode stream
for its duration (the head-of-line blocking Sarathi-Serve measures as
inter-token latency spikes). Instead the slot enters a PREFILLING
state (host-resumable offset into the uncached prompt suffix) and the
scheduler runs MIXED ticks: ONE forward per poll covers every live
decode slot (q_len = 1, or its spec window) AND up to `prefill_budget`
tokens of in-progress prefills (q_len = chunk), riding the per-slot
`q_lens`+`kv_lens` verify masks of kernels/flash_attn.py /
kernels/paged_kv.py. Chunk rows write their KV (contiguous columns or
pages) but emit a next-token logit only when the FINAL chunk lands —
the slot then arms (_arm_slot) and joins decode. The paged admission's
prefix-cache lookup and boundary-page copy-on-write happen ONCE at
chunk 0 (engine.install_slot_paged); the prompt is inserted into the
radix tree only when its KV is fully computed (arming), and a
preempted/cancelled mid-prefill slot donates exactly its VALID extent.
Streams are bitwise identical chunked vs monolithic across
{greedy, sampled, spec=K} x {contiguous, paged+prefix-cache}
(tests/test_chunked_prefill.py), and the maximum prefill work a live
stream waits on between its tokens drops from the full prompt length
to `prefill_budget` (stats(): max_prefill_tokens_per_poll).

Overlap scheduling (the SGLang zero-overhead overlap scheduler —
Zheng et al. 2312.07104, PAPERS.md — over this repo's slot machinery):
with ``ContinuousScheduler(overlap=True)`` the driver DISPATCHES the
device program for tick N+1 BEFORE reading back tick N's results, so
every poll's host bookkeeping (admit/retire, radix-tree inserts,
drafting, stats, the serving layer's socket writes) runs while the
device is busy — at large slot counts host time is otherwise the
inter-token floor. Mechanics:

- every blocking readback rides ``DecodeSlots._fetch`` and is timed
  into ``device_wait_s``, so ``stats()["host_ms_per_poll"]`` reports
  dispatch-to-dispatch host time with device wait subtracted (the EMA
  now lives as the ``host_ms_per_poll`` Gauge in the scheduler's
  metrics registry — runtime/telemetry.py — next to the live
  ``poll_ms``/``ttft_ms``/``inter_token_ms`` histograms); the
  tick's readback is ONE coalesced ``jax.device_get`` per poll (spec
  arming adds a small per-armed-slot seed fetch on top);
- the non-spec emission plan is HOST-DETERMINISTIC (each active slot
  emits min(remaining, chunk) tokens), so ``begin_chunk``/
  ``begin_mixed`` account budgets and clear finished slots' active
  masks at DISPATCH time and defer only the token VALUES — streaming,
  the paged token mirrors, and retirement (the radix-tree insert needs
  the values) — to ``land`` one poll later;
- spec=K drafts need the landed history, so the spec pipeline lands
  within its own poll and instead DEFERS the retire/admit work of the
  previous tick to run between dispatch and land (staged retires);
- admissions see a slot freed by tick N only after N lands — the
  one-tick admission delay — and any path that must mutate an
  in-flight slot (preemption, cancel-on-disconnect, an in-flight
  deadline expiry) DRAINS the pipeline first, so token streams stay
  BITWISE identical overlap-on vs overlap-off across every mode
  (tests/test_overlap.py);
- the watchdog and deadline checks move to LANDED-tick boundaries
  (a dispatch cannot hang; the readback can).

Resilience (the degradation ladder under pressure — vLLM's
preemption/recompute design over the Orca operational model,
PAPERS.md):
- PREEMPTION: a paged admission that cannot get pages even after LRU
  eviction no longer hard-rejects when a victim slot exists. The
  scheduler preempts the victim (fewest generated tokens, then most
  recently admitted): its prompt + generated sequence goes into the
  radix prefix tree through the EXISTING retire path (the pages
  already hold its KV — insertion is bookkeeping), its page refs are
  released (now evictable), and the request re-queues at the front
  with a resume snapshot (ResumeState: evolved PRNG key, pending spec
  seed token, emitted count). On re-admission the prefix cache hands
  the pages back (match capped at n-1, so only the last token
  recomputes) and decode resumes mid-stream — token streams are
  BITWISE identical preempted vs unpreempted, greedy and sampled,
  spec=K included (tests/test_resilience.py). Hard rejection remains
  only when a single request alone exceeds capacity.
- BACKPRESSURE: `max_queue` bounds the waiting line; submit() returns
  False on overflow and the serving layer replies
  {"busy": true, "retry_after_ms": ...} instead of queueing unboundedly.
- DEADLINES: a Request's optional `deadline_ms` budget (stamped at
  submit) expires queued requests before admission and cancels
  in-flight ones mid-stream with a visible error reason.
- WATCHDOG: `watchdog_s` runs every decode chunk under
  runtime/stress.py::watchdog — a hung chunk surfaces as a clean HANG
  verdict in stats() (and a HangError to the caller) instead of a
  frozen model loop.

Telemetry (runtime/telemetry.py): every counter this module used to
keep in hand-rolled ints lives in a per-scheduler METRICS REGISTRY,
so stats() is one deep, single-point-in-time registry snapshot; the
scheduler additionally records each request's lifecycle
(queued → admitted → prefill_chunk*N → first_token → tokens →
preempt/resume → retired/cancelled/expired) — deriving live `ttft_ms`
and `inter_token_ms` p50/p95/p99 histograms — and, with
``trace=True`` (or TDTPU_TRACE set), a perfetto-loadable poll-loop
timeline: host phase spans, device occupancy (dispatch → `_fetch`
landing), and instants for watchdog fires / preemptions / drains.
Requests may carry an SLO CLASS (`Request.slo`, classes + targets via
``slo_classes=``): latencies then also land in per-class histograms
and partition exactly into slo_goodput/slo_violations — the signal an
SLO-aware admission/preemption policy consumes (ROADMAP item 4). The
coalesced device wait additionally splits per program kind
(``stats()["device_wait_s_by_kind"]``, keyed off mark_dispatch(kind)).
Tracing is host-side only: streams stay BITWISE identical trace-on
vs trace-off with zero new XLA programs (tests/test_telemetry.py,
tests/test_observability.py).

Multi-chip TP (ROADMAP open item 1): ONE scheduler drives a whole
TP=N mesh. The paged pool's page payloads are head-sharded over the
mesh (models/kv_cache.PagedSlotCache TP SHARDING) and the slot
programs run each chip's attention over its local kv-head shard under
shard_map, with the projections on the TP comm backends
(kernels/gemm_allreduce.py "gemm_ar" is the decode-regime pick;
kernels/allgather_gemm.py + gemm_reduce_scatter.py under "dist") —
while EVERYTHING in this module stays host-side and layout-oblivious:
admission, preemption, the radix tree, deadlines and the overlap
pipeline mutate page TABLES and masks, never payloads, so the same
scheduler code serves TP=1 and TP=8 with bitwise-identical streams
(tests/test_tp_serving.py). stats() reports tp_size plus aggregate
AND per-chip tok/s.

Disaggregation (models/disagg.py — DistServe, 2401.09670): chunked
prefill BOUNDS the prefill stall on live streams; `DisaggScheduler`
(a subclass of ContinuousScheduler) REMOVES it — dedicated prefill
workers compute admissions' KV into staging paged pools and stream
the finished page-groups to this scheduler's decode pool over the
p2p/DCN transfer plane, so decode polls never run a mixed tick at
all. Streams stay bitwise identical disagg vs fused
(tests/test_disagg.py).

Structured generation (models/structured.py — ISSUE 17): two
policy-layer features riding the machinery above unchanged.
PARALLEL SAMPLING: `Request(n=N)` fans out at admission (_fan_out)
into N children; child 0 prefills normally and the moment its slot
arms, _spawn_forks maps its prompt pages into the siblings' tables
(PagedDecodeSlots.fork — refcount+1 on full pages, CoW boundary, the
exact mapping a prefix-cache hit would build, which is why an
overflowed sibling falling back to ordinary admission stays bitwise).
Child k streams under rid (rid, k) at seed seed+k, cancels/preempts/
retires independently, and equals a sequential same-seed request
token-for-token (tests/test_structured.py). GRAMMAR-CONSTRAINED
DECODING: `Request(grammar=GrammarSpec)` collapses the slot's chunk
to 1 (_eff_chunk), threads per-state token masks into the EXISTING
tick programs as logits operands (_mask_chunk/_mask_window — zero new
XLA programs, zero extra host round trips), advances the host
automaton per emitted token (dead end → loud per-request reject,
final state → early finish), and under spec=K turns the automaton's
forced continuation into jump-ahead drafts through the normal verify
path (structured.constrained_draft; `jump_ahead_tokens` counter).
Overlap grammar polls collapse to the sync iteration — the next mask
needs the unlanded token (_grammar_sync_needed).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

import numpy as np

from triton_dist_tpu.runtime.telemetry import Telemetry, \
    UNTAGGED_PRIORITY, trace_env_enabled
from triton_dist_tpu.models.structured import NO_FORCED, \
    constrained_draft, window_masks


@dataclasses.dataclass
class ResumeState:
    """Mid-stream snapshot carried by a preempted request: everything
    exact resume needs beyond the (prompt + generated) token sequence
    already folded into Request.ids. The KV itself is NOT snapshotted —
    the radix prefix tree holds the preempted pages (until eviction
    recycles them), and re-admission either maps them back or
    recomputes, bitwise identically either way."""
    key: object = None             # evolved per-slot PRNG key (sampled)
    t0: Optional[int] = None       # pending spec-mode seed token
    emitted: int = 0               # tokens already streamed pre-preempt
    preemptions: int = 1           # times this request was displaced
    gstate: Optional[int] = None   # grammar automaton state (constrained)


@dataclasses.dataclass
class Request:
    """One generation request (the scheduler's admission unit).

    deadline_ms: optional latency budget from submit(); an expired
    request is cancelled with a visible error instead of occupying a
    slot past its usefulness. slo: optional SLO class name
    (runtime/telemetry.py DEFAULT_SLO_CLASSES — "interactive" /
    "batch", or any class the scheduler's `slo_classes` configured):
    lifecycle latencies then land in per-class histograms and the
    request is judged into slo_goodput / slo_violations at its final
    transition. resume: set internally by preemption — callers never
    construct it.

    n > 1 requests PARALLEL SAMPLING (models/structured.py + the
    PagedDecodeSlots.fork KV fork): one prefill, n decode streams with
    seeds seed..seed+n-1, each streaming under rid (rid, k) — bitwise
    identical to n sequential same-seed requests. grammar: an optional
    structured.GrammarSpec; every emitted token is then masked to the
    grammar's legal set inside the tick programs and the stream
    finishes when the grammar completes."""
    rid: object                    # caller's id (any hashable)
    ids: np.ndarray                # prompt token ids [S]
    gen_len: int
    seed: int = 0
    deadline_ms: Optional[float] = None
    slo: Optional[str] = None
    resume: Optional[ResumeState] = None
    n: int = 1                     # parallel samples (KV fork fan-out)
    grammar: object = None         # structured.GrammarSpec (optional)


class _TokenLog:
    """Incrementally grown int32 token log (amortized-doubling numpy
    buffer) backing the per-slot history/token mirrors. Replaces the
    Python-list mirrors whose drafter/retire paths rebuilt a fresh
    array from the whole list every time (O(generated^2) host work over
    a stream's life): appends are amortized O(1) numpy copies and
    ``view()`` is a zero-copy slice the drafter's n-gram scan and the
    radix-tree insert consume directly."""

    __slots__ = ("_buf", "_n")

    def __init__(self, init=None, cap: int = 64):
        self._buf = np.empty((max(int(cap), 8),), np.int32)
        self._n = 0
        if init is not None:
            self.extend(init)

    def __len__(self) -> int:
        return self._n

    def extend(self, toks) -> None:
        toks = np.asarray(toks, np.int32).reshape(-1)
        need = self._n + len(toks)
        if need > len(self._buf):
            buf = np.empty((max(need, 2 * len(self._buf)),), np.int32)
            buf[:self._n] = self._buf[:self._n]
            self._buf = buf
        self._buf[self._n:need] = toks
        self._n = need

    def append(self, t: int) -> None:
        if self._n == len(self._buf):
            buf = np.empty((2 * len(self._buf),), np.int32)
            buf[:self._n] = self._buf[:self._n]
            self._buf = buf
        self._buf[self._n] = t
        self._n += 1

    def pop(self) -> None:
        self._n -= 1

    def view(self) -> np.ndarray:
        """Zero-copy window over the valid extent. Treat as read-only;
        it aliases the growing buffer (``.copy()`` anything that must
        outlive the next append). Note in-place appends only ever
        write PAST the window (growth reallocates), so a view's
        contents are stable even while the log keeps growing."""
        return self._buf[:self._n]

    # sequence protocol + zero-copy numpy conversion: drafters receive
    # the log itself (Drafter.propose takes a Sequence[int]), so both
    # `history[-1]`-style scalar access and np.asarray(history) work
    # without rebuilding a list
    def __getitem__(self, i):
        return self.view()[i]

    def __array__(self, dtype=None):
        v = self.view()
        return v if dtype is None else v.astype(dtype)


@dataclasses.dataclass
class _InFlight:
    """One dispatched-but-unlanded tick (the overlap scheduler's
    pipeline register). ``arrs`` are the device arrays the landing
    fetches in ONE coalesced device_get; ``plan`` is the emission plan
    fixed at dispatch time — (slot, rid, keep) rows for the
    deterministic non-spec modes, (slot, rid) verify rows for spec
    (whose keeps are data-dependent). ``finishing`` (non-spec) lists
    the slots the plan determined will have exhausted their budget
    when this tick lands. ``arm`` (spec mixed ticks) lists prefills
    whose final chunk is in this tick — arming needs the landed
    logits, so it runs at land. rids ride along purely as a guard: the
    drain-before-retire invariant means a slot in an unlanded tick is
    never reassigned, and ``land`` asserts it."""
    kind: str                  # "chunk" | "mixed" | "spec" | "mixed_spec"
    arrs: tuple                     # device arrays to fetch
    plan: list
    finishing: list
    tokens: Optional[np.ndarray] = None    # spec: the verify window
    q_lens: Optional[np.ndarray] = None
    arm: list = dataclasses.field(default_factory=list)


# mark_dispatch kinds -> the attribution buckets stats() reports
# (device_wait_s_by_kind): the chunk scan is the decode tick, and a
# mixed verify is still a mixed tick — the operator-facing question is
# "which program CLASS am I waiting on", not which jit entry point
_DISPATCH_KIND = {"chunk": "decode", "mixed_verify": "mixed",
                  "mega": "mega", "sp": "sp_combine"}

# _InFlight.kind -> the same buckets, for the overlap land (which must
# charge the LANDED tick's kind, not whatever dispatched since)
_INFLIGHT_KIND = {"chunk": "decode", "mega": "mega", "mixed": "mixed",
                  "spec": "verify", "mixed_spec": "mixed",
                  "sp": "sp_combine"}


def _merge_out(acc: Dict[object, np.ndarray], rid, toks) -> None:
    """Append landed tokens for one rid to a poll's output dict (a
    drained tick and a freshly landed one can both deliver in the same
    poll — order preserved: drained is older)."""
    toks = np.asarray(toks)
    acc[rid] = (np.concatenate([acc[rid], toks]) if rid in acc
                else toks)


class DecodeSlots:
    """Per-slot decode state: device-side carry (last logits, per-slot
    position, active mask, per-slot PRNG keys) + host-side bookkeeping
    (remaining gen budget, owning request). The device arrays are the
    slot scan's carry — admission and retirement edit rows of them
    between chunks."""

    def __init__(self, engine, batch: int, *, spec: int = 0,
                 drafter=None, telemetry: Optional[Telemetry] = None):
        """spec=K > 0 enables SPECULATIVE DECODING
        (models/spec_decode.py): each step_chunk becomes one
        draft-then-verify iteration — the host `drafter` (default
        NgramDrafter, prompt-lookup over the slot's own history)
        proposes up to K continuation tokens per slot, ONE verify
        forward scores every slot's padded window, and each slot emits
        its seed token plus the accepted draft prefix (1..K+1 tokens
        per forward instead of exactly 1). Greedy streams stay bitwise
        identical to spec=0; sampled streams stay distributionally
        exact (leftover rejection sampling)."""
        import jax
        import jax.numpy as jnp
        self.engine = engine
        self.batch = batch
        # telemetry bundle (runtime/telemetry.py): the registry the
        # lifetime counters below live in, plus the trace hooks the
        # ticks stamp (device occupancy spans, drafter phases). The
        # owning scheduler passes its own; a bare DecodeSlots gets a
        # private trace-off instance.
        self.tele = telemetry if telemetry is not None else Telemetry()
        V = engine.model.config.vocab_size
        self.cache = self._make_cache()
        self.logits = jnp.zeros((batch, V), jnp.float32)
        self.pos = jnp.zeros((batch,), jnp.int32)
        self.active = jnp.zeros((batch,), bool)
        self.keys = (None if engine.sampling == "greedy"
                     else jax.random.split(jax.random.key(0), batch))
        # host mirrors (scheduling is host-side; the model never syncs)
        self.remaining = np.zeros((batch,), np.int64)
        self.rids: List[Optional[object]] = [None] * batch
        # full Request per occupant + admission order — the preemption
        # victim policy reads both (fewest generated tokens, then most
        # recently admitted)
        self.reqs: List[Optional[Request]] = [None] * batch
        self.admit_tick = np.zeros((batch,), np.int64)
        self._admit_seq = 0
        # chunked prefill (step_mixed): per-slot PREFILLING state — the
        # full prompt and the resumable offset of the next un-prefilled
        # token. A slot with _pf_ids[b] set is occupied (rids[b] set)
        # but NOT active: it joins decode only when its final chunk
        # lands and _arm_slot runs. prefill_forwarded counts every
        # prompt token actually pushed through a forward (monolithic
        # admissions included) — the scheduler derives its per-poll
        # stall bound from it.
        self._pf_ids: List[Optional[np.ndarray]] = [None] * batch
        self._pf_off = np.zeros((batch,), np.int64)
        self.prefill_forwarded = 0
        # grammar-constrained decoding (models/structured.py): one live
        # host automaton per constrained slot, advanced per emitted
        # token; its allowed-token row rides the tick programs' mask
        # operand (engine.slot_* mask threading) so greedy AND sampled
        # decode select only grammar-legal tokens in-program. on_armed:
        # scheduler hook fired the instant a slot arms
        # (ContinuousScheduler wires its fork fan-out here).
        self._vocab_size = V
        self._grammar: List[Optional[object]] = [None] * batch
        self.on_armed = None
        # slot -> error message for a stream whose automaton hit a dead
        # end (no legal continuation): the scheduler reports the rid's
        # failure loudly instead of emitting garbage
        self.grammar_dead: Dict[int, str] = {}
        # jump-ahead accounting: the verify-window index the
        # GrammarDrafter's forced segment starts at (NO_FORCED = none)
        self._forced_from = np.full((batch,), NO_FORCED, np.int64)
        self._grammar_steps = 0
        greg = self.tele.registry
        self._c_mask_tokens = greg.counter(
            "grammar_mask_tokens",
            "tokens emitted under a grammar mask")
        self._c_jump = greg.counter(
            "jump_ahead_tokens",
            "grammar-forced draft tokens accepted past the base draft")
        self._g_constrained = greg.gauge(
            "constrained_tokens_per_step",
            "grammar-masked tokens emitted per constrained slot-step")
        # overlap scheduling (module docstring): the pipeline register
        # holding one dispatched-but-unlanded tick, and the cumulative
        # time spent BLOCKED on device readbacks (every blocking fetch
        # goes through _fetch) — the scheduler subtracts it from the
        # dispatch-to-dispatch interval to report host_ms_per_poll
        self._inflight: Optional[_InFlight] = None
        self.device_wait_s = 0.0
        # device-time ATTRIBUTION: the same blocking wait split per
        # program kind, keyed off the kind of the most recent
        # mark_dispatch (decode/verify/mixed; "admit" for the
        # out-of-band arming fetches). The disagg plane owns the
        # "prefill"/"transfer" buckets (models/disagg.py) — together
        # the per-kind gauges tell an operator WHICH program class the
        # host actually waits on (stats()["device_wait_s_by_kind"]).
        # PRE-SEEDED with every bucket so the driver's _fetch only
        # ever updates existing keys — cross-thread stats() readers
        # iterate this dict, and a mid-iteration dict RESIZE (unlike a
        # value update) would raise under them.
        self.device_wait_by_kind: Dict[str, float] = {
            "prefill": 0.0, "decode": 0.0, "verify": 0.0,
            "mixed": 0.0, "admit": 0.0, "transfer": 0.0,
            "mega": 0.0, "sp_combine": 0.0, "other": 0.0}
        # MoE-family serving telemetry (ISSUE 13): every tick program
        # of a Qwen3MoE engine appends its routing-load vector
        # [expert_tokens[0..E-1], capacity_dropped]; _fetch pops ONE
        # per landed tick (engine.pop_moe_load — FIFO, so the overlap
        # pipeline never syncs an in-flight tick's stats) and folds it
        # into per-expert `expert_tokens{expert=...}` counters, the
        # `moe_capacity_drops` counter, and the `expert_load_imbalance`
        # (max/mean of cumulative expert load) gauge — the loud half
        # of dropless-or-loud, observable in stats() and /metrics.
        self._moe_family = bool(getattr(engine, "moe_family", False))
        if self._moe_family:
            # engines are shared across schedulers (the process-wide
            # program cache); a prior scheduler that died mid-tick may
            # have left an unlanded stats entry — start aligned
            engine._moe_pending.clear()
            reg = self.tele.registry
            E = engine.model.config.num_experts
            self._moe_tokens_cum = np.zeros((E,), np.int64)
            self._c_expert = [
                reg.counter("expert_tokens",
                            "routed entries per expert (compute load)",
                            labels={"expert": str(e)})
                for e in range(E)]
            self._c_moe_drops = reg.counter(
                "moe_capacity_drops",
                "routed entries lost to expert capacity (0 under "
                "capacity_factor='dropless')")
            self._g_moe_imb = reg.gauge(
                "expert_load_imbalance",
                "max/mean of cumulative per-expert routed load")
        self.spec = int(spec)
        if self.spec:
            from triton_dist_tpu.models.spec_decode import NgramDrafter
            if engine.backend == "mega":
                raise ValueError(
                    "backend='mega' does not fuse the spec-decode "
                    "verify window yet (the fused tick is the greedy "
                    "S == 1 paged step); serve spec=K on the per-op "
                    "backends")
            self.drafter = drafter if drafter is not None \
                else NgramDrafter()
            self._vocab = V
            # per-slot token history (prompt + emitted) — the drafter's
            # lookup corpus — and the pending seed token each verify
            # window starts with. _TokenLog: amortized-O(1) appends and
            # a zero-copy view per draft, instead of list mirrors whose
            # per-step conversions cost O(generated^2) over a stream
            self._hist: List[_TokenLog] = [_TokenLog()
                                           for _ in range(batch)]
            self._t0 = np.zeros((batch,), np.int64)
            # accept counters (stats(): spec_accept_rate /
            # tokens_per_step, surfaced through TokenServer). The
            # LIFETIME aggregates (they survive slot reuse) are
            # registry Counters; the per-slot arrays cover the current
            # occupants only (zeroed at admit).
            reg = self.tele.registry
            self._spec_steps = reg.counter(
                "spec_steps", "verify forwards run")
            self._spec_slot_steps = reg.counter(
                "spec_slot_steps", "live (slot, forward) pairs")
            self._spec_emitted = reg.counter(
                "spec_emitted", "tokens kept (incl. seeds)")
            self._spec_drafted_total = reg.counter(
                "spec_drafted", "drafter tokens proposed")
            self._spec_accepted_total = reg.counter(
                "spec_accepted", "drafter tokens accepted")
            self._spec_drafted = np.zeros((batch,), np.int64)
            self._spec_accepted = np.zeros((batch,), np.int64)
            # a drafter that raises (or proposes garbage) must degrade
            # to plain decode, never take down the model loop — the
            # chaos harness (runtime/chaos.py::FlakyDrafter) pins this
            self._drafter_errors = reg.counter("drafter_errors")

    def _make_cache(self):
        """Cache-flavor hook (PagedDecodeSlots swaps in the paged pool)."""
        return self.engine.make_slot_cache(self.batch)

    def _tick_kind(self) -> str:
        """mark_dispatch kind of one plain decode tick ("chunk"; the
        paged subclass reports "mega" when the engine routes the tick
        through the fused megakernel program — device_wait_s_by_kind
        then attributes the fused tick separately)."""
        return "chunk"

    @property
    def capacity(self) -> int:
        """Admittable prompt+gen budget per slot."""
        return self.cache.k[0].shape[2]

    @property
    def free(self) -> List[int]:
        return [b for b in range(self.batch) if self.rids[b] is None]

    @property
    def occupied(self) -> List[int]:
        return [b for b in range(self.batch) if self.rids[b] is not None]

    @property
    def prefill_slots(self) -> List[int]:
        """Slots mid-chunked-prefill (occupied but not yet armed)."""
        return [b for b in range(self.batch)
                if self._pf_ids[b] is not None]

    @property
    def decode_slots(self) -> List[int]:
        """Occupied slots that are ARMED (emitting tokens) — the rows
        the per-tick emission/retirement bookkeeping covers."""
        return [b for b in self.occupied if self._pf_ids[b] is None]

    def _arm_slot(self, slot: int, req: Request, row_logits, n: int
                  ) -> None:
        """Arm a freshly prefilled slot's rows of the decode carry
        (shared by the contiguous and paged admit paths). A RESUMED
        request (req.resume set — it was preempted mid-stream) restores
        its snapshot instead of restarting: the evolved PRNG key
        replaces jax.random.key(seed) so the sampled chain continues
        exactly where it stopped, and the pending spec seed token is
        restored rather than re-drawn (re-drawing would consume an
        extra key split the unpreempted chain never spent)."""
        import jax
        rs = req.resume
        g = getattr(req, "grammar", None)
        if g is not None:
            gs = g.fresh()
            if rs is not None and rs.gstate is not None:
                # resumed constrained stream: the automaton continues
                # from the preemption snapshot (the generated suffix is
                # already consumed — re-walking it would double-count)
                gs.state = int(rs.gstate)
            self._grammar[slot] = gs
        else:
            self._grammar[slot] = None
        self.logits = self.logits.at[slot].set(row_logits)
        self.pos = self.pos.at[slot].set(n)
        self.active = self.active.at[slot].set(True)
        if self.keys is not None:
            self.keys = self.keys.at[slot].set(
                rs.key if rs is not None and rs.key is not None
                else jax.random.key(req.seed))
        self.remaining[slot] = req.gen_len
        self.rids[slot] = req.rid
        self.reqs[slot] = req
        self._admit_seq += 1
        self.admit_tick[slot] = self._admit_seq
        if self.spec:
            # seed the slot's verify chain: history = prompt, pending
            # seed token = what spec=0 would emit first from these
            # logits (greedy argmax on the host; sampled draws through
            # the slot's PRNG chain so the chain stays per-slot)
            self._hist[slot] = _TokenLog(req.ids)
            if rs is not None and rs.t0 is not None:
                self._t0[slot] = int(rs.t0)
            elif self.engine.sampling == "greedy":
                # arming readbacks ride _fetch so their device wait is
                # not misattributed as host time (host_ms_per_poll)
                (row,) = self._fetch((row_logits,), land=False)
                row = np.asarray(row)
                if self._grammar[slot] is not None:
                    # the seed obeys the grammar too (host-side masked
                    # argmax — same selection the tick programs make)
                    row = np.where(self._grammar[slot].allowed_row(),
                                   row, -np.inf)
                self._t0[slot] = int(np.argmax(row))
            else:
                gmask = (self._grammar[slot].allowed_row()
                         if self._grammar[slot] is not None else None)
                t0, k2 = self.engine.spec_seed(row_logits,
                                               self.keys[slot],
                                               mask=gmask)
                self.keys = self.keys.at[slot].set(k2)
                (t0,) = self._fetch((t0,), land=False)
                self._t0[slot] = int(t0)
            self._spec_drafted[slot] = 0
            self._spec_accepted[slot] = 0

    def admit(self, slot: int, req: Request) -> None:
        """Prefill req into `slot` and arm its row of the carry. Only
        the slot's rows change — live slots decode on, unaware."""
        assert self.rids[slot] is None, f"slot {slot} is occupied"
        n = len(req.ids)
        if n + req.gen_len > self.capacity:
            raise ValueError(
                f"request {req.rid!r}: prompt {n} + gen {req.gen_len} "
                f"exceeds slot capacity {self.capacity}")
        row, self.cache = self.engine.prefill_into_slot(
            self.cache, slot, req.ids)
        self.prefill_forwarded += n
        self._arm_slot(slot, req, row, n)

    def admit_chunked(self, slot: int, req: Request) -> None:
        """Chunked admission (prefill_budget mode): validate and park
        the request in a PREFILLING slot — NO forward runs here. The
        prompt prefills chunk by chunk inside subsequent step_mixed
        ticks (each one fused with the live decode step), and the slot
        arms when the final chunk lands. Live slots never wait on a
        monolithic prompt program."""
        assert self.rids[slot] is None, f"slot {slot} is occupied"
        ids = np.asarray(req.ids, np.int32).reshape(-1)
        n = len(ids)
        if n == 0:
            raise ValueError(f"request {req.rid!r}: empty prompt")
        if n + req.gen_len > self.capacity:
            raise ValueError(
                f"request {req.rid!r}: prompt {n} + gen {req.gen_len} "
                f"exceeds slot capacity {self.capacity}")
        self._park_prefilling(slot, req, ids, 0)

    def _park_prefilling(self, slot: int, req: Request, ids: np.ndarray,
                         start: int) -> None:
        """Shared tail of the chunked admissions: register the
        PREFILLING state (pos at the first position to compute —
        `start` is the cached-prefix length on the paged path)."""
        self.pos = self.pos.at[slot].set(start)
        self.active = self.active.at[slot].set(False)
        self.remaining[slot] = 0
        self.rids[slot] = req.rid
        self.reqs[slot] = req
        self._admit_seq += 1
        self.admit_tick[slot] = self._admit_seq
        self._pf_ids[slot] = ids
        self._pf_off[slot] = start

    def emitted(self, slot: int) -> int:
        """Tokens this slot's request has streamed since its ORIGINAL
        admission — resume-aware (a preempted request's pre-preemption
        span rides in resume.emitted). The single source for the
        victim policy, deadline messages, and preemption snapshots."""
        req = self.reqs[slot]
        base = req.resume.emitted if req.resume is not None else 0
        if self._pf_ids[slot] is not None:
            # still prefilling: nothing streamed since this admission
            # (remaining is 0 until the slot arms — without this guard
            # the formula below would claim the whole budget emitted)
            return base
        return base + req.gen_len - int(self.remaining[slot])

    def slo_priority(self, slot: int) -> float:
        """Protection rank of the slot's request: its SLO class's
        configured ``priority`` (runtime/telemetry.py::_SloClass —
        interactive 2.0 / batch 0.0 by default), UNTAGGED_PRIORITY for
        requests with no tag. SLO-aware policies (victim choice,
        prefill-budget splits) displace the LOWEST rank first; when
        every live request shares one rank the priority key is constant
        and those policies degenerate bitwise to the class-blind
        orderings (tests/test_resilience.py asserts this)."""
        req = self.reqs[slot]
        slo = req.slo if req is not None else None
        if slo is None:
            return UNTAGGED_PRIORITY
        cls = self.tele.slo_classes.get(slo)
        return cls.priority if cls is not None else UNTAGGED_PRIORITY

    def emitted_since_admit(self, slot: int) -> int:
        """Tokens streamed since this slot's CURRENT admission (a
        resumed request's pre-preemption span excluded — gen_len is
        already the residual budget). The preemption LIVENESS gate:
        only a slot whose progress is banked in its request (>= 1 token
        folded into ids on preempt) may be displaced, otherwise
        admissions under chunked prefill could displace each other's
        in-progress prefills forever — prefill progress lives in
        EVICTABLE tree pages, so a mid-prefill victim can lose
        everything and the system livelocks (monolithic admissions
        never exposed this: their prefill completes inside the
        admission call, so a resident always reaches emission before
        the next admission phase can displace it)."""
        if self._pf_ids[slot] is not None:
            return 0
        req = self.reqs[slot]
        return req.gen_len - int(self.remaining[slot])

    def retire(self, slot: int) -> None:
        """Free a slot: mask it out of the scan. Its cache row and
        carry rows stay as dead data until the next admit overwrites
        them."""
        self.active = self.active.at[slot].set(False)
        self.remaining[slot] = 0
        self.rids[slot] = None
        self.reqs[slot] = None
        self._pf_ids[slot] = None
        self._pf_off[slot] = 0
        self._grammar[slot] = None
        self.grammar_dead.pop(slot, None)
        self._forced_from[slot] = NO_FORCED
        if self.spec:
            self._hist[slot] = _TokenLog()

    def _fetch(self, arrs: tuple, *, land: bool = True,
               kind: Optional[str] = None) -> tuple:
        """The ONE blocking readback of a tick: a single coalesced
        jax.device_get over every array the tick hands back, timed
        into device_wait_s (the scheduler reports host_ms_per_poll =
        dispatch-to-dispatch interval minus this). Shared by the sync
        steps (fetch right after dispatch) and the overlap land (fetch
        one poll later). land=False for out-of-band readbacks (the
        spec arming seed fetches): they must NOT close the device-
        occupancy span of a tick still in flight — under overlap,
        admission runs between a verify's dispatch and its land.

        kind: explicit attribution bucket for the wait. The overlap
        land passes its in-flight tick's own kind — by land time the
        NEXT tick's dispatch has already overwritten tele.last_kind,
        so deriving it here would misattribute every transition poll.
        None (the sync paths, where the fetch directly follows its
        own mark_dispatch) derives from last_kind; land=False charges
        "admit" (arming fetches block on the admission forward)."""
        import jax
        moe_load = (self.engine.pop_moe_load()
                    if land and self._moe_family else None)
        t0 = time.perf_counter()
        if moe_load is not None:
            # the landed tick's routing-load vector rides the SAME
            # coalesced readback (its outputs are computed by now —
            # this is a d2h copy, not a sync)
            out = jax.device_get(arrs + (moe_load,))
            out, moe_load = out[:-1], out[-1]
        else:
            out = jax.device_get(arrs)
        dt = time.perf_counter() - t0
        self.device_wait_s += dt
        if moe_load is not None:
            self._note_moe_load(moe_load)
        if kind is None:
            kind = (_DISPATCH_KIND.get(self.tele.last_kind,
                                       self.tele.last_kind)
                    if land else "admit")
        # pre-seeded buckets only: stats() readers iterate this dict
        # cross-thread, so _fetch must never RESIZE it
        if kind not in self.device_wait_by_kind:
            kind = "other"
        self.device_wait_by_kind[kind] += dt
        if land:
            # close the device-occupancy span stamped at dispatch
            # (no-op when tracing is off or nothing is pending)
            self.tele.device_land()
        return out

    def _note_moe_load(self, load: np.ndarray) -> None:
        """Fold one landed tick's routing-load vector into the MoE
        serving metrics (driver thread only — the same thread that
        lands ticks)."""
        load = np.asarray(load, np.int64)
        counts, dropped = load[:-1], int(load[-1])
        for e in np.nonzero(counts)[0]:
            self._c_expert[int(e)].inc(int(counts[e]))
        if dropped:
            self._c_moe_drops.inc(dropped)
        self._moe_tokens_cum += counts
        mean = self._moe_tokens_cum.mean()
        self._g_moe_imb.set(
            float(self._moe_tokens_cum.max() / mean) if mean > 0
            else 0.0)

    # ------------------------------------------------------------------
    # grammar-constrained decoding (models/structured.py)
    # ------------------------------------------------------------------

    def _grammar_live(self) -> bool:
        return any(self._grammar[b] is not None
                   for b in self.decode_slots)

    def _mask_chunk(self) -> Optional[np.ndarray]:
        """[B, V] allowed-token mask for one decode tick, or None when
        no armed slot is constrained — None keeps the tick on the
        mask-free jit entry (zero new XLA programs per unconstrained
        poll, the churn-guard contract)."""
        if not self._grammar_live():
            return None
        mask = np.ones((self.batch, self._vocab_size), bool)
        for b in self.decode_slots:
            g = self._grammar[b]
            if g is not None:
                row = g.allowed_row()
                if row.any():
                    mask[b] = row
        return mask

    def _mask_window(self, tokens, q_lens) -> Optional[np.ndarray]:
        """[B, S, V] per-position verify-window mask (spec mode), or
        None when no armed slot is constrained. Position j of a row
        constrains the prediction AFTER tokens[b, :j+1]
        (structured.window_masks has the safety argument for the
        all-True rows past a walk break)."""
        if not self._grammar_live():
            return None
        S = tokens.shape[1]
        mask = np.ones((self.batch, S, self._vocab_size), bool)
        for b in self.decode_slots:
            g = self._grammar[b]
            if g is not None:
                mask[b] = window_masks(g, tokens[b], int(q_lens[b]))
        return mask

    def _grammar_advance(self, b: int, kept) -> None:
        """Advance slot b's automaton over its just-emitted tokens; a
        completed grammar (is_final) finishes the stream early, a dead
        end flags grammar_dead[b] for the scheduler's loud per-request
        error."""
        g = self._grammar[b]
        for t in np.asarray(kept).reshape(-1):
            ok = g.advance(int(t))
            self._c_mask_tokens.inc()
            if not ok or g.is_dead:
                self.grammar_dead[b] = (
                    f"grammar dead end after "
                    f"{self.emitted_since_admit(b)} tokens: no legal "
                    f"continuation from the automaton state")
                self.remaining[b] = 0
                break
            if g.is_final:
                self.remaining[b] = 0
                break
        self._grammar_steps += 1

    def _finish_grammar(self, out: Dict[int, np.ndarray],
                        finished: List[Tuple[int, object]]) -> None:
        """Post-tick automaton advance for the deterministic (non-spec)
        paths: walk each constrained slot's emitted tokens and finish
        the stream when its grammar completes (or dies) — the budget
        zeroing in _grammar_advance is what ends it early."""
        fin = {b for b, _ in finished}
        for b, kept in out.items():
            if self._grammar[b] is None or not len(kept):
                continue
            self._grammar_advance(b, kept)
            if self.remaining[b] == 0 and b not in fin:
                finished.append((b, self.rids[b]))
                fin.add(b)

    def _run_chunk(self, chunk: int):
        """Engine-call hook: DISPATCH one chunk of the slot scan (paged
        variant swaps in paged_slot_chunk). Returns the tick's token
        array still on device — the caller lands it through _fetch
        (sync: immediately; overlap: one poll later)."""
        toks, self.logits, self.cache, self.pos, self.keys = \
            self.engine.slot_chunk(self.logits, self.cache, self.pos,
                                   self.active, chunk=chunk,
                                   keys=self.keys,
                                   mask=self._mask_chunk())
        return toks

    def _record(self, slot: int, toks) -> None:
        """Hook: paged slots record kept tokens for the retire-time
        prefix-tree insert; the contiguous path keeps nothing."""

    def _run_verify(self, tokens, q_lens):
        """Engine-call hook: DISPATCH one spec verify forward (paged
        variant swaps in paged_slot_verify_chunk). Returns device
        (n_emit, t0_next) — landed via _fetch."""
        n_emit, t0n, self.cache, self.pos, self.keys = \
            self.engine.slot_verify_chunk(
                self.cache, self.pos, self.active, tokens, q_lens,
                keys=self.keys, mask=self._mask_window(tokens, q_lens))
        return n_emit, t0n

    def _draft_into(self, tokens: np.ndarray, q_lens: np.ndarray,
                    b: int) -> None:
        """Fill row b of a verify window: the slot's pending seed token
        at column 0 plus up to `spec` drafter proposals (capped at
        remaining - 1, so a slot never writes past its budget). Shared
        by the pure-spec step and the mixed prefill+decode tick."""
        tokens[b, 0] = self._t0[b]
        self._forced_from[b] = NO_FORCED
        kmax = min(self.spec, int(self.remaining[b]) - 1)
        if kmax > 0:
            # append the pending seed for the lookup, then undo — the
            # drafter sees a ZERO-COPY window over the log (no per-step
            # rebuild of the growing history)
            h = self._hist[b]
            h.append(int(self._t0[b]))
            try:
                d = [int(t) for t in
                     self.drafter.propose(h, kmax)][:kmax]
                if any(not 0 <= t < self._vocab for t in d):
                    raise ValueError(f"draft token out of vocab "
                                     f"range [0, {self._vocab})")
            except Exception:
                # a broken drafter degrades to plain decode for
                # this window (the verify still emits the seed
                # token) — it must never take down the model loop
                self._drafter_errors.inc()
                d = []
            finally:
                h.pop()
            g = self._grammar[b]
            if g is not None:
                # grammar stacking: the foreign draft is cut at its
                # first grammar-illegal token, then the window extends
                # with the automaton's FORCED run — jump-ahead: under
                # the mask a forced token is the ONLY legal token at
                # its position, so masked verification accepts the
                # whole deterministic segment in one forward
                d, self._forced_from[b] = constrained_draft(
                    g, int(self._t0[b]), d, kmax)
        else:
            d = []
        tokens[b, 1:1 + len(d)] = d
        q_lens[b] = 1 + len(d)

    def _account_spec(self, b: int, tokens, q_lens, n_emit, t0n,
                      out: Dict[int, np.ndarray],
                      finished: List[Tuple[int, object]]) -> None:
        """Post-verify bookkeeping for one DECODE slot (shared by the
        pure-spec step and the mixed tick): trim the accepted window to
        the remaining budget, thread counters/history, stage the next
        seed token."""
        keep = int(min(self.remaining[b], n_emit[b]))
        g = self._grammar[b]
        if keep and g is not None:
            # walk the REAL automaton over the accepted window: the
            # stream keeps tokens up to a grammar completion (or dead
            # end), and forced tokens kept past the base draft count
            # as jump-ahead wins
            keep2 = 0
            for t in tokens[b, :keep]:
                ok = g.advance(int(t))
                self._c_mask_tokens.inc()
                if not ok or g.is_dead:
                    self.grammar_dead[b] = (
                        "grammar dead end: no legal continuation "
                        "from the automaton state")
                    break
                keep2 += 1
                if g.is_final:
                    break
            self._c_jump.inc(max(0, keep2 - int(self._forced_from[b])))
            self._grammar_steps += 1
            keep = keep2
            if b in self.grammar_dead or g.is_final:
                self.remaining[b] = min(self.remaining[b], keep)
        if keep:
            kept = tokens[b, :keep].copy()
            out[b] = kept
            self.remaining[b] -= keep
            self._hist[b].extend(kept)
            self._record(b, kept)
            self._spec_slot_steps.inc()
            self._spec_emitted.inc(keep)
            self._spec_drafted[b] += int(q_lens[b]) - 1
            self._spec_accepted[b] += keep - 1
            self._spec_drafted_total.inc(int(q_lens[b]) - 1)
            self._spec_accepted_total.inc(keep - 1)
            self._t0[b] = int(t0n[b])
        if self.remaining[b] == 0:
            finished.append((b, self.rids[b]))

    def _step_spec(self) -> Tuple[Dict[int, np.ndarray],
                                  List[Tuple[int, object]]]:
        """One speculative draft-then-verify iteration
        (models/spec_decode.py): the drafter proposes up to `spec`
        continuations of each slot's history + pending seed token
        (capped at remaining - 1, so a slot never writes past its
        budget), ONE verify forward scores every window, and each slot
        keeps its seed plus the accepted draft prefix. The corrected
        token returned by the verify becomes the next window's seed."""
        S = self.spec + 1
        tokens = np.zeros((self.batch, S), np.int32)
        q_lens = np.ones((self.batch,), np.int32)
        with self.tele.phase("drafter"):
            for b in self.decode_slots:
                self._draft_into(tokens, q_lens, b)
        self.tele.mark_dispatch("verify")
        n_emit, t0n = self._fetch(self._run_verify(tokens, q_lens))
        n_emit, t0n = np.asarray(n_emit), np.asarray(t0n)
        self._spec_steps.inc()
        out: Dict[int, np.ndarray] = {}
        finished: List[Tuple[int, object]] = []
        for b in self.decode_slots:
            self._account_spec(b, tokens, q_lens, n_emit, t0n, out,
                               finished)
        return out, finished

    @property
    def stats(self) -> dict:
        """Speculative-decoding counters (empty when spec == 0):
        LIFETIME aggregate accept rate (accepted drafts / proposed
        drafts — survives slot reuse, consistent with spec_emitted /
        spec_steps), tokens emitted per slot per verify forward (1.0 =
        no speculation win, K+1 = every draft accepted), and the
        per-slot counter arrays for the CURRENT occupants. Grammar
        runs additionally report the constrained-decoding counters
        (grammar_mask_tokens / jump_ahead_tokens /
        constrained_tokens_per_step)."""
        out: dict = {}
        if self._grammar_steps:
            per_step = (self._c_mask_tokens.value
                        / self._grammar_steps)
            self._g_constrained.set(round(per_step, 3))
            out.update({
                "grammar_mask_tokens": self._c_mask_tokens.value,
                "jump_ahead_tokens": self._c_jump.value,
                "constrained_tokens_per_step": round(per_step, 3),
            })
        if not self.spec:
            return out
        drafted = self._spec_drafted_total.value
        accepted = self._spec_accepted_total.value
        slot_steps = self._spec_slot_steps.value
        out.update({
            "spec": self.spec,
            "spec_steps": self._spec_steps.value,
            "spec_drafted": drafted,
            "spec_accepted": accepted,
            "spec_emitted": self._spec_emitted.value,
            "spec_accept_rate": (accepted / drafted) if drafted else 0.0,
            "tokens_per_step": (self._spec_emitted.value / slot_steps
                                if slot_steps else 0.0),
            "spec_accepted_per_slot": self._spec_accepted.tolist(),
            "spec_drafted_per_slot": self._spec_drafted.tolist(),
            "drafter_errors": self._drafter_errors.value,
        })
        return out

    def step_chunk(self, chunk: int) -> Tuple[Dict[int, np.ndarray],
                                              List[Tuple[int, object]]]:
        """Run one `chunk`-step slot scan. Returns ({slot: kept tokens
        (trimmed to the slot's remaining budget)}, [(slot, rid) of
        requests that just finished]). Finished slots are NOT retired
        here — the caller streams their tail first, then retires.

        In spec mode (spec=K) one call is one draft-then-verify
        iteration instead of `chunk` single-token steps: each live slot
        emits 1..K+1 tokens per call (seed + accepted drafts)."""
        if self.spec:
            return self._step_spec()
        self.tele.mark_dispatch(self._tick_kind())
        (toks,) = self._fetch((self._run_chunk(chunk),))
        toks = np.asarray(toks)
        plan, finished = self._plan_chunk(chunk)
        out: Dict[int, np.ndarray] = {}
        for b, _, keep in plan:
            out[b] = toks[b, :keep]
            self._record(b, toks[b, :keep])
        self._finish_grammar(out, finished)
        return out, finished

    def _plan_chunk(self, chunk: int, skip=frozenset()
                    ) -> Tuple[list, list]:
        """The deterministic non-spec emission plan of one chunk tick:
        charge each armed slot min(remaining, chunk) and list the
        (slot, rid, keep) rows plus the slots that finish. ONE copy of
        the budget arithmetic, shared by the sync step (which fills in
        the landed token values immediately) and the overlap dispatch
        (which defers them to land()) — the bitwise overlap-on==off
        contract rides on these never drifting."""
        plan, finishing = [], []
        for b in self.decode_slots:
            if b in skip:
                continue
            keep = int(min(self.remaining[b], chunk))
            if keep:
                plan.append((b, self.rids[b], keep))
                self.remaining[b] -= keep
            if self.remaining[b] == 0:
                finishing.append((b, self.rids[b]))
        return plan, finishing

    # ------------------------------------------------------------------
    # chunked prefill: the mixed prefill+decode tick (Sarathi-Serve)
    # ------------------------------------------------------------------

    def _run_mixed(self, tokens, q_lens, pf):
        """Engine hook: DISPATCH one non-spec mixed tick (paged variant
        swaps in paged_slot_mixed_chunk). Updates the carry logits to
        each row's last-valid-window-position logits — a decode row's
        next carry, a final-chunk prefill row's arming logits. Returns
        the device token array (landed via _fetch)."""
        toks, self.logits, self.cache, self.pos, self.keys = \
            self.engine.slot_mixed_chunk(
                self.logits, self.cache, self.pos, self.active, pf,
                tokens, q_lens, keys=self.keys,
                mask=self._mask_chunk())
        return toks

    def _run_mixed_verify(self, tokens, q_lens, pf):
        """Engine hook: DISPATCH one spec-mode mixed tick. The returned
        arming logits replace the (spec-unused) carry so _arm_slot can
        read them per completed prefill. Returns device
        (n_emit, t0_next) — landed via _fetch."""
        n_emit, t0n, self.logits, self.cache, self.pos, self.keys = \
            self.engine.slot_mixed_verify_chunk(
                self.cache, self.pos, self.active, pf, tokens, q_lens,
                keys=self.keys, mask=self._mask_window(tokens, q_lens))
        return n_emit, t0n

    def _pf_record(self, slot: int, toks) -> None:
        """Hook: paged slots extend the VALID-extent token mirror as
        prefill chunks land (retire/preempt mid-prefill must donate
        only tokens whose KV was actually computed)."""

    def _pf_armed(self, slot: int) -> None:
        """Hook: paged slots insert the fully-prefilled prompt into the
        radix tree here (only now is its KV complete — inserting at
        admission, as the monolithic path does, would poison the cache
        with pages the chunks have not written yet)."""

    def step_mixed(self, budget: int) -> Tuple[Dict[int, np.ndarray],
                                               List[Tuple[int, object]]]:
        """One MIXED prefill+decode tick (chunked prefill): ONE forward
        covers every armed decode slot (q_len = 1, or its spec draft
        window) and up to `budget` prompt tokens of in-progress
        prefills, split FIFO by admission order (the oldest admission
        finishes its prefill — and starts streaming — soonest). A
        prefill whose final chunk lands this tick ARMS: its
        last-position logits become the slot's carry and it joins
        decode next tick, exactly as if a monolithic admission had just
        returned. Decode slots emit one token per tick (or their
        accepted spec window) — the most prefill work any live stream
        ever waits on between two of its tokens is `budget` tokens.
        Same return contract as step_chunk."""
        tokens, q_lens, pf, chunks = self._build_mixed_window(budget)
        decode = self.decode_slots
        out: Dict[int, np.ndarray] = {}
        finished: List[Tuple[int, object]] = []
        if self.spec:
            with self.tele.phase("drafter"):
                for b in decode:
                    self._draft_into(tokens, q_lens, b)
            self.tele.mark_dispatch("mixed_verify")
            n_emit, t0n = self._fetch(
                self._run_mixed_verify(tokens, q_lens, pf))
            n_emit, t0n = np.asarray(n_emit), np.asarray(t0n)
            self._spec_steps.inc()
            for b in decode:
                self._account_spec(b, tokens, q_lens, n_emit, t0n, out,
                                   finished)
        else:
            self.tele.mark_dispatch("mixed")
            (toks,) = self._fetch((self._run_mixed(tokens, q_lens, pf),))
            toks = np.asarray(toks)
            plan, finished = self._plan_mixed_decode(decode)
            for b, _, _ in plan:
                kept = toks[b:b + 1].copy()
                out[b] = kept
                self._record(b, kept)
            self._finish_grammar(out, finished)
        # advance the prefills; arm the ones whose final chunk landed
        self._advance_prefills(chunks)
        return out, finished

    def _build_mixed_window(self, budget: int):
        """One mixed tick's window: prefill chunk rows split by SLO
        protection rank (highest class first — an interactive prompt
        absorbs budget before a batch one), FIFO by admission order
        within a rank, under the token budget (q_len 0 = starved, no
        progress). Uniform classes make the rank key constant, so the
        split is the original pure-FIFO one bitwise. ONE copy of the
        split arithmetic, shared by the sync step and the overlap
        dispatch. Returns (tokens, q_lens, pf mask,
        {slot: chunk len})."""
        S = max(int(budget), (self.spec + 1) if self.spec else 1)
        tokens = np.zeros((self.batch, S), np.int32)
        q_lens = np.ones((self.batch,), np.int32)
        pf = np.zeros((self.batch,), bool)
        left = int(budget)
        chunks: Dict[int, int] = {}
        for b in sorted(self.prefill_slots,
                        key=lambda b: (-self.slo_priority(b),
                                       self.admit_tick[b])):
            ids = self._pf_ids[b]
            off = int(self._pf_off[b])
            c = min(len(ids) - off, left, S)
            pf[b] = True
            q_lens[b] = c          # 0 = budget-starved, no progress
            if c:
                tokens[b, :c] = ids[off:off + c]
                chunks[b] = c
            left -= c
        return tokens, q_lens, pf, chunks

    def _plan_mixed_decode(self, decode) -> Tuple[list, list]:
        """Mixed-tick twin of _plan_chunk: each live decode row emits
        exactly one token. Shared by the sync step and the overlap
        dispatch."""
        plan, finishing = [], []
        for b in decode:
            if self.remaining[b] > 0:
                plan.append((b, self.rids[b], 1))
                self.remaining[b] -= 1
            if self.remaining[b] == 0:
                finishing.append((b, self.rids[b]))
        return plan, finishing

    def _advance_prefills(self, chunks: Dict[int, int],
                          arm: Optional[list] = None) -> None:
        """Advance the dispatched prefill chunks' offsets/mirrors and
        handle completions: arm immediately (sync, and the non-spec
        overlap dispatch — arming is sync-free there), or defer by
        appending (slot, req, n) to `arm` (spec overlap: the arming
        logits have not landed yet)."""
        for b, c in chunks.items():
            self.prefill_forwarded += c
            ids = self._pf_ids[b]
            off = int(self._pf_off[b])
            self._pf_record(b, ids[off:off + c])
            self._pf_off[b] = off + c
            self.tele.req_event(self.rids[b], "prefill_chunk", c)
            if self._pf_off[b] == len(ids):
                req = self.reqs[b]
                self._pf_ids[b] = None
                self._pf_off[b] = 0
                if arm is not None:
                    arm.append((b, req, len(ids)))
                else:
                    self._arm_slot(b, req, self.logits[b], len(ids))
                    self._pf_armed(b)
                    if self.on_armed is not None:
                        self.on_armed(b)

    # ------------------------------------------------------------------
    # overlap scheduling: the dispatch/land split (module docstring).
    # begin_* dispatches the SAME engine program its sync step_* twin
    # runs (identical shapes — no new executables) and fixes the
    # emission plan on the host; land() fetches the landed values ONE
    # coalesced device_get later and finishes the bookkeeping that
    # needed them. ContinuousScheduler(overlap=True) drives these.
    # ------------------------------------------------------------------

    def begin_chunk(self, chunk: int, skip=frozenset()) -> None:
        """Dispatch one decode tick WITHOUT reading it back. Non-spec:
        the emission plan is host-deterministic (each armed slot emits
        min(remaining, chunk) tokens), so budgets are charged and
        finishing slots' active masks cleared NOW — the next dispatch
        can run before this tick lands — and only the token VALUES
        (streaming, the paged token mirrors, retirement) wait for
        land(). spec=K delegates to begin_spec (drafts need landed
        history, so the spec pipeline lands within its own poll and
        overlaps the deferred bookkeeping instead). `skip`: slots that
        landed as finished but are not yet retired — no part of this
        tick."""
        assert self._inflight is None, "land() the previous tick first"
        if self.spec:
            self.begin_spec(skip)
            return
        kind = self._tick_kind()
        self.tele.mark_dispatch(kind)
        toks_dev = self._run_chunk(chunk)
        plan, finishing = self._plan_chunk(chunk, skip)
        for b, _ in finishing:
            # masked out of the NEXT tick at dispatch time (sync
            # retires between ticks; the retire itself waits for
            # land — the radix-tree insert needs the token values)
            self.active = self.active.at[b].set(False)
        self._inflight = _InFlight(kind, (toks_dev,), plan, finishing)

    def begin_spec(self, skip=frozenset()) -> None:
        """Dispatch one spec verify tick: drafting reads the LANDED
        history (that is why the spec pipeline cannot dispatch-ahead
        across polls), accept counts are data-dependent, so the whole
        emission plan defers to land()."""
        assert self._inflight is None, "land() the previous tick first"
        S = self.spec + 1
        tokens = np.zeros((self.batch, S), np.int32)
        q_lens = np.ones((self.batch,), np.int32)
        plan = []
        with self.tele.phase("drafter"):
            for b in self.decode_slots:
                if b in skip:
                    continue
                self._draft_into(tokens, q_lens, b)
                plan.append((b, self.rids[b]))
        self.tele.mark_dispatch("verify")
        arrs = self._run_verify(tokens, q_lens)
        self._spec_steps.inc()
        self._inflight = _InFlight("spec", arrs, plan, [],
                                   tokens=tokens, q_lens=q_lens)

    def begin_mixed(self, budget: int, skip=frozenset()) -> None:
        """Dispatch one mixed prefill+decode tick (step_mixed's
        dispatch half). Prefill offsets/mirrors advance NOW (the chunk
        contents are host-known prompt tokens) and a completed
        prefill's arming is sync-free under non-spec (the carry rows
        are device futures); spec arming needs the landed logits so it
        rides the pipeline register to land()."""
        assert self._inflight is None, "land() the previous tick first"
        tokens, q_lens, pf, chunks = self._build_mixed_window(budget)
        decode = [b for b in self.decode_slots if b not in skip]
        if self.spec:
            with self.tele.phase("drafter"):
                for b in decode:
                    self._draft_into(tokens, q_lens, b)
            self.tele.mark_dispatch("mixed_verify")
            arrs = self._run_mixed_verify(tokens, q_lens, pf)
            self._spec_steps.inc()
            inf = _InFlight("mixed_spec", arrs,
                            [(b, self.rids[b]) for b in decode], [],
                            tokens=tokens, q_lens=q_lens)
        else:
            self.tele.mark_dispatch("mixed")
            toks_dev = self._run_mixed(tokens, q_lens, pf)
            plan, finishing = self._plan_mixed_decode(decode)
            for b, _ in finishing:
                self.active = self.active.at[b].set(False)
            inf = _InFlight("mixed", (toks_dev,), plan, finishing)
        # advance the prefills at dispatch time (host-deterministic);
        # spec arming waits for the landed logits (inf.arm)
        self._advance_prefills(chunks, inf.arm if self.spec else None)
        self._inflight = inf

    def land(self) -> Tuple[Dict[int, np.ndarray],
                            List[Tuple[int, object]]]:
        """Fetch the in-flight tick (ONE coalesced device_get) and run
        the value-dependent half of its bookkeeping. Same return
        contract as step_chunk — finished slots are NOT retired here;
        the caller streams their tail first, then retires. No-op
        ({}, []) when nothing is in flight."""
        inf, self._inflight = self._inflight, None
        if inf is None:
            return {}, []
        out: Dict[int, np.ndarray] = {}
        finished: List[Tuple[int, object]] = []
        if inf.kind in ("chunk", "mega", "sp", "mixed"):
            (toks,) = self._fetch(inf.arrs,
                                  kind=_INFLIGHT_KIND[inf.kind])
            toks = np.asarray(toks)
            for b, rid, keep in inf.plan:
                assert self.rids[b] == rid, \
                    "slot reassigned under an unlanded tick"
                kept = (toks[b:b + 1] if inf.kind == "mixed"
                        else toks[b, :keep]).copy()
                out[b] = kept
                self._record(b, kept)
            finished = inf.finishing
        else:                                  # "spec" / "mixed_spec"
            n_emit, t0n = self._fetch(inf.arrs,
                                      kind=_INFLIGHT_KIND[inf.kind])
            n_emit, t0n = np.asarray(n_emit), np.asarray(t0n)
            for b, rid in inf.plan:
                assert self.rids[b] == rid, \
                    "slot reassigned under an unlanded tick"
                self._account_spec(b, inf.tokens, inf.q_lens, n_emit,
                                   t0n, out, finished)
            for b, _ in finished:
                # sync clears this inside retire(); the overlap spec
                # pipeline STAGES the retire for the next poll, and the
                # next verify dispatch must not step a finished slot
                self.active = self.active.at[b].set(False)
            for b, req, n in inf.arm:
                self._arm_slot(b, req, self.logits[b], n)
                self._pf_armed(b)
                if self.on_armed is not None:
                    self.on_armed(b)
        return out, finished


class PagedDecodeSlots(DecodeSlots):
    """DecodeSlots over the PAGED pool with the shared-prefix radix
    cache (models/prefix_cache.py): admission consults the radix tree
    for the longest cached prefix, maps those pages READ-ONLY into the
    slot's table rows (refcount +1 each), copy-on-writes the partially
    matched boundary page, and prefills ONLY the uncached suffix
    (engine.admit_slot_paged's prefill-from-offset). Retirement inserts
    the finished sequence (prompt + generated) back into the tree —
    donating the slot's pages — so the NEXT request sharing the prefix
    skips that prefill work. With prefix_cache=False the same programs
    run with a never-matching tree (the bitwise cache-off reference).

    margin: the slot scan keeps stepping a finished slot to its chunk
    boundary; those surplus writes land in the slot's own reserved
    pages (or the trash page past its table rows), so every admission
    reserves capacity for prompt + gen + margin - 1 positions. Pass
    the scheduler's chunk."""

    def __init__(self, engine, batch: int, *, page: int = 16,
                 num_pages: Optional[int] = None,
                 prefix_cache: bool = True, margin: int = 4,
                 spec: int = 0, drafter=None,
                 host_pool_pages: int = 0, fault=None,
                 telemetry: Optional[Telemetry] = None):
        """host_pool_pages > 0 attaches the HOST-RAM KV TIER
        (models/kv_tier.py): LRU eviction demotes unreferenced spans
        to a host pool of that many device-page-sized buffers (d2h
        gather at evict time) instead of dropping them, and a prefix
        match on a host-resident path promotes the span back into
        fresh device pages (h2d install) before the suffix prefill —
        the effective cache grows to num_pages + host_pool_pages while
        streams stay bitwise identical (tests/test_kv_tier.py).
        Meaningful only with prefix_cache=True (a never-matching tree
        never demotes). fault: chaos hook consulted on demotions
        (runtime/chaos.py::FaultInjector.host_demotion)."""
        from triton_dist_tpu.models.prefix_cache import PrefixCache
        self.page = page
        self.margin = margin
        self._num_pages = num_pages
        super().__init__(engine, batch, spec=spec, drafter=drafter,
                         telemetry=telemetry)
        Hkv = engine.model.config.num_kv_heads
        # the prefix cache publishes its counters into the SAME
        # registry, so the scheduler's stats() snapshot covers it
        # a SEQUENCE-PARALLEL pool partitions the page-id space per sp
        # shard (kv_cache.PagedSlotCache SP SHARDING): the allocator
        # mirrors that split host-side and rotates fresh groups across
        # shards so a slot's logical tiles interleave chips
        self.prefix = PrefixCache(self.cache.num_pages, Hkv, page,
                                  enabled=prefix_cache,
                                  host_pool_pages=host_pool_pages,
                                  fault=fault, telemetry=self.tele,
                                  shards=self.cache.sp)
        if host_pool_pages:
            self.prefix.attach_host_tier(self._tier_extract,
                                         self._tier_restore)
        # both sides reserve the same trash page (pool page 0)
        assert self.prefix.pool.trash == self.cache.trash
        # per-slot host mirrors: mapped page groups (absolute page
        # order) and the token stream (prompt + kept generated) whose
        # KV those pages hold — the retire-time tree insert. _TokenLog:
        # amortized-O(1) appends + zero-copy views for the tree insert
        # and the preemption snapshot (the list mirrors' per-call
        # rebuilds were O(generated^2) host work over a stream)
        self._groups: List[List[np.ndarray]] = [[] for _ in range(batch)]
        self._tokens: List[_TokenLog] = [_TokenLog()
                                         for _ in range(batch)]
        # KV fork (parallel sampling — fork() below): per-slot flag
        # backing the forks_active gauge, plus the sharing counters
        self._is_fork = np.zeros((batch,), bool)
        freg = self.tele.registry
        self._c_fork_shared = freg.counter(
            "fork_shared_pages",
            "pages mapped shared (refcount+1) by slot forks")
        self._c_fork_cow = freg.counter(
            "fork_cow_breaks",
            "boundary pages copy-on-written at fork time")
        self._g_forks = freg.gauge(
            "forks_active", "live forked decode slots")

    def _make_cache(self):
        return self.engine.make_paged_slot_cache(
            self.batch, page=self.page, num_pages=self._num_pages)

    def _tick_kind(self) -> str:
        # backend='mega' routes the pure-decode paged tick through the
        # fused megakernel program (engine.paged_slot_chunk) — mixed
        # ticks still dispatch per-op and keep their "mixed" kind.
        # A SEQUENCE-PARALLEL pool's decode tick runs the split-KV
        # partial + cross-chip LSE combine (layers/tp_attn.py
        # fwd_cached_slots_paged_sp) — attributed as "sp_combine" in
        # device_wait_kind_s so an operator sees what the long-context
        # path actually waits on.
        if self.engine.backend == "mega":
            return "mega"
        if getattr(self.engine, "sp_size", 1) > 1:
            return "sp"
        return "chunk"

    # host KV tier copy callbacks (prefix_cache.attach_host_tier):
    # the residency machine calls these from inside evict_until /
    # promote_path — always on the driver thread, with self.cache the
    # live paged pool, so the jitted gather/scatter sequence correctly
    # with the admission/decode programs through data dependence.

    def _tier_extract(self, groups):
        """Demotion d2h: snapshot the span's pages (all layers). An
        int8 pool's payload carries the scale planes too ("ks"/"vs")
        — the d2h/h2d round trip stays bitwise for both layouts,
        including the TP-sharded pool: each group is head-ordered, so
        the per-page kv-head indices passed here let the gather pick
        every page's owning payload plane (Engine.extract_pages_host
        heads contract)."""
        ids = np.concatenate([np.asarray(g, np.int32) for g in groups])
        Hkv = self.engine.model.config.num_kv_heads
        heads = np.tile(np.arange(Hkv, dtype=np.int32), len(groups))
        out = self.engine.extract_pages_host(self.cache, ids,
                                             heads=heads)
        return dict(zip(("k", "v", "ks", "vs"), out))

    def _tier_restore(self, payload, groups) -> None:
        """Promotion h2d: install a snapshot into fresh pages."""
        ids = np.concatenate([np.asarray(g, np.int32) for g in groups])
        self.cache = self.engine.restore_pages_host(
            self.cache, ids, payload["k"], payload["v"],
            payload.get("ks"), payload.get("vs"))

    @property
    def capacity(self) -> int:
        """Admittable prompt+gen budget (table capacity minus the
        chunk-surplus margin)."""
        return self.cache.capacity - self.margin + 1

    @property
    def stats(self) -> dict:
        out = dict(DecodeSlots.stats.fget(self))   # spec + grammar
        nf = int(self._is_fork.sum())
        self._g_forks.set(nf)
        out["forks_active"] = nf
        out["fork_shared_pages"] = self._c_fork_shared.value
        out["fork_cow_breaks"] = self._c_fork_cow.value
        out.update(self.prefix.stats())
        return out

    def validate_admission(self, req: Request, tokens: np.ndarray
                           ) -> None:
        """The cheap upfront refusals of a paged admission — ONE copy,
        shared by _reserve_pages and the disagg scheduler's routing
        (models/disagg.py rejects before burning prefill-plane work):
        - empty prompt: the suffix forward needs at least one token
          (and a zero-length prompt would leak the refs _reserve_pages
          retains when the engine refused it);
        - prompt + gen beyond slot capacity;
        - TOTAL footprint beyond the whole pool (shared + fresh groups
          must all coexist): reject upfront with a plain ValueError so
          the scheduler does not preempt every live slot discovering
          it (the cheap denial-of-service a repeated never-fits
          request would otherwise buy)."""
        n = len(tokens)
        if n == 0:
            raise ValueError(f"request {req.rid!r}: empty prompt")
        if n + req.gen_len > self.capacity:
            raise ValueError(
                f"request {req.rid!r}: prompt {n} + gen {req.gen_len} "
                f"exceeds slot capacity {self.capacity}")
        pool = self.prefix.pool
        total = -(-(n + req.gen_len + self.margin - 1) // self.page)
        usable = (pool.num_pages - 1) // pool.n_kv_heads
        if total > usable:
            raise ValueError(
                f"request {req.rid!r}: worst-case footprint {total} "
                f"page groups exceeds the whole pool ({usable} usable "
                f"groups) — page pool exhausted for this request alone")

    def _reserve_pages(self, req: Request, tokens: np.ndarray):
        """Validation + prefix lookup + page reservation shared by the
        monolithic and CHUNKED paged admissions. Returns (slot_groups,
        m, rows, cow_src, cow_dst, r, boundary) with every ref taken
        (release `boundary` after the device-side CoW ran); raises with
        everything released."""
        n = len(tokens)
        self.validate_admission(req, tokens)
        pool = self.prefix.pool
        # total page groups the admitted slot will map (shared + fresh
        # must all coexist in the pool); `need` below is total - full
        total = -(-(n + req.gen_len + self.margin - 1) // self.page)
        m, shared = self.prefix.lookup(tokens)
        full, r = m // self.page, m % self.page
        retained: List[np.ndarray] = []
        fresh: List[np.ndarray] = []
        try:
            # pin everything the admission program will read BEFORE
            # eviction can run
            for g in shared[:full]:
                pool.retain(g)
                retained.append(g)
            boundary = shared[full] if r else None
            if boundary is not None:
                pool.retain(boundary)
                retained.append(boundary)
            need = total - full
            if not self.prefix.ensure_pages(need * pool.n_kv_heads):
                from triton_dist_tpu.models.prefix_cache import \
                    PoolExhausted
                raise PoolExhausted(
                    f"request {req.rid!r}: page pool exhausted "
                    f"({need} fresh groups needed, "
                    f"{pool.available} pages free, nothing evictable)")
            fresh = [pool.alloc_group() for _ in range(need)]
        except ValueError:
            for g in fresh + retained:
                pool.release(g)
            raise
        slot_groups = list(shared[:full]) + fresh
        Hkv, maxp = pool.n_kv_heads, self.cache.table.shape[1]
        rows = np.full((Hkv, maxp), self.cache.trash, np.int32)
        for j, g in enumerate(slot_groups):
            rows[:, j] = g
        trash_vec = np.full((Hkv,), self.cache.trash, np.int32)
        cow_src = boundary if r else trash_vec
        cow_dst = fresh[0] if r else trash_vec
        return slot_groups, m, rows, cow_src, cow_dst, r, boundary

    def admit(self, slot: int, req: Request) -> None:
        """Consult the radix tree, map the cached prefix read-only,
        allocate fresh writable pages for the rest (evicting LRU tree
        leaves under pressure), and prefill the uncached suffix."""
        assert self.rids[slot] is None, f"slot {slot} is occupied"
        tokens = np.asarray(req.ids, np.int32).reshape(-1)
        n = len(tokens)
        slot_groups, m, rows, cow_src, cow_dst, r, boundary = \
            self._reserve_pages(req, tokens)
        pool = self.prefix.pool
        row, self.cache = self.engine.admit_slot_paged(
            self.cache, slot, tokens, rows, m, cow_src, cow_dst, r)
        if boundary is not None:
            # only the CoW copy read it; the slot maps its own copy
            pool.release(boundary)
        self.prefill_forwarded += n - m
        self._arm_slot(slot, req, row, n)
        self._groups[slot] = slot_groups
        self._tokens[slot] = _TokenLog(tokens)
        self.prefix.record(n, m)
        # insert the PROMPT pages now (not just at retire): the next
        # admission — even one in the same poll — can already share
        # them. N clients connecting at once with one system prompt is
        # the headline case, and they must not all prefill it.
        self.prefix.insert(tokens, slot_groups[:-(-n // self.page)])

    def admit_chunked(self, slot: int, req: Request) -> None:
        """Chunked paged admission: everything that must happen ONCE —
        prefix lookup, page reservation, table install, boundary-page
        copy-on-write (engine.install_slot_paged) — runs at chunk 0;
        the uncached-suffix forward is left to the step_mixed ticks,
        which scatter their KV through the table just installed. The
        token mirror starts at the CACHED extent (tokens[:m] — their
        pages already hold valid KV) and grows only as chunks land, so
        a retire/preempt/cancel mid-prefill donates exactly what was
        computed; the prompt joins the radix tree at ARMING
        (_pf_armed), not at admission, because until the final chunk
        its fresh pages hold garbage."""
        assert self.rids[slot] is None, f"slot {slot} is occupied"
        tokens = np.asarray(req.ids, np.int32).reshape(-1)
        n = len(tokens)
        slot_groups, m, rows, cow_src, cow_dst, r, boundary = \
            self._reserve_pages(req, tokens)
        self.cache = self.engine.install_slot_paged(
            self.cache, slot, rows, cow_src, cow_dst, r)
        if boundary is not None:
            self.prefix.pool.release(boundary)
        self._groups[slot] = slot_groups
        self._tokens[slot] = _TokenLog(tokens[:m])
        self.prefix.record(n, m)
        self._park_prefilling(slot, req, tokens, m)

    def fork(self, parent: int, slot: int, req: Request) -> None:
        """Clone slot `parent`'s sequence into free slot `slot` — the
        KV fork of parallel sampling (PagedAttention's headline
        physical-sharing case): every FULL page of the parent's current
        sequence maps SHARED (refcount+1; read-only for both sides by
        the write-exclusivity rule tools/tdcheck proves), the partially
        filled boundary page copy-on-writes through the same engine
        path a prefix-cache hit uses, and the fork arms from the
        parent's carry logits with its OWN PRNG key (req.seed) —
        bitwise identical to admitting `req` as a fresh request whose
        prompt fully hits the prefix cache. Fork at ARMING, before the
        parent diverges: both streams then match their sequential
        same-seed replays. After this call the fork is an ordinary
        slot — cancel/preempt/retire/eviction need no special cases
        (retire's tree insert dedups against the parent's pages)."""
        assert self.rids[slot] is None, f"slot {slot} is occupied"
        assert self.rids[parent] is not None \
            and self._pf_ids[parent] is None, \
            f"fork parent {parent} must be an ARMED slot"
        pool = self.prefix.pool
        parent_groups = self._groups[parent]
        # own copy: the parent's log keeps growing under the fork
        tokens = self._tokens[parent].view().copy()
        L = len(tokens)
        self.validate_admission(req, tokens)
        full, r = L // self.page, L % self.page
        total = -(-(L + req.gen_len + self.margin - 1) // self.page)
        retained: List[np.ndarray] = []
        fresh: List[np.ndarray] = []
        try:
            # pin the shared prefix (and the boundary the CoW reads)
            # BEFORE eviction can run for the fresh allocations
            for g in parent_groups[:full]:
                pool.retain(g)
                retained.append(g)
            boundary = parent_groups[full] if r else None
            if boundary is not None:
                pool.retain(boundary)
                retained.append(boundary)
            need = total - full
            if not self.prefix.ensure_pages(need * pool.n_kv_heads):
                from triton_dist_tpu.models.prefix_cache import \
                    PoolExhausted
                raise PoolExhausted(
                    f"request {req.rid!r}: page pool exhausted at "
                    f"fork ({need} fresh groups needed, "
                    f"{pool.available} pages free, nothing evictable)")
            fresh = [pool.alloc_group() for _ in range(need)]
        except ValueError:
            for g in fresh + retained:
                pool.release(g)
            raise
        slot_groups = list(parent_groups[:full]) + fresh
        Hkv, maxp = pool.n_kv_heads, self.cache.table.shape[1]
        rows = np.full((Hkv, maxp), self.cache.trash, np.int32)
        for j, g in enumerate(slot_groups):
            rows[:, j] = g
        trash_vec = np.full((Hkv,), self.cache.trash, np.int32)
        cow_src = boundary if r else trash_vec
        cow_dst = fresh[0] if r else trash_vec
        self.cache = self.engine.install_slot_paged(
            self.cache, slot, rows, cow_src, cow_dst, r)
        if boundary is not None:
            # only the CoW copy read it; the fork maps its own copy
            pool.release(boundary)
        self._arm_slot(slot, req, self.logits[parent], L)
        self._groups[slot] = slot_groups
        self._tokens[slot] = _TokenLog(tokens)
        self.prefix.record(L, L)      # the whole prefill was skipped
        self._is_fork[slot] = True
        self._c_fork_shared.inc(full * Hkv)
        if r:
            self._c_fork_cow.inc()

    def preempt(self, slot: int) -> Request:
        """Evict a LIVE slot under pool pressure (vLLM-style recompute
        preemption) and return the request to re-queue. The snapshot is
        tiny because the token sequence IS the state: prompt + kept
        generated tokens become the re-queued request's prompt (its KV
        goes into the radix tree through the normal retire path, so
        re-admission maps the pages back while they survive eviction —
        capped at n-1, only the last token recomputes), gen_len drops
        to the remaining budget, and ResumeState carries what tokens
        cannot encode: the evolved PRNG key (sampled chains continue
        exactly) and the pending spec seed token (already determined,
        never emitted). Works for slots that were themselves resumed —
        ids and the emitted counter just keep accumulating.

        A slot preempted MID-PREFILL (chunked admissions) re-queues its
        ORIGINAL request unchanged — nothing was emitted, so the prompt,
        gen_len, PRNG chain and pending seed are exactly what they were
        at submit (a previously-resumed request keeps its snapshot).
        The computed extent of its prefill still goes into the radix
        tree through retire, so re-admission skips recomputing it while
        the pages survive eviction."""
        req = self.reqs[slot]
        assert req is not None, f"slot {slot} is empty"
        if self._pf_ids[slot] is not None:
            rs = req.resume
            snap = dataclasses.replace(
                rs, preemptions=rs.preemptions + 1) if rs is not None \
                else ResumeState(key=None, t0=None, emitted=0,
                                 preemptions=1)
            self.retire(slot)  # donates the valid prefill extent
            return dataclasses.replace(req, resume=snap)
        # zero-copy: retire() below replaces the log, so the view's
        # buffer is never appended to again — the re-queued request
        # owns it alone
        toks = self._tokens[slot].view()
        remaining = int(self.remaining[slot])
        rs = req.resume
        snap = ResumeState(
            key=self.keys[slot] if self.keys is not None else None,
            t0=int(self._t0[slot]) if self.spec else None,
            emitted=self.emitted(slot),
            preemptions=(rs.preemptions + 1) if rs is not None else 1,
            gstate=(self._grammar[slot].state
                    if self._grammar[slot] is not None else None))
        self.retire(slot)      # tree insert + ref release + trash rows
        return dataclasses.replace(req, ids=toks, gen_len=remaining,
                                   resume=snap)

    def retire(self, slot: int) -> None:
        """Insert the finished sequence back into the tree (the pages
        already hold its KV — insertion is pure bookkeeping), release
        the slot's page refs, and point its table rows at the trash
        page so the masked-out scan rows can never write into a page
        the allocator hands to someone else."""
        if len(self._tokens[slot]):
            npg = -(-len(self._tokens[slot]) // self.page)
            self.prefix.insert(self._tokens[slot].view(),
                               self._groups[slot][:npg])
        for g in self._groups[slot]:
            self.prefix.pool.release(g)
        self.cache = self.engine.retire_slot_paged(self.cache, slot)
        self._groups[slot] = []
        self._tokens[slot] = _TokenLog()
        self._is_fork[slot] = False
        super().retire(slot)

    def _run_chunk(self, chunk: int):
        toks, self.logits, self.cache, self.pos, self.keys = \
            self.engine.paged_slot_chunk(self.logits, self.cache,
                                         self.pos, self.active,
                                         chunk=chunk, keys=self.keys,
                                         mask=self._mask_chunk())
        return toks

    def _run_verify(self, tokens, q_lens):
        n_emit, t0n, self.cache, self.pos, self.keys = \
            self.engine.paged_slot_verify_chunk(
                self.cache, self.pos, self.active, tokens, q_lens,
                keys=self.keys, mask=self._mask_window(tokens, q_lens))
        return n_emit, t0n

    def _run_mixed(self, tokens, q_lens, pf):
        toks, self.logits, self.cache, self.pos, self.keys = \
            self.engine.paged_slot_mixed_chunk(
                self.logits, self.cache, self.pos, self.active, pf,
                tokens, q_lens, keys=self.keys,
                mask=self._mask_chunk())
        return toks

    def _run_mixed_verify(self, tokens, q_lens, pf):
        n_emit, t0n, self.logits, self.cache, self.pos, self.keys = \
            self.engine.paged_slot_mixed_verify_chunk(
                self.cache, self.pos, self.active, pf, tokens, q_lens,
                keys=self.keys, mask=self._mask_window(tokens, q_lens))
        return n_emit, t0n

    def _record(self, slot: int, toks) -> None:
        self._tokens[slot].extend(toks)

    def _pf_record(self, slot: int, toks) -> None:
        # a landed chunk extends the VALID extent — these tokens' KV is
        # now in the slot's pages, so retire/preempt may donate them
        self._tokens[slot].extend(toks)

    def _pf_armed(self, slot: int) -> None:
        # the prompt's KV is complete only now — insert it so the next
        # admission can share it (the monolithic path does this at
        # admit time, where the KV is computed in the same program)
        n = len(self._tokens[slot])
        self.prefix.insert(
            self._tokens[slot].view(),
            self._groups[slot][:-(-n // self.page)])


class ContinuousScheduler:
    """Admit-from-queue / step_chunk / retire loop over DecodeSlots
    (Orca iteration-level scheduling). Single-threaded on the model:
    callers enqueue requests from any thread; one driver thread calls
    poll() (or run()) and owns every jax dispatch."""

    def __init__(self, engine, *, batch: int, chunk: int = 4,
                 paged: bool = False, prefix_cache: bool = True,
                 page: int = 16, num_pages: Optional[int] = None,
                 spec: int = 0, drafter=None,
                 max_queue: Optional[int] = None,
                 watchdog_s: Optional[float] = None,
                 preempt: bool = True, fault=None,
                 prefill_budget: Optional[int] = None,
                 host_pool_pages: int = 0, overlap: bool = False,
                 telemetry: Optional[Telemetry] = None,
                 trace: Optional[bool] = None,
                 slo_classes: Optional[dict] = None):
        """paged=True serves over the paged KV pool with the
        shared-prefix radix cache (models/prefix_cache.py): admissions
        reuse cached prefix pages and skip that prefill work;
        prefix_cache=False keeps the paged pool but never shares (the
        bitwise cache-off reference). num_pages sizes the pool (default:
        worst case, no sharing needed to fit `batch` full slots).

        spec=K > 0 turns each poll's decode step into one speculative
        draft-then-verify iteration (models/spec_decode.py): up to K
        drafter-proposed tokens per slot are scored in ONE forward and
        each slot emits its seed token plus the accepted prefix
        (1..K+1 tokens per forward). Greedy streams are bitwise
        identical to spec=0; sampled streams stay distributionally
        exact. `drafter` defaults to the n-gram/prompt-lookup
        NgramDrafter; stats() then reports spec_accept_rate and
        tokens_per_step.

        Resilience knobs (module docstring has the full story):
        max_queue bounds the waiting line (submit() returns False on
        overflow — backpressure, not an unbounded deque); watchdog_s
        runs each decode chunk under runtime/stress.py::watchdog so a
        hang becomes a HANG verdict in stats() + a HangError, never a
        frozen loop (cost: one short-lived thread per chunk — the
        verdict's price; leave it None when chasing peak loop
        throughput); preempt=False disables KV-pressure preemption
        (pool exhaustion then hard-rejects as before — the differential
        baseline for the bitwise preemption tests); fault is an
        optional chaos hook (runtime/chaos.py::FaultInjector) consulted
        before every admission.

        prefill_budget: CHUNKED PREFILL (Sarathi-Serve, 2403.02310 —
        module docstring). None (default) keeps monolithic admissions;
        an int caps the prompt tokens prefilled per poll across all
        in-progress admissions — while any prefill is in flight, each
        poll runs ONE mixed forward fusing the live decode step with up
        to that many chunk tokens, so the longest stall a live stream
        sees between its tokens is `prefill_budget` prompt tokens
        instead of a whole prompt. Streams are bitwise identical either
        way; tune it to the largest chunk whose added forward latency
        you are willing to put on every live stream's inter-token path
        (decode is bandwidth-bound, so chunks up to a few dozen tokens
        ride the same weight read nearly for free).

        host_pool_pages: HOST-RAM KV TIER (paged path only —
        models/kv_tier.py; PagedDecodeSlots docstring has the design).
        0 (default) keeps single-tier LRU eviction; N > 0 demotes
        evicted spans to a host pool of N device-page-sized buffers
        and promotes them back on a prefix hit, multiplying the
        effective cache to num_pages + N while every stream stays
        bitwise identical. Size it to the host RAM you can pin — tens
        to hundreds of x the HBM pool is the regime it exists for.

        overlap: DISPATCH-AHEAD OVERLAP SCHEDULING (the SGLang
        zero-overhead overlap scheduler — module docstring has the
        pipeline design). False (default) keeps the synchronous poll:
        dispatch, block on the readback, then do host bookkeeping with
        the device idle. True dispatches tick N+1 before reading back
        tick N (non-spec; spec=K overlaps the deferred retire/admit
        work with its in-poll verify instead), so admissions, the
        radix-tree bookkeeping, drafting and the serving layer's
        socket writes all run while the device computes. Streams are
        BITWISE identical either way (tests/test_overlap.py) — tokens
        just arrive one poll later at stream start, and a freed slot
        re-admits one tick later. Watch stats()["host_ms_per_poll"]:
        when it approaches the device step time, overlap=True is the
        difference between host-bound and device-bound serving.

        telemetry/trace (runtime/telemetry.py — module docstring):
        every scheduler owns a Telemetry bundle; its registry holds
        the counters stats() snapshots and the live `ttft_ms` /
        `inter_token_ms` / `poll_ms` histograms. trace=True
        additionally records per-request event rings and the
        perfetto-loadable poll-loop timeline (host phases + device
        occupancy); the default is the TDTPU_TRACE env convention.
        Tracing is host-side only — streams stay bitwise identical
        and no new XLA program compiles (tests/test_telemetry.py).
        Pass `telemetry` to share or pre-configure the bundle.

        slo_classes: {class_name: {"ttft_target_ms": float,
        "itl_target_ms": float}} — the SLO classes requests may tag at
        submit (Request.slo). None registers the telemetry defaults
        (interactive/batch, runtime/telemetry.DEFAULT_SLO_CLASSES).
        Tagged requests land their latencies in per-class histograms
        and partition into slo_goodput / slo_violations counters at
        their final transition — the measurement substrate ROADMAP
        item 4's admission/preemption policies will consume."""
        if prefill_budget is not None and prefill_budget < 1:
            raise ValueError(f"prefill_budget must be >= 1, got "
                             f"{prefill_budget}")
        if telemetry is not None:
            self.tele = telemetry
        else:
            if trace is None:
                trace = trace_env_enabled()
            self.tele = Telemetry(trace=trace)
        self.tele.configure_slo(slo_classes)
        if getattr(engine, "backend", None) == "mega" and not paged:
            raise ValueError(
                "backend='mega' fuses the PAGED decode tick only "
                "(engine.paged_slot_chunk); construct "
                "ContinuousScheduler(paged=True), or serve contiguous "
                "slots on a per-op backend such as 'flash'")
        if paged:
            self.slots = PagedDecodeSlots(
                engine, batch, page=page, num_pages=num_pages,
                prefix_cache=prefix_cache, margin=chunk,
                spec=spec, drafter=drafter,
                host_pool_pages=host_pool_pages, fault=fault,
                telemetry=self.tele)
        else:
            self.slots = DecodeSlots(engine, batch, spec=spec,
                                     drafter=drafter,
                                     telemetry=self.tele)
        # KV-fork fan-out (Request.n > 1): siblings of an n>1 parent
        # wait here (keyed by the parent child-0 rid) and fork the
        # parent's pages the instant it arms — the on_armed hook
        # covers the chunked-prefill arming sites; the monolithic
        # admit path calls _spawn_forks directly
        self.slots.on_armed = self._spawn_forks
        self._pending_forks: Dict[object, List[Request]] = {}
        self.chunk = chunk
        self.prefill_budget = prefill_budget
        # the stall bound the chunking buys: the most prefill tokens
        # any single poll pushed through a forward while live streams
        # waited on it (== the longest prompt suffix under monolithic
        # admissions; <= prefill_budget under chunked ones)
        self.max_prefill_tokens_per_poll = 0
        self.max_queue = max_queue
        self.watchdog_s = watchdog_s
        self.preempt = preempt
        self.fault = fault
        self.overlap = bool(overlap)
        # overlap pipeline state: spec-mode finished-but-unretired
        # slots (their retire is deferred to overlap with the next
        # verify), and the carry buffers a mid-phase/between-poll
        # drain lands into (delivered by the next poll)
        self._staged: List[Tuple[int, object]] = []
        self._carry_out: Dict[object, np.ndarray] = {}
        self._carry_done: List[object] = []
        # host_ms_per_poll gauge: dispatch-to-dispatch wall time minus
        # the device wait accumulated in between (DecodeSlots._fetch)
        self._host_ms_ema: Optional[float] = None
        self._last_mark: Optional[Tuple[float, float]] = None
        self._queue: deque = deque()
        # guards _queue/_deadline against cross-thread submit()/cancel()
        # racing the driver thread's poll() (the class contract allows
        # enqueueing from any thread; a bare deque.append was atomic
        # under the GIL, but the deadline stamp + max_queue bound are
        # check-then-act sequences and _expire_deadlines iterates).
        # Reentrant: the overlap drain paths pop finished deadlines
        # from inside already-locked phases.
        self._lock = threading.RLock()
        # rid -> absolute monotonic deadline for requests that carry a
        # deadline_ms budget; preserved across preemptions (keyed by
        # rid, stamped once at first submit)
        self._deadline: Dict[object, float] = {}
        # rid -> rejection reason for requests the slots refused (the
        # serving layer pops these to tell the client WHY it got zero
        # tokens instead of a success-shaped empty stream)
        self.rejected: Dict[object, str] = {}
        # resilience counters, registry-homed (stats() snapshots them;
        # the int-valued properties below keep the old attribute API)
        reg = self.tele.registry
        self._c_preemptions = reg.counter(
            "preemptions", "KV-pressure slot preemptions")
        self._c_deadline_expired = reg.counter(
            "deadline_expired", "requests cancelled past deadline_ms")
        self._c_busy_rejections = reg.counter(
            "busy_rejections", "submits refused at max_queue")
        self._g_host_ms = reg.gauge(
            "host_ms_per_poll", "dispatch-to-dispatch host time minus "
                                "device wait (EMA)")
        # TP topology + live throughput (multi-chip serving — ROADMAP
        # open item 1): ONE scheduler drives the whole TP mesh, so
        # multi-chip runs must report both aggregate and per-chip
        # numbers. tokens_emitted counts every token delivered to a
        # stream; _busy_s accumulates dispatch-to-dispatch wall time
        # while slots were occupied (idle gaps excluded, same rule as
        # host_ms_per_poll) — stats() derives
        # serving_tok_per_s_aggregate and /tp_size per-chip from them,
        # and the gauges ride the Prometheus exposition.
        self.tp_size = int(
            engine.model.mesh.shape[engine.model.axis])
        reg.gauge("tp_size",
                  "TP mesh size this scheduler drives").set(self.tp_size)
        # sequence-parallel topology (long-context serving): the sp
        # mesh size the paged pool's page-id space shards over —
        # per-chip KV reads and attention FLOPs drop to ~1/sp_size and
        # max context scales with it (1 = no sp)
        self.sp_size = int(getattr(engine, "sp_size", 1))
        reg.gauge("sp_size",
                  "sp mesh size the paged pool shards over").set(
            self.sp_size)
        # megakernel serving gauge (ISSUE 12 satellite): 1 when the
        # pure-decode paged tick runs the fused program — paired with
        # device_wait_kind_s{kind="mega"} it tells an operator the
        # fused tick is live and what the host actually waits on
        reg.gauge("mega_enabled",
                  "1 = decode ticks run the fused megakernel "
                  "program").set(
            1.0 if getattr(engine, "backend", None) == "mega" else 0.0)
        self._c_tokens = reg.counter(
            "tokens_emitted", "tokens delivered to client streams")
        self._busy_s = 0.0
        self._hang: Optional[str] = None

    # registry-homed counters behind the old int attribute API (tests
    # and bench read these as plain ints)
    @property
    def preemptions(self) -> int:
        return self._c_preemptions.value

    @property
    def deadline_expired(self) -> int:
        return self._c_deadline_expired.value

    @property
    def busy_rejections(self) -> int:
        return self._c_busy_rejections.value

    def dump_trace(self, path: str) -> None:
        """Write the telemetry export (poll timeline + request traces
        + metrics snapshot) as perfetto-loadable JSON — the
        TDTPU_TRACE dump; summarize with tools/trace_view.py."""
        self.tele.dump(path)

    def submit(self, req: Request) -> bool:
        """Enqueue a request. Returns False — WITHOUT queueing — when
        the waiting line is at max_queue: the caller owes the client a
        busy/retry-later reply instead of unbounded buffering. Internal
        re-queues (preemption) bypass the bound — a preempted request
        was already admitted once and must never be dropped.
        Thread-safe: any thread may submit while the driver polls."""
        with self._lock:
            if self.max_queue is not None \
                    and len(self._queue) >= self.max_queue:
                self._c_busy_rejections.inc()
                return False
            if req.deadline_ms is not None \
                    and req.rid not in self._deadline:
                self._deadline[req.rid] = time.monotonic() \
                    + req.deadline_ms / 1e3
            # lifecycle stamp INSIDE the lock: the driver may admit
            # (and emit for) this request the instant it is visible in
            # the queue, and emit/retire need the record to exist
            self.tele.queued(req.rid, slo=req.slo)
            self._queue.append(req)
        return True

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    def cancel(self, rid) -> bool:
        """Drop a request mid-flight (cancel-on-disconnect): a queued
        request is removed; an in-flight one retires NOW — its slot,
        carry rows and (paged) pages free immediately instead of
        decoding to gen_len with the tokens falling on the floor. The
        tokens generated so far are still valid, so a paged retire
        inserts them into the prefix tree as usual. Returns False for
        an unknown/finished rid.

        Threading contract: removing a QUEUED request is safe from any
        thread (it shares the submit lock). Cancelling an IN-FLIGHT
        slot mutates the decode carry and releases pages, so it must
        run on the driver thread or be serialized with poll() by the
        caller — racing a live chunk could retire a slot the driver
        just re-armed and free pages a masked row still writes.
        TokenServer does exactly this: cancel and poll both run under
        its own lock."""
        with self._lock:
            for i, r in enumerate(self._queue):
                if r.rid == rid:
                    del self._queue[i]
                    self._deadline.pop(rid, None)
                    self.tele.retire(rid, "cancelled")
                    # a cancelled fork parent orphans its waiting
                    # siblings: they queue as ordinary admissions
                    # (prefix cache keeps their streams identical)
                    for kid in self._pending_forks.pop(rid, ()):
                        self._queue.append(kid)
                    return True
            # a fork sibling still waiting on its parent's arming
            for kids in self._pending_forks.values():
                for i, kid in enumerate(kids):
                    if kid.rid == rid:
                        del kids[i]
                        self._deadline.pop(rid, None)
                        self.tele.retire(rid, "cancelled")
                        return True
        if self.overlap and not self._pipeline_idle() \
                and any(self.slots.rids[b] == rid
                        for b in self.slots.occupied):
            # the rid's slot may be in the unlanded tick: land + retire
            # first (other streams' landed tokens go to the carry
            # buffers, delivered by the next poll), then cancel on
            # consistent state — the rid may turn out to have finished
            self._drain(self._carry_out, self._carry_done)
        for b in self.slots.occupied:
            if self.slots.rids[b] == rid:
                self.slots.retire(b)
                with self._lock:
                    self._deadline.pop(rid, None)
                    # parent cancelled mid-prefill: its waiting
                    # siblings re-queue as ordinary admissions
                    for kid in self._pending_forks.pop(rid, ()):
                        self._queue.append(kid)
                self.tele.retire(rid, "cancelled")
                return True
        return False

    def stats(self) -> dict:
        """Serving counters: prefix-cache hit/skip (paged path),
        speculative-decoding accept counters (spec=K mode —
        spec_accept_rate, tokens_per_step), the resilience counters
        (queue_depth, preemptions, deadline_expired, busy_rejections,
        plus a "hang" verdict string once a watchdogged chunk has
        missed its deadline), and the live latency histograms
        (`ttft_ms` / `inter_token_ms` / `request_latency_ms` /
        `poll_ms` as {count, sum, mean, p50, p95, p99} dicts).

        The result is a DEEP, single-point-in-time snapshot of the
        metrics registry (runtime/telemetry.py) taken under the
        scheduler and registry locks: every container is freshly
        allocated, so cross-thread readers can iterate/serialize it
        while the driver keeps polling — the shallow-copy race the
        old three hand-maintained dicts carried is structurally
        gone (tests/test_telemetry.py hammers this)."""
        reg = self.tele.registry
        with self._lock, reg.lock:
            # point-in-time gauges refreshed first (prefix/host-tier
            # gauges refresh inside slots.stats), then ONE registry
            # snapshot, then the config echoes and derived rates
            reg.gauge("queue_depth").set(len(self._queue))
            reg.gauge("prefill_tokens_forwarded").set(
                self.slots.prefill_forwarded)
            reg.gauge("max_prefill_tokens_per_poll").set(
                self.max_prefill_tokens_per_poll)
            reg.gauge("prefills_in_progress").set(
                len(self.slots.prefill_slots))
            reg.gauge("device_wait_s").set(self.slots.device_wait_s)
            # device-time attribution: the coalesced wait split per
            # program kind (decode/verify/mixed/admit — the fused
            # planes; the disagg subclass owns prefill/transfer). A
            # DISTINCT base name from the device_wait_s total, so
            # summing the labeled series never double-counts it.
            by_kind = {k: round(v, 4) for k, v in
                       self.slots.device_wait_by_kind.items()}
            for k in ("prefill", "decode", "verify", "mixed",
                      "mega", "sp_combine", "admit", "transfer"):
                reg.gauge("device_wait_kind_s",
                          labels={"kind": k}).set(by_kind.get(k, 0.0))
            # live throughput, aggregate AND per-chip (one scheduler
            # drives the whole TP mesh — the per-chip number is the
            # one comparable across topologies)
            reg.gauge("tp_size").set(self.tp_size)
            agg = (self._c_tokens.value / self._busy_s
                   if self._busy_s > 0 else 0.0)
            nchips = self.tp_size * self.sp_size
            reg.gauge("serving_tok_per_s_aggregate",
                      "tokens/s across the whole mesh while "
                      "serving").set(round(agg, 3))
            reg.gauge("serving_tok_per_s_per_chip",
                      "aggregate tok/s / mesh size").set(
                round(agg / nchips, 3))
            slots_stats = dict(getattr(self.slots, "stats", {}) or {})
            out = reg.snapshot()
            out.update(slots_stats)
            out.update({
                "tp_size": self.tp_size,
                "sp_size": self.sp_size,
                "tokens_emitted": self._c_tokens.value,
                "serving_tok_per_s_aggregate": round(agg, 3),
                "serving_tok_per_s_per_chip":
                    round(agg / nchips, 3),
                "queue_depth": len(self._queue),
                "preemptions": self._c_preemptions.value,
                "deadline_expired": self._c_deadline_expired.value,
                "busy_rejections": self._c_busy_rejections.value,
                "prefill_budget": self.prefill_budget,
                "prefill_tokens_forwarded":
                    self.slots.prefill_forwarded,
                "max_prefill_tokens_per_poll":
                    self.max_prefill_tokens_per_poll,
                "prefills_in_progress": len(self.slots.prefill_slots),
                # host time per poll with device wait subtracted
                # (EMA): the number overlap=True exists to hide
                # behind the device
                "overlap": self.overlap,
                "host_ms_per_poll": (0.0 if self._host_ms_ema is None
                                     else round(self._host_ms_ema, 3)),
                "device_wait_s": round(self.slots.device_wait_s, 4),
                "device_wait_s_by_kind": by_kind,
                "slo_classes": {
                    name: {"ttft_target_ms": c.ttft_target_ms,
                           "itl_target_ms": c.itl_target_ms,
                           "priority": c.priority}
                    for name, c in self.tele.slo_classes.items()},
            })
            if self._hang is not None:
                out["hang"] = self._hang
        return out

    def _mark_dispatch(self) -> None:
        """Stamp a device-step dispatch: host_ms_per_poll is the time
        since the previous stamp minus the device wait accrued in
        between (DecodeSlots._fetch) — i.e. what the HOST spent
        scheduling, drafting, streaming and admitting per poll,
        whether or not the device was busy under it."""
        now = time.monotonic()
        wait = self.slots.device_wait_s
        if self._last_mark is not None:
            t0, w0 = self._last_mark
            host_ms = max(0.0, ((now - t0) - (wait - w0)) * 1e3)
            self._host_ms_ema = host_ms if self._host_ms_ema is None \
                else 0.8 * self._host_ms_ema + 0.2 * host_ms
            self._g_host_ms.set(self._host_ms_ema)   # registry mirror
            # serving time base for the live tok/s gauges (stats()):
            # dispatch-to-dispatch wall while occupied, idle excluded
            self._busy_s += now - t0
        self._last_mark = (now, wait)

    @property
    def idle(self) -> bool:
        return (not self._queue and not self.slots.occupied
                and not self._carry_out and not self._carry_done)

    def _eff_chunk(self) -> int:
        """Decode chunk for the next tick: a grammar mask is a
        per-step scan constant (engine.slot_chunk contract), so any
        live constrained slot drops the tick to single-step;
        unconstrained polls keep the configured chunk."""
        slots = self.slots
        if any(slots._grammar[b] is not None
               for b in slots.decode_slots):
            return 1
        return self.chunk

    def _grammar_sync_needed(self) -> bool:
        """overlap=True cannot dispatch-ahead a spec=0 grammar tick:
        the next tick's mask depends on the token the unlanded tick
        emits. spec=K grammar polls land in-poll already (begin_spec)
        and stay on the overlap path."""
        if self.slots.spec:
            return False
        slots = self.slots
        if any(slots.reqs[b] is not None
               and getattr(slots.reqs[b], "grammar", None) is not None
               for b in range(slots.batch)):
            return True
        with self._lock:
            return any(getattr(r, "grammar", None) is not None
                       for r in self._queue)

    def _fan_out(self, req: Request) -> Request:
        """Validate the structured-generation fields of the admission
        at the queue head and split an n>1 request into n same-prompt
        children: child 0 prefills normally; children 1..n-1 wait in
        _pending_forks and FORK the armed slot's pages (one prefill, n
        decode streams). Child k streams under rid (rid, k) with seed
        seed+k — bitwise identical to n sequential same-seed requests
        (the fork maps exactly the pages a sequential admission's
        prefix-cache hit would). Raises ValueError (the caller's
        reject path) on invalid n or an unsupported combination."""
        n = int(getattr(req, "n", 1) or 1)
        g = getattr(req, "grammar", None)
        if n < 1:
            raise ValueError(
                f"request {req.rid!r}: n must be >= 1, got {n}")
        if g is not None:
            if getattr(self.slots.engine, "backend", None) == "mega":
                raise ValueError(
                    f"request {req.rid!r}: backend='mega' fuses the "
                    f"greedy paged tick with an in-kernel argmax and "
                    f"takes no grammar mask operand; serve constrained "
                    f"requests on the per-op backends")
            if g.vocab_size != self.slots._vocab_size:
                raise ValueError(
                    f"request {req.rid!r}: grammar compiled for vocab "
                    f"{g.vocab_size}, engine vocab is "
                    f"{self.slots._vocab_size}")
        if n == 1:
            return req
        if not hasattr(self.slots, "fork"):
            raise ValueError(
                f"request {req.rid!r}: n={n} parallel sampling needs "
                f"the paged KV pool (ContinuousScheduler(paged=True)) "
                f"— contiguous slots cannot share prefix pages")
        if n > self.slots.batch:
            raise ValueError(
                f"request {req.rid!r}: n={n} exceeds the slot batch "
                f"{self.slots.batch}")
        kids = [dataclasses.replace(req, rid=(req.rid, k),
                                    seed=req.seed + k, n=1)
                for k in range(n)]
        dl = self._deadline.pop(req.rid, None)
        for kid in kids:
            self.tele.queued(kid.rid, slo=kid.slo)
            if dl is not None:
                self._deadline[kid.rid] = dl
        # the parent rid's lifecycle record closes here — the client
        # streams under the (rid, k) children from now on
        self.tele.retire(req.rid, "forked")
        self._queue[0] = kids[0]
        self._pending_forks[kids[0].rid] = kids[1:]
        return kids[0]

    def _spawn_forks(self, slot: int) -> None:
        """on_armed hook: the instant an n>1 parent (child 0) arms,
        fork its pages into free slots for the waiting siblings. A
        sibling that cannot fork NOW (no free slot / pool exhausted)
        falls back to the FRONT of the queue as an ordinary admission
        — the parent's prompt pages are in the prefix tree, so it
        still skips the shared prefill (same streams, degraded
        sharing)."""
        rid = self.slots.rids[slot]
        kids = self._pending_forks.pop(rid, None)
        if not kids:
            return
        from triton_dist_tpu.models.prefix_cache import PoolExhausted
        overflow: List[Request] = []
        for i, kid in enumerate(kids):
            free = self.slots.free
            if not free:
                overflow = kids[i:]
                break
            try:
                self.slots.fork(slot, free[0], kid)
                self.tele.req_event(kid.rid, "admitted", free[0])
            except (PoolExhausted, ValueError):
                overflow = kids[i:]
                break
        if overflow:
            with self._lock:
                for kid in reversed(overflow):
                    self._queue.appendleft(kid)

    def _reject(self, rid, reason: str,
                status: str = "rejected") -> None:
        import sys
        print(f"[scheduler] rejected request {rid!r}: {reason}",
              file=sys.stderr)
        self.rejected[rid] = reason
        while len(self.rejected) > 1024:
            # bound the side channel: callers that never read
            # reasons (run()/bench loops) must not leak — drop
            # oldest first (dict preserves insertion order)
            self.rejected.pop(next(iter(self.rejected)))
        self._deadline.pop(rid, None)
        self.tele.retire(rid, status)

    def _expire_deadlines(self, done: List[object]) -> None:
        """Cancel everything past its deadline_ms budget: queued
        requests are dropped before wasting an admission; in-flight
        slots retire NOW (a paged retire still donates the partial
        sequence to the prefix tree — the tokens are valid), with a
        visible reason the serving layer reports as an error."""
        if not self._deadline:
            return
        now = time.monotonic()
        expired = {rid for rid, dl in self._deadline.items()
                   if now >= dl}
        if not expired:
            return
        if any(r.rid in expired for r in self._queue):
            keep: deque = deque()
            for r in self._queue:
                if r.rid in expired:
                    self._c_deadline_expired.inc()
                    if r.resume is not None:
                        # preempted mid-stream, expired while waiting
                        # to resume: the client DID receive tokens —
                        # say so, like the in-flight branch
                        reason = (f"deadline_ms={r.deadline_ms:g} "
                                  f"exceeded after {r.resume.emitted} "
                                  f"tokens (preempted, awaiting resume)")
                    else:
                        reason = (f"deadline_ms={r.deadline_ms:g} "
                                  f"expired before admission")
                    self._reject(r.rid, reason, status="expired")
                    done.append(r.rid)
                    # siblings of an expired fork parent re-queue as
                    # ordinary admissions (their own copied deadlines
                    # expire them on the next pass)
                    for kid in self._pending_forks.pop(r.rid, ()):
                        keep.append(kid)
                else:
                    keep.append(r)
            self._queue = keep
        for b in list(self.slots.occupied):
            rid = self.slots.rids[b]
            if rid in expired:
                req = self.slots.reqs[b]
                emitted = self.slots.emitted(b)
                self.slots.retire(b)
                self._c_deadline_expired.inc()
                self._reject(rid, f"deadline_ms={req.deadline_ms:g} "
                                  f"exceeded after {emitted} tokens",
                             status="expired")
                done.append(rid)
                for kid in self._pending_forks.pop(rid, ()):
                    self._queue.append(kid)

    def _eligible_victims(self) -> List[int]:
        """Slots that may be preempted: they emitted at least one token
        since their current admission, so displacement banks real
        progress in the re-queued request (see
        DecodeSlots.emitted_since_admit — the liveness gate that keeps
        chunked-prefill admissions from thrashing each other's
        in-progress, eviction-fragile prefills forever)."""
        slots = self.slots
        return [b for b in slots.occupied
                if slots.emitted_since_admit(b) > 0]

    def _pick_victim(self, candidates: List[int]) -> int:
        """Preemption victim policy: lowest SLO protection rank first
        (a "batch" stream is displaced before an "interactive" one —
        DecodeSlots.slo_priority; uniform classes collapse the leading
        key and the choice is the class-blind one bitwise), then fewest
        generated tokens (least recompute thrown away — the
        long-running streams finish), ties to the most recently
        admitted (it displaced the least)."""
        slots = self.slots
        return min(candidates,
                   key=lambda b: (slots.slo_priority(b),
                                  slots.emitted(b),
                                  -int(slots.admit_tick[b])))

    def _preempt_for(self, rid, preempted_now: set, reason: str, *,
                     drop, requeue_at: int = 1) -> bool:
        """The preempt-or-wait ladder of one PoolExhausted admission —
        ONE copy, shared by the fused _admit and the disagg
        scheduler's install/resume paths (models/disagg.py). Returns
        False = stop admitting this poll (an in-flight resident may
        become eligible, or this rid was already preempted-for once);
        True = retry (a victim was freed, or preemption is off and the
        request was hard-rejected via `drop(reason)`). requeue_at: the
        victim's queue position — 1 when the displacer is _queue[0]
        (the victim must NOT jump ahead of the request it was evicted
        for, or the two ping-pong the slot while the displacer
        starves), 0 when the displacer is not in the queue (the disagg
        transfer queue installs ahead of the queue anyway)."""
        can_preempt = (self.preempt and self.slots.occupied
                       and hasattr(self.slots, "preempt"))
        if not can_preempt:
            drop(reason)
            return True
        if rid in preempted_now:
            return False
        victims = self._eligible_victims()
        if not victims:
            # in-flight slots exist but none has banked progress yet
            # (fresh admissions / mid-chunked-prefill): WAIT a poll
            # instead of displacing them — the step advances them to
            # eligibility (or retirement), where preempting now could
            # throw away eviction-fragile prefill work forever
            return False
        victim = self.slots.preempt(self._pick_victim(victims))
        self._c_preemptions.inc()
        self.tele.req_event(victim.rid, "preempt")
        self.tele.instant("preempt", str(victim.rid))
        preempted_now.add(victim.rid)
        self._queue.insert(min(requeue_at, len(self._queue)), victim)
        return True

    def _pipeline_idle(self) -> bool:
        """No dispatched-but-unlanded tick and no staged retires — the
        host mirrors equal what sync mode would show at this poll
        boundary, so preempt/cancel/deadline paths may mutate slots."""
        return self.slots._inflight is None and not self._staged

    def _drain(self, out_acc: Dict[object, np.ndarray],
               done: List[object]) -> None:
        """Collapse the overlap pipeline to the sync post-poll state:
        land the in-flight tick (its tokens/done merge into the given
        accumulators) and retire every finished-but-unretired slot —
        staged spec finishers first, then the just-landed ones. The
        drain-before-mutate rule (module docstring) routes every
        preemption, cancel and in-flight deadline expiry through
        here. The land runs watchdogged (_land_watchdog) — a drain's
        readback can hang exactly like a poll's."""
        self.tele.instant("drain")
        out, finished = self._land_watchdog()
        rid_of = self.slots.rids
        for b, t in out.items():
            _merge_out(out_acc, rid_of[b], t)
        with self._lock:
            for b, rid in finished:
                self._deadline.pop(rid, None)
                done.append(rid)
        for b, rid in self._staged + finished:
            if self.slots.rids[b] == rid:
                self.slots.retire(b)
        self._staged = []

    def _expire_overlap(self, out_acc: Dict[object, np.ndarray],
                        done: List[object]) -> None:
        """_expire_deadlines behind the drain rule: an expired rid that
        occupies a slot may be in the unlanded tick (its mirrors lag by
        one tick), so the pipeline drains first. Queued-only expiries
        never need the drain."""
        if self._deadline and not self._pipeline_idle():
            now = time.monotonic()
            live = {r for r in self.slots.rids if r is not None}
            if any(now >= dl and rid in live
                   for rid, dl in self._deadline.items()):
                self._drain(out_acc, done)
        self._expire_deadlines(done)

    def _admit(self, done: List[object],
               out_acc: Optional[Dict[object, np.ndarray]] = None
               ) -> None:
        """Refill free slots from the waiting line. A PoolExhausted
        admission PREEMPTS a victim and retries instead of rejecting,
        whenever an ELIGIBLE victim exists — one that emitted at least
        a token since its current admission (_eligible_victims: the
        liveness gate; a fresh or mid-chunked-prefill resident may not
        be displaced, the admission waits a poll instead). The victim's
        request re-queues right behind the admission that displaced it,
        its pages now evictable through the prefix tree. Hard rejection
        remains only when every victim is gone and the pool still
        cannot fit the request (it alone exceeds capacity). A request
        preempted within THIS poll that immediately fails re-admission
        waits for the next chunk instead of thrashing the slots it just
        lost."""
        from triton_dist_tpu.models.prefix_cache import PoolExhausted
        preempted_now: set = set()
        while self._queue:
            free = self.slots.free
            if not free:
                return
            req = self._queue[0]
            try:
                if self.fault is not None:
                    self.fault.admission(req)
                req = self._fan_out(req)
                if self.prefill_budget is not None:
                    self.slots.admit_chunked(free[0], req)
                else:
                    self.slots.admit(free[0], req)
                self._queue.popleft()
                self.tele.req_event(
                    req.rid,
                    "resume" if req.resume is not None else "admitted",
                    free[0])
                if self.prefill_budget is None:
                    # monolithic arming happened inside admit (no
                    # on_armed site): fan the waiting siblings out now
                    self._spawn_forks(free[0])
            except PoolExhausted as e:
                if self.overlap and not self._pipeline_idle():
                    # land + retire first: pages still held by the
                    # in-flight tick's finishers may satisfy the
                    # admission without preempting anyone — and
                    # preempt() itself must only run on landed state
                    self._drain(self._carry_out if out_acc is None
                                else out_acc, done)
                    continue

                def _drop(reason, req=req):
                    self._queue.popleft()
                    self._reject(req.rid, reason)
                    done.append(req.rid)
                    # a hard-rejected fork parent orphans its waiting
                    # siblings — reject them with the same reason
                    for kid in self._pending_forks.pop(req.rid, ()):
                        self._reject(kid.rid, reason)
                        done.append(kid.rid)

                if not self._preempt_for(req.rid, preempted_now,
                                         str(e), drop=_drop):
                    return
            except ValueError as e:
                self._queue.popleft()
                self._reject(req.rid, str(e))
                done.append(req.rid)
                for kid in self._pending_forks.pop(req.rid, ()):
                    self._reject(kid.rid, str(e))
                    done.append(kid.rid)

    def poll(self) -> Tuple[Dict[object, np.ndarray], List[object]]:
        """One scheduling iteration: expire deadlines, refill free
        slots from the queue (preempting under pool pressure), run one
        decode chunk (optionally under the watchdog), retire what
        finished. Returns ({rid: new tokens}, [rids done this chunk] —
        finished, rejected, or deadline-expired; rejected/expired rids
        have their reason in self.rejected). A request the slots REJECT
        (e.g. prompt + gen beyond capacity) is reported as finished
        with no tokens — one bad request must never take down the
        serving loop. A PREEMPTED request is in neither list: it
        silently re-queues and its rid keeps streaming on resume.

        overlap=True swaps in the pipeline-aware iteration
        (_poll_overlap): same contract, same streams, with the host
        phases running under the device's compute instead of after
        its readback.

        Every poll rides a telemetry span (poll_ms histogram always;
        a timeline span + nested host-phase spans when tracing), and
        delivered tokens drive the live ttft_ms / inter_token_ms
        histograms."""
        with self.tele.poll_span():
            if self.overlap:
                if self._grammar_sync_needed():
                    # spec=0 grammar ticks cannot dispatch-ahead (the
                    # next mask needs the unlanded token): collapse
                    # the pipeline and take the sync iteration —
                    # unconstrained polls return to overlap untouched
                    if not self._pipeline_idle():
                        self._drain(self._carry_out, self._carry_done)
                    carry_out, carry_done = \
                        self._carry_out, self._carry_done
                    self._carry_out, self._carry_done = {}, []
                    out, done = self._poll_sync()
                    for rid, t in carry_out.items():
                        if len(t):
                            self.tele.emit(rid, len(t))
                            self._c_tokens.inc(len(t))
                    for rid in carry_done:
                        self.tele.retire(rid)
                    for rid, t in out.items():
                        _merge_out(carry_out, rid, t)
                    return carry_out, carry_done + done
                return self._poll_overlap()
            return self._poll_sync()

    def _poll_sync(self) -> Tuple[Dict[object, np.ndarray],
                                  List[object]]:
        """The synchronous iteration (poll() has the contract)."""
        done: List[object] = []
        pf_before = self.slots.prefill_forwarded
        with self._lock, self.tele.phase("bookkeep"):
            # the queue-mutating phases run under the submit lock; the
            # decode chunk below does not (submitters may enqueue while
            # the model steps). NOTE: under MONOLITHIC admissions the
            # lock also covers each admission's whole prefill forward
            # (+ first-call compile), stalling cross-thread submit()
            # for its duration and outside the watchdog's reach —
            # chunked prefill (prefill_budget) removes that hold time,
            # since admit_chunked runs no forward at all
            self._expire_deadlines(done)
            self._admit(done)
        if not self.slots.occupied:
            # idle poll, nothing dispatched: drop the stamp so the idle
            # gap is not charged as host time at the next burst's first
            # dispatch (the EMA would jump by the whole wait)
            self._last_mark = None
            self.max_prefill_tokens_per_poll = max(
                self.max_prefill_tokens_per_poll,
                self.slots.prefill_forwarded - pf_before)
            return {}, done
        # a poll with prefills in flight runs ONE mixed tick fusing the
        # decode step with budgeted prompt chunks; otherwise the plain
        # chunk-length slot scan
        if self.slots.prefill_slots:
            step = lambda: self.slots.step_mixed(self.prefill_budget)
            label = (f"scheduler mixed tick "
                     f"(prefill_budget={self.prefill_budget})")
        else:
            ec = self._eff_chunk()
            step = lambda: self.slots.step_chunk(ec)
            label = f"scheduler chunk (chunk={ec})"
        self._mark_dispatch()
        with self.tele.phase("step"):
            if self.watchdog_s is not None:
                from triton_dist_tpu.runtime.stress import watchdog
                try:
                    by_slot, finished = watchdog(step, self.watchdog_s,
                                                 label=label)
                except Exception as e:
                    from triton_dist_tpu.runtime.stress import HangError
                    if isinstance(e, HangError):
                        # record the verdict for stats(), then unwind:
                        # the process is poisoned (stress.watchdog
                        # contract) and the one unacceptable outcome
                        # is a silent freeze
                        self._hang = str(e)
                        self.tele.instant("watchdog_hang", str(e))
                    raise
            else:
                by_slot, finished = step()
        self.max_prefill_tokens_per_poll = max(
            self.max_prefill_tokens_per_poll,
            self.slots.prefill_forwarded - pf_before)
        rid_of = self.slots.rids
        out = {rid_of[b]: t for b, t in by_slot.items()}
        for rid, toks in out.items():
            if len(toks):
                self.tele.emit(rid, len(toks))
                self._c_tokens.inc(len(toks))
        with self.tele.phase("retire"):
            dead = self.slots.grammar_dead
            for b, rid in finished:
                msg = dead.pop(b, None)
                if msg is not None:
                    # dead-end automaton: the stream ends LOUDLY — the
                    # serving layer pops the reason off self.rejected
                    self._reject(rid, msg)
                self.slots.retire(b)
                with self._lock:
                    self._deadline.pop(rid, None)
                self.tele.retire(rid)
                done.append(rid)
        return out, done

    def _land_watchdog(self) -> Tuple[Dict[int, np.ndarray],
                                      List[Tuple[int, object]]]:
        """Land the in-flight tick, watchdogged: under overlap the
        DISPATCH cannot hang (it queues and returns) — the blocking
        readback can, so the hang deadline moves to the landed-tick
        boundary."""
        if self.slots._inflight is None:
            return {}, []
        if self.watchdog_s is not None:
            from triton_dist_tpu.runtime.stress import watchdog
            try:
                return watchdog(self.slots.land, self.watchdog_s,
                                label="scheduler land (overlap)")
            except Exception as e:
                from triton_dist_tpu.runtime.stress import HangError
                if isinstance(e, HangError):
                    self._hang = str(e)
                    self.tele.instant("watchdog_hang", str(e))
                raise
        return self.slots.land()

    def _poll_overlap(self) -> Tuple[Dict[object, np.ndarray],
                                     List[object]]:
        """Pipeline-aware poll (overlap=True — module docstring).

        Non-spec: this poll's bookkeeping (deadlines, admissions) runs
        FIRST, while tick N-1 — dispatched at the end of the previous
        poll — is still computing; only then does the one blocking
        readback land it. Tick N dispatches immediately after, and the
        retire work for N-1's finishers runs under it. Between polls
        the in-flight tick also covers the serving layer's socket
        writes and stats reads.

        spec=K: drafting needs the LANDED history, so the pipeline
        cannot cross the poll boundary. Instead the verify dispatches
        first and the deferred work — the PREVIOUS tick's staged
        retires, deadlines, admissions — runs between dispatch and
        land (the host work hides under the verify forward)."""
        slots = self.slots
        out_acc: Dict[object, np.ndarray] = self._carry_out
        done: List[object] = self._carry_done
        self._carry_out, self._carry_done = {}, []
        pf_before = slots.prefill_forwarded
        tele = self.tele
        if slots.spec:
            skip = frozenset(b for b, _ in self._staged)
            with tele.phase("dispatch"):
                if any(b not in skip for b in slots.occupied):
                    if slots.prefill_slots:
                        slots.begin_mixed(self.prefill_budget,
                                          skip=skip)
                    else:
                        slots.begin_chunk(self.chunk, skip=skip)
                    self._mark_dispatch()
                else:
                    self._last_mark = None  # idle: no dispatch stamp
            # deferred bookkeeping — overlapped with the verify: the
            # previous tick's retires (tree inserts + page releases),
            # deadline expiry, admissions (one-tick slot-free delay)
            with tele.phase("retire"):
                for b, rid in self._staged:
                    if slots.rids[b] == rid:
                        slots.retire(b)
                self._staged = []
            with self._lock, tele.phase("bookkeep"):
                self._expire_overlap(out_acc, done)
                self._admit(done, out_acc)
            with tele.phase("land"):
                out, finished = self._land_watchdog()
            rid_of = slots.rids
            for b, t in out.items():
                _merge_out(out_acc, rid_of[b], t)
            dead = slots.grammar_dead
            with self._lock:
                for b, rid in finished:
                    msg = dead.pop(b, None)
                    if msg is not None:
                        self._reject(rid, msg)
                    self._deadline.pop(rid, None)
                    done.append(rid)
            self._staged.extend(finished)
        else:
            with self._lock, tele.phase("bookkeep"):
                self._expire_overlap(out_acc, done)
                self._admit(done, out_acc)
            with tele.phase("land"):
                out, finished = self._land_watchdog()
            rid_of = slots.rids
            for b, t in out.items():
                _merge_out(out_acc, rid_of[b], t)
            # dispatch tick N before retiring N-1's finishers: the
            # device starts immediately and the retire bookkeeping
            # (radix-tree inserts, page releases) hides under it
            skip = frozenset(b for b, _ in finished)
            with tele.phase("dispatch"):
                if any(b not in skip for b in slots.occupied):
                    if slots.prefill_slots:
                        slots.begin_mixed(self.prefill_budget,
                                          skip=skip)
                    else:
                        slots.begin_chunk(self.chunk, skip=skip)
                    self._mark_dispatch()
                else:
                    self._last_mark = None  # idle: no dispatch stamp
            with tele.phase("retire"):
                for b, rid in finished:
                    if slots.rids[b] == rid:
                        slots.retire(b)
                    with self._lock:
                        self._deadline.pop(rid, None)
                    done.append(rid)
        # drains during the phases above landed into the carry buffers
        for rid, t in self._carry_out.items():
            _merge_out(out_acc, rid, t)
        done.extend(self._carry_done)
        self._carry_out, self._carry_done = {}, []
        self.max_prefill_tokens_per_poll = max(
            self.max_prefill_tokens_per_poll,
            slots.prefill_forwarded - pf_before)
        # lifecycle: token deliveries first (a finishing stream's last
        # chunk must land its ttft/inter-token samples before the
        # retired event pops its record), then the final transitions
        # ({rejected, expired, cancelled} rids already recorded their
        # status — the repeat retire no-ops)
        for rid, t in out_acc.items():
            if len(t):
                tele.emit(rid, len(t))
                self._c_tokens.inc(len(t))
        for rid in done:
            tele.retire(rid)
        return out_acc, done

    def run(self, requests) -> Dict[object, np.ndarray]:
        """Drive a batch of requests to completion (the test/bench
        harness loop; a server calls poll() itself to interleave
        streaming I/O). Returns {rid: tokens [gen_len]}."""
        for r in requests:
            if not self.submit(r):
                raise RuntimeError(
                    f"queue full (max_queue={self.max_queue}); run() "
                    f"has no retry loop — submit through a server")
        acc: Dict[object, list] = {r.rid: [] for r in requests}
        while not self.idle:
            out, _ = self.poll()
            for rid, toks in out.items():
                # setdefault: an n>1 request streams under its (rid, k)
                # fork children, not the submitted rid
                acc.setdefault(rid, []).extend(toks.tolist())
        return {rid: np.asarray(t, np.int64) for rid, t in acc.items()}
